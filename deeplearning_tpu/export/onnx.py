"""Self-contained jaxpr → ONNX exporter + load-back evaluator.

The reference ships an ONNX deployment path (detection/yolov5/export.py:43
``torch.onnx.export``; others/deploy/pytorch2onnx/support_new_ops.py —
registering a symbolic for an op the exporter doesn't know). This image has
neither the ``onnx`` package nor ``tf2onnx``/``onnxruntime``, so this module
implements the whole path from first principles:

- a minimal protobuf **wire-format** writer/reader for the stable public
  ONNX schema (ModelProto/GraphProto/NodeProto/TensorProto/AttributeProto,
  opset 12 — attribute-style Reduce* axes);
- a jaxpr walker that lowers each primitive through ``ONNX_LOWERINGS``;
- ``register_onnx_lowering`` — the ``support_new_ops.py`` ``g.op()``
  symbolic-registration analog: models using a primitive outside the
  built-in table register a lowering and export cleanly;
- ``load_onnx``/``run_onnx`` — parse the serialized file back and evaluate
  it (numpy + lax for conv/pool), so tests assert the ARTIFACT, not the
  in-memory graph, matches the jax forward.

Layout convention: tensors keep jax's layout (NHWC for images); Conv and
MaxPool nodes are wrapped in Transpose pairs since ONNX defines them NCHW.
"""

from __future__ import annotations

import struct
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

__all__ = ["export_onnx", "load_onnx", "run_onnx",
           "register_onnx_lowering", "ONNX_LOWERINGS"]

OPSET = 12
IR_VERSION = 7            # IR for opset-12-era onnx releases

# TensorProto.DataType
_DTYPES = {
    np.dtype("float32"): 1, np.dtype("uint8"): 2, np.dtype("int8"): 3,
    np.dtype("int32"): 6, np.dtype("int64"): 7, np.dtype("bool"): 9,
    np.dtype("float16"): 10, np.dtype("float64"): 11,
}
try:                       # BFLOAT16=16 (opset 13 tensor type; we emit it
    import ml_dtypes       # only when the traced fn itself computes in bf16)
    _DTYPES[np.dtype(ml_dtypes.bfloat16)] = 16
except ImportError:        # pragma: no cover
    pass
_DTYPES_INV = {v: k for k, v in _DTYPES.items()}


# --------------------------------------------------------------- protobuf

def _varint(n: int) -> bytes:
    out = bytearray()
    n &= (1 << 64) - 1
    while True:
        b = n & 0x7F
        n >>= 7
        out.append(b | (0x80 if n else 0))
        if not n:
            return bytes(out)


def _tag(field: int, wire: int) -> bytes:
    return _varint((field << 3) | wire)


def _len_field(field: int, payload: bytes) -> bytes:
    return _tag(field, 2) + _varint(len(payload)) + payload


def _int_field(field: int, value: int) -> bytes:
    return _tag(field, 0) + _varint(value)


def _packed_ints(field: int, values: Sequence[int]) -> bytes:
    if not values:
        return b""
    payload = b"".join(_varint(v) for v in values)
    return _len_field(field, payload)


class _Reader:
    def __init__(self, data: bytes):
        self.data, self.pos = data, 0

    def eof(self) -> bool:
        return self.pos >= len(self.data)

    def varint(self) -> int:
        shift = result = 0
        while True:
            b = self.data[self.pos]
            self.pos += 1
            result |= (b & 0x7F) << shift
            if not b & 0x80:
                return result
            shift += 7

    def field(self) -> Tuple[int, int, Any]:
        key = self.varint()
        field, wire = key >> 3, key & 7
        if wire == 0:
            return field, wire, self.varint()
        if wire == 2:
            n = self.varint()
            blob = self.data[self.pos:self.pos + n]
            self.pos += n
            return field, wire, blob
        if wire == 5:
            blob = self.data[self.pos:self.pos + 4]
            self.pos += 4
            return field, wire, struct.unpack("<f", blob)[0]
        if wire == 1:
            blob = self.data[self.pos:self.pos + 8]
            self.pos += 8
            return field, wire, struct.unpack("<d", blob)[0]
        raise ValueError(f"unsupported wire type {wire}")


def _read_packed_ints(blob: bytes) -> List[int]:
    r = _Reader(blob)
    out = []
    while not r.eof():
        out.append(r.varint())
    return out


# ---------------------------------------------------------- proto builders

def _tensor_proto(name: str, arr: np.ndarray) -> bytes:
    arr = np.ascontiguousarray(arr)
    if arr.dtype not in _DTYPES:
        raise ValueError(f"unsupported dtype {arr.dtype} for ONNX export")
    parts = [
        _packed_ints(1, arr.shape),              # dims
        _int_field(2, _DTYPES[arr.dtype]),       # data_type
        _len_field(8, name.encode()),            # name
        _len_field(9, arr.tobytes()),            # raw_data
    ]
    return b"".join(parts)


def _value_info(name: str, shape: Sequence[int], dtype: np.dtype) -> bytes:
    dims = b"".join(_len_field(1, _int_field(1, d)) for d in shape)
    tensor_type = (_int_field(1, _DTYPES[np.dtype(dtype)])
                   + _len_field(2, dims))
    type_proto = _len_field(1, tensor_type)
    return _len_field(1, name.encode()) + _len_field(2, type_proto)


def _attribute(name: str, value: Any) -> bytes:
    parts = [_len_field(1, name.encode())]
    if isinstance(value, float):
        parts += [_tag(2, 5) + struct.pack("<f", value), _int_field(20, 1)]
    elif isinstance(value, bool) or isinstance(value, (int, np.integer)):
        parts += [_int_field(3, int(value)), _int_field(20, 2)]
    elif isinstance(value, (list, tuple)) and all(
            isinstance(v, (int, np.integer)) for v in value):
        parts += [b"".join(_int_field(8, int(v)) for v in value),
                  _int_field(20, 7)]
    elif isinstance(value, str):
        parts += [_len_field(4, value.encode()), _int_field(20, 3)]
    else:
        raise ValueError(f"unsupported attribute {name}={value!r}")
    return b"".join(parts)


def _node_proto(op_type: str, inputs: Sequence[str], outputs: Sequence[str],
                attrs: Dict[str, Any], domain: str = "") -> bytes:
    parts = [_len_field(1, i.encode()) for i in inputs]
    parts += [_len_field(2, o.encode()) for o in outputs]
    parts += [_len_field(4, op_type.encode())]
    parts += [_len_field(5, _attribute(k, v)) for k, v in attrs.items()]
    if domain:
        parts += [_len_field(7, domain.encode())]
    return b"".join(parts)


# ----------------------------------------------------------- graph builder

class _GraphBuilder:
    def __init__(self):
        self.nodes: List[bytes] = []
        self.initializers: List[bytes] = []
        self._names: Dict[Any, str] = {}
        self._const_cache: Dict[Any, str] = {}
        self._counter = 0

    def fresh(self, hint: str = "t") -> str:
        self._counter += 1
        return f"{hint}_{self._counter}"

    def node(self, op_type: str, inputs: Sequence[str],
             outputs: Optional[Sequence[str]] = None,
             domain: str = "", **attrs) -> str:
        outputs = list(outputs) if outputs else [self.fresh(op_type.lower())]
        self.nodes.append(_node_proto(op_type, inputs, outputs, attrs,
                                      domain))
        return outputs[0]

    def constant(self, arr: np.ndarray, hint: str = "const") -> str:
        arr = np.asarray(arr)
        # dedupe small constants (jaxpr literals recur per-op: BN eps adds,
        # activation thresholds, reshape targets)
        key = None
        if arr.size <= 64:
            key = (str(arr.dtype), arr.shape, arr.tobytes())
            if key in self._const_cache:
                return self._const_cache[key]
        name = self.fresh(hint)
        self.initializers.append(_tensor_proto(name, arr))
        if key is not None:
            self._const_cache[key] = name
        return name

    def name_of(self, var) -> str:
        if type(var).__name__ == "Literal":
            return self.constant(np.asarray(var.val, var.aval.dtype), "lit")
        if var not in self._names:
            self._names[var] = self.fresh("v")
        return self._names[var]

    def bind(self, var, name: str):
        self._names[var] = name


# ------------------------------------------------------ lowering registry

ONNX_LOWERINGS: Dict[str, Callable] = {}


def register_onnx_lowering(primitive_name: str):
    """Register a jax-primitive → ONNX lowering — the analog of the
    reference's symbolic registration for unsupported ops
    (others/deploy/pytorch2onnx/support_new_ops.py ``g.op()``). The
    function receives (builder, eqn, in_names, out_names) and emits nodes
    via ``builder.node``."""
    def deco(fn):
        ONNX_LOWERINGS[primitive_name] = fn
        return fn
    return deco


def _simple(op_type: str):
    def lower(g, eqn, ins, outs):
        g.node(op_type, ins, outs)
    return lower


for _prim, _op in [
        ("add", "Add"), ("sub", "Sub"), ("mul", "Mul"), ("div", "Div"),
        ("max", "Max"), ("min", "Min"), ("pow", "Pow"), ("neg", "Neg"),
        ("exp", "Exp"), ("log", "Log"), ("tanh", "Tanh"), ("sqrt", "Sqrt"),
        ("erf", "Erf"), ("logistic", "Sigmoid"), ("abs", "Abs"),
        ("sign", "Sign"), ("floor", "Floor"), ("ceil", "Ceil"),
        ("stop_gradient", "Identity"), ("copy", "Identity"),
        ("eq", "Equal"), ("lt", "Less"), ("gt", "Greater"),
        ("le", "LessOrEqual"), ("ge", "GreaterOrEqual"),
        ("and", "And"), ("or", "Or"), ("not", "Not"),
]:
    ONNX_LOWERINGS[_prim] = _simple(_op)


@register_onnx_lowering("erfc")
def _erfc(g, eqn, ins, outs):
    one = g.constant(np.asarray(1.0, np.float32))
    e = g.node("Erf", ins)
    g.node("Sub", [one, e], outs)


@register_onnx_lowering("square")
def _square(g, eqn, ins, outs):
    g.node("Mul", [ins[0], ins[0]], outs)


@register_onnx_lowering("rsqrt")
def _rsqrt(g, eqn, ins, outs):
    s = g.node("Sqrt", ins)
    g.node("Reciprocal", [s], outs)


@register_onnx_lowering("integer_pow")
def _integer_pow(g, eqn, ins, outs):
    y = g.constant(np.asarray(float(eqn.params["y"]), np.float32))
    g.node("Pow", [ins[0], y], outs)


@register_onnx_lowering("select_n")
def _select_n(g, eqn, ins, outs):
    if len(ins) != 3:
        raise NotImplementedError("select_n with >2 cases")
    # select_n(pred, on_false, on_true) → Where(pred, on_true, on_false)
    g.node("Where", [ins[0], ins[2], ins[1]], outs)


@register_onnx_lowering("convert_element_type")
def _convert(g, eqn, ins, outs):
    to = _DTYPES[np.dtype(eqn.params["new_dtype"])]
    g.node("Cast", ins, outs, to=to)


def _shape_only(g, eqn, ins, outs):
    """Static-shape Reshape covers reshape/squeeze/expand_dims alike."""
    shape = g.constant(np.asarray(eqn.outvars[0].aval.shape, np.int64))
    g.node("Reshape", [ins[0], shape], outs)


for _prim in ("reshape", "squeeze", "expand_dims"):
    ONNX_LOWERINGS[_prim] = _shape_only


@register_onnx_lowering("transpose")
def _transpose(g, eqn, ins, outs):
    g.node("Transpose", ins, outs,
           perm=[int(p) for p in eqn.params["permutation"]])


@register_onnx_lowering("broadcast_in_dim")
def _broadcast_in_dim(g, eqn, ins, outs):
    target = eqn.outvars[0].aval.shape
    bdims = eqn.params["broadcast_dimensions"]
    # reshape to put existing dims at their broadcast positions...
    interm = [1] * len(target)
    for src_axis, dst_axis in enumerate(bdims):
        interm[dst_axis] = eqn.invars[0].aval.shape[src_axis]
    shape = g.constant(np.asarray(interm, np.int64))
    reshaped = g.node("Reshape", [ins[0], shape])
    # ...then Expand to the full target
    tgt = g.constant(np.asarray(target, np.int64))
    g.node("Expand", [reshaped, tgt], outs)


@register_onnx_lowering("concatenate")
def _concatenate(g, eqn, ins, outs):
    g.node("Concat", ins, outs, axis=int(eqn.params["dimension"]))


@register_onnx_lowering("slice")
def _slice(g, eqn, ins, outs):
    starts = eqn.params["start_indices"]
    ends = eqn.params["limit_indices"]
    steps = eqn.params["strides"] or (1,) * len(starts)
    axes = list(range(len(starts)))
    g.node("Slice", [
        ins[0],
        g.constant(np.asarray(starts, np.int64)),
        g.constant(np.asarray(ends, np.int64)),
        g.constant(np.asarray(axes, np.int64)),
        g.constant(np.asarray(steps, np.int64))], outs)


def _reduce(op_type: str):
    def lower(g, eqn, ins, outs):
        axes = [int(a) for a in eqn.params["axes"]]
        g.node(op_type, ins, outs, axes=axes, keepdims=0)
    return lower


ONNX_LOWERINGS["reduce_sum"] = _reduce("ReduceSum")
ONNX_LOWERINGS["reduce_max"] = _reduce("ReduceMax")
ONNX_LOWERINGS["reduce_min"] = _reduce("ReduceMin")
ONNX_LOWERINGS["reduce_prod"] = _reduce("ReduceProd")


@register_onnx_lowering("dot_general")
def _dot_general(g, eqn, ins, outs):
    (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
    lhs, rhs = eqn.invars[0].aval, eqn.invars[1].aval
    if len(lc) != 1 or len(rc) != 1:
        raise NotImplementedError("dot_general with multiple contractions")
    lc, rc = lc[0], rc[0]

    def normalize(name, aval, batch, contract, contract_last):
        free = [d for d in range(len(aval.shape))
                if d not in batch and d != contract]
        perm = list(batch) + free + [contract] if contract_last else \
            list(batch) + [contract] + free
        if perm != list(range(len(aval.shape))):
            name = g.node("Transpose", [name], perm=perm)
        b = int(np.prod([aval.shape[d] for d in batch])) if batch else 1
        f = int(np.prod([aval.shape[d] for d in free])) if free else 1
        c = aval.shape[contract]
        shape3 = [b, f, c] if contract_last else [b, c, f]
        name = g.node("Reshape", [
            name, g.constant(np.asarray(shape3, np.int64))])
        free_shape = [aval.shape[d] for d in free]
        return name, free_shape

    ln, lfree = normalize(ins[0], lhs, lb, lc, True)
    rn, rfree = normalize(ins[1], rhs, rb, rc, False)
    mm = g.node("MatMul", [ln, rn])
    out_shape = eqn.outvars[0].aval.shape
    g.node("Reshape", [mm, g.constant(np.asarray(out_shape, np.int64))],
           outs)


@register_onnx_lowering("conv_general_dilated")
def _conv(g, eqn, ins, outs):
    p = eqn.params
    dn = p["dimension_numbers"]
    if (dn.lhs_spec, dn.rhs_spec, dn.out_spec) != \
            ((0, 3, 1, 2), (3, 2, 0, 1), (0, 3, 1, 2)):
        raise NotImplementedError(
            f"conv dimension_numbers {dn} (expected NHWC/HWIO/NHWC)")
    if any(d != 1 for d in p["lhs_dilation"]):
        raise NotImplementedError("transposed conv")
    x = g.node("Transpose", [ins[0]], perm=[0, 3, 1, 2])      # NHWC→NCHW
    w = g.node("Transpose", [ins[1]], perm=[3, 2, 0, 1])      # HWIO→OIHW
    (ph0, ph1), (pw0, pw1) = p["padding"]
    y = g.node("Conv", [x, w],
               strides=[int(s) for s in p["window_strides"]],
               pads=[int(ph0), int(pw0), int(ph1), int(pw1)],
               dilations=[int(d) for d in p["rhs_dilation"]],
               group=int(p["feature_group_count"]))
    g.node("Transpose", [y], outs, perm=[0, 2, 3, 1])         # NCHW→NHWC


@register_onnx_lowering("reduce_window_max")
def _reduce_window_max(g, eqn, ins, outs):
    p = eqn.params
    win, strides, pad = (p["window_dimensions"], p["window_strides"],
                         p["padding"])
    if win[0] != 1 or win[3] != 1 or strides[0] != 1 or strides[3] != 1 \
            or pad[0] != (0, 0) or pad[3] != (0, 0):
        raise NotImplementedError("reduce_window_max beyond NHWC pooling")
    if any(d != 1 for d in p.get("base_dilation", (1,) * 4)) or \
            any(d != 1 for d in p.get("window_dilation", (1,) * 4)):
        raise NotImplementedError("dilated pooling")
    x = g.node("Transpose", ins, perm=[0, 3, 1, 2])
    y = g.node("MaxPool", [x],
               kernel_shape=[int(win[1]), int(win[2])],
               strides=[int(strides[1]), int(strides[2])],
               pads=[int(pad[1][0]), int(pad[2][0]),
                     int(pad[1][1]), int(pad[2][1])])
    g.node("Transpose", [y], outs, perm=[0, 2, 3, 1])


@register_onnx_lowering("iota")
def _iota(g, eqn, ins, outs):
    """Static-shape iota: constant-folded to an initializer."""
    p = eqn.params
    shape, dim = p["shape"], p["dimension"]
    rng = np.arange(shape[dim], dtype=np.dtype(p["dtype"]))
    view = [1] * len(shape)
    view[dim] = shape[dim]
    arr = np.ascontiguousarray(np.broadcast_to(rng.reshape(view), shape))
    g.node("Identity", [g.constant(arr, "iota")], outs)


@register_onnx_lowering("gather")
def _gather(g, eqn, ins, outs):
    """lax.gather restricted to the take / advanced-indexing class where
    every operand dim is either a collapsed size-1 indexed dim or a full
    slice (jnp.take, x[idx_a, idx_b], strided fancy indexing): lowered as
    Transpose -> GatherND -> Transpose. The general windowed gather is
    out of scope (detection graphs never emit it)."""
    p = eqn.params
    dn = p["dimension_numbers"]
    if dn.operand_batching_dims or dn.start_indices_batching_dims:
        raise NotImplementedError("batched gather dims")
    op_aval, idx_aval = eqn.invars[0].aval, eqn.invars[1].aval
    ndim = len(op_aval.shape)
    slice_sizes = p["slice_sizes"]
    sim = list(dn.start_index_map)
    collapsed = set(dn.collapsed_slice_dims)
    free = [d for d in range(ndim) if d not in collapsed]
    if not (set(sim) == collapsed
            and all(slice_sizes[d] == 1 for d in collapsed)
            and all(slice_sizes[d] == op_aval.shape[d] for d in free)):
        raise NotImplementedError(
            f"general gather {dn} slice_sizes={slice_sizes}")
    x = ins[0]
    perm_in = sim + free          # indexed dims first, start_index_map order
    if perm_in != list(range(ndim)):
        x = g.node("Transpose", [x], perm=perm_in)
    idx = g.node("Cast", [ins[1]], to=_DTYPES[np.dtype(np.int64)])
    # jax out-of-bounds semantics: CLIP clamps per dim; FILL (jnp.take
    # default) returns a fill value GatherND cannot express
    mode = str(p.get("mode", ""))
    if "FILL" in mode:
        raise NotImplementedError(
            "gather mode FILL (jnp.take default); use mode='clip' or "
            "'promise_in_bounds' in the traced function")
    if "CLIP" in mode:
        hi = np.asarray([op_aval.shape[d] - 1 for d in sim], np.int64)
        idx = g.node("Max", [idx, g.constant(np.int64(0))])
        idx = g.node("Min", [idx, g.constant(hi)])
    gnd = g.node("GatherND", [x, idx])
    # GatherND layout: [idx batch dims..., free dims...]; lax.gather puts
    # free dims at offset_dims positions (operand order), idx batch dims
    # at the remaining output positions in order
    out_rank = len(eqn.outvars[0].aval.shape)
    n_batch = len(idx_aval.shape) - 1
    layout = [("b", i) for i in range(n_batch)] + [("f", d) for d in free]
    desired, bi, fi = [], 0, 0
    for j in range(out_rank):
        if j in dn.offset_dims:
            desired.append(("f", free[fi]))
            fi += 1
        else:
            desired.append(("b", bi))
            bi += 1
    perm_out = [layout.index(t) for t in desired]
    if perm_out != list(range(out_rank)):
        g.node("Transpose", [gnd], outs, perm=perm_out)
    else:
        g.node("Identity", [gnd], outs)


@register_onnx_lowering("top_k")
def _top_k(g, eqn, ins, outs):
    """lax.top_k -> ONNX TopK along the last axis (int64 indices cast
    back to the int32 jax convention) — the postprocess candidate-select
    step of the pre-NMS detection graphs."""
    kc = g.constant(np.asarray([eqn.params["k"]], np.int64))
    idx64 = g.fresh("topk_idx")
    g.node("TopK", [ins[0], kc], [outs[0], idx64],
           axis=-1, largest=1, sorted=1)
    g.node("Cast", [idx64], [outs[1]], to=_DTYPES[np.dtype(np.int32)])


@register_onnx_lowering("sort")
def _sort(g, eqn, ins, outs):
    """lax.sort (the jnp.sort/argsort primitive): ascending TopK over the
    full axis; payload operands ride along via GatherElements. Tie order
    follows ONNX TopK, not jax's stable sort — equal-key payloads may
    permute."""
    p = eqn.params
    if p.get("num_keys", 1) != 1:
        raise NotImplementedError("lexicographic multi-key sort")
    dim = p["dimension"]
    n = eqn.invars[0].aval.shape[dim]
    kc = g.constant(np.asarray([n], np.int64))
    idx64 = g.fresh("sort_idx")
    g.node("TopK", [ins[0], kc], [outs[0], idx64],
           axis=dim, largest=0, sorted=1)
    for i in range(1, len(ins)):
        g.node("GatherElements", [ins[i], idx64], [outs[i]], axis=dim)


@register_onnx_lowering("argmax")
def _argmax(g, eqn, ins, outs):
    axes = eqn.params["axes"]
    if len(axes) != 1:
        raise NotImplementedError("multi-axis argmax")
    a64 = g.node("ArgMax", ins, axis=int(axes[0]), keepdims=0,
                 select_last_index=0)
    g.node("Cast", [a64], outs,
           to=_DTYPES[np.dtype(eqn.outvars[0].aval.dtype)])


# ---------------------------------------------------------------- export

_INLINE = ("jit", "pjit", "custom_jvp_call", "custom_vjp_call",
           "custom_jvp_call_jaxpr", "remat", "remat2",
           "checkpoint", "custom_vjp_call_jaxpr")


def _walk(g: _GraphBuilder, jaxpr) -> None:
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name in _INLINE:
            sub = None
            for key in ("jaxpr", "call_jaxpr", "fun_jaxpr"):
                if key in eqn.params:
                    sub = eqn.params[key]
                    break
            if sub is None:
                raise NotImplementedError(f"cannot inline {name}")
            inner = sub.jaxpr if hasattr(sub, "jaxpr") else sub
            consts = getattr(sub, "consts", getattr(inner, "consts", []))
            # jax CACHES sub-jaxprs (the relu custom_jvp jaxpr for a given
            # shape is one object reused at every call site), so inner Var
            # objects recur across inlinings — bind them in a scratch scope
            # that is dropped afterwards, or every later inlining would
            # reuse the first one's output names and corrupt the dataflow.
            saved = g._names
            g._names = dict(saved)
            for cv, c in zip(inner.constvars, consts):
                g.bind(cv, g.constant(np.asarray(c)))
            for iv, outer in zip(inner.invars, eqn.invars):
                g.bind(iv, g.name_of(outer))
            _walk(g, inner)
            out_names = [g.name_of(ov) for ov in inner.outvars]
            g._names = saved
            for outer, nm in zip(eqn.outvars, out_names):
                g.bind(outer, nm)
            continue
        if name not in ONNX_LOWERINGS:
            raise NotImplementedError(
                f"no ONNX lowering for primitive '{name}'; add one with "
                "register_onnx_lowering (the support_new_ops.py analog)")
        ins = [g.name_of(v) for v in eqn.invars]
        outs = [g.name_of(v) for v in eqn.outvars]
        ONNX_LOWERINGS[name](g, eqn, ins, outs)


def export_onnx(fn: Callable, example_args: Sequence[Any],
                path: Optional[str] = None,
                graph_name: str = "deeplearning_tpu") -> bytes:
    """Trace ``fn`` on ``example_args`` and serialize the jaxpr as an ONNX
    ModelProto (opset 12). Returns the bytes; writes ``path`` if given."""
    closed = jax.make_jaxpr(fn)(*example_args)
    jaxpr = closed.jaxpr
    g = _GraphBuilder()

    flat_args = jax.tree.leaves(list(example_args))
    if len(jaxpr.invars) != len(flat_args):
        raise ValueError(
            f"traced fn has {len(jaxpr.invars)} array inputs but "
            f"example_args flattened to {len(flat_args)} leaves")
    inputs = []
    for i, var in enumerate(jaxpr.invars):
        name = f"input_{i}"
        g.bind(var, name)
        inputs.append(_value_info(name, var.aval.shape,
                                  np.dtype(var.aval.dtype)))
    for cv, c in zip(jaxpr.constvars, closed.consts):
        g.bind(cv, g.constant(np.asarray(c), "w"))

    _walk(g, jaxpr)

    outputs = []
    out_renames = []
    for i, var in enumerate(jaxpr.outvars):
        name = f"output_{i}"
        out_renames.append(_node_proto("Identity", [g.name_of(var)],
                                       [name], {}))
        outputs.append(_value_info(name, var.aval.shape,
                                   np.dtype(var.aval.dtype)))

    graph = b"".join(
        [_len_field(1, n) for n in g.nodes + out_renames]
        + [_len_field(2, graph_name.encode())]
        + [_len_field(5, t) for t in g.initializers]
        + [_len_field(11, i) for i in inputs]
        + [_len_field(12, o) for o in outputs])
    opset = _int_field(2, OPSET)                   # default domain ""
    model = b"".join([
        _int_field(1, IR_VERSION),
        _len_field(2, b"deeplearning_tpu"),
        _len_field(7, graph),
        _len_field(8, opset),
    ])
    if path:
        with open(path, "wb") as f:
            f.write(model)
    return model


# ------------------------------------------------------------------ load

def _parse_tensor(blob: bytes) -> Tuple[str, np.ndarray]:
    r = _Reader(blob)
    dims: List[int] = []
    dtype = 1
    raw = b""
    name = ""
    while not r.eof():
        field, wire, val = r.field()
        if field == 1:
            dims += _read_packed_ints(val) if wire == 2 else [val]
        elif field == 2:
            dtype = val
        elif field == 8:
            name = val.decode()
        elif field == 9:
            raw = val
    arr = np.frombuffer(raw, _DTYPES_INV[dtype]).reshape(dims)
    return name, arr


def _signed64(v: int) -> int:
    """Protobuf int64 varints are two's complement; undo the encoder's
    `n & (1<<64)-1` so negative attributes (axis=-1) read back signed."""
    return v - (1 << 64) if v >= (1 << 63) else v


def _parse_attr(blob: bytes) -> Tuple[str, Any]:
    r = _Reader(blob)
    name, value, ints = "", None, []
    while not r.eof():
        field, wire, val = r.field()
        if field == 1:
            name = val.decode()
        elif field == 2:
            value = val
        elif field == 3:
            value = _signed64(val)
        elif field == 4:
            value = val.decode()
        elif field == 8:
            ints += ([_signed64(v) for v in _read_packed_ints(val)]
                     if wire == 2 else [_signed64(val)])
    return name, (ints if ints else value)


def _parse_node(blob: bytes) -> Dict[str, Any]:
    r = _Reader(blob)
    node = {"inputs": [], "outputs": [], "op": "", "attrs": {}}
    while not r.eof():
        field, wire, val = r.field()
        if field == 1:
            node["inputs"].append(val.decode())
        elif field == 2:
            node["outputs"].append(val.decode())
        elif field == 4:
            node["op"] = val.decode()
        elif field == 5:
            k, v = _parse_attr(val)
            node["attrs"][k] = v
    return node


def _parse_value_info(blob: bytes) -> str:
    r = _Reader(blob)
    while not r.eof():
        field, wire, val = r.field()
        if field == 1:
            return val.decode()
    return ""


def load_onnx(data: bytes) -> Dict[str, Any]:
    """Parse serialized ONNX bytes into {nodes, initializers, inputs,
    outputs}."""
    r = _Reader(data)
    graph_blob = None
    while not r.eof():
        field, wire, val = r.field()
        if field == 7:
            graph_blob = val
    if graph_blob is None:
        raise ValueError("no GraphProto in model")
    g = _Reader(graph_blob)
    out = {"nodes": [], "initializers": {}, "inputs": [], "outputs": []}
    while not g.eof():
        field, wire, val = g.field()
        if field == 1:
            out["nodes"].append(_parse_node(val))
        elif field == 5:
            name, arr = _parse_tensor(val)
            out["initializers"][name] = arr
        elif field == 11:
            out["inputs"].append(_parse_value_info(val))
        elif field == 12:
            out["outputs"].append(_parse_value_info(val))
    return out


# ------------------------------------------------------------- evaluator

def _np_cast(arr, to):
    return np.asarray(arr).astype(_DTYPES_INV[to])


def _np_slice(x):
    data, starts, ends = x[0], x[1], x[2]
    axes = x[3] if len(x) > 3 else np.arange(len(starts))
    steps = x[4] if len(x) > 4 else np.ones(len(starts), np.int64)
    idx = [slice(None)] * data.ndim
    for a, s0, e0, st in zip(axes, starts, ends, steps):
        idx[int(a)] = slice(int(s0), int(e0), int(st))
    return data[tuple(idx)]


def _eval_node(node: Dict[str, Any], vals: Dict[str, np.ndarray]):
    import jax.numpy as jnp
    from jax import lax

    op = node["op"]
    A = node["attrs"]
    x = [np.asarray(vals[i]) for i in node["inputs"]]
    if op == "Conv":
        y = lax.conv_general_dilated(
            jnp.asarray(x[0]), jnp.asarray(x[1]),
            window_strides=tuple(A["strides"]),
            padding=[(A["pads"][0], A["pads"][2]),
                     (A["pads"][1], A["pads"][3])],
            rhs_dilation=tuple(A.get("dilations", [1, 1])),
            feature_group_count=int(A.get("group", 1)),
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
        return np.asarray(y)
    if op == "MaxPool":
        pads = A.get("pads", [0, 0, 0, 0])
        y = lax.reduce_window(
            jnp.asarray(x[0]), -np.inf, lax.max,
            (1, 1) + tuple(A["kernel_shape"]),
            (1, 1) + tuple(A["strides"]),
            [(0, 0), (0, 0), (pads[0], pads[2]), (pads[1], pads[3])])
        return np.asarray(y)
    if op == "Erf":
        from jax.scipy.special import erf
        return np.asarray(erf(jnp.asarray(x[0])))
    simple = {
        "Add": lambda: x[0] + x[1], "Sub": lambda: x[0] - x[1],
        "Mul": lambda: x[0] * x[1], "Div": lambda: x[0] / x[1],
        "Max": lambda: np.maximum(x[0], x[1]),
        "Min": lambda: np.minimum(x[0], x[1]),
        "Pow": lambda: np.power(x[0], x[1]),
        "Neg": lambda: -x[0], "Exp": lambda: np.exp(x[0]),
        "Log": lambda: np.log(x[0]), "Tanh": lambda: np.tanh(x[0]),
        "Sqrt": lambda: np.sqrt(x[0]),
        "Reciprocal": lambda: 1.0 / x[0],
        "Sigmoid": lambda: 1.0 / (1.0 + np.exp(-x[0])),
        "Abs": lambda: np.abs(x[0]), "Sign": lambda: np.sign(x[0]),
        "Floor": lambda: np.floor(x[0]), "Ceil": lambda: np.ceil(x[0]),
        "Identity": lambda: x[0],
        "Equal": lambda: x[0] == x[1], "Less": lambda: x[0] < x[1],
        "Greater": lambda: x[0] > x[1],
        "LessOrEqual": lambda: x[0] <= x[1],
        "GreaterOrEqual": lambda: x[0] >= x[1],
        "And": lambda: np.logical_and(x[0], x[1]),
        "Or": lambda: np.logical_or(x[0], x[1]),
        "Not": lambda: np.logical_not(x[0]),
        "Where": lambda: np.where(x[0], x[1], x[2]),
        "MatMul": lambda: np.matmul(x[0], x[1]),
        "Reshape": lambda: x[0].reshape([int(d) for d in x[1]]),
        "Expand": lambda: np.broadcast_to(
            x[0], [int(d) for d in x[1]]).copy(),
        "Concat": lambda: np.concatenate(x, axis=int(A["axis"])),
        "Transpose": lambda: np.transpose(x[0], A["perm"]),
        "Cast": lambda: _np_cast(x[0], int(A["to"])),
        "ReduceSum": lambda: np.sum(
            x[0], axis=tuple(A["axes"]), keepdims=bool(A["keepdims"])),
        "ReduceMax": lambda: np.max(
            x[0], axis=tuple(A["axes"]), keepdims=bool(A["keepdims"])),
        "ReduceMin": lambda: np.min(
            x[0], axis=tuple(A["axes"]), keepdims=bool(A["keepdims"])),
        "ReduceProd": lambda: np.prod(
            x[0], axis=tuple(A["axes"]), keepdims=bool(A["keepdims"])),
        "Slice": lambda: _np_slice(x),
        "GatherND": lambda: x[0][tuple(
            np.asarray(x[1])[..., j] for j in range(x[1].shape[-1]))],
        "GatherElements": lambda: np.take_along_axis(
            x[0], np.asarray(x[1], np.int64), axis=int(A["axis"])),
        "ArgMax": lambda: np.argmax(x[0], axis=int(A["axis"])).astype(
            np.int64) if not int(A.get("keepdims", 1)) else np.argmax(
            x[0], axis=int(A["axis"]), keepdims=True).astype(np.int64),
    }
    if op == "TopK":
        k = int(np.asarray(x[1]).reshape(-1)[0])
        axis = int(A.get("axis", -1))
        largest = int(A.get("largest", 1))
        key = -x[0] if largest else x[0]
        idx = np.argsort(key, axis=axis, kind="stable")
        idx = np.take(idx, np.arange(k), axis=axis)
        vals_ = np.take_along_axis(x[0], idx, axis=axis)
        return (vals_, idx.astype(np.int64))
    if op not in simple:
        raise NotImplementedError(f"evaluator: unsupported op {op}")
    return simple[op]()


def run_onnx(graph: Dict[str, Any], *inputs: np.ndarray
             ) -> List[np.ndarray]:
    """Evaluate a parsed graph on numpy inputs (topological node order as
    serialized — the exporter emits in dependency order)."""
    vals: Dict[str, np.ndarray] = dict(graph["initializers"])
    for name, arr in zip(graph["inputs"], inputs):
        vals[name] = np.asarray(arr)
    for node in graph["nodes"]:
        out = _eval_node(node, vals)
        outs = node["outputs"]
        if isinstance(out, tuple):
            if len(outs) != len(out):
                raise NotImplementedError("output arity mismatch")
            vals.update(zip(outs, out))
        elif len(outs) != 1:
            raise NotImplementedError("multi-output node")
        else:
            vals[outs[0]] = out
    return [vals[o] for o in graph["outputs"]]
