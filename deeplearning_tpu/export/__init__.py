from . import custom_call, serialize  # noqa: F401
