"""XLA FFI custom-call registration demo.

The "teach the compiler a new op" tutorial the reference does for ONNX
(others/deploy/pytorch2onnx: my_add.cpp + setup.py + support_new_ops.py
g.op symbolic). TPU-era flow: C++ handler built against jaxlib's FFI
headers (native/my_add.cc), registered for the Host platform, invoked
via jax.ffi.ffi_call — usable under jit and composable with everything
else (CPU callback path; a real TPU kernel would be Pallas instead, see
ops/pallas/).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "native")
_LOCK = threading.Lock()
_REGISTERED = False


def _ffi():
    """``jax.ffi`` graduated from ``jax.extend.ffi`` after 0.4.x; the
    two expose the same register/pycapsule/ffi_call surface."""
    try:
        import jax.ffi as ffi
    except ImportError:
        import jax.extend.ffi as ffi
    return ffi


def _build() -> Optional[str]:
    src = os.path.join(_DIR, "my_add.cc")
    out = os.path.join(_DIR, "libmy_add.so")
    if os.path.exists(out) and \
            os.path.getmtime(out) >= os.path.getmtime(src):
        return out
    cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17",
           f"-I{_ffi().include_dir()}", src, "-o", out]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=180)
        return out
    except (subprocess.CalledProcessError, FileNotFoundError,
            subprocess.TimeoutExpired):
        return None


def register() -> bool:
    """Compile + register the MyAdd FFI handler (idempotent). Returns
    False when no host compiler is available."""
    global _REGISTERED
    with _LOCK:
        if _REGISTERED:
            return True
        path = _build()
        if path is None:
            return False
        lib = ctypes.CDLL(path)
        ffi = _ffi()
        ffi.register_ffi_target(
            "my_add", ffi.pycapsule(lib.MyAdd), platform="cpu")
        _REGISTERED = True
        return True


def my_add(a: jax.Array, b: jax.Array) -> jax.Array:
    """3a + 2b via the native handler (my_add.cpp semantics)."""
    if not register():
        raise RuntimeError("no host toolchain to build the FFI demo")
    call = _ffi().ffi_call(
        "my_add", jax.ShapeDtypeStruct(a.shape, jnp.float32))
    return call(a.astype(jnp.float32), b.astype(jnp.float32))
