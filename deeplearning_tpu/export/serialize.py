"""Model export: StableHLO serialization + TF SavedModel + param I/O.

The deployment-path successor (SURVEY.md L7): where the reference exports
TorchScript/ONNX/TensorRT/CoreML (yolov5 export.py:29-159, YOLOX
tools/export_onnx.py, others/deploy/*), the TPU-era flow is:

- ``export_stablehlo``: jax.export → portable StableHLO bytes (the IR
  every XLA-based runtime consumes; the ONNX analog).
- ``export_savedmodel``: jax2tf → TF SavedModel (the TF-serving /
  TFLite-converter entry; replaces the TensorRT engine-build path).
- RepVGG deploy conversion is models/classification/repvgg.reparameterize
  (structural re-param, convert.py analog).
"""

from __future__ import annotations

import os
from typing import Any, Callable, Optional, Sequence, Tuple

import jax
import jax.export  # noqa: F401  (0.4.x: submodule not loaded by jax/__init__)
import jax.numpy as jnp
import numpy as np


def export_stablehlo(fn: Callable, example_args: Sequence[Any],
                     path: Optional[str] = None) -> bytes:
    """Serialize a jittable fn to portable StableHLO bytes; reload with
    ``load_stablehlo``."""
    exported = jax.export.export(jax.jit(fn))(*example_args)
    blob = exported.serialize()
    if path:
        with open(path, "wb") as f:
            f.write(blob)
    return blob


def load_stablehlo(blob: bytes) -> Callable:
    exported = jax.export.deserialize(blob)
    return exported.call


def export_savedmodel(fn: Callable, example_args: Sequence[Any],
                      path: str) -> bool:
    """jax2tf → tf.saved_model.save. Returns False when TF is absent."""
    try:
        import tensorflow as tf
        from jax.experimental import jax2tf
    except ImportError:
        return False
    tf_fn = tf.function(
        jax2tf.convert(fn, with_gradient=False),
        autograph=False,
        input_signature=[
            tf.TensorSpec(np.shape(a), np.asarray(a).dtype, name=f"arg{i}")
            for i, a in enumerate(example_args)])
    module = tf.Module()
    module.f = tf_fn
    # explicit serving signature so native runners (C API,
    # native/savedmodel_runner.cc) find serving_default_arg0 /
    # StatefulPartitionedCall ops
    tf.saved_model.save(
        module, path,
        signatures={"serving_default": tf_fn.get_concrete_function()})
    return True


def flops_estimate(fn: Callable, *example_args) -> float:
    """Compiled-graph FLOPs from XLA cost analysis — the thop/fvcore
    FLOPs-counter successor (vision_transformer/flops.py, yolov5
    torch_utils.py:104). Delegates to utils/profiling.compiled_flops."""
    from ..utils.profiling import compiled_flops
    return compiled_flops(fn, *example_args)
