"""Deploy-time Conv+BatchNorm folding (yolov5 utils/torch_utils.py:211
``fuse_conv_and_bn`` analog) as a pure pytree transform.

Folds each BatchNorm's inference affine into the preceding conv's kernel
so the exported graph does one multiply less per channel and — more
usefully — so fused weights can be exported to runtimes that expect
conv-only graphs. The BN node is rewritten to an exact identity
(mean=0, var=0, scale=sqrt(eps)) rather than removed, because flax
module structure is static; applying the fused tree through the original
model reproduces the unfused outputs bit-for-bit up to one rounding.

Pairing is by the repo's naming convention (resnet.py, yolox.py,
hrnet.py ConvBN): a sibling ``bnX`` folds into ``convX``; ``bn`` into
``conv``; inside a ConvBN-style wrapper the children are literally
``conv``/``bn``. Explicit (conv_path, bn_path) pairs override.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp

__all__ = ["fuse_conv_bn"]


def _candidate_conv(bn_name: str, siblings) -> Optional[str]:
    for conv_name in (bn_name.replace("bn", "conv"),
                      bn_name.replace("_bn", "_conv"),
                      "conv" + bn_name[2:] if bn_name.startswith("bn") else ""):
        if conv_name and conv_name != bn_name and conv_name in siblings:
            return conv_name
    return None


def _walk(params: Dict, stats: Dict, path: Tuple[str, ...],
          found: List[Tuple[Tuple[str, ...], Tuple[str, ...]]]):
    bn_names = [k for k, v in params.items()
                if isinstance(v, dict) and "scale" in v
                and k in stats and "mean" in stats[k]]
    for bn in bn_names:
        conv = _candidate_conv(bn, params)
        if conv is not None and isinstance(params[conv], dict) \
                and "kernel" in params[conv] \
                and params[conv]["kernel"].ndim >= 2:
            found.append((path + (conv,), path + (bn,)))
    for key, value in params.items():
        if isinstance(value, dict):
            _walk(value, stats.get(key, {}), path + (key,), found)


def _get(tree: Dict, path: Sequence[str]) -> Dict:
    for p in path:
        tree = tree[p]
    return tree


def fuse_conv_bn(variables: Dict, *,
                 pairs: Optional[Sequence[Tuple[Sequence[str],
                                                Sequence[str]]]] = None,
                 eps=1e-5,
                 verify=None, verify_tol: float = 1e-3) -> Dict:
    """Return new ``{"params", "batch_stats"}`` with every detected
    (conv, bn) pair folded. Shapes and tree structure are unchanged, so
    the result applies through the original module with ``train=False``.

    ``eps`` MUST equal each BatchNorm module's epsilon — both the folded
    multiplier and the identity-BN rewrite depend on it, so a mismatch
    (e.g. fusing an eps=1e-3 model with the 1e-5 default) mis-scales
    every fused layer. Pass a callable ``eps('/'.join(bn_path)) -> float``
    for models mixing epsilons.

    ``verify``: optional ``f(variables) -> array`` (typically a closure
    over ``model.apply(..., train=False)`` on a probe batch). When given,
    the fused tree is applied through it and compared against the
    original's output; a max abs deviation above ``verify_tol`` raises —
    catching exactly the silent mis-pairing / wrong-epsilon failure the
    naming convention can't."""
    import jax

    params = jax.tree_util.tree_map(lambda x: x, variables["params"])
    stats = jax.tree_util.tree_map(lambda x: x, variables["batch_stats"])
    if pairs is None:
        auto: List[Tuple[Tuple[str, ...], Tuple[str, ...]]] = []
        _walk(params, stats, (), auto)
        pairs = auto

    for conv_path, bn_path in pairs:
        bn_eps = eps("/".join(bn_path)) if callable(eps) else float(eps)
        conv = _get(params, conv_path)
        bn = _get(params, bn_path)
        st = _get(stats, bn_path)
        gamma = jnp.asarray(bn["scale"], jnp.float32)
        beta = jnp.asarray(bn["bias"], jnp.float32)
        mean = jnp.asarray(st["mean"], jnp.float32)
        var = jnp.asarray(st["var"], jnp.float32)
        g = gamma * jax.lax.rsqrt(var + bn_eps)

        kernel = jnp.asarray(conv["kernel"])
        conv["kernel"] = (kernel.astype(jnp.float32) * g).astype(kernel.dtype)
        bias = jnp.asarray(conv.get("bias", jnp.zeros_like(mean)), jnp.float32)
        fused_bias = (bias - mean) * g + beta
        if "bias" in conv:
            conv["bias"] = fused_bias.astype(kernel.dtype)
            bn["bias"] = jnp.zeros_like(beta)
        else:
            # conv has no bias param; carry the shift in the identity BN
            bn["bias"] = fused_bias
        # (z - 0) / sqrt(0 + eps) * sqrt(eps) == z exactly in real math
        bn["scale"] = jnp.full_like(gamma, jnp.sqrt(jnp.float32(bn_eps)))
        st["mean"] = jnp.zeros_like(mean)
        st["var"] = jnp.zeros_like(var)

    fused = {"params": params, "batch_stats": stats}
    if verify is not None:
        import numpy as np
        ref = np.asarray(verify(variables), jnp.float32)
        got = np.asarray(verify(fused), jnp.float32)
        dev = float(np.max(np.abs(ref - got)))
        if not np.isfinite(dev) or dev > verify_tol:
            raise ValueError(
                f"fuse_conv_bn self-check failed: max|orig-fused|={dev:.3e} "
                f"> tol={verify_tol:.1e} — wrong epsilon or mis-paired "
                f"conv/bn (pass explicit pairs= or eps=)")
    return fused
