"""Loss functions shared across the zoo.

Consolidates the reference's per-project loss code into one module:
cross-entropy + label smoothing + soft-target CE (swin main.py:111-118,
TransFG losses/labelSmoothing.py), sigmoid focal loss (RetinaNet
network_files/losses.py:5-60 — pure-python fvcore port, here vectorized),
dice (U-Net loss/dice_score.py:5-36), OHEM CE (HR-Net-Seg
loss/OhemCrossEntropy.py:6), supervised-contrastive (SupCon
losses/SupConLoss.py:5), triplet + ArcFace (BDB utils/loss.py,
Happy-Whale retrieval/models/arcFaceloss.py:6), GIoU/IoU losses
(FCOS models/loss.py:311, YOLOX models/losses.py), smooth-L1
(fasterRcnn utils/det_utils.py:386), keypoint heatmap MSE
(Insulator utils/loss.py:6). All take logits/labels with a leading batch
dim and reduce with an explicit ``weights`` mask so padded/invalid entries
(the XLA static-shape idiom) drop out of the mean.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import optax


def _weighted_mean(x: jax.Array, weights: Optional[jax.Array]) -> jax.Array:
    if weights is None:
        return jnp.mean(x)
    weights = weights.astype(x.dtype)
    return jnp.sum(x * weights) / jnp.maximum(jnp.sum(weights), 1.0)


def _reduce(losses, weights, reduction):
    """Shared none/sum/weighted-mean reduction used by the loss family."""
    if weights is not None and reduction in ("none", "sum"):
        losses = losses * weights
    if reduction == "none":
        return losses
    if reduction == "sum":
        return jnp.sum(losses)
    return _weighted_mean(losses, weights)


def cross_entropy(logits: jax.Array, labels: jax.Array,
                  label_smoothing: float = 0.0,
                  weights: Optional[jax.Array] = None) -> jax.Array:
    """Integer-label CE with optional smoothing; labels < 0 are ignored
    (the ignore_index idiom of segmentation losses)."""
    num_classes = logits.shape[-1]
    valid = labels >= 0
    labels = jnp.where(valid, labels, 0)
    onehot = jax.nn.one_hot(labels, num_classes, dtype=logits.dtype)
    if label_smoothing > 0:
        onehot = onehot * (1 - label_smoothing) + label_smoothing / num_classes
    losses = optax.softmax_cross_entropy(logits, onehot)
    w = valid.astype(logits.dtype)
    if weights is not None:
        w = w * weights.astype(logits.dtype)
    return _weighted_mean(losses, w)


def soft_target_cross_entropy(logits: jax.Array, targets: jax.Array,
                              weights: Optional[jax.Array] = None) -> jax.Array:
    """CE against soft targets (mixup path, swin main.py:112)."""
    losses = optax.softmax_cross_entropy(logits, targets.astype(logits.dtype))
    return _weighted_mean(losses, weights)


def binary_cross_entropy(logits: jax.Array, targets: jax.Array,
                         weights: Optional[jax.Array] = None,
                         pos_weight: float = 1.0,
                         reduction: str = "mean") -> jax.Array:
    log_p = jax.nn.log_sigmoid(logits)
    log_not_p = jax.nn.log_sigmoid(-logits)
    losses = -(pos_weight * targets * log_p + (1.0 - targets) * log_not_p)
    return _reduce(losses, weights, reduction)


def sigmoid_focal_loss(logits: jax.Array, targets: jax.Array,
                       alpha: float = 0.25, gamma: float = 2.0,
                       weights: Optional[jax.Array] = None,
                       reduction: str = "mean") -> jax.Array:
    """RetinaNet focal loss (network_files/losses.py:5-60 surface)."""
    p = jax.nn.sigmoid(logits)
    ce = -(targets * jax.nn.log_sigmoid(logits)
           + (1 - targets) * jax.nn.log_sigmoid(-logits))
    p_t = p * targets + (1 - p) * (1 - targets)
    loss = ce * jnp.power(1 - p_t, gamma)
    if alpha >= 0:
        alpha_t = alpha * targets + (1 - alpha) * (1 - targets)
        loss = alpha_t * loss
    return _reduce(loss, weights, reduction)


def dice_coefficient(probs: jax.Array, targets: jax.Array,
                     eps: float = 1e-6, spatial_axes=(-2, -1)) -> jax.Array:
    """Per-channel dice coefficient (U-Net loss/dice_score.py:5)."""
    inter = jnp.sum(probs * targets, axis=spatial_axes)
    denom = jnp.sum(probs, axis=spatial_axes) + jnp.sum(targets, axis=spatial_axes)
    return jnp.mean((2 * inter + eps) / (denom + eps))


def dice_loss(logits: jax.Array, labels: jax.Array,
              num_classes: Optional[int] = None) -> jax.Array:
    """Multiclass dice loss over softmax probs (dice_score.py:26-36).
    logits: (B,H,W,C); labels: (B,H,W) int, <0 ignored."""
    num_classes = num_classes or logits.shape[-1]
    probs = jax.nn.softmax(logits, axis=-1)
    valid = (labels >= 0)[..., None]
    onehot = jax.nn.one_hot(jnp.where(labels >= 0, labels, 0), num_classes,
                            dtype=logits.dtype) * valid
    probs = probs * valid
    return 1.0 - dice_coefficient(
        jnp.moveaxis(probs, -1, 1), jnp.moveaxis(onehot, -1, 1))


def ohem_cross_entropy(logits: jax.Array, labels: jax.Array,
                       thresh: float = 0.7, min_kept: int = 100000) -> jax.Array:
    """Online hard-example mining CE (HR-Net-Seg OhemCrossEntropy.py:6):
    keep pixels whose correct-class prob < thresh, but at least min_kept,
    expressed as a fixed-shape top-k mask (XLA-safe)."""
    b = logits.shape[0]
    num_classes = logits.shape[-1]
    flat_logits = logits.reshape(-1, num_classes)
    flat_labels = labels.reshape(-1)
    valid = flat_labels >= 0
    safe_labels = jnp.where(valid, flat_labels, 0)
    probs = jax.nn.softmax(flat_logits, axis=-1)
    correct_p = jnp.take_along_axis(probs, safe_labels[:, None], axis=-1)[:, 0]
    correct_p = jnp.where(valid, correct_p, jnp.inf)
    k = min(min_kept * b, flat_labels.shape[0])
    kth = jnp.sort(correct_p)[k - 1]
    threshold = jnp.maximum(kth, thresh)
    keep = valid & (correct_p <= threshold)
    losses = optax.softmax_cross_entropy_with_integer_labels(
        flat_logits, safe_labels)
    return _weighted_mean(losses, keep)


def smooth_l1(pred: jax.Array, target: jax.Array, beta: float = 1.0 / 9,
              weights: Optional[jax.Array] = None,
              reduction: str = "mean") -> jax.Array:
    """Huber / smooth-L1 (fasterRcnn utils/det_utils.py:386)."""
    diff = jnp.abs(pred - target)
    loss = jnp.where(diff < beta, 0.5 * diff * diff / beta, diff - 0.5 * beta)
    return _reduce(loss, weights, reduction)


def supcon_loss(features: jax.Array, labels: jax.Array,
                temperature: float = 0.07) -> jax.Array:
    """Supervised contrastive loss (SupCon losses/SupConLoss.py:5).
    features: (B, V, D) L2-normalized views; labels: (B,)."""
    b, v, d = features.shape
    feats = features.reshape(b * v, d)
    anchor_labels = jnp.repeat(labels, v)
    sim = feats @ feats.T / temperature
    # numerical stability
    sim = sim - jax.lax.stop_gradient(jnp.max(sim, axis=1, keepdims=True))
    self_mask = 1.0 - jnp.eye(b * v, dtype=sim.dtype)
    pos_mask = (anchor_labels[:, None] == anchor_labels[None, :]).astype(
        sim.dtype) * self_mask
    exp_sim = jnp.exp(sim) * self_mask
    log_prob = sim - jnp.log(jnp.sum(exp_sim, axis=1, keepdims=True) + 1e-12)
    mean_log_prob_pos = jnp.sum(pos_mask * log_prob, axis=1) / jnp.maximum(
        jnp.sum(pos_mask, axis=1), 1.0)
    return -jnp.mean(mean_log_prob_pos)


def triplet_loss(embeddings: jax.Array, labels: jax.Array,
                 margin: float = 0.3) -> jax.Array:
    """Batch-hard triplet loss (BDB utils/loss.py TripletLoss surface):
    hardest positive / hardest negative per anchor within the batch."""
    dist = jnp.sqrt(jnp.maximum(
        jnp.sum((embeddings[:, None] - embeddings[None, :]) ** 2, -1), 1e-12))
    same = labels[:, None] == labels[None, :]
    eye = jnp.eye(labels.shape[0], dtype=bool)
    pos_mask = same & ~eye
    neg_mask = ~same
    hardest_pos = jnp.max(jnp.where(pos_mask, dist, -jnp.inf), axis=1)
    hardest_neg = jnp.min(jnp.where(neg_mask, dist, jnp.inf), axis=1)
    has_both = jnp.any(pos_mask, 1) & jnp.any(neg_mask, 1)
    loss = jnp.maximum(hardest_pos - hardest_neg + margin, 0.0)
    return _weighted_mean(loss, has_both)


def safe_normalize(x: jax.Array, axis: int = -1,
                   eps: float = 1e-6) -> jax.Array:
    """L2-normalize with a finite gradient at x == 0: ``jnp.linalg.norm``
    differentiates to NaN at exactly zero (sqrt'(0)), and an untrained
    ReLU backbone CAN emit an all-zero embedding for a dark image —
    rsqrt(max(|x|^2, eps^2)) keeps the zero row zero with gradient x/eps."""
    sq = jnp.sum(x * x, axis=axis, keepdims=True)
    return x * jax.lax.rsqrt(jnp.maximum(sq, eps * eps))


def arcface_logits(embeddings: jax.Array, weight: jax.Array,
                   labels: jax.Array, s: float = 64.0, m: float = 0.5
                   ) -> jax.Array:
    """ArcFace margin logits (Happy-Whale arcFaceloss.py:6: s=64, m=0.5).
    embeddings: (B,D); weight: (D,C) class centers. Returns scaled logits
    to feed cross_entropy."""
    emb = safe_normalize(embeddings, axis=-1)
    w = safe_normalize(weight, axis=0)
    cos = jnp.clip(emb @ w, -1 + 1e-7, 1 - 1e-7)
    theta = jnp.arccos(cos)
    target_cos = jnp.cos(theta + m)
    onehot = jax.nn.one_hot(labels, weight.shape[1], dtype=cos.dtype)
    return s * (onehot * target_cos + (1 - onehot) * cos)


def wnfc_logits(embeddings: jax.Array, weight: jax.Array,
                s: float = 64.0) -> jax.Array:
    """Weight-normalized FC logits (Happy-Whale arcFaceloss.py:58 wnfc):
    cosine classifier without the angular margin — scaled cos(theta)."""
    emb = safe_normalize(embeddings, axis=-1)
    w = safe_normalize(weight, axis=0)
    return s * (emb @ w)


def heatmap_mse_loss(pred: jax.Array, target: jax.Array,
                     visible: jax.Array) -> jax.Array:
    """Visibility-weighted keypoint-heatmap MSE (Insulator utils/loss.py:6).
    pred/target: (B,H,W,K); visible: (B,K) in {0,1,2} — >0 counts."""
    per_kp = jnp.mean(jnp.square(pred - target), axis=(1, 2))
    w = (visible > 0).astype(pred.dtype)
    return _weighted_mean(per_kp, w)
