"""Test-time augmentation (TTA) for inference.

Reference surface: yolov5's augmented inference — ``Model.forward_augment``
runs the net at scales (1, 0.83, 0.67) with a horizontal flip on the
second, de-scales each prediction set back to the input frame
(``models/yolo.py:183-244`` forward_augment/_descale_pred/_clip_augmented)
and concatenates before ONE non_max_suppression; plus the classification
flip-averaging idiom used across the zoo's predict scripts.

TPU-first formulation: every (scale, flip) variant is a *static* shape —
each runs as its own jit-compiled forward (same bucketed-static-shapes
policy as multi-scale training, data/multiscale.py), predictions are
de-scaled with pure array ops, merged along the anchor axis, and a single
fixed-shape padded NMS (ops/nms.py) suppresses across variants. No
dynamic shapes anywhere, so XLA caches one executable per scale.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

__all__ = ["flip_lr_boxes", "descale_boxes", "classify_tta", "yolox_tta"]


def flip_lr_boxes(boxes: jax.Array, width: float) -> jax.Array:
    """Mirror xyxy boxes horizontally inside an image of ``width``."""
    x1 = width - boxes[..., 2]
    x2 = width - boxes[..., 0]
    return jnp.stack([x1, boxes[..., 1], x2, boxes[..., 3]], axis=-1)


def descale_boxes(boxes: jax.Array, scale, flip_lr: bool,
                  width: float) -> jax.Array:
    """Map xyxy boxes predicted in a scaled(+flipped) frame back to the
    base frame (yolov5 _descale_pred, models/yolo.py:229: divide by the
    scale gain; un-mirror x for lr flips). ``width`` is the AUGMENTED
    frame's width (un-flip happens before un-scaling). ``scale`` is a
    float or an (sx, sy) pair when divisor rounding made the horizontal
    and vertical gains differ."""
    if flip_lr:
        boxes = flip_lr_boxes(boxes, width)
    sx, sy = scale if isinstance(scale, (tuple, list)) else (scale, scale)
    return boxes / jnp.asarray([sx, sy, sx, sy], boxes.dtype)


def classify_tta(logits_fn: Callable[[jax.Array], jax.Array],
                 images: jax.Array,
                 flip: bool = True,
                 extra_views: Sequence[Callable[[jax.Array], jax.Array]] = ()
                 ) -> jax.Array:
    """Average class PROBABILITIES over augmented views of NHWC images:
    identity + horizontal flip (+ caller-supplied view transforms).
    Returns the averaged probabilities. Softmax-then-mean (not
    logit-mean) matches the ensemble semantics of the reference's
    predict scripts."""
    views = [lambda x: x]
    if flip:
        views.append(lambda x: x[:, :, ::-1, :])
    views.extend(extra_views)
    return sum(jax.nn.softmax(logits_fn(v(images)), axis=-1)
               for v in views) / len(views)


def yolox_tta(raw_fn: Callable[[jax.Array], jax.Array],
              images: jax.Array,
              scales: Sequence[float] = (1.0, 0.83, 0.67),
              flips: Sequence[bool] = (False, True, False),
              size_divisor: int = 32,
              score_thresh: float = 0.01,
              nms_thresh: float = 0.65,
              max_det: int = 100,
              grid_fn=None,
              decode_fn=None,
              nms_impl: str = "auto") -> Dict[str, jax.Array]:
    """Multi-scale + flip TTA for the YOLOX family.

    ``raw_fn(images) -> (B, A, 5+C)`` is the model forward (apply bound
    with variables). Each (scale, flip) pair resizes the NHWC batch to a
    ``size_divisor``-aligned static shape, runs the forward, decodes on
    that scale's own anchor grid, de-scales boxes to the base frame, then
    every variant's decoded predictions are concatenated along A and
    suppressed by one fixed-shape NMS — the TPU analog of yolov5
    forward_augment (scales/flips defaults match models/yolo.py:185-186).
    """
    from ..models.detection.yolox import (decode_outputs, postprocess_decoded,
                                          yolox_grid)
    grid_fn = grid_fn or yolox_grid
    decode_fn = decode_fn or decode_outputs

    b, h, w, c = images.shape
    merged = []
    for scale, flip in zip(scales, flips):
        sh = max(size_divisor,
                 int(round(h * scale / size_divisor)) * size_divisor)
        sw = max(size_divisor,
                 int(round(w * scale / size_divisor)) * size_divisor)
        view = images
        if (sh, sw) != (h, w):
            view = jax.image.resize(view, (b, sh, sw, c), "bilinear")
        if flip:
            view = view[:, :, ::-1, :]
        raw = raw_fn(view)
        centers, strides = grid_fn((sh, sw))
        dec = decode_fn(raw, jnp.asarray(centers), jnp.asarray(strides))
        boxes = descale_boxes(dec[..., :4], (sw / w, sh / h), flip,
                              float(sw))
        merged.append(jnp.concatenate([boxes, dec[..., 4:]], axis=-1))
    decoded = jnp.concatenate(merged, axis=1)
    return postprocess_decoded(decoded, score_thresh=score_thresh,
                               nms_thresh=nms_thresh, max_det=max_det,
                               nms_impl=nms_impl)
