"""Anchor↔GT matching + balanced sampling as masked fixed-shape ops.

Surface of detection/fasterRcnn/utils/det_utils.py: Matcher (:260 —
IoU-threshold assignment with allow_low_quality_matches) and
BalancedPositiveNegativeSampler (:7 — fixed pos/neg counts per image).
XLA form: gt boxes are padded to a fixed count with a validity mask;
matches are indices + category codes; "random" subsampling uses a
top-k-of-random-keys trick so the selected count is exact without
dynamic shapes.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

BELOW_LOW = -1
BETWEEN = -2


def match_anchors(iou: jax.Array, gt_valid: jax.Array,
                  high_threshold: float, low_threshold: float,
                  allow_low_quality: bool = True) -> jax.Array:
    """iou (G, A) with padded gt rows masked by gt_valid (G,) →
    matches (A,): gt index, or BELOW_LOW / BETWEEN codes."""
    iou = jnp.where(gt_valid[:, None], iou, -1.0)
    best_gt = jnp.argmax(iou, axis=0)                 # (A,)
    best_iou = jnp.max(iou, axis=0)
    matches = jnp.where(
        best_iou >= high_threshold, best_gt,
        jnp.where(best_iou >= low_threshold, BETWEEN, BELOW_LOW))
    if allow_low_quality:
        # for each valid gt, force-match its highest-IoU anchors (ties
        # incl.). torchvision's Matcher restores the anchor's OWN
        # pre-threshold best match (all_matches), which may be a different
        # gt than the one it is best-anchor for — mirror that semantics.
        best_anchor_iou = jnp.max(iou, axis=1, keepdims=True)   # (G, 1)
        is_best = (iou >= best_anchor_iou - 1e-7) & (best_anchor_iou > 0) \
            & gt_valid[:, None]
        force = jnp.any(is_best, axis=0)
        matches = jnp.where(force, best_gt, matches)
    return matches


def balanced_sample(matches: jax.Array, rng: jax.Array,
                    batch_size_per_image: int, positive_fraction: float
                    ) -> Tuple[jax.Array, jax.Array]:
    """Select up to num_pos positives and (batch - num_pos) negatives,
    uniformly at random, as boolean masks (pos_mask, neg_mask) over anchors.

    Exact-count random subset under static shapes: give each candidate a
    random key, keep the top-k keys among candidates.
    """
    a = matches.shape[0]
    pos_cand = matches >= 0
    neg_cand = matches == BELOW_LOW
    num_pos_target = int(batch_size_per_image * positive_fraction)

    k_pos, k_neg = jax.random.split(rng)

    def pick(cand, key, limit):
        n_cand = jnp.sum(cand)
        take = jnp.minimum(n_cand, limit)
        scores = jnp.where(cand, jax.random.uniform(key, (a,)), -1.0)
        # rank by random score; the top `take` candidates win
        order = jnp.argsort(-scores)
        rank = jnp.zeros((a,), jnp.int32).at[order].set(jnp.arange(a))
        return cand & (rank < take)

    pos_mask = pick(pos_cand, k_pos, num_pos_target)
    num_pos = jnp.sum(pos_mask)
    neg_mask = pick(neg_cand, k_neg, batch_size_per_image - num_pos)
    return pos_mask, neg_mask
