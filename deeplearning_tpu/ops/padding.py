"""Torch-semantics conv padding.

torch's Conv2d(k, s, p=(k-1)//2) pads symmetrically (== k//2 for the odd
kernels torch models use); XLA's "SAME" pads
asymmetrically ((0,1) at stride 2 for k=3), which shifts sampling centers
and breaks weight-port parity with the reference models (see
tests/test_reference_parity.py). Use ``torch_pad(k)`` for any conv whose
reference counterpart is a torch Conv2d with p=k//2 — identical to SAME at
stride 1 (odd k), torch-correct at stride 2.

(The MadNet family is the exception: its reference reimplements TF SAME,
so those convs keep padding="SAME".)
"""

from __future__ import annotations

from typing import List, Tuple


def torch_pad(kernel: int, dilation: int = 1) -> List[Tuple[int, int]]:
    """Explicit symmetric padding equal to torch's p = dilation*(k-1)//2."""
    p = dilation * (kernel - 1) // 2
    return [(p, p), (p, p)]
