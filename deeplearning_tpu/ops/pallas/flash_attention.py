"""Flash attention for TPU: fused online-softmax attention in Pallas.

This is the framework's answer to the reference's attention hot paths —
the naive materialized softmax in ViT (classification/vision_transformer/
vit_model.py:100-111) and the CUDA window kernel motivation in Swin
(SURVEY.md §2.10.1): never materialize the (N, N) attention matrix in HBM.
Forward and backward are Pallas kernels with a custom VJP; the backward
recomputes P = exp(S - LSE) blockwise from the saved logsumexp, FlashAttention-2
style.

Ring attention (parallel/ring_attention.py) is the sequence-parallel
counterpart; ``flash_attention_with_lse`` exposes the per-row logsumexp
so the ring's online-softmax merge can combine per-chunk kernel outputs
exactly — blockwise HBM savings and ring scaling stack.

Layout: (B, H, N, D). N must be a multiple of the block size — wrappers
pad and mask via ``kv_len`` (the number of valid key tokens).
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .common import interpret_mode

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
NEG_INF = -1e30


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *,
                sm_scale: float, block_k: int, kv_len: int, causal: bool,
                q_block: int):
    # q_ref: (1, block_q, d); k_ref/v_ref: (1, n, d); o_ref like q_ref;
    # lse_ref: (1, block_q, 8) — 8-lane padded, lane 0 meaningful.
    qi = pl.program_id(1)
    q = q_ref[0]  # native dtype (bf16 in production) -> MXU full rate
    n = k_ref.shape[1]
    nk = n // block_k

    def body(ki, carry):
        acc, m_prev, l_prev = carry
        k = k_ref[0, pl.ds(ki * block_k, block_k), :]
        v = v_ref[0, pl.ds(ki * block_k, block_k), :]
        s = sm_scale * jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        col = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (q.shape[0], block_k), 1)
        mask = col < kv_len
        if causal:
            row = qi * q_block + jax.lax.broadcasted_iota(
                jnp.int32, (q.shape[0], block_k), 0)
            mask = mask & (col <= row)
        s = jnp.where(mask, s, NEG_INF)
        m_cur = jnp.max(s, axis=1)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=1)
        acc = acc * alpha[:, None] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return acc, m_new, l_new

    bq, d = q.shape
    acc = jnp.zeros((bq, d), jnp.float32)
    m0 = jnp.full((bq,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq,), jnp.float32)
    acc, m, l = jax.lax.fori_loop(0, nk, body, (acc, m0, l0))
    l_safe = jnp.maximum(l, 1e-30)
    o_ref[0] = (acc / l_safe[:, None]).astype(o_ref.dtype)
    lse = m + jnp.log(l_safe)
    lse_ref[0] = jnp.broadcast_to(lse[:, None], (lse.shape[0], 8))


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, *,
                   sm_scale: float, block_k: int, kv_len: int, causal: bool,
                   q_block: int):
    qi = pl.program_id(1)
    q = q_ref[0]
    do = do_ref[0]
    lse = lse_ref[0, :, 0]
    delta = delta_ref[0, :, 0]
    n = k_ref.shape[1]
    nk = n // block_k

    def body(ki, dq):
        k = k_ref[0, pl.ds(ki * block_k, block_k), :]
        v = v_ref[0, pl.ds(ki * block_k, block_k), :]
        s = sm_scale * jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        col = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (q.shape[0], block_k), 1)
        mask = col < kv_len
        if causal:
            row = qi * q_block + jax.lax.broadcasted_iota(
                jnp.int32, (q.shape[0], block_k), 0)
            mask = mask & (col <= row)
        p = jnp.where(mask, jnp.exp(s - lse[:, None]), 0.0)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * sm_scale
        dq = dq + jax.lax.dot_general(ds.astype(k.dtype), k,
                                      (((1,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        return dq

    dq = jax.lax.fori_loop(0, nk, body,
                           jnp.zeros(q.shape, jnp.float32))
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, *, sm_scale: float, block_q: int,
                    kv_len: int, causal: bool, k_block: int):
    ki = pl.program_id(1)
    k = k_ref[0]
    v = v_ref[0]
    n = q_ref.shape[1]
    nq = n // block_q
    col = ki * k_block + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, k.shape[0]), 1)

    def body(qi, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(qi * block_q, block_q), :]
        do = do_ref[0, pl.ds(qi * block_q, block_q), :]
        lse = lse_ref[0, pl.ds(qi * block_q, block_q), 0]
        delta = delta_ref[0, pl.ds(qi * block_q, block_q), 0]
        s = sm_scale * jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        mask = col < kv_len
        if causal:
            row = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, k.shape[0]), 0)
            mask = mask & (col <= row)
        p = jnp.where(mask, jnp.exp(s - lse[:, None]), 0.0)
        dv = dv + jax.lax.dot_general(p.astype(do.dtype), do,
                                      (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * sm_scale
        dk = dk + jax.lax.dot_general(ds.astype(q.dtype), q,
                                      (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        return dk, dv

    dk0 = jnp.zeros(k.shape, jnp.float32)
    dv0 = jnp.zeros(v.shape, jnp.float32)
    dk, dv = jax.lax.fori_loop(0, nq, body, (dk0, dv0))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _fwd_kernel_hb(q_ref, k_ref, v_ref, o_ref, lse_ref, *,
                   sm_scale: float, block_k: int, kv_len: int,
                   causal: bool, q_block: int):
    """Head-batched forward: blocks carry HB heads so each program feeds
    the MXU HB small matmuls in one batched dot_general — amortizes the
    per-program overhead that dominates at short N / small head dim."""
    qi = pl.program_id(1)
    q = q_ref[...]                      # (HB, block_q, d)
    n = k_ref.shape[1]
    nk = n // block_k
    hb, bq, d = q.shape

    def body(ki, carry):
        acc, m_prev, l_prev = carry
        k = k_ref[:, pl.ds(ki * block_k, block_k), :]
        v = v_ref[:, pl.ds(ki * block_k, block_k), :]
        s = sm_scale * jax.lax.dot_general(
            q, k, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)   # (HB, bq, block_k)
        col = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 2)
        mask = col < kv_len
        if causal:
            row = qi * q_block + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 1)
            mask = mask & (col <= row)
        s = jnp.where(mask, s, NEG_INF)
        m_cur = jnp.max(s, axis=2)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new[..., None])
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=2)
        acc = acc * alpha[..., None] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)
        return acc, m_new, l_new

    acc = jnp.zeros((hb, bq, d), jnp.float32)
    m0 = jnp.full((hb, bq), NEG_INF, jnp.float32)
    l0 = jnp.zeros((hb, bq), jnp.float32)
    acc, m, l = jax.lax.fori_loop(0, nk, body, (acc, m0, l0))
    l_safe = jnp.maximum(l, 1e-30)
    o_ref[...] = (acc / l_safe[..., None]).astype(o_ref.dtype)
    lse = m + jnp.log(l_safe)
    lse_ref[...] = jnp.broadcast_to(lse[..., None],
                                    lse.shape + (8,))


def _bwd_dq_kernel_hb(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                      dq_ref, *, sm_scale: float, block_k: int,
                      kv_len: int, causal: bool, q_block: int):
    """Head-batched dQ: every dot_general carries the HB batch dim, so
    one program amortizes HB heads (the short-N regime where per-program
    overhead dominates the per-head kernels)."""
    qi = pl.program_id(1)
    q = q_ref[...]                       # (HB, bq, d)
    do = do_ref[...]
    lse = lse_ref[..., 0]                # (HB, bq)
    delta = delta_ref[..., 0]
    n = k_ref.shape[1]
    nk = n // block_k

    def body(ki, dq):
        k = k_ref[:, pl.ds(ki * block_k, block_k), :]
        v = v_ref[:, pl.ds(ki * block_k, block_k), :]
        s = sm_scale * jax.lax.dot_general(
            q, k, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)   # (HB, bq, block_k)
        col = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 2)
        mask = col < kv_len
        if causal:
            row = qi * q_block + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 1)
            mask = mask & (col <= row)
        p = jnp.where(mask, jnp.exp(s - lse[..., None]), 0.0)
        dp = jax.lax.dot_general(do, v, (((2,), (2,)), ((0,), (0,))),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[..., None]) * sm_scale
        return dq + jax.lax.dot_general(
            ds.astype(k.dtype), k, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(0, nk, body, jnp.zeros(q.shape, jnp.float32))
    dq_ref[...] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel_hb(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                       dk_ref, dv_ref, *, sm_scale: float, block_q: int,
                       kv_len: int, causal: bool, k_block: int):
    ki = pl.program_id(1)
    k = k_ref[...]                       # (HB, bk, d)
    v = v_ref[...]
    n = q_ref.shape[1]
    nq = n // block_q
    col = ki * k_block + jax.lax.broadcasted_iota(
        jnp.int32, (k.shape[0], block_q, k.shape[1]), 2)

    def body(qi, carry):
        dk, dv = carry
        q = q_ref[:, pl.ds(qi * block_q, block_q), :]
        do = do_ref[:, pl.ds(qi * block_q, block_q), :]
        lse = lse_ref[:, pl.ds(qi * block_q, block_q), 0]
        delta = delta_ref[:, pl.ds(qi * block_q, block_q), 0]
        s = sm_scale * jax.lax.dot_general(
            q, k, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)   # (HB, bq, bk)
        mask = col < kv_len
        if causal:
            row = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 1)
            mask = mask & (col <= row)
        p = jnp.where(mask, jnp.exp(s - lse[..., None]), 0.0)
        dv = dv + jax.lax.dot_general(
            p.astype(do.dtype), do, (((1,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((2,), (2,)), ((0,), (0,))),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[..., None]) * sm_scale
        dk = dk + jax.lax.dot_general(
            ds.astype(q.dtype), q, (((1,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)
        return dk, dv

    dk0 = jnp.zeros(k.shape, jnp.float32)
    dv0 = jnp.zeros(v.shape, jnp.float32)
    dk, dv = jax.lax.fori_loop(0, nq, body, (dk0, dv0))
    dk_ref[...] = dk.astype(dk_ref.dtype)
    dv_ref[...] = dv.astype(dv_ref.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _flash_hb(q, k, v, sm_scale, kv_len, causal, block_q, block_k, hb):
    out, _ = _flash_hb_fwd(q, k, v, sm_scale, kv_len, causal, block_q,
                           block_k, hb)
    return out


def _flash_hb_fwd(q, k, v, sm_scale, kv_len, causal, block_q, block_k,
                  hb):
    b, h, n, d = q.shape
    qf, kf, vf = map(_flatten_bh, (q, k, v))
    grid = (b * h // hb, n // block_q)
    kernel = functools.partial(_fwd_kernel_hb, sm_scale=sm_scale,
                               block_k=block_k, kv_len=kv_len,
                               causal=causal, q_block=block_q)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((hb, block_q, d), lambda g, qi: (g, qi, 0)),
            pl.BlockSpec((hb, n, d), lambda g, qi: (g, 0, 0)),
            pl.BlockSpec((hb, n, d), lambda g, qi: (g, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((hb, block_q, d), lambda g, qi: (g, qi, 0)),
            pl.BlockSpec((hb, block_q, 8), lambda g, qi: (g, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, n, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, n, 8), jnp.float32),
        ],
        interpret=interpret_mode(),
    )(qf, kf, vf)
    return out.reshape(b, h, n, d), (q, k, v, out.reshape(b, h, n, d), lse)


def _flash_hb_bwd(sm_scale, kv_len, causal, block_q, block_k, hb, res,
                  dout):
    q, k, v, out, lse = res
    b, h, n, d = q.shape
    qf, kf, vf = map(_flatten_bh, (q, k, v))
    dof = _flatten_bh(dout)
    of = _flatten_bh(out)
    delta = jnp.sum(dof.astype(jnp.float32) * of.astype(jnp.float32),
                    axis=-1, keepdims=True)
    delta = jnp.broadcast_to(delta, (b * h, n, 8))

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel_hb, sm_scale=sm_scale,
                          block_k=block_k, kv_len=kv_len, causal=causal,
                          q_block=block_q),
        grid=(b * h // hb, n // block_q),
        in_specs=[
            pl.BlockSpec((hb, block_q, d), lambda g, qi: (g, qi, 0)),
            pl.BlockSpec((hb, n, d), lambda g, qi: (g, 0, 0)),
            pl.BlockSpec((hb, n, d), lambda g, qi: (g, 0, 0)),
            pl.BlockSpec((hb, block_q, d), lambda g, qi: (g, qi, 0)),
            pl.BlockSpec((hb, block_q, 8), lambda g, qi: (g, qi, 0)),
            pl.BlockSpec((hb, block_q, 8), lambda g, qi: (g, qi, 0)),
        ],
        out_specs=pl.BlockSpec((hb, block_q, d), lambda g, qi: (g, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, n, d), q.dtype),
        interpret=interpret_mode(),
    )(qf, kf, vf, dof, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel_hb, sm_scale=sm_scale,
                          block_q=block_q, kv_len=kv_len, causal=causal,
                          k_block=block_k),
        grid=(b * h // hb, n // block_k),
        in_specs=[
            pl.BlockSpec((hb, n, d), lambda g, ki: (g, 0, 0)),
            pl.BlockSpec((hb, block_k, d), lambda g, ki: (g, ki, 0)),
            pl.BlockSpec((hb, block_k, d), lambda g, ki: (g, ki, 0)),
            pl.BlockSpec((hb, n, d), lambda g, ki: (g, 0, 0)),
            pl.BlockSpec((hb, n, 8), lambda g, ki: (g, 0, 0)),
            pl.BlockSpec((hb, n, 8), lambda g, ki: (g, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((hb, block_k, d), lambda g, ki: (g, ki, 0)),
            pl.BlockSpec((hb, block_k, d), lambda g, ki: (g, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, n, d), k.dtype),
            jax.ShapeDtypeStruct((b * h, n, d), v.dtype),
        ],
        interpret=interpret_mode(),
    )(qf, kf, vf, dof, lse, delta)

    unflat = lambda x: x.reshape(b, h, n, d)
    return unflat(dq), unflat(dk), unflat(dv)


_flash_hb.defvjp(_flash_hb_fwd, _flash_hb_bwd)


def flash_attention_hb(q, k, v, *, sm_scale=None, causal=False,
                       block_q: int = DEFAULT_BLOCK_Q,
                       block_k: int = DEFAULT_BLOCK_K,
                       head_block: int = 4):
    """Head-batched flash attention (B, H, N, D), trainable: forward AND
    backward kernels batch ``head_block`` heads per program, amortizing
    program overhead in the short-N regime (ViT N=197, MAE N=50) where
    the per-head kernels lose to naive XLA attention."""
    b, h, n, d = q.shape
    if sm_scale is None:
        sm_scale = d ** -0.5
    while h % head_block:
        head_block //= 2
    head_block = max(head_block, 1)
    block_q, block_k, _, (q, k, v) = _blocks_and_pad(n, block_q, block_k,
                                                     q, k, v)
    out = _flash_hb(q, k, v, sm_scale, n, causal, block_q, block_k,
                    head_block)
    return out[:, :, :n, :]


def _flatten_bh(x):
    b, h, n, d = x.shape
    return x.reshape(b * h, n, d)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, sm_scale, kv_len, causal, block_q, block_k):
    out, _ = _flash_fwd(q, k, v, sm_scale, kv_len, causal, block_q, block_k)
    return out


def _flash_fwd(q, k, v, sm_scale, kv_len, causal, block_q, block_k):
    b, h, n, d = q.shape
    qf, kf, vf = map(_flatten_bh, (q, k, v))
    grid = (b * h, n // block_q)
    kernel = functools.partial(_fwd_kernel, sm_scale=sm_scale,
                               block_k=block_k, kv_len=kv_len, causal=causal,
                               q_block=block_q)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, n, d), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((1, n, d), lambda bh, qi: (bh, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, block_q, 8), lambda bh, qi: (bh, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, n, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, n, 8), jnp.float32),
        ],
        interpret=interpret_mode(),
    )(qf, kf, vf)
    out = out.reshape(b, h, n, d)
    return out, (q, k, v, out, lse)


def _flash_bwd(sm_scale, kv_len, causal, block_q, block_k, res, dout):
    q, k, v, out, lse = res
    b, h, n, d = q.shape
    qf, kf, vf = map(_flatten_bh, (q, k, v))
    dof = _flatten_bh(dout)
    of = _flatten_bh(out)
    # delta_i = rowsum(dO_i * O_i); stored (bh, n, 8) like lse
    delta = jnp.sum(dof.astype(jnp.float32) * of.astype(jnp.float32),
                    axis=-1, keepdims=True)
    delta = jnp.broadcast_to(delta, (b * h, n, 8))
    dqf, dkf, dvf = _bwd_calls(qf, kf, vf, dof, lse, delta,
                               sm_scale=sm_scale, kv_len=kv_len,
                               causal=causal, block_q=block_q,
                               block_k=block_k)
    unflat = lambda x: x.reshape(b, h, n, d)
    return unflat(dqf), unflat(dkf), unflat(dvf)


def _bwd_calls(qf, kf, vf, dof, lse, delta, *, sm_scale, kv_len, causal,
               block_q, block_k, out_dtype=None):
    """The two backward pallas_calls over flattened (BH, N, D) operands
    with caller-supplied lse/delta (BH, N, 8). Shared by the plain VJP
    and by ring attention's chunk backward (which passes the GLOBAL
    logsumexp/delta so per-chunk gradients sum to the exact full-sequence
    gradient). ``out_dtype`` overrides the gradients' dtype (the ring
    accumulates per-chunk grads in f32, so bf16 round trips per ring
    step would otherwise lose precision)."""
    bh, n, d = qf.shape

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, sm_scale=sm_scale, block_k=block_k,
                          kv_len=kv_len, causal=causal, q_block=block_q),
        grid=(bh, n // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, n, d), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((1, n, d), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, block_q, 8), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, block_q, 8), lambda bh, qi: (bh, qi, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, n, d), out_dtype or qf.dtype),
        interpret=interpret_mode(),
    )(qf, kf, vf, dof, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, sm_scale=sm_scale,
                          block_q=block_q, kv_len=kv_len, causal=causal,
                          k_block=block_k),
        grid=(bh, n // block_k),
        in_specs=[
            pl.BlockSpec((1, n, d), lambda bh, ki: (bh, 0, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, ki: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, ki: (bh, ki, 0)),
            pl.BlockSpec((1, n, d), lambda bh, ki: (bh, 0, 0)),
            pl.BlockSpec((1, n, 8), lambda bh, ki: (bh, 0, 0)),
            pl.BlockSpec((1, n, 8), lambda bh, ki: (bh, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda bh, ki: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, ki: (bh, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, n, d), out_dtype or kf.dtype),
            jax.ShapeDtypeStruct((bh, n, d), out_dtype or vf.dtype),
        ],
        interpret=interpret_mode(),
    )(qf, kf, vf, dof, lse, delta)

    return dq, dk, dv


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                    sm_scale: Optional[float] = None,
                    causal: bool = False,
                    block_q: int = DEFAULT_BLOCK_Q,
                    block_k: int = DEFAULT_BLOCK_K) -> jax.Array:
    """Fused attention. q,k,v: (B, H, N, D) with any N — padded internally
    to a block multiple; padded KEY positions are masked out and padded
    QUERY rows are dropped on return. D should be 64/128 for best MXU use.
    """
    b, h, n, d = q.shape
    if sm_scale is None:
        sm_scale = d ** -0.5
    block_q, block_k, _, (q, k, v) = _blocks_and_pad(n, block_q, block_k,
                                                     q, k, v)
    out = _flash(q, k, v, sm_scale, n, causal, block_q, block_k)
    return out[:, :, :n, :]


def flash_attention_with_lse(q: jax.Array, k: jax.Array, v: jax.Array, *,
                             sm_scale: Optional[float] = None,
                             causal: bool = False,
                             block_q: int = DEFAULT_BLOCK_Q,
                             block_k: int = DEFAULT_BLOCK_K):
    """Forward pass returning (out, lse): out (B, H, N, D) and the
    per-row logsumexp (B, H, N) of the scaled scores. This is the hook
    ring attention uses to merge per-chunk kernel results exactly —
    chunks combine as out = Σᵢ outᵢ·exp(lseᵢ − LSE), LSE = logsumexpᵢ.
    Forward-only (no custom VJP through the pair)."""
    b, h, n, d = q.shape
    if sm_scale is None:
        sm_scale = d ** -0.5
    block_q, block_k, n_pad, (q, k, v) = _blocks_and_pad(
        n, block_q, block_k, q, k, v)
    out, res = _flash_fwd(q, k, v, sm_scale, n, causal, block_q, block_k)
    lse = res[4][:, :, 0].reshape(b, h, n + n_pad)
    return out[:, :, :n, :], lse[:, :, :n]


def flash_chunk_grads(q: jax.Array, k: jax.Array, v: jax.Array,
                      do: jax.Array, lse: jax.Array, delta: jax.Array, *,
                      sm_scale: Optional[float] = None,
                      block_q: int = DEFAULT_BLOCK_Q,
                      block_k: int = DEFAULT_BLOCK_K):
    """(dq, dk, dv) of attention over ONE KV chunk given the GLOBAL
    softmax statistics: ``lse``/``delta`` (B, H, Nq) are the full-sequence
    logsumexp and rowsum(dO·O). Because dS_ij = P_ij·(dP_ij − delta_i)
    with P taken against the global LSE, per-chunk gradients computed
    this way sum over chunks to the exact full-attention gradient — this
    is ring attention's backward building block (Liu & Abbeel, ring
    attention; same decomposition as FlashAttention-2's dKV pass).

    q/do: (B, H, Nq, D); k/v: (B, H, Nk, D) with Nq == Nk (equal ring
    chunks). Gradients come back in float32 (the caller accumulates
    across ring steps)."""
    b, h, n, d = q.shape
    if k.shape[2] != n:
        raise ValueError(f"ring chunks must be equal: Nq={n} "
                         f"Nk={k.shape[2]}")
    if sm_scale is None:
        sm_scale = d ** -0.5
    block_q, block_k, n_pad, (q, k, v, do) = _blocks_and_pad(
        n, block_q, block_k, q, k, v, do)
    if n_pad:
        pad3 = [(0, 0), (0, 0), (0, n_pad)]
        # padded query rows: do rows are zero, so any finite lse/delta
        # yields zero contributions to dk/dv (ds == 0, p^T do == 0)
        lse = jnp.pad(lse, pad3)
        delta = jnp.pad(delta, pad3)
    np_ = n + n_pad
    qf, kf, vf, dof = map(_flatten_bh, (q, k, v, do))
    lse8 = jnp.broadcast_to(
        lse.astype(jnp.float32).reshape(b * h, np_, 1), (b * h, np_, 8))
    delta8 = jnp.broadcast_to(
        delta.astype(jnp.float32).reshape(b * h, np_, 1), (b * h, np_, 8))
    # f32 gradients: the ring accumulates per-chunk grads across
    # axis_size steps — bf16 round trips each step would compound error
    dqf, dkf, dvf = _bwd_calls(qf, kf, vf, dof, lse8, delta8,
                               sm_scale=sm_scale, kv_len=n, causal=False,
                               block_q=block_q, block_k=block_k,
                               out_dtype=jnp.float32)
    unflat = lambda x: x.reshape(b, h, np_, d)[:, :, :n, :]
    return unflat(dqf), unflat(dkf), unflat(dvf)


def _round_block(n: int) -> int:
    """Largest power-of-two block <= max(n, 128) capped at 128, >=8."""
    b = 128
    while b > 8 and b > n:
        b //= 2
    return max(b, 8)


def _blocks_and_pad(n, block_q, block_k, *arrays):
    """Clamp block sizes to the sequence and zero-pad every (B, H, N, D)
    array along N to the blocks' lcm. Returns (block_q, block_k, n_pad,
    padded_arrays) — the one place the padding policy lives."""
    block_q = min(block_q, _round_block(n))
    block_k = min(block_k, _round_block(n))
    n_pad = -n % math.lcm(block_q, block_k)
    if n_pad:
        pad = [(0, 0), (0, 0), (0, n_pad), (0, 0)]
        arrays = tuple(jnp.pad(t, pad) for t in arrays)
    return block_q, block_k, n_pad, arrays


def flash_attention_bnhd(q: jax.Array, k: jax.Array, v: jax.Array,
                         **kw) -> jax.Array:
    """(B, N, H, D) layout convenience wrapper (the models' layout)."""
    out = flash_attention(q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
                          v.transpose(0, 2, 1, 3), **kw)
    return out.transpose(0, 2, 1, 3)
