"""Shared Pallas helpers."""

from __future__ import annotations

import jax


def interpret_mode() -> bool:
    """True when kernels must run in interpret mode (CPU backend — used by
    the virtual-device test mesh and multi-chip dry-runs)."""
    return jax.default_backend() == "cpu"
