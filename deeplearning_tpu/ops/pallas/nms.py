"""Blocked bitmask NMS as a Pallas TPU kernel.

Same algorithm as ``ops.nms.nms_blocked`` (sort by score once, sweep
B-wide blocks in score order, suppress later candidates per block) with
the tile math inside one Pallas kernel so the whole sweep runs out of
VMEM: grid programs execute *sequentially* on TPU, and the alive vector
is an output block whose index_map is constant across the grid, so it
stays resident in VMEM and program i sees program i-1's suppressions —
the same revisited-accumulator pattern a matmul uses for its K loop.

Layout choices (all picked to avoid in-kernel transposes):

- ``boxes_blk`` (Npad, 8): row-major candidates, cols 0..3 = x1 y1 x2 y2
  (lane-padded to 8). Block i's rows slice out as (B, 1) columns.
- ``boxes_all`` (8, Npad): the same boxes transposed, rows 0..3 the
  coordinates (sublane-padded to 8 — the f32 min tile, same trick as
  flash attention's (…, 8) lse). Any column block slices out as (1, B).
  Broadcasting (B,1) against (1,B) gives the (B, B) IoU tile directly.
- ``alive`` (8, Npad) f32 0/1, row 0 meaningful. The suppression
  reduction is a matmul — hits(1,B) = keep(1,B) @ [iou>th](B,B) — which
  keeps the reduction on the MXU instead of a cross-lane reduce.

Per-program VMEM: one (B, B) f32 tile (256 KB at B=256) + the resident
boxes/alive rows (~1 MB at N=20k) — far under the ~16 MB budget; the
N×N IoU matrix is never materialized anywhere.

``interpret=interpret_mode()`` makes the kernel run (and get property
tested) on CPU; on a TPU backend ``ops.nms.nms(impl="auto")`` routes
here for N >= 1024.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ..nms import DEFAULT_BLOCK_SIZE, _emit_from_alive, sort_pad_candidates
from .common import interpret_mode


def _nms_sweep_kernel(boxes_blk_ref, boxes_all_ref, alive_init_ref,
                      alive_ref, *, iou_threshold: float, block: int,
                      nb: int):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        alive_ref[...] = alive_init_ref[...]

    start = i * block
    # This block's boxes as (B, 1) columns; areas precomputed once.
    bx1 = boxes_blk_ref[:, 0:1]
    by1 = boxes_blk_ref[:, 1:2]
    bx2 = boxes_blk_ref[:, 2:3]
    by2 = boxes_blk_ref[:, 3:4]
    barea = jnp.maximum(bx2 - bx1, 0.0) * jnp.maximum(by2 - by1, 0.0)

    def iou_tile(cs):
        """(B, B) IoU of this block's boxes vs columns [cs, cs+B)."""
        cx1 = boxes_all_ref[0:1, pl.ds(cs, block)]
        cy1 = boxes_all_ref[1:2, pl.ds(cs, block)]
        cx2 = boxes_all_ref[2:3, pl.ds(cs, block)]
        cy2 = boxes_all_ref[3:4, pl.ds(cs, block)]
        iw = jnp.maximum(jnp.minimum(bx2, cx2) - jnp.maximum(bx1, cx1), 0.0)
        ih = jnp.maximum(jnp.minimum(by2, cy2) - jnp.maximum(by1, cy1), 0.0)
        inter = iw * ih
        carea = jnp.maximum(cx2 - cx1, 0.0) * jnp.maximum(cy2 - cy1, 0.0)
        union = barea + carea - inter
        return inter / jnp.maximum(union, 1e-9)

    # --- intra-block: fixed point of the strictly-upper-triangular
    # suppression relation == the greedy keep set (see ops.nms).
    row = jax.lax.broadcasted_iota(jnp.int32, (block, block), 0)
    col = jax.lax.broadcasted_iota(jnp.int32, (block, block), 1)
    sup_in = jnp.where((iou_tile(start) > iou_threshold) & (row < col),
                       1.0, 0.0)
    blk_alive = alive_ref[0:1, pl.ds(start, block)]

    def fp_cond(state):
        return state[1]

    def fp_body(state):
        keep, _ = state
        hits = jax.lax.dot_general(keep, sup_in, (((1,), (0,)), ((), ())),
                                   preferred_element_type=jnp.float32)
        new = blk_alive * (hits < 0.5).astype(jnp.float32)
        return new, jnp.any(new != keep)

    keep, _ = jax.lax.while_loop(fp_cond, fp_body,
                                 (blk_alive, jnp.asarray(True)))
    alive_ref[0:1, pl.ds(start, block)] = keep

    # --- cross-suppress every later column block with one (B, B) tile
    # each; hits(1,B) = keep(1,B) @ [iou>th](B,B) counts kept suppressors.
    def cross(j, _):
        cs = j * block
        sup = jnp.where(iou_tile(cs) > iou_threshold, 1.0, 0.0)
        hits = jax.lax.dot_general(keep, sup, (((1,), (0,)), ((), ())),
                                   preferred_element_type=jnp.float32)
        colblk = alive_ref[0:1, pl.ds(cs, block)]
        alive_ref[0:1, pl.ds(cs, block)] = \
            colblk * (hits < 0.5).astype(jnp.float32)
        return 0

    jax.lax.fori_loop(i + 1, nb, cross, 0)


def nms_pallas(boxes: jax.Array, scores: jax.Array, iou_threshold: float,
               max_out: int, score_threshold: float = float("-inf"),
               block_size: int = DEFAULT_BLOCK_SIZE
               ) -> Tuple[jax.Array, jax.Array]:
    """Pallas blocked NMS — identical contract and keep set as
    ``ops.nms.nms_reference`` / ``nms_blocked``: boxes (N,4), scores
    (N,) → (idx (max_out,), valid (max_out,) bool)."""
    block = int(min(block_size, max(8, boxes.shape[0])))
    sboxes, alive0, order, nb = sort_pad_candidates(
        boxes, scores, score_threshold, block)
    npad = alive0.shape[0]
    f32 = jnp.float32
    boxes_blk = jnp.zeros((npad, 8), f32).at[:, :4].set(sboxes.astype(f32))
    boxes_all = jnp.zeros((8, npad), f32).at[:4, :].set(
        sboxes.astype(f32).T)
    alive_init = jnp.broadcast_to(alive0.astype(f32)[None, :], (8, npad))

    kernel = functools.partial(_nms_sweep_kernel,
                               iou_threshold=float(iou_threshold),
                               block=block, nb=nb)
    alive = pl.pallas_call(
        kernel,
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((block, 8), lambda i: (i, 0)),
            pl.BlockSpec((8, npad), lambda i: (0, 0)),
            pl.BlockSpec((8, npad), lambda i: (0, 0)),
        ],
        # Constant index_map: the alive row stays VMEM-resident across
        # the (sequential) grid so later programs see earlier writes.
        out_specs=pl.BlockSpec((8, npad), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((8, npad), f32),
        interpret=interpret_mode(),
    )(boxes_blk, boxes_all, alive_init)
    return _emit_from_alive(alive[0] > 0.5, order, max_out)
