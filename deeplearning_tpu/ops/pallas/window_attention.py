"""Fused window attention — the Swin CUDA kernel's TPU-era successor.

The reference hand-fuses roll+partition in CUDA (classification/
swin_transformer/kernels/window_process/swin_window_process_kernel.cu:41-64)
because torch dispatches each of roll/view/permute as a separate kernel. On
TPU, XLA already fuses those copies; what XLA does NOT do is keep the
per-window attention matrix out of HBM. So the Pallas kernel here fuses the
ATTENTION: for a block of windows at once — QK^T, +relative-position bias,
+shift mask, softmax, PV — entirely in VMEM, batched over (windows ×
heads) so the MXU sees one big batched matmul per program.

Works on pre-partitioned qkv (use ops/window_utils.window_partition, whose
roll/reshape XLA fuses into the producing matmul's epilogue). The bias and
shift mask are pre-combined host-side into one additive (nW, heads, Np, Np)
tensor whose block is selected per program via the index map — no gather in
the kernel.

Token count N (e.g. 49) is padded to a sublane multiple; padded KEY
positions carry -inf in the combined bias so they vanish in the softmax.
Differentiable via jax.custom_vjp? Not needed: the kernel is re-derived by
autodiff through a recompute wrapper (window N is tiny; recompute is free
relative to HBM traffic), see ``window_attention`` below.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from ..window_utils import windowed_attention_reference
from .common import interpret_mode


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def _attn_kernel(q_ref, k_ref, v_ref, bias_ref, o_ref, *, scale: float):
    # blocks: q/k/v (WB, heads, Np, d); bias (WB, heads, Np, Np).
    # (WB, heads) collapse to ONE batch dim for the dots — Mosaic's
    # tpu.matmul supports at most one batch dim (leading-dim reshapes are
    # layout no-ops in VMEM, so this costs nothing)
    wb, h, npad, d = q_ref.shape
    q = q_ref[...].reshape(wb * h, npad, d)
    k = k_ref[...].reshape(wb * h, npad, d)
    v = v_ref[...].reshape(wb * h, npad, d)
    s = jax.lax.dot_general(
        q, k, (((2,), (2,)), ((0,), (0,))),
        preferred_element_type=jnp.float32)          # (WB*heads, Np, Np)
    s = s * scale + bias_ref[...].reshape(wb * h, npad, npad)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    p = p / jnp.sum(p, axis=-1, keepdims=True)
    o = jax.lax.dot_general(
        p.astype(v.dtype), v, (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32)
    o_ref[...] = o.reshape(wb, h, npad, d).astype(o_ref.dtype)


def window_attention(qkv: jax.Array, bias: jax.Array,
                     mask: Optional[jax.Array] = None,
                     windows_per_block: int = 8) -> jax.Array:
    """Fused attention over partitioned windows.

    qkv:  (BW, N, 3, heads, d) — BW = batch*num_windows, N = window².
    bias: (heads, N, N) relative-position bias (trainable).
    mask: (nW, N, N) additive shift mask or None.
    Returns (BW, N, heads*d).
    """
    bw, n, three, heads, d = qkv.shape
    assert three == 3
    np_pad = _round_up(n, 8)
    nw = mask.shape[0] if mask is not None else 1
    wb = windows_per_block
    while wb > 1 and bw % wb:
        wb //= 2

    # combined additive term, (nW, heads, Np, Np); padded keys get -1e9
    comb = jnp.broadcast_to(bias[None].astype(jnp.float32),
                            (nw, heads, n, n))
    if mask is not None:
        comb = comb + mask[:, None].astype(jnp.float32)
    comb = jnp.pad(comb, ((0, 0), (0, 0), (0, np_pad - n),
                          (0, np_pad - n)), constant_values=-1e9)
    # tile so a WB-window block always sees its own mask rows: tiling to
    # lcm(nW, wb) makes block i's rows [(i*wb) % nW, ...] line up with the
    # index map's (i % (nb/wb)) block selection.
    if nw % wb:
        comb = jnp.tile(comb, (int(np.lcm(nw, wb) // nw), 1, 1, 1))
    nb = comb.shape[0]

    q = jnp.moveaxis(qkv[:, :, 0], 1, 2)   # (BW, heads, N, d)
    k = jnp.moveaxis(qkv[:, :, 1], 1, 2)
    v = jnp.moveaxis(qkv[:, :, 2], 1, 2)
    pad = ((0, 0), (0, 0), (0, np_pad - n), (0, 0))
    q, k, v = (jnp.pad(t, pad) for t in (q, k, v))

    grid = (bw // wb,)
    out = pl.pallas_call(
        functools.partial(_attn_kernel, scale=d ** -0.5),
        grid=grid,
        in_specs=[
            pl.BlockSpec((wb, heads, np_pad, d), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((wb, heads, np_pad, d), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((wb, heads, np_pad, d), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((wb, heads, np_pad, np_pad),
                         lambda i, _nb=nb // wb: (i % _nb, 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((wb, heads, np_pad, d),
                               lambda i: (i, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((bw, heads, np_pad, d), qkv.dtype),
        interpret=interpret_mode(),
    )(q, k, v, comb)
    out = out[:, :, :n, :]                  # drop padded query rows
    return jnp.moveaxis(out, 1, 2).reshape(bw, n, heads * d)


def window_attention_checkpointed(qkv, bias, mask=None, **kw):
    """Differentiable wrapper: forward runs the fused kernel; the custom
    VJP recomputes the backward through the lax reference (which DOES
    materialize per-window P matrices during the bwd pass — the fused
    saving applies to the forward only)."""

    @jax.custom_vjp
    def f(qkv, bias):
        return window_attention(qkv, bias, mask, **kw)

    def fwd(qkv, bias):
        return f(qkv, bias), (qkv, bias)

    def bwd(res, g):
        qkv, bias = res
        _, vjp = jax.vjp(
            lambda a, b: windowed_attention_reference(a, b, mask), qkv, bias)
        return vjp(g)

    f.defvjp(fwd, bwd)
    return f(qkv, bias)
