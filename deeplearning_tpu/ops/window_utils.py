"""Window partition/merge + shifted-window masks + relative position index.

Pure-lax reference implementations of Swin's window machinery
(classification/swin_transformer/models/swin_transformer.py: window_partition
:25, window_reverse :40, the shift mask construction :233-238, and the
relative-position-bias index :70-166). These are the golden path the Pallas
fused kernel (ops/pallas/window_attention.py) is tested against — the same
role unit_test.py played for the reference's CUDA kernel.

XLA note: roll + reshape/transpose fuse into a single copy on TPU, so
unlike CUDA there is no dispatch-overhead reason to hand-fuse partition;
the fusion win is keeping the per-window attention matrix out of HBM.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def window_partition(x: jax.Array, window: int) -> jax.Array:
    """(B, H, W, C) -> (B*nW, window*window, C)."""
    b, h, w, c = x.shape
    x = x.reshape(b, h // window, window, w // window, window, c)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(-1, window * window, c)


def window_merge(windows: jax.Array, window: int, h: int, w: int) -> jax.Array:
    """(B*nW, window*window, C) -> (B, H, W, C)."""
    c = windows.shape[-1]
    b = windows.shape[0] // ((h // window) * (w // window))
    x = windows.reshape(b, h // window, w // window, window, window, c)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(b, h, w, c)


def shift_window_mask(h: int, w: int, window: int, shift: int) -> np.ndarray:
    """Additive attention mask (nW, N, N) with 0 / -inf for shifted windows
    (swin_transformer.py:233-238 construction, computed host-side once)."""
    img = np.zeros((1, h, w, 1), np.float32)
    cnt = 0
    for hs in (slice(0, -window), slice(-window, -shift), slice(-shift, None)):
        for ws in (slice(0, -window), slice(-window, -shift),
                   slice(-shift, None)):
            img[:, hs, ws, :] = cnt
            cnt += 1
    # region ids are already laid out in the shifted frame — partition
    # directly, no roll (matches the reference construction). Pure numpy so
    # it stays host-side even when called during a jit trace.
    wins = img.reshape(1, h // window, window, w // window, window, 1)
    wins = wins.transpose(0, 1, 3, 2, 4, 5).reshape(-1, window * window)
    diff = wins[:, None, :] - wins[:, :, None]
    return np.where(diff != 0, -1e9, 0.0).astype(np.float32)


def relative_position_index(window: int) -> np.ndarray:
    """(N, N) index into the (2w-1)^2 relative-position-bias table
    (swin_transformer.py:82-96 arithmetic, host-side)."""
    coords = np.stack(np.meshgrid(np.arange(window), np.arange(window),
                                  indexing="ij"))           # (2, w, w)
    flat = coords.reshape(2, -1)
    rel = flat[:, :, None] - flat[:, None, :]                # (2, N, N)
    rel = rel.transpose(1, 2, 0).astype(np.int64)
    rel[:, :, 0] += window - 1
    rel[:, :, 1] += window - 1
    rel[:, :, 0] *= 2 * window - 1
    return (rel[:, :, 0] + rel[:, :, 1]).astype(np.int32)    # (N, N)


def windowed_attention_reference(
    qkv: jax.Array,            # (BW, N, 3, heads, d)
    bias: jax.Array,           # (heads, N, N) relative-position bias
    mask: Optional[jax.Array], # (nW, N, N) shift mask or None
) -> jax.Array:
    """Naive per-window attention — numerical golden path. Returns (BW, N,
    heads*d)."""
    bw, n, _, heads, d = qkv.shape
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]   # (BW, N, heads, d)
    scale = d ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q * scale, k).astype(jnp.float32)
    s = s + bias[None].astype(jnp.float32)
    if mask is not None:
        nw = mask.shape[0]
        s = s.reshape(bw // nw, nw, heads, n, n) + \
            mask[None, :, None].astype(jnp.float32)
        s = s.reshape(bw, heads, n, n)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v)
    return out.reshape(bw, n, heads * d)
