"""Attention dispatch: naive lax path vs Pallas flash kernel.

``get_attn_fn("flash")`` plugs into models' ``attn_fn`` slot
(models/classification/vit.py Attention). The naive path is the golden
reference; the flash path is the TPU production path. Attention dropout is
applied on the naive path only — flash attention ignores it (attn-dropout
is 0 in all reference training configs; ViT uses drop_path instead).
"""

from __future__ import annotations

from typing import Callable, Optional

import jax

from .pallas.flash_attention import flash_attention, flash_attention_hb


def _check_no_dropout(dropout_rate: float, deterministic: bool):
    if dropout_rate > 0.0 and not deterministic:
        raise NotImplementedError(
            "flash attention does not implement attention dropout; set "
            "attn_drop_rate=0 (use drop_path for regularization) or use "
            "the naive attention path.")


def flash_attn_adapter(q, k, v, dropout_rate: float = 0.0,
                       deterministic: bool = True,
                       rng: Optional[jax.Array] = None):
    """(B, N, H, D) adapter matching models' attn_fn signature (per-head
    kernel — the long-N path)."""
    _check_no_dropout(dropout_rate, deterministic)
    del rng
    t = lambda x: x.transpose(0, 2, 1, 3)
    return t(flash_attention(t(q), t(k), t(v)))


def flash_hb_adapter(q, k, v, dropout_rate: float = 0.0,
                     deterministic: bool = True,
                     rng: Optional[jax.Array] = None):
    """(B, N, H, D) adapter for the head-batched kernel — the short-N
    path (ViT/MAE token counts), trainable."""
    _check_no_dropout(dropout_rate, deterministic)
    del rng
    t = lambda x: x.transpose(0, 2, 1, 3)
    return t(flash_attention_hb(t(q), t(k), t(v)))


def sdpa_adapter(q, k, v, dropout_rate: float = 0.0,
                 deterministic: bool = True,
                 rng: Optional[jax.Array] = None):
    """(B, N, H, D) adapter over jax.nn.dot_product_attention — the
    XLA-native SDPA entry (can lower to a fused attention)."""
    _check_no_dropout(dropout_rate, deterministic)
    del rng
    return jax.nn.dot_product_attention(q, k, v)


def get_attn_fn(name: str = "flash") -> Optional[Callable]:
    if name in ("flash", "pallas"):
        return flash_attn_adapter
    if name in ("flash_hb", "pallas_hb", "head_batched"):
        return flash_hb_adapter
    if name in ("sdpa", "xla"):
        return sdpa_adapter
    if name in ("naive", "lax", "reference"):
        return None  # models fall back to their built-in naive path
    raise ValueError(f"Unknown attention implementation {name!r}")
