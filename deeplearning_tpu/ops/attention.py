"""Attention dispatch: naive lax path vs Pallas flash kernel.

``get_attn_fn("flash")`` plugs into models' ``attn_fn`` slot
(models/classification/vit.py Attention). The naive path is the golden
reference; the flash path is the TPU production path. Attention dropout is
applied on the naive path only — flash attention ignores it (attn-dropout
is 0 in all reference training configs; ViT uses drop_path instead).
"""

from __future__ import annotations

from typing import Callable, Optional

import jax

from .pallas.flash_attention import flash_attention_bnhd


def flash_attn_adapter(q, k, v, dropout_rate: float = 0.0,
                       deterministic: bool = True,
                       rng: Optional[jax.Array] = None):
    """(B, N, H, D) adapter matching models' attn_fn signature."""
    if dropout_rate > 0.0 and not deterministic:
        raise NotImplementedError(
            "flash attention does not implement attention dropout; set "
            "attn_drop_rate=0 (use drop_path for regularization) or use "
            "the naive attention path.")
    del rng
    return flash_attention_bnhd(q, k, v)


def get_attn_fn(name: str = "flash") -> Optional[Callable]:
    if name in ("flash", "pallas"):
        return flash_attn_adapter
    if name in ("naive", "lax", "reference"):
        return None  # models fall back to their built-in naive path
    raise ValueError(f"Unknown attention implementation {name!r}")
