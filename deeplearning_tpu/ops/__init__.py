from . import losses  # noqa: F401
