from . import anchors, attention, boxes, losses, matcher, nms, roi_align  # noqa: F401
from . import window_utils  # noqa: F401
from .padding import torch_pad  # noqa: F401
