"""Box ops: IoU, encode/decode, clip — fixed-shape and fully vectorized.

Surface of detection/fasterRcnn/utils/boxes.py (:143 box_iou) and
utils/det_utils.py (:137 BoxCoder encode/decode with weights and the
bbox_xform_clip guard), shared by RetinaNet (network_files/boxes.py) and
the YOLO heads. Boxes are (x1, y1, x2, y2); invalid/padded boxes are
handled by callers via masks (the XLA static-shape idiom) rather than by
shrinking arrays.
"""

from __future__ import annotations

import math
from typing import Tuple

import jax
import jax.numpy as jnp

BBOX_XFORM_CLIP = math.log(1000.0 / 16)


def box_area(boxes: jax.Array) -> jax.Array:
    return jnp.maximum(boxes[..., 2] - boxes[..., 0], 0) * \
        jnp.maximum(boxes[..., 3] - boxes[..., 1], 0)


def box_iou(boxes1: jax.Array, boxes2: jax.Array) -> jax.Array:
    """(N, 4) × (M, 4) → (N, M) IoU matrix."""
    area1 = box_area(boxes1)
    area2 = box_area(boxes2)
    lt = jnp.maximum(boxes1[:, None, :2], boxes2[None, :, :2])
    rb = jnp.minimum(boxes1[:, None, 2:], boxes2[None, :, 2:])
    wh = jnp.clip(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    union = area1[:, None] + area2[None, :] - inter
    return inter / jnp.maximum(union, 1e-9)


def generalized_box_iou(boxes1: jax.Array, boxes2: jax.Array) -> jax.Array:
    """GIoU matrix (FCOS models/loss.py:311 loss surface, matrix form)."""
    iou = box_iou(boxes1, boxes2)
    lt = jnp.minimum(boxes1[:, None, :2], boxes2[None, :, :2])
    rb = jnp.maximum(boxes1[:, None, 2:], boxes2[None, :, 2:])
    wh = jnp.clip(rb - lt, 0)
    hull = wh[..., 0] * wh[..., 1]
    area1 = box_area(boxes1)
    area2 = box_area(boxes2)
    inter = iou * (area1[:, None] + area2[None, :]) / (1 + iou)  # recover
    union = area1[:, None] + area2[None, :] - inter
    return iou - (hull - union) / jnp.maximum(hull, 1e-9)


def elementwise_box_iou(boxes1: jax.Array, boxes2: jax.Array,
                        kind: str = "iou") -> jax.Array:
    """Paired IoU/GIoU/DIoU/CIoU of equal-shaped (..., 4) boxes (yolov5
    utils/metrics.py bbox_iou surface — used by CIoU loss)."""
    lt = jnp.maximum(boxes1[..., :2], boxes2[..., :2])
    rb = jnp.minimum(boxes1[..., 2:], boxes2[..., 2:])
    wh = jnp.clip(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    area1 = box_area(boxes1)
    area2 = box_area(boxes2)
    union = jnp.maximum(area1 + area2 - inter, 1e-9)
    iou = inter / union
    if kind == "iou":
        return iou
    hull_lt = jnp.minimum(boxes1[..., :2], boxes2[..., :2])
    hull_rb = jnp.maximum(boxes1[..., 2:], boxes2[..., 2:])
    hull_wh = jnp.clip(hull_rb - hull_lt, 0)
    if kind == "giou":
        hull = jnp.maximum(hull_wh[..., 0] * hull_wh[..., 1], 1e-9)
        return iou - (hull - union) / hull
    c2 = jnp.sum(jnp.square(hull_wh), -1) + 1e-9
    ctr1 = (boxes1[..., :2] + boxes1[..., 2:]) / 2
    ctr2 = (boxes2[..., :2] + boxes2[..., 2:]) / 2
    rho2 = jnp.sum(jnp.square(ctr2 - ctr1), -1)
    if kind == "diou":
        return iou - rho2 / c2
    if kind == "ciou":
        w1 = boxes1[..., 2] - boxes1[..., 0]
        h1 = jnp.maximum(boxes1[..., 3] - boxes1[..., 1], 1e-9)
        w2 = boxes2[..., 2] - boxes2[..., 0]
        h2 = jnp.maximum(boxes2[..., 3] - boxes2[..., 1], 1e-9)
        v = (4 / math.pi ** 2) * jnp.square(
            jnp.arctan(w2 / h2) - jnp.arctan(w1 / h1))
        alpha = v / jnp.maximum(1 - iou + v, 1e-9)
        alpha = jax.lax.stop_gradient(alpha)
        return iou - rho2 / c2 - alpha * v
    raise ValueError(kind)


def encode_boxes(reference: jax.Array, proposals: jax.Array,
                 weights: Tuple[float, float, float, float] = (1, 1, 1, 1)
                 ) -> jax.Array:
    """Regression targets (dx, dy, dw, dh) of ``reference`` (gt) w.r.t.
    ``proposals`` (anchors) — BoxCoder.encode surface."""
    wx, wy, ww, wh = weights
    px = (proposals[..., 0] + proposals[..., 2]) / 2
    py = (proposals[..., 1] + proposals[..., 3]) / 2
    pw = jnp.maximum(proposals[..., 2] - proposals[..., 0], 1e-6)
    ph = jnp.maximum(proposals[..., 3] - proposals[..., 1], 1e-6)
    gx = (reference[..., 0] + reference[..., 2]) / 2
    gy = (reference[..., 1] + reference[..., 3]) / 2
    gw = jnp.maximum(reference[..., 2] - reference[..., 0], 1e-6)
    gh = jnp.maximum(reference[..., 3] - reference[..., 1], 1e-6)
    return jnp.stack([
        wx * (gx - px) / pw, wy * (gy - py) / ph,
        ww * jnp.log(gw / pw), wh * jnp.log(gh / ph)], axis=-1)


def decode_boxes(deltas: jax.Array, anchors: jax.Array,
                 weights: Tuple[float, float, float, float] = (1, 1, 1, 1)
                 ) -> jax.Array:
    """Apply (dx, dy, dw, dh) deltas to anchors — BoxCoder.decode surface
    with the log-space clip (det_utils.py:225 bbox_xform_clip)."""
    wx, wy, ww, wh = weights
    ax = (anchors[..., 0] + anchors[..., 2]) / 2
    ay = (anchors[..., 1] + anchors[..., 3]) / 2
    aw = anchors[..., 2] - anchors[..., 0]
    ah = anchors[..., 3] - anchors[..., 1]
    dx = deltas[..., 0] / wx
    dy = deltas[..., 1] / wy
    dw = jnp.minimum(deltas[..., 2] / ww, BBOX_XFORM_CLIP)
    dh = jnp.minimum(deltas[..., 3] / wh, BBOX_XFORM_CLIP)
    cx = dx * aw + ax
    cy = dy * ah + ay
    w = jnp.exp(dw) * aw
    h = jnp.exp(dh) * ah
    return jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2],
                     axis=-1)


def clip_boxes(boxes: jax.Array, size_hw: Tuple[int, int]) -> jax.Array:
    h, w = size_hw
    return jnp.stack([
        jnp.clip(boxes[..., 0], 0, w), jnp.clip(boxes[..., 1], 0, h),
        jnp.clip(boxes[..., 2], 0, w), jnp.clip(boxes[..., 3], 0, h)],
        axis=-1)


def remove_small_boxes_mask(boxes: jax.Array, min_size: float) -> jax.Array:
    """Validity mask instead of index list (static shapes)."""
    w = boxes[..., 2] - boxes[..., 0]
    h = boxes[..., 3] - boxes[..., 1]
    return (w >= min_size) & (h >= min_size)
