"""RoIAlign as bilinear gather — the torchvision MultiScaleRoIAlign successor.

The reference consumes torchvision's compiled RoIAlign
(fasterRcnn/models/faster_rcnn.py:8,305 MultiScaleRoIAlign). XLA version:
each output cell samples a fixed ``sampling_ratio²`` grid of bilinear
points — a dense gather, fully vectorized over (rois × cells × samples),
which XLA lowers to efficient dynamic-gathers. FPN level assignment
follows the canonical heuristic (level = 4 + log2(sqrt(area)/224),
clamped).

``multiscale_roi_align`` is **one-pass**: the pyramid levels are packed
into a single flat (ΣH·W, C) buffer with static per-level row offsets,
each RoI's sample coordinates are computed in its *assigned* level's
frame, and one bilinear gather against the packed buffer samples every
RoI exactly once — L× fewer FLOPs/gathers than evaluating each RoI on
every level. The old evaluate-everywhere-and-mask formulation is kept
as ``multiscale_roi_align_masked`` (equivalence oracle; see
tests/test_detection_ops.py / test_blocked_nms.py parity tests).
"""

from __future__ import annotations

from typing import Dict, Sequence, Tuple

import jax
import jax.numpy as jnp


def _bilinear(features: jax.Array, y: jax.Array, x: jax.Array) -> jax.Array:
    """Sample features (H, W, C) at float coords y/x (...,) → (..., C).
    Out-of-bounds sampling returns 0 (torchvision semantics)."""
    h, w, c = features.shape
    in_bounds = (y >= -1.0) & (y <= h) & (x >= -1.0) & (x <= w)
    y = jnp.clip(y, 0.0, h - 1.0)
    x = jnp.clip(x, 0.0, w - 1.0)
    y0 = jnp.floor(y).astype(jnp.int32)
    x0 = jnp.floor(x).astype(jnp.int32)
    y1 = jnp.minimum(y0 + 1, h - 1)
    x1 = jnp.minimum(x0 + 1, w - 1)
    ly = (y - y0)[..., None]
    lx = (x - x0)[..., None]
    v00 = features[y0, x0]
    v01 = features[y0, x1]
    v10 = features[y1, x0]
    v11 = features[y1, x1]
    val = (v00 * (1 - ly) * (1 - lx) + v01 * (1 - ly) * lx
           + v10 * ly * (1 - lx) + v11 * ly * lx)
    return val * in_bounds[..., None]


def roi_align(features: jax.Array, rois: jax.Array, output_size: int,
              spatial_scale: float = 1.0, sampling_ratio: int = 2,
              aligned: bool = False) -> jax.Array:
    """features (H, W, C); rois (R, 4) in image coords → (R, S, S, C)."""
    s = output_size
    sr = max(sampling_ratio, 1)
    offset = 0.5 if aligned else 0.0
    boxes = rois * spatial_scale - offset
    x1, y1, x2, y2 = (boxes[:, i] for i in range(4))
    roi_w = jnp.maximum(x2 - x1, 1.0 if not aligned else 1e-6)
    roi_h = jnp.maximum(y2 - y1, 1.0 if not aligned else 1e-6)
    bin_h = roi_h / s
    bin_w = roi_w / s
    # sample grid: (R, S, sr) per axis
    iy = jnp.arange(s)
    ir = jnp.arange(sr)
    ys = (y1[:, None, None] + (iy[None, :, None]
          + (ir[None, None, :] + 0.5) / sr) * bin_h[:, None, None])
    xs = (x1[:, None, None] + (iy[None, :, None]
          + (ir[None, None, :] + 0.5) / sr) * bin_w[:, None, None])
    # full coordinate grid (R, S, sr, S, sr)
    yy = ys[:, :, :, None, None]
    xx = xs[:, None, None, :, :]
    yy = jnp.broadcast_to(yy, ys.shape + (s, sr))
    xx = jnp.broadcast_to(xx, (xs.shape[0], s, sr) + xs.shape[1:])
    vals = _bilinear(features, yy, xx)           # (R, S, sr, S, sr, C)
    return jnp.mean(vals, axis=(2, 4))           # (R, S, S, C)


def _assign_levels(feature_pyramid, rois, canonical_level, canonical_scale):
    """Canonical FPN level per RoI → (sorted level names, per-roi index
    into that list)."""
    levels = sorted(feature_pyramid, key=lambda k: int(k[1]))
    lmin, lmax = int(levels[0][1]), int(levels[-1][1])
    areas = jnp.maximum(rois[:, 2] - rois[:, 0], 0) * \
        jnp.maximum(rois[:, 3] - rois[:, 1], 0)
    target = jnp.floor(canonical_level
                       + jnp.log2(jnp.sqrt(areas) / canonical_scale + 1e-8))
    target = jnp.clip(target, lmin, lmax).astype(jnp.int32)
    return levels, target - lmin


def _bilinear_packed(packed: jax.Array, y: jax.Array, x: jax.Array,
                     h: jax.Array, w: jax.Array, off: jax.Array
                     ) -> jax.Array:
    """Per-RoI bilinear sampling against a flat packed (ΣH·W, C) buffer.

    y/x: (R, ...) float coords in each RoI's own level frame; h/w/off:
    (R,) that level's height, width and flat row offset. Identical
    out-of-bounds/clip semantics to ``_bilinear`` — per-roi bounds keep
    every flat index inside the roi's own level slab."""
    expand = (slice(None),) + (None,) * (y.ndim - 1)
    hf = h.astype(y.dtype)[expand]
    wf = w.astype(y.dtype)[expand]
    wi = w.astype(jnp.int32)[expand]
    hi = h.astype(jnp.int32)[expand]
    base = off.astype(jnp.int32)[expand]
    in_bounds = (y >= -1.0) & (y <= hf) & (x >= -1.0) & (x <= wf)
    y = jnp.clip(y, 0.0, hf - 1.0)
    x = jnp.clip(x, 0.0, wf - 1.0)
    y0 = jnp.floor(y).astype(jnp.int32)
    x0 = jnp.floor(x).astype(jnp.int32)
    y1 = jnp.minimum(y0 + 1, hi - 1)
    x1 = jnp.minimum(x0 + 1, wi - 1)
    ly = (y - y0)[..., None]
    lx = (x - x0)[..., None]
    v00 = packed[base + y0 * wi + x0]
    v01 = packed[base + y0 * wi + x1]
    v10 = packed[base + y1 * wi + x0]
    v11 = packed[base + y1 * wi + x1]
    val = (v00 * (1 - ly) * (1 - lx) + v01 * (1 - ly) * lx
           + v10 * ly * (1 - lx) + v11 * ly * lx)
    return val * in_bounds[..., None]


def multiscale_roi_align(
    feature_pyramid: Dict[str, jax.Array],
    rois: jax.Array,
    output_size: int = 7,
    canonical_level: int = 4,
    canonical_scale: float = 224.0,
    sampling_ratio: int = 2,
    strides: Dict[str, int] | None = None,
    impl: str = "onepass",
) -> jax.Array:
    """FPN-aware RoIAlign (MultiScaleRoIAlign surface). feature_pyramid
    maps 'p2'..'p5' → (H_l, W_l, C); rois (R, 4) → (R, S, S, C).

    One bilinear pass total: levels are flattened into a packed
    (ΣH·W, C) buffer, each RoI's sample grid is laid out in its assigned
    level's coordinate frame, and a single flat gather (4 corner reads)
    samples all RoIs at once. ``impl="masked"`` selects the old
    evaluate-every-level-and-mask reference."""
    if impl == "masked":
        return multiscale_roi_align_masked(
            feature_pyramid, rois, output_size, canonical_level,
            canonical_scale, sampling_ratio, strides)
    if impl != "onepass":
        raise ValueError(f"multiscale_roi_align impl must be 'onepass' or "
                         f"'masked', got {impl!r}")
    if strides is None:
        strides = {k: 2 ** int(k[1]) for k in feature_pyramid}
    levels, lvl_idx = _assign_levels(feature_pyramid, rois,
                                     canonical_level, canonical_scale)
    # Static per-level geometry + one packed feature buffer.
    hs, ws, offs, flats = [], [], [], []
    row = 0
    for name in levels:
        f = feature_pyramid[name]
        h, w, c = f.shape
        hs.append(h)
        ws.append(w)
        offs.append(row)
        row += h * w
        flats.append(f.reshape(h * w, c))
    packed = jnp.concatenate(flats, axis=0)
    scale_tab = jnp.asarray([1.0 / strides[name] for name in levels],
                            rois.dtype)
    h_tab = jnp.asarray(hs, jnp.int32)
    w_tab = jnp.asarray(ws, jnp.int32)
    off_tab = jnp.asarray(offs, jnp.int32)

    scale = scale_tab[lvl_idx]                       # (R,) per-roi
    boxes = rois * scale[:, None]
    s = output_size
    sr = max(sampling_ratio, 1)
    x1, y1, x2, y2 = (boxes[:, i] for i in range(4))
    roi_w = jnp.maximum(x2 - x1, 1.0)
    roi_h = jnp.maximum(y2 - y1, 1.0)
    bin_h = roi_h / s
    bin_w = roi_w / s
    iy = jnp.arange(s)
    ir = jnp.arange(sr)
    ys = (y1[:, None, None] + (iy[None, :, None]
          + (ir[None, None, :] + 0.5) / sr) * bin_h[:, None, None])
    xs = (x1[:, None, None] + (iy[None, :, None]
          + (ir[None, None, :] + 0.5) / sr) * bin_w[:, None, None])
    yy = jnp.broadcast_to(ys[:, :, :, None, None], ys.shape + (s, sr))
    xx = jnp.broadcast_to(xs[:, None, None, :, :],
                          (xs.shape[0], s, sr) + xs.shape[1:])
    vals = _bilinear_packed(packed, yy, xx, h_tab[lvl_idx], w_tab[lvl_idx],
                            off_tab[lvl_idx])       # (R, S, sr, S, sr, C)
    return jnp.mean(vals, axis=(2, 4))              # (R, S, S, C)


def multiscale_roi_align_masked(
    feature_pyramid: Dict[str, jax.Array],
    rois: jax.Array,
    output_size: int = 7,
    canonical_level: int = 4,
    canonical_scale: float = 224.0,
    sampling_ratio: int = 2,
    strides: Dict[str, int] | None = None,
) -> jax.Array:
    """Reference formulation: every roi is aligned on every level then
    the assigned level is selected by mask — L× redundant compute, kept
    as the equivalence oracle for the one-pass path."""
    if strides is None:
        strides = {k: 2 ** int(k[1]) for k in feature_pyramid}
    levels, lvl_idx = _assign_levels(feature_pyramid, rois,
                                     canonical_level, canonical_scale)
    lmin = int(levels[0][1])

    out = None
    for name in levels:
        lvl = int(name[1])
        aligned = roi_align(feature_pyramid[name], rois, output_size,
                            1.0 / strides[name], sampling_ratio)
        sel = (lvl_idx == lvl - lmin).astype(aligned.dtype)[:, None, None,
                                                            None]
        out = aligned * sel if out is None else out + aligned * sel
    return out
