"""Anchor generation for FPN detectors.

Surface of detection/fasterRcnn/models/rpn_function.py:25 AnchorsGenerator
and RetinaNet network_files/anchor_utils.py: per-level (sizes × ratios)
anchor grids in image coordinates. Host-side numpy (shapes are static per
image size), returned as one concatenated (A, 4) array plus per-level
counts — anchors are constants folded into the jitted graph.
"""

from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

import numpy as np


def base_anchors(sizes: Sequence[float], ratios: Sequence[float]
                 ) -> np.ndarray:
    """(len(sizes)*len(ratios), 4) centered zero-origin anchors."""
    sizes_arr = np.asarray(sizes, np.float32)
    ratios_arr = np.asarray(ratios, np.float32)
    h_ratios = np.sqrt(ratios_arr)
    w_ratios = 1.0 / h_ratios
    ws = (w_ratios[:, None] * sizes_arr[None, :]).reshape(-1)
    hs = (h_ratios[:, None] * sizes_arr[None, :]).reshape(-1)
    return np.stack([-ws, -hs, ws, hs], axis=1) / 2.0


def grid_anchors(feature_hw: Tuple[int, int], stride: int,
                 cell_anchors: np.ndarray) -> np.ndarray:
    """(H*W*A, 4) anchors for one level."""
    h, w = feature_hw
    shifts_x = (np.arange(w, dtype=np.float32) + 0.0) * stride
    shifts_y = (np.arange(h, dtype=np.float32) + 0.0) * stride
    sy, sx = np.meshgrid(shifts_y, shifts_x, indexing="ij")
    shifts = np.stack([sx.ravel(), sy.ravel(), sx.ravel(), sy.ravel()],
                      axis=1)
    anchors = shifts[:, None, :] + cell_anchors[None, :, :]
    return anchors.reshape(-1, 4).astype(np.float32)


def pyramid_anchors(
    feature_shapes: Dict[str, Tuple[int, int]],
    strides: Dict[str, int],
    sizes_per_level: Dict[str, Sequence[float]],
    ratios: Sequence[float] = (0.5, 1.0, 2.0),
) -> Tuple[np.ndarray, List[int]]:
    """All-level anchors concatenated + per-level counts (order = sorted
    level names p2 < p3 < ...)."""
    out, counts = [], []
    for name in sorted(feature_shapes, key=lambda k: int(k[1:])):
        cell = base_anchors(sizes_per_level[name], ratios)
        a = grid_anchors(feature_shapes[name], strides[name], cell)
        out.append(a)
        counts.append(len(a))
    return np.concatenate(out, axis=0), counts


def retinanet_sizes(levels: Sequence[int] = (3, 4, 5, 6, 7)
                    ) -> Dict[str, Sequence[float]]:
    """RetinaNet 3-scale-per-level sizes: 2^lvl*4 * {1, 2^(1/3), 2^(2/3)}."""
    return {f"p{l}": tuple(2 ** l * 4 * 2 ** (i / 3) for i in range(3))
            for l in levels}
