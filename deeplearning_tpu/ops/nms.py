"""Padded top-k NMS — the torchvision.ops.nms successor under XLA.

The reference calls the compiled torchvision NMS everywhere
(fasterRcnn/utils/boxes.py:32, RetinaNet network_files/boxes.py:35, YOLOX
utils/boxes.py:57-67, yolov5 utils/general.py non_max_suppression). Those
return variable-length index lists — impossible under XLA's static shapes.
TPU-first formulation: NMS(boxes, scores) → (keep_indices[max_out],
keep_mask[max_out]) with fixed ``max_out``; suppressed slots are masked.

Two implementations behind one contract:

``nms_reference`` — O(max_out · N) greedy: each of ``max_out`` fixed
iterations selects the argmax of the still-alive scores and suppresses
neighbors over the IoU threshold. Simple, but it materializes the full
N×N IoU matrix up front (1.6 GB f32 at N=20k) and the per-step
data-dependent argmax serializes the device for ``max_out`` steps.

``nms_blocked`` — blocked bitmask sweep (the torchvision-CUDA /
TF-TPU ``sorted_non_max_suppression_padded`` formulation): sort by score
once, tile the sorted candidates into blocks of B, and process blocks
in score order. Per block: resolve intra-block suppression by iterating
the suppression relation to its (unique, = greedy) fixed point, then
kill every *later* candidate that overlaps a kept box using one
(B, N) IoU tile computed on the fly. Sequential depth is the number of
blocks actually needed to collect ``max_out`` keeps (early exit), peak
memory is O(N·B) — the N×N matrix is never materialized. Dense tiles
are MXU/VPU-friendly; a Pallas kernel with the same contract lives in
``ops/pallas/nms.py``.

Both paths emit the identical keep set in the identical (descending
score, stable) order — property-tested in tests/test_blocked_nms.py.
``batched_nms`` uses the reference's category-offset trick
(boxes.py:35-60) so classes never suppress each other.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from .boxes import box_iou

# Default tile width for the blocked sweep. 256 keeps the intra-block
# (B, B) tile at 256 KB f32 and is a multiple of the TPU lane width.
DEFAULT_BLOCK_SIZE = 256

# nms(impl="auto") policy: below _AUTO_BLOCKED_MIN candidates the greedy
# scan is cheaper than sort + tile bookkeeping; at/above it the blocked
# sweep wins; on a TPU backend with >= _AUTO_PALLAS_MIN candidates the
# Pallas kernel takes over (on CPU it would only add interpret overhead).
_AUTO_BLOCKED_MIN = 256
_AUTO_PALLAS_MIN = 1024

_VALID_IMPLS = ("auto", "greedy", "reference", "blocked", "pallas")
_default_impl = "auto"


def set_default_nms_impl(impl: str) -> str:
    """Set the library-wide default for ``nms(impl=None)`` calls; returns
    the previous default. Accepts "auto", "greedy"/"reference",
    "blocked" or "pallas"."""
    global _default_impl
    if impl not in _VALID_IMPLS:
        raise ValueError(f"nms impl must be one of {_VALID_IMPLS}, "
                         f"got {impl!r}")
    prev = _default_impl
    _default_impl = impl
    return prev


def get_default_nms_impl() -> str:
    return _default_impl


def _resolve_impl(impl: Optional[str], n: int) -> str:
    impl = _default_impl if impl is None or impl == "auto" else impl
    if impl == "reference":
        return "greedy"
    if impl == "auto":
        if n < _AUTO_BLOCKED_MIN:
            return "greedy"
        if n >= _AUTO_PALLAS_MIN and jax.default_backend() == "tpu":
            return "pallas"
        return "blocked"
    if impl not in _VALID_IMPLS:
        raise ValueError(f"nms impl must be one of {_VALID_IMPLS}, "
                         f"got {impl!r}")
    return impl


def nms_reference(boxes: jax.Array, scores: jax.Array, iou_threshold: float,
                  max_out: int, score_threshold: float = float("-inf")
                  ) -> Tuple[jax.Array, jax.Array]:
    """Greedy NMS. boxes (N,4), scores (N,) → (idx (max_out,), valid
    (max_out,) bool). Padded slots have idx 0 and valid False.

    Kept as the equivalence oracle for the blocked/Pallas paths; its
    full N×N IoU build makes it the wrong choice beyond a few hundred
    candidates."""
    n = boxes.shape[0]
    iou = box_iou(boxes, boxes)
    alive = scores > score_threshold

    def body(state, _):
        alive, = state
        masked = jnp.where(alive, scores, -jnp.inf)
        best = jnp.argmax(masked)
        valid = masked[best] > -jnp.inf
        suppress = iou[best] > iou_threshold
        new_alive = alive & ~suppress & (jnp.arange(n) != best)
        # if nothing valid remains, keep alive unchanged (all False anyway)
        return (jnp.where(valid, new_alive, alive),), (best, valid)

    (_,), (idx, valid) = jax.lax.scan(body, (alive,), None, length=max_out)
    return idx, valid


def _emit_from_alive(alive: jax.Array, order: jax.Array, max_out: int
                     ) -> Tuple[jax.Array, jax.Array]:
    """Turn a keep mask over *sorted* (score-descending) positions into the
    fixed-shape (idx[max_out], valid[max_out]) contract, without any
    data-dependent shapes: rank kept positions by prefix count and
    scatter their original indices into the output slots.

    ``alive`` may contain stale True entries past the point where
    ``max_out`` keeps were already collected (blocked early exit) —
    those have rank >= max_out and are dropped by the scatter."""
    npad = alive.shape[0]
    n = order.shape[0]
    rank = jnp.cumsum(alive.astype(jnp.int32)) - 1
    slot = jnp.where(alive & (rank < max_out), rank, max_out)
    src = jnp.zeros((max_out,), jnp.int32).at[slot].set(
        jnp.arange(npad, dtype=jnp.int32), mode="drop")
    total = jnp.minimum(jnp.sum(alive.astype(jnp.int32)), max_out)
    valid = jnp.arange(max_out, dtype=jnp.int32) < total
    order_pad = jnp.zeros((npad,), order.dtype).at[:n].set(order)
    idx = jnp.where(valid, order_pad[src], 0)
    return idx, valid


def sort_pad_candidates(boxes: jax.Array, scores: jax.Array,
                        score_threshold: float, block_size: int):
    """Shared blocked-NMS prologue: stable sort by descending score, pad
    to a whole number of blocks. Returns (sboxes (Npad,4),
    alive0 (Npad,) bool, order (N,) int, nb). Padded slots carry -inf
    scores so they are never alive; NaN scores sort last and are dead
    under any threshold (NaN > t is False), matching the greedy path."""
    n = boxes.shape[0]
    nb = max(1, -(-n // block_size))
    npad = nb * block_size
    order = jnp.argsort(-scores)  # stable → greedy argmax tie order
    sboxes = jnp.zeros((npad, 4), boxes.dtype).at[:n].set(boxes[order])
    sscores = jnp.full((npad,), -jnp.inf, scores.dtype).at[:n].set(
        scores[order])
    alive0 = sscores > score_threshold
    return sboxes, alive0, order, nb


def _intra_block_keep(blk_boxes: jax.Array, blk_alive: jax.Array,
                      iou_threshold: float) -> jax.Array:
    """Greedy keep set within one sorted block via fixed-point iteration.

    With M[j,k] = 1 iff j<k and iou(j,k) > th (strictly upper
    triangular), iterate A ← alive0 ∧ ¬(∃j: A[j] ∧ M[j,k]). Any fixed
    point of that map equals the greedy set (induction over k), and
    position k stabilizes after ≤ k+1 sweeps, so the loop converges in
    ≤ B+1 iterations and usually far fewer."""
    block = blk_boxes.shape[0]
    iou_in = box_iou(blk_boxes, blk_boxes)
    pos = jnp.arange(block)
    sup_in = (iou_in > iou_threshold) & (pos[:, None] < pos[None, :])

    def cond(state):
        return state[1]

    def body(state):
        keep, _ = state
        new = blk_alive & ~jnp.any(sup_in & keep[:, None], axis=0)
        return new, jnp.any(new != keep)

    keep, _ = jax.lax.while_loop(cond, body, (blk_alive, jnp.asarray(True)))
    return keep


def nms_blocked(boxes: jax.Array, scores: jax.Array, iou_threshold: float,
                max_out: int, score_threshold: float = float("-inf"),
                block_size: int = DEFAULT_BLOCK_SIZE
                ) -> Tuple[jax.Array, jax.Array]:
    """Blocked bitmask NMS — same contract and keep set as
    ``nms_reference`` with O(N·B) peak memory and sequential depth
    ceil(N/B), stopping early once ``max_out`` keeps are collected."""
    block_size = int(min(block_size, max(8, boxes.shape[0])))
    sboxes, alive0, order, nb = sort_pad_candidates(
        boxes, scores, score_threshold, block_size)
    npad = alive0.shape[0]
    col = jnp.arange(npad)

    def cond(state):
        i, _, kept = state
        return (i < nb) & (kept < max_out)

    def body(state):
        i, alive, kept = state
        start = i * block_size
        blk = jax.lax.dynamic_slice(sboxes, (start, 0), (block_size, 4))
        blk_alive = jax.lax.dynamic_slice(alive, (start,), (block_size,))
        keep = _intra_block_keep(blk, blk_alive, iou_threshold)
        # One (B, Npad) tile kills every later candidate that overlaps a
        # kept box. NaN boxes never suppress (NaN > th is False), same
        # as the greedy path.
        cross = box_iou(blk, sboxes)
        hit = jnp.any((cross > iou_threshold) & keep[:, None], axis=0)
        alive = alive & ~(hit & (col >= start + block_size))
        alive = jax.lax.dynamic_update_slice(alive, keep, (start,))
        return i + 1, alive, kept + jnp.sum(keep.astype(jnp.int32))

    _, alive, _ = jax.lax.while_loop(
        cond, body, (jnp.asarray(0), alive0, jnp.asarray(0)))
    return _emit_from_alive(alive, order, max_out)


def nms(boxes: jax.Array, scores: jax.Array, iou_threshold: float,
        max_out: int, score_threshold: float = float("-inf"),
        impl: Optional[str] = None, block_size: int = DEFAULT_BLOCK_SIZE
        ) -> Tuple[jax.Array, jax.Array]:
    """NMS dispatcher. boxes (N,4), scores (N,) → (idx (max_out,), valid
    (max_out,) bool). Padded slots have idx 0 and valid False.

    ``impl``: None → library default (``set_default_nms_impl``);
    "auto" → greedy below 256 candidates, blocked above, Pallas kernel
    on a TPU backend at >= 1024; or force "greedy"/"reference",
    "blocked", "pallas"."""
    resolved = _resolve_impl(impl, boxes.shape[0])
    if resolved == "greedy":
        return nms_reference(boxes, scores, iou_threshold, max_out,
                             score_threshold)
    if resolved == "pallas":
        from .pallas import nms as pallas_nms  # lazy: avoids import cycle
        return pallas_nms.nms_pallas(boxes, scores, iou_threshold, max_out,
                                     score_threshold, block_size=block_size)
    return nms_blocked(boxes, scores, iou_threshold, max_out,
                       score_threshold, block_size=block_size)


def batched_nms(boxes: jax.Array, scores: jax.Array, classes: jax.Array,
                iou_threshold: float, max_out: int,
                score_threshold: float = float("-inf"),
                impl: Optional[str] = None,
                block_size: int = DEFAULT_BLOCK_SIZE
                ) -> Tuple[jax.Array, jax.Array]:
    """Class-aware NMS via per-class coordinate offsets
    (fasterRcnn utils/boxes.py:35-60 trick, fixed-shape).

    The offset scale is computed from *finite* boxes only: one NaN/inf
    box (a decode overflow, a masked pad slot) must not poison
    ``max_coord`` and with it every class offset. Non-finite boxes keep
    their own coordinates — they already never suppress anything (NaN
    IoU compares False) and can only be selected if their score says
    so, same as plain ``nms``."""
    finite = jnp.all(jnp.isfinite(boxes), axis=-1)
    max_coord = jnp.max(jnp.where(finite[:, None], boxes, 0.0)) + 1.0
    offsets = classes.astype(boxes.dtype)[:, None] * max_coord
    return nms(boxes + offsets, scores, iou_threshold, max_out,
               score_threshold, impl=impl, block_size=block_size)


def gather_nms_outputs(idx: jax.Array, valid: jax.Array, *arrays,
                       fill: Union[float, Sequence[float]] = 0
                       ) -> Tuple[jax.Array, ...]:
    """Gather (boxes/scores/classes/...) at keep indices, overwriting
    padded slots with ``fill`` so downstream fixed-shape consumers see
    clean data.

    ``fill`` is a scalar applied to every array, or one value per array.
    Pass -1 for class arrays: a zero-filled padded slot is otherwise
    indistinguishable from a real class-0 / score-0 detection in COCO
    eval."""
    if isinstance(fill, (tuple, list)):
        if len(fill) != len(arrays):
            raise ValueError(
                f"gather_nms_outputs: got {len(arrays)} arrays but "
                f"{len(fill)} fill values")
        fills = fill
    else:
        fills = (fill,) * len(arrays)
    out = []
    for a, f in zip(arrays, fills):
        g = a[idx]
        mask = valid.reshape(valid.shape + (1,) * (g.ndim - 1))
        out.append(jnp.where(mask, g, jnp.asarray(f, dtype=g.dtype)))
    return tuple(out)
