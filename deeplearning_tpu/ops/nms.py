"""Padded top-k NMS — the torchvision.ops.nms successor under XLA.

The reference calls the compiled torchvision NMS everywhere
(fasterRcnn/utils/boxes.py:32, RetinaNet network_files/boxes.py:35, YOLOX
utils/boxes.py:57-67, yolov5 utils/general.py non_max_suppression). Those
return variable-length index lists — impossible under XLA's static shapes.
TPU-first formulation: NMS(boxes, scores) → (keep_indices[max_out],
keep_mask[max_out]) with fixed ``max_out``; suppressed slots are masked.

Algorithm: O(max_out · N) greedy — each of ``max_out`` fixed iterations
selects the argmax of the still-alive scores and suppresses neighbors over
the IoU threshold. All dense vector math (VPU-friendly); no data-dependent
shapes. ``batched_nms`` uses the reference's category-offset trick
(boxes.py:35-60) so classes never suppress each other.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from .boxes import box_iou


def nms(boxes: jax.Array, scores: jax.Array, iou_threshold: float,
        max_out: int, score_threshold: float = float("-inf")
        ) -> Tuple[jax.Array, jax.Array]:
    """Greedy NMS. boxes (N,4), scores (N,) → (idx (max_out,), valid
    (max_out,) bool). Padded slots have idx 0 and valid False."""
    n = boxes.shape[0]
    iou = box_iou(boxes, boxes)
    alive = scores > score_threshold

    def body(state, _):
        alive, = state
        masked = jnp.where(alive, scores, -jnp.inf)
        best = jnp.argmax(masked)
        valid = masked[best] > -jnp.inf
        suppress = iou[best] > iou_threshold
        new_alive = alive & ~suppress & (jnp.arange(n) != best)
        # if nothing valid remains, keep alive unchanged (all False anyway)
        return (jnp.where(valid, new_alive, alive),), (best, valid)

    (_,), (idx, valid) = jax.lax.scan(body, (alive,), None, length=max_out)
    return idx, valid


def batched_nms(boxes: jax.Array, scores: jax.Array, classes: jax.Array,
                iou_threshold: float, max_out: int,
                score_threshold: float = float("-inf")
                ) -> Tuple[jax.Array, jax.Array]:
    """Class-aware NMS via per-class coordinate offsets
    (fasterRcnn utils/boxes.py:35-60 trick, fixed-shape)."""
    max_coord = jnp.max(boxes) + 1.0
    offsets = classes.astype(boxes.dtype)[:, None] * max_coord
    return nms(boxes + offsets, scores, iou_threshold, max_out,
               score_threshold)


def gather_nms_outputs(idx: jax.Array, valid: jax.Array, *arrays
                       ) -> Tuple[jax.Array, ...]:
    """Gather (boxes/scores/classes/...) at keep indices, zeroing padded
    slots so downstream fixed-shape consumers see clean data."""
    out = []
    for a in arrays:
        g = a[idx]
        mask = valid.reshape(valid.shape + (1,) * (g.ndim - 1))
        out.append(jnp.where(mask, g, 0))
    return tuple(out)
