"""Crash flight recorder: last-K structured events + config, dumped on
divergence abort, uncaught trainer exception, or SIGTERM.

A diverged or preempted run previously left nothing to autopsy — the
metrics ring dies with the process and the log file stops mid-line. The
recorder keeps a bounded in-memory ring of recent structured events
(step metric snapshots, feed stats, retrace warnings, compile events,
serve rejections — anything a layer ``record()``s) and serializes it to
``runs/<dir>/flightrec.json`` together with the run config, an HBM
snapshot, and the exception, the moment something goes wrong.

Recording is always-on and cheap (bounded ``deque.append`` under a
lock; no device syncs, no I/O); DUMPING requires a path — either
``configure(path, config)`` (the Trainer does this per run) or an
explicit ``dump(path=...)``. The default process-wide recorder is what
the convenience ``record(kind, **data)`` feeds, so layers don't need a
handle threaded through them.
"""

from __future__ import annotations

import collections
import json
import os
import signal
import threading
import time
import traceback
from typing import Any, Dict, List, Optional

__all__ = ["FlightRecorder", "get_recorder", "record", "configure",
           "dump", "install_signal_handler", "flush_pending"]


def _jsonable(obj: Any, depth: int = 0) -> Any:
    """Best-effort JSON projection: configs arrive as dataclass-dicts,
    numpy scalars, device arrays — serialize what we can, stringify the
    rest (a flight record must never fail to write)."""
    if depth > 6:
        return repr(obj)
    if obj is None or isinstance(obj, (bool, int, str)):
        return obj
    if isinstance(obj, float):
        return obj if obj == obj and abs(obj) != float("inf") else repr(obj)
    if isinstance(obj, dict):
        return {str(k): _jsonable(v, depth + 1) for k, v in obj.items()}
    if isinstance(obj, (list, tuple, set)):
        return [_jsonable(v, depth + 1) for v in obj]
    if hasattr(obj, "item"):           # numpy / jax scalars
        try:
            return _jsonable(obj.item(), depth + 1)
        except Exception:  # noqa: BLE001
            pass
    if hasattr(obj, "__dataclass_fields__"):
        import dataclasses
        try:
            return _jsonable(dataclasses.asdict(obj), depth + 1)
        except Exception:  # noqa: BLE001
            pass
    return repr(obj)


class FlightRecorder:
    """Bounded ring of recent events with a one-shot crash dump."""

    def __init__(self, capacity: int = 256):
        self.capacity = int(capacity)
        self._ring: collections.deque = collections.deque(maxlen=capacity)
        self._lock = threading.Lock()
        self.path: Optional[str] = None
        self.config: Optional[Dict[str, Any]] = None
        self.dumps = 0
        self.recorded = 0

    # ------------------------------------------------------- recording
    def record(self, kind: str, **data: Any) -> None:
        event = {"kind": kind, "time": time.time(),
                 "thread": threading.current_thread().name, **data}
        with self._lock:
            self.recorded += 1
            self._ring.append(event)

    def events(self, kind: Optional[str] = None) -> List[Dict[str, Any]]:
        with self._lock:
            ring = list(self._ring)
        return ring if kind is None else [e for e in ring
                                          if e["kind"] == kind]

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self.recorded = 0

    # --------------------------------------------------------- dumping
    def configure(self, path: str,
                  config: Optional[Any] = None) -> "FlightRecorder":
        """Arm the recorder: where to dump and what run config to embed
        (any object; serialized best-effort)."""
        self.path = path
        self.config = _jsonable(config) if config is not None else None
        return self

    def dump(self, reason: str = "manual", *,
             exception: Optional[BaseException] = None,
             path: Optional[str] = None,
             include_hbm: bool = True) -> Optional[str]:
        """Write ``flightrec.json``; returns the path (None when no path
        is configured — recording without arming is legal). Never raises:
        this runs inside except blocks and signal handlers.

        ``include_hbm=False`` skips the device-memory snapshot — the run
        supervisor uses it because ``hbm_snapshot`` initializes the jax
        backend, and a supervisor must not wedge in the same device init
        it polices."""
        try:
            path = path or self.path
            if not path:
                return None
            exc_info = None
            if exception is not None:
                exc_info = {
                    "type": type(exception).__name__,
                    "message": str(exception),
                    "traceback": traceback.format_exception(
                        type(exception), exception,
                        exception.__traceback__),
                }
            hbm = None
            if include_hbm:
                from .xla import hbm_snapshot   # lazy: avoid import cycle
                hbm = _jsonable(hbm_snapshot())
            doc = {
                "reason": reason,
                "time": time.time(),
                "pid": os.getpid(),
                "config": self.config,
                "exception": exc_info,
                "hbm": hbm,
                "events": _jsonable(self.events()),
            }
            os.makedirs(os.path.dirname(os.path.abspath(path)),
                        exist_ok=True)
            with open(path, "w") as f:
                json.dump(doc, f, indent=1)
            self.dumps += 1
            return path
        except Exception:  # noqa: BLE001 - a dump failure must not mask
            return None    # the original crash


# process-wide default recorder: layers record into it without plumbing
_RECORDER = FlightRecorder()
_SIGNAL_INSTALLED = False


def get_recorder() -> FlightRecorder:
    return _RECORDER


def record(kind: str, **data: Any) -> None:
    """Append one event to the default recorder (always cheap/bounded)."""
    _RECORDER.record(kind, **data)


def configure(path: str, config: Optional[Any] = None) -> FlightRecorder:
    return _RECORDER.configure(path, config)


def dump(reason: str = "manual", *,
         exception: Optional[BaseException] = None,
         path: Optional[str] = None) -> Optional[str]:
    return _RECORDER.dump(reason, exception=exception, path=path)


_PENDING = threading.Event()


def _sigterm_dump(signum: int, frame) -> None:
    # Signal-handler discipline (DLT103): mark the dump pending and get
    # out. When a graceful subscriber owns this signal the process
    # keeps running to its next step boundary, where flush_pending()
    # does the open()/json work on the normal call stack.
    _PENDING.set()
    from ..elastic import signals
    if any(graceful for _fn, graceful
           in signals.subscribers(signal.SIGTERM)):
        return
    # Terminating chain: no graceful owner means the pre-registry
    # handler / OS default kills the process right after this handler
    # returns — there IS no later flush point, so the unsafe dump here
    # is the only dump. Justified, not fixed:
    flush_pending()  # dltpu: allow(DLT103) terminating chain: last chance to write


def flush_pending() -> Optional[str]:
    """Write a dump the SIGTERM handler deferred; no-op when none is
    pending. Called from the Trainer's step boundary (next to the
    preemption poll) and from its graceful-exit path."""
    if not _PENDING.is_set():
        return None
    _PENDING.clear()
    return _RECORDER.dump("sigterm")


def install_signal_handler() -> bool:
    """Dump on SIGTERM (preemption / driver kill). Subscribes through
    the elastic signal registry, so this hook COEXISTS with the
    preemption guard instead of silently replacing it: without a
    graceful subscriber the process still terminates after the dump
    (pre-registry handler or OS default chained); with one, the
    handler only marks the dump pending and the trainer flushes it at
    the next step boundary (``flush_pending``) before checkpointing
    out. Main thread only; returns False when it isn't."""
    global _SIGNAL_INSTALLED
    if _SIGNAL_INSTALLED:
        return True
    from ..elastic import signals      # lazy: flight must import light
    if signals.subscribe(signal.SIGTERM, _sigterm_dump):
        _SIGNAL_INSTALLED = True
        return True
    return False
