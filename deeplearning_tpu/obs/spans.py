"""Thread-aware ring-buffered host span tracer.

The run-wide timeline the ROADMAP's on-chip calibration items consume:
every layer that owns a thread (Trainer hot loop, DevicePrefetcher
worker, MicroBatcher dispatch, the obs HBM sampler) marks its phases
with ``span("data_wait")`` blocks, and the tracer serializes them as
Chrome trace-event JSON (``runs/<dir>/trace.json``) that Perfetto /
``chrome://tracing`` loads directly — one timeline across threads
instead of four disjoint counter surfaces.

Cost discipline (the hot-loop rule from README "Hot-loop sync policy"
extended to instrumentation):
- **Disabled** (the default): ``span(...)`` allocates one slotted object
  and performs two ``is None`` checks — no lock, no clock read, no
  allocation growth. The bench obs-overhead smoke asserts the enabled
  path stays within 2% of this.
- **Enabled**: one ``perf_counter`` read on enter, one on exit, and a
  bounded ``deque.append`` under a lock. Never a device sync.

XLA correlation: ``enable(xla_annotate=True)`` makes every span also
enter a ``jax.profiler.TraceAnnotation`` so that when a device trace is
active (``utils.profiling.trace``), host spans land on the same
TensorBoard/XPlane timeline as the XLA ops they bracket.
``step_span(step_num)`` additionally wraps
``jax.profiler.StepTraceAnnotation`` — the annotation the profiler's
step-time analysis keys on.
"""

from __future__ import annotations

import collections
import functools
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

__all__ = ["SpanTracer", "enable", "disable", "get_tracer", "enabled",
           "span", "step_span", "traced"]

# module-level pointer: the `is None` check is the entire disabled-path
# cost, so spans stay near-free in un-instrumented processes
_TRACER: Optional["SpanTracer"] = None


class SpanTracer:
    """Bounded ring of completed host spans, one ring per process.

    Events are recorded with absolute wall-clock microsecond timestamps
    (``ts = epoch + perf_counter delta``) so traces from cooperating
    processes can be merged by a viewer without re-basing.
    """

    def __init__(self, capacity: int = 65536, xla_annotate: bool = False):
        self._ring: collections.deque = collections.deque(maxlen=capacity)
        self._lock = threading.Lock()
        self.capacity = capacity
        self.xla_annotate = xla_annotate
        self.dropped = 0          # spans evicted from the ring
        self.recorded = 0
        # perf_counter -> wall-clock anchor, taken once
        self._wall0 = time.time()
        self._perf0 = time.perf_counter()

    # ------------------------------------------------------- recording
    def _abs_us(self, t_perf: float) -> float:
        return (self._wall0 + (t_perf - self._perf0)) * 1e6

    def record(self, name: str, t_start: float, duration: float,
               args: Optional[Dict[str, Any]] = None) -> None:
        """Append one completed span; ``t_start`` is a ``perf_counter``
        value, ``duration`` in seconds."""
        th = threading.current_thread()
        with self._lock:
            if len(self._ring) == self._ring.maxlen:
                self.dropped += 1
            self.recorded += 1
            self._ring.append((name, th.ident, th.name,
                               self._abs_us(t_start), duration * 1e6,
                               args))

    def record_instant(self, name: str,
                       args: Optional[Dict[str, Any]] = None) -> None:
        """A zero-duration marker (rendered as an instant event)."""
        self.record(name, time.perf_counter(), 0.0, args)

    # -------------------------------------------------------- snapshot
    def events(self) -> List[Dict[str, Any]]:
        """Chrome trace-event dicts for every retained span, prefixed
        with per-thread name metadata events."""
        with self._lock:
            ring = list(self._ring)
        pid = os.getpid()
        threads = {}
        for _, tid, tname, _, _, _ in ring:
            threads.setdefault(tid, tname)
        out: List[Dict[str, Any]] = [
            {"ph": "M", "name": "thread_name", "pid": pid, "tid": tid,
             "args": {"name": tname}}
            for tid, tname in threads.items()]
        for name, tid, _, ts, dur, args in ring:
            ev: Dict[str, Any] = {
                "ph": "X" if dur > 0 else "i", "name": name, "pid": pid,
                "tid": tid, "ts": round(ts, 3)}
            if dur > 0:
                ev["dur"] = round(dur, 3)
            else:
                ev["s"] = "t"          # instant event scope: thread
            if args:
                ev["args"] = args
            out.append(ev)
        return out

    def dump(self, path: str) -> str:
        """Write ``trace.json`` (Chrome trace-event JSON). Loadable by
        Perfetto / chrome://tracing; ``tools/obs_report.py`` renders the
        phase breakdown from the same file; ``tools/trace_merge.py``
        joins per-replica dumps by the identity stamped here."""
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        events = self.events()
        other: Dict[str, Any] = {"recorded": self.recorded,
                                 "dropped": self.dropped}
        run_id = os.environ.get("DLTPU_RUN_ID")
        replica = os.environ.get("DLTPU_REPLICA")
        if run_id:
            other["run_id"] = run_id
        if replica is not None and replica != "":
            other["replica"] = replica
            # name the process row so a merged fleet timeline shows
            # "replica-N" instead of a bare pid
            events.insert(0, {
                "ph": "M", "name": "process_name", "pid": os.getpid(),
                "tid": 0, "args": {"name": f"replica-{replica}"}})
        doc = {"traceEvents": events, "displayTimeUnit": "ms",
               "otherData": other}
        with open(path, "w") as f:
            json.dump(doc, f)
        return path

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self.dropped = 0
            self.recorded = 0


# --------------------------------------------------------------- toggles
def enable(capacity: int = 65536,
           xla_annotate: bool = False) -> SpanTracer:
    """Install (or return) the process-wide tracer. Idempotent: a second
    enable keeps the existing ring so layered callers (Trainer + tests)
    share one timeline."""
    global _TRACER
    if _TRACER is None:
        _TRACER = SpanTracer(capacity=capacity, xla_annotate=xla_annotate)
    elif xla_annotate:
        _TRACER.xla_annotate = True
    return _TRACER


def disable() -> Optional[SpanTracer]:
    """Uninstall the tracer; returns it (un-dumped spans stay readable)."""
    global _TRACER
    t, _TRACER = _TRACER, None
    return t


def get_tracer() -> Optional[SpanTracer]:
    return _TRACER


def enabled() -> bool:
    return _TRACER is not None


class span:
    """``with span("data_wait"): ...`` — records one host span.

    Slotted, lock-free and clock-free when tracing is disabled; when
    ``enable(xla_annotate=True)`` is active it also brackets the block
    in a ``jax.profiler.TraceAnnotation`` so the device trace shows it.
    """

    __slots__ = ("name", "args", "_t0", "_ann")

    def __init__(self, name: str, **args: Any):
        self.name = name
        self.args = args or None
        self._t0 = None
        self._ann = None

    def __enter__(self) -> "span":
        tracer = _TRACER
        if tracer is None:
            return self
        if tracer.xla_annotate:
            try:
                import jax
                self._ann = jax.profiler.TraceAnnotation(self.name)
                self._ann.__enter__()
            except Exception:  # noqa: BLE001 - annotation is best-effort
                self._ann = None
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> bool:
        tracer = _TRACER
        if tracer is not None and self._t0 is not None:
            t1 = time.perf_counter()
            if self._ann is not None:
                try:
                    self._ann.__exit__(*exc)
                except Exception:  # noqa: BLE001
                    pass
            tracer.record(self.name, self._t0, t1 - self._t0, self.args)
        self._t0 = None
        self._ann = None
        return False


class step_span:
    """Per-training-step span: a host ``span`` plus
    ``jax.profiler.StepTraceAnnotation`` (the marker XLA's step-time
    tooling groups device ops under). Annotation only happens while the
    tracer is enabled with ``xla_annotate`` so the disabled hot loop
    never constructs profiler objects."""

    __slots__ = ("_span", "_ann", "step_num")

    def __init__(self, name: str, step_num: int):
        self.step_num = step_num
        self._span = span(name, step=step_num)
        self._ann = None

    def __enter__(self) -> "step_span":
        tracer = _TRACER
        if tracer is not None and tracer.xla_annotate:
            try:
                import jax
                self._ann = jax.profiler.StepTraceAnnotation(
                    "train", step_num=self.step_num)
                self._ann.__enter__()
            except Exception:  # noqa: BLE001
                self._ann = None
        self._span.__enter__()
        return self

    def __exit__(self, *exc) -> bool:
        self._span.__exit__(*exc)
        if self._ann is not None:
            try:
                self._ann.__exit__(*exc)
            except Exception:  # noqa: BLE001
                pass
            self._ann = None
        return False


def traced(name: Optional[str] = None):
    """Decorator form: ``@traced("checkpoint")`` wraps calls in a span."""
    def deco(fn):
        span_name = name or fn.__qualname__

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            if _TRACER is None:       # fast path: no span object at all
                return fn(*args, **kwargs)
            with span(span_name):
                return fn(*args, **kwargs)
        return wrapper
    return deco
