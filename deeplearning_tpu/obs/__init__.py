"""Run-wide observability: span timeline, compile/HBM telemetry, crash
flight recorder.

Three pieces, one policy (README "Observability policy"):

- ``spans``  — thread-aware ring-buffered host span tracer; emits
  Chrome trace-event JSON (``trace.json``) and correlates with XLA
  device traces via ``jax.profiler`` annotations. Near-zero cost when
  disabled.
- ``xla``    — compile telemetry (seconds / FLOPs / peak HBM /
  persistent-cache hit per lowering, via ``tracked_compile``) and
  device-memory watermarking (``hbm_snapshot`` + the ``HbmWatermark``
  sampler thread).
- ``flight`` — bounded ring of recent structured events (step metric
  snapshots, feed stats, retraces, compiles, serve rejections) dumped
  to ``flightrec.json`` on divergence abort, uncaught trainer
  exception, or SIGTERM.
- ``metrics`` — sync-free Counter/Gauge/Histogram registry with
  Prometheus text exposition (``/metrics`` on every replica via
  ``MetricsServer``) and a JSON snapshot. Same disabled-path budget
  as spans: the hot-path helpers are one ``is None`` check when off.
- ``threads`` — the thread spawn registry: every background thread in
  the runtime is created via ``threads.spawn(target, name=...)`` so
  the concurrency linter (DLT204) and the strict-mode thread sanitizer
  know every entry point. Stdlib-only.
- ``fleet``  — scraper/aggregator over N replica ``/metrics``
  endpoints: rollups (summed QPS, max e2e p99, queue depth, replica
  status counts), SLO breach flight events, ``fleet.jsonl``
  timeseries. Pure stdlib; imported lazily (``from .obs import
  fleet``) since only supervisors need it.

``tools/obs_report.py`` renders a run directory (metrics.jsonl +
trace.json + flightrec.json + fleet.jsonl) into the phase-time report
every ROADMAP on-chip calibration item consumes;
``tools/trace_merge.py`` joins per-replica trace.json dumps into one
fleet timeline.
"""

from . import flight, metrics, spans, threads, xla
from .flight import FlightRecorder
from .metrics import MetricsRegistry, MetricsServer
from .spans import SpanTracer, span, step_span, traced
from .xla import HbmWatermark, hbm_snapshot, tracked_compile

__all__ = ["spans", "xla", "flight", "metrics", "threads", "SpanTracer",
           "span", "step_span", "traced", "FlightRecorder",
           "HbmWatermark", "hbm_snapshot", "tracked_compile",
           "MetricsRegistry", "MetricsServer"]
