"""Run-wide observability: span timeline, compile/HBM telemetry, crash
flight recorder.

Three pieces, one policy (README "Observability policy"):

- ``spans``  — thread-aware ring-buffered host span tracer; emits
  Chrome trace-event JSON (``trace.json``) and correlates with XLA
  device traces via ``jax.profiler`` annotations. Near-zero cost when
  disabled.
- ``xla``    — compile telemetry (seconds / FLOPs / peak HBM /
  persistent-cache hit per lowering, via ``tracked_compile``) and
  device-memory watermarking (``hbm_snapshot`` + the ``HbmWatermark``
  sampler thread).
- ``flight`` — bounded ring of recent structured events (step metric
  snapshots, feed stats, retraces, compiles, serve rejections) dumped
  to ``flightrec.json`` on divergence abort, uncaught trainer
  exception, or SIGTERM.

``tools/obs_report.py`` renders a run directory (metrics.jsonl +
trace.json + flightrec.json) into the phase-time report every ROADMAP
on-chip calibration item consumes.
"""

from . import flight, spans, xla
from .flight import FlightRecorder
from .spans import SpanTracer, span, step_span, traced
from .xla import HbmWatermark, hbm_snapshot, tracked_compile

__all__ = ["spans", "xla", "flight", "SpanTracer", "span", "step_span",
           "traced", "FlightRecorder", "HbmWatermark", "hbm_snapshot",
           "tracked_compile"]
