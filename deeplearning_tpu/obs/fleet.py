"""Fleet scraper/aggregator: N replica /metrics endpoints → one rollup.

The aggregation layer the ROADMAP fleet-controller item consumes: a
:class:`FleetScraper` polls every replica's ``/metrics`` (the uniform
schema ``obs/metrics.py`` exposes from both serve and train processes)
plus ``/healthz``, and :func:`compute_rollup` folds the per-replica
samples into the controller's decision signals — summed QPS, max/mean
e2e p99, total queue depth, replicas ready/warming/wedged — while an
:class:`SLOPolicy` turns budget violations (p99 over budget, error-rate
burn) into ``slo_breach`` flight events, the exact triggers a future
autoscaler keys on. Every poll appends one JSON line to
``fleet.jsonl``, the timeseries ``tools/obs_report.py --fleet`` renders.

Replica discovery: ``tools/supervise.py`` exports
``DLTPU_ENDPOINT_FILE`` per replica; each replica advertises its URL
there (``metrics.write_endpoint``), and :func:`discover_endpoints`
reads the set back from the supervisor workdir — no service registry
needed for a single-host fleet.

The module is stdlib-only (urllib against loopback replicas, json, no
jax/numpy — it is DLT100 hot-path covered) and standalone-loadable:
``tools/obs_report.py --check`` exercises the parser and rollup without
importing the package. Flight recording degrades to a no-op there.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional, Sequence, Tuple

__all__ = [
    "SLOPolicy", "FleetScraper", "parse_prometheus_text",
    "scrape_replica", "compute_rollup", "rollup_delta",
    "discover_endpoints", "record_fleet_event", "FLEET_FILE",
]

FLEET_FILE = "fleet.jsonl"

# one exposition line: name{labels} value [timestamp]
_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>[^}]*)\})?"
    r"\s+(?P<value>[^\s]+)"
    r"(?:\s+(?P<ts>-?\d+))?$")
_LABEL_RE = re.compile(
    r'(?P<key>[a-zA-Z_][a-zA-Z0-9_]*)="(?P<val>(?:\\.|[^"\\])*)"')

# the /metrics schema contract (README "Observability policy"): the
# serve adapter in tools/serve.py publishes these names; the rollup
# below consumes them. Train replicas expose dltpu_train_* instead and
# simply contribute zeros to the serve sums.
_QPS = "dltpu_serve_requests_per_s"
_REJECTS_PER_S = "dltpu_serve_rejects_per_s"
_E2E_P99 = "dltpu_serve_e2e_ms_p99"
_QUEUE_DEPTH = "dltpu_serve_queue_depth"
_REQUESTS_TOTAL = "dltpu_serve_requests_total"
_REJECTED_TOTAL = "dltpu_serve_rejected_total"
_TIMED_OUT_TOTAL = "dltpu_serve_timed_out_total"
_COMPLETED_TOTAL = "dltpu_serve_completed_total"


def _unescape(v: str) -> str:
    return v.replace(r"\"", '"').replace(r"\n", "\n").replace("\\\\", "\\")


def parse_prometheus_text(text: str
                          ) -> List[Tuple[str, Dict[str, str], float]]:
    """Parse text exposition format 0.0.4 into (name, labels, value)
    samples. Strict on purpose — this parser IS the line-format
    conformance check the acceptance test runs against our own
    exposition; a malformed line raises ``ValueError``."""
    samples: List[Tuple[str, Dict[str, str], float]] = []
    for lineno, line in enumerate(text.splitlines(), 1):
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            if not (line.startswith("# HELP ")
                    or line.startswith("# TYPE ")):
                raise ValueError(f"line {lineno}: bad comment {line!r}")
            if line.startswith("# TYPE "):
                parts = line.split(None, 3)
                if len(parts) < 4 or parts[3] not in (
                        "counter", "gauge", "histogram", "summary",
                        "untyped"):
                    raise ValueError(f"line {lineno}: bad TYPE {line!r}")
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            raise ValueError(f"line {lineno}: unparseable sample {line!r}")
        labels: Dict[str, str] = {}
        raw = m.group("labels")
        if raw:
            consumed = 0
            for lm in _LABEL_RE.finditer(raw):
                labels[lm.group("key")] = _unescape(lm.group("val"))
                consumed = lm.end()
            # everything between label pairs must be separators only
            leftover = re.sub(_LABEL_RE, "", raw).replace(",", "").strip()
            if leftover or (raw and not consumed):
                raise ValueError(f"line {lineno}: bad labels {raw!r}")
        val = m.group("value")
        if val == "+Inf":
            value = float("inf")
        elif val == "-Inf":
            value = float("-inf")
        else:
            try:
                value = float(val)
            except ValueError as e:
                raise ValueError(
                    f"line {lineno}: bad value {val!r}") from e
        samples.append((m.group("name"), labels, value))
    return samples


def _flat(samples: List[Tuple[str, Dict[str, str], float]]
          ) -> Dict[str, float]:
    """Unlabeled samples as one name→value dict (labeled samples keep
    their raw shape in the caller; the rollup only sums scalars)."""
    return {name: value for name, labels, value in samples if not labels}


def _http_json(url: str, timeout_s: float) -> Tuple[int, Any]:
    req = urllib.request.Request(url, headers={"Accept": "*/*"})
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as resp:
            return resp.status, json.loads(resp.read().decode())
    except urllib.error.HTTPError as e:
        # health endpoints answer 503 with a JSON body — read it
        try:
            return e.code, json.loads(e.read().decode())
        except Exception:  # noqa: BLE001 - body optional on errors
            return e.code, {}


def scrape_replica(url: str, timeout_s: float = 2.0) -> Dict[str, Any]:
    """One replica's sample: parsed /metrics + /healthz verdict.
    Unreachable or malformed replicas report ``ok=False`` with the error
    — the rollup counts them, it never dies on them."""
    base = url.rstrip("/")
    out: Dict[str, Any] = {"url": base, "time": time.time()}
    try:
        req = urllib.request.Request(base + "/metrics")
        with urllib.request.urlopen(req, timeout=timeout_s) as resp:
            text = resp.read().decode()
        samples = parse_prometheus_text(text)
    except (OSError, ValueError, urllib.error.URLError) as e:
        out.update(ok=False, status="unreachable", error=repr(e))
        return out
    out["ok"] = True
    out["metrics"] = _flat(samples)
    # per-tenant series: the zoo serve adapter labels its serve counters
    # with model="<alias>"; keep them grouped so the rollup can fold
    # per-model signals across replicas (the unlabeled sums above stay
    # the fleet-wide view)
    by_model: Dict[str, Dict[str, float]] = {}
    for name, labels, value in samples:
        model = labels.get("model")
        if model:
            by_model.setdefault(model, {})[name] = value
    if by_model:
        out["by_model"] = by_model
    for name, labels, _ in samples:
        if name == "dltpu_replica_info":
            out.update({k: v for k, v in labels.items()
                        if k in ("run_id", "replica")})
    try:
        code, payload = _http_json(base + "/healthz", timeout_s)
        out["status"] = str(payload.get("status")
                            or ("ready" if code == 200 else "degraded"))
        out["healthz_code"] = code
    except (OSError, ValueError, urllib.error.URLError) as e:
        # metrics answered but health didn't: count it degraded
        out["status"] = "degraded"
        out["healthz_error"] = repr(e)
    return out


def compute_rollup(samples: Sequence[Dict[str, Any]],
                   slo: Optional["SLOPolicy"] = None) -> Dict[str, Any]:
    """Fold per-replica samples into the fleet decision signals. Pure —
    no I/O — so tests and ``obs_report --check`` drive it directly."""
    statuses: Dict[str, int] = {}
    p99s: List[float] = []
    qps_total = rejects_per_s = queue_depth = 0.0
    requests_total = rejected_total = timed_out_total = 0.0
    completed_total = 0.0
    standby_replicas = 0
    for s in samples:
        statuses[s.get("status", "unreachable")] = \
            statuses.get(s.get("status", "unreachable"), 0) + 1
        if s.get("status") == "standby":
            # warm spares serve nothing — counting them as capacity
            # would dilute every per-replica signal the policy scales on
            standby_replicas += 1
            continue
        m = s.get("metrics") or {}
        qps_total += m.get(_QPS, 0.0)
        rejects_per_s += m.get(_REJECTS_PER_S, 0.0)
        queue_depth += m.get(_QUEUE_DEPTH, 0.0)
        requests_total += m.get(_REQUESTS_TOTAL, 0.0)
        rejected_total += m.get(_REJECTED_TOTAL, 0.0)
        timed_out_total += m.get(_TIMED_OUT_TOTAL, 0.0)
        completed_total += m.get(_COMPLETED_TOTAL, 0.0)
        if _E2E_P99 in m:
            p99s.append(m[_E2E_P99])
    # fold per-tenant series across replicas (zoo serving: every serve
    # counter carries a model label next to the fleet-wide sum)
    model_acc: Dict[str, Dict[str, Any]] = {}
    for s in samples:
        if s.get("status") == "standby":
            continue
        for model, m in (s.get("by_model") or {}).items():
            acc = model_acc.setdefault(model, {
                "qps_total": 0.0, "rejects_per_s_total": 0.0,
                "queue_depth_total": 0.0, "requests_total": 0.0,
                "rejected_total": 0.0, "timed_out_total": 0.0,
                "completed_total": 0.0, "_p99s": []})
            acc["qps_total"] += m.get(_QPS, 0.0)
            acc["rejects_per_s_total"] += m.get(_REJECTS_PER_S, 0.0)
            acc["queue_depth_total"] += m.get(_QUEUE_DEPTH, 0.0)
            acc["requests_total"] += m.get(_REQUESTS_TOTAL, 0.0)
            acc["rejected_total"] += m.get(_REJECTED_TOTAL, 0.0)
            acc["timed_out_total"] += m.get(_TIMED_OUT_TOTAL, 0.0)
            acc["completed_total"] += m.get(_COMPLETED_TOTAL, 0.0)
            if _E2E_P99 in m:
                acc["_p99s"].append(m[_E2E_P99])
    models: Dict[str, Dict[str, Any]] = {}
    for model, acc in model_acc.items():
        p99s_m = acc.pop("_p99s")
        acc["e2e_ms_p99_max"] = round(max(p99s_m), 3) if p99s_m else 0.0
        errs = acc["rejected_total"] + acc["timed_out_total"]
        acc["error_rate"] = round(
            errs / max(acc["requests_total"] + acc["rejected_total"],
                       1.0), 5)
        if slo is not None:
            acc["slo"] = slo.evaluate(acc)
        models[model] = acc

    errors = rejected_total + timed_out_total
    error_rate = errors / max(requests_total + rejected_total, 1.0)
    rollup: Dict[str, Any] = {
        "time": time.time(),
        "replicas": len(samples) - standby_replicas,
        "standby_replicas": standby_replicas,
        "replica_status": statuses,
        "qps_total": round(qps_total, 3),
        "rejects_per_s_total": round(rejects_per_s, 3),
        "e2e_ms_p99_max": round(max(p99s), 3) if p99s else 0.0,
        "e2e_ms_p99_mean": round(sum(p99s) / len(p99s), 3)
        if p99s else 0.0,
        "queue_depth_total": round(queue_depth, 1),
        "requests_total": requests_total,
        "completed_total": completed_total,
        "rejected_total": rejected_total,
        "timed_out_total": timed_out_total,
        "error_rate": round(error_rate, 5),
    }
    if models:
        rollup["models"] = models
    if slo is not None:
        rollup["slo"] = slo.evaluate(rollup)
    return rollup


_DELTA_COUNTERS = ("requests_total", "completed_total",
                   "rejected_total", "timed_out_total")


def rollup_delta(prev: Optional[Dict[str, Any]],
                 cur: Dict[str, Any]) -> Dict[str, Any]:
    """Counter movement between two rollups — the *rate* view a
    controller scales on (cumulative totals only ever grow, so "is the
    fleet actually serving right now" needs the difference). Pure.
    Negative movement (a replica restarted and its counters reset) is
    clamped to 0 rather than reported as negative throughput."""
    dt = max(cur.get("time", 0.0) - (prev or {}).get("time", 0.0), 0.0)
    delta: Dict[str, Any] = {"dt_s": round(dt, 3)}
    for key in _DELTA_COUNTERS:
        d = cur.get(key, 0.0) - (prev or {}).get(key, 0.0)
        d = max(d, 0.0)
        delta[key] = d
        delta[key.replace("_total", "_per_s")] = (
            round(d / dt, 3) if dt > 0 else 0.0)
    return delta


def record_fleet_event(kind: str, **data: Any) -> None:
    """Controller actuation events (``fleet_scale``/``fleet_drain``/
    ``fleet_requeue``) into the process flight ring — the same ring the
    ``slo_breach`` triggers land in, so cause and action interleave in
    one timeline. Best-effort, like every fleet flight write."""
    _flight_record(kind, **data)


class SLOPolicy:
    """Fleet SLO: an e2e p99 budget and an error-rate budget (rejected +
    timed-out over submitted). ``evaluate`` stamps the verdict into the
    rollup; the scraper records each breach as a flight event — the
    trigger stream a fleet controller will consume."""

    def __init__(self, p99_budget_ms: float = 500.0,
                 error_rate_budget: float = 0.01):
        self.p99_budget_ms = float(p99_budget_ms)
        self.error_rate_budget = float(error_rate_budget)

    def evaluate(self, rollup: Dict[str, Any]) -> Dict[str, Any]:
        p99 = rollup.get("e2e_ms_p99_max", 0.0)
        err = rollup.get("error_rate", 0.0)
        p99_breach = p99 > self.p99_budget_ms
        error_breach = err > self.error_rate_budget
        return {
            "p99_budget_ms": self.p99_budget_ms,
            "error_rate_budget": self.error_rate_budget,
            "p99_ms": p99,
            "error_rate": err,
            "p99_breach": p99_breach,
            "error_breach": error_breach,
            "breach": p99_breach or error_breach,
        }


def _flight_record(kind: str, **data: Any) -> None:
    """Best-effort flight event; a no-op when this module is loaded
    standalone (obs_report --check has no package context)."""
    try:
        from .flight import record
    except ImportError:
        return
    record(kind, **data)


def _thread_registry():
    """obs.threads resolvable under standalone file loads too (same
    trick as ``metrics._thread_registry``): one registry per process."""
    import sys
    mod = sys.modules.get("deeplearning_tpu.obs.threads")
    if mod is None:
        import importlib.util
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "threads.py")
        spec = importlib.util.spec_from_file_location(
            "deeplearning_tpu.obs.threads", path)
        mod = importlib.util.module_from_spec(spec)
        sys.modules[spec.name] = mod
        spec.loader.exec_module(mod)
    return mod


def _pid_alive(pid: Any) -> bool:
    try:
        pid = int(pid)
    except (TypeError, ValueError):
        return False
    if pid <= 0:
        return False
    try:
        os.kill(pid, 0)               # signal 0: existence probe only
    except ProcessLookupError:
        return False
    except OSError:
        return True                   # exists but not ours (EPERM)
    return True


def discover_endpoints(run_dir: str, *,
                       live_only: bool = False) -> List[str]:
    """Replica URLs advertised under a supervisor workdir: reads
    ``endpoint.json`` in the dir itself and in each ``replica-*/``
    child dir, ordered by replica id then path. With ``live_only`` the
    advertised pid must still exist — endpoint files are per-workdir
    leftovers that outlive their process, and a controller that counts
    a dead replica's stale advert as capacity will never scale up."""
    candidates = [os.path.join(run_dir, "endpoint.json")]
    try:
        entries = sorted(os.listdir(run_dir))
    except OSError:
        entries = []
    for name in entries:
        p = os.path.join(run_dir, name, "endpoint.json")
        if os.path.isdir(os.path.join(run_dir, name)):
            candidates.append(p)
    found: List[Tuple[int, str]] = []
    for path in candidates:
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        url = doc.get("url") if isinstance(doc, dict) else None
        if not url:
            continue
        if live_only and not _pid_alive(doc.get("pid")):
            continue
        try:
            order = int(doc.get("replica", len(found)))
        except (TypeError, ValueError):
            order = len(found)
        found.append((order, url))
    return [url for _, url in sorted(found)]


class FleetScraper:
    """Poll a replica set, compute the rollup, track the SLO, append the
    ``fleet.jsonl`` timeseries. ``scrape_once()`` is the unit of work;
    ``start()`` runs it on an interval from a daemon thread
    ("fleet-scrape") for long-lived supervisors."""

    def __init__(self, endpoints: Sequence[str], *,
                 slo: Optional[SLOPolicy] = None,
                 fleet_path: Optional[str] = None,
                 timeout_s: float = 2.0,
                 interval_s: float = 5.0,
                 breach_cooldown_s: float = 60.0):
        self.endpoints = list(endpoints)
        self.slo = slo
        self.fleet_path = fleet_path
        self.timeout_s = float(timeout_s)
        self.interval_s = max(float(interval_s), 0.05)
        # slo_breach events are EDGE-triggered per signal: one event when
        # a signal starts breaching, at most one refresher per cooldown
        # while it stays breached, one slo_clear when it recovers — a
        # 10-minute sustained breach is 10-ish events, not 120 identical
        # lines flooding the flight ring
        self.breach_cooldown_s = float(breach_cooldown_s)
        self.polls = 0
        self.breaches = 0
        self.model_breaches = 0
        self.last_rollup: Optional[Dict[str, Any]] = None
        self._breach_fired_at: Dict[str, float] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------- poll
    def _edge(self, key: str, breached: bool, now: float) -> bool:
        """True when a breach event should FIRE for this signal now:
        the rising edge, or a cooldown-spaced refresher while sustained.
        Falling edges emit one ``slo_clear`` and reset the state."""
        fired_at = self._breach_fired_at.get(key)
        if breached:
            if fired_at is None:
                self._breach_fired_at[key] = now
                return True
            if now - fired_at >= self.breach_cooldown_s:
                self._breach_fired_at[key] = now
                return True
            return False
        if fired_at is not None:
            del self._breach_fired_at[key]
            _flight_record("slo_clear", signal=key)
        return False

    def scrape_once(self) -> Dict[str, Any]:
        samples = [scrape_replica(u, self.timeout_s)
                   for u in self.endpoints]
        rollup = compute_rollup(samples, self.slo)
        rollup["per_replica"] = [
            {k: s.get(k) for k in ("url", "replica", "run_id", "status")
             if s.get(k) is not None}
            for s in samples]
        rollup["delta"] = rollup_delta(self.last_rollup, rollup)
        self.polls += 1
        self.last_rollup = rollup
        now = time.monotonic()
        verdict = rollup.get("slo") or {}
        if verdict.get("breach"):
            self.breaches += 1
        for signal, flag in (("p99", "p99_breach"),
                             ("error_rate", "error_breach")):
            if self._edge(signal, bool(verdict.get(flag)), now):
                _flight_record(
                    "slo_breach", signal=signal,
                    p99_ms=verdict["p99_ms"],
                    p99_budget_ms=verdict["p99_budget_ms"],
                    error_rate=verdict["error_rate"],
                    error_rate_budget=verdict["error_rate_budget"],
                    qps_total=rollup["qps_total"],
                    replicas=rollup["replicas"])
        # per-tenant breaches: one event per breaching model so the
        # controller can act on the hot tenant, not the whole fleet —
        # edge-triggered per (model, signal) like the fleet-wide pair
        models = rollup.get("models") or {}
        for model, row in sorted(models.items()):
            mv = row.get("slo") or {}
            if mv.get("breach"):
                self.model_breaches += 1
            breach_signal = ("p99" if mv.get("p99_breach")
                             else "error_rate")
            if self._edge(f"model:{model}", bool(mv.get("breach")), now):
                _flight_record(
                    "slo_breach", model=model,
                    signal=breach_signal,
                    p99_ms=mv["p99_ms"],
                    p99_budget_ms=mv["p99_budget_ms"],
                    error_rate=mv["error_rate"],
                    error_rate_budget=mv["error_rate_budget"],
                    qps_total=row.get("qps_total", 0.0))
        if self.fleet_path:
            self._append(rollup)
        return rollup

    def _append(self, rollup: Dict[str, Any]) -> None:
        d = os.path.dirname(os.path.abspath(self.fleet_path))
        try:
            os.makedirs(d, exist_ok=True)
            with open(self.fleet_path, "a") as f:
                f.write(json.dumps(rollup) + "\n")
        except OSError as e:
            # a missed timeseries row is not a scrape failure
            self.last_write_error = repr(e)

    # ------------------------------------------------------- background
    def _run(self) -> None:
        self.scrape_once()
        while not self._stop.wait(self.interval_s):
            try:
                self.scrape_once()
            except Exception as e:  # noqa: BLE001 - keep polling
                self.last_poll_error = repr(e)

    def start(self) -> "FleetScraper":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = _thread_registry().spawn(
                self._run, name="fleet-scrape", daemon=True)
        return self

    def stop(self, timeout: float = 2.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None
