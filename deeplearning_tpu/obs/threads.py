"""Thread spawn registry: the one place background threads are born.

The runtime grew a real thread fleet — feed prefetcher, serve dispatch,
zoo loaders, HBM sampler, heartbeat writer, metrics/fleet servers,
wedge watchers — and the concurrency linter (``analysis/concurrency.py``,
rule DLT204) needs every entry point to be enumerable: a ``Thread``
whose target nobody can find is a shared-state writer nobody audits.
``spawn()`` is that choke point. It creates, records, and (by default)
starts a **named** thread; ``inventory()`` exposes what was spawned so
``tools/obs_report.py`` and the strict-mode thread sanitizer can cross-
check the live fleet against the statically known spawn sites.

Stdlib-only by construction (no jax, no intra-package imports): the
supervisor and ``tools/check.py`` load paths must stay light, and the
registry itself must be importable from a signal handler's drain hook.

Contract (README "Concurrency policy"):

- every background thread is created via ``spawn(target, name=...)`` —
  raw ``threading.Thread(...)`` anywhere else is a DLT204 finding;
- every thread has a stable, grep-able name (it shows up in span
  timelines, flight events, and sanitizer autopsies);
- non-daemon threads are the caller's to ``join()`` (DLT203 audits
  that); the registry records daemon-ness so the report can show which
  threads can outlive a clean shutdown.
"""

from __future__ import annotations

import threading
import time
import weakref
from typing import Any, Callable, Dict, List, Optional, Tuple

__all__ = ["spawn", "inventory", "counts", "live", "clear"]

_LOCK = threading.Lock()
_MAX_RECORDS = 4096              # loadgen fleets are the realistic ceiling
_RECORDS: List[Dict[str, Any]] = []
_spawned_total = 0


def spawn(target: Callable[..., Any], *, name: str,
          args: Tuple = (), kwargs: Optional[Dict[str, Any]] = None,
          daemon: bool = True, start: bool = True) -> threading.Thread:
    """Create (and by default start) a registered background thread.

    ``name`` is mandatory — an anonymous thread is un-auditable. With
    ``start=False`` the caller finishes its own bookkeeping (publish the
    handle, attach a stop event) before calling ``.start()`` itself.
    """
    if not name:
        raise ValueError("spawn() requires a non-empty thread name")
    thread = threading.Thread(target=target, name=name, args=args,
                              kwargs=kwargs or {}, daemon=daemon)
    record = {
        "name": name,
        "daemon": bool(daemon),
        "target": getattr(target, "__qualname__", None) or repr(target),
        "created": time.time(),
        "ref": weakref.ref(thread),
    }
    global _spawned_total
    with _LOCK:
        _spawned_total += 1
        _RECORDS.append(record)
        if len(_RECORDS) > _MAX_RECORDS:
            del _RECORDS[: len(_RECORDS) - _MAX_RECORDS]
    if start:
        thread.start()
    return thread


def inventory() -> List[Dict[str, Any]]:
    """Snapshot of every recorded spawn (newest last): name, target,
    daemon-ness, and whether the thread is still alive. Dead threads
    whose objects were collected stay listed with ``alive=False`` —
    the inventory is a history, not just a census."""
    with _LOCK:
        records = list(_RECORDS)
    out = []
    for r in records:
        thread = r["ref"]()
        out.append({
            "name": r["name"],
            "target": r["target"],
            "daemon": r["daemon"],
            "created": r["created"],
            "alive": bool(thread is not None and thread.is_alive()),
        })
    return out

def live() -> List[str]:
    """Names of registered threads currently alive."""
    return [r["name"] for r in inventory() if r["alive"]]


def counts() -> Dict[str, int]:
    inv = inventory()
    return {
        "spawned_total": _spawned_total,
        "recorded": len(inv),
        "alive": sum(1 for r in inv if r["alive"]),
        "non_daemon": sum(1 for r in inv if not r["daemon"]),
    }


def clear() -> None:
    """Test hook: drop the history (does not touch live threads)."""
    global _spawned_total
    with _LOCK:
        _RECORDS.clear()
        _spawned_total = 0
