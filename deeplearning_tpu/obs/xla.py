"""Compile + device-memory telemetry.

Every AOT lowering in the library (Trainer.precompile, the serving
engine's bucket warmup, ``utils.profiling.compiled_flops``) funnels
through ``tracked_compile``: the compile is timed, XLA's
``cost_analysis`` (FLOPs) and ``memory_analysis`` (peak HBM) are read
off the executable, a persistent-cache hit/miss verdict is taken from
the cache directory, and the event lands in three places at once — the
bounded ``compile_events()`` ring (the ``/stats`` surface), the span
timeline (a ``compile/<name>`` span with FLOPs/HBM args), and the
flight recorder (so a crash dump shows what was compiled when).

HBM watermarking: ``hbm_snapshot()`` reads ``device.memory_stats()``
(TPU runtimes report ``bytes_in_use``/``peak_bytes_in_use``; CPU
returns nothing) plus a ``jax.live_arrays()`` census — count and total
bytes of every live buffer the process holds. ``HbmWatermark`` samples
that snapshot from its own thread ("obs-metrics") on an interval,
tracking run-peak values; its samples are spans, so the timeline shows
memory next to the phases that allocated it.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Dict, List, Optional

from . import flight, metrics, spans
from . import threads as obs_threads

__all__ = ["tracked_compile", "compile_events", "compile_stats",
           "memory_analysis_dict", "hbm_snapshot", "HbmWatermark",
           "set_hbm_alert_frac"]

# bounded ring of compile-event dicts (module-wide: compiles are rare
# and the ring is the natural join point for /stats and obs_report)
_EVENTS: collections.deque = collections.deque(maxlen=512)
_EVENTS_LOCK = threading.Lock()

_MEM_FIELDS = ("temp_size_in_bytes", "argument_size_in_bytes",
               "output_size_in_bytes", "alias_size_in_bytes",
               "generated_code_size_in_bytes")


def memory_analysis_dict(compiled) -> Dict[str, float]:
    """``Compiled.memory_analysis()`` as a plain dict (missing fields and
    backends without the analysis yield ``{}`` — never raises)."""
    try:
        mem = compiled.memory_analysis()
    except Exception:  # noqa: BLE001 - analysis is backend-optional
        return {}
    if mem is None:
        return {}
    out: Dict[str, float] = {}
    for field in _MEM_FIELDS:
        val = getattr(mem, field, None)
        if val is not None:
            out[field] = float(val)
    if out:
        # the executable's device-memory high-water mark: resident
        # args + outputs + scratch (aliased bytes counted once)
        out["peak_hbm_bytes"] = (
            out.get("argument_size_in_bytes", 0.0)
            + out.get("output_size_in_bytes", 0.0)
            + out.get("temp_size_in_bytes", 0.0)
            - out.get("alias_size_in_bytes", 0.0))
    return out


def _cache_entries() -> Optional[int]:
    """File count in the persistent compile cache (None when disabled)."""
    import os

    from ..core.compile_cache import active_cache_dir
    cache_dir = active_cache_dir()
    if not cache_dir or not os.path.isdir(cache_dir):
        return None
    try:
        return len(os.listdir(cache_dir))
    except OSError:
        return None


def tracked_compile(lowered, name: str):
    """``lowered.compile()`` with telemetry: returns the executable and
    records {fn, seconds, flops, peak_hbm_bytes, cache_hit} everywhere
    the observability stack looks. Never raises past the compile itself
    — a telemetry failure must not fail a warmup path."""
    before = _cache_entries()
    t0 = time.perf_counter()
    compiled = lowered.compile()
    seconds = time.perf_counter() - t0
    try:
        from ..utils.profiling import cost_analysis_dict
        cost = cost_analysis_dict(compiled)
        mem = memory_analysis_dict(compiled)
        after = _cache_entries()
        # no new cache entry materialized -> the persistent cache (or
        # jit's in-memory executable cache) served this lowering
        cache_hit = (None if before is None or after is None
                     else after <= before)
        event = {
            "fn": name,
            "seconds": round(seconds, 4),
            "flops": float(cost.get("flops", 0.0)),
            "bytes_accessed": float(cost.get("bytes accessed", 0.0)),
            "peak_hbm_bytes": mem.get("peak_hbm_bytes", 0.0),
            "cache_hit": cache_hit,
            "time": time.time(),
        }
        with _EVENTS_LOCK:
            _EVENTS.append(event)
        tracer = spans.get_tracer()
        if tracer is not None:
            tracer.record(f"compile/{name}", t0, seconds,
                          {k: event[k] for k in
                           ("seconds", "flops", "peak_hbm_bytes",
                            "cache_hit")})
        flight.record("compile", **event)
        metrics.inc("dltpu_compiles_total")
        metrics.inc("dltpu_compile_seconds_total", seconds)
        if cache_hit:
            metrics.inc("dltpu_compile_cache_hits_total")
    except Exception:  # noqa: BLE001 - telemetry never fails a compile
        pass
    return compiled


def compile_events(last: Optional[int] = None) -> List[Dict[str, Any]]:
    with _EVENTS_LOCK:
        events = list(_EVENTS)
    return events if last is None else events[-last:]


def compile_stats() -> Dict[str, float]:
    """Aggregate view for /stats and bench rows."""
    events = compile_events()
    hits = sum(1 for e in events if e.get("cache_hit"))
    return {
        "compiles": float(len(events)),
        "compile_seconds_total": round(
            sum(e["seconds"] for e in events), 4),
        "compile_cache_hits": float(hits),
        "compile_peak_hbm_bytes": max(
            (e["peak_hbm_bytes"] for e in events), default=0.0),
    }


def clear_compile_events() -> None:
    with _EVENTS_LOCK:
        _EVENTS.clear()


# ------------------------------------------------------------- memory
# ROADMAP calibration-debt note: memory_stats() field sets vary by
# device generation (v4 lacks some of what v5 reports, CPU reports
# nothing), so every field is individually optional and individually
# int-converted — one odd field must not drop the whole entry.
_HBM_FIELDS = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit",
               "largest_alloc_size", "bytes_reserved",
               "pool_bytes", "num_allocs")

# alert when bytes_in_use crosses this fraction of bytes_limit (None =
# off). Process-wide because hbm_snapshot is called from crash dumps and
# sampler threads that have no config handle.
_ALERT_FRAC: Optional[float] = None
_ALERTED: set = set()          # device ids already alerted (edge-trigger)


def set_hbm_alert_frac(frac: Optional[float]) -> Optional[float]:
    """Configure (or disable, with None) the HBM usage alert threshold;
    returns the previous value. The Trainer wires its ``hbm_alert_frac``
    kwarg here; ``DLTPU_HBM_ALERT_FRAC`` seeds it for bare scripts."""
    global _ALERT_FRAC
    previous = _ALERT_FRAC
    _ALERT_FRAC = None if frac is None else float(frac)
    _ALERTED.clear()
    return previous


def _env_alert_frac() -> Optional[float]:
    import os
    raw = os.environ.get("DLTPU_HBM_ALERT_FRAC")
    if not raw:
        return None
    try:
        return float(raw)
    except ValueError:
        return None


def _mem_entry(dev, stats, alert_frac: Optional[float]) -> Dict[str, Any]:
    """One device's snapshot entry from a raw memory_stats() dict, with
    per-field guards and the optional usage-fraction alert."""
    entry: Dict[str, Any] = {"id": dev.id,
                             "kind": getattr(dev, "device_kind", "")}
    if not stats:
        return entry
    for key in _HBM_FIELDS:
        if key in stats:
            try:
                entry[key] = int(stats[key])
            except (TypeError, ValueError):
                pass           # generation reports a non-numeric field
    in_use, limit = entry.get("bytes_in_use"), entry.get("bytes_limit")
    if in_use is not None and limit:
        frac = in_use / limit
        entry["usage_frac"] = round(frac, 4)
        if alert_frac is not None and frac >= alert_frac:
            entry["alert"] = {"threshold_frac": alert_frac,
                              "usage_frac": round(frac, 4)}
            if dev.id not in _ALERTED:     # edge-trigger: once per device
                _ALERTED.add(dev.id)
                flight.record("hbm_alert", device=dev.id,
                              usage_frac=round(frac, 4),
                              threshold_frac=alert_frac,
                              bytes_in_use=in_use, bytes_limit=limit)
        elif alert_frac is not None:
            _ALERTED.discard(dev.id)       # re-arm once usage recedes
    return entry


def hbm_snapshot(alert_frac: Optional[float] = None) -> Dict[str, Any]:
    """One point-in-time device-memory reading; cheap enough to take at
    crash time and from the sampler thread. Fields that a backend or
    device generation does not report are simply absent. When an alert
    fraction is configured (argument > ``set_hbm_alert_frac`` >
    ``DLTPU_HBM_ALERT_FRAC``), a device crossing it gets an ``alert``
    sub-dict and an edge-triggered ``hbm_alert`` flight event."""
    if alert_frac is None:
        alert_frac = _ALERT_FRAC if _ALERT_FRAC is not None \
            else _env_alert_frac()
    snap: Dict[str, Any] = {"time": time.time()}
    try:
        import jax
        devices = []
        for d in jax.devices():
            try:
                stats = d.memory_stats()
            except Exception:  # noqa: BLE001 - CPU backends raise/None
                stats = None
            devices.append(_mem_entry(d, stats, alert_frac))
        snap["devices"] = devices
        arrs = jax.live_arrays()
        snap["live_arrays"] = {
            "count": len(arrs),
            "nbytes": int(sum(getattr(a, "nbytes", 0) for a in arrs)),
        }
    except Exception:  # noqa: BLE001 - snapshot is best-effort
        pass
    return snap


class HbmWatermark:
    """Background HBM sampler: one daemon thread ("obs-metrics") taking
    ``hbm_snapshot()`` every ``interval_s``, keeping run-peak watermarks
    and emitting each sample as a span from its own thread — the third
    lane of the trace timeline next to the hot loop and the feed worker.

    An immediate first sample on ``start()`` guarantees even a 5-step
    smoke run records at least one memory point."""

    def __init__(self, interval_s: float = 0.5,
                 alert_frac: Optional[float] = None):
        self.interval_s = max(float(interval_s), 0.01)
        self.alert_frac = alert_frac
        self.samples = 0
        self.peak_live_bytes = 0
        self.peak_bytes_in_use = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _sample(self) -> None:
        t0 = time.perf_counter()
        snap = hbm_snapshot(alert_frac=self.alert_frac)
        self.samples += 1
        live = snap.get("live_arrays", {}).get("nbytes", 0)
        self.peak_live_bytes = max(self.peak_live_bytes, live)
        for dev in snap.get("devices", []):
            in_use = dev.get("bytes_in_use", 0)
            self.peak_bytes_in_use = max(self.peak_bytes_in_use, in_use)
        tracer = spans.get_tracer()
        if tracer is not None:
            tracer.record("hbm_sample", t0,
                          time.perf_counter() - t0,
                          {"live_bytes": live,
                           "live_count":
                               snap.get("live_arrays", {}).get("count", 0),
                           "peak_live_bytes": self.peak_live_bytes})
        metrics.set_gauge("dltpu_hbm_live_bytes", float(live))
        metrics.set_gauge("dltpu_hbm_peak_live_bytes",
                          float(self.peak_live_bytes))
        metrics.set_gauge("dltpu_hbm_peak_bytes_in_use",
                          float(self.peak_bytes_in_use))

    def _run(self) -> None:
        self._sample()                       # guaranteed first point
        while not self._stop.wait(self.interval_s):
            try:
                self._sample()
            except Exception:  # noqa: BLE001 - sampling is best-effort
                pass

    def start(self) -> "HbmWatermark":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = obs_threads.spawn(
                self._run, name="obs-metrics", daemon=True)
        return self

    def stop(self, timeout: float = 2.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)

    def watermark(self) -> Dict[str, float]:
        return {
            "hbm_samples": float(self.samples),
            "peak_live_bytes": float(self.peak_live_bytes),
            "peak_bytes_in_use": float(self.peak_bytes_in_use),
        }

    def __enter__(self) -> "HbmWatermark":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
