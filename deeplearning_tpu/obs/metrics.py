"""Sync-free metrics registry: Counter/Gauge/Histogram + /metrics.

The scrape surface of the fleet telemetry plane. Every replica — train
or serve — exposes ONE uniform schema (Prometheus text format on
``GET /metrics``, a JSON snapshot on ``GET /metrics.json``) that
``obs/fleet.py`` aggregates into rollups. Sources feed the registry two
ways:

- **push**: hot-path sites call the module-level ``inc()`` /
  ``set_gauge()`` / ``observe()`` helpers (``tracked_compile``,
  ``HbmWatermark``, Trainer step/feed/recovery, quarantine).
- **pull**: ``register_collector(fn)`` hooks run at scrape time and
  mirror an existing telemetry surface (``ServeTelemetry.snapshot()``,
  ``engine.stats()``) into gauges/counters — zero added cost on the
  request path.

Cost discipline (same budget as ``obs/spans.py``, enforced by the
bench ``metrics_overhead`` A/B and by DLT100 coverage of this module):
- **Disabled** (the default): each helper is one module-pointer load
  plus an ``is None`` check — no lock, no allocation.
- **Enabled**: a dict lookup and one O(1) add under the metric's own
  lock. Histograms hold a fixed bucket array; nothing grows with
  traffic. Never a device sync — this module imports neither jax nor
  numpy, and scrape-time collection happens on the HTTP thread.

Identity: when ``tools/supervise.py`` hands down ``DLTPU_RUN_ID`` /
``DLTPU_REPLICA``, the exposition carries a ``dltpu_replica_info``
gauge with those labels — the join key fleet scrapes, heartbeats, and
merged traces share.

Stdlib-only and importable standalone (no relative imports):
``tools/obs_report.py --check`` loads this file without jax.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "MetricsServer",
    "enable", "disable", "get_registry", "enabled",
    "inc", "set_gauge", "observe",
    "replica_identity", "write_endpoint", "read_endpoint",
    "DEFAULT_BUCKETS_MS",
]

# module-level pointer: the `is None` check is the entire disabled-path
# cost (the spans.py discipline, applied to counters)
_REGISTRY: Optional["MetricsRegistry"] = None

# fixed latency-style bucket bounds (ms). Fixed at metric creation so
# enabled-path state is a constant-size int array, never a growing one.
DEFAULT_BUCKETS_MS: Tuple[float, ...] = (
    1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0, 1000.0, 2500.0)

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")

# the env contract tools/supervise.py hands its children (also stamped
# into heartbeat files and trace metadata)
RUN_ID_VAR = "DLTPU_RUN_ID"
REPLICA_VAR = "DLTPU_REPLICA"
ENDPOINT_FILE_VAR = "DLTPU_ENDPOINT_FILE"


def replica_identity() -> Dict[str, str]:
    """{run_id, replica} from the supervisor-handed env, empty when
    unsupervised — the join key across /metrics, heartbeats, traces."""
    out: Dict[str, str] = {}
    run_id = os.environ.get(RUN_ID_VAR)
    replica = os.environ.get(REPLICA_VAR)
    if run_id:
        out["run_id"] = run_id
    if replica is not None and replica != "":
        out["replica"] = replica
    return out


def _check_name(name: str) -> str:
    if not _NAME_RE.match(name):
        raise ValueError(f"invalid metric name {name!r} "
                         "(prometheus [a-zA-Z_:][a-zA-Z0-9_:]*)")
    return name


def _escape_label(value: str) -> str:
    return (str(value).replace("\\", r"\\").replace("\n", r"\n")
            .replace('"', r'\"'))


def _fmt_labels(labels: Optional[Dict[str, str]]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape_label(v)}"'
                     for k, v in sorted(labels.items()))
    return "{" + inner + "}"


def _fmt_value(v: float) -> str:
    if v == float("inf"):
        return "+Inf"
    if v == float("-inf"):
        return "-Inf"
    if v != v:                                   # NaN
        return "NaN"
    return repr(float(v))


class Counter:
    """Monotonic float counter. ``inc()`` is the push path;
    ``set_total()`` mirrors an external monotonic count at scrape time
    (pull collectors) — it never moves the value backwards, so the
    prometheus counter contract holds even when the source resets."""

    __slots__ = ("name", "help", "labels", "_lock", "_value")
    kind = "counter"

    def __init__(self, name: str, help: str = "",
                 labels: Optional[Dict[str, str]] = None):
        self.name = _check_name(name)
        self.help = help
        self.labels = dict(labels) if labels else None
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    def set_total(self, value: float) -> None:
        with self._lock:
            if value > self._value:
                self._value = float(value)

    @property
    def value(self) -> float:
        return self._value

    def _sample(self) -> Dict[str, Any]:
        return {"type": self.kind, "help": self.help, "value": self._value}

    def _expose(self) -> List[str]:
        return [f"{self.name}{_fmt_labels(self.labels)} "
                f"{_fmt_value(self._value)}"]


class Gauge(Counter):
    """Point-in-time value; ``set()`` overwrites, ``inc()`` adjusts."""

    __slots__ = ()
    kind = "gauge"

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)


class Histogram:
    """Fixed-bucket histogram: ``observe(v)`` bumps exactly one bucket
    slot plus sum/count under one lock — bounded state, O(buckets)
    exposition, never a growing ring."""

    __slots__ = ("name", "help", "labels", "buckets", "_lock",
                 "_counts", "_sum", "_count")
    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 buckets: Optional[Sequence[float]] = None,
                 labels: Optional[Dict[str, str]] = None):
        self.name = _check_name(name)
        self.help = help
        self.labels = dict(labels) if labels else None
        bounds = tuple(sorted(float(b) for b in
                              (buckets or DEFAULT_BUCKETS_MS)))
        if not bounds:
            raise ValueError("histogram needs at least one bucket bound")
        self.buckets = bounds
        self._lock = threading.Lock()
        self._counts = [0] * (len(bounds) + 1)   # +1: the +Inf overflow
        self._sum = 0.0
        self._count = 0

    def observe(self, value: float) -> None:
        v = float(value)
        i = len(self.buckets)                    # default: +Inf slot
        for j, bound in enumerate(self.buckets):
            if v <= bound:
                i = j
                break
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def _cumulative(self) -> List[Tuple[str, int]]:
        with self._lock:
            counts = list(self._counts)
        out, running = [], 0
        for bound, c in zip(self.buckets, counts):
            running += c
            out.append((_fmt_value(bound), running))
        out.append(("+Inf", running + counts[-1]))
        return out

    def _sample(self) -> Dict[str, Any]:
        return {"type": self.kind, "help": self.help,
                "buckets": {le: c for le, c in self._cumulative()},
                "sum": round(self._sum, 6), "count": self._count}

    def _expose(self) -> List[str]:
        base = dict(self.labels) if self.labels else {}
        lines = []
        for le, c in self._cumulative():
            lines.append(f"{self.name}_bucket"
                         f"{_fmt_labels({**base, 'le': le})} {c}")
        lab = _fmt_labels(self.labels)
        lines.append(f"{self.name}_sum{lab} {_fmt_value(self._sum)}")
        lines.append(f"{self.name}_count{lab} {self._count}")
        return lines


class MetricsRegistry:
    """One process's metric store: get-or-create metric handles plus
    scrape-time pull collectors. All ops are lock-light and host-only;
    exposition runs on the scraping thread, never a hot path."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: Dict[str, Any] = {}       # name -> metric (ordered)
        self._collectors: List[Callable[["MetricsRegistry"], None]] = []
        self.collect_errors = 0
        self.created = time.time()

    # ------------------------------------------------------ get-or-create
    def _get(self, name: str, factory: Callable[[], Any], kind: str,
             labels: Optional[Dict[str, str]] = None):
        # a labeled series is its own metric object keyed by
        # name+labelset (the prometheus data model: one timeseries per
        # distinct label combination under a shared metric name)
        key = name if not labels else name + _fmt_labels(labels)
        metric = self._metrics.get(key)          # GIL-safe fast path
        if metric is None:
            with self._lock:
                metric = self._metrics.get(key)
                if metric is None:
                    metric = factory()
                    self._metrics[key] = metric
        if metric.kind != kind:
            raise TypeError(f"metric {name!r} is a {metric.kind}, "
                            f"not a {kind}")
        return metric

    def counter(self, name: str, help: str = "",
                labels: Optional[Dict[str, str]] = None) -> Counter:
        return self._get(name, lambda: Counter(name, help, labels),
                         "counter", labels)

    def gauge(self, name: str, help: str = "",
              labels: Optional[Dict[str, str]] = None) -> Gauge:
        return self._get(name, lambda: Gauge(name, help, labels),
                         "gauge", labels)

    def histogram(self, name: str, help: str = "",
                  buckets: Optional[Sequence[float]] = None,
                  labels: Optional[Dict[str, str]] = None) -> Histogram:
        return self._get(name,
                         lambda: Histogram(name, help, buckets, labels),
                         "histogram", labels)

    # --------------------------------------------------------- collectors
    def register_collector(
            self, fn: Callable[["MetricsRegistry"], None]) -> None:
        """Scrape-time hook mirroring an existing telemetry surface into
        this registry (the pull path: zero hot-path cost)."""
        with self._lock:
            if fn not in self._collectors:
                self._collectors.append(fn)

    def collect(self) -> None:
        with self._lock:
            collectors = list(self._collectors)
        for fn in collectors:
            try:
                fn(self)
            except Exception:  # noqa: BLE001 - one bad source must not
                self.collect_errors += 1         # poison the whole scrape

    # --------------------------------------------------------- exposition
    def _info_metric(self) -> Optional[Gauge]:
        ident = replica_identity()
        if not ident:
            return None
        g = Gauge("dltpu_replica_info",
                  "replica identity handed down by the supervisor",
                  labels=ident)
        g.set(1.0)
        return g

    def _all_metrics(self) -> List[Any]:
        with self._lock:
            metrics = list(self._metrics.values())
        info = self._info_metric()
        return ([info] + metrics) if info is not None else metrics

    def prometheus_text(self) -> str:
        """Prometheus text exposition format 0.0.4 (# HELP / # TYPE +
        sample lines; histograms as cumulative _bucket/_sum/_count).
        Labeled series of one name are grouped under a single
        HELP/TYPE header, per the format's one-family-per-name rule."""
        self.collect()
        by_name: Dict[str, List[Any]] = {}
        for m in self._all_metrics():
            by_name.setdefault(m.name, []).append(m)
        lines: List[str] = []
        for name, family in by_name.items():
            head = family[0]
            help_text = next((m.help for m in family if m.help), "")
            if help_text:
                lines.append(f"# HELP {name} {help_text}")
            lines.append(f"# TYPE {name} {head.kind}")
            for m in family:
                lines.extend(m._expose())
        return "\n".join(lines) + "\n"

    def snapshot(self) -> Dict[str, Any]:
        """JSON view of the same state the text format exposes, plus
        identity — what ``obs/fleet.py`` and ``obs_report`` consume.
        Unlabeled metrics keep their bare name as the key; labeled
        series are keyed ``name{label="value"}``."""
        self.collect()
        doc: Dict[str, Any] = {"time": time.time(),
                               **replica_identity(),
                               "collect_errors": self.collect_errors}
        doc["metrics"] = {m.name + _fmt_labels(m.labels): m._sample()
                          for m in self._all_metrics()}
        return doc

    def dump(self, path: str) -> str:
        """Write the JSON snapshot (``metrics_registry.json`` in a run
        dir — the file obs_report's registry section reads)."""
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(self.snapshot(), f)
        os.replace(tmp, path)
        return path


# ----------------------------------------------------------------- toggles
def enable() -> MetricsRegistry:
    """Install (or return) the process-wide registry. Idempotent, like
    ``spans.enable()`` — layered callers share one scrape surface."""
    global _REGISTRY
    if _REGISTRY is None:
        _REGISTRY = MetricsRegistry()
    return _REGISTRY


def disable() -> Optional[MetricsRegistry]:
    """Uninstall; returns the registry (its state stays readable)."""
    global _REGISTRY
    reg, _REGISTRY = _REGISTRY, None
    return reg


def get_registry() -> Optional[MetricsRegistry]:
    return _REGISTRY


def enabled() -> bool:
    return _REGISTRY is not None


# ------------------------------------------------------- hot-path helpers
def inc(name: str, n: float = 1.0,
        labels: Optional[Dict[str, str]] = None) -> None:
    """Counter bump; a no-op costing one ``is None`` check when the
    registry is disabled (hot-path safe by the spans discipline)."""
    reg = _REGISTRY
    if reg is None:
        return
    reg.counter(name, labels=labels).inc(n)


def set_gauge(name: str, value: float,
              labels: Optional[Dict[str, str]] = None) -> None:
    reg = _REGISTRY
    if reg is None:
        return
    reg.gauge(name, labels=labels).set(value)


def observe(name: str, value: float,
            buckets: Optional[Sequence[float]] = None,
            labels: Optional[Dict[str, str]] = None) -> None:
    reg = _REGISTRY
    if reg is None:
        return
    reg.histogram(name, buckets=buckets, labels=labels).observe(value)


# --------------------------------------------------------- endpoint files
def write_endpoint(url: str, role: str,
                   path: Optional[str] = None,
                   extra: Optional[Dict[str, Any]] = None
                   ) -> Optional[str]:
    """Advertise this replica's scrape endpoint. The supervisor exports
    ``DLTPU_ENDPOINT_FILE`` per replica; the serving CLI / Trainer stats
    server write {url, role, pid, identity} there (tmp + atomic replace)
    and ``fleet.discover_endpoints`` reads the set back. Returns the
    path written, or None when unadvertised."""
    path = path or os.environ.get(ENDPOINT_FILE_VAR)
    if not path:
        return None
    doc: Dict[str, Any] = {"url": url, "role": role, "pid": os.getpid(),
                           "time": time.time(), **replica_identity()}
    if extra:
        doc.update(extra)
    d = os.path.dirname(os.path.abspath(path))
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        os.makedirs(d, exist_ok=True)
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
    except OSError:
        return None                    # advertising is best-effort
    return path


def read_endpoint(path: str) -> Optional[Dict[str, Any]]:
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    return doc if isinstance(doc, dict) and doc.get("url") else None


def _thread_registry():
    """The obs.threads spawn registry, resolvable even when this module
    was loaded standalone by file path (``tools/obs_report.py --check``):
    load the adjacent ``threads.py`` under its canonical name so the
    process still has exactly one registry."""
    import sys
    mod = sys.modules.get("deeplearning_tpu.obs.threads")
    if mod is None:
        import importlib.util
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "threads.py")
        spec = importlib.util.spec_from_file_location(
            "deeplearning_tpu.obs.threads", path)
        mod = importlib.util.module_from_spec(spec)
        sys.modules[spec.name] = mod
        spec.loader.exec_module(mod)
    return mod


# ------------------------------------------------------------ stats server
class MetricsServer:
    """Opt-in stdlib scrape server: ``/metrics`` (text format),
    ``/metrics.json`` (snapshot), ``/healthz`` (delegates to
    ``healthz_fn() -> (code, payload)`` — the Trainer backs it with the
    elastic heartbeat so train replicas answer the same probe serve
    replicas do). Binds loopback; port 0 picks an ephemeral port."""

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 port: int = 0, host: str = "127.0.0.1",
                 healthz_fn: Optional[
                     Callable[[], Tuple[int, Dict[str, Any]]]] = None):
        self.registry = registry
        self.host = host
        self._requested_port = int(port)
        self.healthz_fn = healthz_fn
        self.port: Optional[int] = None
        self.url: Optional[str] = None
        self._server = None
        self._thread: Optional[threading.Thread] = None

    def _handler_class(self):
        from http.server import BaseHTTPRequestHandler
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):   # quiet: the registry is the log
                pass

            def _send(self, code: int, body: bytes, ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                reg = outer.registry or _REGISTRY
                route = self.path.split("?", 1)[0].rstrip("/")
                if route == "/metrics":
                    if reg is None:
                        return self._send(503, b"registry disabled\n",
                                          "text/plain")
                    return self._send(
                        200, reg.prometheus_text().encode(),
                        "text/plain; version=0.0.4; charset=utf-8")
                if route == "/metrics.json":
                    if reg is None:
                        return self._send(
                            503, b'{"error": "registry disabled"}',
                            "application/json")
                    return self._send(
                        200, json.dumps(reg.snapshot()).encode(),
                        "application/json")
                if route == "/healthz":
                    if outer.healthz_fn is not None:
                        code, payload = outer.healthz_fn()
                    else:
                        code, payload = 200, {"status": "alive",
                                              **replica_identity()}
                    return self._send(code, json.dumps(payload).encode(),
                                      "application/json")
                return self._send(404, b'{"error": "GET /metrics, '
                                  b'/metrics.json or /healthz"}',
                                  "application/json")
        return Handler

    def start(self) -> "MetricsServer":
        if self._server is not None:
            return self
        from http.server import ThreadingHTTPServer
        self._server = ThreadingHTTPServer(
            (self.host, self._requested_port), self._handler_class())
        self.port = self._server.server_port
        self.url = f"http://{self.host}:{self.port}"
        self._thread = _thread_registry().spawn(
            self._server.serve_forever, name="obs-metrics-http",
            daemon=True)
        return self

    def stop(self, timeout: float = 2.0) -> None:
        if self._server is None:
            return
        self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout)
        self._server = None
        self._thread = None

    def __enter__(self) -> "MetricsServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
