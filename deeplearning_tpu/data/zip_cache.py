"""Zip-backed image source + shared-memory array cache.

Surface of the swin loader's zip-cache path (classification/
swin_transformer/dataLoader/zipreader.py:23 + build.py CACHE_MODE: read
images straight out of a .zip so one file serves many workers) and
YOLOX's RAM cache (numpy memmap shared across forked workers,
yolox/core/launch.py:72-80). TPU-era framing: data loading is host-side;
these sources slot into MapSource/DataLoader.
"""

from __future__ import annotations

import io
import os
import threading
import zipfile
from typing import Dict, Optional, Sequence, Tuple

import numpy as np


class ZipImageSource:
    """Lazy image reads from a zip archive; one handle per thread (zip
    handles are not thread-safe — zipreader's is_zip_path/read pattern)."""

    def __init__(self, zip_path: str, extensions=(".png", ".jpg", ".jpeg",
                                                  ".bmp", ".npy")):
        self.zip_path = zip_path
        self._local = threading.local()
        with zipfile.ZipFile(zip_path) as z:
            self.names = sorted(
                n for n in z.namelist()
                if n.lower().endswith(extensions) and not n.endswith("/"))

    def _handle(self) -> zipfile.ZipFile:
        if not hasattr(self._local, "z"):
            self._local.z = zipfile.ZipFile(self.zip_path)
        return self._local.z

    def __len__(self) -> int:
        return len(self.names)

    def read_bytes(self, idx: int) -> bytes:
        return self._handle().read(self.names[idx])

    def read_image(self, idx: int) -> np.ndarray:
        name = self.names[idx]
        raw = self.read_bytes(idx)
        if name.lower().endswith(".npy"):
            return np.load(io.BytesIO(raw))
        try:
            from PIL import Image
            return np.asarray(Image.open(io.BytesIO(raw)).convert("RGB"))
        except ImportError:
            import cv2
            arr = cv2.imdecode(np.frombuffer(raw, np.uint8),
                               cv2.IMREAD_COLOR)
            return arr[:, :, ::-1]


class MemmapCache:
    """Decode-once image cache in a disk-backed memmap shared across
    processes (the YOLOX cache_mode analog)."""

    def __init__(self, cache_path: str, shape: Tuple[int, ...],
                 dtype=np.uint8):
        self.cache_path = cache_path
        self.shape = shape
        exists = os.path.exists(cache_path)
        self.arr = np.memmap(cache_path, dtype=dtype,
                             mode="r+" if exists else "w+", shape=shape)
        flag_path = cache_path + ".filled"
        self._filled = np.memmap(flag_path, dtype=np.uint8,
                                 mode="r+" if os.path.exists(flag_path)
                                 else "w+", shape=(shape[0],))

    def get(self, idx: int, produce) -> np.ndarray:
        if not self._filled[idx]:
            self.arr[idx] = produce(idx)
            self._filled[idx] = 1
        return np.asarray(self.arr[idx])

    @property
    def fill_fraction(self) -> float:
        return float(np.mean(self._filled))
