"""Real-image input pipeline builder.

Capability surface of classification/swin_transformer/dataLoader/build.py
(:38 build_loader — ImageFolder/zip dataset + DistributedSampler + torch
DataLoader(num_workers, pin_memory) + mixup) and its ~16 per-project
copies (classification/mnist/dataLoader/dataSet.py etc.), reshaped for
TPU hosts:

- each host scans the folder once and loads ONLY its slice of every
  global batch (DataLoader host sharding — the DistributedSampler
  successor);
- JPEG decode + augmentation run on a thread pool (``num_workers``)
  overlapped with step compute via ``prefetch_to_device`` — the
  pin_memory/CUDA-stream prefetch analog without streams;
- batches are fixed-shape so the jitted step never retraces.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, Optional, Tuple

import jax
import numpy as np

from .datasets import folder_source, read_split_data, write_class_indices
from .device_prefetch import DevicePrefetcher
from .loader import DataLoader, prefetch_to_device  # noqa: F401 - re-export
from .transforms import eval_image_transform, get_train_transform


@dataclasses.dataclass(frozen=True)
class LoaderConfig:
    """Knobs of build_loader (dataLoader/build.py:38) that survive the
    torch→TPU translation."""
    global_batch: int = 128
    image_size: int = 224
    val_rate: float = 0.2
    num_workers: int = 8
    lookahead: int = 4
    seed: int = 0
    prefetch: int = 2
    augment: str = "imagenet"        # imagenet | light | none


def build_classification_loaders(
        root: str, cfg: LoaderConfig = LoaderConfig(), *,
        mesh=None, class_indices_path: Optional[str] = None,
        train_transform: Optional[Callable] = None,
        eval_transform: Optional[Callable] = None,
) -> Tuple[DataLoader, DataLoader, Dict[str, int]]:
    """(train_loader, val_loader, class_to_idx) from an ImageFolder root.

    Decode/augment happen per sample inside folder_source's fetch, so the
    DataLoader's worker pool parallelizes the full decode+augment path.
    """
    split = read_split_data(root, val_rate=cfg.val_rate, seed=cfg.seed)
    if class_indices_path:
        write_class_indices(split["class_to_idx"], class_indices_path)
    size = (cfg.image_size, cfg.image_size)
    tt = train_transform or get_train_transform(cfg.augment, size,
                                                seed=cfg.seed)
    et = eval_transform or eval_image_transform(size)
    train = DataLoader(
        folder_source(split["train_paths"], split["train_labels"], tt),
        cfg.global_batch, shuffle=True, seed=cfg.seed, mesh=mesh,
        num_workers=cfg.num_workers, lookahead=cfg.lookahead)
    # clamp the val batch so a split smaller than global_batch still
    # yields batches (drop-last would otherwise drop the whole set);
    # keep it divisible by process count, repeating tail paths when the
    # split is smaller than the process count (multi-host degenerate
    # case — a duplicated val image beats an empty evaluation)
    n_proc = jax.process_count()
    val_paths = list(split["val_paths"])
    val_labels = list(split["val_labels"])
    orig_len = len(val_paths)
    while val_paths and len(val_paths) % n_proc:
        # round-robin distinct tail entries so no single image dominates
        val_paths.append(val_paths[len(val_paths) % orig_len])
        val_labels.append(val_labels[len(val_labels) % orig_len])
    val_batch = min(cfg.global_batch,
                    max(len(val_paths) // n_proc, 1) * n_proc)
    val = DataLoader(
        folder_source(val_paths, np.asarray(val_labels), et),
        val_batch, shuffle=False, seed=cfg.seed, mesh=mesh,
        num_workers=cfg.num_workers, lookahead=cfg.lookahead)
    return train, val, split["class_to_idx"]


def device_iterator(loader: DataLoader, cfg: LoaderConfig, sharding=None):
    """Loader wrapped in a threaded host→HBM prefetch stage.

    Returns a :class:`DevicePrefetcher` (full loader protocol —
    ``__len__``/``set_epoch``/``last_data_wait`` — so the Trainer can use
    it directly), which takes over the loader's own device-put: each
    batch is transferred exactly ONCE, on the prefetch worker thread.
    The old shape of this function double-transferred (loader
    ``_finalize`` device-put, then ``prefetch_to_device`` device-put
    again)."""
    return DevicePrefetcher(loader, depth=cfg.prefetch, sharding=sharding)


def measure_throughput(loader: DataLoader, n_batches: int = 30,
                       warmup: int = 2) -> float:
    """Host-pipeline images/sec (decode+augment+batch, no device work),
    cycling epochs if the loader is shorter than warmup+n_batches."""
    import itertools
    import time

    def cycle():
        while True:
            got_any = False
            for item in iter(loader):
                got_any = True
                yield item
            if not got_any:
                raise ValueError(
                    "loader yielded zero batches (fewer images than one "
                    "global batch under drop-last?) — cannot measure "
                    "throughput")

    it = cycle()
    n = 0
    for _ in range(warmup):
        next(it)
    t0 = time.perf_counter()
    for batch in itertools.islice(it, n_batches):
        n += len(next(iter(batch.values())))
    dt = time.perf_counter() - t0
    return n / dt
