"""Input pipeline: per-host sharded batching with device prefetch.

Replaces the reference's Dataset/DataLoader/DistributedSampler stack
(SURVEY.md L3; others/train_with_DDP/train.py:140-141, YOLOX
data_prefetcher.py:8 CUDA-stream prefetch). TPU-first shape: every host
loads ONLY its slice of the global batch (the DistributedSampler
successor), batches are fixed-shape (drop_last semantics so jit never
retraces), and ``prefetch_to_device`` overlaps host→HBM transfer with
compute — the DataPrefetcher analog without CUDA streams.
"""

from __future__ import annotations

import collections
import itertools
from typing import Any, Callable, Dict, Iterator, Optional, Sequence

import jax
import numpy as np

from ..elastic import faults
from ..parallel.sharding import batch_spec, make_global_array
from .quarantine import PoisonedData, QuarantineLog, quarantinable
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


class ArraySource:
    """In-memory dataset of parallel arrays (images, labels, ...)."""

    def __init__(self, **arrays: np.ndarray):
        sizes = {k: len(v) for k, v in arrays.items()}
        if len(set(sizes.values())) != 1:
            raise ValueError(f"Array length mismatch: {sizes}")
        self.arrays = arrays
        self.size = next(iter(sizes.values()))

    def __len__(self) -> int:
        return self.size

    def __getitem__(self, idx) -> Dict[str, np.ndarray]:
        return {k: v[idx] for k, v in self.arrays.items()}


class MapSource:
    """Lazy dataset: indices → sample dict via ``fetch`` (the Dataset
    __getitem__ analog; per-sample decode/augment lives in fetch)."""

    def __init__(self, size: int, fetch: Callable[[int], Dict[str, np.ndarray]]):
        self.size = size
        self.fetch = fetch

    def __len__(self) -> int:
        return self.size

    def __getitem__(self, idx):
        if isinstance(idx, (int, np.integer)):
            return self.fetch(int(idx))
        samples = [self.fetch(int(i)) for i in idx]
        return {k: np.stack([s[k] for s in samples]) for k in samples[0]}


def epoch_indices(size: int, *, shuffle: bool, seed: int, epoch: int,
                  drop_last_to: Optional[int] = None) -> np.ndarray:
    """Deterministic per-epoch permutation — sampler.set_epoch(epoch)
    becomes seeding by (seed, epoch)."""
    idx = np.arange(size)
    if shuffle:
        idx = np.random.default_rng((seed, epoch)).permutation(size)
    if drop_last_to:
        idx = idx[: (size // drop_last_to) * drop_last_to]
    return idx


class DataLoader:
    """Fixed-shape global batches, host-sharded, optionally device-put.

    - ``global_batch`` is the batch across ALL hosts/devices; each host
      materializes only its ``global_batch / process_count`` slice.
    - with a mesh, batches are assembled into global jax.Arrays sharded
      over the data axes (multi-host DP); without, plain numpy dicts.
    """

    def __init__(self, source, global_batch: int, *, shuffle: bool = True,
                 seed: int = 0, mesh: Optional[Mesh] = None,
                 transform: Optional[Callable[[Dict], Dict]] = None,
                 infinite: bool = False, num_workers: int = 0,
                 lookahead: int = 4, quarantine=None):
        self.source = source
        self.global_batch = global_batch
        self.shuffle = shuffle
        self.seed = seed
        self.mesh = mesh
        self.transform = transform
        self.infinite = infinite
        self.epoch = 0
        self.num_workers = num_workers
        self.lookahead = max(lookahead, 1)
        self._pool = None
        # bad-sample quarantine (README "Self-healing policy"): a
        # QuarantineLog (or a manifest path to build one) switches fetch
        # to per-sample so a decode failure substitutes + logs instead
        # of killing the epoch; None keeps the fast vectorized path.
        self.quarantine: Optional[QuarantineLog] = (
            QuarantineLog(quarantine) if isinstance(quarantine, str)
            else quarantine)
        self._fetch_counter = itertools.count(1)  # bad_sample fault site
        self._last_good: Optional[Dict[str, Any]] = None
        # divergence rollback support: a reseed(salt) perturbs the
        # shuffle seed so the replayed window draws a different
        # permutation — the "skip past the offending data" half of the
        # Trainer's rollback-and-skip.
        self._seed_salt = 0
        # when False, batches are yielded as HOST numpy dicts even with a
        # mesh — a wrapping DevicePrefetcher flips this to take over the
        # host→HBM transfer on its worker thread (exactly one transfer
        # per batch, off the consumer's critical path)
        self.device_transfer = True
        # starvation telemetry (parallel path only): time the consumer
        # actually blocked waiting for decode futures of the LAST yielded
        # batch, and the running total for the epoch. None on the serial
        # path — consumers (Trainer data_time) fall back to wall-clock.
        self.last_data_wait: Optional[float] = None
        self.data_wait_total = 0.0
        n_proc = jax.process_count()
        if global_batch % n_proc:
            raise ValueError(f"global_batch {global_batch} not divisible by "
                             f"process count {n_proc}")
        self.host_batch = global_batch // n_proc

    def __len__(self) -> int:
        return len(self.source) // self.global_batch

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch

    def reseed(self, salt: int) -> None:
        """Perturb the effective shuffle seed (idempotent per ``salt``).
        After a divergence rollback the Trainer replays from its anchor;
        with the SAME permutation it would march straight back into the
        offending batch — a new salt draws a fresh permutation, which is
        the skip."""
        self._seed_salt = int(salt)

    def _effective_seed(self) -> int:
        return self.seed + self._seed_salt * 1_000_003

    def _local_indices(self, epoch: int) -> Iterator[np.ndarray]:
        idx = epoch_indices(len(self.source), shuffle=self.shuffle,
                            seed=self._effective_seed(), epoch=epoch,
                            drop_last_to=self.global_batch)
        # contiguous host slice of each global batch
        p = jax.process_index()
        for start in range(0, len(idx), self.global_batch):
            gbatch = idx[start:start + self.global_batch]
            yield gbatch[p * self.host_batch:(p + 1) * self.host_batch]

    def _finalize(self, batch: Dict[str, Any]) -> Dict[str, Any]:
        if self.transform:
            batch = self.transform(batch)
        if self.mesh is not None and self.device_transfer:
            batch = {k: make_global_array(np.asarray(v), self.mesh)
                     for k, v in batch.items()}
        return batch

    def element_spec(self) -> Optional[Dict[str, jax.ShapeDtypeStruct]]:
        """Abstract (shape, dtype, sharding) of one yielded batch — the
        AOT-warmup surface: ``Trainer.precompile()`` lowers the jitted
        step against these without materializing any data. Derived from
        ONE source sample pushed through ``transform``, so it costs a
        single decode, not a batch."""
        try:
            first = int(next(iter(self._local_indices(self.epoch)))[0])
        except StopIteration:       # fewer samples than one global batch
            return None
        sample = self.source[np.asarray([first])]
        if self.transform:
            sample = self.transform(sample)
        # with a mesh the consumer sees GLOBAL sharded arrays (assembled
        # here or by a wrapping DevicePrefetcher); without, host-local
        # numpy batches of host_batch rows
        sharding = (NamedSharding(self.mesh, batch_spec())
                    if self.mesh is not None else None)
        lead = self.global_batch if self.mesh is not None else \
            self.host_batch

        def spec(v):
            v = np.asarray(v)
            shape = (lead, *v.shape[1:])
            if sharding is not None:
                return jax.ShapeDtypeStruct(shape, v.dtype,
                                            sharding=sharding)
            return jax.ShapeDtypeStruct(shape, v.dtype)
        return {k: spec(v) for k, v in sample.items()}

    # ------------------------------------------------ per-sample fetch
    def _fetch_one(self, i: int) -> Dict[str, np.ndarray]:
        """One sample through the fault harness (``bad_sample@step:N``
        counts FETCHES); exceptions propagate to the caller — the
        quarantine decision lives on the consumer thread."""
        ordinal = next(self._fetch_counter)
        if faults.consume("bad_sample", "step", step=ordinal):
            raise faults.InjectedBadSample(
                f"injected bad sample at fetch {ordinal} (index {i})")
        return self.source[int(i)]

    def _quarantine_or_raise(self, i: int, exc: BaseException) -> None:
        """Quarantine a per-sample failure, or re-raise it on the
        consumer thread with its original traceback when it is not a
        sample's fault (interrupts, escalation, OOM)."""
        if self.quarantine is None or not quarantinable(exc):
            raise exc
        self.quarantine.record(int(i), exc, step=self.epoch)

    def _assemble(self, local, samples) -> Dict[str, Any]:
        """Stack per-sample dicts into one fixed-shape batch,
        substituting quarantined slots (None) with good samples so jit
        never sees a short batch. A batch with NO survivors is a hard
        error — there is nothing honest to substitute."""
        good = [s for s in samples if s is not None]
        if good:
            self._last_good = good[-1]
            if self.quarantine is not None:
                self.quarantine.note_ok(len(good))
        elif self._last_good is not None:
            good = [self._last_good]
        else:
            raise PoisonedData(
                f"every sample in batch {list(map(int, local))} failed "
                "with none seen before it — nothing to substitute")
        samples = [s if s is not None else good[j % len(good)]
                   for j, s in enumerate(samples)]
        return {k: np.stack([s[k] for s in samples]) for k in samples[0]}

    def _epoch_iter(self, epoch: int) -> Iterator[Dict[str, Any]]:
        if self.num_workers:
            yield from self._epoch_iter_parallel(epoch)
            return
        for local in self._local_indices(epoch):
            if self.quarantine is None:
                yield self._finalize(self.source[local])
                continue
            samples = []
            for i in local:
                try:
                    samples.append(self._fetch_one(int(i)))
                except BaseException as exc:  # noqa: BLE001
                    self._quarantine_or_raise(int(i), exc)
                    samples.append(None)
            yield self._finalize(self._assemble(local, samples))

    def _epoch_iter_parallel(self, epoch: int) -> Iterator[Dict[str, Any]]:
        """num_workers>0: decode samples on a thread pool (the DataLoader
        num_workers analog — PIL/cv2 JPEG decode releases the GIL), keeping
        ``lookahead`` batches of per-sample futures in flight so decode
        overlaps step compute. Worker exceptions surface HERE, on the
        consumer thread with their original tracebacks (``f.result()``
        re-raises) — quarantinable ones substitute + log, everything
        else kills the epoch loudly, never silently."""
        if self._pool is None:
            import concurrent.futures
            self._pool = concurrent.futures.ThreadPoolExecutor(
                max_workers=self.num_workers)
        pending: collections.deque = collections.deque()
        it = self._local_indices(epoch)
        self.data_wait_total = 0.0
        import time as _time

        def submit(local):
            pending.append((local, [self._pool.submit(self._fetch_one, i)
                                    for i in local]))
        try:
            for local in itertools.islice(it, self.lookahead):
                submit(local)
            while pending:
                local, futs = pending.popleft()
                # queue-empty wait: blocking on not-yet-done futures IS
                # the starvation signal (done futures return instantly),
                # so this isolates decode lag from batch assembly below
                t0 = _time.perf_counter()
                samples = []
                for i, f in zip(local, futs):
                    try:
                        samples.append(f.result())
                    except BaseException as exc:  # noqa: BLE001
                        self._quarantine_or_raise(int(i), exc)
                        samples.append(None)
                self.last_data_wait = _time.perf_counter() - t0
                self.data_wait_total += self.last_data_wait
                yield self._finalize(self._assemble(local, samples))
                for local in itertools.islice(it, 1):
                    submit(local)
        finally:
            for _, futs in pending:
                for f in futs:
                    f.cancel()

    def __iter__(self) -> Iterator[Dict[str, Any]]:
        if not self.infinite:
            yield from self._epoch_iter(self.epoch)
            return
        for epoch in itertools.count(self.epoch):
            yield from self._epoch_iter(epoch)


def prefetch_to_device(iterator: Iterator, size: int = 2,
                       sharding: Optional[NamedSharding] = None,
                       mesh: Optional[Mesh] = None) -> Iterator:
    """Overlap host→device copies with compute (DataPrefetcher analog;
    flax.jax_utils.prefetch_to_device surface, mesh-sharding aware).

    Multi-host correct: with a ``mesh``, numpy leaves are assembled into
    GLOBAL sharded arrays via ``make_global_array`` (a bare per-leaf
    ``jax.device_put`` would build process-local arrays whose shapes
    disagree with the jitted step's global batch spec). Leaves that are
    already ``jax.Array`` pass through untouched, so an upstream loader
    that device-puts internally is never double-transferred.

    Prefer :class:`~deeplearning_tpu.data.device_prefetch.DevicePrefetcher`
    for the Trainer path — it keeps the loader protocol (``set_epoch``,
    ``__len__``) and runs the transfer on a real background thread; this
    generator remains the minimal flax-style surface.
    """
    queue: collections.deque = collections.deque()

    def place(x):
        if isinstance(x, jax.Array):
            return x                       # already on device — no copy
        if mesh is not None:
            return make_global_array(np.asarray(x), mesh)
        if sharding is not None:
            return jax.device_put(x, sharding)
        return jax.device_put(x)

    def put(batch):
        queue.append(jax.tree.map(place, batch))

    it = iter(iterator)
    for b in itertools.islice(it, size):
        put(b)
    while queue:
        yield queue.popleft()
        for b in itertools.islice(it, 1):
            put(b)
