"""Annotation format converters: VOC ↔ COCO ↔ YOLO.

Surface of others/label_convert (voc2coco.py, coco2voc.py, yolo2coco.py,
coco2yolo.py, voc2yolo.py, yolo2voc.py + show_img_by_* viewers). Formats:

- VOC:  per-image XML with absolute xyxy boxes + class names.
- COCO: one JSON with images/annotations/categories, boxes xywh absolute.
- YOLO: per-image .txt rows ``cls cx cy w h`` normalized to [0, 1].

Converters operate on in-memory dicts (parse/serialize helpers included),
so they also serve as the dataset-loading path for detection training.
"""

from __future__ import annotations

import json
import os
import xml.etree.ElementTree as ET
from typing import Dict, List, Sequence, Tuple

import numpy as np


# ------------------------------------------------------------- VOC (XML)
def parse_voc_xml(path: str) -> Dict:
    root = ET.parse(path).getroot()
    size = root.find("size")
    rec = {
        "filename": root.findtext("filename", ""),
        "width": int(size.findtext("width")),
        "height": int(size.findtext("height")),
        "boxes": [], "names": [], "difficult": [],
    }
    for obj in root.findall("object"):
        bb = obj.find("bndbox")
        rec["boxes"].append([float(bb.findtext(k)) for k in
                             ("xmin", "ymin", "xmax", "ymax")])
        rec["names"].append(obj.findtext("name"))
        rec["difficult"].append(int(obj.findtext("difficult", "0")))
    rec["boxes"] = np.asarray(rec["boxes"], np.float32).reshape(-1, 4)
    rec["difficult"] = np.asarray(rec["difficult"], bool)
    return rec


def write_voc_xml(rec: Dict, path: str) -> None:
    root = ET.Element("annotation")
    ET.SubElement(root, "filename").text = rec.get("filename", "")
    size = ET.SubElement(root, "size")
    ET.SubElement(size, "width").text = str(rec["width"])
    ET.SubElement(size, "height").text = str(rec["height"])
    ET.SubElement(size, "depth").text = "3"
    difficult = rec.get("difficult")
    if difficult is None:
        difficult = np.zeros(len(rec["boxes"]), bool)
    for box, name, diff in zip(rec["boxes"], rec["names"], difficult):
        obj = ET.SubElement(root, "object")
        ET.SubElement(obj, "name").text = str(name)
        ET.SubElement(obj, "difficult").text = str(int(diff))
        bb = ET.SubElement(obj, "bndbox")
        for k, v in zip(("xmin", "ymin", "xmax", "ymax"), box):
            ET.SubElement(bb, k).text = str(float(v))
    ET.ElementTree(root).write(path)


# ------------------------------------------------------------ COCO (JSON)
def records_to_coco(records: Sequence[Dict], class_names: Sequence[str]
                    ) -> Dict:
    name_to_id = {n: i + 1 for i, n in enumerate(class_names)}  # 1-based
    coco = {"images": [], "annotations": [],
            "categories": [{"id": i + 1, "name": n}
                           for i, n in enumerate(class_names)]}
    ann_id = 1
    for img_id, rec in enumerate(records, start=1):
        coco["images"].append({
            "id": img_id, "file_name": rec.get("filename", f"{img_id}.jpg"),
            "width": rec["width"], "height": rec["height"]})
        for box, name in zip(rec["boxes"], rec["names"]):
            x1, y1, x2, y2 = (float(v) for v in box)
            coco["annotations"].append({
                "id": ann_id, "image_id": img_id,
                "category_id": name_to_id[name],
                "bbox": [x1, y1, x2 - x1, y2 - y1],
                "area": (x2 - x1) * (y2 - y1), "iscrowd": 0})
            ann_id += 1
    return coco


def coco_to_records(coco: Dict) -> List[Dict]:
    cats = {c["id"]: c["name"] for c in coco["categories"]}
    by_img = {img["id"]: {"filename": img.get("file_name", ""),
                          "width": img["width"], "height": img["height"],
                          "boxes": [], "names": [], "difficult": []}
              for img in coco["images"]}
    for ann in coco["annotations"]:
        rec = by_img[ann["image_id"]]
        x, y, w, h = ann["bbox"]
        rec["boxes"].append([x, y, x + w, y + h])
        rec["names"].append(cats[ann["category_id"]])
        rec["difficult"].append(bool(ann.get("iscrowd", 0)))
    out = []
    for img in coco["images"]:              # preserve image order
        rec = by_img[img["id"]]
        rec["boxes"] = np.asarray(rec["boxes"], np.float32).reshape(-1, 4)
        rec["difficult"] = np.asarray(rec["difficult"], bool)
        out.append(rec)
    return out


# ------------------------------------------------------------ YOLO (txt)
def record_to_yolo(rec: Dict, class_names: Sequence[str]) -> str:
    """One image's boxes → 'cls cx cy w h' normalized lines."""
    name_to_id = {n: i for i, n in enumerate(class_names)}   # 0-based
    lines = []
    w, h = rec["width"], rec["height"]
    for box, name in zip(rec["boxes"], rec["names"]):
        x1, y1, x2, y2 = (float(v) for v in box)
        lines.append(f"{name_to_id[name]} {(x1 + x2) / 2 / w:.6f} "
                     f"{(y1 + y2) / 2 / h:.6f} {(x2 - x1) / w:.6f} "
                     f"{(y2 - y1) / h:.6f}")
    return "\n".join(lines)


def yolo_to_record(text: str, width: int, height: int,
                   class_names: Sequence[str]) -> Dict:
    boxes, names = [], []
    for line in text.strip().splitlines():
        if not line.strip():
            continue
        cls, cx, cy, w, h = line.split()
        cx, cy, w, h = (float(v) for v in (cx, cy, w, h))
        boxes.append([(cx - w / 2) * width, (cy - h / 2) * height,
                      (cx + w / 2) * width, (cy + h / 2) * height])
        names.append(class_names[int(cls)])
    return {"width": width, "height": height,
            "boxes": np.asarray(boxes, np.float32).reshape(-1, 4),
            "names": names,
            "difficult": np.zeros(len(names), bool)}


def records_to_arrays(records: Sequence[Dict], class_names: Sequence[str],
                      max_boxes: int = 64) -> Dict[str, np.ndarray]:
    """Padded fixed-shape training arrays {boxes, labels, valid} — the
    bridge from any annotation format to the jitted detectors."""
    name_to_id = {n: i for i, n in enumerate(class_names)}
    n = len(records)
    boxes = np.zeros((n, max_boxes, 4), np.float32)
    labels = np.zeros((n, max_boxes), np.int64)
    valid = np.zeros((n, max_boxes), bool)
    for i, rec in enumerate(records):
        take = min(len(rec["boxes"]), max_boxes)
        boxes[i, :take] = rec["boxes"][:take]
        labels[i, :take] = [name_to_id[x] for x in rec["names"][:take]]
        valid[i, :take] = True
    return {"boxes": boxes, "labels": labels, "valid": valid}
