"""Dataset discovery: class-folder scanning + train/val splitting.

Surface of the archetype-A loader stack (classification/mnist/dataLoader/
dataSet.py read_split_data and its ~16 copies): scan a root directory of
per-class subfolders, build (paths, labels), split train/val by ratio
with a fixed seed, and expose a MapSource that decodes+transforms on
access. Also the class_indices.json writer the predict CLIs consume.
"""

from __future__ import annotations

import json
import os
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .loader import MapSource

IMG_EXTS = (".jpg", ".jpeg", ".png", ".bmp", ".webp", ".npy")


def read_split_data(root: str, val_rate: float = 0.2, seed: int = 0
                    ) -> Dict[str, object]:
    """Scan root/<class>/* images → shuffled train/val path+label splits
    and the class-index mapping (read_split_data surface)."""
    classes = sorted(d for d in os.listdir(root)
                     if os.path.isdir(os.path.join(root, d)))
    if not classes:
        raise FileNotFoundError(f"no class subdirectories under {root}")
    class_to_idx = {c: i for i, c in enumerate(classes)}
    paths: List[str] = []
    labels: List[int] = []
    for c in classes:
        cdir = os.path.join(root, c)
        for fname in sorted(os.listdir(cdir)):
            if fname.lower().endswith(IMG_EXTS):
                paths.append(os.path.join(cdir, fname))
                labels.append(class_to_idx[c])
    rng = np.random.default_rng(seed)
    order = rng.permutation(len(paths))
    n_val = int(len(paths) * val_rate)
    val_idx = set(order[:n_val].tolist())
    tr_p, tr_l, va_p, va_l = [], [], [], []
    for i, (p, l) in enumerate(zip(paths, labels)):
        if i in val_idx:
            va_p.append(p)
            va_l.append(l)
        else:
            tr_p.append(p)
            tr_l.append(l)
    return {"train_paths": tr_p, "train_labels": np.asarray(tr_l),
            "val_paths": va_p, "val_labels": np.asarray(va_l),
            "class_to_idx": class_to_idx}


def write_class_indices(class_to_idx: Dict[str, int], path: str) -> None:
    """class_indices.json (index -> name) for predict CLIs."""
    inv = {str(v): k for k, v in class_to_idx.items()}
    parent = os.path.dirname(path)
    if parent:
        os.makedirs(parent, exist_ok=True)
    with open(path, "w") as f:
        json.dump(inv, f, indent=2)


def load_image(path: str) -> np.ndarray:
    if path.lower().endswith(".npy"):
        return np.load(path)
    if path.lower().endswith((".jpg", ".jpeg")):
        # native libjpeg fast path (native/imagedec.cpp); decodes off the
        # GIL so loader threads overlap. Check availability BEFORE the
        # read so the fallback doesn't pay double file I/O.
        from .native_decode import available, decode_jpeg
        if available():
            with open(path, "rb") as f:
                data = f.read()
            img = decode_jpeg(data)
            if img is not None:
                return img.astype(np.float32)
    from PIL import Image
    return np.asarray(Image.open(path).convert("RGB"), np.float32)


def folder_source(paths: Sequence[str], labels: np.ndarray,
                  transform: Optional[Callable] = None) -> MapSource:
    """MapSource decoding images lazily from disk (the Dataset analog)."""
    labels = np.asarray(labels)

    def fetch(i: int) -> Dict[str, np.ndarray]:
        img = load_image(paths[i])
        if transform is not None:
            img = transform(img)
        return {"image": np.asarray(img, np.float32),
                "label": np.asarray(labels[i], np.int32)}

    return MapSource(len(paths), fetch)
