"""Mixup / CutMix batch augmentation + mosaic for detection.

Surface of the timm-style mixup the B-harness uses (swin main.py:111-118
mixup_fn with label smoothing folded into soft targets) and YOLOX's
MosaicDetection (yolox/data/datasets/mosaicdetection.py:37: 4-image
mosaic + box-aware mixup). Mixup/cutmix are jittable (device-side, on the
global batch); mosaic is host numpy (it reshapes images before batching).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def one_hot_smooth(labels: jax.Array, num_classes: int,
                   smoothing: float = 0.0) -> jax.Array:
    off = smoothing / num_classes
    on = 1.0 - smoothing + off
    return jax.nn.one_hot(labels, num_classes) * (on - off) + off


def mixup_cutmix(batch: Dict[str, jax.Array], rng: jax.Array,
                 num_classes: int, mixup_alpha: float = 0.8,
                 cutmix_alpha: float = 1.0, smoothing: float = 0.1,
                 switch_prob: float = 0.5) -> Dict[str, jax.Array]:
    """Pair each sample with the reversed batch; mixup or cutmix chosen
    per batch. Returns batch with soft-target 'label'."""
    imgs = batch["image"]
    labels = batch["label"]
    k_lam, k_switch, k_box = jax.random.split(rng, 3)
    use_cutmix = jax.random.uniform(k_switch) < switch_prob
    alpha = jnp.where(use_cutmix, cutmix_alpha, mixup_alpha)
    lam = jax.random.beta(k_lam, alpha, alpha)

    flipped = imgs[::-1]
    b, h, w, c = imgs.shape
    # cutmix box with area ratio (1-lam)
    cut = jnp.sqrt(1.0 - lam)
    ch, cw = (h * cut).astype(jnp.int32), (w * cut).astype(jnp.int32)
    ky, kx = jax.random.split(k_box)
    cy = jax.random.randint(ky, (), 0, h)
    cx = jax.random.randint(kx, (), 0, w)
    y0 = jnp.clip(cy - ch // 2, 0, h)
    x0 = jnp.clip(cx - cw // 2, 0, w)
    y1 = jnp.clip(cy + ch // 2, 0, h)
    x1 = jnp.clip(cx + cw // 2, 0, w)
    rows = jnp.arange(h)[None, :, None, None]
    cols = jnp.arange(w)[None, None, :, None]
    in_box = ((rows >= y0) & (rows < y1) & (cols >= x0) & (cols < x1))
    lam_cutmix = 1.0 - ((y1 - y0) * (x1 - x0)) / (h * w)

    mixed_mixup = lam * imgs + (1 - lam) * flipped
    mixed_cutmix = jnp.where(in_box, flipped, imgs)
    out_imgs = jnp.where(use_cutmix, mixed_cutmix, mixed_mixup)
    lam_eff = jnp.where(use_cutmix, lam_cutmix, lam)

    t1 = one_hot_smooth(labels, num_classes, smoothing)
    t2 = one_hot_smooth(labels[::-1], num_classes, smoothing)
    soft = lam_eff * t1 + (1 - lam_eff) * t2
    return {**batch, "image": out_imgs.astype(imgs.dtype), "label": soft}


def mosaic4(images: Sequence[np.ndarray], boxes: Sequence[np.ndarray],
            labels: Sequence[np.ndarray], out_size: int,
            rng: np.random.Generator,
            max_boxes: int = 64,
            perspective: Optional[Dict] = None,
            fill: float = 114.0) -> Tuple[np.ndarray, np.ndarray,
                                          np.ndarray, np.ndarray]:
    """4-image mosaic (MosaicDetection surface): random center, each
    quadrant filled by one scaled image; boxes shifted+clipped, padded to
    ``max_boxes`` with a validity mask. Host-side numpy.

    ``perspective``: kwargs for :func:`random_perspective` — when given,
    the 2s canvas goes through the geometric augmentation with
    border=(-s//2, -s//2) exactly like yolov5's mosaic
    (utils/datasets.py:836), instead of the plain 2s→s downscale."""
    assert len(images) == 4
    s = out_size
    yc = int(rng.uniform(0.5 * s, 1.5 * s))
    xc = int(rng.uniform(0.5 * s, 1.5 * s))
    canvas = np.full((2 * s, 2 * s, images[0].shape[-1]), fill, np.float32)
    all_boxes, all_labels = [], []
    from .transforms import resize_bilinear
    for i, (img, bxs, lbs) in enumerate(zip(images, boxes, labels)):
        h0, w0 = img.shape[:2]
        scale = min(s / h0, s / w0) * rng.uniform(0.5, 1.5)
        nh, nw = max(int(h0 * scale), 1), max(int(w0 * scale), 1)
        img = resize_bilinear(img, (nh, nw))
        if i == 0:      # top-left quadrant, anchored at (yc, xc)
            y1a, x1a = max(yc - nh, 0), max(xc - nw, 0)
            y2a, x2a = yc, xc
        elif i == 1:    # top-right
            y1a, x1a = max(yc - nh, 0), xc
            y2a, x2a = yc, min(xc + nw, 2 * s)
        elif i == 2:    # bottom-left
            y1a, x1a = yc, max(xc - nw, 0)
            y2a, x2a = min(yc + nh, 2 * s), xc
        else:           # bottom-right
            y1a, x1a = yc, xc
            y2a, x2a = min(yc + nh, 2 * s), min(xc + nw, 2 * s)
        # matching source crop
        y1b = nh - (y2a - y1a) if i < 2 else 0
        x1b = nw - (x2a - x1a) if i in (0, 2) else 0
        canvas[y1a:y2a, x1a:x2a] = img[y1b:y1b + (y2a - y1a),
                                       x1b:x1b + (x2a - x1a)]
        if len(bxs):
            shifted = np.asarray(bxs, np.float32) * scale
            shifted[:, [0, 2]] += x1a - x1b
            shifted[:, [1, 3]] += y1a - y1b
            all_boxes.append(shifted)
            all_labels.append(np.asarray(lbs))
    if all_boxes:
        out_boxes = np.concatenate(all_boxes)
        out_labels = np.concatenate(all_labels)
        out_boxes[:, [0, 2]] = out_boxes[:, [0, 2]].clip(0, 2 * s)
        out_boxes[:, [1, 3]] = out_boxes[:, [1, 3]].clip(0, 2 * s)
        wh = out_boxes[:, 2:] - out_boxes[:, :2]
        keep = (wh > 2).all(axis=1)
        out_boxes, out_labels = out_boxes[keep], out_labels[keep]
    else:
        out_boxes = np.zeros((0, 4), np.float32)
        out_labels = np.zeros((0,), np.int64)
    if perspective is not None:
        if s % 2:
            raise ValueError(
                f"mosaic with random_perspective needs an even out_size "
                f"(got {s}): the 2s canvas shrinks by s//2 borders")
        canvas, out_boxes, out_labels = random_perspective(
            canvas, out_boxes, out_labels, rng,
            border=(-s // 2, -s // 2), fill=fill, **perspective)
    else:
        # downscale canvas 2s -> s
        canvas = resize_bilinear(canvas, (s, s))
        out_boxes = out_boxes / 2.0
    # pad to fixed count
    n = len(out_boxes)
    boxes_pad = np.zeros((max_boxes, 4), np.float32)
    labels_pad = np.zeros((max_boxes,), np.int64)
    valid = np.zeros((max_boxes,), bool)
    take = min(n, max_boxes)
    boxes_pad[:take] = out_boxes[:take]
    labels_pad[:take] = out_labels[:take]
    valid[:take] = True
    return canvas, boxes_pad, labels_pad, valid


def random_perspective(img: np.ndarray, boxes: np.ndarray,
                       labels: np.ndarray, rng: np.random.Generator,
                       degrees: float = 0.0, translate: float = 0.1,
                       scale: float = 0.5, shear: float = 0.0,
                       perspective: float = 0.0,
                       border: Tuple[int, int] = (0, 0),
                       fill: float = 114.0
                       ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """yolov5's geometric detection augmentation
    (utils/augmentations.py:144 random_perspective): center → perspective
    → rotation+scale → shear → translate, one combined 3x3 matrix applied
    to the image (cv2.warpAffine) and to all 4 box corners, then
    box_candidates filtering (:343 — min size 2px, aspect < 20, area
    ratio > 0.1). Defaults are the hyp.scratch.yaml values
    (degrees 0, translate .1, scale .5, shear 0, perspective 0).

    boxes: (N, 4) xyxy pixels; returns the warped (img, boxes, labels).
    """
    import math

    height = img.shape[0] + border[0] * 2
    width = img.shape[1] + border[1] * 2

    C = np.eye(3)
    C[0, 2] = -img.shape[1] / 2
    C[1, 2] = -img.shape[0] / 2
    P = np.eye(3)
    P[2, 0] = rng.uniform(-perspective, perspective)
    P[2, 1] = rng.uniform(-perspective, perspective)
    R = np.eye(3)
    a = math.radians(rng.uniform(-degrees, degrees))
    s = rng.uniform(1 - scale, 1 + scale)
    # cv2.getRotationMatrix2D(center=(0,0), angle, scale) equivalent
    R[0, :2] = [s * math.cos(a), s * math.sin(a)]
    R[1, :2] = [-s * math.sin(a), s * math.cos(a)]
    S = np.eye(3)
    S[0, 1] = math.tan(math.radians(rng.uniform(-shear, shear)))
    S[1, 0] = math.tan(math.radians(rng.uniform(-shear, shear)))
    T = np.eye(3)
    T[0, 2] = rng.uniform(0.5 - translate, 0.5 + translate) * width
    T[1, 2] = rng.uniform(0.5 - translate, 0.5 + translate) * height
    M = T @ S @ R @ P @ C            # right-to-left order matters

    if (border[0] != 0) or (border[1] != 0) or (M != np.eye(3)).any():
        try:
            import cv2
        except ImportError:
            cv2 = None
        if cv2 is not None:
            fv = (fill,) * img.shape[-1]
            if perspective:
                img = cv2.warpPerspective(img, M, dsize=(width, height),
                                          borderValue=fv)
            else:
                img = cv2.warpAffine(img, M[:2], dsize=(width, height),
                                     borderValue=fv)
            if img.ndim == 2:        # cv2 drops a size-1 channel axis
                img = img[..., None]
        else:
            img = _warp_np(img, M, (height, width), fill,
                           bool(perspective))

    n = len(boxes)
    if n:
        xy = np.ones((n * 4, 3))
        xy[:, :2] = boxes[:, [0, 1, 2, 3, 0, 3, 2, 1]].reshape(n * 4, 2)
        xy = xy @ M.T
        xy = (xy[:, :2] / xy[:, 2:3] if perspective
              else xy[:, :2]).reshape(n, 8)
        x, y = xy[:, [0, 2, 4, 6]], xy[:, [1, 3, 5, 7]]
        new = np.stack([x.min(1), y.min(1), x.max(1), y.max(1)], axis=1)
        new[:, [0, 2]] = new[:, [0, 2]].clip(0, width)
        new[:, [1, 3]] = new[:, [1, 3]].clip(0, height)
        keep = box_candidates(boxes.T * s, new.T)
        boxes, labels = new[keep].astype(np.float32), labels[keep]
    return img, boxes, labels


def _warp_np(img: np.ndarray, M: np.ndarray, out_hw: Tuple[int, int],
             fill: float, perspective: bool) -> np.ndarray:
    """Pure-numpy inverse-mapped bilinear warp — the cv2-free fallback so
    the augmentation never becomes a hard opencv dependency."""
    h, w = out_hw
    Minv = np.linalg.inv(M)
    ys, xs = np.meshgrid(np.arange(h, dtype=np.float64),
                         np.arange(w, dtype=np.float64), indexing="ij")
    ones = np.ones_like(xs)
    src = np.stack([xs, ys, ones], -1) @ Minv.T
    sx, sy = src[..., 0], src[..., 1]
    if perspective:
        sx, sy = sx / src[..., 2], sy / src[..., 2]
    x0, y0 = np.floor(sx).astype(int), np.floor(sy).astype(int)
    fx, fy = (sx - x0)[..., None], (sy - y0)[..., None]

    def tap(xi, yi):
        inside = (xi >= 0) & (xi < img.shape[1]) &                  (yi >= 0) & (yi < img.shape[0])
        vals = img[np.clip(yi, 0, img.shape[0] - 1),
                   np.clip(xi, 0, img.shape[1] - 1)].astype(np.float32)
        return np.where(inside[..., None], vals, fill)

    out = (tap(x0, y0) * (1 - fx) * (1 - fy)
           + tap(x0 + 1, y0) * fx * (1 - fy)
           + tap(x0, y0 + 1) * (1 - fx) * fy
           + tap(x0 + 1, y0 + 1) * fx * fy)
    return out.astype(np.float32)


def box_candidates(box1: np.ndarray, box2: np.ndarray, wh_thr: float = 2,
                   ar_thr: float = 20, area_thr: float = 0.1,
                   eps: float = 1e-16) -> np.ndarray:
    """Keep boxes that survived the warp (augmentations.py:343): still
    >2px each side, aspect ratio < 20, area > 10% of the pre-warp box."""
    w1, h1 = box1[2] - box1[0], box1[3] - box1[1]
    w2, h2 = box2[2] - box2[0], box2[3] - box2[1]
    ar = np.maximum(w2 / (h2 + eps), h2 / (w2 + eps))
    return ((w2 > wh_thr) & (h2 > wh_thr)
            & (w2 * h2 / (w1 * h1 + eps) > area_thr) & (ar < ar_thr))


def mosaic_array_source(images: np.ndarray, boxes: np.ndarray,
                        labels: np.ndarray, valid: np.ndarray,
                        out_size: int, max_boxes: int, seed: int,
                        perspective: Optional[Dict] = None,
                        fill: float = 0.0):
    """MapSource over in-memory arrays where each sample is a fresh
    4-image mosaic (+ optional random_perspective) — wires the mosaic
    path into the npz/synthetic detection flows. ``fill`` defaults to 0
    because array datasets here are float images (not 0-255 JPEG)."""
    import threading

    from .loader import MapSource
    from .transforms import thread_rng

    local = threading.local()
    n = len(images)

    def fetch(i: int) -> Dict[str, np.ndarray]:
        rng = thread_rng(local, seed)
        idxs = [i] + [int(rng.integers(0, n)) for _ in range(3)]
        imgs = [np.asarray(images[j], np.float32) for j in idxs]
        bxs = [np.asarray(boxes[j][valid[j]], np.float32) for j in idxs]
        lbs = [np.asarray(labels[j][valid[j]]) for j in idxs]
        # 4 images' boxes merge into one sample: carry 4x the per-image
        # capacity so mosaic never silently truncates ground truth
        canvas, b, l, v = mosaic4(imgs, bxs, lbs, out_size, rng,
                                  max_boxes=4 * max_boxes,
                                  perspective=perspective, fill=fill)
        return {"image": canvas, "boxes": b, "labels": l, "valid": v}

    return MapSource(n, fetch)
