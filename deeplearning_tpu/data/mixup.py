"""Mixup / CutMix batch augmentation + mosaic for detection.

Surface of the timm-style mixup the B-harness uses (swin main.py:111-118
mixup_fn with label smoothing folded into soft targets) and YOLOX's
MosaicDetection (yolox/data/datasets/mosaicdetection.py:37: 4-image
mosaic + box-aware mixup). Mixup/cutmix are jittable (device-side, on the
global batch); mosaic is host numpy (it reshapes images before batching).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def one_hot_smooth(labels: jax.Array, num_classes: int,
                   smoothing: float = 0.0) -> jax.Array:
    off = smoothing / num_classes
    on = 1.0 - smoothing + off
    return jax.nn.one_hot(labels, num_classes) * (on - off) + off


def mixup_cutmix(batch: Dict[str, jax.Array], rng: jax.Array,
                 num_classes: int, mixup_alpha: float = 0.8,
                 cutmix_alpha: float = 1.0, smoothing: float = 0.1,
                 switch_prob: float = 0.5) -> Dict[str, jax.Array]:
    """Pair each sample with the reversed batch; mixup or cutmix chosen
    per batch. Returns batch with soft-target 'label'."""
    imgs = batch["image"]
    labels = batch["label"]
    k_lam, k_switch, k_box = jax.random.split(rng, 3)
    use_cutmix = jax.random.uniform(k_switch) < switch_prob
    alpha = jnp.where(use_cutmix, cutmix_alpha, mixup_alpha)
    lam = jax.random.beta(k_lam, alpha, alpha)

    flipped = imgs[::-1]
    b, h, w, c = imgs.shape
    # cutmix box with area ratio (1-lam)
    cut = jnp.sqrt(1.0 - lam)
    ch, cw = (h * cut).astype(jnp.int32), (w * cut).astype(jnp.int32)
    ky, kx = jax.random.split(k_box)
    cy = jax.random.randint(ky, (), 0, h)
    cx = jax.random.randint(kx, (), 0, w)
    y0 = jnp.clip(cy - ch // 2, 0, h)
    x0 = jnp.clip(cx - cw // 2, 0, w)
    y1 = jnp.clip(cy + ch // 2, 0, h)
    x1 = jnp.clip(cx + cw // 2, 0, w)
    rows = jnp.arange(h)[None, :, None, None]
    cols = jnp.arange(w)[None, None, :, None]
    in_box = ((rows >= y0) & (rows < y1) & (cols >= x0) & (cols < x1))
    lam_cutmix = 1.0 - ((y1 - y0) * (x1 - x0)) / (h * w)

    mixed_mixup = lam * imgs + (1 - lam) * flipped
    mixed_cutmix = jnp.where(in_box, flipped, imgs)
    out_imgs = jnp.where(use_cutmix, mixed_cutmix, mixed_mixup)
    lam_eff = jnp.where(use_cutmix, lam_cutmix, lam)

    t1 = one_hot_smooth(labels, num_classes, smoothing)
    t2 = one_hot_smooth(labels[::-1], num_classes, smoothing)
    soft = lam_eff * t1 + (1 - lam_eff) * t2
    return {**batch, "image": out_imgs.astype(imgs.dtype), "label": soft}


def mosaic4(images: Sequence[np.ndarray], boxes: Sequence[np.ndarray],
            labels: Sequence[np.ndarray], out_size: int,
            rng: np.random.Generator,
            max_boxes: int = 64) -> Tuple[np.ndarray, np.ndarray,
                                          np.ndarray, np.ndarray]:
    """4-image mosaic (MosaicDetection surface): random center, each
    quadrant filled by one scaled image; boxes shifted+clipped, padded to
    ``max_boxes`` with a validity mask. Host-side numpy."""
    assert len(images) == 4
    s = out_size
    yc = int(rng.uniform(0.5 * s, 1.5 * s))
    xc = int(rng.uniform(0.5 * s, 1.5 * s))
    canvas = np.full((2 * s, 2 * s, images[0].shape[-1]), 114.0, np.float32)
    all_boxes, all_labels = [], []
    from .transforms import resize_bilinear
    for i, (img, bxs, lbs) in enumerate(zip(images, boxes, labels)):
        h0, w0 = img.shape[:2]
        scale = min(s / h0, s / w0) * rng.uniform(0.5, 1.5)
        nh, nw = max(int(h0 * scale), 1), max(int(w0 * scale), 1)
        img = resize_bilinear(img, (nh, nw))
        if i == 0:      # top-left quadrant, anchored at (yc, xc)
            y1a, x1a = max(yc - nh, 0), max(xc - nw, 0)
            y2a, x2a = yc, xc
        elif i == 1:    # top-right
            y1a, x1a = max(yc - nh, 0), xc
            y2a, x2a = yc, min(xc + nw, 2 * s)
        elif i == 2:    # bottom-left
            y1a, x1a = yc, max(xc - nw, 0)
            y2a, x2a = min(yc + nh, 2 * s), xc
        else:           # bottom-right
            y1a, x1a = yc, xc
            y2a, x2a = min(yc + nh, 2 * s), min(xc + nw, 2 * s)
        # matching source crop
        y1b = nh - (y2a - y1a) if i < 2 else 0
        x1b = nw - (x2a - x1a) if i in (0, 2) else 0
        canvas[y1a:y2a, x1a:x2a] = img[y1b:y1b + (y2a - y1a),
                                       x1b:x1b + (x2a - x1a)]
        if len(bxs):
            shifted = np.asarray(bxs, np.float32) * scale
            shifted[:, [0, 2]] += x1a - x1b
            shifted[:, [1, 3]] += y1a - y1b
            all_boxes.append(shifted)
            all_labels.append(np.asarray(lbs))
    if all_boxes:
        out_boxes = np.concatenate(all_boxes)
        out_labels = np.concatenate(all_labels)
        out_boxes[:, [0, 2]] = out_boxes[:, [0, 2]].clip(0, 2 * s)
        out_boxes[:, [1, 3]] = out_boxes[:, [1, 3]].clip(0, 2 * s)
        wh = out_boxes[:, 2:] - out_boxes[:, :2]
        keep = (wh > 2).all(axis=1)
        out_boxes, out_labels = out_boxes[keep], out_labels[keep]
    else:
        out_boxes = np.zeros((0, 4), np.float32)
        out_labels = np.zeros((0,), np.int64)
    # downscale canvas 2s -> s
    canvas = resize_bilinear(canvas, (s, s))
    out_boxes = out_boxes / 2.0
    # pad to fixed count
    n = len(out_boxes)
    boxes_pad = np.zeros((max_boxes, 4), np.float32)
    labels_pad = np.zeros((max_boxes,), np.int64)
    valid = np.zeros((max_boxes,), bool)
    take = min(n, max_boxes)
    boxes_pad[:take] = out_boxes[:take]
    labels_pad[:take] = out_labels[:take]
    valid[:take] = True
    return canvas, boxes_pad, labels_pad, valid
