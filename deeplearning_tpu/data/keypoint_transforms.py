"""Keypoint (pose) data path: person-box affine crop + flip transforms.

Host-side numpy port of pose_estimation/Insulator/dataset/
coco_transforms.py: HalfBody (:232 — crop to upper/lower body subset),
AffineTransform (:276 — random scale/rotation warp of the person box to
a FIXED network input size), RandomHorizontalFlip (:344 — image flip +
left/right joint swap), affine_points (:56), flip_back (:18 — swap
channels of test-time flipped heatmaps), adjust_box (:157) and
scale_box (:179). The fixed output size keeps the jitted model at one
static shape; heatmap target generation lives in
evaluation/keypoints.make_heatmap_targets.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import numpy as np

# COCO 17-keypoint left/right pairs (matched_parts)
COCO_FLIP_PAIRS: Tuple[Tuple[int, int], ...] = (
    (1, 2), (3, 4), (5, 6), (7, 8), (9, 10), (11, 12), (13, 14), (15, 16))
COCO_UPPER_BODY = tuple(range(11))
COCO_LOWER_BODY = tuple(range(11, 17))


def adjust_box(xmin: float, ymin: float, w: float, h: float,
               fixed_size: Tuple[float, float]
               ) -> Tuple[float, float, float, float]:
    """Grow the box to the fixed h/w aspect ratio about its center
    (coco_transforms.py:157)."""
    xmax, ymax = xmin + w, ymin + h
    hw_ratio = fixed_size[0] / fixed_size[1]
    if h / max(w, 1e-6) > hw_ratio:
        wi = h / hw_ratio
        pad = (wi - w) / 2
        xmin, xmax = xmin - pad, xmax + pad
    else:
        hi = w * hw_ratio
        pad = (hi - h) / 2
        ymin, ymax = ymin - pad, ymax + pad
    return xmin, ymin, xmax - xmin, ymax - ymin


def scale_box(xmin: float, ymin: float, w: float, h: float,
              scale: Tuple[float, float]
              ) -> Tuple[float, float, float, float]:
    """Scale the box about its center (coco_transforms.py:179)."""
    s_h, s_w = h * scale[0], w * scale[1]
    return (xmin - (s_w - w) / 2, ymin - (s_h - h) / 2, s_w, s_h)


def half_body_box(keypoints: np.ndarray, visible: np.ndarray,
                  rng: np.random.Generator,
                  upper_ids: Sequence[int] = COCO_UPPER_BODY,
                  lower_ids: Sequence[int] = COCO_LOWER_BODY,
                  min_visible: int = 3
                  ) -> Optional[Tuple[float, float, float, float]]:
    """HalfBody augmentation (:232): box around the visible upper OR
    lower body joints, expanded 1.5×. None if too few are visible."""
    upper = [i for i in upper_ids if visible[i] > 0]
    lower = [i for i in lower_ids if visible[i] > 0]
    chosen = upper if (rng.random() < 0.5 and len(upper) > 2) else lower
    if len(chosen) <= min_visible - 1:
        chosen = upper if len(upper) > 2 else lower
    if len(chosen) <= min_visible - 1:
        return None
    pts = keypoints[chosen]
    xmin, ymin = pts.min(0)
    xmax, ymax = pts.max(0)
    w, h = xmax - xmin, ymax - ymin
    if w < 1 or h < 1:
        return None
    return scale_box(xmin, ymin, w, h, (1.5, 1.5))


def get_affine_matrix(box: Tuple[float, float, float, float],
                      out_hw: Tuple[int, int], rotation_deg: float = 0.0
                      ) -> np.ndarray:
    """2×3 matrix mapping src box coords → fixed out_hw crop, rotation
    about the box center (AffineTransform :276 semantics). The box must
    already have the output aspect ratio (adjust_box)."""
    xmin, ymin, w, h = box
    cx, cy = xmin + w / 2, ymin + h / 2
    oh, ow = out_hw
    theta = np.deg2rad(rotation_deg)
    cos_t, sin_t = np.cos(theta), np.sin(theta)
    # translate(-center) → rotate → scale to out → translate(out center)
    sx, sy = ow / w, oh / h
    m = np.array([
        [sx * cos_t, -sx * sin_t, 0.0],
        [sy * sin_t, sy * cos_t, 0.0]], np.float64)
    m[:, 2] = [ow / 2 - m[0, 0] * cx - m[0, 1] * cy,
               oh / 2 - m[1, 0] * cx - m[1, 1] * cy]
    return m.astype(np.float32)


def invert_affine(m: np.ndarray) -> np.ndarray:
    """Inverse of a 2×3 affine (for mapping predictions back —
    get_final_preds/affine_points usage)."""
    full = np.vstack([m, [0, 0, 1]]).astype(np.float64)
    return np.linalg.inv(full)[:2].astype(np.float32)


def affine_points(pts: np.ndarray, m: np.ndarray) -> np.ndarray:
    """Apply a 2×3 affine to (N, 2) points (coco_transforms.py:56)."""
    return pts @ m[:, :2].T + m[:, 2]


def warp_affine(img: np.ndarray, m: np.ndarray, out_hw: Tuple[int, int]
                ) -> np.ndarray:
    """Bilinear affine warp to a fixed output size. cv2 when available,
    pure-numpy inverse-mapping otherwise."""
    oh, ow = out_hw
    try:
        import cv2
        return cv2.warpAffine(img, m, (ow, oh),
                              flags=cv2.INTER_LINEAR)
    except ImportError:
        pass
    inv = invert_affine(m)
    ys, xs = np.mgrid[0:oh, 0:ow].astype(np.float32)
    src = affine_points(
        np.stack([xs.ravel(), ys.ravel()], -1), inv)
    h, w = img.shape[:2]
    sx = np.clip(src[:, 0], 0, w - 1)
    sy = np.clip(src[:, 1], 0, h - 1)
    oob = ((src[:, 0] < -0.5) | (src[:, 0] > w - 0.5)
           | (src[:, 1] < -0.5) | (src[:, 1] > h - 0.5))
    x0, y0 = np.floor(sx).astype(int), np.floor(sy).astype(int)
    x1, y1 = np.minimum(x0 + 1, w - 1), np.minimum(y0 + 1, h - 1)
    wx, wy = (sx - x0)[:, None], (sy - y0)[:, None]
    f = img.astype(np.float32).reshape(h * w, -1)
    idx = lambda yy, xx: f[yy * w + xx]  # noqa: E731
    out = (idx(y0, x0) * (1 - wy) * (1 - wx) + idx(y0, x1) * (1 - wy) * wx
           + idx(y1, x0) * wy * (1 - wx) + idx(y1, x1) * wy * wx)
    out[oob] = 0.0
    return out.reshape(oh, ow, -1 if img.ndim == 3 else 1).squeeze()


def flip_keypoints_lr(keypoints: np.ndarray, visible: np.ndarray,
                      width: float,
                      pairs: Sequence[Tuple[int, int]] = COCO_FLIP_PAIRS
                      ) -> Tuple[np.ndarray, np.ndarray]:
    """Mirror keypoints about the vertical axis and swap left/right
    joints (RandomHorizontalFlip :344)."""
    kps = keypoints.copy()
    vis = visible.copy()
    kps[:, 0] = width - 1 - kps[:, 0]
    for a, b in pairs:
        kps[[a, b]] = kps[[b, a]]
        vis[[a, b]] = vis[[b, a]]
    return kps, vis


def flip_back(heatmaps: np.ndarray,
              pairs: Sequence[Tuple[int, int]] = COCO_FLIP_PAIRS
              ) -> np.ndarray:
    """Un-flip test-time flipped heatmaps (H, W, K): mirror W and swap
    paired channels (coco_transforms.py:18)."""
    out = heatmaps[:, ::-1].copy()
    for a, b in pairs:
        out[..., [a, b]] = out[..., [b, a]]
    return out


def keypoint_train_transform(
        fixed_size: Tuple[int, int] = (256, 192),
        scale_range: Tuple[float, float] = (0.65, 1.35),
        rotation_range: Tuple[float, float] = (-45.0, 45.0),
        half_body_prob: float = 0.3,
        flip_prob: float = 0.5,
        heatmap_stride: int = 4,
        sigma: float = 2.0,
        seed: int = 0):
    """Full train-time pipeline for one (image, person box, keypoints)
    sample → dict with fixed-shape 'image' (H, W, 3), 'heatmaps'
    (H/s, W/s, K), 'kp_weights' (K,) — the Compose([HalfBody,
    AffineTransform, RandomHorizontalFlip, KeypointToHeatMap]) stack."""
    from ..evaluation.keypoints import make_heatmap_targets
    rng = np.random.default_rng(seed)

    def fn(image: np.ndarray, box, keypoints: np.ndarray,
           visible: np.ndarray) -> Dict[str, np.ndarray]:
        kps = np.asarray(keypoints, np.float32)
        vis = np.asarray(visible, np.float32)
        xmin, ymin, w, h = box
        if rng.random() < half_body_prob:
            hb = half_body_box(kps, vis, rng)
            if hb is not None:
                xmin, ymin, w, h = hb
        s = rng.uniform(*scale_range)
        xmin, ymin, w, h = scale_box(xmin, ymin, w, h, (s, s))
        xmin, ymin, w, h = adjust_box(xmin, ymin, w, h, fixed_size)
        rot = rng.uniform(*rotation_range)
        m = get_affine_matrix((xmin, ymin, w, h), fixed_size, rot)
        crop = warp_affine(image, m, fixed_size)
        kps_t = affine_points(kps, m)
        if rng.random() < flip_prob:
            crop = crop[:, ::-1].copy()
            kps_t, vis = flip_keypoints_lr(kps_t, vis, fixed_size[1])
        # joints warped outside the crop become invisible
        inside = ((kps_t[:, 0] >= 0) & (kps_t[:, 0] < fixed_size[1])
                  & (kps_t[:, 1] >= 0) & (kps_t[:, 1] < fixed_size[0]))
        vis = vis * inside
        heat_hw = (fixed_size[0] // heatmap_stride,
                   fixed_size[1] // heatmap_stride)
        heat = make_heatmap_targets(kps_t, vis, heat_hw,
                                    stride=heatmap_stride, sigma=sigma)
        return {"image": crop.astype(np.float32),
                "heatmaps": heat,
                "keypoints": kps_t,
                "kp_weights": (vis > 0).astype(np.float32),
                "affine": m}

    return fn
