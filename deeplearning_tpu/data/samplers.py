"""Sampling strategies: identity PK, aspect-ratio grouping, infinite.

Surface of the reference's sampler zoo: BDB's identity PK sampler
(metric_learning/BDB/data/samplers.py — P identities × K instances per
batch for triplet mining), fasterRcnn's GroupedBatchSampler
(utils/group_by_aspect_ratio.py:23 — batches of similar aspect ratio to
minimize pad waste), YOLOX's InfiniteSampler (yolox/data/samplers.py).
All emit numpy index arrays that plug into DataLoader via a custom
epoch-indices hook or direct batch iteration.
"""

from __future__ import annotations

from collections import defaultdict
from typing import Dict, Iterator, List, Sequence

import numpy as np


def pk_batches(labels: np.ndarray, p: int, k: int, *, seed: int = 0,
               epoch: int = 0) -> np.ndarray:
    """(num_batches, P*K) index batches: P random identities × K samples
    each (with replacement when an identity has < K)."""
    rng = np.random.default_rng((seed, epoch))
    by_id: Dict[int, np.ndarray] = defaultdict(list)
    for i, lab in enumerate(np.asarray(labels)):
        by_id[int(lab)].append(i)
    ids = [i for i, idxs in by_id.items() if len(idxs) >= 1]
    rng.shuffle(ids)
    n_batches = max(len(ids) // p, 1)
    batches = []
    for b in range(n_batches):
        chosen = list(ids[b * p:(b + 1) * p])
        if len(chosen) < p:
            # top up from identities not already in the batch; only reuse
            # identities when the dataset has fewer than P of them
            pool = [i for i in ids if i not in chosen]
            need = p - len(chosen)
            if pool:
                take = min(need, len(pool))
                chosen += list(rng.choice(pool, take, replace=False))
                need -= take
            if need > 0:
                chosen += list(rng.choice(ids, need, replace=True))
        batch = []
        for ident in chosen:
            pool = np.asarray(by_id[ident])
            batch.extend(rng.choice(pool, k, replace=len(pool) < k))
        batches.append(np.asarray(batch))
    return np.stack(batches)


def aspect_ratio_groups(aspect_ratios: Sequence[float], n_groups: int = 2
                        ) -> np.ndarray:
    """Group id per sample by aspect-ratio quantile bins
    (group_by_aspect_ratio surface)."""
    ar = np.asarray(aspect_ratios, np.float64)
    edges = np.quantile(ar, np.linspace(0, 1, n_groups + 1)[1:-1]) \
        if n_groups > 1 else np.asarray([])
    return np.searchsorted(edges, ar)


def grouped_batches(aspect_ratios: Sequence[float], batch_size: int, *,
                    n_groups: int = 2, seed: int = 0, epoch: int = 0
                    ) -> np.ndarray:
    """(num_batches, batch_size) indices where every batch comes from one
    aspect-ratio group (drops the ragged remainder per group)."""
    rng = np.random.default_rng((seed, epoch))
    groups = aspect_ratio_groups(aspect_ratios, n_groups)
    batches = []
    for g in np.unique(groups):
        idx = np.where(groups == g)[0]
        rng.shuffle(idx)
        for start in range(0, len(idx) - batch_size + 1, batch_size):
            batches.append(idx[start:start + batch_size])
    order = rng.permutation(len(batches))
    return np.stack([batches[i] for i in order]) if batches else \
        np.zeros((0, batch_size), np.int64)


def infinite_indices(size: int, *, seed: int = 0) -> Iterator[int]:
    """Endless shuffled index stream (InfiniteSampler surface)."""
    epoch = 0
    while True:
        rng = np.random.default_rng((seed, epoch))
        yield from rng.permutation(size)
        epoch += 1
