"""ctypes binding for the native JPEG decode worker (native/imagedec.cpp).

Drops into the data pipeline as a fast path: ``decode_jpeg`` replaces
PIL for single images (datasets.load_image), ``decode_resize_batch``
decodes+resizes a whole batch off the GIL with a C++ thread pool — the
native input-path analog of the reference's cv2/torchvision decode
underneath its DataLoaders. Falls back cleanly when g++/libjpeg are
absent: ``available()`` gates every call site.
"""

from __future__ import annotations

import ctypes
from typing import List, Optional

import numpy as np

from ..native.build import load

_CACHE = {"lib": False}  # False = not tried, None = unavailable


def _lib():
    if _CACHE["lib"] is False:
        lib = load("imagedec")
        if lib is not None:
            lib.decode_jpeg_info.restype = ctypes.c_int
            lib.decode_jpeg_info.argtypes = [
                ctypes.c_char_p, ctypes.c_long,
                ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int)]
            lib.decode_jpeg.restype = ctypes.c_int
            lib.decode_jpeg.argtypes = [
                ctypes.c_char_p, ctypes.c_long,
                ctypes.POINTER(ctypes.c_uint8), ctypes.c_long]
            lib.decode_resize_batch.restype = ctypes.c_int
            lib.decode_resize_batch.argtypes = [
                ctypes.POINTER(ctypes.c_char_p),
                ctypes.POINTER(ctypes.c_long),
                ctypes.c_int, ctypes.c_int, ctypes.c_int,
                ctypes.POINTER(ctypes.c_uint8), ctypes.c_int]
        _CACHE["lib"] = lib
    return _CACHE["lib"]


def available() -> bool:
    return _lib() is not None


def decode_jpeg(data: bytes) -> Optional[np.ndarray]:
    """JPEG bytes -> (H, W, 3) uint8 RGB, or None on failure."""
    lib = _lib()
    if lib is None:
        return None
    w, h = ctypes.c_int(), ctypes.c_int()
    if lib.decode_jpeg_info(data, len(data), ctypes.byref(w),
                            ctypes.byref(h)):
        return None
    out = np.empty((h.value, w.value, 3), np.uint8)
    rc = lib.decode_jpeg(
        data, len(data),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), out.nbytes)
    return out if rc == 0 else None


def decode_resize_batch(blobs: List[bytes], out_h: int, out_w: int,
                        n_threads: int = 4,
                        strict: bool = False) -> Optional[np.ndarray]:
    """List of JPEG byte strings -> (N, out_h, out_w, 3) uint8, decoded
    and bilinear-resized by a C++ thread pool (GIL released for the whole
    batch). Returns None only if the native lib is unavailable.

    Failed decodes come back as zero images. The C worker reports how many
    failed: with ``strict=True`` any failure raises; otherwise a warning
    is logged so corrupt inputs can't silently poison a training batch."""
    lib = _lib()
    if lib is None:
        return None
    n = len(blobs)
    out = np.zeros((n, out_h, out_w, 3), np.uint8)
    if n == 0:
        return out
    bufs = (ctypes.c_char_p * n)(*blobs)
    lens = (ctypes.c_long * n)(*[len(b) for b in blobs])
    n_errors = lib.decode_resize_batch(
        bufs, lens, n, out_h, out_w,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), n_threads)
    if n_errors:
        if strict:
            raise ValueError(
                f"decode_resize_batch: {n_errors}/{n} JPEG decodes failed")
        import logging
        logging.getLogger(__name__).warning(
            "decode_resize_batch: %d/%d JPEG decodes failed "
            "(zero-filled in output)", n_errors, n)
    return out
