"""Overlapped device feed: a threaded host→HBM prefetch stage.

PR 1 made the *fetch* side of the hot loop sync-free (DeferredMetrics);
this is the *feed*-side counterpart. ``Trainer._train_one_epoch`` used to
pay a blocking ``make_global_array`` host→device transfer on the consumer
thread before every ``train_step`` dispatch — serial feed is the single
biggest non-compute slice of the step on a fast chip. ``DevicePrefetcher``
moves that transfer onto a background thread with a bounded depth-k
queue, so batch k+1's decode **and** H2D copy overlap batch k's compute.

Unlike the bare ``prefetch_to_device`` generator, the prefetcher
preserves the full loader protocol (``__len__``, ``set_epoch``,
``last_data_wait``, ``mesh``) so the Trainer — and anything else written
against ``DataLoader`` — can wrap any loader transparently, including
across epochs. It is also the single place that owns the transfer: when
the wrapped loader is a ``DataLoader`` with a mesh, the prefetcher takes
over its device-put (``loader.device_transfer = False``) so batches are
transferred exactly once, on the worker thread (the double-transfer
``build.py`` used to do is structurally impossible here).

Telemetry (feeds Trainer ``data_time``/``throughput_stats``):
- ``last_data_wait`` / ``data_wait_total``: time the CONSUMER actually
  blocked on the queue — true feed starvation, not wall clock.
- ``h2d_wait_total``: worker-thread time spent assembling/transferring
  device arrays (the cost the pipeline hides).
- ``occupancy_mean`` / ``stats()``: queue depth observed at each get —
  near ``depth`` means the feed keeps up, near 0 means input-bound.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Dict, Iterator, Optional

import jax
import numpy as np

from ..obs import spans
from ..obs import threads as obs_threads
from ..parallel.sharding import make_global_array

_END = object()          # producer exhausted its epoch normally


class _WorkerError:
    """Exception carrier: re-raised on the consumer thread."""

    def __init__(self, exc: BaseException):
        self.exc = exc


class DevicePrefetcher:
    """Bounded background-thread device feed wrapping any loader.

    - ``depth``: max batches resident in HBM ahead of the consumer (the
      queue bound; 2 hides one full transfer+decode behind each step
      without hoarding device memory).
    - ``mesh``: assemble numpy leaves into GLOBAL sharded arrays via
      ``make_global_array`` (multi-host correct). Defaults to the wrapped
      loader's own mesh, whose per-batch transfer is taken over.
    - ``sharding``: single-host NamedSharding device_put (mutually
      exclusive with mesh).
    Leaves that are already ``jax.Array`` pass through untouched, so
    wrapping a loader that device-puts internally never double-transfers.
    """

    def __init__(self, loader, depth: int = 2, *,
                 mesh=None, sharding=None, spec=None):
        if mesh is not None and sharding is not None:
            raise ValueError("pass mesh OR sharding, not both")
        self.loader = loader
        self.depth = max(int(depth), 1)
        self.sharding = sharding
        self.spec = spec
        # take over the wrapped loader's transfer so every batch is
        # device-put exactly once, on OUR worker thread (honest
        # h2d_wait_total, and build.py can't double-transfer)
        if mesh is None and sharding is None:
            mesh = getattr(loader, "mesh", None)
        self.mesh = mesh
        if self.mesh is not None and \
                getattr(loader, "device_transfer", None) is True and \
                getattr(loader, "mesh", None) is self.mesh:
            loader.device_transfer = False
        self.epoch = getattr(loader, "epoch", 0)
        # consumer-side starvation telemetry (the DataLoader surface)
        self.last_data_wait: Optional[float] = None
        self.data_wait_total = 0.0
        # worker-side H2D telemetry
        self.h2d_wait_total = 0.0
        self.source_wait_total = 0.0
        self.batches_fed = 0
        self._occ_sum = 0
        self._occ_n = 0
        self._active: Optional[Dict[str, Any]] = None   # started pipeline

    # ------------------------------------------------- loader protocol
    def __len__(self) -> int:
        return len(self.loader)

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch
        if hasattr(self.loader, "set_epoch"):
            self.loader.set_epoch(epoch)
        # a pipeline started for a different epoch is stale — discard it
        if self._active is not None and self._active["epoch"] != epoch:
            self._shutdown(self._active)
            self._active = None

    def element_spec(self):
        """Delegate abstract batch shapes (AOT warmup) to the loader."""
        fn = getattr(self.loader, "element_spec", None)
        return fn() if fn is not None else None

    def reseed(self, salt: int) -> None:
        """Delegate divergence-recovery reseeding (skip-the-window) to
        the wrapped loader, discarding any already-started pipeline —
        its batches were drawn from the old permutation."""
        fn = getattr(self.loader, "reseed", None)
        if fn is not None:
            fn(salt)
        if self._active is not None:
            self._shutdown(self._active)
            self._active = None

    @property
    def quarantine(self):
        """The wrapped loader's QuarantineLog, if any."""
        return getattr(self.loader, "quarantine", None)

    # ---------------------------------------------------- device place
    def _to_device(self, batch):
        def put(x):
            if isinstance(x, jax.Array):
                return x                      # already placed — no copy
            x = np.asarray(x)  # dltpu: allow(DLT100) H2D staging, worker thread
            if self.mesh is not None:
                return make_global_array(x, self.mesh, self.spec)
            if self.sharding is not None:
                return jax.device_put(x, self.sharding)
            return jax.device_put(x)
        return jax.tree.map(put, batch)

    # -------------------------------------------------------- pipeline
    def _worker(self, it, q: "queue.Queue", stop: threading.Event) -> None:
        try:
            while not stop.is_set():
                t0 = time.perf_counter()
                try:
                    batch = next(it)
                except StopIteration:
                    break
                t1 = time.perf_counter()
                batch = self._to_device(batch)
                t2 = time.perf_counter()
                self.source_wait_total += t1 - t0
                self.h2d_wait_total += t2 - t1
                # trace lanes from the worker thread — reuses the clock
                # reads above, so the disabled path costs one None check
                tracer = spans.get_tracer()
                if tracer is not None:
                    tracer.record("feed/decode", t0, t1 - t0)
                    tracer.record("feed/h2d", t1, t2 - t1)
                # bounded put that stays responsive to shutdown
                while not stop.is_set():
                    try:
                        q.put(batch, timeout=0.1)
                        break
                    except queue.Full:
                        continue
            if not stop.is_set():
                q.put(_END)
        except BaseException as exc:  # noqa: BLE001 - relayed to consumer
            # same responsive bounded-put as the data path: a one-shot
            # put(timeout=1.0) against a full queue used to DROP the
            # exception, turning a worker crash into a silent early end
            # of the epoch — the consumer must re-raise it, with the
            # original traceback riding on exc.__traceback__
            while not stop.is_set():
                try:
                    q.put(_WorkerError(exc), timeout=0.1)
                    break
                except queue.Full:
                    continue

    def start(self) -> None:
        """Eagerly start producing the CURRENT epoch's batches.

        Lets the caller overlap first-batch decode+transfer with other
        host work — ``Trainer.precompile()`` runs the AOT step compile
        while this queue fills. ``__iter__`` consumes the started
        pipeline instead of spinning up a second one."""
        if self._active is None:
            self._active = self._start()

    def _start(self) -> Dict[str, Any]:
        q: "queue.Queue" = queue.Queue(maxsize=self.depth)
        stop = threading.Event()
        thread = obs_threads.spawn(
            self._worker, args=(iter(self.loader), q, stop),
            name="device-prefetch", daemon=True)
        return {"queue": q, "stop": stop, "thread": thread,
                "epoch": self.epoch}

    @staticmethod
    def _shutdown(pipe: Dict[str, Any]) -> None:
        pipe["stop"].set()
        try:                      # unblock a producer stuck in put()
            while True:
                pipe["queue"].get_nowait()
        except queue.Empty:
            pass
        pipe["thread"].join(timeout=5.0)

    def __iter__(self) -> Iterator[Any]:
        pipe, self._active = (self._active or self._start()), None
        q = pipe["queue"]
        try:
            while True:
                t0 = time.perf_counter()
                item = q.get()
                self.last_data_wait = time.perf_counter() - t0
                self.data_wait_total += self.last_data_wait
                if item is _END:
                    break
                if isinstance(item, _WorkerError):
                    raise item.exc
                self._occ_sum += q.qsize()
                self._occ_n += 1
                self.batches_fed += 1
                yield item
        finally:
            self._shutdown(pipe)

    # ------------------------------------------------------- telemetry
    @property
    def occupancy_mean(self) -> float:
        """Mean queue depth seen at each consumer get (0..depth)."""
        return self._occ_sum / self._occ_n if self._occ_n else 0.0

    def stats(self) -> Dict[str, float]:
        """Feed telemetry snapshot for throughput_stats / bench rows."""
        busy = self.source_wait_total + self.h2d_wait_total
        out = {
            "prefetch_depth": float(self.depth),
            "prefetch_occupancy": self.occupancy_mean,
            "batches_fed": float(self.batches_fed),
            "data_wait_total": self.data_wait_total,
            "h2d_wait_total": self.h2d_wait_total,
            "h2d_wait_frac": (self.h2d_wait_total / busy) if busy else 0.0,
        }
        if self.quarantine is not None:
            out["quarantined"] = float(self.quarantine.quarantined)
        return out

    def reset_stats(self) -> None:
        self.last_data_wait = None
        self.data_wait_total = 0.0
        self.h2d_wait_total = 0.0
        self.source_wait_total = 0.0
        self.batches_fed = 0
        self._occ_sum = 0
        self._occ_n = 0
