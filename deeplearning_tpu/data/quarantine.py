"""Bad-sample quarantine: one corrupt file must not kill a run.

At production scale the input set always contains poison — truncated
JPEGs, mislabeled rows, a decoder that segfault-adjacent-raises on one
file in ten million. The reference stacks die on the first one (the
DataLoader worker raises, the epoch dies with it). Here the loader's
per-sample fetch catches the exception, substitutes a known-good sample
from the same batch (keeping batch shapes fixed so jit never retraces),
and appends one JSON line to a ``quarantine.jsonl`` manifest — the
operator's list of files to delete or re-encode.

Substitution is only safe while poison is RARE: a dataset that is 30%
unreadable is a broken dataset, and silently training on 70% duplicated
survivors would be worse than crashing. The ``max_poisoned_frac``
threshold (checked once at least ``min_samples`` fetches have been
seen, so one early failure can't trip it) escalates to
:class:`PoisonedData` — a hard error the loader and Trainer propagate,
never quarantine.

Every quarantined sample also lands a ``quarantine`` flight event, so a
crash dump / ``tools/obs_report`` recovery section carries the count
next to the rollback and checkpoint-retry telemetry.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, Optional

__all__ = ["PoisonedData", "QuarantineLog", "quarantinable"]


class PoisonedData(RuntimeError):
    """Poisoned fraction crossed the threshold (or a whole batch failed)
    — substitution would silently distort training, so this is a hard
    error, never quarantined."""


def quarantinable(exc: BaseException) -> bool:
    """Per-SAMPLE failures are quarantinable; process-level failures
    (interrupts, OOM, the escalation itself) must propagate."""
    return isinstance(exc, Exception) and not isinstance(
        exc, (PoisonedData, MemoryError))


class QuarantineLog:
    """Append-only ``quarantine.jsonl`` manifest + poisoned-fraction
    accounting. Thread-safe: the loader's parallel path records from the
    consumer thread while workers keep fetching."""

    def __init__(self, path: str, *, max_poisoned_frac: float = 0.01,
                 min_samples: int = 100):
        self.path = os.path.abspath(path)
        self.max_poisoned_frac = float(max_poisoned_frac)
        self.min_samples = int(min_samples)
        self.quarantined = 0
        self.total = 0                 # every fetch attempt, good or bad
        self._lock = threading.Lock()

    @property
    def poisoned_frac(self) -> float:
        with self._lock:
            return self.quarantined / self.total if self.total else 0.0

    def note_ok(self, n: int = 1) -> None:
        with self._lock:
            self.total += int(n)

    def record(self, index: Any, exc: BaseException, *,
               step: Optional[int] = None,
               path: Optional[str] = None) -> None:
        """Log one quarantined sample (manifest line + flight event),
        then escalate if the poisoned fraction crossed the threshold."""
        entry: Dict[str, Any] = {
            "time": time.time(),
            "index": int(index) if isinstance(index, (int,)) else index,
            "error": repr(exc),
        }
        if step is not None:
            entry["step"] = int(step)
        if path is not None:
            entry["path"] = path
        with self._lock:
            self.quarantined += 1
            self.total += 1
            try:
                parent = os.path.dirname(self.path)
                if parent:
                    os.makedirs(parent, exist_ok=True)
                with open(self.path, "a") as f:
                    f.write(json.dumps(entry) + "\n")
            except OSError:
                pass               # losing a manifest line beats dying
        from ..obs import flight, metrics   # lazy: flight never raises
        flight.record("quarantine", **entry)
        metrics.inc("dltpu_quarantine_total")
        self.check_escalation()

    def check_escalation(self) -> None:
        with self._lock:
            total, bad = self.total, self.quarantined
        if total >= self.min_samples and \
                bad / total > self.max_poisoned_frac:
            raise PoisonedData(
                f"{bad}/{total} samples quarantined "
                f"({bad / total:.1%} > {self.max_poisoned_frac:.1%} "
                f"threshold) — the dataset is poisoned, not unlucky; "
                f"manifest: {self.path}")
