from .loader import ArraySource, MapSource, DataLoader, prefetch_to_device  # noqa: F401
