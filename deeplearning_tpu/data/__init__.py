from . import datasets, label_convert, mixup, samplers, transforms, zip_cache  # noqa: F401
from .device_prefetch import DevicePrefetcher  # noqa: F401
from .loader import ArraySource, MapSource, DataLoader, prefetch_to_device  # noqa: F401
from .quarantine import PoisonedData, QuarantineLog, quarantinable  # noqa: F401
