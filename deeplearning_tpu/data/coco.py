"""COCO-format detection dataset: real JPEGs + instances.json.

Capability surface of detection/YOLOX/yolox/data/datasets/coco.py
(COCODataset: json parse → per-image (img, padded boxes) with decode on
access) and fasterRcnn's VOC/COCO dataset classes, reshaped for fixed
TPU batches: every sample is resize-with-pad to a static size with boxes
rescaled, gt padded to ``max_gt`` with a valid mask, so the jitted step
never retraces. Decode runs per-sample inside the loader's thread pool.
"""

from __future__ import annotations

import json
import os
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

from .datasets import load_image
from .label_convert import coco_to_records
from .loader import MapSource
from .transforms import random_flip_lr, resize_with_pad, thread_rng


def load_coco_json(json_path: str) -> Tuple[Sequence[Dict], Sequence[str]]:
    """(records, class_names) from an instances.json. Records carry
    filename + absolute xyxy boxes + class names (label_convert schema)."""
    with open(json_path) as f:
        coco = json.load(f)
    class_names = [c["name"] for c in
                   sorted(coco["categories"], key=lambda c: c["id"])]
    return coco_to_records(coco), class_names


def coco_detection_source(json_path: Optional[str] = None,
                          images_dir: Optional[str] = None,
                          *, image_size: int = 256, max_gt: int = 16,
                          augment: bool = False, seed: int = 0,
                          records: Optional[Sequence[Dict]] = None,
                          class_names: Optional[Sequence[str]] = None,
                          mosaic: bool = False,
                          perspective: Optional[Dict] = None,
                          mosaic_pool: Optional[Sequence[int]] = None,
                          ) -> Tuple[MapSource, Sequence[str]]:
    """MapSource of fixed-shape samples {image, boxes, labels, valid}
    decoded lazily from disk. ``augment`` adds horizontal flip (the
    YOLOX/fasterRcnn baseline transform). ``mosaic`` makes every sample
    a fresh 4-image mosaic (MosaicDetection / yolov5 load_mosaic flow),
    and ``perspective`` threads random_perspective kwargs through it
    (yolov5 utils/datasets.py:836). Pass pre-parsed ``records``/
    ``class_names`` (from load_coco_json) to build several sources —
    e.g. augmented train + raw val — without re-parsing the json.
    ``mosaic_pool`` restricts the 3 extra mosaic tiles to those record
    indices (pass the TRAIN split so held-out val images never leak into
    training mosaics)."""
    if records is None:
        if json_path is None:
            raise ValueError("need json_path or records")
        records, class_names = load_coco_json(json_path)
    if images_dir is None:
        if json_path is None:
            raise ValueError("need images_dir when passing records")
        images_dir = os.path.join(os.path.dirname(json_path), "images")
    name_to_id = {n: i for i, n in enumerate(class_names)}
    out_hw = (image_size, image_size)

    import threading
    local = threading.local()

    def _load_raw(i: int):
        rec = records[i]
        img = load_image(os.path.join(images_dir, rec["filename"]))
        labels = np.asarray([name_to_id[x] for x in rec["names"]],
                            np.int64)
        return (np.asarray(img, np.float32),
                np.asarray(rec["boxes"], np.float32).reshape(-1, 4),
                labels)

    def fetch(i: int) -> Dict[str, np.ndarray]:
        rng = thread_rng(local, seed)
        if mosaic:
            from .mixup import mosaic4
            pool = (np.asarray(mosaic_pool) if mosaic_pool is not None
                    else np.arange(len(records)))
            idxs = [i] + [int(pool[rng.integers(0, len(pool))])
                          for _ in range(3)]
            raws = [_load_raw(j) for j in idxs]
            # a mosaic merges 4 images' boxes: pad to 4*max_gt so no
            # ground truth is silently dropped (loss masks by valid)
            img, boxes, labels, pvalid = mosaic4(
                [r[0] for r in raws], [r[1] for r in raws],
                [r[2] for r in raws], image_size, rng,
                max_boxes=4 * max_gt, perspective=perspective,
                fill=114.0)
            if augment:
                img, boxes = random_flip_lr(img, rng, boxes)
            return {"image": img / 255.0, "boxes": boxes,
                    "labels": labels, "valid": pvalid}
        rec = records[i]
        img = load_image(os.path.join(images_dir, rec["filename"]))
        img, _, boxes = resize_with_pad(img, out_hw, rec["boxes"])
        if augment:
            img, boxes = random_flip_lr(img, rng, boxes)
        pboxes = np.zeros((max_gt, 4), np.float32)
        plabels = np.zeros((max_gt,), np.int64)
        pvalid = np.zeros((max_gt,), bool)
        take = min(len(boxes), max_gt)
        if take:
            pboxes[:take] = boxes[:take]
            plabels[:take] = [name_to_id[x] for x in rec["names"][:take]]
            pvalid[:take] = True
        return {"image": np.asarray(img, np.float32) / 255.0,
                "boxes": pboxes, "labels": plabels, "valid": pvalid}

    return MapSource(len(records), fetch), class_names
