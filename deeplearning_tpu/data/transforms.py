"""Host-side image transforms (numpy/PIL) for fixed-shape TPU batches.

Covers the reference's transform stacks (SURVEY.md L3): classification
train/eval pipelines (RandomResizedCrop + flip + normalize,
classification/*/dataLoader), detection resize-with-pad
(fasterRcnn models/transform.py:70 GeneralizedRCNNTransform — here the
output is FIXED size so the jitted model never retraces), color jitter
(yolov5 augment_hsv style). All pure numpy: runs in loader workers/host.
"""

from __future__ import annotations

import itertools
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

IMAGENET_MEAN = np.asarray([0.485, 0.456, 0.406], np.float32)
IMAGENET_STD = np.asarray([0.229, 0.224, 0.225], np.float32)


def normalize(img: np.ndarray, mean=IMAGENET_MEAN, std=IMAGENET_STD
              ) -> np.ndarray:
    return (img.astype(np.float32) / 255.0 - mean) / std


def resize_bilinear(img: np.ndarray, out_hw: Tuple[int, int]) -> np.ndarray:
    """Simple numpy bilinear resize (no cv2 dependency needed, but uses
    cv2 when available for speed)."""
    try:
        import cv2
        return cv2.resize(img, (out_hw[1], out_hw[0]),
                          interpolation=cv2.INTER_LINEAR)
    except ImportError:
        h, w = img.shape[:2]
        oh, ow = out_hw
        ys = np.clip((np.arange(oh) + 0.5) * h / oh - 0.5, 0, h - 1)
        xs = np.clip((np.arange(ow) + 0.5) * w / ow - 0.5, 0, w - 1)
        y0 = np.floor(ys).astype(int)
        x0 = np.floor(xs).astype(int)
        y1 = np.minimum(y0 + 1, h - 1)
        x1 = np.minimum(x0 + 1, w - 1)
        wy = (ys - y0)[:, None, None]
        wx = (xs - x0)[None, :, None]
        img = img.astype(np.float32)
        out = (img[y0][:, x0] * (1 - wy) * (1 - wx)
               + img[y0][:, x1] * (1 - wy) * wx
               + img[y1][:, x0] * wy * (1 - wx)
               + img[y1][:, x1] * wy * wx)
        return out


def resize_with_pad(img: np.ndarray, out_hw: Tuple[int, int],
                    boxes: Optional[np.ndarray] = None,
                    pad_value: float = 114.0):
    """Aspect-preserving resize + bottom/right pad to a FIXED size, with
    box rescaling — the GeneralizedRCNNTransform successor. Returns
    (padded_img, scale, boxes?)."""
    h, w = img.shape[:2]
    oh, ow = out_hw
    scale = min(oh / h, ow / w)
    nh, nw = int(round(h * scale)), int(round(w * scale))
    resized = resize_bilinear(img, (nh, nw))
    out = np.full((oh, ow) + img.shape[2:], pad_value, np.float32)
    out[:nh, :nw] = resized
    if boxes is not None:
        boxes = np.asarray(boxes, np.float32) * scale
        return out, scale, boxes
    return out, scale


def random_flip_lr(img: np.ndarray, rng: np.random.Generator,
                   boxes: Optional[np.ndarray] = None, p: float = 0.5):
    if rng.uniform() >= p:
        return (img, boxes) if boxes is not None else img
    img = img[:, ::-1]
    if boxes is not None:
        w = img.shape[1]
        boxes = boxes.copy()
        boxes[:, [0, 2]] = w - boxes[:, [2, 0]]
        return img, boxes
    return img


def random_resized_crop(img: np.ndarray, rng: np.random.Generator,
                        out_hw: Tuple[int, int],
                        scale: Tuple[float, float] = (0.08, 1.0),
                        ratio: Tuple[float, float] = (3 / 4, 4 / 3)
                        ) -> np.ndarray:
    h, w = img.shape[:2]
    area = h * w
    for _ in range(10):
        target_area = rng.uniform(*scale) * area
        aspect = np.exp(rng.uniform(np.log(ratio[0]), np.log(ratio[1])))
        cw = int(round(np.sqrt(target_area * aspect)))
        ch = int(round(np.sqrt(target_area / aspect)))
        if cw <= w and ch <= h:
            y0 = rng.integers(0, h - ch + 1)
            x0 = rng.integers(0, w - cw + 1)
            crop = img[y0:y0 + ch, x0:x0 + cw]
            return resize_bilinear(crop, out_hw)
    return resize_bilinear(img, out_hw)   # fallback: full image


def color_jitter(img: np.ndarray, rng: np.random.Generator,
                 brightness: float = 0.4, contrast: float = 0.4,
                 saturation: float = 0.4) -> np.ndarray:
    """Uint8-range jitter (applied before normalize)."""
    img = img.astype(np.float32)
    if brightness:
        img = img * rng.uniform(1 - brightness, 1 + brightness)
    if contrast:
        mean = img.mean()
        img = (img - mean) * rng.uniform(1 - contrast, 1 + contrast) + mean
    if saturation:
        gray = img.mean(axis=-1, keepdims=True)
        img = gray + (img - gray) * rng.uniform(1 - saturation,
                                                1 + saturation)
    return np.clip(img, 0, 255)


def classification_train_transform(out_hw=(224, 224), seed: int = 0):
    """Batch-level wrapper over train_image_transform for
    DataLoader(transform=...)."""
    one = train_image_transform(out_hw, seed)

    def fn(batch: Dict) -> Dict:
        return {**batch, "image": np.stack([one(i)
                                            for i in batch["image"]])}
    return fn


_THREAD_SEED = itertools.count()


def thread_rng(local, seed: int) -> np.random.Generator:
    """Per-thread Generator for transforms running inside a worker pool
    (numpy Generators are not thread-safe). Each thread draws a unique
    counter value, so streams never collide — masked thread idents do
    (glibc reuses low address bits across pool threads)."""
    rng = getattr(local, "rng", None)
    if rng is None:
        rng = local.rng = np.random.default_rng(
            (seed, next(_THREAD_SEED)))
    return rng


def train_image_transform(out_hw=(224, 224), seed: int = 0):
    """Per-IMAGE augment closure for folder_source(transform=...) — runs
    inside the loader's decode worker pool."""
    import threading
    local = threading.local()

    def fn(img: np.ndarray) -> np.ndarray:
        rng = thread_rng(local, seed)
        img = random_resized_crop(img, rng, out_hw)
        img = random_flip_lr(img, rng)
        img = color_jitter(img, rng)
        return normalize(img)
    return fn


def light_image_transform(out_hw=(224, 224), seed: int = 0,
                          shift_frac: float = 0.1, flip: bool = False):
    """Per-IMAGE light augment: resize + random shift (pad-and-crop) —
    the small-image recipe (CIFAR/digits style) where ImageNet-strength
    RandomResizedCrop would destroy the object."""
    import threading
    local = threading.local()

    def fn(img: np.ndarray) -> np.ndarray:
        rng = thread_rng(local, seed)
        img = resize_bilinear(img, out_hw)
        ph = max(int(out_hw[0] * shift_frac), 1)
        pw = max(int(out_hw[1] * shift_frac), 1)
        img = np.pad(img, [(ph, ph), (pw, pw), (0, 0)], mode="edge")
        y0 = rng.integers(0, 2 * ph + 1)
        x0 = rng.integers(0, 2 * pw + 1)
        img = img[y0:y0 + out_hw[0], x0:x0 + out_hw[1]]
        if flip:
            img = random_flip_lr(img, rng)
        return normalize(img)
    return fn


def get_train_transform(preset: str, out_hw=(224, 224), seed: int = 0):
    """Augmentation preset registry for the classification pipeline:
    'imagenet' (RRC+flip+jitter), 'light' (resize+shift), 'none'."""
    if preset == "imagenet":
        return train_image_transform(out_hw, seed)
    if preset == "light":
        return light_image_transform(out_hw, seed)
    if preset == "none":
        return eval_image_transform(out_hw, crop_frac=1.0)
    raise ValueError(f"unknown augment preset {preset!r}")


def eval_image_transform(out_hw=(224, 224), crop_frac=0.875):
    """Per-IMAGE resize + center-crop + normalize closure."""
    def fn(img: np.ndarray) -> np.ndarray:
        rh, rw = int(out_hw[0] / crop_frac), int(out_hw[1] / crop_frac)
        img = resize_bilinear(img, (rh, rw))
        y0 = (rh - out_hw[0]) // 2
        x0 = (rw - out_hw[1]) // 2
        return normalize(img[y0:y0 + out_hw[0], x0:x0 + out_hw[1]])
    return fn


def classification_eval_transform(out_hw=(224, 224), crop_frac=0.875):
    """Batch-level wrapper over eval_image_transform."""
    one = eval_image_transform(out_hw, crop_frac)

    def fn(batch: Dict) -> Dict:
        return {**batch, "image": np.stack([one(i)
                                            for i in batch["image"]])}
    return fn
