"""Health surface: the one readiness/overload verdict for a serving
process.

``GET /healthz`` (tools/serve.py) answers the two questions an operator
or load balancer actually asks, from state the stack already tracks —
no device work, no syncs, safe to poll at any rate:

- **Ready?** The engine is *warm* when every batch bucket has its AOT
  executable (``compile_count >= len(buckets)``) — before that, a
  request would pay an XLA compile, so the process reports 503 and the
  balancer keeps traffic away until warmup finishes.
- **Degraded?** The admission policy's shed verdict on the live queue
  depth (``AdmissionController.overloaded``). A shedding server still
  answers — it is maximizing throughput, not down — but it reports 503
  so upstream can drain toward healthier replicas before the queue
  converts overload into rejections.

The payload carries the operating numbers next to the verdict (queue
depth, e2e p99, reject count, bucket table) so a 503 is diagnosable
from the probe alone.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

__all__ = ["health"]


def health(engine, batcher=None) -> Tuple[int, Dict[str, Any]]:
    """(http_status, payload) for one engine (+ optional batcher).

    200 "ready": warm engine, not shedding. 503 "warming" until every
    bucket is compiled; 503 "degraded" while admission sheds. Pure host
    reads — never compiles, never syncs the device."""
    warm = engine.compile_count >= len(engine.buckets)
    depth = batcher.queue_depth if batcher is not None else 0
    shed = (batcher.admission.overloaded(depth)
            if batcher is not None else False)
    status = "ready" if warm and not shed else (
        "warming" if not warm else "degraded")
    payload: Dict[str, Any] = {
        "status": status,
        "engine_warm": warm,
        "queue_depth": depth,
        "shed": shed,
        "model": engine.name,
        "task": engine.task,
        "buckets": list(engine.buckets),
    }
    if batcher is not None:
        payload["e2e_ms_p99"] = batcher.telemetry.latency_ms("e2e")["p99"]
        payload["rejected"] = batcher.telemetry.rejected
    return (200 if status == "ready" else 503), payload
