"""Health surface: the one readiness/overload verdict for a serving
process.

``GET /healthz`` (tools/serve.py) answers the two questions an operator
or load balancer actually asks, from state the stack already tracks —
no device work, no syncs, safe to poll at any rate:

- **Ready?** The engine is *warm* when every batch bucket has its AOT
  executable (``compile_count >= len(buckets)``) — before that, a
  request would pay an XLA compile, so the process reports 503 and the
  balancer keeps traffic away until warmup finishes.
- **Degraded?** The admission policy's shed verdict on the live queue
  depth (``AdmissionController.overloaded``). A shedding server still
  answers — it is maximizing throughput, not down — but it reports 503
  so upstream can drain toward healthier replicas before the queue
  converts overload into rejections.

The payload carries the operating numbers next to the verdict (queue
depth, e2e p99, reject count, bucket table) so a 503 is diagnosable
from the probe alone.

**Wedged?** (PR 7) ``DispatchWatch`` applies the supervisor's
``WedgeDetector`` grammar to the serving path: requests queued (or a
batch in flight) while the dispatched-batch counter is frozen past the
deadline means the device stream is stuck — the worst serving failure
mode, because the process still accepts connections. An idle server
(empty queue, dispatch thread parked) ticks the detector's activity
itself, so quiet traffic never reads as wedged.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

from ..elastic.supervisor import WedgeDetector

__all__ = ["health", "zoo_health", "DispatchWatch"]


class DispatchWatch:
    """Wedge verdict over a ``MicroBatcher``'s dispatch progress.

    Each ``verdict()`` call feeds the detector the dispatched-batch
    counter, plus a synthetic idle tick whenever there is genuinely
    nothing to do — so only "work waiting, counter frozen for
    ``deadline_s``" ever reads ``"wedged"``. Host-only; safe to poll
    from the healthz handler at any rate."""

    def __init__(self, batcher, deadline_s: float = 30.0):
        self.batcher = batcher
        self.detector = WedgeDetector(deadline_s)
        self._idle = 0

    def verdict(self, now: Optional[float] = None) -> str:
        if self.batcher.queue_depth == 0 and not self.batcher.busy:
            self._idle += 1           # idle is progress, not a wedge
        activity = int(self.batcher.dispatched) + self._idle
        return self.detector.observe(None, activity, now=now)

    def stalled_for(self, now: Optional[float] = None) -> float:
        return self.detector.stalled_for(now)


def health(engine, batcher=None,
           wedge: Optional[DispatchWatch] = None
           ) -> Tuple[int, Dict[str, Any]]:
    """(http_status, payload) for one engine (+ optional batcher).

    200 "ready": warm engine, not shedding. 503 "warming" until every
    bucket is compiled; 503 "degraded" while admission sheds; 503
    "draining" while the batcher refuses new work but still flushes its
    lanes (the controller's drain-and-requeue window — routers must
    stop sending, in-flight clients still get answers); 503 "standby"
    while the batcher is a fully-warmed spare awaiting promotion
    (unroutable, but one ``/admin/promote`` flip from "ready"); 503
    "wedged" (highest precedence) when ``wedge`` reports a frozen
    dispatch stream. Pure host reads — never compiles, never syncs the
    device."""
    warm = engine.compile_count >= len(engine.buckets)
    depth = batcher.queue_depth if batcher is not None else 0
    shed = (batcher.admission.overloaded(depth)
            if batcher is not None else False)
    wedged = wedge is not None and wedge.verdict() == "wedged"
    draining = bool(getattr(batcher, "draining", False))
    standby = bool(getattr(batcher, "standby", False))
    status = "wedged" if wedged else (
        "draining" if draining else (
            "standby" if standby else (
                "ready" if warm and not shed else (
                    "warming" if not warm else "degraded"))))
    payload: Dict[str, Any] = {
        "status": status,
        "standby": standby,
        "engine_warm": warm,
        "queue_depth": depth,
        "shed": shed,
        "model": engine.name,
        "task": engine.task,
        "buckets": list(engine.buckets),
        "wedged": wedged,
        "draining": draining,
        "drained": bool(getattr(batcher, "drained", False)),
    }
    if batcher is not None:
        payload["e2e_ms_p99"] = batcher.telemetry.latency_ms("e2e")["p99"]
        payload["rejected"] = batcher.telemetry.rejected
        payload["dispatched"] = getattr(batcher, "dispatched", 0)
    if wedged:
        payload["stalled_s"] = round(wedge.stalled_for(), 3)
    return (200 if status == "ready" else 503), payload


def zoo_health(zoo, batcher=None,
               wedge: Optional[DispatchWatch] = None
               ) -> Tuple[int, Dict[str, Any]]:
    """(http_status, payload) for a multi-tenant zoo process.

    200 "ready" when no tenant is mid-load and no lane sheds — cold
    (registered/evicted) tenants do NOT block readiness, because a
    request for one triggers a hot-load rather than an error. 503
    "warming" while any load is in flight, "degraded" while any lane
    sheds, "draining" while the batcher flushes toward a requeue,
    "wedged" (precedence) on a frozen dispatch stream. The
    payload carries the full per-model state table (warm/evicted/
    loading, bytes, quotas, queue depths) so per-tenant posture is
    diagnosable from the probe alone. Pure host reads."""
    zs = zoo.stats()
    models: Dict[str, Any] = {}
    any_loading = False
    any_shed = False
    for alias, row in zs["models"].items():
        entry = dict(row)
        if batcher is not None:
            depth = batcher.lane_depth(alias)
            entry["queue_depth"] = depth
            entry["shed"] = zoo.admission_for(alias).overloaded(depth)
            any_shed = any_shed or entry["shed"]
            lane_tel = batcher.lane_telemetry(alias)
            if lane_tel is not None:
                entry["e2e_ms_p99"] = lane_tel.latency_ms("e2e")["p99"]
                entry["rejected"] = lane_tel.rejected
        any_loading = any_loading or row["state"] == "loading"
        models[alias] = entry
    wedged = wedge is not None and wedge.verdict() == "wedged"
    draining = bool(getattr(batcher, "draining", False))
    standby = bool(getattr(batcher, "standby", False))
    status = "wedged" if wedged else (
        "draining" if draining else (
            "standby" if standby else (
                "warming" if any_loading else (
                    "degraded" if any_shed else "ready"))))
    payload: Dict[str, Any] = {
        "status": status,
        "standby": standby,
        "zoo": {k: zs[k] for k in ("registered", "resident", "loads",
                                   "evictions", "rejected_loads",
                                   "alert_frac")},
        "models": models,
        "wedged": wedged,
        "draining": draining,
        "drained": bool(getattr(batcher, "drained", False)),
    }
    if batcher is not None:
        payload["queue_depth"] = batcher.queue_depth
        payload["dispatched"] = getattr(batcher, "dispatched", 0)
    if wedged:
        payload["stalled_s"] = round(wedge.stalled_for(), 3)
    return (200 if status == "ready" else 503), payload
