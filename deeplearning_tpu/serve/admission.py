"""Admission control: bounded queues, deadlines, and overload shedding.

A serving queue with no admission policy converts overload into
unbounded latency — every request is eventually served, long after its
caller stopped waiting. This module makes the three overload decisions
explicit and testable, decoupled from the batcher mechanics:

- **Backpressure**: the queue has a hard depth bound. A submit against a
  full queue raises ``Rejected`` carrying a ``retry_after_s`` hint
  (estimated from the recent drain rate) instead of enqueueing — the
  client sees a fast 429, not a slow timeout.
- **Deadlines**: every request may carry an absolute deadline. The
  dispatcher drops expired requests *before* padding them into an
  executable (``DeadlineExceeded`` on the future) — device cycles are
  never spent on an answer nobody is waiting for.
- **Degradation**: past ``shed_threshold`` queued requests the policy
  stops optimizing latency and targets the LARGEST batch bucket only
  (max throughput per dispatch), reporting the shed via telemetry so
  operators see the mode switch, not just a p99 cliff.
"""

from __future__ import annotations

import time
from typing import Optional, Sequence

__all__ = ["AdmissionController", "Rejected", "DeadlineExceeded"]


class Rejected(Exception):
    """Queue-full backpressure: retry after ``retry_after_s`` seconds."""

    def __init__(self, depth: int, retry_after_s: float):
        self.depth = depth
        self.retry_after_s = retry_after_s
        super().__init__(
            f"serve queue full ({depth} pending); "
            f"retry after {retry_after_s:.3f}s")


class DeadlineExceeded(Exception):
    """The request's deadline passed while it waited in the queue."""


class AdmissionController:
    """Pure policy object consulted by the batcher (no threads, no
    queue ownership — everything takes the observed depth as input, so
    tests drive it directly).

    - ``max_queue``: hard pending-request bound (backpressure trigger).
    - ``shed_threshold``: depth at which batching degrades to
      largest-bucket-only dispatch (default: the largest bucket — once a
      full max-throughput batch is waiting, padding smaller buckets only
      burns cycles).
    - ``default_timeout_s``: deadline applied to requests that don't
      carry one (None = wait forever).
    """

    def __init__(self, buckets: Sequence[int], *, max_queue: int = 256,
                 shed_threshold: Optional[int] = None,
                 default_timeout_s: Optional[float] = None):
        self.buckets = tuple(sorted(int(b) for b in buckets))
        if not self.buckets:
            raise ValueError("admission needs at least one batch bucket")
        self.max_queue = int(max_queue)
        self.shed_threshold = (int(shed_threshold) if shed_threshold
                               is not None else self.buckets[-1])
        self.default_timeout_s = default_timeout_s
        # drain-rate estimate for retry_after hints (EWMA of req/s seen
        # at each dispatch; updated by the batcher)
        self._drain_rate = 0.0

    # ----------------------------------------------------- backpressure
    def admit(self, queue_depth: int) -> None:
        """Raise ``Rejected`` when the queue cannot take one more."""
        if queue_depth >= self.max_queue:
            raise Rejected(queue_depth, self.retry_after_s(queue_depth))

    def retry_after_s(self, queue_depth: int) -> float:
        """Time until the backlog plausibly has room: depth over the
        observed drain rate, clamped to a sane hint window."""
        if self._drain_rate > 0:
            return min(max(queue_depth / self._drain_rate, 1e-3), 30.0)
        return 0.05     # no throughput observed yet: cheap quick retry

    def note_drained(self, n: int, seconds: float) -> None:
        """EWMA drain-rate update from the batcher: ``n`` requests left
        the queue over ``seconds`` of dispatch."""
        if seconds <= 0:
            return
        rate = n / seconds
        self._drain_rate = (rate if self._drain_rate == 0.0
                            else 0.8 * self._drain_rate + 0.2 * rate)

    # -------------------------------------------------------- deadlines
    def deadline_for(self, timeout_s: Optional[float],
                     now: Optional[float] = None) -> Optional[float]:
        """Absolute deadline for a new request (None = no deadline)."""
        timeout_s = (timeout_s if timeout_s is not None
                     else self.default_timeout_s)
        if timeout_s is None:
            return None
        return (now if now is not None else time.perf_counter()) \
            + timeout_s

    @staticmethod
    def expired(deadline: Optional[float],
                now: Optional[float] = None) -> bool:
        if deadline is None:
            return False
        return (now if now is not None else time.perf_counter()) \
            >= deadline

    # ------------------------------------------------------ degradation
    def overloaded(self, queue_depth: int) -> bool:
        return queue_depth >= self.shed_threshold

    def target_bucket(self, queue_depth: int) -> int:
        """Batch size the dispatcher should accumulate toward. Normal
        mode: the smallest bucket admitting the current backlog (+1 for
        the request already popped), so light traffic dispatches
        immediately at small buckets. Overload: the largest bucket only."""
        if self.overloaded(queue_depth):
            return self.buckets[-1]
        want = queue_depth + 1
        for b in self.buckets:
            if b >= want:
                return b
        return self.buckets[-1]
