"""Admission control: bounded queues, deadlines, and overload shedding.

A serving queue with no admission policy converts overload into
unbounded latency — every request is eventually served, long after its
caller stopped waiting. This module makes the three overload decisions
explicit and testable, decoupled from the batcher mechanics:

- **Backpressure**: the queue has a hard depth bound. A submit against a
  full queue raises ``Rejected`` carrying a ``retry_after_s`` hint
  (estimated from the recent drain rate) instead of enqueueing — the
  client sees a fast 429, not a slow timeout.
- **Deadlines**: every request may carry an absolute deadline. The
  dispatcher drops expired requests *before* padding them into an
  executable (``DeadlineExceeded`` on the future) — device cycles are
  never spent on an answer nobody is waiting for.
- **Degradation**: past ``shed_threshold`` queued requests the policy
  stops optimizing latency and targets the LARGEST batch bucket only
  (max throughput per dispatch), reporting the shed via telemetry so
  operators see the mode switch, not just a p99 cliff.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional, Sequence

__all__ = ["Ewma", "AdmissionController", "TenantAdmission", "Rejected",
           "DeadlineExceeded"]


class Ewma:
    """Exponentially-weighted moving average with first-sample seeding:
    the first ``update`` sets the value outright, later ones fold in at
    ``alpha`` — the "sustained, not instantaneous" smoothing used for
    the admission drain rate and the fleet controller's scaling signals
    (one smoothing rule, one set of tests)."""

    __slots__ = ("alpha", "value", "samples")

    def __init__(self, alpha: float = 0.2):
        if not 0.0 < alpha <= 1.0:
            raise ValueError(f"alpha must be in (0, 1], got {alpha}")
        self.alpha = float(alpha)
        self.value = 0.0
        self.samples = 0

    def update(self, sample: float) -> float:
        sample = float(sample)
        self.value = (sample if self.samples == 0
                      else (1.0 - self.alpha) * self.value
                      + self.alpha * sample)
        self.samples += 1
        return self.value

    def reset(self) -> None:
        self.value = 0.0
        self.samples = 0


class Rejected(Exception):
    """Queue-full backpressure: retry after ``retry_after_s`` seconds.

    ``model`` names the tenant whose queue rejected the request (None in
    single-model serving); ``reason`` distinguishes a full per-model
    queue (``"queue_full"``) from zoo capacity pressure with nothing
    evictable (``"hbm_pressure"``). Both surface in the 429 body."""

    def __init__(self, depth: int, retry_after_s: float,
                 model: Optional[str] = None,
                 reason: str = "queue_full"):
        self.depth = depth
        self.retry_after_s = retry_after_s
        self.model = model
        self.reason = reason
        who = f"model {model!r} " if model else ""
        super().__init__(
            f"serve {who}{reason.replace('_', ' ')} ({depth} pending); "
            f"retry after {retry_after_s:.3f}s")


class DeadlineExceeded(Exception):
    """The request's deadline passed while it waited in the queue."""


class AdmissionController:
    """Pure policy object consulted by the batcher (no threads, no
    queue ownership — everything takes the observed depth as input, so
    tests drive it directly).

    - ``max_queue``: hard pending-request bound (backpressure trigger).
    - ``shed_threshold``: depth at which batching degrades to
      largest-bucket-only dispatch (default: the largest bucket — once a
      full max-throughput batch is waiting, padding smaller buckets only
      burns cycles).
    - ``default_timeout_s``: deadline applied to requests that don't
      carry one (None = wait forever).
    """

    def __init__(self, buckets: Sequence[int], *, max_queue: int = 256,
                 shed_threshold: Optional[int] = None,
                 default_timeout_s: Optional[float] = None,
                 model: Optional[str] = None):
        self.buckets = tuple(sorted(int(b) for b in buckets))
        if not self.buckets:
            raise ValueError("admission needs at least one batch bucket")
        self.max_queue = int(max_queue)
        self.shed_threshold = (int(shed_threshold) if shed_threshold
                               is not None else self.buckets[-1])
        self.default_timeout_s = default_timeout_s
        self.model = model
        # drain-rate estimate for retry_after hints (EWMA of req/s seen
        # at each dispatch; updated by the batcher). Per-controller
        # state: in multi-tenant serving every model owns one controller
        # (see TenantAdmission), so a 429's retry_after always quotes
        # the TARGET model's drain — never a hotter neighbor's.
        self._drain = Ewma(alpha=0.2)

    # ----------------------------------------------------- backpressure
    def admit(self, queue_depth: int) -> None:
        """Raise ``Rejected`` when the queue cannot take one more."""
        if queue_depth >= self.max_queue:
            raise Rejected(queue_depth, self.retry_after_s(queue_depth),
                           model=self.model)

    @property
    def _drain_rate(self) -> float:
        return self._drain.value

    def retry_after_s(self, queue_depth: int) -> float:
        """Time until the backlog plausibly has room: depth over the
        observed drain rate, clamped to a sane hint window."""
        if self._drain_rate > 0:
            return min(max(queue_depth / self._drain_rate, 1e-3), 30.0)
        return 0.05     # no throughput observed yet: cheap quick retry

    def note_drained(self, n: int, seconds: float) -> None:
        """EWMA drain-rate update from the batcher: ``n`` requests left
        the queue over ``seconds`` of dispatch."""
        if seconds <= 0:
            return
        self._drain.update(n / seconds)

    # -------------------------------------------------------- deadlines
    def deadline_for(self, timeout_s: Optional[float],
                     now: Optional[float] = None) -> Optional[float]:
        """Absolute deadline for a new request (None = no deadline)."""
        timeout_s = (timeout_s if timeout_s is not None
                     else self.default_timeout_s)
        if timeout_s is None:
            return None
        return (now if now is not None else time.perf_counter()) \
            + timeout_s

    @staticmethod
    def expired(deadline: Optional[float],
                now: Optional[float] = None) -> bool:
        if deadline is None:
            return False
        return (now if now is not None else time.perf_counter()) \
            >= deadline

    # ------------------------------------------------------ degradation
    def overloaded(self, queue_depth: int) -> bool:
        return queue_depth >= self.shed_threshold

    def target_bucket(self, queue_depth: int) -> int:
        """Batch size the dispatcher should accumulate toward. Normal
        mode: the smallest bucket admitting the current backlog (+1 for
        the request already popped), so light traffic dispatches
        immediately at small buckets. Overload: the largest bucket only."""
        if self.overloaded(queue_depth):
            return self.buckets[-1]
        want = queue_depth + 1
        for b in self.buckets:
            if b >= want:
                return b
        return self.buckets[-1]


class TenantAdmission:
    """Per-tenant admission for multi-model serving: one
    :class:`AdmissionController` per model, each with its own queue
    quota, shed threshold, deadline default — and its own EWMA drain
    rate, which is the bugfix over sharing one controller: a cold
    tenant's ``Rejected.retry_after_s`` is computed from that tenant's
    OWN drain history, not from whichever hot neighbor last dispatched.

    ``configure`` registers a model's policy (the zoo does this at
    ``register`` time); ``for_model`` is the per-request lookup, falling
    back to a default-policy controller for unconfigured models so bare
    batcher usage keeps working."""

    def __init__(self, *, default_buckets: Sequence[int] = (1, 8, 32, 128),
                 default_max_queue: int = 256,
                 default_timeout_s: Optional[float] = None):
        self._lock = threading.Lock()
        self._controllers: Dict[str, AdmissionController] = {}
        self.default_buckets = tuple(sorted(int(b)
                                            for b in default_buckets))
        self.default_max_queue = int(default_max_queue)
        self.default_timeout_s = default_timeout_s

    def configure(self, model: str, buckets: Sequence[int], *,
                  max_queue: Optional[int] = None,
                  shed_threshold: Optional[int] = None,
                  default_timeout_s: Optional[float] = None
                  ) -> AdmissionController:
        ctrl = AdmissionController(
            buckets,
            max_queue=(max_queue if max_queue is not None
                       else self.default_max_queue),
            shed_threshold=shed_threshold,
            default_timeout_s=(default_timeout_s
                               if default_timeout_s is not None
                               else self.default_timeout_s),
            model=model)
        with self._lock:
            self._controllers[model] = ctrl
        return ctrl

    def for_model(self, model: str) -> AdmissionController:
        ctrl = self._controllers.get(model)      # GIL-safe fast path
        if ctrl is None:
            with self._lock:
                ctrl = self._controllers.get(model)
            if ctrl is None:
                ctrl = self.configure(model, self.default_buckets)
        return ctrl

    def models(self) -> Dict[str, AdmissionController]:
        with self._lock:
            return dict(self._controllers)
