"""ModelZoo: N model sessions in one serving process, hot load/evict.

The reference repo is a ~40-project zoo where every project runs
standalone; the production shape is the inverse — ONE fleet process
holding many resident :class:`~.engine.InferenceEngine` sessions and
routing mixed traffic across them. The zoo is the residency manager
that makes that safe:

- **Registry-driven hot load.** ``register()`` records a model spec
  (engine kwargs + quota policy) without touching the device. The first
  request — or an admin load call — builds the engine on a background
  ``zoo-load-<alias>`` thread; the per-model state flips to ``"warm"``
  only after the constructor returns, i.e. after every batch bucket's
  AOT warmup landed through ``tracked_compile``. Until then the
  dispatcher skips the tenant's lane, so no request ever pays an XLA
  compile.
- **Per-tenant contracts.** Every alias owns its bucket family and its
  engine's ``trace_count``/``compile_count`` — the zero-recompiles-
  after-warmup invariant holds per model, interleaved traffic or not
  (``analysis/jaxpr.py`` ``zoo_multimodel`` audits exactly this). Every
  alias also owns one ``AdmissionController`` (via ``TenantAdmission``),
  so queue quotas, deadlines, shed thresholds, and the EWMA drain rate
  behind ``retry_after_s`` are all per-model.
- **HBM-pressure LRU eviction.** Before a load, the zoo projects the
  model's bytes onto the worst device's ``usage_frac`` from
  ``obs/xla.hbm_snapshot`` (tests stub the snapshot; CPU backends with
  no ``memory_stats`` report no pressure). Crossing the alert fraction
  evicts the least-recently-used idle model first; when nothing is
  evictable the load is refused with ``Rejected`` (HTTP 429) instead of
  OOMing the fleet.
- **Density.** ``weight_quant="int8"`` per spec stores resident weights
  as block-scaled int8 (``parallel/collectives.py`` quantize machinery,
  dequantized inside each executable) — ~4x more models per chip.

Host-side manager: the request path through a warm engine does no zoo
work beyond a dict lookup and an LRU timestamp. This module is DLT100
hot-path covered.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from ..obs import flight
from ..obs import metrics as obs_metrics
from ..obs import threads as obs_threads
from .admission import AdmissionController, Rejected, TenantAdmission

__all__ = ["ModelZoo", "ModelSpec"]

_DEFAULT_BUCKETS = (1, 8, 32, 128)
_DEFAULT_ALERT_FRAC = 0.9


class ModelSpec:
    """One registered tenant: how to build its engine + its quotas."""

    __slots__ = ("alias", "model_name", "engine_kwargs", "weight_quant",
                 "max_queue", "shed_threshold", "default_timeout_s",
                 "est_bytes", "engine_factory")

    def __init__(self, alias: str, model_name: Optional[str], *,
                 weight_quant: str = "fp32",
                 max_queue: int = 256,
                 shed_threshold: Optional[int] = None,
                 default_timeout_s: Optional[float] = None,
                 est_bytes: Optional[int] = None,
                 engine_factory: Optional[Callable[[], Any]] = None,
                 **engine_kwargs: Any):
        self.alias = alias
        self.model_name = model_name
        self.engine_kwargs = dict(engine_kwargs)
        self.weight_quant = weight_quant
        self.max_queue = int(max_queue)
        self.shed_threshold = shed_threshold
        self.default_timeout_s = default_timeout_s
        self.est_bytes = est_bytes
        self.engine_factory = engine_factory

    @property
    def image_size(self) -> int:
        return int(self.engine_kwargs.get("image_size", 224))

    @property
    def buckets(self) -> tuple:
        return tuple(sorted(int(b) for b in self.engine_kwargs.get(
            "batch_buckets", _DEFAULT_BUCKETS)))


class ModelZoo:
    """Residency manager for N servable models in one process.

    States per alias: ``registered`` → ``loading`` → ``warm`` →
    (``evicted`` → ``loading`` → ``warm`` ...), with ``failed`` holding
    the last load error. ``request()`` is the submit-path entry: it
    returns immediately for a warm model, kicks a background load for a
    cold one (possibly evicting the LRU idle model first), and raises
    ``Rejected`` when HBM pressure leaves nothing evictable.
    """

    def __init__(self, *, alert_frac: Optional[float] = None,
                 hbm_snapshot_fn: Optional[Callable[[], Dict]] = None,
                 max_resident: Optional[int] = None):
        self._lock = threading.RLock()
        self._specs: Dict[str, ModelSpec] = {}
        self._engines: Dict[str, Any] = {}
        self._state: Dict[str, str] = {}
        self._last_used: Dict[str, float] = {}
        self._in_flight: Dict[str, int] = {}     # batches mid-dispatch
        self._resident_bytes: Dict[str, int] = {}  # survives evict
        self._load_threads: Dict[str, threading.Thread] = {}
        self._load_seconds: Dict[str, float] = {}
        self.load_errors: Dict[str, str] = {}
        self.admission = TenantAdmission()
        self.loads = 0
        self.evictions = 0
        self.rejected_loads = 0
        self._alert_frac = alert_frac
        self._hbm_fn = hbm_snapshot_fn
        self.max_resident = max_resident

    # -------------------------------------------------------- registry
    def register(self, alias: str, model_name: Optional[str] = None, *,
                 engine: Any = None, **spec_kwargs: Any) -> str:
        """Register one tenant. ``model_name`` + engine kwargs describe
        a lazy build; ``engine=`` installs a prebuilt (already warm)
        session immediately — the test seam, and the path for callers
        that built their engine elsewhere. ``engine_factory=`` defers to
        a zero-arg callable per (re)load."""
        if engine is not None and "engine_factory" not in spec_kwargs:
            spec_kwargs.setdefault("batch_buckets",
                                   tuple(engine.buckets))
            spec_kwargs.setdefault(
                "image_size", getattr(engine, "image_size", 224))
        spec = ModelSpec(alias, model_name, **spec_kwargs)
        with self._lock:
            if alias in self._specs:
                raise ValueError(f"model {alias!r} already registered")
            self._specs[alias] = spec
            self._state[alias] = "registered"
            self._in_flight[alias] = 0
            self.admission.configure(
                alias, spec.buckets, max_queue=spec.max_queue,
                shed_threshold=spec.shed_threshold,
                default_timeout_s=spec.default_timeout_s)
            if engine is not None:
                self._install(alias, engine, seconds=0.0)
        return alias

    def models(self) -> List[str]:
        with self._lock:
            return sorted(self._specs)

    def spec(self, alias: str) -> ModelSpec:
        spec = self._specs.get(alias)
        if spec is None:
            raise KeyError(f"model {alias!r} not registered "
                           f"(have {sorted(self._specs)})")
        return spec

    def state(self, alias: str) -> str:
        self.spec(alias)
        return self._state[alias]

    def image_size(self, alias: str) -> int:
        with self._lock:
            eng = self._engines.get(alias)
            if eng is not None:
                return int(eng.image_size)
            return self.spec(alias).image_size

    def admission_for(self, alias: str) -> AdmissionController:
        self.spec(alias)
        return self.admission.for_model(alias)

    # ------------------------------------------------------ request path
    def engine(self, alias: str) -> Optional[Any]:
        """The warm engine for ``alias``, or None while cold/loading —
        the dispatcher's per-batch lookup (one dict read)."""
        with self._lock:
            if self._state.get(alias) == "warm":
                return self._engines[alias]
            return None

    def touch(self, alias: str) -> None:
        # under the (reentrant) lock: also written by loader threads
        # via _install, and read by the eviction victim scan — an
        # unguarded write here was the textbook DLT200
        with self._lock:
            self._last_used[alias] = time.monotonic()

    def mark_dispatch(self, alias: str, delta: int) -> None:
        """Dispatch-thread bracket around a running batch: an engine
        with a batch in flight is never an eviction victim."""
        with self._lock:
            self._in_flight[alias] = max(
                0, self._in_flight.get(alias, 0) + delta)
        if delta > 0:
            self.touch(alias)

    def request(self, alias: str) -> str:
        """Submit-path hook: make sure ``alias`` is warm or on its way.
        Returns the state after the call ("warm" | "loading"). Raises
        ``Rejected`` when a needed load cannot be admitted (HBM
        pressure, nothing evictable) and ``KeyError`` for unregistered
        aliases."""
        with self._lock:
            st = self.state(alias)
            if st == "warm":
                self.touch(alias)
                return "warm"
            if st == "loading":
                return "loading"
            # registered / evicted / failed: (re)start the load
            self._ensure_capacity(alias)
            self._start_load(alias)
            return "loading"

    # ------------------------------------------------------------- load
    def load(self, alias: str, wait: bool = True,
             timeout_s: float = 600.0) -> str:
        """Admin load: kick (or join) the background load. With
        ``wait=True`` blocks until the warm flag flips (or the load
        fails)."""
        state = self.request(alias)
        if not wait or state == "warm":
            return self.state(alias)
        with self._lock:
            thread = self._load_threads.get(alias)
        if thread is not None:
            thread.join(timeout_s)
        return self.state(alias)

    def _start_load(self, alias: str) -> None:
        thread = self._load_threads.get(alias)
        if thread is not None and thread.is_alive():
            return
        self._state[alias] = "loading"
        thread = obs_threads.spawn(self._do_load, args=(alias,),
                                   name=f"zoo-load-{alias}",
                                   daemon=True, start=False)
        self._load_threads[alias] = thread
        thread.start()

    def _build_engine(self, spec: ModelSpec) -> Any:
        if spec.engine_factory is not None:
            return spec.engine_factory()
        from .engine import InferenceEngine
        if spec.model_name is None:
            raise ValueError(f"model {spec.alias!r} registered without "
                             "model_name, engine, or engine_factory")
        # precompile=True: the constructor runs every bucket's AOT
        # warmup through tracked_compile before it returns, which is
        # what lets _do_load flip the warm flag atomically after it
        return InferenceEngine(spec.model_name,
                               weight_quant=spec.weight_quant,
                               precompile=True, **spec.engine_kwargs)

    def _do_load(self, alias: str) -> None:
        spec = self.spec(alias)
        t0 = time.perf_counter()
        try:
            engine = self._build_engine(spec)
        except BaseException as e:  # noqa: BLE001 - surfaced in stats
            with self._lock:
                self._state[alias] = "failed"
                self.load_errors[alias] = repr(e)
            flight.record("zoo_load_failed", model=alias, error=repr(e))
            return
        seconds = time.perf_counter() - t0
        with self._lock:
            self._install(alias, engine, seconds=seconds)
        flight.record("zoo_load", model=alias,
                      seconds=round(seconds, 3),
                      bytes=self._resident_bytes.get(alias, 0),
                      weight_quant=spec.weight_quant)

    def _install(self, alias: str, engine: Any, seconds: float) -> None:
        """Under the lock: make a fully-warmed engine servable. This is
        the ONLY place the warm flag flips on — strictly after every
        bucket executable exists, never mid-warmup."""
        self._engines[alias] = engine
        try:
            self._resident_bytes[alias] = int(engine.variables_nbytes())
        except Exception:  # noqa: BLE001 - fakes may not implement it
            self._resident_bytes.setdefault(alias, 0)
        self._state[alias] = "warm"
        self._load_seconds[alias] = seconds
        self.load_errors.pop(alias, None)
        self.touch(alias)
        self.loads += 1
        obs_metrics.inc("dltpu_zoo_loads_total")
        obs_metrics.set_gauge("dltpu_zoo_resident_models",
                              float(len(self._engines)))

    # ------------------------------------------------------------ evict
    def evict(self, alias: str) -> bool:
        """Drop ``alias``'s engine (resident weights + executables) —
        False when it isn't warm or has a batch in flight. The spec
        stays registered: the next request hot-reloads it fresh (new
        engine, new executables — stale buckets can never serve)."""
        with self._lock:
            return self._evict_locked(alias)

    def _evict_locked(self, alias: str) -> bool:
        if self._state.get(alias) != "warm":
            return False
        if self._in_flight.get(alias, 0) > 0:
            return False
        del self._engines[alias]
        self._state[alias] = "evicted"
        self.evictions += 1
        obs_metrics.inc("dltpu_zoo_evictions_total")
        obs_metrics.set_gauge("dltpu_zoo_resident_models",
                              float(len(self._engines)))
        flight.record("zoo_evict", model=alias,
                      bytes=self._resident_bytes.get(alias, 0))
        return True

    def demote_residency(self, alias: str) -> bool:
        """Brownout step 2: re-pin ``alias`` to block-scaled int8
        residency. Flips the spec's ``weight_quant`` and evicts the
        fp32-resident engine so the next request hot-reloads it ~4x
        denser; a no-op (False) when the tenant is already int8 or not
        registered. Best-effort — a load in flight just means the
        eviction lands on a later call."""
        with self._lock:
            spec = self._specs.get(alias)
            if spec is None or spec.weight_quant == "int8":
                return False
            spec.weight_quant = "int8"
            self._evict_locked(alias)
        flight.record("zoo_demote", model=alias, weight_quant="int8")
        return True

    def _lru_victim(self, exclude: str) -> Optional[str]:
        candidates = [a for a, st in self._state.items()
                      if st == "warm" and a != exclude
                      and self._in_flight.get(a, 0) == 0]
        if not candidates:
            return None
        return min(candidates,
                   key=lambda a: self._last_used.get(a, 0.0))

    # --------------------------------------------------------- pressure
    def alert_frac(self) -> float:
        if self._alert_frac is not None:
            return float(self._alert_frac)
        raw = os.environ.get("DLTPU_HBM_ALERT_FRAC")
        try:
            return float(raw) if raw else _DEFAULT_ALERT_FRAC
        except ValueError:
            return _DEFAULT_ALERT_FRAC

    def hbm_pressure(self) -> Dict[str, Any]:
        """Worst-device {usage_frac, bytes_in_use, bytes_limit} from the
        snapshot hook (``obs/xla.hbm_snapshot`` unless a test stubbed
        it). Backends that report no ``memory_stats`` — CPU — yield
        ``usage_frac=None``: no pressure signal, no eviction."""
        if self._hbm_fn is not None:
            snap = self._hbm_fn()
        else:
            from ..obs.xla import hbm_snapshot
            snap = hbm_snapshot()
        worst: Dict[str, Any] = {"usage_frac": None, "bytes_in_use": 0,
                                 "bytes_limit": 0}
        for dev in snap.get("devices") or []:
            limit = dev.get("bytes_limit") or 0
            in_use = dev.get("bytes_in_use") or 0
            if limit <= 0:
                continue
            frac = dev.get("usage_frac")
            frac = in_use / limit if frac is None else float(frac)
            if worst["usage_frac"] is None or frac > worst["usage_frac"]:
                worst = {"usage_frac": frac, "bytes_in_use": in_use,
                         "bytes_limit": limit}
        return worst

    def _est_bytes(self, alias: str) -> int:
        remembered = self._resident_bytes.get(alias)
        if remembered:
            return remembered
        return int(self.spec(alias).est_bytes or 0)

    def _ensure_capacity(self, alias: str) -> None:
        """Evict LRU idle models until ``alias`` projects under the
        alert fraction (and under ``max_resident``); ``Rejected`` when
        the projection still crosses with nothing left to evict."""
        limit_models = self.max_resident
        while (limit_models is not None
               and len(self._engines) >= limit_models):
            victim = self._lru_victim(exclude=alias)
            if victim is None or not self._evict_locked(victim):
                self.rejected_loads += 1
                raise Rejected(0, 1.0, model=alias,
                               reason="zoo_capacity")
            # loop: several residents may need to go
        freed = 0
        alert = self.alert_frac()
        while True:
            pressure = self.hbm_pressure()
            frac, limit = pressure["usage_frac"], pressure["bytes_limit"]
            if frac is None or limit <= 0:
                return                      # no signal: admit the load
            projected = frac + (self._est_bytes(alias) - freed) / limit
            if projected < alert:
                return
            victim = self._lru_victim(exclude=alias)
            if victim is None:
                self.rejected_loads += 1
                obs_metrics.inc("dltpu_zoo_load_rejects_total")
                flight.record("zoo_load_rejected", model=alias,
                              usage_frac=round(frac, 4),
                              projected_frac=round(projected, 4),
                              alert_frac=alert)
                raise Rejected(0, 1.0, model=alias,
                               reason="hbm_pressure")
            freed += self._resident_bytes.get(victim, 0)
            self._evict_locked(victim)

    def enforce_pressure(self) -> int:
        """Reactive sweep (admin / watermark hook): evict LRU models
        until current usage is back under the alert fraction. Returns
        the number evicted."""
        evicted = 0
        with self._lock:
            while True:
                pressure = self.hbm_pressure()
                frac = pressure["usage_frac"]
                if frac is None or frac < self.alert_frac():
                    return evicted
                victim = self._lru_victim(exclude="")
                if victim is None or not self._evict_locked(victim):
                    return evicted
                evicted += 1

    # ------------------------------------------------------ introspection
    def stats(self) -> Dict[str, Any]:
        with self._lock:
            now = time.monotonic()
            models: Dict[str, Any] = {}
            for alias in sorted(self._specs):
                spec = self._specs[alias]
                row: Dict[str, Any] = {
                    "state": self._state[alias],
                    "warm": self._state[alias] == "warm",
                    "weight_quant": spec.weight_quant,
                    "buckets": list(spec.buckets),
                    "max_queue": spec.max_queue,
                    "bytes": self._resident_bytes.get(alias, 0),
                }
                if alias in self._last_used:
                    row["idle_s"] = round(
                        now - self._last_used[alias], 3)
                if alias in self._load_seconds:
                    row["load_seconds"] = round(
                        self._load_seconds[alias], 3)
                if alias in self.load_errors:
                    row["load_error"] = self.load_errors[alias]
                eng = self._engines.get(alias)
                if eng is not None:
                    row["trace_count"] = eng.trace_count
                    row["compile_count"] = eng.compile_count
                models[alias] = row
            return {
                "registered": len(self._specs),
                "resident": len(self._engines),
                "loads": self.loads,
                "evictions": self.evictions,
                "rejected_loads": self.rejected_loads,
                "alert_frac": self.alert_frac(),
                "models": models,
            }
