"""Dynamic micro-batching: many concurrent requests, one device stream.

The throughput case for serving on a TPU is the same as for training:
the chip wants large static batches, clients send batch-1 requests. The
``MicroBatcher`` closes the gap with the ``DevicePrefetcher`` worker
discipline — one dedicated dispatch thread owns the device, everything
else talks to it through a queue:

1. ``submit()`` runs admission control (backpressure/deadline stamping),
   enqueues a request, and returns a ``SubmitHandle`` future.
2. The dispatch thread pops the first waiting request, then accumulates
   followers until the admission policy's target bucket is full or
   ``max_wait_ms`` expires — light traffic dispatches immediately in the
   smallest bucket, bursts fill big buckets.
3. The batch is padded to its bucket, run through the engine's AOT
   executable (never a compile), and demultiplexed: each request's
   future resolves to ITS row of the device outputs. Padding rows are
   sliced away here and never observable (detection padding additionally
   carries class −1 inside each row's fixed-shape slots, PR 3).

The dispatch thread never materializes device values — demux is an
async row-slice, latency bookkeeping is host timestamps — so a slow
client can never stall batch formation (the ``async_metrics`` rule:
syncs happen on the thread that wants the number).
"""

from __future__ import annotations

import itertools
import queue
import threading
import time
from concurrent.futures import Future
from typing import Any, Optional

import jax
import numpy as np

from ..obs import flight
from ..obs.spans import span
from .admission import AdmissionController, DeadlineExceeded
from .telemetry import ServeTelemetry

__all__ = ["MicroBatcher", "SubmitHandle"]


class _Request:
    __slots__ = ("rid", "image", "future", "deadline", "t_submit")

    def __init__(self, rid, image, future, deadline, t_submit):
        self.rid = rid
        self.image = image
        self.future = future
        self.deadline = deadline
        self.t_submit = t_submit


class _SharedBatch:
    """One dispatched batch's DEVICE outputs with a lazily-cached host
    copy. The dispatch thread only wraps the output tree (no sync); the
    FIRST requester to ask pays one bulk D2H for the whole batch, every
    other row rides the cache — N clients cost one transfer, not N
    row-sliced dispatches."""

    __slots__ = ("_device", "_host", "_lock")

    def __init__(self, device_tree: Any):
        self._device = device_tree
        self._host = None
        self._lock = threading.Lock()

    def row(self, i: int) -> Any:
        with self._lock:
            if self._host is None:
                self._host = jax.tree.map(np.asarray, self._device)
                self._device = None     # free HBM once host copy exists
        return jax.tree.map(lambda a: a[i], self._host)


class SubmitHandle:
    """Per-request future. ``result()`` blocks for the demuxed row and
    materializes it on the CALLING thread (the D2H lands on the
    requester, keeping the dispatcher sync-free), recording e2e latency
    into telemetry exactly once."""

    def __init__(self, rid: int, future: Future, t_submit: float,
                 telemetry: Optional[ServeTelemetry]):
        self.rid = rid
        self._future = future
        self._t_submit = t_submit
        self._telemetry = telemetry
        self._recorded = False

    def done(self) -> bool:
        return self._future.done()

    def result(self, timeout: Optional[float] = None) -> Any:
        shared, i = self._future.result(timeout)
        out = shared.row(i)
        if not self._recorded and self._telemetry is not None:
            self._recorded = True
            self._telemetry.record_e2e_latency(
                time.perf_counter() - self._t_submit)
        return out

    def exception(self, timeout: Optional[float] = None):
        return self._future.exception(timeout)


class MicroBatcher:
    """Dynamic micro-batching front of an ``InferenceEngine``.

    - ``max_wait_ms``: how long the dispatcher holds an underfull batch
      open for followers before padding and going (the latency the
      lightest-traffic request pays for batching).
    - ``admission``: an ``AdmissionController``; defaults to one sized
      on the engine's buckets with ``max_queue`` pending requests.
    - Runs its dispatch thread from construction; ``close()`` (or the
      context manager) drains and stops it.
    """

    def __init__(self, engine, *, max_wait_ms: float = 5.0,
                 max_queue: int = 256,
                 default_timeout_s: Optional[float] = None,
                 admission: Optional[AdmissionController] = None,
                 telemetry: Optional[ServeTelemetry] = None,
                 heartbeat=None,
                 start: bool = True):
        self.engine = engine
        self.max_wait_s = max_wait_ms / 1e3
        self.admission = admission or AdmissionController(
            engine.buckets, max_queue=max_queue,
            default_timeout_s=default_timeout_s)
        self.telemetry = telemetry or ServeTelemetry()
        # elastic surface: an elastic.heartbeat.Heartbeat whose activity
        # watermark advances once per dispatched batch — the same
        # liveness contract the Trainer gives its supervisor
        self._beat = heartbeat
        self.dispatched = 0            # batches the dispatch loop finished
        self._busy = False             # dispatch thread is inside a batch
        self._q: "queue.Queue[_Request]" = queue.Queue()
        self._ids = itertools.count()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if start:
            self.start()

    # ------------------------------------------------------- lifecycle
    def start(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._dispatch_loop, name="serve-dispatch",
                daemon=True)
            self._thread.start()

    def close(self, timeout: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)

    def __enter__(self) -> "MicroBatcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    @property
    def queue_depth(self) -> int:
        return self._q.qsize()

    @property
    def busy(self) -> bool:
        """True while the dispatch thread is inside a batch (collected
        but not yet demuxed) — a wedge detector must not call an
        in-flight batch idle."""
        return self._busy

    # ----------------------------------------------------------- submit
    def submit(self, image, timeout_s: Optional[float] = None
               ) -> SubmitHandle:
        """Admit one request. Raises ``serve.Rejected`` on a full queue
        (backpressure, with a retry-after hint); the returned handle's
        ``result()`` raises ``DeadlineExceeded`` if the request expired
        before dispatch. ``image`` must be one model-ready
        (image_size, image_size, 3) frame — resizing/normalizing is the
        client's job (tools/serve.py does it for files)."""
        size = self.engine.image_size
        image = np.asarray(image, np.float32)  # dltpu: allow(DLT100) host input
        if image.shape != (size, size, 3):
            raise ValueError(f"request image shape {image.shape} != "
                             f"({size}, {size}, 3); resize client-side")
        try:
            self.admission.admit(self._q.qsize())
        except Exception:
            self.telemetry.record_reject()
            flight.record("serve_reject", depth=self._q.qsize())
            raise
        now = time.perf_counter()
        req = _Request(next(self._ids), image, Future(),
                       self.admission.deadline_for(timeout_s, now), now)
        self.telemetry.record_submit()
        self._q.put(req)
        return SubmitHandle(req.rid, req.future, now, self.telemetry)

    # --------------------------------------------------------- dispatch
    def _expire(self, req: _Request, now: float) -> bool:
        """Cancel a request whose deadline passed BEFORE spending device
        time on it; True when the request was dropped."""
        if self.admission.expired(req.deadline, now):
            req.future.set_exception(DeadlineExceeded(
                f"request {req.rid} expired after "
                f"{now - req.t_submit:.3f}s in queue"))
            self.telemetry.record_timeout()
            return True
        return False

    def _collect(self) -> list:
        """Block for one request, then hold the batch open for followers
        until the LARGEST bucket fills or ``max_wait_ms`` expires — a
        burst rides one big executable, a lone request pays at most
        ``max_wait_ms`` extra latency before going out in bucket 1."""
        try:
            first = self._q.get(timeout=0.05)
        except queue.Empty:
            return []
        t0 = time.perf_counter()
        batch = [] if self._expire(first, t0) else [first]
        wait_until = t0 + self.max_wait_s
        big = self.engine.buckets[-1]
        while len(batch) < big:
            remaining = wait_until - time.perf_counter()
            if remaining <= 0:
                break
            try:
                req = self._q.get(timeout=remaining)
            except queue.Empty:
                break
            if not self._expire(req, time.perf_counter()):
                batch.append(req)
        return batch

    def _dispatch_loop(self) -> None:
        while not self._stop.is_set():
            batch = self._collect()
            if not batch:
                continue
            self._busy = True
            try:
                self._dispatch_one(batch)
            finally:
                # count the batch whether it ran or errored — both mean
                # the dispatch thread is ALIVE (what a wedge probe asks)
                self._busy = False
                self.dispatched += 1
                if self._beat is not None:
                    self._beat.touch("dispatch", step=self.dispatched)

    def _dispatch_one(self, batch: list) -> None:
        t0 = time.perf_counter()
        depth = self._q.qsize()
        shed = self.admission.overloaded(depth)
        bucket = (self.engine.buckets[-1] if shed
                  else self.engine.bucket_for(len(batch)))
        try:
            with span("serve/dispatch", bucket=bucket, n=len(batch),
                      depth=depth, shed=shed):
                padded = self.engine.pad_to_bucket(
                    np.stack([r.image for r in batch]), bucket)
                out = self.engine.run(bucket, padded)
        except BaseException as exc:  # noqa: BLE001 - to the futures
            for r in batch:
                if not r.future.done():
                    r.future.set_exception(exc)
            return
        now = time.perf_counter()
        shared = _SharedBatch(out)
        for i, r in enumerate(batch):
            # hand each request its row of the shared device batch —
            # no sync here; the first result() call materializes once
            r.future.set_result((shared, i))
            self.telemetry.record_dispatch_latency(now - r.t_submit)
        self.telemetry.record_batch(bucket, len(batch),
                                    self._q.qsize(), shed)
        self.admission.note_drained(len(batch), now - t0)
