"""Dynamic micro-batching: many concurrent requests, one device stream.

The throughput case for serving on a TPU is the same as for training:
the chip wants large static batches, clients send batch-1 requests. The
``MicroBatcher`` closes the gap with the ``DevicePrefetcher`` worker
discipline — one dedicated dispatch thread owns the device, everything
else talks to it through per-model queues ("lanes"):

1. ``submit()`` runs admission control (backpressure/deadline stamping)
   against the TARGET model's lane, enqueues, and returns a
   ``SubmitHandle`` future.
2. The dispatch thread round-robins over lanes with waiting work (so
   one hot tenant cannot starve the rest), pops the first request, then
   accumulates same-model followers until the lane's bucket family is
   full or ``max_wait_ms`` expires — light traffic dispatches
   immediately in the smallest bucket, bursts fill big buckets.
3. The batch is padded to its bucket, run through that model's AOT
   executable (never a compile), and demultiplexed: each request's
   future resolves to ITS row of the device outputs. Padding rows are
   sliced away here and never observable (detection padding additionally
   carries class −1 inside each row's fixed-shape slots, PR 3).

Two fronting modes share all of the above: ``MicroBatcher(engine)``
serves one model through one implicit lane (the PR 4 surface,
unchanged), while ``MicroBatcher(zoo=...)`` serves every model a
:class:`~.zoo.ModelZoo` holds — ``submit(image, model=alias)`` routes
to the tenant's lane, cold tenants get a background hot-load kicked
and their lane skipped until the zoo's warm flag flips, and each lane
owns its telemetry + admission controller (per-model EWMA drain).

The dispatch thread never materializes device values — demux is an
async row-slice, latency bookkeeping is host timestamps — so a slow
client can never stall batch formation (the ``async_metrics`` rule:
syncs happen on the thread that wants the number).
"""

from __future__ import annotations

import collections
import itertools
import threading
import time
from concurrent.futures import Future
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from ..elastic import faults
from ..obs import flight
from ..obs import threads as obs_threads
from ..obs.spans import span
from .admission import AdmissionController, DeadlineExceeded, Rejected
from .telemetry import ServeTelemetry

__all__ = ["MicroBatcher", "SubmitHandle"]


class _Request:
    __slots__ = ("rid", "image", "future", "deadline", "t_submit")

    def __init__(self, rid, image, future, deadline, t_submit):
        self.rid = rid
        self.image = image
        self.future = future
        self.deadline = deadline
        self.t_submit = t_submit


class _Lane:
    """One model's wait queue + policy + counters. The deque is guarded
    by the batcher's condition variable; admission/telemetry objects are
    internally locked."""

    __slots__ = ("model", "q", "admission", "telemetry")

    def __init__(self, model: str, admission: AdmissionController,
                 telemetry: ServeTelemetry):
        self.model = model
        self.q: "collections.deque[_Request]" = collections.deque()
        self.admission = admission
        self.telemetry = telemetry


class _SharedBatch:
    """One dispatched batch's DEVICE outputs with a lazily-cached host
    copy. The dispatch thread only wraps the output tree (no sync); the
    FIRST requester to ask pays one bulk D2H for the whole batch, every
    other row rides the cache — N clients cost one transfer, not N
    row-sliced dispatches."""

    __slots__ = ("_device", "_host", "_lock")

    def __init__(self, device_tree: Any):
        self._device = device_tree
        self._host = None
        self._lock = threading.Lock()

    def row(self, i: int) -> Any:
        with self._lock:
            if self._host is None:
                self._host = jax.tree.map(np.asarray, self._device)
                self._device = None     # free HBM once host copy exists
        return jax.tree.map(lambda a: a[i], self._host)


class SubmitHandle:
    """Per-request future. ``result()`` blocks for the demuxed row and
    materializes it on the CALLING thread (the D2H lands on the
    requester, keeping the dispatcher sync-free), recording e2e latency
    into telemetry exactly once (into the lane's AND the aggregate
    rings in zoo mode)."""

    def __init__(self, rid: int, future: Future, t_submit: float,
                 telemetry: Any):
        self.rid = rid
        self._future = future
        self._t_submit = t_submit
        if telemetry is None:
            telemetry = ()
        elif isinstance(telemetry, ServeTelemetry):
            telemetry = (telemetry,)
        self._telemetry = tuple(telemetry)
        self._recorded = False

    def done(self) -> bool:
        return self._future.done()

    def result(self, timeout: Optional[float] = None) -> Any:
        shared, i = self._future.result(timeout)
        out = shared.row(i)
        if not self._recorded and self._telemetry:
            self._recorded = True
            e2e = time.perf_counter() - self._t_submit
            for t in self._telemetry:
                t.record_e2e_latency(e2e)
        return out

    def exception(self, timeout: Optional[float] = None):
        return self._future.exception(timeout)


class MicroBatcher:
    """Dynamic micro-batching front of one ``InferenceEngine`` or a
    whole ``ModelZoo``.

    - ``max_wait_ms``: how long the dispatcher holds an underfull batch
      open for followers before padding and going (the latency the
      lightest-traffic request pays for batching).
    - ``admission``: an ``AdmissionController``; single-engine mode
      defaults to one sized on the engine's buckets with ``max_queue``
      pending requests. Zoo mode ignores it — each tenant's controller
      comes from ``zoo.admission_for``.
    - Runs its dispatch thread from construction; ``close()`` (or the
      context manager) drains and stops it.
    """

    def __init__(self, engine=None, *, zoo=None,
                 max_wait_ms: float = 5.0,
                 max_queue: int = 256,
                 default_timeout_s: Optional[float] = None,
                 admission: Optional[AdmissionController] = None,
                 telemetry: Optional[ServeTelemetry] = None,
                 heartbeat=None,
                 standby: bool = False,
                 start: bool = True):
        if (engine is None) == (zoo is None):
            raise ValueError("pass exactly one of engine= or zoo=")
        self.engine = engine
        self.zoo = zoo
        self.max_wait_s = max_wait_ms / 1e3
        self.telemetry = telemetry or ServeTelemetry()
        self._cv = threading.Condition()
        self._lanes: Dict[str, _Lane] = {}
        self._rr = 0                   # round-robin cursor over lanes
        if engine is not None:
            self.admission = admission or AdmissionController(
                engine.buckets, max_queue=max_queue,
                default_timeout_s=default_timeout_s)
            # the single-engine surface is one implicit lane sharing the
            # aggregate telemetry (so nothing records twice)
            self._default_lane = _Lane(
                getattr(engine, "name", "model"), self.admission,
                self.telemetry)
            self._lanes[self._default_lane.model] = self._default_lane
        else:
            self.admission = None
            self._default_lane = None
        # elastic surface: an elastic.heartbeat.Heartbeat whose activity
        # watermark advances once per dispatched batch — the same
        # liveness contract the Trainer gives its supervisor
        self._beat = heartbeat
        self.dispatched = 0            # batches the dispatch loop finished
        self._busy = False             # dispatch thread is inside a batch
        self._ids = itertools.count()
        self._stop = threading.Event()
        # fleet surface: drain() flips _draining (new submits 429 with
        # reason="draining", queued work still dispatches); on_preempt,
        # when set by the owning CLI, is invoked once if a
        # preempt_replica fault targets this replica
        self._draining = threading.Event()
        self.on_preempt = None
        self.on_crash = None
        # resilience surface: a standby replica warms fully but refuses
        # traffic (healthz "standby") until promote(); brownout steps
        # per model degrade one hot tenant without touching the rest
        self._standby = threading.Event()
        if standby:
            self._standby.set()
        self._brownout: Dict[str, int] = {}     # model -> ladder step
        self._bo_count: Dict[str, int] = {}     # model -> submit ordinal
        self._thread: Optional[threading.Thread] = None
        if start:
            self.start()

    # ------------------------------------------------------- lifecycle
    def start(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = obs_threads.spawn(
                self._dispatch_loop, name="serve-dispatch", daemon=True)

    def close(self, timeout: float = 5.0) -> None:
        self._stop.set()
        with self._cv:
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout)

    def __enter__(self) -> "MicroBatcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    @property
    def queue_depth(self) -> int:
        with self._cv:
            return sum(len(lane.q) for lane in self._lanes.values())

    def lane_depth(self, model: str) -> int:
        with self._cv:
            lane = self._lanes.get(model)
            return len(lane.q) if lane is not None else 0

    def lane_telemetry(self, model: str) -> Optional[ServeTelemetry]:
        lane = self._lanes.get(model)
        return lane.telemetry if lane is not None else None

    @property
    def busy(self) -> bool:
        """True while the dispatch thread is inside a batch (collected
        but not yet demuxed) — a wedge detector must not call an
        in-flight batch idle."""
        return self._busy

    # ------------------------------------------------------------ drain
    def drain(self) -> None:
        """Stop ACCEPTING without stopping WORKING: new submits are
        rejected (429 reason="draining", retry elsewhere) while every
        already-queued request still dispatches — the graceful half of
        the controller's drain-and-requeue. Idempotent."""
        if not self._draining.is_set():
            self._draining.set()
            flight.record("serve_drain", depth=self.queue_depth)

    @property
    def draining(self) -> bool:
        return self._draining.is_set()

    @property
    def drained(self) -> bool:
        """True once a drain has fully flushed: draining was requested,
        the lanes are empty, and no batch is in flight."""
        return (self._draining.is_set() and not self._busy
                and self.queue_depth == 0)

    # ------------------------------------------------ standby/brownout
    @property
    def standby(self) -> bool:
        return self._standby.is_set()

    def promote(self) -> bool:
        """Flip a warm standby into rotation: healthz goes "standby" →
        "ready" on the next probe and submits are accepted immediately.
        The engine warmed at construction, so promotion costs a flag
        flip, not an AOT pass. True when this call did the flip."""
        if self._standby.is_set():
            self._standby.clear()
            flight.record("serve_promote", dispatched=self.dispatched)
            return True
        return False

    def set_brownout(self, model: str, step: int) -> int:
        """Set one tenant's degrade-ladder step (0 = full service).
        Step >= 1: the lane dispatches largest-bucket-only (max
        throughput posture). Step >= 3: additionally shed a fixed
        fraction of that lane's submits (deterministic 1-in-4, reason
        "brownout"). Step 2's int8-residency move belongs to the zoo —
        the serve CLI applies it when it owns one. Returns the step
        actually stored (clamped to [0, 3])."""
        step = max(0, min(int(step), 3))
        with self._cv:
            if step:
                self._brownout[model] = step
            else:
                self._brownout.pop(model, None)
                self._bo_count.pop(model, None)
        flight.record("serve_brownout", model=model, step=step)
        return step

    def brownout_step(self, model: str) -> int:
        with self._cv:
            return self._brownout.get(model, 0)

    # -------------------------------------------------------- lanes
    def _lane(self, model: Optional[str]) -> _Lane:
        if self._default_lane is not None:
            return self._default_lane
        if model is None:
            models = self.zoo.models()
            if len(models) != 1:
                raise ValueError(
                    f"zoo serves {models}; submit(model=...) required")
            model = models[0]
        lane = self._lanes.get(model)
        if lane is None:
            admission = self.zoo.admission_for(model)  # raises KeyError
            with self._cv:
                lane = self._lanes.get(model)
                if lane is None:
                    lane = _Lane(model, admission, ServeTelemetry())
                    self._lanes[model] = lane
        return lane

    def _tels(self, lane: _Lane) -> Tuple[ServeTelemetry, ...]:
        if lane.telemetry is self.telemetry:
            return (lane.telemetry,)
        return (lane.telemetry, self.telemetry)

    def _engine_for(self, lane: _Lane):
        """The lane's warm engine, or None (zoo lane still loading — the
        load was kicked at submit; the dispatcher just skips the lane)."""
        if self.engine is not None:
            return self.engine
        return self.zoo.engine(lane.model)

    # ----------------------------------------------------------- submit
    def submit(self, image, timeout_s: Optional[float] = None,
               model: Optional[str] = None) -> SubmitHandle:
        """Admit one request. Raises ``serve.Rejected`` on a full lane
        (backpressure, with the TARGET model's retry-after hint) or —
        zoo mode — when the model would need a load that HBM pressure
        refuses; the returned handle's ``result()`` raises
        ``DeadlineExceeded`` if the request expired before dispatch.
        ``image`` must be one model-ready (image_size, image_size, 3)
        frame — resizing/normalizing is the client's job
        (tools/serve.py does it for files)."""
        lane = self._lane(model)
        if self.engine is not None:
            size = self.engine.image_size
        else:
            size = self.zoo.image_size(lane.model)
        image = np.asarray(image, np.float32)  # dltpu: allow(DLT100) host input
        if image.shape != (size, size, 3):
            raise ValueError(f"request image shape {image.shape} != "
                             f"({size}, {size}, 3); resize client-side")
        try:
            if self._standby.is_set():
                # a standby is warm but OUT of rotation — a request
                # reaching it is a routing error, not load to absorb
                raise Rejected(len(lane.q), 0.0, model=lane.model,
                               reason="standby")
            if self._draining.is_set():
                # a draining replica refuses new work outright — no
                # retry_after hint would help; the caller must reroute
                raise Rejected(len(lane.q), 0.0, model=lane.model,
                               reason="draining")
            if faults.consume("e503", "submit", self.dispatched):
                # seeded chaos: one injected 503 — exercises router
                # failover and the per-replica breaker for real
                raise Rejected(len(lane.q), 0.0, model=lane.model,
                               reason="injected")
            if self.brownout_step(lane.model) >= 3:
                n = 0
                with self._cv:
                    n = self._bo_count.get(lane.model, 0) + 1
                    self._bo_count[lane.model] = n
                if n % 4 == 0:
                    raise Rejected(
                        len(lane.q),
                        lane.admission.retry_after_s(len(lane.q)),
                        model=lane.model, reason="brownout")
            if self.zoo is not None:
                # warm fast-path: dict lookup. Cold: kicks a background
                # hot-load (may LRU-evict; raises Rejected on pressure)
                self.zoo.request(lane.model)
            lane.admission.admit(len(lane.q))
        except Exception:
            for t in self._tels(lane):
                t.record_reject()
            flight.record("serve_reject", model=lane.model,
                          depth=len(lane.q))
            raise
        now = time.perf_counter()
        req = _Request(next(self._ids), image, Future(),
                       lane.admission.deadline_for(timeout_s, now), now)
        for t in self._tels(lane):
            t.record_submit()
        with self._cv:
            lane.q.append(req)
            self._cv.notify_all()
        return SubmitHandle(req.rid, req.future, now, self._tels(lane))

    # --------------------------------------------------------- dispatch
    def _expire(self, lane: _Lane, req: _Request, now: float) -> bool:
        """Cancel a request whose deadline passed BEFORE spending device
        time on it; True when the request was dropped."""
        if lane.admission.expired(req.deadline, now):
            req.future.set_exception(DeadlineExceeded(
                f"request {req.rid} expired after "
                f"{now - req.t_submit:.3f}s in queue"))
            for t in self._tels(lane):
                t.record_timeout()
            return True
        return False

    def _purge_expired(self, lane: _Lane) -> None:
        """Deadline enforcement for a lane whose engine is still
        warming: expired requests fail now, not after the load."""
        now = time.perf_counter()
        with self._cv:
            keep = collections.deque()
            for req in lane.q:
                if not self._expire(lane, req, now):
                    keep.append(req)
            lane.q = keep

    def _pick_lane(self) -> Optional[Tuple[_Lane, Any]]:
        """Wait (≤50ms) for any lane with work, then round-robin to the
        next one whose engine is ready. Lanes of still-loading models
        are skipped (their hot-load is already running); round-robin
        across ready lanes is the anti-starvation guarantee — a
        saturated tenant gets one batch per turn, not the whole
        thread."""
        with self._cv:
            self._cv.wait_for(
                lambda: self._stop.is_set()
                or any(lane.q for lane in self._lanes.values()),
                timeout=0.05)
            if self._stop.is_set():
                return None
            names: List[str] = [name for name, lane
                                in self._lanes.items() if lane.q]
        if not names:
            return None
        order = sorted(names)
        start = self._rr % len(order)
        cold = []
        for name in order[start:] + order[:start]:
            lane = self._lanes[name]
            engine = self._engine_for(lane)
            if engine is None:
                cold.append(lane)
                continue
            if lane.q:
                self._rr += 1
                return lane, engine
        for lane in cold:
            self._purge_expired(lane)
        if cold:
            # every pending lane is warming: don't spin on the CV (the
            # warm flag flips without a notify) — nap one poll tick
            self._stop.wait(0.01)
        return None

    def _collect(self, lane: _Lane, engine) -> list:
        """Pop one request from the lane, then hold the batch open for
        same-model followers until the LARGEST bucket fills or
        ``max_wait_ms`` expires — a burst rides one big executable, a
        lone request pays at most ``max_wait_ms`` extra latency before
        going out in bucket 1."""
        with self._cv:
            if not lane.q:
                return []
            first = lane.q.popleft()
        t0 = time.perf_counter()
        batch = [] if self._expire(lane, first, t0) else [first]
        wait_until = t0 + self.max_wait_s
        big = engine.buckets[-1]
        while len(batch) < big:
            remaining = wait_until - time.perf_counter()
            if remaining <= 0:
                break
            with self._cv:
                if not lane.q:
                    self._cv.wait(timeout=remaining)
                if not lane.q:
                    continue            # spurious/other-lane wakeup
                req = lane.q.popleft()
            if not self._expire(lane, req, time.perf_counter()):
                batch.append(req)
        return batch

    def _poll_faults(self) -> None:
        """Fleet-choreography fault hooks, polled once per dispatch-loop
        iteration (~20 Hz when idle). ``wedge_replica`` freezes THIS
        thread while the heartbeat writer stays alive — ``dispatched``
        stops with work queued, exactly the frozen-stream signature
        ``DispatchWatch`` classifies. ``preempt_replica`` hands control
        to the CLI's callback (drain → exit 75); it is only consumed
        once a callback exists, so the spec can't burn before the
        owner wires it."""
        if faults.consume("wedge_replica", "step", self.dispatched):
            deadline = time.monotonic() + faults.WEDGE_SLEEP_S
            while (not self._stop.is_set()
                   and time.monotonic() < deadline):
                self._stop.wait(0.25)
        cb = self.on_preempt
        if cb is not None and faults.consume(
                "preempt_replica", "step", self.dispatched):
            cb()
        cb = self.on_crash
        if cb is not None and faults.consume(
                "crash_replica", "step", self.dispatched):
            cb()

    def _dispatch_loop(self) -> None:
        while not self._stop.is_set():
            self._poll_faults()
            picked = self._pick_lane()
            if picked is None:
                continue
            lane, engine = picked
            batch = self._collect(lane, engine)
            if not batch:
                continue
            self._busy = True
            try:
                self._dispatch_one(lane, engine, batch)
            finally:
                # count the batch whether it ran or errored — both mean
                # the dispatch thread is ALIVE (what a wedge probe asks)
                self._busy = False
                self.dispatched += 1
                if self._beat is not None:
                    self._beat.touch("dispatch", step=self.dispatched)

    def _dispatch_one(self, lane: _Lane, engine, batch: list) -> None:
        t0 = time.perf_counter()
        depth = len(lane.q)
        # brownout step >= 1 pins the lane to its max-throughput
        # posture (largest bucket) even before admission sheds
        shed = (lane.admission.overloaded(depth)
                or self.brownout_step(lane.model) >= 1)
        bucket = (engine.buckets[-1] if shed
                  else engine.bucket_for(len(batch)))
        lat_ms = faults.consume_arg("latency", "step", self.dispatched)
        if lat_ms:
            # seeded chaos: injected tail latency — the stimulus the
            # router's hedging policy exists to absorb
            time.sleep(lat_ms / 1e3)
        if self.zoo is not None:
            self.zoo.mark_dispatch(lane.model, +1)
        try:
            with span("serve/dispatch", model=lane.model, bucket=bucket,
                      n=len(batch), depth=depth, shed=shed):
                padded = engine.pad_to_bucket(
                    np.stack([r.image for r in batch]), bucket)
                out = engine.run(bucket, padded)
        except BaseException as exc:  # noqa: BLE001 - to the futures
            for r in batch:
                if not r.future.done():
                    r.future.set_exception(exc)
            return
        finally:
            if self.zoo is not None:
                self.zoo.mark_dispatch(lane.model, -1)
        now = time.perf_counter()
        shared = _SharedBatch(out)
        tels = self._tels(lane)
        for i, r in enumerate(batch):
            # hand each request its row of the shared device batch —
            # no sync here; the first result() call materializes once
            r.future.set_result((shared, i))
            for t in tels:
                t.record_dispatch_latency(now - r.t_submit)
        for t in tels:
            t.record_batch(bucket, len(batch), len(lane.q), shed)
        # per-model EWMA: the drain estimate behind retry_after quotes
        # THIS tenant's dispatch history (the TenantAdmission bugfix)
        lane.admission.note_drained(len(batch), now - t0)
