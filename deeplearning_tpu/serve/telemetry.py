"""Serving telemetry: sync-free request/batch gauges for the dispatcher.

Same discipline as ``train/async_metrics.DeferredMetrics``: the thread
that talks to the device (the batcher's dispatch loop) must never pay a
D2H sync to record a number. Everything recorded here is host-side
bookkeeping — timestamps taken at submit/demux, queue depths read off a
``queue.Queue``, bucket occupancy known at padding time — appended to
bounded rings (``collections.deque(maxlen=...)``), so a snapshot is a
pure host computation over already-resolved floats.

Two latency views, deliberately distinct:
- ``dispatch``: submit → demux (futures resolved with DEVICE arrays; no
  sync happened yet). What the engine itself controls: queueing + batch
  formation + executable dispatch.
- ``e2e``: submit → result materialized on the host. Recorded by the
  CLIENT thread (``SubmitHandle.result()`` / tools/loadgen.py), which is
  the thread that pays the D2H anyway — the device wait lands on the
  requester, never on the dispatcher (the lagged-ring idiom).
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Dict, Optional, Sequence

__all__ = ["ServeTelemetry", "percentile"]


def percentile(values: Sequence[float], q: float) -> float:
    """Nearest-rank percentile over a small host ring (no numpy import
    on the hot path; rings are <= maxlen floats)."""
    if not values:
        return 0.0
    xs = sorted(values)
    idx = min(int(q / 100.0 * len(xs)), len(xs) - 1)
    return xs[idx]


class ServeTelemetry:
    """Bounded-ring counters and gauges for one engine+batcher pair.

    Thread-safe: submit paths, the dispatch thread, and client threads
    all record concurrently (one lock; every op is O(1) appends/adds).
    """

    def __init__(self, ring: int = 2048):
        self._lock = threading.Lock()
        self._dispatch_lat = collections.deque(maxlen=ring)
        self._e2e_lat = collections.deque(maxlen=ring)
        self._batch_real = collections.deque(maxlen=ring)
        self._batch_bucket = collections.deque(maxlen=ring)
        self._queue_depth = collections.deque(maxlen=ring)
        # event timestamps for windowed rates (the fleet aggregator sums
        # rates across replicas — cumulative counters alone can't say
        # "QPS now"). Same bounded-ring discipline: one append per event.
        self._submit_ts = collections.deque(maxlen=ring)
        self._reject_ts = collections.deque(maxlen=ring)
        self._complete_ts = collections.deque(maxlen=ring)
        self._born = time.monotonic()
        self.submitted = 0
        self.completed = 0
        self.rejected = 0
        self.timed_out = 0
        self.batches = 0
        self.shed_batches = 0

    # ------------------------------------------------------- recording
    def record_submit(self) -> None:
        with self._lock:
            self.submitted += 1
            self._submit_ts.append(time.monotonic())

    def record_reject(self) -> None:
        with self._lock:
            self.rejected += 1
            self._reject_ts.append(time.monotonic())

    def record_timeout(self, n: int = 1) -> None:
        with self._lock:
            self.timed_out += n

    def record_batch(self, bucket: int, n_real: int, queue_depth: int,
                     shed: bool = False) -> None:
        """One dispatched micro-batch: ``n_real`` requests padded into a
        ``bucket``-row executable, observed ``queue_depth`` left behind."""
        with self._lock:
            self.batches += 1
            if shed:
                self.shed_batches += 1
            self._batch_real.append(float(n_real))
            self._batch_bucket.append(float(bucket))
            self._queue_depth.append(float(queue_depth))

    def record_dispatch_latency(self, seconds: float, n: int = 1) -> None:
        with self._lock:
            self.completed += n
            self._dispatch_lat.append(float(seconds))
            now = time.monotonic()
            for _ in range(n):
                self._complete_ts.append(now)

    def record_e2e_latency(self, seconds: float) -> None:
        with self._lock:
            self._e2e_lat.append(float(seconds))

    # -------------------------------------------------------- snapshot
    def latency_ms(self, kind: str = "e2e") -> Dict[str, float]:
        """{p50, p90, p99} over the ring, in milliseconds."""
        with self._lock:
            ring = list(self._e2e_lat if kind == "e2e"
                        else self._dispatch_lat)
        return {f"p{q}": round(percentile(ring, q) * 1e3, 3)
                for q in (50, 90, 99)}

    @property
    def batch_occupancy(self) -> float:
        """Mean real-rows / bucket-rows over recent batches (1.0 = every
        executable ran full; low values mean latency-bound padding)."""
        with self._lock:
            if not self._batch_real:
                return 0.0
            return (sum(self._batch_real)
                    / max(sum(self._batch_bucket), 1.0))

    @property
    def queue_depth_mean(self) -> float:
        with self._lock:
            ring = self._queue_depth
            return sum(ring) / len(ring) if ring else 0.0

    def rates(self, window_s: float = 10.0) -> Dict[str, float]:
        """{requests_per_s, rejects_per_s, completions_per_s} over the
        trailing ``window_s``. The divisor is the *effective* window —
        min(window_s, age of this telemetry object) — so a short burst
        right after startup measures its true rate instead of being
        diluted by a window that predates the process."""
        now = time.monotonic()
        cut = now - window_s
        eff = max(min(window_s, now - self._born), 1e-6)
        with self._lock:
            counts = {
                "requests_per_s": sum(1 for t in self._submit_ts
                                      if t >= cut),
                "rejects_per_s": sum(1 for t in self._reject_ts
                                     if t >= cut),
                "completions_per_s": sum(1 for t in self._complete_ts
                                         if t >= cut),
            }
        out = {k: round(v / eff, 3) for k, v in counts.items()}
        out["window_s"] = round(eff, 3)
        return out

    def snapshot(self) -> Dict[str, float]:
        """One flat dict for bench rows / the serve CLI stats line."""
        disp = self.latency_ms("dispatch")
        e2e = self.latency_ms("e2e")
        with self._lock:
            out = {
                "submitted": float(self.submitted),
                "completed": float(self.completed),
                "rejected": float(self.rejected),
                "timed_out": float(self.timed_out),
                "batches": float(self.batches),
                "shed_batches": float(self.shed_batches),
            }
        out["batch_occupancy"] = round(self.batch_occupancy, 4)
        out["queue_depth_mean"] = round(self.queue_depth_mean, 2)
        out.update(self.rates())
        for k, v in disp.items():
            out[f"dispatch_ms_{k}"] = v
        for k, v in e2e.items():
            out[f"e2e_ms_{k}"] = v
        return out
