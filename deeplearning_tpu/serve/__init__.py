"""TPU-native batched inference serving.

The request-path counterpart of the training stack: an
``InferenceEngine`` (one resident model session, bucketed AOT
executables precompiled at startup), a ``MicroBatcher`` (dynamic
micro-batching on a dedicated dispatch thread), admission control
(bounded queue + deadlines + overload shedding), and sync-free
telemetry. CLIs: ``tools/serve.py`` (server), ``tools/loadgen.py``
(load generator), ``tools/predict.py`` (one-shot client).

    from deeplearning_tpu import serve
    engine = serve.InferenceEngine("resnet18", num_classes=10,
                                   image_size=96, batch_buckets=(1, 8))
    with serve.MicroBatcher(engine) as mb:
        handle = mb.submit(image)          # (96, 96, 3) model-ready
        probs = handle.result(timeout=1.0)

See README "Serving policy" for the bucket table and overload rules.
"""

from .admission import AdmissionController, DeadlineExceeded, Rejected
from .batcher import MicroBatcher, SubmitHandle
from .engine import InferenceEngine
from .health import health
from .telemetry import ServeTelemetry

__all__ = ["InferenceEngine", "MicroBatcher", "SubmitHandle",
           "AdmissionController", "Rejected", "DeadlineExceeded",
           "ServeTelemetry", "health"]
