"""TPU-native batched inference serving.

The request-path counterpart of the training stack: an
``InferenceEngine`` (one resident model session, bucketed AOT
executables precompiled at startup), a ``MicroBatcher`` (dynamic
micro-batching on a dedicated dispatch thread), admission control
(bounded queue + deadlines + overload shedding), and sync-free
telemetry. CLIs: ``tools/serve.py`` (server), ``tools/loadgen.py``
(load generator), ``tools/predict.py`` (one-shot client).

    from deeplearning_tpu import serve
    engine = serve.InferenceEngine("resnet18", num_classes=10,
                                   image_size=96, batch_buckets=(1, 8))
    with serve.MicroBatcher(engine) as mb:
        handle = mb.submit(image)          # (96, 96, 3) model-ready
        probs = handle.result(timeout=1.0)

Multi-tenant: a ``ModelZoo`` fronts N models in one process (hot
load/evict under HBM pressure, per-tenant quotas, optional int8 weight
residency):

    zoo = serve.ModelZoo()
    zoo.register("digits", "mnist_fcn", num_classes=10, image_size=28)
    with serve.MicroBatcher(zoo=zoo) as mb:
        probs = mb.submit(image, model="digits").result(timeout=30.0)

See README "Serving policy" / "Multi-tenant serving policy" for the
bucket table, overload rules, and the load/evict lifecycle.
"""

from .admission import (AdmissionController, DeadlineExceeded, Rejected,
                        TenantAdmission)
from .batcher import MicroBatcher, SubmitHandle
from .engine import InferenceEngine
from .health import health, zoo_health
from .telemetry import ServeTelemetry
from .zoo import ModelSpec, ModelZoo

__all__ = ["InferenceEngine", "MicroBatcher", "SubmitHandle",
           "AdmissionController", "TenantAdmission", "Rejected",
           "DeadlineExceeded", "ServeTelemetry", "health", "zoo_health",
           "ModelZoo", "ModelSpec"]
