"""InferenceEngine: one model session, bucketed AOT executables.

The training side already holds every ingredient a serving stack needs —
``hub.load``-style session construction, ``core/checkpoint`` restore,
the persistent compile cache, AOT ``jit().lower(spec).compile()`` warmup
(PR 2), and fixed-shape detection postprocess with class −1 padding
(PR 3). The engine composes them into the request path:

- **One session.** Params are loaded once (registry build + optional
  checkpoint restore, EMA-preferring) and ``device_put`` once; every
  request-path executable closes over the same resident variables —
  requests never re-transfer weights.
- **Bucketed static shapes.** Requests are only ever executed at a fixed
  set of padded batch sizes (default 1/8/32/128 × one image size). Same
  policy as multi-scale training: a small static family of shapes, one
  executable each, zero retraces in steady state.
- **AOT warmup.** Every bucket is precompiled at startup from abstract
  ``ShapeDtypeStruct`` specs (the ``element_spec`` idiom) through the
  library-wide persistent compile cache — first-request latency never
  includes an XLA compile, and a restarted server rewarms from disk.
- **Counters as contract.** ``trace_count`` / ``compile_count`` are the
  test surface for "zero compiles after warmup": the traced forward
  bumps ``trace_count`` exactly when XLA retraces it, so a steady-state
  serve loop must leave it at ``len(buckets)``.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..obs.xla import tracked_compile

__all__ = ["InferenceEngine"]

# int8 weight residency (the EQuARX block-scaled machinery from
# parallel/collectives.py, applied to resident weights instead of
# gradient wires): each 256-element block shares one power-of-two fp32
# scale, so a resident leaf costs ~1 byte/elem + 4/256 scale overhead —
# ~3.9x denser than fp32. Leaves below _QUANT_MIN_SIZE stay fp32
# (biases, norm scales: quantizing them buys nothing and costs
# accuracy).
_QUANT_BLOCK = 256
_QUANT_MIN_SIZE = 1024


def _quantize_variables(variables):
    """variables pytree -> (quantized leaves list, meta list, treedef).
    Large float leaves become {"q": int8, "s": fp32 scales} pairs; the
    meta entry carries (shape, dtype, size) to invert the flatten+pad."""
    from ..parallel.collectives import _pad_to, _quantize_blocks
    leaves, treedef = jax.tree_util.tree_flatten(variables)
    qleaves, meta = [], []
    for x in leaves:
        arr = jnp.asarray(x)
        if (jnp.issubdtype(arr.dtype, jnp.floating)
                and arr.size >= _QUANT_MIN_SIZE):
            flat, _ = _pad_to(arr.astype(jnp.float32).reshape(-1),
                              _QUANT_BLOCK)
            q, s = _quantize_blocks(flat.reshape(-1, _QUANT_BLOCK))
            qleaves.append({"q": q, "s": s})
            meta.append((arr.shape, arr.dtype, arr.size))
        else:
            qleaves.append(arr)
            meta.append(None)
    return qleaves, meta, treedef


def _dequantize_variables(qleaves, meta, treedef):
    """Inverse of ``_quantize_variables``; runs INSIDE the traced
    forward, so dequantization is part of each bucket's executable and
    HBM holds only the int8 payloads between requests."""
    from ..parallel.collectives import _dequantize_blocks
    out = []
    for leaf, m in zip(qleaves, meta):
        if m is None:
            out.append(leaf)
        else:
            shape, dtype, size = m
            x = _dequantize_blocks(leaf["q"], leaf["s"]).reshape(-1)
            out.append(x[:size].reshape(shape).astype(dtype))
    return jax.tree_util.tree_unflatten(treedef, out)


class InferenceEngine:
    """A servable model session with per-bucket AOT executables.

    Build from a registry name (plus optional orbax checkpoint), or pass
    an already-built ``(model, variables)`` pair via ``model=`` /
    ``variables=`` (the ``hub.load`` return surface). ``task`` is
    auto-detected from the registry name ("detect" for the five
    detection families, else "classify"); detection engines run the
    family's fixed-shape postprocess inside the executable, so a request
    answer is {boxes, scores, labels, valid} rows, never raw heads.
    """

    def __init__(self, model_name: Optional[str] = None, *,
                 num_classes: int = 1000,
                 ckpt: Optional[str] = None,
                 image_size: int = 224,
                 batch_buckets: Sequence[int] = (1, 8, 32, 128),
                 task: str = "auto",
                 model: Any = None,
                 variables: Optional[Dict] = None,
                 tta: bool = False,
                 score_thresh: float = 0.05,
                 max_det: int = 100,
                 nms_impl: str = "auto",
                 post_nms_top_n: int = 256,
                 seed: int = 0,
                 precompile: bool = True,
                 use_compile_cache: bool = True,
                 weight_quant: str = "fp32"):
        from ..models.detection.predict import is_detection_model

        if model is None and model_name is None:
            raise ValueError("pass model_name or a prebuilt model")
        self.name = model_name or type(model).__name__.lower()
        self.task = (("detect" if is_detection_model(self.name)
                      else "classify") if task == "auto" else task)
        self.num_classes = num_classes
        self.image_size = int(image_size)
        self.buckets: Tuple[int, ...] = tuple(
            sorted({int(b) for b in batch_buckets}))
        if not self.buckets or self.buckets[0] < 1:
            raise ValueError(f"bad batch_buckets {batch_buckets!r}")
        self.tta = tta
        self.score_thresh = score_thresh
        self.max_det = max_det
        self.nms_impl = nms_impl
        self.post_nms_top_n = post_nms_top_n

        if use_compile_cache:
            from ..core.compile_cache import enable_compile_cache
            enable_compile_cache()

        if model is None:
            from .. import hub
            # fasterrcnn heads carry class 0 = background: build with
            # num_classes+1 (postprocess shifts labels back to 0-based)
            head_classes = num_classes + (
                1 if self.name.startswith("fasterrcnn") else 0)
            # hub.load is the one session constructor (registry build +
            # EMA-preferring checkpoint restore); its jitted forward is
            # discarded — the engine's bucketed AOT executables replace it
            model, hub_vars, _ = hub.load(
                self.name, num_classes=head_classes, ckpt=ckpt,
                input_shape=(1, self.image_size, self.image_size, 3),
                seed=seed)
            if variables is None:
                variables = hub_vars
        self.model = model
        if variables is None:
            variables = model.init(
                jax.random.key(seed),
                jnp.zeros((1, self.image_size, self.image_size, 3),
                          jnp.float32), train=False)
            if ckpt:
                from ..core.checkpoint import restore_variables
                variables = restore_variables(ckpt, variables)
        if weight_quant not in ("fp32", "int8"):
            raise ValueError(f"weight_quant must be fp32 or int8, "
                             f"got {weight_quant!r}")
        self.weight_quant = weight_quant
        self._quant_meta = None
        self._quant_treedef = None
        if weight_quant == "int8":
            variables, self._quant_meta, self._quant_treedef = \
                _quantize_variables(variables)
        # the session's single resident copy of the weights (int8
        # payloads + block scales when weight_quant="int8")
        self._variables = jax.device_put(variables)

        # counters: the "zero compiles after warmup" test surface
        self.trace_count = 0        # bumped inside the traced forward
        self.compile_count = 0      # bumped per lower().compile()
        self.warmup_seconds: Dict[int, float] = {}   # per-bucket warmup
        self._forward = self._make_forward()
        self._executables: Dict[int, Any] = {}
        self._compile_lock = threading.Lock()
        if precompile:
            self.warmup()

    # ------------------------------------------------------- forward fn
    def _make_forward(self) -> Callable:
        inner = self._make_inner_forward()
        if self.weight_quant != "int8":
            return inner
        meta, treedef = self._quant_meta, self._quant_treedef

        def forward(qleaves, images):
            # dequantize inside the trace: the executable reads int8
            # payloads from HBM and reconstructs fp32 weights on the fly
            return inner(_dequantize_variables(qleaves, meta, treedef),
                         images)
        return forward

    def _make_inner_forward(self) -> Callable:
        model = self.model
        if self.task == "classify":
            if self.tta:
                from ..ops.tta import classify_tta

                def forward(variables, images):
                    self.trace_count += 1   # runs at trace time only
                    return classify_tta(
                        lambda im: model.apply(variables, im,
                                               train=False), images)
            else:
                def forward(variables, images):
                    self.trace_count += 1
                    return jax.nn.softmax(
                        model.apply(variables, images, train=False), -1)
            return forward

        from ..models.detection.predict import build_predict_fn
        predict = build_predict_fn(
            model, self.name, self.num_classes,
            score_thresh=self.score_thresh, max_det=self.max_det,
            post_nms_top_n=self.post_nms_top_n, nms_impl=self.nms_impl)

        def forward(variables, images):
            self.trace_count += 1
            return predict(variables["params"],
                           variables.get("batch_stats", {}), images)
        return forward

    # --------------------------------------------------------- buckets
    def bucket_for(self, n: int) -> int:
        """Smallest bucket admitting ``n`` requests (largest bucket for
        oversize batches — callers chunk, see ``infer``)."""
        for b in self.buckets:
            if b >= n:
                return b
        return self.buckets[-1]

    def bucket_spec(self, bucket: int) -> jax.ShapeDtypeStruct:
        """Abstract input spec of one bucket — what warmup lowers
        against (the loader ``element_spec`` idiom: no data touched)."""
        return jax.ShapeDtypeStruct(
            (bucket, self.image_size, self.image_size, 3), jnp.float32)

    def _compile_bucket(self, bucket: int):
        with self._compile_lock:
            if bucket not in self._executables:
                lowered = jax.jit(self._forward).lower(
                    self._variables, self.bucket_spec(bucket))
                self._executables[bucket] = tracked_compile(
                    lowered, f"serve/{self.name}/b{bucket}")
                self.compile_count += 1
            return self._executables[bucket]

    def warmup(self) -> Dict[int, float]:
        """AOT-compile every bucket (persistent-cache-backed); returns
        {bucket: seconds}. Idempotent — a warmed engine never compiles
        again, which is exactly what the serve tests assert."""
        import time
        times = {}
        for b in self.buckets:
            t0 = time.perf_counter()
            self._compile_bucket(b)
            times[b] = time.perf_counter() - t0
        self.warmup_seconds.update(times)
        return times

    # ------------------------------------------------------- execution
    def run(self, bucket: int, images) -> Any:
        """Execute one bucket's AOT executable on an exactly-``bucket``
        row batch. Never traces or compiles for a warmed bucket; returns
        DEVICE outputs (callers materialize — the dispatch thread stays
        sync-free)."""
        if bucket not in self.buckets:
            raise ValueError(f"unknown bucket {bucket} "
                             f"(have {self.buckets})")
        images = jnp.asarray(images, jnp.float32)
        if images.shape[0] != bucket:
            raise ValueError(f"bucket {bucket} executable fed "
                             f"{images.shape[0]} rows")
        return self._compile_bucket(bucket)(self._variables, images)

    def pad_to_bucket(self, images: np.ndarray,
                      bucket: int) -> np.ndarray:
        """Zero-pad rows up to ``bucket`` (padded rows are sliced away
        before any caller sees them; for detection they additionally
        carry the class −1 convention end-to-end)."""
        n = images.shape[0]
        if n == bucket:
            return images
        pad = np.zeros((bucket - n, *images.shape[1:]), images.dtype)
        return np.concatenate([images, pad], axis=0)

    def infer(self, images, materialize: bool = True) -> Any:
        """Synchronous batched inference for ad-hoc callers (predict.py,
        loadgen's sequential baseline): pads to the smallest admitting
        bucket, runs, slices padding away; oversize inputs chunk through
        the largest bucket. The dynamic-batching request path is
        ``serve.batcher.MicroBatcher`` — this is the one-shot surface."""
        images = np.asarray(images, np.float32)  # dltpu: allow(DLT100) host input
        if images.ndim == 3:
            images = images[None]
        n = images.shape[0]
        big = self.buckets[-1]
        outs = []
        for start in range(0, n, big):
            chunk = images[start:start + big]
            bucket = self.bucket_for(chunk.shape[0])
            out = self.run(bucket, self.pad_to_bucket(chunk, bucket))
            outs.append(jax.tree.map(
                lambda a, k=chunk.shape[0]: a[:k], out))
        out = outs[0] if len(outs) == 1 else jax.tree.map(
            lambda *xs: jnp.concatenate(xs, axis=0), *outs)
        if materialize:
            out = jax.tree.map(np.asarray, out)
        return out

    # ------------------------------------------------------ introspection
    def variables_nbytes(self) -> int:
        """Resident weight bytes (host metadata read over the device
        arrays — never a sync). With ``weight_quant="int8"`` this is the
        quantized footprint, the number HBM actually pays."""
        return int(sum(getattr(x, "nbytes", 0) for x in
                       jax.tree_util.tree_leaves(self._variables)))

    def stats(self) -> Dict[str, Any]:
        return {
            "model": self.name,
            "task": self.task,
            "image_size": self.image_size,
            "buckets": list(self.buckets),
            "trace_count": self.trace_count,
            "compile_count": self.compile_count,
            "warm": self.compile_count >= len(self.buckets),
            "weight_quant": self.weight_quant,
            "variables_bytes": self.variables_nbytes(),
            "warmup_seconds": {str(b): round(s, 4)
                               for b, s in self.warmup_seconds.items()},
        }
