"""Deterministic PRNG management.

Replaces the reference's per-project ``torch.manual_seed(seed + rank)``
idiom (classification/swin_transformer/main.py:321-323) with JAX's explicit
key threading: one root key per experiment, folded per-host and per-step so
every jitted step is deterministic and replicable.
"""

from __future__ import annotations

from typing import Dict, Sequence

import jax
import jax.numpy as jnp


def root_key(seed: int) -> jax.Array:
    return jax.random.key(seed)


def host_key(seed: int) -> jax.Array:
    """Per-host key: distinct data-augmentation streams on each host."""
    return jax.random.fold_in(jax.random.key(seed), jax.process_index())


def step_key(key: jax.Array, step: jax.Array | int) -> jax.Array:
    """Fold the global step in — makes each train step's dropout/augment
    stream independent while keeping resume-determinism (the same step
    replayed after a checkpoint restore sees the same randomness)."""
    return jax.random.fold_in(key, jnp.asarray(step, jnp.uint32))


def split_named(key: jax.Array, names: Sequence[str]) -> Dict[str, jax.Array]:
    keys = jax.random.split(key, len(names))
    return dict(zip(names, keys))
