"""Orbax-backed checkpointing with auto-resume and key-surgery loading.

TPU-native replacement for the reference's checkpoint stack (SURVEY.md §5):
full train-state dicts {model, optimizer, lr_scheduler, scaler, epoch,
max_accuracy} (swin utils/torch_utils.py:233-245 save / :116-141 load),
auto-resume directory scan (:261-271), rank-0-only writes
(others/train_with_DDP/train.py:303-308), best-copy
(classification/mnist/train.py:158-165), and partial/pretrained loading
with key surgery (others/load_weights_test/load_weights.py, swin
load_pretrained torch_utils.py:143-231).

Orbax handles multi-host coordination and sharded pytree save/restore, so
unlike the reference no "rank 0 only" guard is needed around saves.
"""

from __future__ import annotations

import glob
import json
import os
import re
import shutil
import time
import zlib
from typing import Any, Callable, Dict, Optional

import jax
import numpy as np
import orbax.checkpoint as ocp

from .logging import create_logger


def _file_crc(path: str) -> tuple[int, int]:
    """Streaming (crc32, size) of one file — 1 MB chunks, so verifying a
    multi-GB checkpoint never materializes it in host RAM."""
    crc, size = 0, 0
    with open(path, "rb") as f:
        while True:
            chunk = f.read(1 << 20)
            if not chunk:
                break
            crc = zlib.crc32(chunk, crc)
            size += len(chunk)
    return crc & 0xFFFFFFFF, size


def checksum_dir(root: str) -> Dict[str, Dict[str, int]]:
    """{relpath: {crc32, size}} over every file under ``root`` — the
    integrity record written beside a committed checkpoint step."""
    out: Dict[str, Dict[str, int]] = {}
    for dirpath, _, names in os.walk(root):
        for name in names:
            path = os.path.join(dirpath, name)
            try:
                crc, size = _file_crc(path)
            except OSError:
                continue
            out[os.path.relpath(path, root)] = {"crc32": crc, "size": size}
    return out


class CheckpointManager:
    """Step-numbered checkpoints + best tracking + auto-resume.

    ``async_save=True`` enables Orbax async checkpointing: ``save``
    snapshots device arrays and returns while the host write happens on
    a background thread, so the train loop keeps stepping during I/O —
    the TPU-native answer to the reference's blocking per-epoch
    ``torch.save`` (training stalls for the full serialize+write there).
    In-flight writes are awaited before the next save, before any
    best-copy, and on close()."""

    def __init__(self, directory: str, max_to_keep: int = 3,
                 async_save: bool = False, save_retries: int = 2,
                 retry_base_s: float = 0.25, retry_max_s: float = 4.0):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True,
                best_fn=None, enable_async_checkpointing=async_save),
        )
        self._async = async_save
        self._pending_best: Optional[int] = None
        # steps whose async write hasn't committed yet — checksummed at
        # the next wait_until_finished(), when the files exist on disk
        self._pending_checksums: set[int] = set()
        self._save_retries = int(save_retries)
        self._retry_base_s = float(retry_base_s)
        self._retry_max_s = float(retry_max_s)
        self._logger = create_logger()

    def _finish_pending_best(self) -> None:
        if self._pending_best is None or jax.process_index() != 0:
            self._pending_best = None
            return
        step, self._pending_best = self._pending_best, None
        best = os.path.join(self.directory, "best")
        src = os.path.join(self.directory, str(step))
        if os.path.isdir(src):
            if os.path.isdir(best):
                shutil.rmtree(best)
            shutil.copytree(src, best)

    def save(self, step: int, state: Any, metrics: Optional[Dict] = None,
             is_best: bool = False,
             topology: Optional[Dict[str, Any]] = None) -> None:
        """``topology``: fingerprint dict (``elastic.topology.
        current_topology``) recorded in a JSON sidecar next to the step,
        so a resume on different hardware can tell — and report — that
        it is re-sharding."""
        if self._pending_best is not None:
            # the previous async write has committed by now; copy its
            # best BEFORE this save can trigger max_to_keep GC of it
            self.wait_until_finished()
        self._save_with_retry(step, state, metrics)
        if topology is not None:
            self._write_topology(step, topology)
        if self._async:
            self._pending_checksums.add(step)
        else:
            self._mgr.wait_until_finished()
            self._write_checksums(step)
        if is_best:
            self._pending_best = step
            if not self._async:
                self._finish_pending_best()

    def wait_until_finished(self) -> None:
        self._mgr.wait_until_finished()
        while self._pending_checksums:
            self._write_checksums(self._pending_checksums.pop())
        self._finish_pending_best()

    def _save_with_retry(self, step: int, state: Any,
                         metrics: Optional[Dict]) -> None:
        """Save with capped-exponential-backoff retries (the supervisor's
        one backoff curve). Between attempts the partial step dir and any
        Orbax staging dirs are cleared so the retry writes into a clean
        slot — a half-written dir would otherwise fail the atomic-rename
        commit forever."""
        last_exc: Optional[BaseException] = None
        for attempt in range(1, self._save_retries + 2):
            try:
                self._mgr.save(step, args=ocp.args.StandardSave(state),
                               metrics=metrics)
                return
            except Exception as exc:  # noqa: BLE001 - classified below
                last_exc = exc
                from ..obs import flight
                flight.record("ckpt_retry", step=int(step), attempt=attempt,
                              error=repr(exc))
                if attempt > self._save_retries:
                    break
                try:
                    self._mgr.wait_until_finished()
                except Exception:  # noqa: BLE001 - already failing
                    pass
                if jax.process_index() == 0:
                    for pattern in (str(step), f"{step}.orbax*"):
                        for path in glob.glob(
                                os.path.join(self.directory, pattern)):
                            shutil.rmtree(path, ignore_errors=True)
                self._mgr.reload()
                from ..elastic.supervisor import backoff_schedule
                delay = backoff_schedule(
                    attempt, base_s=self._retry_base_s, factor=2.0,
                    max_s=self._retry_max_s, jitter=0.25)
                self._logger.warning(
                    f"checkpoint save step {step} failed "
                    f"(attempt {attempt}/{self._save_retries + 1}): "
                    f"{exc!r}; retrying in {delay:.2f}s")
                time.sleep(delay)
        assert last_exc is not None
        raise last_exc

    def flush(self) -> None:
        """Barrier: block until every in-flight async write has
        committed. This is what the preemption guard calls from the
        SIGTERM handler — after it returns, the newest checkpoint on
        disk is complete and a restart loses nothing."""
        self.wait_until_finished()

    # -------------------------------------------------- topology sidecar
    # One JSON file for the whole directory ({step: fingerprint}), not a
    # file inside each step dir: Orbax owns the step dirs (atomic-rename
    # commit + GC) and a foreign file there would race both.
    _TOPOLOGY_KEEP = 32

    def _topology_path(self) -> str:
        return os.path.join(self.directory, "topology.json")

    def _write_topology(self, step: int, topology: Dict[str, Any]) -> None:
        if jax.process_index() != 0:
            return
        try:
            docs = self._read_topology_file()
            docs[str(step)] = topology
            if len(docs) > self._TOPOLOGY_KEEP:
                for key in sorted(docs, key=int)[:-self._TOPOLOGY_KEEP]:
                    del docs[key]
            tmp = self._topology_path() + ".tmp"
            with open(tmp, "w") as f:
                json.dump(docs, f, indent=1)
            os.replace(tmp, self._topology_path())
        except (OSError, ValueError) as e:
            self._logger.warning(f"topology sidecar write failed: {e}")

    def _read_topology_file(self) -> Dict[str, Any]:
        try:
            with open(self._topology_path()) as f:
                docs = json.load(f)
            return docs if isinstance(docs, dict) else {}
        except (OSError, ValueError):
            return {}

    def topology(self, step: Optional[int] = None) -> Optional[Dict[str, Any]]:
        """Fingerprint recorded at ``step`` (default: latest step); None
        for checkpoints saved without one."""
        step = self.latest_step() if step is None else step
        if step is None:
            return None
        return self._read_topology_file().get(str(step))

    # -------------------------------------------------- checksum sidecar
    # Same shape as the topology sidecar: ONE JSON file for the whole
    # directory ({step: {relpath: {crc32, size}}}), never a file inside
    # the step dirs Orbax owns.
    _CHECKSUM_KEEP = 32

    def _checksum_path(self) -> str:
        return os.path.join(self.directory, "checksums.json")

    def _write_checksums(self, step: int) -> None:
        if jax.process_index() != 0:
            return
        root = os.path.join(self.directory, str(step))
        if not os.path.isdir(root):
            return
        try:
            docs = self._read_checksum_file()
            docs[str(step)] = checksum_dir(root)
            if len(docs) > self._CHECKSUM_KEEP:
                for key in sorted(docs, key=int)[:-self._CHECKSUM_KEEP]:
                    del docs[key]
            tmp = self._checksum_path() + ".tmp"
            with open(tmp, "w") as f:
                json.dump(docs, f)
            os.replace(tmp, self._checksum_path())
        except (OSError, ValueError) as e:
            self._logger.warning(f"checksum sidecar write failed: {e}")

    def _read_checksum_file(self) -> Dict[str, Any]:
        try:
            with open(self._checksum_path()) as f:
                docs = json.load(f)
            return docs if isinstance(docs, dict) else {}
        except (OSError, ValueError):
            return {}

    def verify_step(self, step: int) -> bool:
        """True when every file recorded at save time still exists with
        matching size+crc32. A step with no sidecar entry (saved before
        hardening, or by a foreign writer) is trusted — verification
        can only ever REJECT known-bad data, never block a resume."""
        recorded = self._read_checksum_file().get(str(step))
        if recorded is None:
            return True
        root = os.path.join(self.directory, str(step))
        for rel, meta in recorded.items():
            path = os.path.join(root, rel)
            try:
                crc, size = _file_crc(path)
            except OSError:
                return False
            if size != meta.get("size") or crc != meta.get("crc32"):
                return False
        return True

    def _quarantine_step(self, step: int, reason: str) -> None:
        """Move a corrupt step dir aside (``corrupt-<step>`` — non-numeric,
        so Orbax's step scan ignores it) instead of deleting: the operator
        may want the carcass for forensics."""
        from ..obs import flight
        flight.record("ckpt_corrupt", step=int(step), reason=reason)
        self._logger.warning(
            f"checkpoint step {step} failed integrity check ({reason}); "
            f"moving aside and falling back")
        if jax.process_index() == 0:
            src = os.path.join(self.directory, str(step))
            dst = os.path.join(self.directory, f"corrupt-{step}")
            try:
                if os.path.isdir(dst):
                    shutil.rmtree(dst)
                if os.path.isdir(src):
                    os.replace(src, dst)
            except OSError as e:
                self._logger.warning(f"could not quarantine step {step}: {e}")
        self._mgr.reload()

    def _newest_step_at_most(self, ceiling: Optional[int]) -> Optional[int]:
        steps = [s for s in self._mgr.all_steps()
                 if ceiling is None or s <= ceiling]
        return max(steps) if steps else None

    def restore_verified(self, state: Any,
                         step: Optional[int] = None) -> tuple[Any, int]:
        """Integrity-checked restore with fallback: verify the newest
        step (<= ``step`` if given) against its checksum sidecar, restore
        it, and on mismatch or restore failure quarantine the dir and
        walk back to the next-newest intact step. Returns ``(None, 0)``
        when nothing restorable remains."""
        first: Optional[int] = None
        ceiling = step
        while True:
            candidate = self._newest_step_at_most(ceiling)
            if candidate is None:
                return None, 0
            if first is None:
                first = candidate
            if not self.verify_step(candidate):
                self._quarantine_step(candidate, "checksum mismatch")
                ceiling = candidate - 1
                continue
            try:
                restored = self._mgr.restore(
                    candidate, args=ocp.args.StandardRestore(state))
            except Exception as exc:  # noqa: BLE001 - corrupt beyond crc
                self._quarantine_step(candidate, f"restore failed: {exc!r}")
                ceiling = candidate - 1
                continue
            if candidate != first:
                from ..obs import flight
                flight.record("ckpt_fallback", from_step=int(first),
                              to_step=int(candidate))
                self._logger.warning(
                    f"restored fallback step {candidate} "
                    f"(newest step {first} was corrupt)")
            return restored, candidate

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def restore(self, state: Any, step: Optional[int] = None) -> Any:
        """Restore into the structure/shardings of ``state`` (an abstract
        or concrete pytree)."""
        step = self._mgr.latest_step() if step is None else step
        if step is None:
            return None
        return self._mgr.restore(step, args=ocp.args.StandardRestore(state))

    def auto_resume(self, state: Any) -> tuple[Any, int]:
        """Scan the directory for the newest checkpoint and restore it —
        the swin auto_resume_helper pattern (torch_utils.py:261-271).
        Restores into ``state``'s existing shardings; for resuming onto
        a *different* mesh use ``elastic.resume.elastic_restore``."""
        restored, step = self.restore_verified(state)
        if restored is None:
            return state, 0
        self._logger.info(f"auto-resume from step {step} in {self.directory}")
        try:
            from ..elastic import topology as topo
            from ..obs import flight
            saved = self.topology(step)
            current = topo.current_topology(state=state)
            cross = topo.topology_changed(saved, current) \
                if saved is not None else False
            flight.record("resume", step=int(step),
                          cross_topology=bool(cross),
                          saved_topology=topo.topology_str(saved),
                          current_topology=topo.topology_str(current))
            if cross:
                self._logger.info(
                    "cross-topology resume: saved on "
                    f"{topo.topology_str(saved)}, restoring on "
                    f"{topo.topology_str(current)}")
        except Exception:  # noqa: BLE001 - telemetry must not block resume
            pass
        return restored, step

    def close(self) -> None:
        self.wait_until_finished()
        self._mgr.close()


def save_pytree(path: str, tree: Any) -> None:
    """One-shot save of a pytree (e.g. exported params) without a manager."""
    path = os.path.abspath(path)
    if os.path.isdir(path):
        shutil.rmtree(path)
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(path, tree)


def load_pytree(path: str, target: Optional[Any] = None) -> Any:
    path = os.path.abspath(path)
    # CheckpointManager steps wrap the tree in a "default" item dir
    default = os.path.join(path, "default")
    if not os.path.exists(os.path.join(path, "_METADATA")) \
            and os.path.exists(os.path.join(default, "_METADATA")):
        path = default
    with ocp.StandardCheckpointer() as ckptr:
        if target is not None:
            return ckptr.restore(path, target)
        return ckptr.restore(path)


def restore_variables(path: str, init_variables: Dict[str, Any],
                      prefer_ema: bool = True) -> Dict[str, Any]:
    """ONE interpretation of an inference checkpoint for every CLI
    (predict/evaluate/demo previously each re-implemented this
    differently). Accepts a TrainState-style dict ({params, ema_params?,
    batch_stats?, ...}) or a bare parameter tree, merges into the
    model's ``init`` variables, and by default prefers EMA weights —
    the reference evaluates EMA everywhere it tracks one (YOLOX
    trainer.py evaluate_and_save_model, yolov5 val). BatchNorm stats
    come from the checkpoint when present: eval with init-time stats is
    silently wrong."""
    restored = load_pytree(path)
    variables = dict(init_variables)
    if isinstance(restored, dict) and (
            "params" in restored or "ema_params" in restored):
        params = None
        if prefer_ema:
            params = restored.get("ema_params")
        if params is None:
            params = restored.get("params")
        variables["params"] = params
        if restored.get("batch_stats"):
            variables["batch_stats"] = restored["batch_stats"]
    else:
        variables["params"] = restored
    return variables


def surgical_load(
    params: Dict[str, Any],
    pretrained: Dict[str, Any],
    rename: Optional[Dict[str, str]] = None,
    drop: Optional[list[str]] = None,
    resize_fn: Optional[Callable[[str, np.ndarray, tuple], np.ndarray]] = None,
) -> Dict[str, Any]:
    """Partial/renamed pretrained loading (load_weights_test pattern).

    Flattens both trees to '/'-joined paths; copies every pretrained leaf
    whose (renamed) path exists in ``params`` and matches shape. ``drop`` is
    a list of regexes to skip (e.g. the classifier head when num_classes
    differs — mnist/train.py:112-117). ``resize_fn(path, value, new_shape)``
    may adapt mismatched leaves (e.g. position-embedding interpolation, the
    analog of swin's relative-position-bias interpolation
    torch_utils.py:143-231); returning None skips the leaf.
    """
    flat_params = _flatten(params)
    flat_pre = _flatten(pretrained)
    rename = rename or {}
    drop_res = [re.compile(d) for d in (drop or [])]
    logger = create_logger()
    loaded, skipped = 0, []
    for path, value in flat_pre.items():
        tgt_path = rename.get(path, path)
        if any(r.search(tgt_path) for r in drop_res):
            skipped.append(tgt_path)
            continue
        if tgt_path not in flat_params:
            skipped.append(tgt_path)
            continue
        want = flat_params[tgt_path]
        value = np.asarray(value)
        if value.shape != want.shape:
            if resize_fn is not None:
                value = resize_fn(tgt_path, value, want.shape)
            if value is None or value.shape != want.shape:
                skipped.append(tgt_path)
                continue
        flat_params[tgt_path] = value.astype(np.asarray(want).dtype)
        loaded += 1
    if skipped:
        logger.info(f"surgical_load: loaded {loaded}, skipped {len(skipped)}: "
                    f"{skipped[:8]}{'...' if len(skipped) > 8 else ''}")
    return _unflatten(flat_params)


def resize_vit_pos_embed(path: str, value: np.ndarray,
                         new_shape: tuple) -> Optional[np.ndarray]:
    """``resize_fn`` for ViT ``pos_embed`` (1, 1+N, C): bicubic-free 2-D
    bilinear resize of the patch-grid part, cls token kept. The swin
    load_pretrained absolute_pos_embed interpolation analog
    (swin utils/torch_utils.py:186-201)."""
    if "pos_embed" not in path or value.ndim != 3 or len(new_shape) != 3:
        return None
    n_old, n_new = value.shape[1] - 1, new_shape[1] - 1
    g_old, g_new = int(round(n_old ** 0.5)), int(round(n_new ** 0.5))
    if g_old * g_old != n_old or g_new * g_new != n_new:
        return None
    cls, grid = value[:, :1], value[:, 1:]
    grid = grid.reshape(g_old, g_old, -1)
    grid = _bilinear_resize(grid, g_new, g_new)
    return np.concatenate(
        [cls, grid.reshape(1, g_new * g_new, -1)], axis=1)


def resize_relative_position_bias(path: str, value: np.ndarray,
                                  new_shape: tuple) -> Optional[np.ndarray]:
    """``resize_fn`` for swin ``relative_position_bias_table``
    ((2w-1)^2, H): bilinear resize over the (2w-1, 2w-1) offset grid when
    the window size changes (swin utils/torch_utils.py:160-185)."""
    if "relative_position_bias" not in path or value.ndim != 2 \
            or len(new_shape) != 2 or value.shape[1] != new_shape[1]:
        return None
    s_old = int(round(value.shape[0] ** 0.5))
    s_new = int(round(new_shape[0] ** 0.5))
    if s_old * s_old != value.shape[0] or s_new * s_new != new_shape[0]:
        return None
    grid = value.reshape(s_old, s_old, -1)
    grid = _bilinear_resize(grid, s_new, s_new)
    return grid.reshape(s_new * s_new, -1)


def default_resize_fn(path: str, value: np.ndarray,
                      new_shape: tuple) -> Optional[np.ndarray]:
    """Chain of the built-in interpolators; pass to surgical_load as
    ``resize_fn=default_resize_fn`` for ViT/Swin size transfers."""
    for fn in (resize_vit_pos_embed, resize_relative_position_bias):
        out = fn(path, value, new_shape)
        if out is not None:
            return out
    return None


def _bilinear_resize(grid: np.ndarray, h: int, w: int) -> np.ndarray:
    """(H, W, C) -> (h, w, C) bilinear, align_corners=True semantics (what
    torch F.interpolate uses in the swin loader for these tables)."""
    h_old, w_old = grid.shape[:2]
    if (h_old, w_old) == (h, w):
        return grid
    ys = np.linspace(0, h_old - 1, h)
    xs = np.linspace(0, w_old - 1, w)
    y0 = np.clip(np.floor(ys).astype(int), 0, h_old - 1)
    y1 = np.clip(y0 + 1, 0, h_old - 1)
    x0 = np.clip(np.floor(xs).astype(int), 0, w_old - 1)
    x1 = np.clip(x0 + 1, 0, w_old - 1)
    wy = (ys - y0)[:, None, None]
    wx = (xs - x0)[None, :, None]
    top = grid[y0][:, x0] * (1 - wx) + grid[y0][:, x1] * wx
    bot = grid[y1][:, x0] * (1 - wx) + grid[y1][:, x1] * wx
    return top * (1 - wy) + bot * wy


def _flatten(tree: Dict[str, Any], prefix: str = "") -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for k, v in tree.items():
        path = f"{prefix}/{k}" if prefix else k
        if isinstance(v, dict):
            out.update(_flatten(v, path))
        else:
            out[path] = v
    return out


def _unflatten(flat: Dict[str, Any]) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for path, v in flat.items():
        node = out
        parts = path.split("/")
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = v
    return out
