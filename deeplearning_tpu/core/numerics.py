"""Trace-time numerics mode: fast-TPU defaults vs exact-torch parity.

Round 4 switched ViT/Swin/ConvNeXt to exact-erf GELU for torch parity
(reference uses ``torch.nn.GELU()`` = erf, e.g.
classification/vision_transformer/vit_model.py:114) asserting the cost was
~0 because "the elementwise op fuses either way". Round 5 measured it on a
TPU v5e (tools/mfu_results.jsonl): the erf lowering costs **3.8 MFU
points** on the ViT-B/16 train step — 47.94% (erf) vs 51.71% (tanh) at
batch 128 — because XLA lowers erf to a long polynomial while tanh uses the
fast rational approximation.

Policy: training defaults to the tanh approximation (max abs deviation from
erf-GELU is ~1e-3, irrelevant to SGD); weight-port / reference-parity paths
enable exact mode. The flag is read at **trace time** only, so wrap
``model.init`` / ``model.apply`` (or the jit that traces them) — flipping it
after a function is compiled has no effect on the cached executable.

Usage:
    from deeplearning_tpu.core import numerics
    y = numerics.gelu(x)                 # in a flax module

    with numerics.exact_numerics():      # parity tests / torch-weight eval
        out = model.apply(variables, x)

    tools/train.py: ``model.exact_gelu=true`` sets the mode process-wide.
"""

from __future__ import annotations

import contextlib
from typing import Iterator

import flax.linen as nn
import jax

_EXACT = False


def exact_enabled() -> bool:
    return _EXACT


def set_exact(flag: bool) -> None:
    """Process-wide switch (CLI entry points). Prefer the context manager."""
    global _EXACT
    _EXACT = bool(flag)


@contextlib.contextmanager
def exact_numerics(flag: bool = True) -> Iterator[None]:
    """Temporarily select exact-torch numerics for anything traced inside."""
    global _EXACT
    old = _EXACT
    _EXACT = bool(flag)
    try:
        yield
    finally:
        _EXACT = old


def gelu(x: jax.Array) -> jax.Array:
    """GELU honoring the numerics mode.

    exact mode → erf (matches torch nn.GELU() bit-for-bit in f32);
    default   → tanh approximation (fast TPU lowering, measured above).
    """
    return nn.gelu(x, approximate=not _EXACT)
