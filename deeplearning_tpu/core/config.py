"""Unified config system: nested dataclasses + YAML overlay + CLI overrides.

Subsumes the reference's three config tiers (SURVEY.md §5): plain argparse
(classification/mnist/train.py:168-186), argparse+YAML merge
(others/train_with_DDP/train.py:41-80), and the yacs CfgNode tree with BASE
inheritance (classification/swin_transformer/config.py:3-60, main.py:30-81).
YOLOX-style "config as code" (yolox/exp/base_exp.py:17) is preserved by
letting experiments subclass the dataclasses directly.

Design: a config is any (nested) dataclass. ``load_config`` merges, in
order: dataclass defaults < BASE yaml files < the yaml file < dotted CLI
overrides (``opts=['train.lr', '3e-4']``), then returns a frozen instance.
"""

from __future__ import annotations

import copy
import dataclasses
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple, Type, TypeVar

import yaml

T = TypeVar("T")

_BASE_KEY = "_base_"


def asdict(cfg: Any) -> Dict[str, Any]:
    """Recursively convert a dataclass config to a plain dict."""
    if dataclasses.is_dataclass(cfg) and not isinstance(cfg, type):
        return {f.name: asdict(getattr(cfg, f.name)) for f in dataclasses.fields(cfg)}
    if isinstance(cfg, (list, tuple)):
        return type(cfg)(asdict(v) for v in cfg)
    if isinstance(cfg, dict):
        return {k: asdict(v) for k, v in cfg.items()}
    return cfg


def _coerce(value: Any, target_type: Any) -> Any:
    """Best-effort coercion of a YAML/CLI value to the field's type."""
    if value is None:
        return None
    origin = getattr(target_type, "__origin__", None)
    if origin in (tuple, Tuple):
        args = getattr(target_type, "__args__", ())
        if args and args[-1] is Ellipsis:
            return tuple(_coerce(v, args[0]) for v in value)
        if args and len(args) == len(value):
            return tuple(_coerce(v, t) for v, t in zip(value, args))
        return tuple(value)
    if origin in (list, List):
        args = getattr(target_type, "__args__", ())
        elem = args[0] if args else None
        return [_coerce(v, elem) if elem else v for v in value]
    if origin is not None:  # Optional[X] / Union
        for arg in getattr(target_type, "__args__", ()):
            if arg is type(None):
                continue
            try:
                return _coerce(value, arg)
            except (TypeError, ValueError):
                continue
        return value
    if isinstance(target_type, type):
        if target_type is bool and isinstance(value, str):
            return value.lower() in ("1", "true", "yes", "on")
        if target_type in (int, float, str) and not isinstance(value, target_type):
            return target_type(value)
    return value


def merge_dict(cfg: T, overrides: Dict[str, Any], strict: bool = True) -> T:
    """Return a new config with ``overrides`` (a nested dict) merged in."""
    if not dataclasses.is_dataclass(cfg):
        raise TypeError(f"merge_dict expects a dataclass, got {type(cfg)}")
    field_map = {f.name: f for f in dataclasses.fields(cfg)}
    # resolve string annotations (`from __future__ import annotations`
    # makes f.type the STRING "float", which _coerce would skip — a CLI
    # "1e-4" would then survive as a string into optax)
    try:
        import typing
        hints = typing.get_type_hints(type(cfg))
    except Exception:                                    # noqa: BLE001
        hints = {}
    updates = {}
    for key, value in overrides.items():
        if key == _BASE_KEY:
            continue
        if key not in field_map:
            if strict:
                raise KeyError(
                    f"Unknown config key {key!r} for {type(cfg).__name__}; "
                    f"valid keys: {sorted(field_map)}"
                )
            continue
        current = getattr(cfg, key)
        if dataclasses.is_dataclass(current) and isinstance(value, dict):
            updates[key] = merge_dict(current, value, strict=strict)
        else:
            updates[key] = _coerce(value, hints.get(key,
                                                    field_map[key].type))
    return dataclasses.replace(cfg, **updates)


def _parse_dotted(opts: Sequence[str]) -> Dict[str, Any]:
    """``['a.b', '1', 'c', 'true']`` or ``['a.b=1']`` → nested dict."""
    flat: List[Tuple[str, str]] = []
    i = 0
    opts = list(opts)
    while i < len(opts):
        if "=" in opts[i]:
            k, v = opts[i].split("=", 1)
            flat.append((k, v))
            i += 1
        else:
            if i + 1 >= len(opts):
                raise ValueError(f"Dangling config override key {opts[i]!r}")
            flat.append((opts[i], opts[i + 1]))
            i += 2
    nested: Dict[str, Any] = {}
    for key, raw in flat:
        try:
            value = yaml.safe_load(raw)
        except yaml.YAMLError:
            value = raw
        node = nested
        parts = key.split(".")
        for part in parts[:-1]:
            node = node.setdefault(part, {})
        node[parts[-1]] = value
    return nested


def _load_yaml_with_bases(path: str) -> Dict[str, Any]:
    """Load a YAML file, recursively resolving ``_base_`` inheritance
    (the yacs BASE pattern, swin config.py:62-80)."""
    with open(path) as f:
        data = yaml.safe_load(f) or {}
    bases = data.pop(_BASE_KEY, [])
    if isinstance(bases, str):
        bases = [bases]
    merged: Dict[str, Any] = {}
    for base in bases:
        base_path = base if os.path.isabs(base) else os.path.join(
            os.path.dirname(path), base)
        _deep_update(merged, _load_yaml_with_bases(base_path))
    _deep_update(merged, data)
    return merged


def _deep_update(dst: Dict[str, Any], src: Dict[str, Any]) -> Dict[str, Any]:
    for k, v in src.items():
        if isinstance(v, dict) and isinstance(dst.get(k), dict):
            _deep_update(dst[k], v)
        else:
            dst[k] = copy.deepcopy(v)
    return dst


def load_config(
    defaults: T,
    yaml_path: Optional[str] = None,
    opts: Optional[Sequence[str]] = None,
    strict: bool = True,
) -> T:
    """defaults < yaml (with _base_ chain) < dotted CLI opts."""
    cfg = defaults
    if yaml_path:
        cfg = merge_dict(cfg, _load_yaml_with_bases(yaml_path), strict=strict)
    if opts:
        cfg = merge_dict(cfg, _parse_dotted(opts), strict=strict)
    return cfg


def save_config(cfg: Any, path: str) -> None:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w") as f:
        yaml.safe_dump(asdict(cfg), f, sort_keys=False)


def pop_flag(argv: list, name: str) -> Optional[str]:
    """Extract ``name VALUE`` or ``name=VALUE`` from argv in place and
    return the value (None if absent). For CLI flags that must be read
    before config_cli's argparse (e.g. --exp / --task selectors).

    The scan stops at a literal ``--`` separator so a matching token that
    is merely another flag's VALUE can be protected: put it after ``--``.
    The selector flag itself must therefore precede any ``--``."""
    for i, a in enumerate(argv):
        if a == "--":
            return None
        if a == name:
            if i + 1 >= len(argv):
                raise SystemExit(f"{name} requires a value")
            value = argv[i + 1]
            del argv[i:i + 2]
            return value
        if a.startswith(name + "="):
            del argv[i]
            return a.split("=", 1)[1]
    return None


def config_cli(defaults: T, argv: Optional[Sequence[str]] = None,
               description: str = "") -> T:
    """Standard CLI: ``prog [--cfg FILE] [key value | key=value ...]``."""
    import argparse

    parser = argparse.ArgumentParser(description=description)
    parser.add_argument("--cfg", type=str, default=None, help="YAML config file")
    parser.add_argument("opts", nargs="*", default=[],
                        help="dotted overrides: train.lr 3e-4 or train.lr=3e-4")
    args = parser.parse_args(argv)
    return load_config(defaults, args.cfg, args.opts)
