"""Library-wide persistent XLA compile cache.

One helper instead of the cache block previously copy-pasted in
``bench.py`` and ``tools/bench_util.py``: every entry point (training
CLIs, experiment loader, perf tools) calls ``enable_compile_cache()`` so
a given step function is compiled at most once per machine, not once per
process. On a wedge-prone remote-tunnel TPU the cold ViT-B/16 train-step
compile is the longest single device-holding operation any tool runs;
serializing the executable makes every later invocation near-instant.

Env overrides:
- ``DLTPU_COMPILE_CACHE=<dir>`` relocates the cache.
- ``DLTPU_COMPILE_CACHE=0`` (or ``off``/``none``) disables it.
"""

from __future__ import annotations

import os
from typing import Optional

# repo-root .jax_cache — the same location bench.py has always used, so
# executables cached by the bench are hits for the CLIs and vice versa
_DEFAULT_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), ".jax_cache")

_enabled_dir: Optional[str] = None


def enable_compile_cache(cache_dir: Optional[str] = None) -> Optional[str]:
    """Point JAX's persistent compilation cache at ``cache_dir``
    (default: repo-root ``.jax_cache``, overridable via
    ``DLTPU_COMPILE_CACHE``). Idempotent and never fatal — the cache is
    an optimization, so any failure returns None instead of raising.
    Returns the active cache dir, or None when disabled/unavailable."""
    global _enabled_dir
    env = os.environ.get("DLTPU_COMPILE_CACHE", "")
    if env.lower() in ("0", "off", "none", "false"):
        return None
    cache_dir = cache_dir or env or _DEFAULT_DIR
    if _enabled_dir == cache_dir:
        return _enabled_dir
    try:
        import jax
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # cache even sub-second compiles: CPU smoke runs benefit too, and
        # the min-entry-size floor would otherwise skip small executables
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception:  # noqa: BLE001 - never fail an entry point over caching
        return None
    _enabled_dir = cache_dir
    return _enabled_dir


def active_cache_dir() -> Optional[str]:
    """The directory enabled by ``enable_compile_cache``, if any."""
    return _enabled_dir
