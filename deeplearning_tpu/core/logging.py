"""Process-0 logging + metric meters + TensorBoard writer.

TPU-native rework of the reference's logging stack (SURVEY.md §5):
per-rank colored logger (swin utils/logger.py:9), AverageMeter/ProgressMeter
(swin utils/torch_utils.py:342,367), SmoothedValue/MetricLogger
(fasterRcnn utils/distributed_utils.py:12,144), TensorBoard SummaryWriter
usage across 39 files. Cross-replica metric reduction happens on-device via
``jax.lax.pmean`` inside jitted steps, so host-side meters stay simple.
"""

from __future__ import annotations

import logging
import os
import sys
import time
from collections import defaultdict, deque
from typing import Any, Dict, Iterable, Optional, Sequence

import jax
import numpy as np

_LOGGERS: Dict[str, logging.Logger] = {}
# output dirs a cached logger already writes to — a cache hit with a NEW
# dir attaches its file handler instead of silently dropping the dir
# (the old behavior lost the second run's log file entirely)
_LOGGER_DIRS: Dict[str, set] = {}


def is_main_process() -> bool:
    return jax.process_index() == 0


def _fmt() -> logging.Formatter:
    fmt = (f"[%(asctime)s p{jax.process_index()}] "
           "(%(filename)s:%(lineno)d) %(levelname)s: %(message)s")
    return logging.Formatter(fmt, datefmt="%Y-%m-%d %H:%M:%S")


def _attach_file(logger: logging.Logger, name: str,
                 output_dir: str) -> None:
    if output_dir in _LOGGER_DIRS.setdefault(name, set()):
        return
    os.makedirs(output_dir, exist_ok=True)
    fh = logging.FileHandler(
        os.path.join(output_dir, f"log_p{jax.process_index()}.txt"))
    fh.setLevel(logging.DEBUG)
    fh.setFormatter(_fmt())
    logger.addHandler(fh)
    _LOGGER_DIRS[name].add(output_dir)


def create_logger(name: str = "dltpu", output_dir: Optional[str] = None,
                  to_console: bool = True) -> logging.Logger:
    """Formatted logger; console on process 0 only, per-process file logs.

    Cached by ``name``, but an ``output_dir`` the cached logger has not
    seen yet still gets a file handler — so two sequential runs in one
    process each produce their own log file."""
    if name in _LOGGERS:
        logger = _LOGGERS[name]
        if output_dir:
            _attach_file(logger, name, output_dir)
        return logger
    logger = logging.getLogger(name)
    logger.setLevel(logging.DEBUG)
    logger.propagate = False
    if to_console and is_main_process():
        h = logging.StreamHandler(sys.stdout)
        h.setLevel(logging.INFO)
        h.setFormatter(_fmt())
        logger.addHandler(h)
    _LOGGERS[name] = logger
    if output_dir:
        _attach_file(logger, name, output_dir)
    return logger


class AverageMeter:
    """Running average over a window plus a global average."""

    def __init__(self, window: int = 50):
        self._window: deque = deque(maxlen=window)
        self.sum = 0.0
        self.count = 0

    def update(self, value: float, n: int = 1) -> None:
        value = float(value)
        self._window.append(value)
        self.sum += value * n
        self.count += n

    @property
    def avg(self) -> float:
        return self.sum / max(self.count, 1)

    @property
    def smoothed(self) -> float:
        return float(np.mean(self._window)) if self._window else 0.0

    def reset(self) -> None:
        self._window.clear()
        self.sum = 0.0
        self.count = 0


class MetricLogger:
    """Dict of AverageMeters + iteration timing + ETA, tqdm-free."""

    def __init__(self, delimiter: str = "  ", window: int = 50):
        self.meters: Dict[str, AverageMeter] = defaultdict(
            lambda: AverageMeter(window))
        self.delimiter = delimiter

    def update(self, **kwargs: float) -> None:
        for k, v in kwargs.items():
            if hasattr(v, "item"):
                v = float(v)
            self.meters[k].update(v)

    def __getattr__(self, name: str) -> AverageMeter:
        if name in self.meters:
            return self.meters[name]
        raise AttributeError(name)

    def __str__(self) -> str:
        return self.delimiter.join(
            f"{k}: {m.smoothed:.4f} ({m.avg:.4f})" for k, m in self.meters.items())

    def log_every(self, iterable: Iterable, print_freq: int,
                  logger: Optional[logging.Logger] = None,
                  header: str = "") -> Iterable:
        logger = logger or create_logger()
        n = len(iterable) if hasattr(iterable, "__len__") else None
        iter_time = AverageMeter()
        end = time.time()
        for i, obj in enumerate(iterable):
            yield obj
            iter_time.update(time.time() - end)
            end = time.time()
            if i % print_freq == 0 or (n and i == n - 1):
                eta = ""
                if n:
                    eta = f" eta: {iter_time.smoothed * (n - i - 1):.0f}s"
                logger.info(f"{header} [{i}{'/' + str(n) if n else ''}]"
                            f" {self}{eta} iter_t: {iter_time.smoothed:.4f}s")


class TensorBoardWriter:
    """Thin process-0-only wrapper over torch's SummaryWriter; no-op elsewhere.

    Covers the reference's TB feature tour (others/tensorboard_test/
    train.py:77-158): scalars, images, histograms, figures.
    """

    def __init__(self, log_dir: Optional[str]):
        self._writer = None
        if log_dir is not None and is_main_process():
            try:
                from torch.utils.tensorboard import SummaryWriter
                self._writer = SummaryWriter(log_dir)
            except ImportError:
                pass

    def add_scalar(self, tag: str, value: Any, step: int) -> None:
        if self._writer:
            self._writer.add_scalar(tag, float(value), step)

    def add_scalars(self, scalars: Dict[str, Any], step: int) -> None:
        for tag, value in scalars.items():
            self.add_scalar(tag, value, step)

    def add_image(self, tag: str, img: np.ndarray, step: int,
                  dataformats: str = "HWC") -> None:
        if self._writer:
            self._writer.add_image(tag, img, step, dataformats=dataformats)

    def add_histogram(self, tag: str, values: np.ndarray, step: int) -> None:
        if self._writer:
            self._writer.add_histogram(tag, np.asarray(values), step)

    def add_figure(self, tag: str, figure: Any, step: int) -> None:
        if self._writer:
            self._writer.add_figure(tag, figure, step)

    def flush(self) -> None:
        if self._writer:
            self._writer.flush()

    def close(self) -> None:
        if self._writer:
            self._writer.close()


class CsvLogger:
    """Append-per-step CSV metrics file, process-0 only — the yolov5
    pluggable-loggers csv path (utils/loggers/__init__.py:17-27,
    results.csv). Columns are set on first write; later dicts may omit
    keys (blank cell), and new keys widen the header in place (the file
    is rewritten with the wider header, old rows padded with blanks)."""

    def __init__(self, path: Optional[str]):
        self._path = path if (path and is_main_process()) else None
        self._columns: Optional[list] = None

    def log(self, step: int, metrics: Dict[str, Any]) -> None:
        if self._path is None:
            return
        import csv
        import os
        row = {"step": step, **{k: _scalar(v) for k, v in metrics.items()}}
        write_header = False
        if self._columns is None:
            os.makedirs(os.path.dirname(os.path.abspath(self._path)),
                        exist_ok=True)
            # resumed run: adopt the existing file's header instead of
            # appending a duplicate header row mid-file
            if os.path.exists(self._path) and os.path.getsize(self._path):
                with open(self._path, newline="") as f:
                    self._columns = next(csv.reader(f), None)
            if self._columns is None:
                self._columns = list(row)
                write_header = True
        extra = [k for k in row if k not in self._columns]
        if extra:
            # extend the header in place (train/* rows come first, eval/*
            # appears later — dropping them would lose eval metrics):
            # rewrite the small file with the widened column set
            with open(self._path, newline="") as f:
                rows = list(csv.DictReader(f))
            self._columns = self._columns + extra
            with open(self._path, "w", newline="") as f:
                w = csv.DictWriter(f, self._columns)
                w.writeheader()
                w.writerows(rows)
            write_header = False
        with open(self._path, "a", newline="") as f:
            w = csv.DictWriter(f, self._columns, extrasaction="ignore")
            if write_header:
                w.writeheader()
            w.writerow(row)


def _scalar(v: Any) -> Any:
    if isinstance(v, bool):        # bools are metadata flags, not metrics
        return v
    try:
        return float(v)
    except (TypeError, ValueError):
        return v


# ---------------------------------------------------------------------------
# Pluggable logger backends — the yolov5 Loggers shape
# (utils/loggers/__init__.py:17-27: csv / TensorBoard / W&B behind one
# object). The W&B slot is an OFFLINE JSONL sink (this image has no
# network); its record structure mirrors a wandb offline run: one JSON
# object per log call with step + wall time + metrics, plus a final
# summary record.
# ---------------------------------------------------------------------------

from .registry import Registry

LOGGERS = Registry("loggers")


class JsonlLogger:
    """Offline W&B-style sink: runs/<dir>/metrics.jsonl."""

    def __init__(self, path: Optional[str]):
        self._path = path if (path and is_main_process()) else None

    def log(self, step: int, metrics: Dict[str, Any]) -> None:
        if self._path is None:
            return
        import json
        import os
        import time
        os.makedirs(os.path.dirname(os.path.abspath(self._path)),
                    exist_ok=True)
        rec = {"step": int(step), "time": time.time(),
               **{k: _scalar(v) for k, v in metrics.items()}}
        with open(self._path, "a") as f:
            f.write(json.dumps(rec) + "\n")

    def summary(self, results: Dict[str, Any]) -> None:
        self.log(-1, {"summary": True, **results})


@LOGGERS.register("tensorboard")
def _tb_backend(workdir: str):
    return TensorBoardWriter(workdir)


@LOGGERS.register("csv")
def _csv_backend(workdir: str):
    import os
    return CsvLogger(os.path.join(workdir, "results.csv"))


@LOGGERS.register("jsonl")
def _jsonl_backend(workdir: str):
    import os
    return JsonlLogger(os.path.join(workdir, "metrics.jsonl"))


class LoggerHub:
    """One dispatch point over the selected backends (the Loggers class
    analog). Unknown backend names fail loudly at construction — the
    reference prints and drops, which hides config typos."""

    def __init__(self, workdir: Optional[str],
                 backends: Sequence[str] = ("tensorboard", "csv",
                                            "jsonl")):
        self.workdir = workdir
        self.backends: Dict[str, Any] = {}
        if workdir:
            for name in backends:
                self.backends[name] = LOGGERS.build(name, workdir)

    @property
    def tb(self) -> "TensorBoardWriter":
        return self.backends.get("tensorboard") or TensorBoardWriter(None)

    def scalars(self, metrics: Dict[str, Any], step: int) -> None:
        for name, backend in self.backends.items():
            if isinstance(backend, TensorBoardWriter):
                backend.add_scalars(metrics, step)
            else:
                backend.log(step, metrics)

    def summary(self, results: Dict[str, Any]) -> None:
        for backend in self.backends.values():
            if hasattr(backend, "summary"):
                backend.summary(results)

    def close(self) -> None:
        for backend in self.backends.values():
            if hasattr(backend, "close"):
                backend.close()
