"""Config-as-code experiments — the YOLOX Exp system, TPU-native.

Surface of detection/YOLOX/yolox/exp/base_exp.py:17 (abstract BaseExp with
get_model / get_data_loader / get_optimizer / get_lr_scheduler /
get_evaluator factories; concrete yolox_base.py:16; exps/default/*.py
subclass-per-variant; merge() for CLI opts). An Exp is a plain Python
class whose attributes are the config and whose methods build the pieces;
``get_exp`` loads one from a file path or registry name — the pattern the
reference uses so experiments are versioned as code.
"""

from __future__ import annotations

import importlib.util
import os
from typing import Any, Dict, Optional, Sequence

from .registry import MODELS, Registry

EXPERIMENTS = Registry("experiments")


class BaseExp:
    """Subclass, set attributes, override factories as needed."""
    # mirrored attribute surface of yolox_base.Exp
    model_name: str = "mnist_cnn"
    num_classes: int = 10
    precision: str = "bf16"
    global_batch: int = 64
    max_epochs: int = 3
    base_lr: float = 0.05
    warmup_steps: int = 10
    optimizer: str = "sgd"
    weight_decay: float = 0.0
    scheduler: str = "warmup_cosine"
    label_smoothing: float = 0.0
    ema: bool = False
    seed: int = 0

    def merge(self, opts: Sequence[str]) -> "BaseExp":
        """Apply ['key', 'value', ...] or ['key=value'] CLI overrides
        (base_exp.py merge surface)."""
        import yaml
        i = 0
        opts = list(opts)
        pairs = []
        while i < len(opts):
            if "=" in opts[i]:
                k, v = opts[i].split("=", 1)
                pairs.append((k, v))
                i += 1
            else:
                if i + 1 >= len(opts):
                    raise ValueError(
                        f"missing value for option {opts[i]!r}")
                pairs.append((opts[i], opts[i + 1]))
                i += 2
        for k, v in pairs:
            if not hasattr(self, k):
                raise KeyError(f"Exp has no attribute {k!r}")
            cur = getattr(self, k)
            val = yaml.safe_load(v)
            if cur is not None and not isinstance(val, type(cur)):
                if isinstance(cur, float) and isinstance(val, int):
                    val = float(val)
                elif isinstance(cur, str):
                    val = str(val)
                else:
                    raise ValueError(
                        f"cannot assign {val!r} to {k} "
                        f"(expected {type(cur).__name__})")
            setattr(self, k, val)
        return self

    # ---- factories (override per experiment) ----
    def get_model(self, **kw):
        import jax.numpy as jnp
        dtype = jnp.bfloat16 if self.precision == "bf16" else jnp.float32
        return MODELS.build(self.model_name, num_classes=self.num_classes,
                            dtype=dtype, **kw)

    def get_lr_schedule(self, total_steps: int):
        from ..train.schedules import build_schedule
        return build_schedule(self.scheduler, base_lr=self.base_lr,
                              total_steps=total_steps,
                              warmup_steps=self.warmup_steps)

    def get_optimizer(self, schedule, params):
        from ..train.optim import build_optimizer
        return build_optimizer(self.optimizer, schedule,
                               weight_decay=self.weight_decay,
                               params=params)

    def get_loss_fn(self):
        from ..train.classification import make_loss_fn
        return make_loss_fn(self.label_smoothing)

    def get_eval_fn(self):
        from ..train.classification import make_metric_fn
        return make_metric_fn()


def get_exp(exp_file: Optional[str] = None, exp_name: Optional[str] = None
            ) -> BaseExp:
    """Load an Exp from a python file (must define ``Exp``) or from the
    EXPERIMENTS registry (yolox/exp/build.py get_exp surface)."""
    # every experiment run pays a step-function compile; make it a
    # once-per-machine cost instead of once-per-process
    from .compile_cache import enable_compile_cache
    enable_compile_cache()
    if exp_file:
        spec = importlib.util.spec_from_file_location(
            os.path.basename(exp_file).removesuffix(".py"), exp_file)
        module = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(module)
        return module.Exp()
    if exp_name:
        return EXPERIMENTS.build(exp_name)
    raise ValueError("provide exp_file or exp_name")


@EXPERIMENTS.register("mnist_smoke")
class MnistSmokeExp(BaseExp):
    pass


@EXPERIMENTS.register("vit_b16")
class ViTB16Exp(BaseExp):
    model_name = "vit_base_patch16_224"
    num_classes = 1000
    global_batch = 128
    base_lr = 1e-3
    optimizer = "adamw"
    weight_decay = 0.05
    label_smoothing = 0.1
    ema = True


@EXPERIMENTS.register("swin_tiny")
class SwinTinyExp(BaseExp):
    model_name = "swin_tiny_patch4_window7_224"
    num_classes = 1000
    global_batch = 128
    base_lr = 1e-3
    optimizer = "adamw"
    weight_decay = 0.05
    label_smoothing = 0.1
    ema = True


@EXPERIMENTS.register("resnet50")
class ResNet50Exp(BaseExp):
    model_name = "resnet50"
    num_classes = 1000
    global_batch = 256
    base_lr = 0.1
    optimizer = "sgd"
    weight_decay = 1e-4


@EXPERIMENTS.register("mae_pretrain")
class MAEPretrainExp(BaseExp):
    """MAE pretrain defaults (self-supervised/MAE/train.py surface:
    mask_ratio 0.75, LARS/AdamW large-batch schedule)."""
    model_name = "mae_vit_base_patch16"
    num_classes = 0                  # pretrain has no classifier head
    global_batch = 256
    base_lr = 1.5e-4
    optimizer = "adamw"
    weight_decay = 0.05
    ema = False

    def get_model(self, **kw):
        import jax.numpy as jnp
        dtype = jnp.bfloat16 if self.precision == "bf16" else jnp.float32
        # MAE has no num_classes field (reconstruction objective)
        return MODELS.build(self.model_name, dtype=dtype, **kw)


class DetectionExp(BaseExp):
    """Detector experiment — the yolox_base.py:16 Exp attribute surface
    (input_size, multiscale random_resize:167, test_conf) mapped onto the
    detection CLI's config tree. ``cli_overrides`` turns the exp into
    dotted overrides for tools/train_detection.py --exp."""
    model_name = "yolox_s"
    num_classes = 80
    img_size = 640
    max_gt = 50
    global_batch = 8
    max_steps = 300
    base_lr = 1e-3
    clip_grad_norm = 1.0
    score_thresh = 0.3               # test_conf analog
    multiscale = True                # random_resize bucketed analog

    def cli_overrides(self):
        return [
            f"model.name={self.model_name}",
            f"model.num_classes={self.num_classes}",
            f"model.image_size={self.img_size}",
            f"data.max_gt={self.max_gt}",
            f"data.batch={self.global_batch}",
            f"train.steps={self.max_steps}",
            f"train.lr={self.base_lr}",
            f"train.clip_grad_norm={self.clip_grad_norm}",
            f"train.eval_score_thresh={self.score_thresh}",
            f"train.multiscale={str(self.multiscale).lower()}",
        ]

    def get_evaluator(self):
        from ..evaluation.coco_eval import CocoEvaluator
        return CocoEvaluator(num_classes=self.num_classes)


def _det_exp(name, **attrs):
    cls = type(f"Exp_{name}", (DetectionExp,),
               {"model_name": attrs.pop("model_name", name), **attrs})
    EXPERIMENTS.register(name)(cls)
    return cls


# exps/default/* zoo (s/m/l/x scale by the registry model; tiny/nano use
# the reference's 416 input; yolov3 is the CSP-darknet53 variant)
_det_exp("yolox_s")
_det_exp("yolox_m")
_det_exp("yolox_l")
_det_exp("yolox_x")
_det_exp("yolox_tiny", img_size=416)
_det_exp("yolox_nano", img_size=416)
_det_exp("yolox_yolov3")
# exps/example/yolox_voc/yolox_voc_s.py analog
_det_exp("yolox_voc_s", model_name="yolox_s", num_classes=20)
