"""Mixed-precision policy for TPU.

The reference's AMP stack — ``torch.cuda.amp.autocast`` +
``NativeScalerWithGradNormCount`` (swin utils/torch_utils.py:297-323) —
exists because fp16 under/overflows. On TPU the compute dtype is bfloat16,
whose fp32-sized exponent makes loss scaling unnecessary; what we keep from
the reference scaler is gradient-norm measurement and clipping
(torch_utils.py:303-318), done here as pure optax-compatible transforms.

Policy: params and optimizer state in float32, activations/matmuls in
bfloat16 (``dtype=bf16, param_dtype=f32`` on every flax module), gradients
accumulated in float32.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class Policy:
    param_dtype: Any = jnp.float32
    compute_dtype: Any = jnp.bfloat16
    output_dtype: Any = jnp.float32

    def cast_to_compute(self, tree: Any) -> Any:
        return jax.tree.map(
            lambda x: x.astype(self.compute_dtype)
            if jnp.issubdtype(x.dtype, jnp.floating) else x, tree)

    def cast_to_param(self, tree: Any) -> Any:
        return jax.tree.map(
            lambda x: x.astype(self.param_dtype)
            if jnp.issubdtype(x.dtype, jnp.floating) else x, tree)


def get_policy(name: str = "bf16") -> Policy:
    if name in ("bf16", "bfloat16", "mixed"):
        return Policy()
    if name in ("f32", "float32", "full"):
        return Policy(compute_dtype=jnp.float32)
    raise ValueError(f"Unknown precision policy {name!r}")


def global_norm(tree: Any) -> jax.Array:
    return jnp.sqrt(sum(jnp.sum(jnp.square(x.astype(jnp.float32)))
                        for x in jax.tree.leaves(tree)))


def clip_by_global_norm(tree: Any, max_norm: Optional[float]):
    """Returns (clipped_tree, pre_clip_norm). max_norm None/<=0 disables
    clipping but still reports the norm (the reference logs grad-norm even
    when not clipping, swin main.py:196-205)."""
    norm = global_norm(tree)
    if not max_norm or max_norm <= 0:
        return tree, norm
    scale = jnp.minimum(1.0, max_norm / (norm + 1e-6))
    return jax.tree.map(lambda x: x * scale.astype(x.dtype), tree), norm
