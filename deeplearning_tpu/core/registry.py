"""Registries for models / datasets / losses / optimizers.

The reference has no registry — every project hard-imports its own
``models/`` dir (SURVEY.md §1). One registry per category lets the shared
trainer build anything from a config string, which is what makes a single
harness serve the whole zoo.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Iterator, Optional


class Registry:
    def __init__(self, name: str):
        self._name = name
        self._entries: Dict[str, Callable[..., Any]] = {}

    def register(self, name: Optional[str] = None) -> Callable:
        def deco(fn: Callable) -> Callable:
            key = name or fn.__name__
            if key in self._entries:
                raise KeyError(f"{key!r} already registered in {self._name}")
            self._entries[key] = fn
            return fn
        return deco

    def get(self, name: str) -> Callable[..., Any]:
        if name not in self._entries:
            raise KeyError(
                f"{name!r} not found in registry {self._name!r}. "
                f"Available: {sorted(self._entries)}")
        return self._entries[name]

    def build(self, name: str, *args: Any, **kwargs: Any) -> Any:
        return self.get(name)(*args, **kwargs)

    def __contains__(self, name: str) -> bool:
        return name in self._entries

    def __iter__(self) -> Iterator[str]:
        return iter(sorted(self._entries))

    def keys(self):
        return sorted(self._entries)


MODELS = Registry("models")
DATASETS = Registry("datasets")
LOSSES = Registry("losses")
OPTIMIZERS = Registry("optimizers")
SCHEDULES = Registry("schedules")
