from . import config, experiment, logging, precision, registry, rng  # noqa: F401
from .compile_cache import active_cache_dir, enable_compile_cache  # noqa: F401
from .registry import MODELS, DATASETS, LOSSES, OPTIMIZERS, SCHEDULES  # noqa: F401
