"""Build + load the native C++ helpers via g++ and ctypes.

The reference ships compiled extensions built by setuptools/ninja (YOLOX
setup.py:15-40 CppExtension 'yolox._C'; swin CUDAExtension). Here the
native runtime pieces are plain C-ABI shared objects compiled on first
use with g++ (pybind11 is not in this image) and cached next to the
sources; ctypes does the binding.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_DIR = os.path.dirname(os.path.abspath(__file__))
_LOCK = threading.Lock()
_LIBS: dict = {}

# per-library extra compile/link flags (system libs must be present;
# load() returns None gracefully when they are not)
_FLAGS = {
    "imagedec": ["-ljpeg", "-lpthread"],
}


def _build(name: str) -> Optional[str]:
    src = os.path.join(_DIR, f"{name}.cpp")
    out = os.path.join(_DIR, f"lib{name}.so")
    # stale if older than the source OR this file (flag changes live here)
    fresh_after = max(os.path.getmtime(src), os.path.getmtime(__file__))
    if os.path.exists(out) and os.path.getmtime(out) >= fresh_after:
        return out
    # compile to a private temp name, then atomically rename into place:
    # writing the final path directly lets a CONCURRENT process dlopen a
    # half-written .so — a startup SIGSEGV that vanishes once the cache
    # is warm (the round-4 retinanet rc=-11 signature)
    import glob
    for stale in glob.glob(os.path.join(_DIR, f".lib{name}.*.tmp.so")):
        try:                      # leftovers from a killed compile
            os.unlink(stale)
        except OSError:
            pass
    tmp = os.path.join(_DIR, f".lib{name}.{os.getpid()}.tmp.so")
    cmd = (["g++", "-O3", "-shared", "-fPIC", "-std=c++17", src, "-o", tmp]
           + _FLAGS.get(name, []))
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, out)
        return out
    except (subprocess.CalledProcessError, subprocess.TimeoutExpired,
            OSError):
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return None


def load(name: str) -> Optional[ctypes.CDLL]:
    """Compile (if needed) and dlopen lib<name>.so; None if unavailable."""
    with _LOCK:
        if name in _LIBS:
            return _LIBS[name]
        path = _build(name)
        try:
            lib = ctypes.CDLL(path) if path else None
        except OSError:   # e.g. cached .so but runtime dep now missing
            lib = None
        _LIBS[name] = lib
        return lib
