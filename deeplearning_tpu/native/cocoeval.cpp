// Fast COCO evaluation kernels — the TPU-era counterpart of the
// reference's detectron2-derived C++ COCOeval (detection/YOLOX/yolox/
// layers/csrc/cocoeval/cocoeval.cpp, exposed as yolox._C). Same role —
// move the O(thresholds × dets × gts) greedy matching and the
// precision-accumulation inner loops out of Python — but bound via a
// plain C ABI + ctypes instead of pybind11 (not available in this image).
//
// Semantics mirror pycocotools COCOeval::evaluateImg/accumulate:
//  * detections greedily match the best remaining gt with IoU >= thr;
//    crowd gts may match repeatedly (IoA); ignored gts are only taken
//    when no real gt qualifies; once a det has a real match it never
//    switches to an ignored gt.
//  * unmatched detections outside the area range are ignored.
//
// Built by native/build.py: g++ -O3 -shared -fPIC cocoeval.cpp

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <vector>

extern "C" {

// IoU between det and gt boxes (xyxy); crowd gt uses intersection/det_area.
static inline double box_iou_one(const double* d, const double* g,
                                 bool crowd) {
  const double ix1 = std::max(d[0], g[0]);
  const double iy1 = std::max(d[1], g[1]);
  const double ix2 = std::min(d[2], g[2]);
  const double iy2 = std::min(d[3], g[3]);
  const double iw = std::max(0.0, ix2 - ix1);
  const double ih = std::max(0.0, iy2 - iy1);
  const double inter = iw * ih;
  if (inter <= 0) return 0.0;
  const double ad = std::max(0.0, d[2] - d[0]) * std::max(0.0, d[3] - d[1]);
  const double ag = std::max(0.0, g[2] - g[0]) * std::max(0.0, g[3] - g[1]);
  const double uni = crowd ? ad : (ad + ag - inter);
  return uni <= 0 ? 0.0 : inter / uni;
}

// Match all images of one (category, area range, maxDet) slice.
// Arrays are packed: image i's dets are [d_off[i], d_off[i+1]).
// Gts must be pre-sorted per image with non-ignored first.
// Outputs: dt_matched (n_thr, total_d) gt local index or -1;
//          dt_ignore  (n_thr, total_d) 0/1.
void coco_match(int n_img, const int64_t* d_off, const int64_t* g_off,
                const double* d_boxes, const double* g_boxes,
                const uint8_t* g_crowd, const uint8_t* g_ignore,
                const double* iou_thrs, int n_thr, double area_lo,
                double area_hi, int64_t total_d, int64_t* dt_matched,
                uint8_t* dt_ignore) {
  for (int64_t i = 0; i < (int64_t)n_thr * total_d; ++i) dt_matched[i] = -1;
  for (int64_t i = 0; i < (int64_t)n_thr * total_d; ++i) dt_ignore[i] = 0;

  std::vector<int64_t> gt_taken;
  for (int img = 0; img < n_img; ++img) {
    const int64_t d0 = d_off[img], d1 = d_off[img + 1];
    const int64_t g0 = g_off[img], g1 = g_off[img + 1];
    const int64_t gcount = g1 - g0;
    for (int t = 0; t < n_thr; ++t) {
      const double thr = iou_thrs[t];
      gt_taken.assign(gcount, -1);
      for (int64_t di = d0; di < d1; ++di) {
        double best_iou = std::min(thr, 1.0 - 1e-10);
        int64_t best_g = -1;
        for (int64_t gi = 0; gi < gcount; ++gi) {
          const bool crowd = g_crowd[g0 + gi] != 0;
          if (gt_taken[gi] >= 0 && !crowd) continue;
          const bool ign = g_ignore[g0 + gi] != 0;
          if (best_g >= 0 && !g_ignore[g0 + best_g] && ign) break;
          const double iou =
              box_iou_one(d_boxes + 4 * di, g_boxes + 4 * (g0 + gi), crowd);
          if (iou < best_iou) continue;
          best_iou = iou;
          best_g = gi;
        }
        if (best_g >= 0) {
          gt_taken[best_g] = di;
          dt_matched[(int64_t)t * total_d + di] = best_g;
          dt_ignore[(int64_t)t * total_d + di] = g_ignore[g0 + best_g];
        } else {
          const double* b = d_boxes + 4 * di;
          const double area = std::max(0.0, b[2] - b[0]) *
                              std::max(0.0, b[3] - b[1]);
          if (area < area_lo || area > area_hi)
            dt_ignore[(int64_t)t * total_d + di] = 1;
        }
      }
    }
  }
}

}  // extern "C"
