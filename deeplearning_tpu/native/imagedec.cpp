// Native JPEG decode (+ optional fused bilinear resize) batch worker.
//
// The reference's input pipeline leans on native decode underneath
// torchvision/cv2 (YOLOX setup_env.py configures cv2 threads; swin's
// zipreader feeds PIL from zip bytes). This is the TPU-era equivalent:
// a C-ABI libjpeg path the Python DataLoader calls via ctypes, decoding
// off the GIL with its own thread pool so one host core can still keep
// the feed ahead of the device. Plain C ABI (no pybind11 in the image).
//
// Exported:
//   decode_jpeg_info(buf, len, &w, &h)      -> 0 ok
//   decode_jpeg(buf, len, out, cap)         -> 0 ok (RGB8, w*h*3 bytes)
//   decode_resize_batch(bufs, lens, n, oh, ow, out, n_threads) -> #errors
//     (each output slot oh*ow*3 RGB8; failed decodes are zero-filled)

#include <cstddef>
#include <cstdio>  // jpeglib.h needs size_t/FILE declared first

#include <jpeglib.h>

#include <atomic>
#include <csetjmp>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

struct ErrMgr {
  jpeg_error_mgr pub;
  jmp_buf jump;
};

void err_exit(j_common_ptr cinfo) {
  longjmp(reinterpret_cast<ErrMgr*>(cinfo->err)->jump, 1);
}

int decode_rgb(const uint8_t* buf, long len, std::vector<uint8_t>* out,
               int* w, int* h) {
  jpeg_decompress_struct cinfo;
  ErrMgr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = err_exit;
  if (setjmp(jerr.jump)) {
    jpeg_destroy_decompress(&cinfo);
    return 1;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, buf, static_cast<unsigned long>(len));
  jpeg_read_header(&cinfo, TRUE);
  cinfo.out_color_space = JCS_RGB;
  jpeg_start_decompress(&cinfo);
  *w = static_cast<int>(cinfo.output_width);
  *h = static_cast<int>(cinfo.output_height);
  out->resize(static_cast<size_t>(*w) * *h * 3);
  while (cinfo.output_scanline < cinfo.output_height) {
    uint8_t* row =
        out->data() + static_cast<size_t>(cinfo.output_scanline) * *w * 3;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  return 0;
}

// half-pixel-center bilinear (the cv2/PIL "linear" convention)
void resize_bilinear(const uint8_t* src, int sw, int sh, uint8_t* dst,
                     int dw, int dh) {
  if (sw == dw && sh == dh) {
    std::memcpy(dst, src, static_cast<size_t>(sw) * sh * 3);
    return;
  }
  const float sx = static_cast<float>(sw) / dw;
  const float sy = static_cast<float>(sh) / dh;
  for (int y = 0; y < dh; ++y) {
    float fy = (y + 0.5f) * sy - 0.5f;
    if (fy < 0) fy = 0;
    int y0 = static_cast<int>(fy);
    if (y0 > sh - 1) y0 = sh - 1;
    int y1 = y0 + 1 < sh ? y0 + 1 : sh - 1;
    float wy = fy - y0;
    for (int x = 0; x < dw; ++x) {
      float fx = (x + 0.5f) * sx - 0.5f;
      if (fx < 0) fx = 0;
      int x0 = static_cast<int>(fx);
      if (x0 > sw - 1) x0 = sw - 1;
      int x1 = x0 + 1 < sw ? x0 + 1 : sw - 1;
      float wx = fx - x0;
      const uint8_t* p00 = src + (static_cast<size_t>(y0) * sw + x0) * 3;
      const uint8_t* p01 = src + (static_cast<size_t>(y0) * sw + x1) * 3;
      const uint8_t* p10 = src + (static_cast<size_t>(y1) * sw + x0) * 3;
      const uint8_t* p11 = src + (static_cast<size_t>(y1) * sw + x1) * 3;
      uint8_t* d = dst + (static_cast<size_t>(y) * dw + x) * 3;
      for (int c = 0; c < 3; ++c) {
        float top = p00[c] * (1 - wx) + p01[c] * wx;
        float bot = p10[c] * (1 - wx) + p11[c] * wx;
        float v = top * (1 - wy) + bot * wy;
        d[c] = static_cast<uint8_t>(v + 0.5f);
      }
    }
  }
}

}  // namespace

extern "C" {

int decode_jpeg_info(const uint8_t* buf, long len, int* w, int* h) {
  // header-only: this runs before EVERY single-image decode (the Python
  // wrapper sizes its output buffer from it), so no scanline work here
  jpeg_decompress_struct cinfo;
  ErrMgr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = err_exit;
  if (setjmp(jerr.jump)) {
    jpeg_destroy_decompress(&cinfo);
    return 1;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, buf, static_cast<unsigned long>(len));
  jpeg_read_header(&cinfo, TRUE);
  cinfo.out_color_space = JCS_RGB;
  jpeg_calc_output_dimensions(&cinfo);
  *w = static_cast<int>(cinfo.output_width);
  *h = static_cast<int>(cinfo.output_height);
  jpeg_destroy_decompress(&cinfo);
  return 0;
}

int decode_jpeg(const uint8_t* buf, long len, uint8_t* out, long cap) {
  std::vector<uint8_t> tmp;
  int w = 0, h = 0;
  if (decode_rgb(buf, len, &tmp, &w, &h)) return 1;
  if (static_cast<long>(tmp.size()) > cap) return 2;
  std::memcpy(out, tmp.data(), tmp.size());
  return 0;
}

int decode_resize_batch(const uint8_t** bufs, const long* lens, int n,
                        int out_h, int out_w, uint8_t* out, int n_threads) {
  std::atomic<int> next(0), errs(0);
  const size_t slot = static_cast<size_t>(out_h) * out_w * 3;
  auto worker = [&]() {
    std::vector<uint8_t> tmp;
    for (int i = next.fetch_add(1); i < n; i = next.fetch_add(1)) {
      int w = 0, h = 0;
      uint8_t* dst = out + slot * i;
      if (decode_rgb(bufs[i], lens[i], &tmp, &w, &h)) {
        errs.fetch_add(1);
        std::memset(dst, 0, slot);
        continue;
      }
      resize_bilinear(tmp.data(), w, h, dst, out_w, out_h);
    }
  };
  int nt = n_threads > 0 ? n_threads : 1;
  if (nt > n) nt = n > 0 ? n : 1;
  std::vector<std::thread> pool;
  pool.reserve(nt - 1);
  for (int t = 1; t < nt; ++t) pool.emplace_back(worker);
  worker();
  for (auto& th : pool) th.join();
  return errs.load();
}

}  // extern "C"
