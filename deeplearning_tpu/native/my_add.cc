// XLA FFI custom-call demo — the TPU-era analog of the reference's
// custom-op tutorial (others/deploy/pytorch2onnx/my_add.cpp:5-12, which
// registers `3a + 2b` as a torch extension and exports it to ONNX via
// g.op symbolic registration). Here the same toy op is an XLA FFI
// handler: compiled with the jaxlib headers, registered on the Host
// platform, and invoked from JAX via jax.ffi.ffi_call — demonstrating
// the full "teach XLA a new op" path (export/custom_call.py wires it).

#include <cstdint>

#include "xla/ffi/api/ffi.h"

namespace ffi = xla::ffi;

static ffi::Error MyAddImpl(ffi::Buffer<ffi::F32> a,
                            ffi::Buffer<ffi::F32> b,
                            ffi::ResultBuffer<ffi::F32> out) {
  const int64_t n = static_cast<int64_t>(a.element_count());
  const float* pa = a.typed_data();
  const float* pb = b.typed_data();
  float* po = out->typed_data();
  for (int64_t i = 0; i < n; ++i) po[i] = 3.0f * pa[i] + 2.0f * pb[i];
  return ffi::Error::Success();
}

XLA_FFI_DEFINE_HANDLER_SYMBOL(MyAdd, MyAddImpl,
                              ffi::Ffi::Bind()
                                  .Arg<ffi::Buffer<ffi::F32>>()
                                  .Arg<ffi::Buffer<ffi::F32>>()
                                  .Ret<ffi::Buffer<ffi::F32>>());
