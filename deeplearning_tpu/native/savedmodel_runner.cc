// Native C++ inference runner over the TensorFlow C API — the TPU-era
// successor of the reference's C++ deployment demos (others/deploy/
// onnx2trt/inference_trt.cpp:105 TensorRT engine runner and YOLOX's C++
// demos): load the jax2tf-exported SavedModel (export/serialize.py
// export_savedmodel), feed a float32 NHWC tensor, run the
// serving_default signature, print the output logits.
//
//   savedmodel_runner <export_dir> <input_op> <output_op> d0,d1,...
//
// Op names come from the SavedModel signature (printed by
// export/serialize.py when exporting, typically
// serving_default_<arg>:0 -> StatefulPartitionedCall:0).
//
// Built by tools/build_savedmodel_runner.py:
//   g++ -O2 -std=c++17 savedmodel_runner.cc -I<tf>/include
//       -L<tf> -l:libtensorflow_cc.so.2 -Wl,-rpath,<tf>

#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

#include "tensorflow/c/c_api.h"

static void check(TF_Status* s, const char* what) {
  if (TF_GetCode(s) != TF_OK) {
    std::fprintf(stderr, "%s failed: %s\n", what, TF_Message(s));
    std::exit(1);
  }
}

int main(int argc, char** argv) {
  if (argc < 5) {
    std::fprintf(stderr,
                 "usage: %s <saved_model_dir> <input_op> <output_op> "
                 "d0,d1,d2,...\n", argv[0]);
    return 2;
  }
  const char* dir = argv[1];
  std::string in_name = argv[2];
  std::string out_name = argv[3];

  std::vector<int64_t> dims;
  int64_t count = 1;
  for (char* tok = std::strtok(argv[4], ","); tok;
       tok = std::strtok(nullptr, ",")) {
    dims.push_back(std::atoll(tok));
    count *= dims.back();
  }

  TF_Status* status = TF_NewStatus();
  TF_Graph* graph = TF_NewGraph();
  TF_SessionOptions* opts = TF_NewSessionOptions();
  const char* tags[] = {"serve"};
  TF_Session* session = TF_LoadSessionFromSavedModel(
      opts, nullptr, dir, tags, 1, graph, nullptr, status);
  check(status, "TF_LoadSessionFromSavedModel");

  // split "name:idx"
  auto split = [](std::string& s) {
    int idx = 0;
    auto pos = s.rfind(':');
    if (pos != std::string::npos) {
      idx = std::atoi(s.c_str() + pos + 1);
      s = s.substr(0, pos);
    }
    return idx;
  };
  int in_idx = split(in_name);
  int out_idx = split(out_name);
  TF_Operation* in_op = TF_GraphOperationByName(graph, in_name.c_str());
  TF_Operation* out_op = TF_GraphOperationByName(graph, out_name.c_str());
  if (!in_op || !out_op) {
    std::fprintf(stderr, "op not found (input %s, output %s)\n",
                 in_name.c_str(), out_name.c_str());
    return 1;
  }

  TF_Tensor* in_tensor = TF_AllocateTensor(
      TF_FLOAT, dims.data(), (int)dims.size(), count * sizeof(float));
  float* data = static_cast<float*>(TF_TensorData(in_tensor));
  // deterministic ramp input so python can cross-check exactly
  for (int64_t i = 0; i < count; ++i)
    data[i] = 0.001f * (float)(i % 1000);

  TF_Output inputs[1] = {{in_op, in_idx}};
  TF_Output outputs[1] = {{out_op, out_idx}};
  TF_Tensor* out_tensor = nullptr;
  TF_SessionRun(session, nullptr, inputs, &in_tensor, 1, outputs,
                &out_tensor, 1, nullptr, 0, nullptr, status);
  check(status, "TF_SessionRun");

  const float* out_data = static_cast<const float*>(
      TF_TensorData(out_tensor));
  int64_t out_count = 1;
  for (int i = 0; i < TF_NumDims(out_tensor); ++i)
    out_count *= TF_Dim(out_tensor, i);
  std::printf("output_shape:");
  for (int i = 0; i < TF_NumDims(out_tensor); ++i)
    std::printf(" %lld", (long long)TF_Dim(out_tensor, i));
  std::printf("\nvalues:");
  for (int64_t i = 0; i < out_count && i < 16; ++i)
    std::printf(" %.6f", out_data[i]);
  std::printf("\n");

  TF_DeleteTensor(in_tensor);
  TF_DeleteTensor(out_tensor);
  TF_CloseSession(session, status);
  TF_DeleteSession(session, status);
  TF_DeleteGraph(graph);
  TF_DeleteSessionOptions(opts);
  TF_DeleteStatus(status);
  return 0;
}
