"""Resilience primitives for the fleet data plane: retry budgets and
per-replica circuit breakers.

The router's failover loop (``fleet/router.py``) is where an outage can
*amplify*: every failed request that retries adds load to the replicas
still standing, and a replica that keeps failing keeps eating one
attempt per request until the next health refresh notices. These two
classes bound both failure modes, client-side and allocation-free:

- :class:`RetryBudget` is a token bucket fed by *successes*: each
  success deposits ``fraction`` tokens, each retry (or hedge) withdraws
  one. With every replica down there are no deposits, so total attempts
  are capped at ``(1 + fraction) x offered load`` plus the configured
  burst — a retry storm cannot multiply an outage (the classic
  retry-budget rule from the SRE literature).
- :class:`CircuitBreaker` tracks a per-replica sliding window of
  attempt outcomes: too many failures trips it OPEN (the router skips
  the replica *between* health refreshes, closing the staleness
  window), a cooldown later it goes HALF_OPEN and admits exactly one
  probe — success re-closes it, failure re-opens with a fresh cooldown.

Both are thread-safe (the router posts from many loadgen sender
threads and from hedge workers) and host-side only — stdlib, no jax.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List

__all__ = ["RetryBudget", "CircuitBreaker"]


class RetryBudget:
    """Token bucket that caps retries as a fraction of recent successes.

    ``note_success()`` deposits ``fraction`` tokens (clamped to
    ``cap``); ``try_spend()`` withdraws one token per retry/hedge and
    refuses when the bucket is empty; ``give_back()`` refunds the token
    of an abandoned hedge (the loser's attempt never cost the fleet a
    full request, so it should not cost the budget one either).
    ``initial`` seeds the bucket so a cold client can still retry a
    transient blip before its first success.
    """

    def __init__(self, fraction: float = 0.2, cap: float = 10.0,
                 initial: float = 0.0):
        if not 0.0 <= fraction <= 1.0:
            raise ValueError(f"fraction must be in [0, 1]: {fraction}")
        self.fraction = float(fraction)
        self.cap = float(cap)
        self._lock = threading.Lock()
        self._tokens = min(float(initial), self.cap)
        self.successes = 0
        self.spent = 0
        self.refunded = 0
        self.exhausted = 0

    def note_success(self) -> None:
        with self._lock:
            self.successes += 1
            self._tokens = min(self._tokens + self.fraction, self.cap)

    def try_spend(self) -> bool:
        """Withdraw one token; False (and no withdrawal) when empty."""
        with self._lock:
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                self.spent += 1
                return True
            self.exhausted += 1
            return False

    def give_back(self) -> None:
        """Refund one token (abandoned hedge loser)."""
        with self._lock:
            self.refunded += 1
            self._tokens = min(self._tokens + 1.0, self.cap)

    def tokens(self) -> float:
        with self._lock:
            return self._tokens

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {"tokens": round(self._tokens, 3),
                    "fraction": self.fraction,
                    "successes": self.successes, "spent": self.spent,
                    "refunded": self.refunded,
                    "exhausted": self.exhausted}


class CircuitBreaker:
    """Per-replica failure-rate breaker: CLOSED -> OPEN -> HALF_OPEN.

    Outcomes land in a sliding window of the last ``window`` attempts.
    Once at least ``min_samples`` are present and the failure rate
    reaches ``failure_threshold`` the breaker OPENs: ``allow()`` turns
    False, so the router drops the replica from rotation immediately —
    no waiting for the next ``/healthz`` refresh to notice. After
    ``reset_timeout_s`` the breaker admits exactly one probe
    (HALF_OPEN): a success re-closes it with a cleared window, a
    failure re-opens it with a fresh cooldown.
    """

    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"

    def __init__(self, window: int = 12, failure_threshold: float = 0.5,
                 min_samples: int = 4, reset_timeout_s: float = 2.0,
                 clock=time.monotonic):
        self.window = int(window)
        self.failure_threshold = float(failure_threshold)
        self.min_samples = int(min_samples)
        self.reset_timeout_s = float(reset_timeout_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._outcomes: List[bool] = []
        self._state = self.CLOSED
        self._opened_at = 0.0
        self._probing = False
        self.opens = 0
        self.closes = 0
        self.probes = 0

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def allow(self) -> bool:
        """May the router send this replica a request right now?

        OPEN past its cooldown transitions to HALF_OPEN and admits the
        single probe attempt; further callers are refused until that
        probe's outcome lands in :meth:`record`.
        """
        with self._lock:
            if self._state == self.CLOSED:
                return True
            if self._state == self.OPEN:
                if self._clock() - self._opened_at < self.reset_timeout_s:
                    return False
                self._state = self.HALF_OPEN
                self._probing = True
                self.probes += 1
                return True
            # HALF_OPEN: exactly one probe in flight at a time
            if self._probing:
                return False
            self._probing = True
            self.probes += 1
            return True

    def blocking(self) -> bool:
        """Non-consuming peek: would :meth:`allow` refuse right now?
        (Listing candidate targets must not eat the half-open probe
        slot — only an actual send may.)"""
        with self._lock:
            if self._state == self.OPEN:
                return (self._clock() - self._opened_at
                        < self.reset_timeout_s)
            if self._state == self.HALF_OPEN:
                return self._probing
            return False

    def release(self) -> None:
        """Un-consume a half-open probe slot when the admitted attempt
        was never actually sent (deadline or retry budget refused it) —
        the probe must stay available for the next real send."""
        with self._lock:
            if self._state == self.HALF_OPEN and self._probing:
                self._probing = False
                self.probes -= 1

    def record(self, ok: bool) -> None:
        """Land an attempt outcome (429-shedding is NOT a failure — the
        replica answered; the caller classifies before recording)."""
        with self._lock:
            if self._state == self.HALF_OPEN:
                self._probing = False
                if ok:
                    self._state = self.CLOSED
                    self._outcomes = []
                    self.closes += 1
                else:
                    self._state = self.OPEN
                    self._opened_at = self._clock()
                return
            self._outcomes.append(bool(ok))
            if len(self._outcomes) > self.window:
                del self._outcomes[: len(self._outcomes) - self.window]
            if self._state == self.CLOSED:
                n = len(self._outcomes)
                fails = n - sum(self._outcomes)
                if (n >= self.min_samples
                        and fails / n >= self.failure_threshold):
                    self._state = self.OPEN
                    self._opened_at = self._clock()
                    self.opens += 1

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            n = len(self._outcomes)
            return {"state": self._state, "samples": n,
                    "failures": n - sum(self._outcomes),
                    "opens": self.opens, "closes": self.closes,
                    "probes": self.probes}
