"""Fleet controller: the closed actuation loop over a replica set.

PR 11 built the sensing half (``obs/fleet.py`` scrape → rollup →
``slo_breach`` events); this is the half that ACTS. Each tick:

1. **Sense** — rediscover live endpoints (``discover_endpoints`` with
   ``live_only=True``: dead replicas' stale adverts are not capacity),
   scrape the fleet, fold the rollup + counter deltas.
2. **Heal** — a replica whose ``/healthz`` reports ``wedged`` is
   drained (``POST /admin/drain`` → routers stop sending; queued work
   gets the drain deadline to flush — a truly frozen dispatch stream
   never flushes, which is fine) and then requeued through its
   supervisor's ``request_restart`` directive: kill, relaunch, no
   restart-budget burn, because the controller — not the child — chose
   this death.
3. **Decide** — feed the rollup to the :class:`~.policy.FleetPolicy`;
   ``scale_up`` spawns a fresh replica, ``scale_down`` drains the
   highest-index live one and stops it once drained (or the deadline
   passes).

Preemption (exit 75) short-circuits the cadence: the supervisor's
``on_outcome`` hook calls :meth:`note_preemption` synchronously and the
policy answers replace-or-shed immediately — ``"requeue_now"`` skips
the backoff curve entirely, ``"stop"`` folds the capacity.

**Warm standbys** (PR 15): with ``standby_target > 0`` the controller
keeps that many spares fully warmed but unroutable (spawned with
``DLTPU_STANDBY=1``; ``/healthz`` says 503 "standby"). Losing capacity
— a wedge, a preemption the policy votes to replace, a scale-up —
*promotes* a standby (``POST /admin/promote``: a healthz flip, no
compile, no process start) instead of cold-spawning, then replenishes
the spare pool in the background. Promotion latency is one HTTP
round-trip; cold spawn is a process launch plus full engine warmup.

**Tenant brownout** (PR 15): per-model SLO verdicts from the rollup
feed :meth:`~.policy.FleetPolicy.brownout_observe`; when a tenant's
ladder moves, the new step is pushed to every live replica via
``POST /admin/brownout/<model>/<step>`` — degrade one tenant (largest-
bucket-only → int8 residency → partial shed) before dimming the fleet.

Every decision lands twice: in the controller's own flight ring
(dumped to ``<run_dir>/flightrec_controller.json`` — the file
``tools/obs_report.py`` renders the fleet-controller section from) and
in the process-global ring next to the ``slo_breach`` triggers, so
cause and action interleave in one timeline. Events: ``fleet_scale``,
``fleet_drain``, ``fleet_requeue``, ``preempt_capacity``,
``fleet_promote``, ``fleet_standby``, ``fleet_brownout``.
"""

from __future__ import annotations

import json
import os
import threading
import time
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional

from ..obs import threads as obs_threads
from ..obs.fleet import (FleetScraper, SLOPolicy, discover_endpoints,
                         record_fleet_event)
from ..obs.flight import FlightRecorder
from .policy import FleetPolicy
from .replicaset import ReplicaSet

__all__ = ["FleetController", "CONTROLLER_FLIGHT_FILE"]

CONTROLLER_FLIGHT_FILE = "flightrec_controller.json"


def _post_json(url: str, timeout_s: float) -> Optional[Dict[str, Any]]:
    req = urllib.request.Request(url, data=b"", method="POST")
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as resp:
            return json.loads(resp.read().decode())
    except (OSError, ValueError, urllib.error.URLError):
        return None


class FleetController:
    """Ticks the sense→heal→decide loop. ``tick()`` is the synchronous
    unit of work (tests drive it directly); ``start()`` runs it on
    ``interval_s`` from a registered ``fleet-controller`` thread."""

    def __init__(self, replica_set: ReplicaSet, policy: FleetPolicy, *,
                 run_dir: str,
                 slo: Optional[SLOPolicy] = None,
                 interval_s: float = 1.0,
                 drain_deadline_s: float = 10.0,
                 scrape_timeout_s: float = 2.0,
                 standby_target: int = 0,
                 fleet_path: Optional[str] = None):
        self.replica_set = replica_set
        self.policy = policy
        self.run_dir = os.path.abspath(run_dir)
        self.interval_s = max(float(interval_s), 0.05)
        self.drain_deadline_s = float(drain_deadline_s)
        self.scrape_timeout_s = float(scrape_timeout_s)
        self.scraper = FleetScraper(
            [], slo=slo, timeout_s=scrape_timeout_s,
            fleet_path=(fleet_path if fleet_path is not None
                        else os.path.join(self.run_dir, "fleet.jsonl")))
        self.flight = FlightRecorder()
        self.flight.configure(
            os.path.join(self.run_dir, CONTROLLER_FLIGHT_FILE),
            config={"policy": policy.snapshot(),
                    "interval_s": self.interval_s,
                    "drain_deadline_s": self.drain_deadline_s})
        self.standby_target = max(int(standby_target), 0)
        self.ticks = 0
        self.scale_ups = 0
        self.scale_downs = 0
        self.drains = 0
        self.requeues = 0
        self.preemptions = 0
        self.promotions = 0
        self.brownouts = 0
        # replicas mid-drain: index -> {"url", "t0", "then"} where
        # "then" is what happens when drained/deadline: restart | stop
        self._draining: Dict[int, Dict[str, Any]] = {}
        # warm spares: indices spawned-as-standby and not yet promoted,
        # plus the URLs the last scrape saw them advertise. Guarded by a
        # lock because the preemption hook reads them from a supervisor
        # thread while tick() writes them from the controller thread.
        self._standby_lock = threading.Lock()
        self._standby_indices: set = set()
        self._standby_urls: Dict[int, str] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # wire preemption-as-capacity into every member's supervisor
        replica_set.on_outcome = self._on_outcome

    # --------------------------------------------------------- record
    def _record(self, kind: str, **data: Any) -> None:
        self.flight.record(kind, **data)
        record_fleet_event(kind, **data)    # global ring: one timeline
        # actuations are rare and the ring is small: dump after each so
        # the decision history survives even an ungraceful controller
        # death (obs_report renders from this file)
        self.flight.dump(kind, include_hbm=False)

    # ------------------------------------------------------------ tick
    def tick(self) -> Dict[str, Any]:
        """One sense→heal→decide pass; returns the rollup it acted on."""
        self.ticks += 1
        self.scraper.endpoints = discover_endpoints(
            self.run_dir, live_only=True)
        rollup = self.scraper.scrape_once()
        per_replica = rollup.get("per_replica") or []
        self._sense_standbys(per_replica)
        self._heal(per_replica)
        self._finish_drains()
        self._replenish_standbys()
        self._drive_brownout(rollup, per_replica)
        # routable capacity: live supervisor slots minus mid-drain ones
        # and minus warm spares (a standby is a promise, not capacity)
        with self._standby_lock:
            spares = set(self._standby_indices)
        live = len([i for i in self.replica_set.live()
                    if i not in self._draining and i not in spares])
        decision = self.policy.observe(rollup, live)
        if decision.action == "scale_up":
            index = self._promote(decision.reason)
            if index is None:
                index = self.replica_set.spawn()
            self.scale_ups += 1
            self._record("fleet_scale", direction="up", replica=index,
                         reason=decision.reason, live=live,
                         **_sig(decision))
        elif decision.action == "scale_down":
            victim = self._pick_victim(per_replica)
            if victim is not None:
                self._begin_drain(victim[0], victim[1], then="stop",
                                  reason=decision.reason)
                self.scale_downs += 1
                self._record("fleet_scale", direction="down",
                             replica=victim[0], reason=decision.reason,
                             live=live, **_sig(decision))
        return rollup

    # ------------------------------------------------------------ heal
    def _heal(self, per_replica: List[Dict[str, Any]]) -> None:
        for row in per_replica:
            if row.get("status") != "wedged":
                continue
            index = _replica_index(row)
            if index is None or index in self._draining:
                continue
            # a warm spare covers the lost capacity NOW; the wedged
            # replica then retires ("stop") instead of restarting. No
            # spare → the original drain-and-requeue path.
            promoted = self._promote("wedged")
            self._begin_drain(
                index, row.get("url"),
                then=("stop" if promoted is not None else "restart"),
                reason="wedged")

    # --------------------------------------------------------- standby
    def _sense_standbys(self, per_replica: List[Dict[str, Any]]) -> None:
        """Refresh the spare map from the scrape: adopt any replica
        advertising ``standby`` (supervise.py may have spawned the
        initial spares before this controller existed) and remember its
        URL — promotion needs an address, not just an index."""
        live = set(self.replica_set.live())
        with self._standby_lock:
            self._standby_indices &= live
            urls: Dict[int, str] = {}
            for row in per_replica:
                if row.get("status") != "standby":
                    continue
                index = _replica_index(row)
                if index is None:
                    continue
                self._standby_indices.add(index)
                url = row.get("url")
                if url:
                    urls[index] = url
            self._standby_urls = urls

    def _replenish_standbys(self) -> None:
        live = set(self.replica_set.live())
        with self._standby_lock:
            have = len(self._standby_indices & live)
            need = self.standby_target - have
        for _ in range(max(need, 0)):
            index = self.replica_set.spawn(standby=True)
            with self._standby_lock:
                self._standby_indices.add(index)
            self._record("fleet_standby", replica=index,
                         target=self.standby_target)

    def _promote(self, reason: str) -> Optional[int]:
        """Flip one warm spare to ready (``POST /admin/promote``);
        returns its index, or None when no addressable spare exists or
        every attempt failed. The promoted replica leaves the spare set
        immediately — it is routable capacity from this moment."""
        while True:
            with self._standby_lock:
                candidates = [(i, u) for i, u in
                              sorted(self._standby_urls.items())
                              if i in self._standby_indices]
            if not candidates:
                return None
            index, url = candidates[0]
            t0 = time.monotonic()
            doc = _post_json(url.rstrip("/") + "/admin/promote",
                             self.scrape_timeout_s)
            with self._standby_lock:
                self._standby_urls.pop(index, None)
                self._standby_indices.discard(index)
            if doc is not None and (doc.get("promoted")
                                    or not doc.get("standby", True)):
                self.promotions += 1
                self._record(
                    "fleet_promote", replica=index, url=url,
                    reason=reason,
                    seconds=round(time.monotonic() - t0, 4))
                return index
            # unreachable spare: drop it from the pool and try the next

    # -------------------------------------------------------- brownout
    def _drive_brownout(self, rollup: Dict[str, Any],
                        per_replica: List[Dict[str, Any]]) -> None:
        """Feed per-tenant SLO verdicts to the policy's ladders; push
        every transition to all routable replicas so the whole fleet
        dims (or undims) that tenant together."""
        models = rollup.get("models") or {}
        if not models:
            return
        urls = [row.get("url") for row in per_replica
                if row.get("url") and row.get("status") != "standby"]
        for alias in sorted(models):
            verdict = models[alias].get("slo") or {}
            step = self.policy.brownout_observe(
                alias, bool(verdict.get("breach")))
            if step is None:
                continue
            pushed = 0
            for url in urls:
                doc = _post_json(
                    url.rstrip("/") + f"/admin/brownout/{alias}/{step}",
                    self.scrape_timeout_s)
                pushed += int(doc is not None)
            self.brownouts += 1
            self._record("fleet_brownout", model=alias, step=step,
                         replicas=pushed,
                         breach=bool(verdict.get("breach")))

    def _begin_drain(self, index: int, url: Optional[str], *,
                     then: str, reason: str) -> None:
        if url:
            _post_json(url.rstrip("/") + "/admin/drain",
                       self.scrape_timeout_s)
        self._draining[index] = {"url": url, "t0": time.monotonic(),
                                 "then": then, "reason": reason}
        self.drains += 1
        self._record("fleet_drain", replica=index, reason=reason,
                     then=then, deadline_s=self.drain_deadline_s)

    def _finish_drains(self) -> None:
        now = time.monotonic()
        for index, state in list(self._draining.items()):
            drained = False
            url = state["url"]
            if url:
                doc = _post_json(url.rstrip("/") + "/admin/drain",
                                 self.scrape_timeout_s)
                drained = bool(doc and doc.get("drained"))
            expired = now - state["t0"] >= self.drain_deadline_s
            if not (drained or expired):
                continue
            del self._draining[index]
            if state["then"] == "stop":
                self.replica_set.stop(index, reason=state["reason"])
                self._record("fleet_stop", replica=index,
                             reason=state["reason"], drained=drained)
            else:
                self.replica_set.restart(index, reason=state["reason"])
                self.requeues += 1
                self._record("fleet_requeue", replica=index,
                             reason=state["reason"], drained=drained,
                             waited_s=round(now - state["t0"], 3))

    def _pick_victim(self, per_replica: List[Dict[str, Any]]
                     ) -> Optional[tuple]:
        """Highest-index live replica not already draining, with its
        URL when the scrape knows it — newest capacity goes first, the
        original floor replicas go last."""
        urls = {}
        for row in per_replica:
            i = _replica_index(row)
            if i is not None:
                urls[i] = row.get("url")
        with self._standby_lock:
            spares = set(self._standby_indices)
        candidates = [i for i in self.replica_set.live()
                      if i not in self._draining and i not in spares]
        if not candidates:
            return None
        victim = max(candidates)
        return victim, urls.get(victim)

    # ------------------------------------------------- preemption hook
    def _on_outcome(self, index: int, sup, outcome: str, attempt: int,
                    rc: int) -> Optional[str]:
        if outcome != "preempted":
            return None
        self.preemptions += 1
        live_after = len([i for i in self.replica_set.live()
                          if i != index and i not in self._draining])
        verdict = self.policy.on_preemption(live_after)
        self._record("preempt_capacity", replica=index,
                     attempt=attempt, verdict=verdict,
                     live_after=live_after)
        self.flight.dump("preempt_capacity", include_hbm=False)
        if verdict == "replace":
            # a warm spare beats a requeue: promote it (one HTTP flip)
            # and retire the preempted slot; replenish runs next tick
            if self._promote("preempted") is not None:
                return "stop"
            return "requeue_now"
        return "stop"

    def note_preemption(self, index: int) -> str:
        """Public flavor of the hook for callers that classify exits
        themselves; returns the policy verdict."""
        hint = self._on_outcome(index, None, "preempted", 0, 75)
        return "replace" if hint == "requeue_now" else "shed"

    # ------------------------------------------------------ background
    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception as e:  # noqa: BLE001 - the loop must live
                self.last_tick_error = repr(e)
                self.flight.record("tick_error", error=repr(e))

    def start(self) -> "FleetController":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = obs_threads.spawn(
                self._run, name="fleet-controller", daemon=True)
        return self

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None
        self.flight.record("controller_stop", ticks=self.ticks,
                           scale_ups=self.scale_ups,
                           scale_downs=self.scale_downs,
                           drains=self.drains, requeues=self.requeues,
                           preemptions=self.preemptions,
                           promotions=self.promotions,
                           brownouts=self.brownouts)
        self.flight.dump("controller_stop", include_hbm=False)

    def summary(self) -> Dict[str, Any]:
        with self._standby_lock:
            standbys = sorted(self._standby_indices)
        return {
            "ticks": self.ticks,
            "scale_ups": self.scale_ups,
            "scale_downs": self.scale_downs,
            "drains": self.drains,
            "requeues": self.requeues,
            "preemptions": self.preemptions,
            "promotions": self.promotions,
            "brownouts": self.brownouts,
            "draining": sorted(self._draining),
            "standbys": standbys,
            "live": self.replica_set.live(),
            "policy": self.policy.snapshot(),
        }


def _replica_index(row: Dict[str, Any]) -> Optional[int]:
    try:
        return int(row.get("replica"))
    except (TypeError, ValueError):
        return None


def _sig(decision) -> Dict[str, Any]:
    """Decision signals flattened for a flight event (prefixed so they
    never collide with the event's own keys)."""
    return {f"sig_{k}": v for k, v in decision.signals.items()}
