"""Replica set: N supervised children as one resizable collection.

``tools/supervise.py --replicas N`` (PR 11) ran a FIXED fleet — N
supervisor loops started together, joined together. The controller
needs the same loops as a mutable set: ``spawn()`` adds a replica at
runtime (scale-up, replacement), ``stop(i)``/``restart(i)`` drive one
member's :class:`~..elastic.supervisor.Supervisor` directives
(drain-and-requeue, scale-down), and ``live()``/``results()`` answer
the census questions the policy and the exit classifier ask.

Each member is one ``Supervisor.run()`` on its own non-daemon
``supervise-<i>`` thread (via the ``obs/threads.py`` spawn registry —
DLT204). Indices are monotonic: a replacement spawned after replica 2
died is replica 3 with a fresh workdir, never a reused identity whose
stale endpoint/heartbeat files could alias the corpse.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional

from ..elastic.supervisor import Supervisor, SupervisorConfig
from ..obs import threads as obs_threads

__all__ = ["ReplicaSet"]


class ReplicaSet:
    """``config_factory(index) -> SupervisorConfig`` builds each
    member's supervisor config (argv, workdir ``replica-<i>/``, env —
    ``tools/supervise.py`` owns that recipe); a factory accepting a
    second ``standby`` argument lets ``spawn(standby=True)`` build
    warm-spare configs (``DLTPU_STANDBY=1`` in the child env).
    ``on_outcome``, when set, is called as ``on_outcome(index,
    supervisor, outcome, attempt, rc)`` for every natural child ending
    and may return the supervisor hints (``"requeue_now"``/``"stop"``)
    — the controller's preemption-as-capacity hook."""

    def __init__(self, config_factory: Callable[[int], SupervisorConfig],
                 *, on_outcome: Optional[Callable[..., Optional[str]]]
                 = None):
        self._factory = config_factory
        self._lock = threading.Lock()
        self._members: Dict[int, Dict[str, Any]] = {}
        self._next_index = 0
        self.on_outcome = on_outcome

    # ----------------------------------------------------------- spawn
    def spawn(self, index: Optional[int] = None, *,
              standby: bool = False) -> int:
        """Add (and start) one supervised replica; returns its index.
        ``standby=True`` asks the factory for a warm-spare config (the
        factory must accept ``(index, standby)`` — single-arg factories
        keep working for regular spawns)."""
        with self._lock:
            if index is None:
                index = self._next_index
            self._next_index = max(self._next_index, index + 1)
            existing = self._members.get(index)
            if existing is not None and existing["thread"].is_alive():
                raise ValueError(f"replica {index} already running")

        config = (self._factory(index, True) if standby
                  else self._factory(index))
        sup = Supervisor(config)
        if self.on_outcome is not None:
            def _hook(_sup, outcome, attempt, rc, _i=index):
                return self.on_outcome(_i, _sup, outcome, attempt, rc)
            sup.on_outcome = _hook
        member: Dict[str, Any] = {"sup": sup, "rc": None}

        def _run(_m=member, _s=sup):
            _m["rc"] = _s.run()

        # non-daemon: a supervisor mid-kill-grace must not be reaped by
        # interpreter exit; join() below is the retirement point
        member["thread"] = obs_threads.spawn(  # dltpu: allow(DLT203)
            _run, name=f"supervise-{index}", daemon=False, start=False)
        with self._lock:
            self._members[index] = member
        member["thread"].start()
        return index

    # ------------------------------------------------------ directives
    def supervisor(self, index: int) -> Optional[Supervisor]:
        m = self._members.get(index)
        return m["sup"] if m else None

    def stop(self, index: int, reason: str = "requested") -> bool:
        sup = self.supervisor(index)
        if sup is None:
            return False
        sup.request_stop(reason)
        return True

    def restart(self, index: int, reason: str = "requested") -> bool:
        sup = self.supervisor(index)
        if sup is None:
            return False
        sup.request_restart(reason)
        return True

    def stop_all(self, reason: str = "shutdown") -> None:
        for index in list(self._members):
            self.stop(index, reason)

    # ---------------------------------------------------------- census
    def indices(self) -> List[int]:
        with self._lock:
            return sorted(self._members)

    def live(self) -> List[int]:
        """Indices whose supervisor loop is still running (the child
        itself may be mid-requeue — live means "this slot is managed",
        which is what capacity math wants)."""
        with self._lock:
            return sorted(i for i, m in self._members.items()
                          if m["thread"].is_alive())

    def results(self) -> Dict[int, Optional[int]]:
        with self._lock:
            return {i: m["rc"] for i, m in sorted(self._members.items())}

    def outcomes(self) -> Dict[int, Optional[str]]:
        with self._lock:
            return {i: m["sup"].final_outcome
                    for i, m in sorted(self._members.items())}

    def join(self, timeout: Optional[float] = None) -> bool:
        """Join every member thread (``timeout`` applies per member);
        True when all finished."""
        done = True
        for i in self.indices():
            m = self._members.get(i)
            if m is None:
                continue
            m["thread"].join(timeout)
            done = done and not m["thread"].is_alive()
        return done
