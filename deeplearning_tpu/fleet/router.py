"""Fleet router: the client-side front queue over N replica URLs.

The drain half of drain-and-requeue only works if SOMETHING stops
routing to a draining replica — in production that is a balancer
honoring 503s; in this repo (and its tier-1 choreography test) it is
this stdlib router: round-robin over replicas whose last ``/healthz``
read was routable (``ready``/``warming``/``degraded`` — states that
still answer), with failover on refusal. A replica reporting
``draining``/``wedged``/``standby``/unreachable is skipped at the
health refresh, and a request that still lands on one (the refresh is
periodic, not clairvoyant) fails over to the next distinct replica.

On top of plain failover sits the resilience layer
(``fleet/resilience.py``):

- **Deadlines**: ``post_ex(..., deadline_s=...)`` stamps the remaining
  budget into an ``X-Deadline-Ms`` header on every attempt and never
  retries or hedges past it — the serve side maps the header onto its
  admission deadline, so the whole chain spends one budget.
- **Retry budget**: every attempt beyond the first withdraws a token
  from a :class:`RetryBudget` fed by successes, so a fleet-wide outage
  cannot be amplified into a retry storm.
- **Hedging**: when the first attempt is slower than the observed p99,
  one token buys a second attempt at a distinct replica; first answer
  wins, the loser is abandoned and (when the primary won) the token is
  refunded.
- **Circuit breakers**: per-replica failure windows open a breaker that
  removes the replica from rotation *between* health refreshes;
  half-open probes re-admit it.

Host-side only — urllib, no jax — usable from ``tools/loadgen.py``
(HTTP open-loop mode) and tests.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request
from collections import deque
from queue import Empty, Queue
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..obs import threads as obs_threads
from .resilience import CircuitBreaker, RetryBudget

__all__ = ["FleetRouter", "DEADLINE_HEADER"]

# healthz statuses a request may still be sent to: a warming replica
# queues (slowly), a degraded one sheds but answers; draining, wedged,
# and standby ones must see no NEW traffic
_ROUTABLE = ("ready", "warming", "degraded")

# outcome codes that dent a replica's breaker: connection-dead, server
# errors, timeouts. 429 is the admission controller *answering* —
# shedding is load, not replica failure.
_FAILURE_CODES = (0, 500, 503, 504)
# codes worth spending budget on at another replica
_RETRYABLE = (0, 429, 503, 504)

DEADLINE_HEADER = "X-Deadline-Ms"


class FleetRouter:
    """Round-robin + failover over ``urls`` (or a live ``refresh_fn``
    returning the current URL set, e.g. a ``discover_endpoints``
    closure — scale-ups join the rotation at the next refresh)."""

    def __init__(self, urls: Sequence[str] = (), *,
                 refresh_fn=None,
                 health_ttl_s: float = 0.5,
                 timeout_s: float = 10.0,
                 budget: Optional[RetryBudget] = None,
                 breaker_factory=CircuitBreaker,
                 hedge: bool = True,
                 hedge_delay_s: float = 0.25):
        self._urls = [u.rstrip("/") for u in urls]
        self._refresh_fn = refresh_fn
        self.health_ttl_s = float(health_ttl_s)
        self.timeout_s = float(timeout_s)
        self.budget = budget if budget is not None else RetryBudget(
            fraction=0.2, cap=10.0, initial=2.0)
        self._breaker_factory = breaker_factory
        self.hedge = bool(hedge)
        self.hedge_delay_s = float(hedge_delay_s)
        self._lock = threading.Lock()
        self._rr = 0
        self._status: Dict[str, str] = {}
        self._breakers: Dict[str, CircuitBreaker] = {}
        self._latencies: deque = deque(maxlen=128)   # successful e2e s
        self._checked_at = 0.0
        self.sent = 0
        self.failovers = 0
        self.no_route = 0
        self.refresh_errors = 0
        self.hedges_fired = 0
        self.hedges_won = 0
        self.deadline_misses = 0
        self.breaker_skips = 0
        self.all_shed = 0
        self.last_refresh_error: Optional[str] = None

    # ---------------------------------------------------------- health
    def _healthz(self, url: str) -> str:
        try:
            req = urllib.request.Request(url + "/healthz")
            with urllib.request.urlopen(
                    req, timeout=self.timeout_s) as resp:
                doc = json.loads(resp.read().decode())
        except urllib.error.HTTPError as e:
            try:
                doc = json.loads(e.read().decode())
            except Exception:  # noqa: BLE001 - body optional
                return "unreachable"
        except (OSError, ValueError, urllib.error.URLError):
            return "unreachable"
        return str(doc.get("status", "unreachable"))

    def _refresh(self, force: bool = False) -> None:
        now = time.monotonic()
        with self._lock:
            stale = force or now - self._checked_at >= self.health_ttl_s
            if not stale:
                return
            self._checked_at = now
            urls = list(self._urls)
        if self._refresh_fn is not None:
            try:
                urls = [u.rstrip("/") for u in self._refresh_fn()]
            except Exception as e:  # noqa: BLE001 - keep the last set
                with self._lock:
                    self.refresh_errors += 1
                    self.last_refresh_error = repr(e)
        status = {u: self._healthz(u) for u in urls}
        with self._lock:
            self._urls = urls
            self._status = status

    def _breaker(self, url: str) -> CircuitBreaker:
        with self._lock:
            br = self._breakers.get(url)
            if br is None:
                br = self._breakers[url] = self._breaker_factory()
            return br

    def routable(self) -> List[str]:
        """URLs fit to receive a request now: healthz-routable AND not
        sitting behind an open circuit breaker (the breaker acts
        between health refreshes; ``blocking()`` is non-consuming, so
        listing targets never eats a half-open probe slot)."""
        self._refresh()
        with self._lock:
            urls = [u for u in self._urls
                    if self._status.get(u) in _ROUTABLE]
            breakers = [self._breakers.get(u) for u in urls]
        return [u for u, br in zip(urls, breakers)
                if br is None or not br.blocking()]

    def statuses(self) -> Dict[str, str]:
        self._refresh()
        with self._lock:
            return dict(self._status)

    # ------------------------------------------------------------ obs
    def observed_p99_s(self) -> Optional[float]:
        with self._lock:
            lat = sorted(self._latencies)
        if len(lat) < 8:
            return None
        return lat[min(len(lat) - 1, int(0.99 * len(lat)))]

    def _hedge_delay(self) -> float:
        p99 = self.observed_p99_s()
        return max(p99, 0.01) if p99 is not None else self.hedge_delay_s

    def resilience_stats(self) -> Dict[str, Any]:
        """One fold of the whole layer — what loadgen dumps next to its
        timeline and the soak e2e gates on."""
        with self._lock:
            breakers = dict(self._breakers)
            out: Dict[str, Any] = {
                "sent": self.sent, "failovers": self.failovers,
                "no_route": self.no_route,
                "hedges_fired": self.hedges_fired,
                "hedges_won": self.hedges_won,
                "deadline_misses": self.deadline_misses,
                "breaker_skips": self.breaker_skips,
                "all_shed": self.all_shed,
            }
        snaps = {u: br.snapshot() for u, br in sorted(breakers.items())}
        out["budget"] = self.budget.snapshot()
        out["breakers"] = snaps
        out["breaker_opens"] = sum(s["opens"] for s in snaps.values())
        out["breaker_closes"] = sum(s["closes"] for s in snaps.values())
        return out

    # ----------------------------------------------------------- send
    def post(self, path: str, body: bytes,
             headers: Optional[Dict[str, str]] = None
             ) -> Tuple[int, Any, Optional[str]]:
        """POST ``body`` to ``path`` on the next routable replica,
        failing over through distinct routable replicas on connection
        errors / 503 / 429 before giving up. Returns
        ``(status_code, payload, url)``; ``(0, None, None)`` when no
        replica is routable at all."""
        code, payload, url, _ = self.post_ex(path, body, headers)
        return code, payload, url

    def post_ex(self, path: str, body: bytes,
                headers: Optional[Dict[str, str]] = None, *,
                deadline_s: Optional[float] = None,
                hedge: Optional[bool] = None
                ) -> Tuple[int, Any, Optional[str], Dict[str, Any]]:
        """:meth:`post` with the resilience layer surfaced: returns
        ``(code, payload, url, meta)`` where ``meta`` counts what the
        layer did for this one request (attempts/retries/hedge/deadline
        verdicts). With ``deadline_s`` every attempt carries the
        *remaining* budget in ``X-Deadline-Ms`` and no retry or hedge
        is launched past it."""
        t0 = time.monotonic()
        deadline = t0 + deadline_s if deadline_s else None
        meta: Dict[str, Any] = {
            "attempts": 0, "retries": 0, "hedged": False,
            "hedge_won": False, "deadline_miss": False,
            "budget_exhausted": False, "no_route": False,
            "retry_after_s": None, "all_shed": False}
        do_hedge = self.hedge if hedge is None else bool(hedge)

        targets = self.routable()
        if not targets:
            self._refresh(force=True)
            targets = self.routable()
        if not targets:
            with self._lock:
                self.no_route += 1
            meta["no_route"] = True
            return 0, None, None, meta
        with self._lock:
            start = self._rr % len(targets)
            self._rr += 1
        order = [targets[(start + i) % len(targets)]
                 for i in range(len(targets))]

        hints: List[float] = []      # retry_after_s from 429 bodies
        codes: List[int] = []
        last: Tuple[int, Any, Optional[str]] = (0, None, None)

        def remaining() -> Optional[float]:
            return None if deadline is None else deadline - time.monotonic()

        def admit(first: bool) -> bool:
            """May another attempt launch? Spends budget past the first."""
            rem = remaining()
            if rem is not None and rem <= 0:
                meta["deadline_miss"] = True
                return False
            if not first and not self.budget.try_spend():
                meta["budget_exhausted"] = True
                return False
            return True

        def settle(code: int, payload: Any, url: str
                   ) -> Optional[Tuple[int, Any, Optional[str]]]:
            """Fold one attempt outcome; non-None means return it."""
            codes.append(code)
            if code == 429 and isinstance(payload, dict):
                try:
                    hints.append(float(payload["retry_after_s"]))
                except (KeyError, TypeError, ValueError):
                    pass
            if code not in _RETRYABLE:
                self.budget.note_success()
                with self._lock:
                    self.sent += 1
                return code, payload, url
            with self._lock:
                self.failovers += 1
            return None

        idx = 0
        first_attempt = True
        while idx < len(order):
            url = order[idx]
            idx += 1
            br = self._breaker(url)
            if not br.allow():
                with self._lock:
                    self.breaker_skips += 1
                continue
            if not admit(first_attempt):
                br.release()     # never sent; free the probe slot
                break
            if not first_attempt:
                meta["retries"] += 1
            meta["attempts"] += 1
            hedged_here = (first_attempt and do_hedge
                           and not meta["hedged"])
            first_attempt = False
            if hedged_here:
                result = self._attempt_hedged(url, order, idx, path,
                                              body, headers, remaining,
                                              meta)
            else:
                code, payload = self._attempt(url, path, body, headers,
                                              remaining())
                result = (code, payload, url)
            if result is None:
                continue
            won = settle(*result)
            if won is not None:
                return won[0], won[1], won[2], meta
            last = result
        if meta["deadline_miss"]:
            with self._lock:
                self.deadline_misses += 1
        if codes and all(c == 429 for c in codes):
            # every replica answered "shedding": not a dead fleet —
            # surface the smallest admission backoff hint it computed
            meta["all_shed"] = True
            with self._lock:
                self.all_shed += 1
            payload = dict(last[1]) if isinstance(last[1], dict) else {}
            payload["all_shed"] = True
            if hints:
                payload["retry_after_s"] = min(hints)
            last = (last[0], payload, last[2])
        if hints:
            meta["retry_after_s"] = min(hints)
        return last[0], last[1], last[2], meta

    # --------------------------------------------------- one attempt
    def _attempt(self, url: str, path: str, body: bytes,
                 headers: Optional[Dict[str, str]],
                 remaining_s: Optional[float]) -> Tuple[int, Any]:
        """One synchronous attempt: capped by the remaining deadline,
        deadline header stamped, breaker + latency recorded."""
        timeout = self.timeout_s
        hdrs = dict(headers or {})
        if remaining_s is not None:
            timeout = max(min(timeout, remaining_s), 1e-3)
            hdrs[DEADLINE_HEADER] = str(max(int(remaining_s * 1000), 1))
        t0 = time.monotonic()
        code, payload = self._post_one(url + path, body, hdrs, timeout)
        self._note_outcome(url, code, time.monotonic() - t0)
        return code, payload

    def _attempt_hedged(self, url: str, order: List[str], next_idx: int,
                        path: str, body: bytes,
                        headers: Optional[Dict[str, str]],
                        remaining, meta: Dict[str, Any]
                        ) -> Optional[Tuple[int, Any, Optional[str]]]:
        """First attempt with tail hedging: launch ``url``, and if no
        answer lands within the observed-p99 delay, spend one budget
        token on a second attempt at the next distinct replica. First
        answer wins; the loser keeps running on its daemon worker (its
        outcome still lands in the breaker) but nobody waits for it.
        Returns the winning ``(code, payload, url)`` or ``None`` when
        every launched attempt failed retryably."""
        results: "Queue[Tuple[str, int, Any]]" = Queue()

        def fire(target: str) -> None:
            rem = remaining()
            timeout = self.timeout_s
            hdrs = dict(headers or {})
            if rem is not None:
                timeout = max(min(timeout, rem), 1e-3)
                hdrs[DEADLINE_HEADER] = str(max(int(rem * 1000), 1))

            def worker() -> None:
                t0 = time.monotonic()
                code, payload = self._post_one(target + path, body, hdrs,
                                               timeout)
                self._note_outcome(target, code,
                                   time.monotonic() - t0)
                results.put((target, code, payload))

            obs_threads.spawn(worker, name="router-hedge", daemon=True)

        fire(url)
        in_flight = 1
        rem = remaining()
        delay = self._hedge_delay()
        if rem is not None:
            delay = min(delay, max(rem, 0.0))
        try:
            target, code, payload = results.get(timeout=delay)
        except Empty:
            pass
        else:
            # primary answered within the hedge delay — no hedge
            # needed; the caller settles success vs failover
            return code, payload, target
        # primary is slow: buy a hedge at the next distinct,
        # breaker-admitted replica (if the budget allows)
        hedge_url = None
        for j in range(next_idx, next_idx + len(order) - 1):
            cand = order[j % len(order)]
            if cand == url:
                continue
            if self._breaker(cand).allow():
                hedge_url = cand
                break
        if hedge_url is not None:
            if self.budget.try_spend():
                meta["hedged"] = True
                with self._lock:
                    self.hedges_fired += 1
                fire(hedge_url)
                in_flight += 1
            else:
                self._breaker(hedge_url).release()
        best: Optional[Tuple[int, Any, Optional[str]]] = None
        while in_flight > 0:
            rem = remaining()
            timeout = self.timeout_s + 1.0 if rem is None else max(rem, 0.0)
            try:
                target, code, payload = results.get(timeout=timeout)
            except Empty:
                meta["deadline_miss"] = True
                break
            in_flight -= 1
            if code not in _RETRYABLE:
                if meta["hedged"]:
                    if target != url:
                        meta["hedge_won"] = True
                        with self._lock:
                            self.hedges_won += 1
                    elif in_flight > 0:
                        # primary won; refund the abandoned loser
                        self.budget.give_back()
                return code, payload, target
            best = (code, payload, target)
        return best

    def _note_outcome(self, url: str, code: int, elapsed_s: float) -> None:
        self._breaker(url).record(code not in _FAILURE_CODES)
        if code not in _RETRYABLE and code != 0:
            with self._lock:
                self._latencies.append(elapsed_s)

    def _post_one(self, url: str, body: bytes,
                  headers: Optional[Dict[str, str]],
                  timeout: Optional[float] = None) -> Tuple[int, Any]:
        req = urllib.request.Request(url, data=body,
                                     headers=headers or {},
                                     method="POST")
        try:
            with urllib.request.urlopen(
                    req, timeout=self.timeout_s
                    if timeout is None else timeout) as resp:
                return resp.status, json.loads(resp.read().decode())
        except urllib.error.HTTPError as e:
            try:
                return e.code, json.loads(e.read().decode())
            except Exception:  # noqa: BLE001
                return e.code, None
        except (OSError, ValueError, urllib.error.URLError):
            return 0, None
