"""Fleet router: the client-side front queue over N replica URLs.

The drain half of drain-and-requeue only works if SOMETHING stops
routing to a draining replica — in production that is a balancer
honoring 503s; in this repo (and its tier-1 choreography test) it is
this stdlib router: round-robin over replicas whose last ``/healthz``
read was routable (``ready``/``warming``/``degraded`` — states that
still answer), with failover on refusal. A replica reporting
``draining``/``wedged``/unreachable is skipped at the health refresh,
and a request that still lands on one (the refresh is periodic, not
clairvoyant) fails over to the next distinct replica instead of
surfacing the 503/connection error to the caller.

Host-side only — urllib, no jax — usable from ``tools/loadgen.py``
(HTTP open-loop mode) and tests.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional, Sequence, Tuple

__all__ = ["FleetRouter"]

# healthz statuses a request may still be sent to: a warming replica
# queues (slowly), a degraded one sheds but answers; draining and
# wedged ones must see no NEW traffic
_ROUTABLE = ("ready", "warming", "degraded")


class FleetRouter:
    """Round-robin + failover over ``urls`` (or a live ``refresh_fn``
    returning the current URL set, e.g. a ``discover_endpoints``
    closure — scale-ups join the rotation at the next refresh)."""

    def __init__(self, urls: Sequence[str] = (), *,
                 refresh_fn=None,
                 health_ttl_s: float = 0.5,
                 timeout_s: float = 10.0):
        self._urls = [u.rstrip("/") for u in urls]
        self._refresh_fn = refresh_fn
        self.health_ttl_s = float(health_ttl_s)
        self.timeout_s = float(timeout_s)
        self._lock = threading.Lock()
        self._rr = 0
        self._status: Dict[str, str] = {}
        self._checked_at = 0.0
        self.sent = 0
        self.failovers = 0
        self.no_route = 0
        self.refresh_errors = 0
        self.last_refresh_error: Optional[str] = None

    # ---------------------------------------------------------- health
    def _healthz(self, url: str) -> str:
        try:
            req = urllib.request.Request(url + "/healthz")
            with urllib.request.urlopen(
                    req, timeout=self.timeout_s) as resp:
                doc = json.loads(resp.read().decode())
        except urllib.error.HTTPError as e:
            try:
                doc = json.loads(e.read().decode())
            except Exception:  # noqa: BLE001 - body optional
                return "unreachable"
        except (OSError, ValueError, urllib.error.URLError):
            return "unreachable"
        return str(doc.get("status", "unreachable"))

    def _refresh(self, force: bool = False) -> None:
        now = time.monotonic()
        with self._lock:
            stale = force or now - self._checked_at >= self.health_ttl_s
            if not stale:
                return
            self._checked_at = now
        if self._refresh_fn is not None:
            try:
                self._urls = [u.rstrip("/")
                              for u in self._refresh_fn()]
            except Exception as e:  # noqa: BLE001 - keep the last set
                self.refresh_errors += 1
                self.last_refresh_error = repr(e)
        status = {u: self._healthz(u) for u in list(self._urls)}
        with self._lock:
            self._status = status

    def routable(self) -> List[str]:
        self._refresh()
        with self._lock:
            return [u for u in self._urls
                    if self._status.get(u) in _ROUTABLE]

    def statuses(self) -> Dict[str, str]:
        self._refresh()
        with self._lock:
            return dict(self._status)

    # ----------------------------------------------------------- send
    def post(self, path: str, body: bytes,
             headers: Optional[Dict[str, str]] = None
             ) -> Tuple[int, Any, Optional[str]]:
        """POST ``body`` to ``path`` on the next routable replica,
        failing over through every distinct routable replica on
        connection errors / 503 / 429 before giving up. Returns
        ``(status_code, payload, url)``; ``(0, None, None)`` when no
        replica is routable at all."""
        targets = self.routable()
        if not targets:
            self._refresh(force=True)
            targets = self.routable()
        if not targets:
            self.no_route += 1
            return 0, None, None
        with self._lock:
            start = self._rr % len(targets)
            self._rr += 1
        last: Tuple[int, Any, Optional[str]] = (0, None, None)
        for i in range(len(targets)):
            url = targets[(start + i) % len(targets)]
            code, payload = self._post_one(url + path, body, headers)
            if code not in (0, 429, 503):
                self.sent += 1
                return code, payload, url
            last = (code, payload, url)
            self.failovers += 1
        return last

    def _post_one(self, url: str, body: bytes,
                  headers: Optional[Dict[str, str]]
                  ) -> Tuple[int, Any]:
        req = urllib.request.Request(url, data=body,
                                     headers=headers or {},
                                     method="POST")
        try:
            with urllib.request.urlopen(
                    req, timeout=self.timeout_s) as resp:
                return resp.status, json.loads(resp.read().decode())
        except urllib.error.HTTPError as e:
            try:
                return e.code, json.loads(e.read().decode())
            except Exception:  # noqa: BLE001
                return e.code, None
        except (OSError, ValueError, urllib.error.URLError):
            return 0, None
