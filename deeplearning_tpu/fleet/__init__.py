"""Fleet control plane: close the sense→decide→act loop over replicas.

- :mod:`.policy` — :class:`FleetPolicy`: EWMA-smoothed hysteresis
  autoscaling verdicts (pure; unit-testable).
- :mod:`.replicaset` — :class:`ReplicaSet`: N supervised children as a
  resizable collection with runtime lifecycle verbs.
- :mod:`.controller` — :class:`FleetController`: the ticking loop that
  scrapes, heals wedged replicas (drain → requeue), autoscales, and
  treats preemption as a capacity event.
- :mod:`.router` — :class:`FleetRouter`: the client-side front queue
  that stops routing to draining/wedged replicas.

Host-only modules (DLT100 hot-path covered): the control plane never
performs device work or syncs — a controller that can wedge in the
same device call it polices is no controller at all.
"""

from .controller import CONTROLLER_FLIGHT_FILE, FleetController
from .policy import Decision, FleetPolicy
from .replicaset import ReplicaSet
from .router import FleetRouter

__all__ = ["FleetPolicy", "Decision", "ReplicaSet", "FleetController",
           "FleetRouter", "CONTROLLER_FLIGHT_FILE"]
