"""Scaling policy: rollup signals in, one actuation verdict out.

The policy is the pure middle of the controller's sense→decide→act
loop: :meth:`FleetPolicy.observe` takes one ``obs/fleet.py`` rollup plus
the live replica count and returns a :class:`Decision` — ``scale_up``,
``scale_down``, or ``hold`` — with the smoothed signals that justified
it. No I/O, no threads, no clock reads of its own (callers pass
``now``), so every hysteresis corner is unit-testable in microseconds.

The "sustained, not instantaneous" judgment reuses the admission
controller's :class:`~..serve.admission.Ewma` smoothing, then demands a
*streak*: a signal must breach for ``breach_polls`` consecutive
observations before a scale-up, and the fleet must sit idle for
``idle_polls`` before a scale-down — one hiccup batch or one quiet
second never moves capacity. ``cooldown_s`` spaces consecutive actions
so a decision gets to land (a replica takes seconds to warm) before the
next one is considered; min/max bounds are absolute.

Preemption is a capacity event, not a failure: :meth:`on_preemption`
answers "replace or shed?" from the same smoothed demand signals —
replace while there is work (or the floor is at risk), shed when the
fleet was idle anyway.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional

from ..serve.admission import Ewma

__all__ = ["Decision", "FleetPolicy"]


class Decision:
    """One policy verdict. ``action`` is ``"scale_up"``/``"scale_down"``/
    ``"hold"``; ``reason`` names the trigger (``"p99_breach"``,
    ``"sustained_idle"``, ``"cooldown"``, ...); ``signals`` carries the
    smoothed values the verdict was computed from, ready for a flight
    event."""

    __slots__ = ("action", "reason", "signals")

    def __init__(self, action: str, reason: str,
                 signals: Optional[Dict[str, Any]] = None):
        self.action = action
        self.reason = reason
        self.signals = dict(signals or {})

    def __repr__(self) -> str:
        return f"Decision({self.action!r}, {self.reason!r})"


class FleetPolicy:
    """Hysteresis autoscaler over fleet rollups.

    Scale-up triggers (any, sustained for ``breach_polls`` polls, EWMA-
    smoothed):

    - e2e p99 (max over replicas) above ``p99_budget_ms``;
    - queue depth per live replica above ``queue_high``;
    - error burn (rejected + timed-out per delta window over submitted)
      above ``error_rate_budget``.

    Scale-down: ``idle_polls`` consecutive polls with (smoothed) empty
    queues, no breach, and per-replica QPS under ``idle_qps`` — and
    never below ``min_replicas``.

    Tenant brownout (:meth:`brownout_observe`) is the same hysteresis
    idea applied per model: sustained per-tenant SLO breach climbs a
    degrade ladder one step at a time, sustained clean polls descend it
    — so one tenant's overload dims that tenant before it dims the
    fleet.
    """

    def __init__(self, *, min_replicas: int = 1, max_replicas: int = 8,
                 p99_budget_ms: float = 500.0,
                 queue_high: float = 16.0,
                 error_rate_budget: float = 0.05,
                 idle_qps: float = 0.05,
                 breach_polls: int = 3,
                 idle_polls: int = 6,
                 cooldown_s: float = 30.0,
                 alpha: float = 0.2,
                 brownout_breach_polls: int = 2,
                 brownout_clear_polls: int = 3,
                 brownout_max_step: int = 3):
        if min_replicas < 0 or max_replicas < max(min_replicas, 1):
            raise ValueError(
                f"bad bounds min={min_replicas} max={max_replicas}")
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.p99_budget_ms = float(p99_budget_ms)
        self.queue_high = float(queue_high)
        self.error_rate_budget = float(error_rate_budget)
        self.idle_qps = float(idle_qps)
        self.breach_polls = int(breach_polls)
        self.idle_polls = int(idle_polls)
        self.cooldown_s = float(cooldown_s)
        # the admission controller's smoothing, one curve per signal
        self.p99 = Ewma(alpha)
        self.queue_per_replica = Ewma(alpha)
        self.error_burn = Ewma(alpha)
        self.qps_per_replica = Ewma(alpha)
        self.breach_streak = 0
        self.idle_streak = 0
        self.decisions = 0
        self._last_action_at: Optional[float] = None
        # tenant brownout ladders: {model: {step, breach, clear}}
        self.brownout_breach_polls = int(brownout_breach_polls)
        self.brownout_clear_polls = int(brownout_clear_polls)
        self.brownout_max_step = int(brownout_max_step)
        self._brownout: Dict[str, Dict[str, int]] = {}

    # -------------------------------------------------------- signals
    def _signals(self, rollup: Dict[str, Any],
                 live: int) -> Dict[str, Any]:
        live = max(int(live), 1)
        delta = rollup.get("delta") or {}
        # error burn from the delta window when available (a restart
        # resets totals; the cumulative ratio would mask a fresh burn),
        # else the cumulative rate
        submitted = delta.get("requests_total", 0.0) \
            + delta.get("rejected_total", 0.0)
        if submitted > 0:
            burn = (delta.get("rejected_total", 0.0)
                    + delta.get("timed_out_total", 0.0)) / submitted
        elif delta.get("dt_s", 0.0) > 0:
            burn = 0.0                 # a window with no traffic
        else:
            burn = rollup.get("error_rate", 0.0)
        qps = rollup.get("qps_total", 0.0)
        return {
            "p99_ms": self.p99.update(
                rollup.get("e2e_ms_p99_max", 0.0)),
            "queue_per_replica": self.queue_per_replica.update(
                rollup.get("queue_depth_total", 0.0) / live),
            "error_burn": self.error_burn.update(burn),
            "qps_per_replica": self.qps_per_replica.update(qps / live),
            "qps_total": qps,
            "live_replicas": live,
        }

    def _in_cooldown(self, now: float) -> bool:
        return (self._last_action_at is not None
                and now - self._last_action_at < self.cooldown_s)

    # -------------------------------------------------------- observe
    def observe(self, rollup: Dict[str, Any], live: int,
                now: Optional[float] = None) -> Decision:
        """Fold one rollup; return the actuation verdict for a fleet of
        ``live`` routable replicas."""
        now = time.monotonic() if now is None else now
        sig = self._signals(rollup, live)
        breaches = []
        if sig["p99_ms"] > self.p99_budget_ms:
            breaches.append("p99_breach")
        if sig["queue_per_replica"] > self.queue_high:
            breaches.append("queue_breach")
        if sig["error_burn"] > self.error_rate_budget:
            breaches.append("error_burn")
        idle = (not breaches
                and sig["queue_per_replica"] < 1.0
                and sig["qps_per_replica"] <= self.idle_qps)
        self.breach_streak = self.breach_streak + 1 if breaches else 0
        self.idle_streak = self.idle_streak + 1 if idle else 0
        sig["breach_streak"] = self.breach_streak
        sig["idle_streak"] = self.idle_streak
        self.decisions += 1

        if live < self.min_replicas:
            return self._act("scale_up", "below_min", sig, now)
        if breaches and self.breach_streak >= self.breach_polls:
            if live >= self.max_replicas:
                return Decision("hold", "at_max", sig)
            if self._in_cooldown(now):
                return Decision("hold", "cooldown", sig)
            return self._act("scale_up", breaches[0], sig, now)
        if idle and self.idle_streak >= self.idle_polls:
            if live <= self.min_replicas:
                return Decision("hold", "at_min", sig)
            if self._in_cooldown(now):
                return Decision("hold", "cooldown", sig)
            return self._act("scale_down", "sustained_idle", sig, now)
        return Decision("hold", "within_band", sig)

    def _act(self, action: str, reason: str, sig: Dict[str, Any],
             now: float) -> Decision:
        self._last_action_at = now
        # an action consumes the streak that earned it: the NEXT action
        # needs fresh evidence gathered after this one lands
        self.breach_streak = 0
        self.idle_streak = 0
        return Decision(action, reason, sig)

    # ------------------------------------------------------- brownout
    def brownout_observe(self, model: str,
                         breach: bool) -> Optional[int]:
        """Fold one per-tenant SLO verdict into that tenant's brownout
        ladder. Same hysteresis shape as scaling: a breach must sustain
        for ``brownout_breach_polls`` polls before the ladder climbs one
        step, and the tenant must run clean for ``brownout_clear_polls``
        polls before it descends one. Returns the NEW step when the
        ladder moved, ``None`` when it held — so the controller only
        actuates (and only records an event) on transitions. Steps:
        1 = largest-bucket-only dispatch, 2 = + int8 residency,
        3 = + shed a fraction of the tenant's lane."""
        st = self._brownout.setdefault(
            model, {"step": 0, "breach": 0, "clear": 0})
        if breach:
            st["breach"] += 1
            st["clear"] = 0
            if st["breach"] >= self.brownout_breach_polls \
                    and st["step"] < self.brownout_max_step:
                st["step"] += 1
                st["breach"] = 0
                return st["step"]
        else:
            st["clear"] += 1
            st["breach"] = 0
            if st["clear"] >= self.brownout_clear_polls \
                    and st["step"] > 0:
                st["step"] -= 1
                st["clear"] = 0
                return st["step"]
        return None

    def brownout_steps(self) -> Dict[str, int]:
        """Current non-zero ladder positions, ``{model: step}``."""
        return {m: st["step"] for m, st in self._brownout.items()
                if st["step"] > 0}

    # ----------------------------------------------------- preemption
    def on_preemption(self, live_after: int) -> str:
        """Exit-75 verdict: ``"replace"`` (requeue the replica now) or
        ``"shed"`` (fold the lost capacity). Replace whenever demand is
        not provably idle or the floor is at risk — losing a replica
        during load must not wait out a backoff curve."""
        if live_after < self.min_replicas:
            return "replace"
        if self.idle_streak >= self.idle_polls \
                and live_after >= self.min_replicas:
            return "shed"
        return "replace"

    def snapshot(self) -> Dict[str, Any]:
        return {
            "min_replicas": self.min_replicas,
            "max_replicas": self.max_replicas,
            "p99_budget_ms": self.p99_budget_ms,
            "queue_high": self.queue_high,
            "error_rate_budget": self.error_rate_budget,
            "breach_polls": self.breach_polls,
            "idle_polls": self.idle_polls,
            "cooldown_s": self.cooldown_s,
            "breach_streak": self.breach_streak,
            "idle_streak": self.idle_streak,
            "p99_ms": round(self.p99.value, 3),
            "queue_per_replica": round(self.queue_per_replica.value, 3),
            "error_burn": round(self.error_burn.value, 5),
            "qps_per_replica": round(self.qps_per_replica.value, 3),
            "brownout_breach_polls": self.brownout_breach_polls,
            "brownout_clear_polls": self.brownout_clear_polls,
            "brownout_steps": self.brownout_steps(),
        }
