"""One-line model loading (yolov5 ``hubconf.py`` surface).

The reference exposes ``torch.hub.load('ultralytics/yolov5', 'yolov5s')``
returning a ready-to-run model. The TPU-native equivalent returns the
flax module plus initialized (optionally checkpoint-restored) variables
and a jitted forward:

    from deeplearning_tpu import hub
    model, variables, forward = hub.load(
        "yolox_s", num_classes=80, ckpt="runs/x/ckpt/best",
        input_shape=(1, 640, 640, 3))
    out = forward(images)
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

__all__ = ["load", "list_models", "serve"]


def list_models(filter: str = "") -> list:
    """Registry names, optionally substring-filtered (timm list_models
    idiom)."""
    from .core.registry import MODELS
    names = sorted(MODELS.keys())
    return [n for n in names if filter in n] if filter else names


def load(name: str, *, num_classes: int = 1000,
         ckpt: Optional[str] = None,
         input_shape: Tuple[int, ...] = (1, 224, 224, 3),
         seed: int = 0, prefer_ema: bool = True,
         **model_kw) -> Tuple[Any, Dict, Callable]:
    """Build a registry model, init its variables on ``input_shape``,
    optionally restore a checkpoint (EMA-preferring, shared
    ``restore_variables`` semantics), and return
    ``(module, variables, forward)`` where ``forward(x)`` is the jitted
    ``train=False`` apply. Detection models return raw head outputs —
    postprocess with their family's ``*_postprocess`` (tools/demo.py
    shows the full pipeline)."""
    from .core.registry import MODELS

    model = MODELS.build(name, num_classes=num_classes, **model_kw)
    variables = model.init(jax.random.key(seed),
                           jnp.zeros(input_shape, jnp.float32),
                           train=False)
    if ckpt:
        from .core.checkpoint import restore_variables
        variables = restore_variables(ckpt, variables,
                                      prefer_ema=prefer_ema)

    @jax.jit
    def forward(x, variables=variables):
        return model.apply(variables, x, train=False)

    return model, variables, forward


def serve(name: str, *, num_classes: int = 1000,
          ckpt: Optional[str] = None, image_size: int = 224,
          batch_buckets: Tuple[int, ...] = (1, 8, 32, 128),
          **engine_kw):
    """One-line serving session: ``hub.serve("resnet18", ...)`` returns
    a warmed ``serve.InferenceEngine`` (bucketed AOT executables, zero
    compiles after this call). Wrap it in ``serve.MicroBatcher`` for the
    concurrent request path — see README "Serving policy"."""
    from .serve import InferenceEngine
    return InferenceEngine(name, num_classes=num_classes, ckpt=ckpt,
                           image_size=image_size,
                           batch_buckets=batch_buckets, **engine_kw)
