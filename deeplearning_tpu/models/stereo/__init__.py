from . import madnet  # noqa: F401
