"""MADNet: real-time self-adaptive stereo depth + MAD online adaptation.

Surface of deep_stereo/Real_time_self_adaptive_depp_stereo: MadNet
(models/MadNet.py — 6-level pyramid towers, correlation-based disparity
estimation per level, warping refinement), the photometric reprojection +
SSIM loss (losses/loss_factory.py), and the repo's only ONLINE training
loop (Stereo_Online_Adaptation.py:43-44 modes NONE/FULL/MAD with
reward-softmax block sampling :197-241; Sampler/sampler_factory.py:5-82).

TPU-first: the MAD trick (backprop only a sampled portion of the net per
frame) maps to per-module gradient gating masks — one jitted step serves
all modes; the probabilistic sampler lives host-side and feeds a
mask pytree (no retracing).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from ...core.registry import MODELS


def warp_right_to_left(right: jax.Array, disparity: jax.Array) -> jax.Array:
    """Sample right image at x - d (bilinear along x)."""
    b, h, w, c = right.shape
    xs = jnp.arange(w, dtype=jnp.float32)[None, None, :]
    src = xs - disparity[..., 0]
    x0 = jnp.clip(jnp.floor(src), 0, w - 1)
    x1 = jnp.clip(x0 + 1, 0, w - 1)
    wx = src - x0
    x0i = x0.astype(jnp.int32)
    x1i = x1.astype(jnp.int32)
    batch_idx = jnp.arange(b)[:, None, None]
    row_idx = jnp.arange(h)[None, :, None]
    v0 = right[batch_idx, row_idx, x0i]
    v1 = right[batch_idx, row_idx, x1i]
    out = v0 * (1 - wx[..., None]) + v1 * wx[..., None]
    valid = (src >= 0) & (src <= w - 1)
    return out * valid[..., None]


def correlation_1d(left: jax.Array, right: jax.Array,
                   max_disp: int = 8) -> jax.Array:
    """Horizontal correlation volume (MadNet cost volume)."""
    b, h, w, c = left.shape
    costs = []
    for d in range(max_disp + 1):
        shifted = jnp.pad(right, ((0, 0), (0, 0), (d, 0), (0, 0)))[:, :, :w]
        costs.append(jnp.mean(left * shifted, axis=-1))
    return jnp.stack(costs, axis=-1)


class PyramidTower(nn.Module):
    """Shared feature pyramid (6 levels, stride 2 each)."""
    widths: Sequence[int] = (16, 32, 64, 96, 128, 192)
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        feats = []
        for i, wdt in enumerate(self.widths):
            # SAME (TF semantics) is correct here: the reference MadNet is
            # a TF port whose conv_with_same_pad.py reimplements TF SAME
            x = nn.Conv(wdt, (3, 3), strides=(2, 2), padding="SAME",
                        dtype=self.dtype, name=f"conv{i}a")(x)
            x = nn.leaky_relu(x, 0.2)
            x = nn.Conv(wdt, (3, 3), padding="SAME", dtype=self.dtype,
                        name=f"conv{i}b")(x)
            x = nn.leaky_relu(x, 0.2)
            feats.append(x)
        return feats


class DispEstimator(nn.Module):
    """Per-level disparity decoder over [corr, left_feat, up_disp]."""
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        for i, wdt in enumerate((128, 128, 96, 64, 32)):
            x = nn.Conv(wdt, (3, 3), padding="SAME", dtype=self.dtype,
                        name=f"c{i}")(x)
            x = nn.leaky_relu(x, 0.2)
        return nn.Conv(1, (3, 3), padding="SAME", dtype=self.dtype,
                       name="pred")(x).astype(jnp.float32)


class MADNet(nn.Module):
    max_disp: int = 8
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, left: jax.Array, right: jax.Array,
                 train: bool = False) -> Dict[str, Any]:
        tower = PyramidTower(dtype=self.dtype, name="tower")
        lf = tower(left.astype(self.dtype))
        rf = tower(right.astype(self.dtype))
        disparities: List[jax.Array] = []
        disp = None
        # coarse-to-fine from the deepest level (module names D6..D2 match
        # the reference's per-block MAD sampling granularity)
        for li in reversed(range(1, len(lf))):
            l_feat, r_feat = lf[li], rf[li]
            if disp is not None:
                b, h, w, _ = l_feat.shape
                disp_up = jax.image.resize(disp, (b, h, w, 1),
                                           "bilinear") * 2.0
                r_feat = warp_right_to_left(r_feat, disp_up)
            else:
                disp_up = jnp.zeros(l_feat.shape[:3] + (1,), jnp.float32)
            corr = correlation_1d(l_feat.astype(jnp.float32),
                                  r_feat.astype(jnp.float32),
                                  self.max_disp)
            inp = jnp.concatenate(
                [corr.astype(self.dtype), l_feat, disp_up.astype(
                    self.dtype)], axis=-1)
            residual = DispEstimator(self.dtype, name=f"D{li + 1}")(inp)
            disp = nn.relu(disp_up + residual)
            disparities.append(disp)
        b, h, w, _ = left.shape
        # finest loop level sits at stride 4: the 4x spatial upsample must
        # scale disparity values by 4 as well
        full = jax.image.resize(disp, (b, h, w, 1), "bilinear") * 4.0
        return {"disparity": full, "pyramid": disparities}


def photometric_loss(left: jax.Array, right: jax.Array,
                     disparity: jax.Array, alpha: float = 0.85
                     ) -> jax.Array:
    """SSIM + L1 reprojection loss (losses/loss_factory.py surface)."""
    warped = warp_right_to_left(right, disparity)
    l1 = jnp.abs(left - warped)
    # simplified 3x3 SSIM
    def pool(x):
        return nn.avg_pool(x, (3, 3), strides=(1, 1), padding="SAME")
    mu_x = pool(left)
    mu_y = pool(warped)
    sx = pool(left ** 2) - mu_x ** 2
    sy = pool(warped ** 2) - mu_y ** 2
    sxy = pool(left * warped) - mu_x * mu_y
    c1, c2 = 0.01 ** 2, 0.03 ** 2
    ssim = ((2 * mu_x * mu_y + c1) * (2 * sxy + c2)) / (
        (mu_x ** 2 + mu_y ** 2 + c1) * (sx + sy + c2))
    dssim = jnp.clip((1 - ssim) / 2, 0, 1)
    return jnp.mean(alpha * dssim + (1 - alpha) * l1)


class MADSampler:
    """Reward-softmax block selection (Stereo_Online_Adaptation.py:197-241
    + sampler_factory.py): keeps a score per trainable block, samples
    which blocks to adapt this frame, updates scores from the loss
    improvement. Host-side; emits a gradient gating mask pytree."""

    def __init__(self, block_names: Sequence[str], sample_n: int = 2,
                 temperature: float = 1.0, ema: float = 0.99,
                 mode: str = "probabilistic", seed: int = 0):
        self.blocks = list(block_names)
        self.scores = np.zeros(len(self.blocks))
        self.sample_n = sample_n
        self.temperature = temperature
        self.ema = ema
        self.mode = mode
        self.rng = np.random.default_rng(seed)
        self.last_loss: Optional[float] = None
        self._round_robin = 0

    def sample(self) -> List[str]:
        if self.mode == "full":
            return list(self.blocks)
        if self.mode == "none":
            return []
        if self.mode == "sequential":
            sel = [self.blocks[self._round_robin % len(self.blocks)]]
            self._round_robin += 1
            return sel
        if self.mode == "argmax":
            order = np.argsort(-self.scores)
            return [self.blocks[i] for i in order[:self.sample_n]]
        if self.mode == "random":
            idx = self.rng.choice(len(self.blocks), self.sample_n,
                                  replace=False)
            return [self.blocks[i] for i in idx]
        # probabilistic (reward softmax)
        p = np.exp(self.scores / self.temperature)
        p = p / p.sum()
        idx = self.rng.choice(len(self.blocks), self.sample_n,
                              replace=False, p=p)
        return [self.blocks[i] for i in idx]

    def update(self, selected: Sequence[str], loss: float) -> None:
        if self.last_loss is not None:
            reward = self.last_loss - loss         # improvement
            for name in selected:
                i = self.blocks.index(name)
                self.scores[i] = self.ema * self.scores[i] \
                    + (1 - self.ema) * reward
        self.last_loss = loss

    def grad_mask(self, params, selected: Sequence[str]):
        """1/0 mask pytree: gradients flow only into selected top-level
        blocks (the MAD partial-backprop trick as a multiply)."""
        sel = set(selected)
        return {k: jax.tree.map(
            lambda _: 1.0 if k in sel else 0.0, v)
            for k, v in params.items()}


@MODELS.register("madnet")
def madnet(**kw):
    return MADNet(**kw)
