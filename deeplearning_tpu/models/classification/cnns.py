"""VGG + GoogLeNet (Inception v1) — classic CNN baselines.

Surface of classification/vggNet (cfg-list VGG-11/13/16/19 builder) and
classification/GoogleNet (Inception v1 with aux classifier heads,
B-harness). The aux heads are returned during training (the caller weighs
them 0.3 as the reference harness does).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, Sequence, Tuple, Union

import flax.linen as nn
import jax.numpy as jnp

from ...ops.padding import torch_pad
from ...core.registry import MODELS

VGG_CFGS: Dict[str, Sequence[Union[int, str]]] = {
    "vgg11": (64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"),
    "vgg13": (64, 64, "M", 128, 128, "M", 256, 256, "M", 512, 512, "M",
              512, 512, "M"),
    "vgg16": (64, 64, "M", 128, 128, "M", 256, 256, 256, "M",
              512, 512, 512, "M", 512, 512, 512, "M"),
    "vgg19": (64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M",
              512, 512, 512, 512, "M", 512, 512, 512, 512, "M"),
}


class VGG(nn.Module):
    cfg: Sequence[Union[int, str]]
    num_classes: int = 1000
    use_bn: bool = True
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        conv_i = 0
        for v in self.cfg:
            if v == "M":
                x = nn.max_pool(x, (2, 2), strides=(2, 2))
            else:
                x = nn.Conv(int(v), (3, 3), padding="SAME",
                            use_bias=not self.use_bn, dtype=self.dtype,
                            name=f"conv{conv_i}")(x)
                if self.use_bn:
                    x = nn.BatchNorm(use_running_average=not train,
                                     momentum=0.9, dtype=self.dtype,
                                     name=f"bn{conv_i}")(x)
                x = nn.relu(x)
                conv_i += 1
        x = x.reshape(x.shape[0], -1)
        x = nn.Dense(4096, dtype=self.dtype, name="fc1")(x)
        x = nn.relu(x)
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.Dense(4096, dtype=self.dtype, name="fc2")(x)
        x = nn.relu(x)
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="fc3")(x)
        return x.astype(jnp.float32)


class InceptionBlock(nn.Module):
    c1: int
    c2: Tuple[int, int]
    c3: Tuple[int, int]
    c4: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        conv = partial(nn.Conv, dtype=self.dtype, padding="SAME")
        b1 = nn.relu(conv(self.c1, (1, 1), name="b1")(x))
        b2 = nn.relu(conv(self.c2[0], (1, 1), name="b2a")(x))
        b2 = nn.relu(conv(self.c2[1], (3, 3), name="b2b")(b2))
        b3 = nn.relu(conv(self.c3[0], (1, 1), name="b3a")(x))
        b3 = nn.relu(conv(self.c3[1], (5, 5), name="b3b")(b3))
        b4 = nn.max_pool(x, (3, 3), strides=(1, 1), padding="SAME")
        b4 = nn.relu(conv(self.c4, (1, 1), name="b4")(b4))
        return jnp.concatenate([b1, b2, b3, b4], axis=-1)


class AuxHead(nn.Module):
    num_classes: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool):
        x = nn.avg_pool(x, (5, 5), strides=(3, 3))
        x = nn.relu(nn.Conv(128, (1, 1), dtype=self.dtype, name="conv")(x))
        x = x.reshape(x.shape[0], -1)
        x = nn.relu(nn.Dense(1024, dtype=self.dtype, name="fc1")(x))
        x = nn.Dropout(0.7, deterministic=not train)(x)
        return nn.Dense(self.num_classes, dtype=self.dtype,
                        name="fc2")(x).astype(jnp.float32)


class GoogLeNet(nn.Module):
    num_classes: int = 1000
    aux_logits: bool = True
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        conv = partial(nn.Conv, dtype=self.dtype, padding="SAME")
        x = x.astype(self.dtype)
        x = nn.relu(conv(64, (7, 7), strides=(2, 2),
                         padding=torch_pad(7), name="conv1")(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        x = nn.relu(conv(64, (1, 1), name="conv2")(x))
        x = nn.relu(conv(192, (3, 3), name="conv3")(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        x = InceptionBlock(64, (96, 128), (16, 32), 32, self.dtype,
                           name="inc3a")(x)
        x = InceptionBlock(128, (128, 192), (32, 96), 64, self.dtype,
                           name="inc3b")(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        x = InceptionBlock(192, (96, 208), (16, 48), 64, self.dtype,
                           name="inc4a")(x)
        # aux heads always run so their params exist under eval-mode init;
        # the tuple is only returned in train mode
        aux1 = (AuxHead(self.num_classes, self.dtype, name="aux1")(x, train)
                if self.aux_logits else None)
        x = InceptionBlock(160, (112, 224), (24, 64), 64, self.dtype,
                           name="inc4b")(x)
        x = InceptionBlock(128, (128, 256), (24, 64), 64, self.dtype,
                           name="inc4c")(x)
        x = InceptionBlock(112, (144, 288), (32, 64), 64, self.dtype,
                           name="inc4d")(x)
        aux2 = (AuxHead(self.num_classes, self.dtype, name="aux2")(x, train)
                if self.aux_logits else None)
        x = InceptionBlock(256, (160, 320), (32, 128), 128, self.dtype,
                           name="inc4e")(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        x = InceptionBlock(256, (160, 320), (32, 128), 128, self.dtype,
                           name="inc5a")(x)
        x = InceptionBlock(384, (192, 384), (48, 128), 128, self.dtype,
                           name="inc5b")(x)
        x = jnp.mean(x.astype(jnp.float32), axis=(1, 2))
        x = nn.Dropout(0.4, deterministic=not train)(x)
        logits = nn.Dense(self.num_classes, dtype=self.dtype,
                          name="fc")(x.astype(self.dtype))
        logits = logits.astype(jnp.float32)
        if self.aux_logits and train:
            return logits, (aux1, aux2)
        return logits


for _name, _cfg in VGG_CFGS.items():
    def _mk(cfg):
        def build(num_classes: int = 1000, **kw):
            return VGG(cfg=cfg, num_classes=num_classes, **kw)
        return build
    MODELS.register(_name)(_mk(_cfg))


@MODELS.register("googlenet")
def googlenet(num_classes: int = 1000, **kw):
    return GoogLeNet(num_classes=num_classes, **kw)
