"""Vision Transformer — the north-star model (BASELINE.md: ViT-B/16 MFU).

Capability surface of classification/vision_transformer/vit_model.py:
drop_path (:12), PatchEmbed (:43), fused-qkv Attention (:71, softmax attn
:100-111), Mlp (:114), Block (:136), VisionTransformer (:164,
forward_features :240 — cls token + learned pos embed), and the model
factories (:290-358: B/16, B/32, L/16, L/32, H/14).

TPU-first design choices (not in the reference):
- bf16 compute / f32 params; logits returned f32.
- attention is a pluggable callable so the Pallas flash-attention kernel
  (ops/pallas) can replace the naive softmax path at scale.
- ``remat`` wraps each Block with jax.checkpoint (the torch
  gradient-checkpointing analog, swin_transformer.py:410-411) to trade
  FLOPs for HBM.
- token count is static → everything tiles cleanly onto the MXU.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from ...core import numerics
from ...core.registry import MODELS


def drop_path(x: jax.Array, rate: float, deterministic: bool,
              rng: Optional[jax.Array] = None) -> jax.Array:
    """Stochastic depth on the residual branch (vit_model.py:12)."""
    if deterministic or rate == 0.0:
        return x
    keep = 1.0 - rate
    shape = (x.shape[0],) + (1,) * (x.ndim - 1)
    mask = jax.random.bernoulli(rng, keep, shape).astype(x.dtype)
    return x / keep * mask


class DropPath(nn.Module):
    rate: float = 0.0

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        if self.rate == 0.0 or deterministic:
            return x
        return drop_path(x, self.rate, deterministic,
                         self.make_rng("dropout"))


class PatchEmbed(nn.Module):
    """Image → patch tokens (vit_model.py:43).

    The reference's strided conv IS a block reshape + matmul; lowering it
    explicitly that way measures +1.2 MFU points on the v5e ViT-B/16 train
    step vs XLA's conv path (52.03% vs 50.87%, tools/mfu_results.jsonl
    patch_matmul_b128). Params keep the conv's HWIO kernel shape
    (p, p, c, embed) and "proj" naming, so checkpoints and torch-weight
    ports are unaffected — the kernel is reshaped at trace time."""
    patch_size: int = 16
    embed_dim: int = 768
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        p = self.patch_size
        b, hh, ww, c = x.shape
        h, w = hh // p, ww // p
        x = x.reshape(b, h, p, w, p, c).transpose(0, 1, 3, 2, 4, 5)
        x = x.reshape(b, h * w, p * p * c)
        return _PatchProj(p, c, self.embed_dim, self.dtype, name="proj")(x)


class _PatchProj(nn.Module):
    """Conv-shaped (HWIO) params applied as a flat matmul (PatchEmbed)."""
    patch_size: int
    in_chans: int
    embed_dim: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        p, c = self.patch_size, self.in_chans
        kernel = self.param("kernel", nn.initializers.lecun_normal(),
                            (p, p, c, self.embed_dim), jnp.float32)
        bias = self.param("bias", nn.initializers.zeros,
                          (self.embed_dim,), jnp.float32)
        y = x.astype(self.dtype) @ kernel.reshape(
            p * p * c, self.embed_dim).astype(self.dtype)
        return y + bias.astype(self.dtype)


def dot_product_attention(q, k, v, dropout_rate=0.0, deterministic=True,
                          rng=None):
    """Naive softmax attention — the lax reference path the Pallas kernel is
    tested against. q,k,v: (B, N, H, D)."""
    scale = q.shape[-1] ** -0.5
    attn = jnp.einsum("bqhd,bkhd->bhqk", q * scale, k)
    attn = jax.nn.softmax(attn.astype(jnp.float32), axis=-1).astype(q.dtype)
    if dropout_rate > 0 and not deterministic:
        keep = jax.random.bernoulli(rng, 1.0 - dropout_rate, attn.shape)
        attn = attn * keep.astype(attn.dtype) / (1.0 - dropout_rate)
    return jnp.einsum("bhqk,bkhd->bqhd", attn, v)


class Attention(nn.Module):
    """Fused-qkv multi-head attention (vit_model.py:71)."""
    num_heads: int = 8
    qkv_bias: bool = True
    attn_drop: float = 0.0
    proj_drop: float = 0.0
    dtype: Any = jnp.bfloat16
    attn_fn: Optional[Callable] = None

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        b, n, c = x.shape
        head_dim = c // self.num_heads
        qkv = nn.Dense(3 * c, use_bias=self.qkv_bias, dtype=self.dtype,
                       name="qkv")(x)
        qkv = qkv.reshape(b, n, 3, self.num_heads, head_dim)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        fn = self.attn_fn or dot_product_attention
        rng = (self.make_rng("dropout")
               if (self.attn_drop > 0 and not deterministic) else None)
        out = fn(q, k, v, dropout_rate=self.attn_drop,
                 deterministic=deterministic, rng=rng)
        out = out.reshape(b, n, c)
        out = nn.Dense(c, dtype=self.dtype, name="proj")(out)
        out = nn.Dropout(self.proj_drop, deterministic=deterministic)(out)
        return out


class Mlp(nn.Module):
    hidden_ratio: float = 4.0
    drop: float = 0.0
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        c = x.shape[-1]
        x = nn.Dense(int(c * self.hidden_ratio), dtype=self.dtype,
                     name="fc1")(x)
        # GELU via the numerics mode: tanh by default (erf costs 3.8 MFU
        # points on the v5e ViT-B/16 step — core/numerics.py), exact erf
        # under parity mode to match torch nn.GELU() (vit_model.py:114)
        x = numerics.gelu(x)
        x = nn.Dropout(self.drop, deterministic=deterministic)(x)
        x = nn.Dense(c, dtype=self.dtype, name="fc2")(x)
        x = nn.Dropout(self.drop, deterministic=deterministic)(x)
        return x


class Block(nn.Module):
    num_heads: int
    mlp_ratio: float = 4.0
    qkv_bias: bool = True
    drop: float = 0.0
    attn_drop: float = 0.0
    drop_path_rate: float = 0.0
    dtype: Any = jnp.bfloat16
    attn_fn: Optional[Callable] = None

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        y = nn.LayerNorm(dtype=self.dtype, name="norm1")(x)
        y = Attention(self.num_heads, self.qkv_bias, self.attn_drop,
                      self.drop, self.dtype, self.attn_fn, name="attn")(
            y, deterministic)
        x = x + DropPath(self.drop_path_rate)(y, deterministic)
        y = nn.LayerNorm(dtype=self.dtype, name="norm2")(x)
        y = Mlp(self.mlp_ratio, self.drop, self.dtype, name="mlp")(
            y, deterministic)
        return x + DropPath(self.drop_path_rate)(y, deterministic)


class VisionTransformer(nn.Module):
    img_size: int = 224
    patch_size: int = 16
    num_classes: int = 1000
    embed_dim: int = 768
    depth: int = 12
    num_heads: int = 12
    mlp_ratio: float = 4.0
    qkv_bias: bool = True
    drop_rate: float = 0.0
    attn_drop_rate: float = 0.0
    drop_path_rate: float = 0.0
    representation_size: Optional[int] = None
    dtype: Any = jnp.bfloat16
    remat: bool = False
    attn_fn: Optional[Callable] = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        deterministic = not train
        x = PatchEmbed(self.patch_size, self.embed_dim, self.dtype,
                       name="patch_embed")(x)
        b, n, c = x.shape
        cls = self.param("cls_token", nn.initializers.zeros, (1, 1, c),
                         jnp.float32)
        x = jnp.concatenate(
            [jnp.broadcast_to(cls.astype(x.dtype), (b, 1, c)), x], axis=1)
        pos = self.param("pos_embed",
                         nn.initializers.truncated_normal(0.02),
                         (1, n + 1, c), jnp.float32)
        # explicit broadcast: its transpose is ONE reduce_sum over batch,
        # which GSPMD shards cleanly; the implicit-broadcast add's
        # transpose accumulated pos grads through an add_any chain whose
        # chosen sharding forced an involuntary full rematerialization
        # under data x fsdp meshes (MULTICHIP r3 tail warnings)
        x = x + jnp.broadcast_to(pos.astype(x.dtype), x.shape)
        x = nn.Dropout(self.drop_rate, deterministic=deterministic)(x)

        import numpy as np
        dpr = [float(r) for r in
               np.linspace(0, self.drop_path_rate, self.depth)]
        block_cls = Block
        if self.remat:
            block_cls = nn.remat(Block, static_argnums=(2,))
        for i in range(self.depth):
            x = block_cls(self.num_heads, self.mlp_ratio, self.qkv_bias,
                          self.drop_rate, self.attn_drop_rate, dpr[i],
                          self.dtype, self.attn_fn, name=f"blocks_{i}")(
                x, deterministic)
        x = nn.LayerNorm(dtype=self.dtype, name="norm")(x)
        x = x[:, 0]
        if self.representation_size is not None:
            x = nn.Dense(self.representation_size, dtype=self.dtype,
                         name="pre_logits")(x)
            x = nn.tanh(x)
        # trunc-normal head like the reference (vit_model.py:276-278, ALL
        # Linears std=.01). A zero-init head makes every backbone gradient
        # zero until the head moves — measured as a hard flatline on the
        # 100-class from-scratch runs (runs/convergence/swin_diag_*).
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="head",
                     kernel_init=nn.initializers.truncated_normal(0.01))(x)
        return x.astype(jnp.float32)


def _factory(name, **defaults):
    @MODELS.register(name)
    def build(num_classes: int = 1000, **kw):
        merged = {**defaults, "num_classes": num_classes, **kw}
        return VisionTransformer(**merged)
    build.__name__ = name
    return build


# Factories mirror vit_model.py:290-358 (+ the timm-standard small
# config the reference file derives from, used by the offline
# convergence runs).
vit_small_patch16_224 = _factory("vit_small_patch16_224",
                                 patch_size=16, embed_dim=384, depth=12,
                                 num_heads=6)
# small-image config (56px offline sets: 14x14 tokens); also the
# transformer control for the swin convergence diagnosis (r5)
vit_micro_patch4_56 = _factory("vit_micro_patch4_56",
                               patch_size=4, embed_dim=128, depth=6,
                               num_heads=4, drop_path_rate=0.0)
vit_base_patch16_224 = _factory("vit_base_patch16_224",
                                patch_size=16, embed_dim=768, depth=12,
                                num_heads=12)
vit_base_patch32_224 = _factory("vit_base_patch32_224",
                                patch_size=32, embed_dim=768, depth=12,
                                num_heads=12)
vit_large_patch16_224 = _factory("vit_large_patch16_224",
                                 patch_size=16, embed_dim=1024, depth=24,
                                 num_heads=16)
vit_large_patch32_224 = _factory("vit_large_patch32_224",
                                 patch_size=32, embed_dim=1024, depth=24,
                                 num_heads=16)
vit_huge_patch14_224 = _factory("vit_huge_patch14_224",
                                patch_size=14, embed_dim=1280, depth=32,
                                num_heads=16)
