"""ResNet family: ResNet / ResNeXt / SE-ResNet / SK-Net / ResNeSt.

One bottleneck skeleton with pluggable channel-attention, covering five
reference projects (SURVEY.md §2.1): classification/resnet
(models/networks.py resnet18/34/50/101), resnext (grouped conv, B-harness),
seNet (squeeze-excitation), skNet (selective kernel), resnest
(split-attention). The reference repeats ~850-2500 LoC per variant; here
each variant is a constructor flag because the only real difference is the
block's inner transform.

TPU-first: NHWC, bf16 compute, BatchNorm via flax (under GSPMD a batch
mean over the sharded batch axis IS cross-replica SyncBN — the
torch.SyncBatchNorm conversion in others/train_with_DDP/train.py:192
becomes a no-op property of the compiler).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from ...ops.padding import torch_pad
from ...core.registry import MODELS

ModuleDef = Any


class SEModule(nn.Module):
    """Squeeze-and-excitation (seNet surface)."""
    reduction: int = 16
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        c = x.shape[-1]
        s = jnp.mean(x.astype(jnp.float32), axis=(1, 2))
        s = nn.Dense(max(c // self.reduction, 8), dtype=self.dtype,
                     name="fc1")(s.astype(self.dtype))
        s = nn.relu(s)
        s = nn.Dense(c, dtype=self.dtype, name="fc2")(s)
        s = nn.sigmoid(s)
        return x * s[:, None, None, :].astype(x.dtype)


class SKConv(nn.Module):
    """Selective kernel: two branches (3x3, dilated 3x3), softmax-fused
    (skNet surface)."""
    features: int
    stride: int = 1
    reduction: int = 16
    norm: ModuleDef = None
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        branches = []
        for i, dil in enumerate((1, 2)):
            b = nn.Conv(self.features, (3, 3), strides=(self.stride,) * 2,
                        kernel_dilation=(dil, dil),
                        padding=torch_pad(3, dil),
                        use_bias=False, dtype=self.dtype,
                        name=f"branch{i}")(x)
            b = self.norm(name=f"bn{i}")(b)
            branches.append(nn.relu(b))
        u = sum(branches)
        s = jnp.mean(u.astype(jnp.float32), axis=(1, 2))
        z = nn.Dense(max(self.features // self.reduction, 32),
                     dtype=self.dtype, name="fc")(s.astype(self.dtype))
        z = nn.relu(z)
        logits = nn.Dense(2 * self.features, dtype=self.dtype,
                          name="select")(z)
        logits = logits.reshape(-1, 2, self.features)
        weights = jax.nn.softmax(logits.astype(jnp.float32), axis=1)
        weights = weights.astype(x.dtype)
        return (branches[0] * weights[:, None, None, 0, :]
                + branches[1] * weights[:, None, None, 1, :])


class SplitAttention(nn.Module):
    """ResNeSt split-attention conv (radix-2) (resnest surface)."""
    features: int
    stride: int = 1
    radix: int = 2
    reduction: int = 4
    norm: ModuleDef = None
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        r = self.radix
        u = nn.Conv(self.features * r, (3, 3), strides=(self.stride,) * 2,
                    padding=torch_pad(3), feature_group_count=r,
                    use_bias=False, dtype=self.dtype, name="conv")(x)
        u = self.norm(name="bn")(u)
        u = nn.relu(u)
        b = u.shape[0]
        splits = u.reshape(*u.shape[:-1], r, self.features)
        gap = jnp.sum(splits, axis=-2)
        gap = jnp.mean(gap.astype(jnp.float32), axis=(1, 2))
        z = nn.Dense(max(self.features // self.reduction, 32),
                     dtype=self.dtype, name="fc1")(gap.astype(self.dtype))
        z = nn.relu(z)
        att = nn.Dense(self.features * r, dtype=self.dtype, name="fc2")(z)
        att = jax.nn.softmax(
            att.reshape(b, r, self.features).astype(jnp.float32), axis=1)
        att = att.astype(x.dtype)
        return jnp.sum(splits * att[:, None, None, :, :], axis=-2)


class BasicBlock(nn.Module):
    features: int
    stride: int = 1
    norm: ModuleDef = None
    attention: Optional[str] = None
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        residual = x
        # explicit symmetric padding: identical to SAME at stride 1 but
        # matches torch's pad=1 semantics at stride 2 (SAME pads (0,1)
        # there, sampling shifted centers — breaks weight-port parity)
        y = nn.Conv(self.features, (3, 3), strides=(self.stride,) * 2,
                    padding=torch_pad(3), use_bias=False,
                    dtype=self.dtype, name="conv1")(x)
        y = self.norm(name="bn1")(y)
        y = nn.relu(y)
        y = nn.Conv(self.features, (3, 3), padding="SAME", use_bias=False,
                    dtype=self.dtype, name="conv2")(y)
        y = self.norm(scale_init=nn.initializers.zeros, name="bn2")(y)
        if self.attention == "se":
            y = SEModule(dtype=self.dtype, name="se")(y)
        if residual.shape != y.shape:
            residual = nn.Conv(self.features, (1, 1),
                               strides=(self.stride,) * 2, use_bias=False,
                               dtype=self.dtype, name="downsample_conv")(x)
            residual = self.norm(name="downsample_bn")(residual)
        return nn.relu(residual + y)


class Bottleneck(nn.Module):
    features: int           # output = features * 4
    stride: int = 1
    groups: int = 1         # >1 => ResNeXt
    width_per_group: int = 64
    norm: ModuleDef = None
    attention: Optional[str] = None   # None | 'se' | 'sk' | 'splat'
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        width = int(self.features * (self.width_per_group / 64.0)) \
            * self.groups
        residual = x
        y = nn.Conv(width, (1, 1), use_bias=False, dtype=self.dtype,
                    name="conv1")(x)
        y = self.norm(name="bn1")(y)
        y = nn.relu(y)
        if self.attention == "sk":
            y = SKConv(width, self.stride, norm=self.norm,
                       dtype=self.dtype, name="sk")(y)
        elif self.attention == "splat":
            y = SplitAttention(width, self.stride, norm=self.norm,
                               dtype=self.dtype, name="splat")(y)
        else:
            y = nn.Conv(width, (3, 3), strides=(self.stride,) * 2,
                        padding=torch_pad(3),
                        feature_group_count=self.groups,
                        use_bias=False, dtype=self.dtype, name="conv2")(y)
            y = self.norm(name="bn2")(y)
            y = nn.relu(y)
        y = nn.Conv(self.features * 4, (1, 1), use_bias=False,
                    dtype=self.dtype, name="conv3")(y)
        y = self.norm(scale_init=nn.initializers.zeros, name="bn3")(y)
        if self.attention == "se":
            y = SEModule(dtype=self.dtype, name="se")(y)
        if residual.shape != y.shape:
            residual = nn.Conv(self.features * 4, (1, 1),
                               strides=(self.stride,) * 2, use_bias=False,
                               dtype=self.dtype, name="downsample_conv")(x)
            residual = self.norm(name="downsample_bn")(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    block: str = "bottleneck"       # 'basic' | 'bottleneck'
    num_classes: int = 1000
    groups: int = 1
    width_per_group: int = 64
    attention: Optional[str] = None
    dtype: Any = jnp.bfloat16
    return_features: bool = False   # backbone mode for detection/seg FPNs
    frozen_bn: bool = False         # FrozenBatchNorm2d semantics
                                    # (fasterRcnn/models/backbone/
                                    # resnet50_fpn.py:5): statistics stay
                                    # fixed even in train mode, so
                                    # small-batch detection fine-tuning
                                    # matches the reference. Freeze the
                                    # scale/bias grads via the optimizer
                                    # freeze mask (train/optim.py).

    @nn.compact
    def __call__(self, x, train: bool = False):
        norm = partial(nn.BatchNorm,
                       use_running_average=(not train) or self.frozen_bn,
                       momentum=0.9, epsilon=1e-5, dtype=self.dtype)
        x = x.astype(self.dtype)
        x = nn.Conv(64, (7, 7), strides=(2, 2), padding=torch_pad(7),
                    use_bias=False, dtype=self.dtype, name="conv1")(x)
        x = norm(name="bn1")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))

        feats = {}
        block_cls = BasicBlock if self.block == "basic" else Bottleneck
        for stage, size in enumerate(self.stage_sizes):
            for i in range(size):
                stride = 2 if stage > 0 and i == 0 else 1
                kwargs = dict(features=64 * 2 ** stage, stride=stride,
                              norm=norm, attention=self.attention,
                              dtype=self.dtype,
                              name=f"layer{stage + 1}_block{i}")
                if block_cls is Bottleneck:
                    kwargs.update(groups=self.groups,
                                  width_per_group=self.width_per_group)
                x = block_cls(**kwargs)(x)
            feats[f"c{stage + 2}"] = x
        if self.return_features:
            return feats
        x = jnp.mean(x.astype(jnp.float32), axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="fc")(x)
        return x.astype(jnp.float32)


def _factory(name, **defaults):
    @MODELS.register(name)
    def build(num_classes: int = 1000, **kw):
        return ResNet(**{**defaults, "num_classes": num_classes, **kw})
    build.__name__ = name
    return build


resnet18 = _factory("resnet18", stage_sizes=(2, 2, 2, 2), block="basic")
resnet34 = _factory("resnet34", stage_sizes=(3, 4, 6, 3), block="basic")
resnet50 = _factory("resnet50", stage_sizes=(3, 4, 6, 3))
resnet101 = _factory("resnet101", stage_sizes=(3, 4, 23, 3))
resnext50_32x4d = _factory("resnext50_32x4d", stage_sizes=(3, 4, 6, 3),
                           groups=32, width_per_group=4)
resnext101_32x8d = _factory("resnext101_32x8d", stage_sizes=(3, 4, 23, 3),
                            groups=32, width_per_group=8)
se_resnet50 = _factory("se_resnet50", stage_sizes=(3, 4, 6, 3),
                       attention="se")
se_resnet18 = _factory("se_resnet18", stage_sizes=(2, 2, 2, 2),
                       block="basic", attention="se")
sknet50 = _factory("sknet50", stage_sizes=(3, 4, 6, 3), attention="sk")
resnest50 = _factory("resnest50", stage_sizes=(3, 4, 6, 3),
                     attention="splat")
