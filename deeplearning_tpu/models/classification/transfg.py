"""TransFG: ViT for fine-grained recognition with part selection.

Surface of classification/TransFG (models/transfg.py: ViT trunk whose
last block consumes only the tokens with highest accumulated attention to
the CLS token — part selection via attention rollout — plus a contrastive
loss on the CLS embedding, losses/contrastive_loss.py). Built on the
shared ViT blocks; attention maps are recomputed cheaply for rollout
(static shapes, no hooks needed).
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from ...core.registry import MODELS
from .vit import Block, Mlp, PatchEmbed


class AttnWithMap(nn.Module):
    num_heads: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        b, n, c = x.shape
        d = c // self.num_heads
        qkv = nn.Dense(3 * c, dtype=self.dtype, name="qkv")(x)
        qkv = qkv.reshape(b, n, 3, self.num_heads, d)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        s = jnp.einsum("bqhd,bkhd->bhqk", q * d ** -0.5, k)
        attn = jax.nn.softmax(s.astype(jnp.float32), -1)
        out = jnp.einsum("bhqk,bkhd->bqhd", attn.astype(v.dtype), v)
        out = nn.Dense(c, dtype=self.dtype, name="proj")(
            out.reshape(b, n, c))
        return out, attn


class TransFGBlock(nn.Module):
    num_heads: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        y, attn = AttnWithMap(self.num_heads, self.dtype, name="attn")(
            nn.LayerNorm(dtype=self.dtype, name="norm1")(x), deterministic)
        x = x + y
        y = Mlp(4.0, 0.0, self.dtype, name="mlp")(
            nn.LayerNorm(dtype=self.dtype, name="norm2")(x), deterministic)
        return x + y, attn


class TransFG(nn.Module):
    num_classes: int = 200
    patch_size: int = 16
    embed_dim: int = 384
    depth: int = 8
    num_heads: int = 6
    num_parts: int = 12
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        deterministic = not train
        x = PatchEmbed(self.patch_size, self.embed_dim, self.dtype,
                       name="patch_embed")(x.astype(self.dtype))
        b, n, c = x.shape
        cls = self.param("cls_token", nn.initializers.zeros, (1, 1, c),
                         jnp.float32)
        x = jnp.concatenate(
            [jnp.broadcast_to(cls.astype(x.dtype), (b, 1, c)), x], 1)
        pos = self.param("pos_embed", nn.initializers.truncated_normal(0.02),
                         (1, n + 1, c), jnp.float32)
        x = x + pos.astype(x.dtype)

        rollout = None          # accumulated CLS->patch attention
        for i in range(self.depth - 1):
            x, attn = TransFGBlock(self.num_heads, self.dtype,
                                   name=f"block{i}")(x, deterministic)
            cls_attn = jnp.mean(attn[:, :, 0, 1:], axis=1)   # (B, N)
            rollout = cls_attn if rollout is None else rollout * cls_attn

        # part selection: keep top-k informative patch tokens + CLS
        k = min(self.num_parts, n)
        _, top_idx = jax.lax.top_k(rollout, k)               # (B, k)
        parts = jnp.take_along_axis(x[:, 1:], top_idx[:, :, None], axis=1)
        x = jnp.concatenate([x[:, :1], parts], axis=1)
        x, _ = TransFGBlock(self.num_heads, self.dtype,
                            name=f"block{self.depth - 1}")(x, deterministic)
        x = nn.LayerNorm(dtype=self.dtype, name="norm")(x)
        embedding = x[:, 0].astype(jnp.float32)
        logits = nn.Dense(self.num_classes, dtype=self.dtype,
                          name="head")(x[:, 0]).astype(jnp.float32)
        return {"logits": logits, "embedding": embedding}


def contrastive_loss(embeddings: jax.Array, labels: jax.Array,
                     margin: float = 0.4) -> jax.Array:
    """TransFG contrastive loss (losses/contrastive_loss.py): pull same-
    class CLS embeddings together, push different-class pairs past a
    cosine margin."""
    from ...ops.losses import safe_normalize
    z = safe_normalize(embeddings, axis=-1)   # NaN-safe at zero rows
    sim = z @ z.T
    same = (labels[:, None] == labels[None, :]).astype(jnp.float32)
    eye = jnp.eye(len(labels))
    pos_loss = jnp.sum((1 - sim) * same * (1 - eye))
    neg_loss = jnp.sum(jnp.maximum(sim - margin, 0.0) * (1 - same))
    denom = len(labels) * (len(labels) - 1)
    return (pos_loss + neg_loss) / max(denom, 1)


@MODELS.register("transfg_small")
def transfg_small(num_classes: int = 200, **kw):
    return TransFG(num_classes=num_classes, **kw)
