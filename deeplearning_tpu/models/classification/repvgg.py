"""RepVGG: train-time 3x3+1x1+identity branches → deploy-time single 3x3.

Surface of classification/RepVGG (models/ get_RepVGG_func_by_name,
repvgg_model_convert; convert.py:17 CLI). The structural
re-parameterization is a pure pytree→pytree transform here
(``reparameterize``): fold each branch's BN into its conv, pad the 1x1 to
3x3, add the identity as a centered-impulse kernel, and emit params for
the ``deploy=True`` model — no module surgery, no state_dict games.
"""

from __future__ import annotations

from typing import Any, Dict, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from ...core.registry import MODELS


class RepVGGBlock(nn.Module):
    out_ch: int
    stride: int = 1
    groups: int = 1
    deploy: bool = False
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        if self.deploy:
            # explicit (1,1) padding: keeps the 3x3 window centered on the
            # same taps as the 1x1 branch under stride 2 (SAME would pad
            # asymmetrically and break reparam equivalence)
            y = nn.Conv(self.out_ch, (3, 3), strides=(self.stride,) * 2,
                        padding=((1, 1), (1, 1)),
                        feature_group_count=self.groups,
                        use_bias=True, dtype=self.dtype, name="reparam")(x)
            return nn.relu(y)
        norm = lambda name: nn.BatchNorm(use_running_average=not train,
                                         momentum=0.9, epsilon=1e-5,
                                         dtype=self.dtype, name=name)
        y3 = nn.Conv(self.out_ch, (3, 3), strides=(self.stride,) * 2,
                     padding=((1, 1), (1, 1)),
                     feature_group_count=self.groups,
                     use_bias=False, dtype=self.dtype, name="dense3")(x)
        y3 = norm("bn3")(y3)
        y1 = nn.Conv(self.out_ch, (1, 1), strides=(self.stride,) * 2,
                     padding="VALID", feature_group_count=self.groups,
                     use_bias=False, dtype=self.dtype, name="dense1")(x)
        y1 = norm("bn1")(y1)
        y = y3 + y1
        if self.stride == 1 and x.shape[-1] == self.out_ch:
            y = y + norm("bnid")(x)
        return nn.relu(y)


class RepVGG(nn.Module):
    num_blocks: Sequence[int] = (2, 4, 14, 1)
    width_mult: Sequence[float] = (0.75, 0.75, 0.75, 2.5)
    num_classes: int = 1000
    deploy: bool = False
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        base = (64, 128, 256, 512)
        in_planes = min(64, int(64 * self.width_mult[0]))
        x = RepVGGBlock(in_planes, 2, deploy=self.deploy, dtype=self.dtype,
                        name="stage0")(x, train)
        for si, (n, w) in enumerate(zip(self.num_blocks, self.width_mult)):
            ch = int(base[si] * w)
            for i in range(n):
                x = RepVGGBlock(ch, 2 if i == 0 else 1,
                                deploy=self.deploy, dtype=self.dtype,
                                name=f"stage{si + 1}_block{i}")(x, train)
        x = jnp.mean(x.astype(jnp.float32), axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="fc")(x)
        return x.astype(jnp.float32)


def _fuse_bn(kernel: np.ndarray, bn: Dict[str, np.ndarray],
             stats: Dict[str, np.ndarray], eps: float = 1e-5):
    """Fold BN(scale,bias,mean,var) into conv kernel (HWIO) + bias."""
    gamma, beta = np.asarray(bn["scale"]), np.asarray(bn["bias"])
    mean, var = np.asarray(stats["mean"]), np.asarray(stats["var"])
    std = np.sqrt(var + eps)
    return kernel * (gamma / std), beta - mean * gamma / std


def reparameterize(params: Dict, batch_stats: Dict) -> Dict:
    """Train-time params → deploy-time params (single fused 3x3/block)."""
    out: Dict[str, Any] = {}
    for name, block in params.items():
        if not (isinstance(block, dict) and "dense3" in block):
            out[name] = jax.tree.map(np.asarray, block)
            continue
        stats = batch_stats[name]
        k3, b3 = _fuse_bn(np.asarray(block["dense3"]["kernel"]),
                          block["bn3"], stats["bn3"])
        k1, b1 = _fuse_bn(np.asarray(block["dense1"]["kernel"]),
                          block["bn1"], stats["bn1"])
        k1 = np.pad(k1, ((1, 1), (1, 1), (0, 0), (0, 0)))
        kernel, bias = k3 + k1, b3 + b1
        if "bnid" in block:
            in_ch = kernel.shape[2]
            out_ch = kernel.shape[3]
            kid = np.zeros((3, 3, in_ch, out_ch), kernel.dtype)
            for o in range(out_ch):
                kid[1, 1, o % in_ch, o] = 1.0
            kid, bid = _fuse_bn(kid, block["bnid"], stats["bnid"])
            kernel, bias = kernel + kid, bias + bid
        out[name] = {"reparam": {"kernel": kernel, "bias": bias}}
    return out


_WIDTHS = {
    "repvgg_a0": ((2, 4, 14, 1), (0.75, 0.75, 0.75, 2.5)),
    "repvgg_a1": ((2, 4, 14, 1), (1.0, 1.0, 1.0, 2.5)),
    "repvgg_a2": ((2, 4, 14, 1), (1.5, 1.5, 1.5, 2.75)),
    "repvgg_b0": ((4, 6, 16, 1), (1.0, 1.0, 1.0, 2.5)),
    "repvgg_b1": ((4, 6, 16, 1), (2.0, 2.0, 2.0, 4.0)),
}

for _name, (_blocks, _widths) in _WIDTHS.items():
    def _mk(blocks, widths):
        def build(num_classes: int = 1000, **kw):
            return RepVGG(num_blocks=blocks, width_mult=widths,
                          num_classes=num_classes, **kw)
        return build
    MODELS.register(_name)(_mk(_blocks, _widths))
