"""Mobile/efficient CNNs: ShuffleNetV2, MobileNetV2, EfficientNet.

Surface of classification/ShuffleNet (v2 channel shuffle),
classification/efficientNet (B0..B7 MBConv scaling), and MobileNetV2
(the fasterRcnn alternative backbone, detection/fasterRcnn/
models/backbone/mobilenetv2_model.py).
"""

from __future__ import annotations

import math
from functools import partial
from typing import Any, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

from ...ops.padding import torch_pad
from ...core.registry import MODELS
from .resnet import SEModule


def channel_shuffle(x, groups: int = 2):
    b, h, w, c = x.shape
    x = x.reshape(b, h, w, groups, c // groups)
    x = x.transpose(0, 1, 2, 4, 3)
    return x.reshape(b, h, w, c)


class ShuffleV2Block(nn.Module):
    out_ch: int
    stride: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, dtype=self.dtype)
        branch = self.out_ch // 2
        if self.stride == 1:
            x1, x2 = jnp.split(x, 2, axis=-1)
        else:
            # spatial-down branch processes the whole input
            x1 = nn.Conv(x.shape[-1], (3, 3), strides=(2, 2),
                         padding=[(1, 1), (1, 1)],
                         feature_group_count=x.shape[-1], use_bias=False,
                         dtype=self.dtype, name="proj_dw")(x)
            x1 = norm(name="proj_dw_bn")(x1)
            x1 = nn.Conv(branch, (1, 1), use_bias=False, dtype=self.dtype,
                         name="proj_pw")(x1)
            x1 = nn.relu(norm(name="proj_pw_bn")(x1))
            x2 = x
        y = nn.Conv(branch, (1, 1), use_bias=False, dtype=self.dtype,
                    name="pw1")(x2)
        y = nn.relu(norm(name="pw1_bn")(y))
        y = nn.Conv(branch, (3, 3), strides=(self.stride,) * 2,
                    padding=[(1, 1), (1, 1)], feature_group_count=branch,
                    use_bias=False, dtype=self.dtype, name="dw")(y)
        y = norm(name="dw_bn")(y)
        y = nn.Conv(branch, (1, 1), use_bias=False, dtype=self.dtype,
                    name="pw2")(y)
        y = nn.relu(norm(name="pw2_bn")(y))
        return channel_shuffle(jnp.concatenate([x1, y], axis=-1))


class ShuffleNetV2(nn.Module):
    stage_repeats: Sequence[int] = (4, 8, 4)
    stage_channels: Sequence[int] = (116, 232, 464)
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        x = nn.Conv(24, (3, 3), strides=(2, 2), padding=[(1, 1), (1, 1)],
                    use_bias=False, dtype=self.dtype, name="stem")(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         dtype=self.dtype, name="stem_bn")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        for si, (reps, ch) in enumerate(zip(self.stage_repeats,
                                            self.stage_channels)):
            for i in range(reps):
                x = ShuffleV2Block(ch, 2 if i == 0 else 1, self.dtype,
                                   name=f"stage{si}_block{i}")(x, train)
        x = nn.Conv(1024, (1, 1), use_bias=False, dtype=self.dtype,
                    name="head_conv")(x)
        x = nn.relu(x)
        x = jnp.mean(x.astype(jnp.float32), axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="fc")(x)
        return x.astype(jnp.float32)


class InvertedResidual(nn.Module):
    """MBConv: expand -> depthwise -> (SE) -> project."""
    out_ch: int
    stride: int
    expand: int = 6
    kernel: int = 3
    use_se: bool = False
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, dtype=self.dtype)
        in_ch = x.shape[-1]
        hidden = in_ch * self.expand
        y = x
        if self.expand != 1:
            y = nn.Conv(hidden, (1, 1), use_bias=False, dtype=self.dtype,
                        name="expand")(y)
            y = nn.silu(norm(name="expand_bn")(y)) if self.use_se else \
                nn.relu6(norm(name="expand_bn")(y))
        y = nn.Conv(hidden, (self.kernel,) * 2, strides=(self.stride,) * 2,
                    padding=torch_pad(self.kernel),
                    feature_group_count=hidden,
                    use_bias=False, dtype=self.dtype, name="dw")(y)
        y = nn.silu(norm(name="dw_bn")(y)) if self.use_se else \
            nn.relu6(norm(name="dw_bn")(y))
        if self.use_se:
            y = SEModule(reduction=4 * self.expand, dtype=self.dtype,
                         name="se")(y)
        y = nn.Conv(self.out_ch, (1, 1), use_bias=False, dtype=self.dtype,
                    name="project")(y)
        y = norm(name="project_bn")(y)
        if self.stride == 1 and in_ch == self.out_ch:
            y = x + y
        return y


class MobileNetV2(nn.Module):
    num_classes: int = 1000
    width_mult: float = 1.0
    dtype: Any = jnp.bfloat16
    return_features: bool = False

    # (expand, out_ch, repeats, stride)
    cfg: Sequence[Tuple[int, int, int, int]] = (
        (1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
        (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1))

    @nn.compact
    def __call__(self, x, train: bool = False):
        def c(ch):
            return max(8, int(ch * self.width_mult + 4) // 8 * 8)
        x = x.astype(self.dtype)
        x = nn.Conv(c(32), (3, 3), strides=(2, 2),
                    padding=[(1, 1), (1, 1)],
                    use_bias=False, dtype=self.dtype, name="stem")(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         dtype=self.dtype, name="stem_bn")(x)
        x = nn.relu6(x)
        feats = {}
        cur_stride = 2                      # after the stem conv
        for bi, (t, ch, reps, s) in enumerate(self.cfg):
            cur_stride *= s
            for i in range(reps):
                x = InvertedResidual(c(ch), s if i == 0 else 1, t,
                                     dtype=self.dtype,
                                     name=f"block{bi}_{i}")(x, train)
            # tap the LAST block at each stride level (cN <=> stride 2^N,
            # matching the ResNet backbone convention FPN consumers assume)
            next_s = self.cfg[bi + 1][3] if bi + 1 < len(self.cfg) else 2
            if next_s == 2 and cur_stride >= 4:
                feats[f"c{cur_stride.bit_length() - 1}"] = x
        x = nn.Conv(c(1280), (1, 1), use_bias=False, dtype=self.dtype,
                    name="head_conv")(x)
        x = nn.relu6(x)
        if self.return_features:
            feats["top"] = x
            return feats
        x = jnp.mean(x.astype(jnp.float32), axis=(1, 2))
        x = nn.Dropout(0.2, deterministic=not train)(x)
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="fc")(x)
        return x.astype(jnp.float32)


class EfficientNet(nn.Module):
    """EfficientNet-B0 base scaled by (width, depth) coefficients
    (efficientNet trans of B0..B7 scaling table)."""
    num_classes: int = 1000
    width_coef: float = 1.0
    depth_coef: float = 1.0
    dropout: float = 0.2
    dtype: Any = jnp.bfloat16

    # (expand, channels, repeats, stride, kernel)
    cfg: Sequence[Tuple[int, int, int, int, int]] = (
        (1, 16, 1, 1, 3), (6, 24, 2, 2, 3), (6, 40, 2, 2, 5),
        (6, 80, 3, 2, 3), (6, 112, 3, 1, 5), (6, 192, 4, 2, 5),
        (6, 320, 1, 1, 3))

    @nn.compact
    def __call__(self, x, train: bool = False):
        def c(ch):
            ch = ch * self.width_coef
            return max(8, int(ch + 4) // 8 * 8)

        def d(reps):
            return int(math.ceil(reps * self.depth_coef))
        x = x.astype(self.dtype)
        x = nn.Conv(c(32), (3, 3), strides=(2, 2),
                    padding=[(1, 1), (1, 1)],
                    use_bias=False, dtype=self.dtype, name="stem")(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         dtype=self.dtype, name="stem_bn")(x)
        x = nn.silu(x)
        for bi, (t, ch, reps, s, k) in enumerate(self.cfg):
            for i in range(d(reps)):
                x = InvertedResidual(c(ch), s if i == 0 else 1, t, k,
                                     use_se=True, dtype=self.dtype,
                                     name=f"block{bi}_{i}")(x, train)
        x = nn.Conv(c(1280), (1, 1), use_bias=False, dtype=self.dtype,
                    name="head_conv")(x)
        x = nn.silu(x)
        x = jnp.mean(x.astype(jnp.float32), axis=(1, 2))
        x = nn.Dropout(self.dropout, deterministic=not train)(x)
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="fc")(x)
        return x.astype(jnp.float32)


@MODELS.register("shufflenet_v2_x1_0")
def shufflenet_v2_x1_0(num_classes: int = 1000, **kw):
    return ShuffleNetV2(num_classes=num_classes, **kw)


@MODELS.register("mobilenet_v2")
def mobilenet_v2(num_classes: int = 1000, **kw):
    return MobileNetV2(num_classes=num_classes, **kw)


_EFFNET_SCALING = {          # width, depth, dropout (B0..B7 table)
    "b0": (1.0, 1.0, 0.2), "b1": (1.0, 1.1, 0.2), "b2": (1.1, 1.2, 0.3),
    "b3": (1.2, 1.4, 0.3), "b4": (1.4, 1.8, 0.4), "b5": (1.6, 2.2, 0.4),
    "b6": (1.8, 2.6, 0.5), "b7": (2.0, 3.1, 0.5),
}

for _suffix, (_w, _d, _p) in _EFFNET_SCALING.items():
    def _mk(w, dd, p):
        def build(num_classes: int = 1000, **kw):
            return EfficientNet(num_classes=num_classes, width_coef=w,
                                depth_coef=dd, dropout=p, **kw)
        return build
    MODELS.register(f"efficientnet_{_suffix}")(_mk(_w, _d, _p))
