"""ConvNeXt + CoAtNet — modern conv / conv-attention hybrids.

Surface of classification/convNext (ConvNeXt-T/S/B blocks: 7x7 depthwise,
LN, pointwise MLP, layer scale, stochastic depth) and classification/
coatNet (MBConv stages then relative-attention transformer stages).
"""

from __future__ import annotations

from typing import Any, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from ...core import numerics
from ...ops.padding import torch_pad
from ...core.registry import MODELS
from .mobile import InvertedResidual
from .vit import Attention, DropPath


class ConvNeXtBlock(nn.Module):
    dim: int
    drop_path_rate: float = 0.0
    layer_scale_init: float = 1e-6
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        y = nn.Conv(self.dim, (7, 7), padding="SAME",
                    feature_group_count=self.dim, dtype=self.dtype,
                    name="dwconv")(x)
        y = nn.LayerNorm(dtype=self.dtype, name="norm")(y)
        y = nn.Dense(4 * self.dim, dtype=self.dtype, name="pw1")(y)
        y = numerics.gelu(y)
        y = nn.Dense(self.dim, dtype=self.dtype, name="pw2")(y)
        gamma = self.param("gamma",
                           nn.initializers.constant(self.layer_scale_init),
                           (self.dim,), jnp.float32)
        y = y * gamma.astype(y.dtype)
        return x + DropPath(self.drop_path_rate)(y, deterministic)


class ConvNeXt(nn.Module):
    depths: Sequence[int] = (3, 3, 9, 3)
    dims: Sequence[int] = (96, 192, 384, 768)
    num_classes: int = 1000
    drop_path_rate: float = 0.0
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        deterministic = not train
        x = x.astype(self.dtype)
        dpr = np.linspace(0, self.drop_path_rate, sum(self.depths))
        bi = 0
        for si, (depth, dim) in enumerate(zip(self.depths, self.dims)):
            if si == 0:
                x = nn.Conv(dim, (4, 4), strides=(4, 4), dtype=self.dtype,
                            name="stem")(x)
                x = nn.LayerNorm(dtype=self.dtype, name="stem_norm")(x)
            else:
                x = nn.LayerNorm(dtype=self.dtype, name=f"down{si}_norm")(x)
                x = nn.Conv(dim, (2, 2), strides=(2, 2), dtype=self.dtype,
                            name=f"down{si}")(x)
            for i in range(depth):
                x = ConvNeXtBlock(dim, float(dpr[bi]), dtype=self.dtype,
                                  name=f"stage{si}_block{i}")(x, deterministic)
                bi += 1
        x = jnp.mean(x.astype(jnp.float32), axis=(1, 2))
        x = nn.LayerNorm(name="head_norm")(x)
        x = nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)
        return x


class CoAtNet(nn.Module):
    """C-C-T-T layout: conv stem, two MBConv stages, two transformer
    stages (coatNet surface)."""
    num_classes: int = 1000
    dims: Sequence[int] = (64, 96, 192, 384, 768)
    depths: Sequence[int] = (2, 2, 3, 5, 2)
    num_heads: int = 8
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        deterministic = not train
        x = x.astype(self.dtype)
        # s0 conv stem
        for i in range(self.depths[0]):
            x = nn.Conv(self.dims[0], (3, 3),
                        strides=(2, 2) if i == 0 else (1, 1),
                        padding=torch_pad(3), dtype=self.dtype,
                        name=f"stem{i}")(x)
            x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                             dtype=self.dtype, name=f"stem{i}_bn")(x)
            x = numerics.gelu(x)
        # s1, s2: MBConv
        for si in (1, 2):
            for i in range(self.depths[si]):
                x = InvertedResidual(self.dims[si], 2 if i == 0 else 1,
                                     expand=4, use_se=True,
                                     dtype=self.dtype,
                                     name=f"s{si}_mb{i}")(x, train)
        # s3, s4: transformer with downsampling by pooling
        for si in (3, 4):
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
            b, h, w, c = x.shape
            x = x.reshape(b, h * w, c)
            x = nn.Dense(self.dims[si], dtype=self.dtype,
                         name=f"s{si}_proj")(x)
            for i in range(self.depths[si]):
                y = nn.LayerNorm(dtype=self.dtype,
                                 name=f"s{si}_b{i}_norm1")(x)
                y = Attention(self.num_heads, dtype=self.dtype,
                              name=f"s{si}_b{i}_attn")(y, deterministic)
                x = x + y
                y = nn.LayerNorm(dtype=self.dtype,
                                 name=f"s{si}_b{i}_norm2")(x)
                y = nn.Dense(4 * self.dims[si], dtype=self.dtype,
                             name=f"s{si}_b{i}_mlp1")(y)
                y = numerics.gelu(y)
                y = nn.Dense(self.dims[si], dtype=self.dtype,
                             name=f"s{si}_b{i}_mlp2")(y)
                x = x + y
            x = x.reshape(b, h, w, self.dims[si])
        x = jnp.mean(x.astype(jnp.float32), axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="head")(x)
        return x.astype(jnp.float32)


@MODELS.register("convnext_tiny")
def convnext_tiny(num_classes: int = 1000, **kw):
    return ConvNeXt(num_classes=num_classes, **kw)


@MODELS.register("convnext_small")
def convnext_small(num_classes: int = 1000, **kw):
    return ConvNeXt(depths=(3, 3, 27, 3), num_classes=num_classes, **kw)


@MODELS.register("convnext_base")
def convnext_base(num_classes: int = 1000, **kw):
    return ConvNeXt(depths=(3, 3, 27, 3), dims=(128, 256, 512, 1024),
                    num_classes=num_classes, **kw)


@MODELS.register("coatnet_0")
def coatnet_0(num_classes: int = 1000, **kw):
    return CoAtNet(num_classes=num_classes, **kw)
