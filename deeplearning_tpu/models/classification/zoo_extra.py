"""Happy-Whale modelZoo backbones: DPN, InceptionV4, Xception, NASNet-A,
PolyNet, SENet-154.

Capability surface of metric_learning/Happy-Whale/retrieval/models/
modelZoo/{dpn.py, inceptionV4.py, nasnet.py, ployNet.py, senet.py,
xception.py} — the alternative retrieval backbones of the Happy-Whale
pipeline. Rebuilt as idiomatic Flax (NHWC, bf16 compute, BatchNorm with
train flag); all are MXU-friendly: static shapes, convs ≥1x1, channel
counts multiples of 8.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

from ...core.registry import MODELS
from .resnet import SEModule


class ConvBN(nn.Module):
    """conv → BN [→ relu], the building unit every zoo backbone shares."""
    features: int
    kernel: Tuple[int, int] = (3, 3)
    strides: Tuple[int, int] = (1, 1)
    padding: str = "SAME"
    groups: int = 1
    relu: bool = True
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.Conv(self.features, self.kernel, strides=self.strides,
                    padding=self.padding, feature_group_count=self.groups,
                    use_bias=False, dtype=self.dtype, name="conv")(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         dtype=self.dtype, name="bn")(x)
        return nn.relu(x) if self.relu else x


class SepConvBN(nn.Module):
    """Depthwise 3x3/5x5/7x7 + pointwise, each BN'd (Xception/NASNet
    separable unit)."""
    features: int
    kernel: Tuple[int, int] = (3, 3)
    strides: Tuple[int, int] = (1, 1)
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        c = x.shape[-1]
        x = nn.Conv(c, self.kernel, strides=self.strides, padding="SAME",
                    feature_group_count=c, use_bias=False,
                    dtype=self.dtype, name="dw")(x)
        x = nn.Conv(self.features, (1, 1), use_bias=False,
                    dtype=self.dtype, name="pw")(x)
        return nn.BatchNorm(use_running_average=not train, momentum=0.9,
                            dtype=self.dtype, name="bn")(x)


def _pool(x, kind: str, window=(3, 3), strides=(1, 1)):
    if kind == "max":
        return nn.max_pool(x, window, strides=strides, padding="SAME")
    return nn.avg_pool(x, window, strides=strides, padding="SAME",
                       count_include_pad=False)


# ---------------------------------------------------------------- Xception

class XceptionBlock(nn.Module):
    """relu→sepconv ×reps with residual 1x1 projection (xception.py Block)."""
    features: int
    reps: int
    stride: int = 1
    grow_first: bool = True
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        res = x
        if self.stride != 1 or x.shape[-1] != self.features:
            res = ConvBN(self.features, (1, 1), (self.stride,) * 2,
                         relu=False, dtype=self.dtype, name="skip")(
                res, train)
        y = x
        feats = x.shape[-1]
        for i in range(self.reps):
            if self.grow_first or i > 0:
                feats = self.features
            y = nn.relu(y)
            y = SepConvBN(feats, dtype=self.dtype, name=f"sep{i}")(y, train)
        if self.stride != 1:
            y = nn.max_pool(y, (3, 3), strides=(self.stride,) * 2,
                            padding="SAME")
        return y + res


class Xception(nn.Module):
    """Entry/middle/exit flows (xception.py:1-194)."""
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = ConvBN(32, (3, 3), (2, 2), dtype=self.dtype, name="stem1")(
            x, train)
        x = ConvBN(64, (3, 3), dtype=self.dtype, name="stem2")(x, train)
        for i, (f, s) in enumerate([(128, 2), (256, 2), (728, 2)]):
            x = XceptionBlock(f, 2, s, dtype=self.dtype,
                              name=f"entry{i}")(x, train)
        for i in range(8):
            x = XceptionBlock(728, 3, 1, dtype=self.dtype,
                              name=f"mid{i}")(x, train)
        x = XceptionBlock(1024, 2, 2, grow_first=False, dtype=self.dtype,
                          name="exit0")(x, train)
        x = nn.relu(SepConvBN(1536, dtype=self.dtype, name="exit1")(
            x, train))
        x = nn.relu(SepConvBN(2048, dtype=self.dtype, name="exit2")(
            x, train))
        x = x.mean(axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="head")(x)
        return x.astype(jnp.float32)


# ------------------------------------------------------------- InceptionV4

class InceptionA(nn.Module):
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        cb = partial(ConvBN, dtype=self.dtype)
        b0 = cb(96, (1, 1), name="b0")(x, train)
        b1 = cb(96, (3, 3), name="b1b")(
            cb(64, (1, 1), name="b1a")(x, train), train)
        b2 = cb(96, (3, 3), name="b2c")(
            cb(96, (3, 3), name="b2b")(
                cb(64, (1, 1), name="b2a")(x, train), train), train)
        b3 = cb(96, (1, 1), name="b3")(_pool(x, "avg"), train)
        return jnp.concatenate([b0, b1, b2, b3], axis=-1)


class InceptionB(nn.Module):
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        cb = partial(ConvBN, dtype=self.dtype)
        b0 = cb(384, (1, 1), name="b0")(x, train)
        b1 = cb(256, (7, 1), name="b1c")(
            cb(224, (1, 7), name="b1b")(
                cb(192, (1, 1), name="b1a")(x, train), train), train)
        b2 = x
        for i, (f, k) in enumerate([(192, (1, 1)), (192, (7, 1)),
                                    (224, (1, 7)), (224, (7, 1)),
                                    (256, (1, 7))]):
            b2 = cb(f, k, name=f"b2{i}")(b2, train)
        b3 = cb(128, (1, 1), name="b3")(_pool(x, "avg"), train)
        return jnp.concatenate([b0, b1, b2, b3], axis=-1)


class InceptionC(nn.Module):
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        cb = partial(ConvBN, dtype=self.dtype)
        b0 = cb(256, (1, 1), name="b0")(x, train)
        b1 = cb(384, (1, 1), name="b1a")(x, train)
        b1 = jnp.concatenate([cb(256, (1, 3), name="b1b")(b1, train),
                              cb(256, (3, 1), name="b1c")(b1, train)],
                             axis=-1)
        b2 = cb(512, (1, 3), name="b2b")(
            cb(448, (3, 1), name="b2a")(
                cb(384, (1, 1), name="b2z")(x, train), train), train)
        b2 = jnp.concatenate([cb(256, (1, 3), name="b2c")(b2, train),
                              cb(256, (3, 1), name="b2d")(b2, train)],
                             axis=-1)
        b3 = cb(256, (1, 1), name="b3")(_pool(x, "avg"), train)
        return jnp.concatenate([b0, b1, b2, b3], axis=-1)


class InceptionV4(nn.Module):
    """Stem + 4A + RedA + 7B + RedB + 3C (inceptionV4.py:1-335)."""
    num_classes: int = 1000
    blocks: Tuple[int, int, int] = (4, 7, 3)
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        cb = partial(ConvBN, dtype=self.dtype)
        x = cb(32, (3, 3), (2, 2), name="s1")(x, train)
        x = cb(32, (3, 3), name="s2")(x, train)
        x = cb(64, (3, 3), name="s3")(x, train)
        x = jnp.concatenate([
            nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME"),
            cb(96, (3, 3), (2, 2), name="s4")(x, train)], axis=-1)
        a = cb(96, (3, 3), name="s5b")(
            cb(64, (1, 1), name="s5a")(x, train), train)
        b = x
        for i, (f, k) in enumerate([(64, (1, 1)), (64, (1, 7)),
                                    (64, (7, 1)), (96, (3, 3))]):
            b = cb(f, k, name=f"s6{i}")(b, train)
        x = jnp.concatenate([a, b], axis=-1)
        x = jnp.concatenate([
            cb(192, (3, 3), (2, 2), name="s7")(x, train),
            nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")],
            axis=-1)
        for i in range(self.blocks[0]):
            x = InceptionA(self.dtype, name=f"a{i}")(x, train)
        x = jnp.concatenate([                       # reduction A
            cb(384, (3, 3), (2, 2), name="ra0")(x, train),
            cb(256, (3, 3), (2, 2), name="ra1c")(
                cb(224, (3, 3), name="ra1b")(
                    cb(192, (1, 1), name="ra1a")(x, train), train), train),
            nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")],
            axis=-1)
        for i in range(self.blocks[1]):
            x = InceptionB(self.dtype, name=f"b{i}")(x, train)
        x = jnp.concatenate([                       # reduction B
            cb(192, (3, 3), (2, 2), name="rb0b")(
                cb(192, (1, 1), name="rb0a")(x, train), train),
            cb(320, (3, 3), (2, 2), name="rb1d")(
                cb(320, (7, 1), name="rb1c")(
                    cb(256, (1, 7), name="rb1b")(
                        cb(256, (1, 1), name="rb1a")(x, train), train),
                    train), train),
            nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")],
            axis=-1)
        for i in range(self.blocks[2]):
            x = InceptionC(self.dtype, name=f"c{i}")(x, train)
        x = x.mean(axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="head")(x)
        return x.astype(jnp.float32)


# -------------------------------------------------------------------- DPN

class DualPathBlock(nn.Module):
    """1x1 → grouped 3x3 → 1x1 with the output split across a residual
    path (first ``bw`` channels, added) and a dense path (last ``inc``
    channels, concatenated) — dpn.py DualPathBlock."""
    r: int                    # bottleneck width
    bw: int                   # residual width
    inc: int                  # dense growth
    groups: int
    stride: int = 1
    has_proj: bool = False
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, carry, train: bool = False):
        res, dense = carry
        x = jnp.concatenate([res, dense], axis=-1)
        if self.has_proj:
            p = ConvBN(self.bw + 2 * self.inc, (1, 1),
                       (self.stride,) * 2, relu=False, dtype=self.dtype,
                       name="proj")(x, train)
            res, dense = p[..., :self.bw], p[..., self.bw:]
        y = ConvBN(self.r, (1, 1), dtype=self.dtype, name="c1")(x, train)
        y = ConvBN(self.r, (3, 3), (self.stride,) * 2, groups=self.groups,
                   dtype=self.dtype, name="c2")(y, train)
        y = ConvBN(self.bw + self.inc, (1, 1), relu=False,
                   dtype=self.dtype, name="c3")(y, train)
        return (res + y[..., :self.bw],
                jnp.concatenate([dense, y[..., self.bw:]], axis=-1))


class DPN(nn.Module):
    """Dual Path Network (dpn.py:1-381). k_sec blocks per stage; stage s
    has residual width bw0*2^s, bottleneck r0*2^s, dense growth inc[s]."""
    num_classes: int = 1000
    k_sec: Sequence[int] = (3, 4, 20, 3)
    inc_sec: Sequence[int] = (16, 32, 24, 128)
    r0: int = 96
    bw0: int = 256
    groups: int = 32
    stem: int = 64
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = ConvBN(self.stem, (7, 7), (2, 2), dtype=self.dtype,
                   name="stem")(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        carry = (x, x[..., :0])
        for s, (n, inc) in enumerate(zip(self.k_sec, self.inc_sec)):
            bw, r = self.bw0 * 2 ** s, self.r0 * 2 ** s
            for i in range(n):
                carry = DualPathBlock(
                    r, bw, inc, self.groups,
                    stride=2 if (i == 0 and s > 0) else 1,
                    has_proj=(i == 0), dtype=self.dtype,
                    name=f"s{s}b{i}")(carry, train)
        x = jnp.concatenate(carry, axis=-1)
        x = nn.relu(x).mean(axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="head")(x)
        return x.astype(jnp.float32)


# ----------------------------------------------------------------- NASNet

class FitReduce(nn.Module):
    """1x1 fit of a cell input to ``features``; factorized stride-2
    reduction when the spatial dims are larger than the reference input
    (nasnet.py CellStem/first-cell path adjustment)."""
    features: int
    reduce: bool = False
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        if self.reduce:
            a = nn.avg_pool(x, (1, 1), strides=(2, 2))
            b = nn.avg_pool(x[:, 1:, 1:], (1, 1), strides=(2, 2))
            b = jnp.pad(b, [(0, 0), (0, a.shape[1] - b.shape[1]),
                            (0, a.shape[2] - b.shape[2]), (0, 0)])
            x = jnp.concatenate([
                nn.Conv(self.features // 2, (1, 1), use_bias=False,
                        dtype=self.dtype, name="p1")(nn.relu(a)),
                nn.Conv(self.features - self.features // 2, (1, 1),
                        use_bias=False, dtype=self.dtype, name="p2")(
                    nn.relu(b))], axis=-1)
            return nn.BatchNorm(use_running_average=not train,
                                momentum=0.9, dtype=self.dtype,
                                name="bn")(x)
        return ConvBN(self.features, (1, 1), dtype=self.dtype,
                      name="fit")(x, train)


class NormalCell(nn.Module):
    """NASNet-A normal cell: 5 pairwise combines over (h, h_prev)
    (nasnet.py NormalCell; wiring per the NASNet-A paper figure)."""
    features: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, h, h_prev, train: bool = False):
        f = self.features
        sep = partial(SepConvBN, dtype=self.dtype)
        h = FitReduce(f, dtype=self.dtype, name="fit_h")(h, train)
        hp = FitReduce(f, reduce=h_prev.shape[1] != h.shape[1],
                       dtype=self.dtype, name="fit_hp")(h_prev, train)
        c0 = sep(f, (3, 3), name="c0")(h, train) + h
        c1 = sep(f, (3, 3), name="c1a")(hp, train) + \
            sep(f, (5, 5), name="c1b")(h, train)
        c2 = _pool(h, "avg") + hp
        c3 = _pool(hp, "avg") + _pool(hp, "avg")
        c4 = sep(f, (5, 5), name="c4a")(hp, train) + \
            sep(f, (3, 3), name="c4b")(hp, train)
        return jnp.concatenate([hp, c0, c1, c2, c3, c4], axis=-1)


class ReductionCell(nn.Module):
    """NASNet-A reduction cell (stride-2 combines)."""
    features: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, h, h_prev, train: bool = False):
        f = self.features
        s2 = (2, 2)
        sep = partial(SepConvBN, dtype=self.dtype)
        h = FitReduce(f, dtype=self.dtype, name="fit_h")(h, train)
        hp = FitReduce(f, reduce=h_prev.shape[1] != h.shape[1],
                       dtype=self.dtype, name="fit_hp")(h_prev, train)
        c0 = sep(f, (7, 7), s2, name="c0a")(hp, train) + \
            sep(f, (5, 5), s2, name="c0b")(h, train)
        c1 = _pool(h, "max", strides=s2) + \
            sep(f, (7, 7), s2, name="c1")(hp, train)
        c2 = _pool(h, "avg", strides=s2) + \
            sep(f, (5, 5), s2, name="c2")(hp, train)
        c3 = _pool(h, "max", strides=s2) + \
            sep(f, (3, 3), name="c3")(c0, train)
        c4 = _pool(c0, "avg") + c1
        return jnp.concatenate([c1, c2, c3, c4], axis=-1)


class NASNetA(nn.Module):
    """NASNet-A (nasnet.py:1-643): stem → (N normal + reduction) ×3 −
    final reduction, doubling filters at each reduction."""
    num_classes: int = 1000
    filters: int = 44
    n_normal: int = 4
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = ConvBN(32, (3, 3), (2, 2), relu=False, dtype=self.dtype,
                   name="stem")(x, train)
        f = self.filters
        h0 = ReductionCell(f // 2, dtype=self.dtype, name="stem0")(
            x, x, train)
        h1 = ReductionCell(f, dtype=self.dtype, name="stem1")(
            h0, x, train)
        h_prev, h = h0, h1
        for stage in range(3):
            for i in range(self.n_normal):
                out = NormalCell(f * 2 ** stage, dtype=self.dtype,
                                 name=f"n{stage}_{i}")(h, h_prev, train)
                h_prev, h = h, out
            if stage < 2:
                out = ReductionCell(f * 2 ** (stage + 1),
                                    dtype=self.dtype,
                                    name=f"r{stage}")(h, h_prev, train)
                h_prev, h = h, out
        x = nn.relu(h).mean(axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="head")(x)
        return x.astype(jnp.float32)


# ---------------------------------------------------------------- PolyNet

class InceptionResUnit(nn.Module):
    """Inception-ResNet residual F used inside poly compositions
    (ployNet.py BlockA/B/C analogs). Returns the residual branch only."""
    kind: str                 # "a" | "b" | "c"
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        cb = partial(ConvBN, dtype=self.dtype)
        c = x.shape[-1]
        if self.kind == "a":
            b0 = cb(32, (1, 1), name="b0")(x, train)
            b1 = cb(32, (3, 3), name="b1b")(
                cb(32, (1, 1), name="b1a")(x, train), train)
            b2 = cb(64, (3, 3), name="b2c")(
                cb(48, (3, 3), name="b2b")(
                    cb(32, (1, 1), name="b2a")(x, train), train), train)
            y = jnp.concatenate([b0, b1, b2], axis=-1)
        elif self.kind == "b":
            b0 = cb(192, (1, 1), name="b0")(x, train)
            b1 = cb(192, (7, 1), name="b1c")(
                cb(160, (1, 7), name="b1b")(
                    cb(128, (1, 1), name="b1a")(x, train), train), train)
            y = jnp.concatenate([b0, b1], axis=-1)
        else:
            b0 = cb(192, (1, 1), name="b0")(x, train)
            b1 = cb(256, (3, 1), name="b1c")(
                cb(224, (1, 3), name="b1b")(
                    cb(192, (1, 1), name="b1a")(x, train), train), train)
            y = jnp.concatenate([b0, b1], axis=-1)
        return ConvBN(c, (1, 1), relu=False, dtype=self.dtype,
                      name="proj")(y, train)


class PolyBlock(nn.Module):
    """Polynomial composition (ployNet.py poly/mpoly/2-way):
    poly2:  x + F(x) + F(F(x))    (shared F)
    mpoly2: x + F(x) + G(F(x))
    2way:   x + F(x) + G(x)
    with the paper's beta residual scaling."""
    kind: str
    mode: str = "poly2"
    beta: float = 0.3
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        f = InceptionResUnit(self.kind, dtype=self.dtype, name="f")
        fx = f(x, train)
        if self.mode == "poly2":
            second = f(nn.relu(x + self.beta * fx), train)
        elif self.mode == "mpoly2":
            second = InceptionResUnit(self.kind, dtype=self.dtype,
                                      name="g")(
                nn.relu(x + self.beta * fx), train)
        else:
            second = InceptionResUnit(self.kind, dtype=self.dtype,
                                      name="g")(x, train)
        return nn.relu(x + self.beta * (fx + second))


class PolyNet(nn.Module):
    """PolyNet (ployNet.py:1-490): inception-resnet-v2 trunk with
    poly-2/2-way mixed stages A/B/C."""
    num_classes: int = 1000
    stage_blocks: Tuple[int, int, int] = (10, 10, 5)
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        cb = partial(ConvBN, dtype=self.dtype)
        x = cb(32, (3, 3), (2, 2), name="s1")(x, train)
        x = cb(64, (3, 3), name="s2")(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        x = cb(80, (1, 1), name="s3")(x, train)
        x = cb(192, (3, 3), name="s4")(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        x = cb(384, (1, 1), name="s5")(x, train)
        modes = ["2way", "poly2", "mpoly2"]
        for i in range(self.stage_blocks[0]):
            x = PolyBlock("a", modes[i % 3], dtype=self.dtype,
                          name=f"a{i}")(x, train)
        x = jnp.concatenate([                       # reduction A
            cb(384, (3, 3), (2, 2), name="ra0")(x, train),
            cb(384, (3, 3), (2, 2), name="ra1c")(
                cb(256, (3, 3), name="ra1b")(
                    cb(256, (1, 1), name="ra1a")(x, train), train), train),
            nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")],
            axis=-1)
        for i in range(self.stage_blocks[1]):
            x = PolyBlock("b", modes[i % 3], dtype=self.dtype,
                          name=f"b{i}")(x, train)
        x = jnp.concatenate([                       # reduction B
            cb(384, (3, 3), (2, 2), name="rb0b")(
                cb(256, (1, 1), name="rb0a")(x, train), train),
            cb(384, (3, 3), (2, 2), name="rb1b")(
                cb(256, (1, 1), name="rb1a")(x, train), train),
            nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")],
            axis=-1)
        for i in range(self.stage_blocks[2]):
            x = PolyBlock("c", modes[i % 3], dtype=self.dtype,
                          name=f"c{i}")(x, train)
        x = x.mean(axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="head")(x)
        return x.astype(jnp.float32)


# -------------------------------------------------------------- SENet-154

class SEBottleneck(nn.Module):
    """SENet-154 bottleneck: double-width 1x1, grouped 3x3, SE(16)
    (senet.py SEBottleneck)."""
    features: int
    stride: int = 1
    groups: int = 64
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        res = x
        if self.stride != 1 or x.shape[-1] != self.features * 4:
            res = ConvBN(self.features * 4, (1, 1), (self.stride,) * 2,
                         relu=False, dtype=self.dtype, name="down")(
                x, train)
        y = ConvBN(self.features * 2, (1, 1), dtype=self.dtype,
                   name="c1")(x, train)
        y = ConvBN(self.features * 4, (3, 3), (self.stride,) * 2,
                   groups=self.groups, dtype=self.dtype, name="c2")(
            y, train)
        y = ConvBN(self.features * 4, (1, 1), relu=False,
                   dtype=self.dtype, name="c3")(y, train)
        y = SEModule(reduction=16, dtype=self.dtype, name="se")(y)
        return nn.relu(y + res)


class SENet154(nn.Module):
    """SENet-154 (senet.py:1-449): 3-conv deep stem + SEBottleneck
    stages (3, 8, 36, 3)."""
    num_classes: int = 1000
    blocks: Sequence[int] = (3, 8, 36, 3)
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = ConvBN(64, (3, 3), (2, 2), dtype=self.dtype, name="s1")(
            x, train)
        x = ConvBN(64, (3, 3), dtype=self.dtype, name="s2")(x, train)
        x = ConvBN(128, (3, 3), dtype=self.dtype, name="s3")(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for s, n in enumerate(self.blocks):
            for i in range(n):
                x = SEBottleneck(64 * 2 ** s,
                                 stride=2 if (i == 0 and s > 0) else 1,
                                 dtype=self.dtype,
                                 name=f"s{s}b{i}")(x, train)
        x = x.mean(axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="head")(x)
        return x.astype(jnp.float32)


@MODELS.register("xception")
def xception(num_classes: int = 1000, **kw):
    return Xception(num_classes=num_classes, **kw)


@MODELS.register("inception_v4")
def inception_v4(num_classes: int = 1000, **kw):
    return InceptionV4(num_classes=num_classes, **kw)


@MODELS.register("dpn92")
def dpn92(num_classes: int = 1000, **kw):
    return DPN(num_classes=num_classes, **kw)


@MODELS.register("dpn68")
def dpn68(num_classes: int = 1000, **kw):
    cfg = dict(k_sec=(3, 4, 12, 3), inc_sec=(16, 32, 32, 64), r0=32,
               bw0=64, stem=16, groups=32)
    cfg.update(kw)
    return DPN(num_classes=num_classes, **cfg)


@MODELS.register("nasnet_a_mobile")
def nasnet_a_mobile(num_classes: int = 1000, **kw):
    return NASNetA(num_classes=num_classes, **kw)


@MODELS.register("polynet")
def polynet(num_classes: int = 1000, **kw):
    return PolyNet(num_classes=num_classes, **kw)


@MODELS.register("senet154")
def senet154(num_classes: int = 1000, **kw):
    return SENet154(num_classes=num_classes, **kw)
