from . import lenet, swin, vit  # noqa: F401  (import registers factories)
