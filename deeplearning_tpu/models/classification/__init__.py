from . import (cnns, convnext, lenet, mobile, repvgg, resnet, swin,  # noqa: F401
               vit)  # import registers factories
