from . import (cnns, convnext, lenet, mobile, repvgg, resnet, swin,  # noqa: F401
               transfg, vit, zoo_extra)  # import registers factories
