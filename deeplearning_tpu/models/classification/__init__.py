from . import lenet, vit  # noqa: F401  (import registers factories)
