"""MNIST CNN/FCN — the archetype-A reference models.

TPU-native rebuild of classification/mnist/models/network.py (mnist_cnn,
mnist_fcn): same capacity/API surface, NHWC layout (XLA's preferred conv
layout on TPU), bf16 compute / f32 params via the dtype policy.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp

from ...core.registry import MODELS


class MnistCNN(nn.Module):
    num_classes: int = 10
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        x = nn.Conv(32, (3, 3), dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(64, (3, 3), dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape(x.shape[0], -1)
        x = nn.Dense(128, dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.Dropout(0.25, deterministic=not train)(x)
        x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return x.astype(jnp.float32)


class MnistFCN(nn.Module):
    num_classes: int = 10
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype).reshape(x.shape[0], -1)
        for width in (512, 256):
            x = nn.Dense(width, dtype=self.dtype)(x)
            x = nn.relu(x)
            x = nn.Dropout(0.2, deterministic=not train)(x)
        x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return x.astype(jnp.float32)


@MODELS.register("mnist_cnn")
def mnist_cnn(num_classes: int = 10, **kw) -> MnistCNN:
    return MnistCNN(num_classes=num_classes, **kw)


@MODELS.register("mnist_fcn")
def mnist_fcn(num_classes: int = 10, **kw) -> MnistFCN:
    return MnistFCN(num_classes=num_classes, **kw)
