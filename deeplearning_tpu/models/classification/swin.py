"""Swin Transformer v1/v2 — hierarchical windowed attention.

Capability surface of classification/swin_transformer/models/
swin_transformer.py: WindowAttention with relative position bias (:70),
SwinTransformerBlock with cyclic shift + mask (:168), PatchMerging (:308),
BasicLayer, SwinTransformer (:410-411 gradient checkpointing), and the
v2 variants (swin_transformer_v2.py: cosine attention with learned
logit scale, log-spaced continuous position bias MLP).

TPU-first: windows are processed as one batched matmul over
(windows × heads); the fused Pallas kernel (ops/pallas/window_attention.py)
replaces the reference's CUDA roll+partition kernel; roll/partition
themselves are lax ops XLA fuses. NHWC throughout.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from ...core.registry import MODELS
from ...ops import window_utils as wu
from .vit import DropPath, Mlp


class WindowAttention(nn.Module):
    """Window MHSA with relative position bias (v1) or cosine attention
    with log-CPB (v2)."""
    dim: int
    window: int
    num_heads: int
    qkv_bias: bool = True
    v2: bool = False
    dtype: Any = jnp.bfloat16
    use_pallas: bool = False

    @nn.compact
    def __call__(self, x, mask: Optional[jax.Array] = None,
                 deterministic: bool = True):
        bw, n, c = x.shape
        d = c // self.num_heads
        if self.v2 and self.use_pallas:
            raise NotImplementedError(
                "Pallas fused window attention supports the v1 "
                "(bias-table) path only; cosine attention runs unfused.")
        if self.v2 and self.qkv_bias:
            # v2 uses q/v biases only: a k bias is NOT softmax-invariant
            # under cosine attention (it shifts keys before normalization).
            qkv = nn.Dense(3 * c, use_bias=False, dtype=self.dtype,
                           name="qkv")(x)
            q_bias = self.param("q_bias", nn.initializers.zeros, (c,),
                                jnp.float32)
            v_bias = self.param("v_bias", nn.initializers.zeros, (c,),
                                jnp.float32)
            bias_vec = jnp.concatenate(
                [q_bias, jnp.zeros_like(q_bias), v_bias])
            qkv = qkv + bias_vec.astype(qkv.dtype)
        else:
            qkv = nn.Dense(3 * c, use_bias=self.qkv_bias, dtype=self.dtype,
                           name="qkv")(x)
        qkv = qkv.reshape(bw, n, 3, self.num_heads, d)

        if self.v2:
            # swin v2: cosine attention + continuous position bias MLP over
            # log-spaced coords (swin_transformer_v2.py surface).
            logit_scale = self.param(
                "logit_scale",
                lambda key, shape: jnp.log(10.0) * jnp.ones(shape),
                (self.num_heads, 1, 1))
            rel_coords = wu.relative_position_index(self.window)
            coords_table = self._log_coords_table()
            cpb = nn.Sequential([
                nn.Dense(512, dtype=jnp.float32, name="cpb_fc1"),
                nn.relu,
                nn.Dense(self.num_heads, use_bias=False, dtype=jnp.float32,
                         name="cpb_fc2")])(coords_table)
            bias = 16.0 * nn.sigmoid(cpb[rel_coords.reshape(-1)])
            bias = bias.reshape(n, n, self.num_heads).transpose(2, 0, 1)
            q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
            from ...ops.losses import safe_normalize
            qn = safe_normalize(q.astype(jnp.float32), axis=-1)
            kn = safe_normalize(k.astype(jnp.float32), axis=-1)
            scale = jnp.exp(jnp.minimum(logit_scale, jnp.log(100.0)))
            s = jnp.einsum("bqhd,bkhd->bhqk", qn, kn).astype(jnp.float32)
            s = s * scale[None] + bias[None]
            if mask is not None:
                nw = mask.shape[0]
                s = s.reshape(bw // nw, nw, self.num_heads, n, n) \
                    + mask[None, :, None]
                s = s.reshape(bw, self.num_heads, n, n)
            p = jax.nn.softmax(s, axis=-1).astype(v.dtype)
            out = jnp.einsum("bhqk,bkhd->bqhd", p, v).reshape(bw, n, c)
        else:
            table = self.param(
                "relative_position_bias_table",
                nn.initializers.truncated_normal(0.02),
                ((2 * self.window - 1) ** 2, self.num_heads), jnp.float32)
            idx = wu.relative_position_index(self.window)
            bias = table[idx.reshape(-1)].reshape(n, n, self.num_heads)
            bias = bias.transpose(2, 0, 1)          # (heads, N, N)
            if self.use_pallas:
                from ...ops.pallas.window_attention import (
                    window_attention_checkpointed)
                out = window_attention_checkpointed(qkv, bias, mask)
            else:
                out = wu.windowed_attention_reference(qkv, bias, mask)

        out = nn.Dense(c, dtype=self.dtype, name="proj")(out)
        return out

    def _log_coords_table(self):
        w = self.window
        rel = np.arange(-(w - 1), w, dtype=np.float32)
        table = np.stack(np.meshgrid(rel, rel, indexing="ij"),
                         axis=-1).reshape(-1, 2)
        table = table / (w - 1) * 8
        table = np.sign(table) * np.log2(np.abs(table) + 1.0) / np.log2(8)
        return jnp.asarray(table)


class SwinBlock(nn.Module):
    dim: int
    input_resolution: Tuple[int, int]
    num_heads: int
    window: int = 7
    shift: int = 0
    mlp_ratio: float = 4.0
    qkv_bias: bool = True
    drop: float = 0.0
    drop_path_rate: float = 0.0
    v2: bool = False
    dtype: Any = jnp.bfloat16
    use_pallas: bool = False
    moe: bool = False                 # MoE MLP (swin_transformer_moe)
    num_experts: int = 8

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        h, w = self.input_resolution
        b, n, c = x.shape
        window = min(self.window, h, w)
        shift = 0 if window >= min(h, w) else self.shift

        shortcut = x
        if not self.v2:                      # v1: pre-norm
            x = nn.LayerNorm(dtype=self.dtype, name="norm1")(x)
        x = x.reshape(b, h, w, c)
        if shift > 0:
            x = jnp.roll(x, (-shift, -shift), axis=(1, 2))
            mask = jnp.asarray(wu.shift_window_mask(h, w, window, shift))
        else:
            mask = None
        wins = wu.window_partition(x, window)          # (B*nW, win², C)
        wins = WindowAttention(self.dim, window, self.num_heads,
                               self.qkv_bias, self.v2, self.dtype,
                               self.use_pallas, name="attn")(
            wins, mask, deterministic)
        x = wu.window_merge(wins, window, h, w)
        if shift > 0:
            x = jnp.roll(x, (shift, shift), axis=(1, 2))
        x = x.reshape(b, n, c)
        if self.v2:                          # v2: post-norm (res-post-norm)
            x = nn.LayerNorm(dtype=self.dtype, name="norm1")(x)
        x = shortcut + DropPath(self.drop_path_rate)(x, deterministic)

        y = x
        if not self.v2:
            y = nn.LayerNorm(dtype=self.dtype, name="norm2")(y)
        if self.moe:
            from ...parallel.moe import MoEMlp
            y, aux = MoEMlp(self.num_experts,
                            hidden_ratio=self.mlp_ratio,
                            drop=self.drop,
                            dtype=self.dtype, name="moe_mlp")(
                y, deterministic)
            self.sow("losses", "moe_aux", aux)
        else:
            y = Mlp(self.mlp_ratio, self.drop, self.dtype, name="mlp")(
                y, deterministic)
        if self.v2:
            y = nn.LayerNorm(dtype=self.dtype, name="norm2")(y)
        return x + DropPath(self.drop_path_rate)(y, deterministic)


class SwinMLPBlock(nn.Module):
    """Swin-MLP block (swin_mlp.py:59-156): window attention replaced by a
    grouped token-mixing linear map — per head, a learned (win², win²)
    matrix over window positions (the reference's grouped Conv1d over
    nH·win² channels). Shifted blocks zero-pad by (window−shift, shift)
    on each spatial side and crop back, instead of cyclic roll + mask.

    TPU-first: the token mix is one batched einsum over
    (windows × heads) — an MXU matmul, no conv needed.
    """
    dim: int
    input_resolution: Tuple[int, int]
    num_heads: int
    window: int = 7
    shift: int = 0
    mlp_ratio: float = 4.0
    drop: float = 0.0
    drop_path_rate: float = 0.0
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        h, w = self.input_resolution
        b, n, c = x.shape
        window = min(self.window, h, w)
        shift = 0 if window >= min(h, w) else self.shift
        d = c // self.num_heads
        n_win = window * window

        shortcut = x
        x = nn.LayerNorm(dtype=self.dtype, name="norm1")(x)
        x = x.reshape(b, h, w, c)
        if shift > 0:
            # P_l = P_t = window - shift, P_r = P_b = shift (swin_mlp.py:91)
            pt, pb = window - shift, shift
            x = jnp.pad(x, ((0, 0), (pt, pb), (pt, pb), (0, 0)))
        hh, ww = x.shape[1], x.shape[2]
        wins = wu.window_partition(x, window)          # (B·nW, win², C)
        nwb = wins.shape[0]
        wins = wins.reshape(nwb, n_win, self.num_heads, d)
        kernel = self.param(
            "spatial_mlp_kernel", nn.initializers.lecun_normal(),
            (self.num_heads, n_win, n_win), jnp.float32)
        bias = self.param("spatial_mlp_bias", nn.initializers.zeros,
                          (self.num_heads, n_win), jnp.float32)
        wins = jnp.einsum("nihd,hoi->nohd", wins,
                          kernel.astype(wins.dtype)) \
            + bias.T[None, :, :, None].astype(wins.dtype)
        wins = wins.reshape(nwb, n_win, c)
        x = wu.window_merge(wins, window, hh, ww)
        if shift > 0:
            x = x[:, pt:pt + h, pt:pt + w, :]
        x = x.reshape(b, n, c)
        x = shortcut + DropPath(self.drop_path_rate)(x, deterministic)

        y = nn.LayerNorm(dtype=self.dtype, name="norm2")(x)
        y = Mlp(self.mlp_ratio, self.drop, self.dtype, name="mlp")(
            y, deterministic)
        return x + DropPath(self.drop_path_rate)(y, deterministic)


class PatchMerging(nn.Module):
    """2×2 patch merge + channel double (swin_transformer.py:308). v2 moves
    the norm AFTER the reduction (res-post-norm, over 2C not 4C)."""
    input_resolution: Tuple[int, int]
    dtype: Any = jnp.bfloat16
    v2: bool = False

    @nn.compact
    def __call__(self, x):
        h, w = self.input_resolution
        b, n, c = x.shape
        # channel order matches the reference concat [x0;x1;x2;x3] =
        # [(0,0),(1,0),(0,1),(1,1)] over (h-sub, w-sub), so pretrained
        # reduction/norm weights load without a channel permutation
        x = x.reshape(b, h // 2, 2, w // 2, 2, c)
        x = x.transpose(0, 1, 3, 4, 2, 5).reshape(b, (h // 2) * (w // 2),
                                                  4 * c)
        if self.v2:
            x = nn.Dense(2 * c, use_bias=False, dtype=self.dtype,
                         name="reduction")(x)
            return nn.LayerNorm(dtype=self.dtype, name="norm")(x)
        x = nn.LayerNorm(dtype=self.dtype, name="norm")(x)
        return nn.Dense(2 * c, use_bias=False, dtype=self.dtype,
                        name="reduction")(x)


class SwinTransformer(nn.Module):
    # input-shape driven: resolution comes from the actual input (H, W);
    # factory names carry the nominal train resolution only
    patch_size: int = 4
    num_classes: int = 1000
    embed_dim: int = 96
    depths: Sequence[int] = (2, 2, 6, 2)
    num_heads: Sequence[int] = (3, 6, 12, 24)
    window: int = 7
    mlp_ratio: float = 4.0
    qkv_bias: bool = True
    drop_rate: float = 0.0
    drop_path_rate: float = 0.1
    v2: bool = False
    dtype: Any = jnp.bfloat16
    remat: bool = False
    use_pallas: bool = False
    moe: bool = False                 # MoE MLP in every 2nd block
    num_experts: int = 8
    spatial_mlp: bool = False         # Swin-MLP (swin_mlp.py) blocks
    ape: bool = False                 # absolute position embedding
    # (swin_transformer.py:516-533). Swin's only position signal is the
    # window-RELATIVE bias + merging hierarchy; tasks whose label depends
    # on absolute layout (e.g. the ordered digit-pair hard set, where
    # ResNet learns via conv zero-padding leakage but swin flatlines —
    # runs/convergence/swin_diag_*) need this on.

    @nn.compact
    def __call__(self, x, train: bool = False):
        deterministic = not train
        x = x.astype(self.dtype)
        x = nn.Conv(self.embed_dim, (self.patch_size, self.patch_size),
                    strides=(self.patch_size, self.patch_size),
                    dtype=self.dtype, name="patch_embed")(x)
        b, h, w, c = x.shape
        x = x.reshape(b, h * w, c)
        x = nn.LayerNorm(dtype=self.dtype, name="patch_norm")(x)
        if self.ape:
            pos = self.param("absolute_pos_embed",
                             nn.initializers.truncated_normal(0.02),
                             (1, h * w, c), jnp.float32)
            x = x + pos.astype(self.dtype)
        x = nn.Dropout(self.drop_rate, deterministic=deterministic)(x)

        total_depth = sum(self.depths)
        dpr = np.linspace(0, self.drop_path_rate, total_depth)
        block_idx = 0
        res = (h, w)
        dim = self.embed_dim
        for stage, (depth, heads) in enumerate(zip(self.depths,
                                                   self.num_heads)):
            for i in range(depth):
                shift = 0 if i % 2 == 0 else self.window // 2
                if self.spatial_mlp:
                    blk = SwinMLPBlock
                    if self.remat:
                        blk = nn.remat(SwinMLPBlock, static_argnums=(2,))
                    x = blk(dim, res, heads, self.window, shift,
                            self.mlp_ratio, self.drop_rate,
                            float(dpr[block_idx]), self.dtype,
                            name=f"stage{stage}_block{i}")(x, deterministic)
                else:
                    blk = SwinBlock
                    if self.remat:
                        blk = nn.remat(SwinBlock, static_argnums=(2,))
                    x = blk(dim, res, heads, self.window, shift,
                            self.mlp_ratio, self.qkv_bias, self.drop_rate,
                            float(dpr[block_idx]), self.v2, self.dtype,
                            self.use_pallas,
                            self.moe and i % 2 == 1, self.num_experts,
                            name=f"stage{stage}_block{i}")(x, deterministic)
                block_idx += 1
            if stage < len(self.depths) - 1:
                x = PatchMerging(res, self.dtype, self.v2,
                                 name=f"stage{stage}_merge")(x)
                res = (res[0] // 2, res[1] // 2)
                dim *= 2
        x = nn.LayerNorm(dtype=self.dtype, name="norm")(x)
        x = jnp.mean(x, axis=1)
        # trunc-normal head like the reference (swin_transformer.py:564-566,
        # ALL Linears std=.02). Zero-init left logits identically zero at
        # init, so backbone grads were zero until the head moved — the
        # 100-class flatline root cause (runs/convergence/swin_diag_*).
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="head",
                     kernel_init=nn.initializers.truncated_normal(0.02))(x)
        return x.astype(jnp.float32)


def _factory(name, **defaults):
    @MODELS.register(name)
    def build(num_classes: int = 1000, **kw):
        return SwinTransformer(**{**defaults, "num_classes": num_classes,
                                  **kw})
    build.__name__ = name
    return build


swin_tiny_patch4_window7_224 = _factory(
    "swin_tiny_patch4_window7_224", embed_dim=96, depths=(2, 2, 6, 2),
    num_heads=(3, 6, 12, 24))
swin_small_patch4_window7_224 = _factory(
    "swin_small_patch4_window7_224", embed_dim=96, depths=(2, 2, 18, 2),
    num_heads=(3, 6, 12, 24))
swin_base_patch4_window7_224 = _factory(
    "swin_base_patch4_window7_224", embed_dim=128, depths=(2, 2, 18, 2),
    num_heads=(4, 8, 16, 32))
swin_large_patch4_window7_224 = _factory(
    "swin_large_patch4_window7_224", embed_dim=192, depths=(2, 2, 18, 2),
    num_heads=(6, 12, 24, 48))
swinv2_tiny_patch4_window7_224 = _factory(
    "swinv2_tiny_patch4_window7_224", embed_dim=96, depths=(2, 2, 6, 2),
    num_heads=(3, 6, 12, 24), v2=True)
swinv2_base_patch4_window7_224 = _factory(
    "swinv2_base_patch4_window7_224", embed_dim=128, depths=(2, 2, 18, 2),
    num_heads=(4, 8, 16, 32), v2=True)
# MoE variant (swin_transformer_moe.py surface): MoE MLP in alternating
# blocks; aux losses are sow'n under the "losses" collection
swin_moe_tiny_patch4_window7_224 = _factory(
    "swin_moe_tiny_patch4_window7_224", embed_dim=96, depths=(2, 2, 6, 2),
    num_heads=(3, 6, 12, 24), moe=True)
# small-image MoE config for the offline convergence runs (56px digits):
# patch 2 / 28->14 token grid keeps the 7-window shifted path + merges
swin_moe_micro_patch2_window7 = _factory(
    "swin_moe_micro_patch2_window7", patch_size=2, embed_dim=32,
    depths=(2, 2), num_heads=(2, 4), moe=True, num_experts=4,
    drop_path_rate=0.0)
# dense twin of the micro MoE config — the equal-size baseline for MoE
# convergence A/B runs (VERDICT r4 #3)
swin_micro_patch2_window7 = _factory(
    "swin_micro_patch2_window7", patch_size=2, embed_dim=32,
    depths=(2, 2), num_heads=(2, 4), drop_path_rate=0.0)
# 3-stage 56px configs (28->14->7 token grids): the micro 2-stage/dim-32
# pair flatlines on the 100-class hard set at every LR/schedule tested
# (r5 diag matrix, runs/convergence/swin_diag_*) while ResNet-18 reaches
# 0.9 — capacity, not optimization; these are the smallest swin shapes
# that actually learn the set
swin_mini_patch2_window7 = _factory(
    "swin_mini_patch2_window7", patch_size=2, embed_dim=64,
    depths=(2, 2, 4), num_heads=(2, 4, 8), drop_path_rate=0.0)
swin_moe_mini_patch2_window7 = _factory(
    "swin_moe_mini_patch2_window7", patch_size=2, embed_dim=64,
    depths=(2, 2, 4), num_heads=(2, 4, 8), moe=True, num_experts=4,
    drop_path_rate=0.0)
# +APE twins: the ordered-pair task is position-dependent (see the ape
# field comment); these are the configs that learn it
swin_mini_patch2_window7_ape = _factory(
    "swin_mini_patch2_window7_ape", patch_size=2, embed_dim=64,
    depths=(2, 2, 4), num_heads=(2, 4, 8), drop_path_rate=0.0, ape=True)
swin_moe_mini_patch2_window7_ape = _factory(
    "swin_moe_mini_patch2_window7_ape", patch_size=2, embed_dim=64,
    depths=(2, 2, 4), num_heads=(2, 4, 8), moe=True, num_experts=4,
    drop_path_rate=0.0, ape=True)
# Swin-MLP variants (swin_mlp.py; configs/swin_mlp_*.yaml): cN = head dim,
# heads per stage = stage dim / N
swin_mlp_tiny_c24_patch4_window8_256 = _factory(
    "swin_mlp_tiny_c24_patch4_window8_256", embed_dim=96,
    depths=(2, 2, 6, 2), num_heads=(4, 8, 16, 32), window=8,
    spatial_mlp=True)
swin_mlp_base_patch4_window7_224 = _factory(
    "swin_mlp_base_patch4_window7_224", embed_dim=128,
    depths=(2, 2, 18, 2), num_heads=(4, 8, 16, 32), window=7,
    spatial_mlp=True)
