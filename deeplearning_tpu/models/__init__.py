from . import classification, detection, metric, segmentation, ssl  # noqa: F401
