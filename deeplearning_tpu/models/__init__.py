from . import classification  # noqa: F401
