from . import classification, detection, metric, segmentation, ssl, stereo  # noqa: F401
