from . import classification, detection, metric, pose, segmentation, ssl, stereo  # noqa: F401
