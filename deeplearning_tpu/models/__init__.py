from . import classification, detection  # noqa: F401
