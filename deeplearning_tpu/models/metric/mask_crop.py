"""Happy-Whale staged FCN-mask-crop pipeline.

Capability surface of metric_learning/Happy-Whale/fcn_mask (predict.py:
run — batch FCN inference writing per-image masks) + retrieval/dataLoader/
data_loader.py:110-130 (read image + stored mask, crop the animal before
augmentation). Stage 1 segments, stage 2 trains retrieval on the crops:

    masks   = predict_masks(fcn, variables, images)        # stage 1
    crops   = [crop_by_mask(img, m) for img, m in ...]     # bridge
    ...ArcFace/triplet training on crops...                # stage 2

TPU shape: stage-1 inference is a single jitted batched forward (masks
for a whole batch at once); the crop itself is host-side numpy like the
reference (it feeds the input pipeline, not the accelerator).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ...data.transforms import resize_bilinear


def mask_to_bbox(mask: np.ndarray, threshold: float = 0.5,
                 pad_frac: float = 0.05, min_size: int = 8
                 ) -> Tuple[int, int, int, int]:
    """Tight (x0, y0, x1, y1) around ``mask > threshold``, padded by
    pad_frac of each side; full image when the mask is empty/tiny."""
    h, w = mask.shape[:2]
    ys, xs = np.nonzero(mask > threshold)
    if len(xs) == 0:
        return 0, 0, w, h
    x0, x1 = int(xs.min()), int(xs.max()) + 1
    y0, y1 = int(ys.min()), int(ys.max()) + 1
    if (x1 - x0) < min_size or (y1 - y0) < min_size:
        return 0, 0, w, h
    px = int((x1 - x0) * pad_frac)
    py = int((y1 - y0) * pad_frac)
    return (max(x0 - px, 0), max(y0 - py, 0),
            min(x1 + px, w), min(y1 + py, h))


def crop_by_mask(image: np.ndarray, mask: np.ndarray,
                 out_hw: Optional[Tuple[int, int]] = None,
                 threshold: float = 0.5, pad_frac: float = 0.05
                 ) -> np.ndarray:
    """Crop ``image`` to the mask bbox (optionally resized to out_hw) —
    the data_loader.py:110-130 crop-before-augment step. The mask may be
    at a different resolution than the image (stage 1 predicts at a
    fixed size); the bbox is rescaled into image space."""
    x0, y0, x1, y1 = mask_to_bbox(mask, threshold, pad_frac)
    ih, iw = image.shape[:2]
    mh, mw = mask.shape[:2]
    if (mh, mw) != (ih, iw):
        sx, sy = iw / mw, ih / mh
        x0, x1 = int(x0 * sx), min(int(round(x1 * sx)), iw)
        y0, y1 = int(y0 * sy), min(int(round(y1 * sy)), ih)
    crop = image[y0:y1, x0:x1]
    if out_hw is not None:
        crop = resize_bilinear(crop, out_hw)
    return crop


def make_mask_predictor(seg_model, variables, *, threshold: float = 0.5):
    """Jitted stage-1 inference: images (B, H, W, C) → float masks
    (B, H, W) in [0, 1]. Handles 1-logit (sigmoid) and K-logit
    (argmax != background) segmentation heads, and dict outputs with an
    'out' key (the torchvision fcn_resnet50 output shape)."""

    @jax.jit
    def predict(images: jax.Array) -> jax.Array:
        out = seg_model.apply(variables, images, train=False)
        if isinstance(out, dict):
            out = out.get("out", next(iter(out.values())))
        if out.shape[-1] == 1:
            return jax.nn.sigmoid(out[..., 0].astype(jnp.float32))
        fg = jnp.argmax(out, axis=-1) != 0
        return fg.astype(jnp.float32)

    def predict_masks(images: np.ndarray) -> np.ndarray:
        return np.asarray(predict(jnp.asarray(images)))

    predict_masks.threshold = threshold
    return predict_masks


def mask_crop_source(paths, labels, masks_dir: str,
                     out_hw: Tuple[int, int] = (224, 224),
                     transform=None):
    """folder_source variant that crops each image by its stored stage-1
    mask (masks_dir/<stem>.png) before the usual transform — the
    retrieval loader's image+mask path."""
    import os

    from ...data.datasets import load_image
    from ...data.loader import MapSource

    labels = np.asarray(labels)

    def fetch(i: int):
        img = load_image(paths[i])
        stem = os.path.splitext(os.path.basename(paths[i]))[0]
        mask_path = os.path.join(masks_dir, stem + ".png")
        if os.path.exists(mask_path):
            from PIL import Image
            mask = np.asarray(Image.open(mask_path).convert("L"),
                              np.float32) / 255.0
            img = crop_by_mask(img, mask, out_hw)
        else:
            img = resize_bilinear(img, out_hw)
        if transform is not None:
            img = transform(img)
        return {"image": np.asarray(img, np.float32),
                "label": np.asarray(labels[i], np.int32)}

    return MapSource(len(paths), fetch)


def write_masks(predict_masks, paths, out_dir: str, *,
                image_size: Tuple[int, int] = (256, 256),
                batch: int = 16) -> int:
    """Stage-1 driver (predict.py:run surface): batch images through the
    predictor, write <stem>.png binary masks. Returns #written."""
    import os

    from PIL import Image

    from ...data.datasets import load_image

    os.makedirs(out_dir, exist_ok=True)
    n = 0
    for start in range(0, len(paths), batch):
        chunk = paths[start:start + batch]
        imgs = np.stack([resize_bilinear(load_image(p), image_size)
                         for p in chunk])
        masks = predict_masks(imgs)
        for p, m in zip(chunk, masks):
            stem = os.path.splitext(os.path.basename(p))[0]
            arr = ((m > predict_masks.threshold) * 255).astype(np.uint8)
            Image.fromarray(arr, "L").save(
                os.path.join(out_dir, stem + ".png"))
            n += 1
    return n
