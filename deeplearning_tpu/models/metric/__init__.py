from . import bdb  # noqa: F401
