from . import bdb, mask_crop  # noqa: F401
