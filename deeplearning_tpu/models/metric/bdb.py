"""Batch-DropBlock (BDB) re-ID network + ArcFace retrieval model.

Surface of metric_learning/BDB (models/networks.py — ResNet50 trunk with a
global branch and a part branch whose feature map gets a fixed-size block
dropped per batch, trained with triplet+softmax, trainers/trainer.py:35)
and metric_learning/Happy-Whale retrieval (models/model.py:11 model_whale:
backbone + BNNeck embedding + ArcFace/wnfc classifier — see
ops/losses.arcface_logits; getLoss :154 combines triplet(global) +
triplet(local) + CE).
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from ...core.registry import MODELS
from ..classification.resnet import ResNet


def batch_drop_block(x: jax.Array, rng: jax.Array, h_ratio: float,
                     w_ratio: float) -> jax.Array:
    """Zero one identical (rh, rw) block across the whole batch — the BDB
    regularizer (networks.py BatchDrop). Fixed block size => static shapes;
    the random position is a traced scalar."""
    b, h, w, c = x.shape
    rh = max(int(round(h * h_ratio)), 1)
    rw = max(int(round(w * w_ratio)), 1)
    ky, kx = jax.random.split(rng)
    y0 = jax.random.randint(ky, (), 0, h - rh + 1)
    x0 = jax.random.randint(kx, (), 0, w - rw + 1)
    rows = jnp.arange(h)[:, None]
    cols = jnp.arange(w)[None, :]
    block = ((rows >= y0) & (rows < y0 + rh)
             & (cols >= x0) & (cols < x0 + rw))
    return x * (1.0 - block[None, :, :, None].astype(x.dtype))


class BDBNetwork(nn.Module):
    num_classes: int
    feat_dim: int = 512
    drop_height_ratio: float = 0.33
    drop_width_ratio: float = 1.0
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        feats = ResNet(stage_sizes=(3, 4, 6, 3), return_features=True,
                       dtype=self.dtype, name="backbone")(x, train=train)
        fmap = feats["c5"]

        # global branch: GAP -> embedding -> classifier
        g = jnp.mean(fmap.astype(jnp.float32), axis=(1, 2))
        g_emb = nn.Dense(self.feat_dim, use_bias=False, dtype=self.dtype,
                         name="global_reduce")(g.astype(self.dtype))
        g_emb = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                             dtype=self.dtype, name="global_bn")(g_emb)
        g_logits = nn.Dense(self.num_classes, dtype=self.dtype,
                            name="global_cls")(g_emb).astype(jnp.float32)

        # part branch: extra bottleneck conv, batch-drop, GMP
        p = nn.Conv(1024, (1, 1), use_bias=False, dtype=self.dtype,
                    name="part_conv")(fmap)
        p = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         dtype=self.dtype, name="part_conv_bn")(p)
        p = nn.relu(p)
        if train:
            p = batch_drop_block(p, self.make_rng("dropout"),
                                 self.drop_height_ratio,
                                 self.drop_width_ratio)
        p_feat = jnp.max(p.astype(jnp.float32), axis=(1, 2))
        p_emb = nn.Dense(1024, use_bias=False, dtype=self.dtype,
                         name="part_reduce")(p_feat.astype(self.dtype))
        p_emb = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                             dtype=self.dtype, name="part_bn")(p_emb)
        p_logits = nn.Dense(self.num_classes, dtype=self.dtype,
                            name="part_cls")(p_emb).astype(jnp.float32)

        embedding = jnp.concatenate(
            [g_emb.astype(jnp.float32), p_emb.astype(jnp.float32)], axis=-1)
        return {"embedding": embedding,
                "global_embedding": g_emb.astype(jnp.float32),
                "part_embedding": p_emb.astype(jnp.float32),
                "global_logits": g_logits, "part_logits": p_logits}


class ArcFaceModel(nn.Module):
    """Backbone + BNNeck embedding + ArcFace class centers (Happy-Whale
    retrieval surface). Use ops/losses.arcface_logits(embedding, centers,
    labels) for the margin loss."""
    num_classes: int
    feat_dim: int = 512
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        feats = ResNet(stage_sizes=(2, 2, 2, 2), block="basic",
                       return_features=True, dtype=self.dtype,
                       name="backbone")(x, train=train)
        h = jnp.mean(feats["c5"].astype(jnp.float32), axis=(1, 2))
        emb = nn.Dense(self.feat_dim, use_bias=False, dtype=self.dtype,
                       name="neck")(h.astype(self.dtype))
        emb = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                           dtype=self.dtype, name="neck_bn")(emb)
        emb = emb.astype(jnp.float32)
        centers = self.param("arcface_centers",
                             nn.initializers.normal(0.01),
                             (self.feat_dim, self.num_classes), jnp.float32)
        return {"embedding": emb, "centers": centers}


@MODELS.register("bdb_resnet50")
def bdb_resnet50(num_classes: int = 751, **kw):
    return BDBNetwork(num_classes=num_classes, **kw)


@MODELS.register("arcface_resnet18")
def arcface_resnet18(num_classes: int = 100, **kw):
    return ArcFaceModel(num_classes=num_classes, **kw)
