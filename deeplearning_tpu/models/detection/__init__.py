from . import faster_rcnn, fcos, fpn, retinanet, yolox  # noqa: F401
