from . import (faster_rcnn, fcos, fpn, retinanet, yolo_builder,  # noqa: F401
               yolov5, yolox)
