from . import faster_rcnn, fcos, fpn, retinanet, yolov5, yolox  # noqa: F401
