from . import (faster_rcnn, fcos, fpn, predict, retinanet,  # noqa: F401
               yolo_builder, yolov5, yolox)
from .predict import build_predict_fn, is_detection_model  # noqa: F401
