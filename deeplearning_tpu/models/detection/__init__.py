from . import faster_rcnn, fpn, retinanet  # noqa: F401
