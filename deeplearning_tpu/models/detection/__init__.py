from . import fpn, retinanet  # noqa: F401
