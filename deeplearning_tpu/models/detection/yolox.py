"""YOLOX: anchor-free YOLO with decoupled head and SimOTA assignment.

Surface of detection/YOLOX: CSPDarknet (yolox/models/darknet.py — Focus
stem, CSP stages, SPP), PAFPN (yolo_pafpn.py — top-down + bottom-up),
decoupled YOLOXHead (yolo_head.py:19), get_losses (:254: obj BCE + cls
BCE + IoU loss on SimOTA-assigned anchors), SimOTA get_assignments (:426:
candidate gating by in-box/in-center, cost = cls + 3·(-log iou) + 1e5·
out-of-candidate, dynamic-k from top-10 IoU sum :608), decode_outputs,
postprocess (yolox/utils/boxes.py).

TPU-first SimOTA (SURVEY.md hard part #2): dynamic-k matching becomes a
dense fixed-shape rank test — for each (padded) gt, an anchor is taken
iff its cost-rank within that gt's row < dynamic_k; multi-assignment
resolves by argmin cost. No sorting-by-variable-k, no CPU fallback
(yolo_head.py:327 OOM fallback is obsolete: the cost matrix is
(MAX_GT × A) and lives comfortably in HBM).
"""

from __future__ import annotations

import math
from typing import Any, Dict, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from ...ops.padding import torch_pad
from ...core.registry import MODELS
from ...ops import boxes as box_ops
from ...ops import losses as L
from ...ops import nms as nms_ops

STRIDES = (8, 16, 32)


class ConvBnSiLU(nn.Module):
    features: int
    kernel: int = 3
    stride: int = 1
    groups: int = 1
    dtype: Any = jnp.bfloat16
    act: str = "silu"      # "lrelu" for the yolov3/YOLOFPN path

    @nn.compact
    def __call__(self, x, train: bool = False):
        # torch autopad semantics (yolov5 common.py autopad); SAME would
        # pad (0,1) at stride 2 and shift sampling centers
        x = nn.Conv(self.features, (self.kernel,) * 2,
                    strides=(self.stride,) * 2,
                    padding=torch_pad(self.kernel),
                    feature_group_count=self.groups, use_bias=False,
                    dtype=self.dtype, name="conv")(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.97,
                         epsilon=1e-3, dtype=self.dtype, name="bn")(x)
        return nn.leaky_relu(x, 0.1) if self.act == "lrelu" else nn.silu(x)


class Bottleneck(nn.Module):
    features: int
    shortcut: bool = True
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        y = ConvBnSiLU(self.features, 1, dtype=self.dtype,
                       name="c1")(x, train)
        y = ConvBnSiLU(self.features, 3, dtype=self.dtype,
                       name="c2")(y, train)
        return x + y if self.shortcut and x.shape[-1] == self.features \
            else y


class CSPLayer(nn.Module):
    features: int
    n: int = 1
    shortcut: bool = True
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        half = self.features // 2
        a = ConvBnSiLU(half, 1, dtype=self.dtype, name="main")(x, train)
        b = ConvBnSiLU(half, 1, dtype=self.dtype, name="skip")(x, train)
        for i in range(self.n):
            a = Bottleneck(half, self.shortcut, self.dtype,
                           name=f"b{i}")(a, train)
        y = jnp.concatenate([a, b], axis=-1)
        return ConvBnSiLU(self.features, 1, dtype=self.dtype,
                          name="out")(y, train)


class SPPBottleneck(nn.Module):
    features: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = ConvBnSiLU(self.features // 2, 1, dtype=self.dtype,
                       name="pre")(x, train)
        pools = [x] + [nn.max_pool(x, (k, k), strides=(1, 1),
                                   padding="SAME") for k in (5, 9, 13)]
        x = jnp.concatenate(pools, axis=-1)
        return ConvBnSiLU(self.features, 1, dtype=self.dtype,
                          name="post")(x, train)


class CSPDarknet(nn.Module):
    depth_mult: float = 0.33       # yolox-s
    width_mult: float = 0.5
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        def w(c):
            return int(c * self.width_mult)

        def d(n):
            return max(int(round(n * self.depth_mult)), 1)
        # Focus: space-to-depth stem (darknet.py Focus)
        patches = jnp.concatenate([
            x[:, 0::2, 0::2], x[:, 1::2, 0::2],
            x[:, 0::2, 1::2], x[:, 1::2, 1::2]], axis=-1)
        y = ConvBnSiLU(w(64), 3, dtype=self.dtype, name="stem")(
            patches.astype(self.dtype), train)
        y = ConvBnSiLU(w(128), 3, 2, dtype=self.dtype, name="d2_conv")(
            y, train)
        y = CSPLayer(w(128), d(3), dtype=self.dtype, name="d2_csp")(
            y, train)
        c3 = y = self._stage(y, w(256), d(9), "d3", train)
        c4 = y = self._stage(y, w(512), d(9), "d4", train)
        y = ConvBnSiLU(w(1024), 3, 2, dtype=self.dtype, name="d5_conv")(
            y, train)
        y = SPPBottleneck(w(1024), self.dtype, name="spp")(y, train)
        c5 = CSPLayer(w(1024), d(3), shortcut=False, dtype=self.dtype,
                      name="d5_csp")(y, train)
        return {"c3": c3, "c4": c4, "c5": c5}

    def _stage(self, y, ch, n, name, train):
        y = ConvBnSiLU(ch, 3, 2, dtype=self.dtype,
                       name=f"{name}_conv")(y, train)
        return CSPLayer(ch, n, dtype=self.dtype,
                        name=f"{name}_csp")(y, train)


class PAFPN(nn.Module):
    width_mult: float = 0.5
    depth_mult: float = 0.33
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, feats, train: bool = False):
        def w(c):
            return int(c * self.width_mult)

        def d(n):
            return max(int(round(n * self.depth_mult)), 1)

        def up(x):
            b, h, wd, c = x.shape
            return jax.image.resize(x, (b, h * 2, wd * 2, c), "nearest")
        c3, c4, c5 = feats["c3"], feats["c4"], feats["c5"]
        # top-down
        p5 = ConvBnSiLU(w(512), 1, dtype=self.dtype,
                        name="lat5")(c5, train)
        y = jnp.concatenate([up(p5), c4], -1)
        p4 = CSPLayer(w(512), d(3), False, self.dtype,
                      name="td4")(y, train)
        p4 = ConvBnSiLU(w(256), 1, dtype=self.dtype, name="lat4")(p4, train)
        y = jnp.concatenate([up(p4), c3], -1)
        p3 = CSPLayer(w(256), d(3), False, self.dtype,
                      name="td3")(y, train)
        # bottom-up
        y = ConvBnSiLU(w(256), 3, 2, dtype=self.dtype,
                       name="bu3")(p3, train)
        y = jnp.concatenate([y, p4], -1)
        n4 = CSPLayer(w(512), d(3), False, self.dtype,
                      name="bu4_csp")(y, train)
        y = ConvBnSiLU(w(512), 3, 2, dtype=self.dtype,
                       name="bu4")(n4, train)
        y = jnp.concatenate([y, p5], -1)
        n5 = CSPLayer(w(1024), d(3), False, self.dtype,
                      name="bu5_csp")(y, train)
        return [p3, n4, n5]


class ResLayer(nn.Module):
    """Darknet residual: 1×1 halve + 3×3 restore, lrelu (darknet.py
    ResLayer)."""
    ch: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        y = ConvBnSiLU(self.ch // 2, 1, dtype=self.dtype, act="lrelu",
                       name="c1")(x, train)
        y = ConvBnSiLU(self.ch, 3, dtype=self.dtype, act="lrelu",
                       name="c2")(y, train)
        return x + y


class Darknet53(nn.Module):
    """Darknet-53 backbone (darknet.py Darknet, depth 53: residual groups
    1/2/8/8/4) with the SPP block YOLOFPN appends to dark5."""
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        y = ConvBnSiLU(32, 3, dtype=self.dtype, act="lrelu",
                       name="stem")(x.astype(self.dtype), train)

        def group(y, ch, n, name):
            y = ConvBnSiLU(ch, 3, 2, dtype=self.dtype, act="lrelu",
                           name=f"{name}_down")(y, train)
            for i in range(n):
                y = ResLayer(ch, self.dtype, name=f"{name}_res{i}")(
                    y, train)
            return y

        y = group(y, 64, 1, "d1")
        y = group(y, 128, 2, "d2")
        c3 = y = group(y, 256, 8, "d3")
        c4 = y = group(y, 512, 8, "d4")
        y = group(y, 1024, 4, "d5")
        # make_spp_block: 1×1/3×3 pre, multi-scale max-pool concat, 1×1
        # bottleneck out at 512ch (yolo_fpn.py)
        y = ConvBnSiLU(512, 1, dtype=self.dtype, act="lrelu",
                       name="spp_pre1")(y, train)
        y = ConvBnSiLU(1024, 3, dtype=self.dtype, act="lrelu",
                       name="spp_pre2")(y, train)
        pools = [y] + [nn.max_pool(y, (k, k), strides=(1, 1),
                                   padding="SAME") for k in (5, 9, 13)]
        y = jnp.concatenate(pools, axis=-1)
        y = ConvBnSiLU(512, 1, dtype=self.dtype, act="lrelu",
                       name="spp_post1")(y, train)
        y = ConvBnSiLU(1024, 3, dtype=self.dtype, act="lrelu",
                       name="spp_post2")(y, train)
        c5 = ConvBnSiLU(512, 1, dtype=self.dtype, act="lrelu",
                        name="spp_out")(y, train)
        return {"c3": c3, "c4": c4, "c5": c5}


class YOLOFPN(nn.Module):
    """yolo_fpn.py: two top-down upsample+concat "embedding" branches
    (five alternating 1×1/3×3 lrelu convs each) over Darknet-53
    features — the yolov3 exp's neck."""
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, feats, train: bool = False):
        def up(x):
            b, h, w, c = x.shape
            return jax.image.resize(x, (b, h * 2, w * 2, c), "nearest")

        def embed(y, ch, name):
            for i, (k, f) in enumerate(
                    [(1, ch), (3, ch * 2), (1, ch), (3, ch * 2), (1, ch)]):
                y = ConvBnSiLU(f, k, dtype=self.dtype, act="lrelu",
                               name=f"{name}_{i}")(y, train)
            return y

        c3, c4, c5 = feats["c3"], feats["c4"], feats["c5"]
        x1 = ConvBnSiLU(256, 1, dtype=self.dtype, act="lrelu",
                        name="out1_cbl")(c5, train)
        p4 = embed(jnp.concatenate([up(x1), c4], -1), 256, "out1")
        x2 = ConvBnSiLU(128, 1, dtype=self.dtype, act="lrelu",
                        name="out2_cbl")(p4, train)
        p3 = embed(jnp.concatenate([up(x2), c3], -1), 128, "out2")
        return [p3, p4, c5]


class YOLOXHead(nn.Module):
    num_classes: int = 80
    width_mult: float = 0.5
    dtype: Any = jnp.bfloat16
    act: str = "silu"

    @nn.compact
    def __call__(self, feats, train: bool = False):
        w = int(256 * self.width_mult)
        outs = []
        for li, x in enumerate(feats):
            x = ConvBnSiLU(w, 1, dtype=self.dtype, act=self.act,
                           name=f"stem{li}")(x, train)
            c = x
            for i in range(2):
                c = ConvBnSiLU(w, 3, dtype=self.dtype, act=self.act,
                               name=f"cls{li}_{i}")(c, train)
            r = x
            for i in range(2):
                r = ConvBnSiLU(w, 3, dtype=self.dtype, act=self.act,
                               name=f"reg{li}_{i}")(r, train)
            cls = nn.Conv(self.num_classes, (1, 1), dtype=self.dtype,
                          bias_init=nn.initializers.constant(
                              -math.log((1 - 0.01) / 0.01)),
                          name=f"cls_pred{li}")(c)
            reg = nn.Conv(4, (1, 1), dtype=self.dtype,
                          name=f"reg_pred{li}")(r)
            obj = nn.Conv(1, (1, 1), dtype=self.dtype,
                          bias_init=nn.initializers.constant(
                              -math.log((1 - 0.01) / 0.01)),
                          name=f"obj_pred{li}")(r)
            b = x.shape[0]
            out = jnp.concatenate([reg, obj, cls], -1)
            outs.append(out.reshape(b, -1, 5 + self.num_classes))
        return jnp.concatenate(outs, axis=1).astype(jnp.float32)


class YOLOX(nn.Module):
    num_classes: int = 80
    depth_mult: float = 0.33
    width_mult: float = 0.5
    dtype: Any = jnp.bfloat16
    backbone_type: str = "cspdarknet"   # "darknet53" = yolov3 exp variant

    @nn.compact
    def __call__(self, images, train: bool = False):
        if self.backbone_type == "darknet53":
            # exps/default/yolov3.py: YOLOFPN backbone + lrelu head
            feats = Darknet53(self.dtype, name="backbone")(images, train)
            pyramid = YOLOFPN(self.dtype, name="neck")(feats, train)
            return YOLOXHead(self.num_classes, self.width_mult,
                             self.dtype, act="lrelu",
                             name="head")(pyramid, train)
        feats = CSPDarknet(self.depth_mult, self.width_mult, self.dtype,
                           name="backbone")(images, train)
        pyramid = PAFPN(self.width_mult, self.depth_mult, self.dtype,
                        name="neck")(feats, train)
        return YOLOXHead(self.num_classes, self.width_mult, self.dtype,
                         name="head")(pyramid, train)


def yolox_grid(image_hw: Tuple[int, int]) -> Tuple[np.ndarray, np.ndarray]:
    """(A, 2) grid centers (cell units NOT scaled) + (A,) strides."""
    h, w = image_hw
    centers, strides = [], []
    for s in STRIDES:
        fh, fw = math.ceil(h / s), math.ceil(w / s)
        ys, xs = np.mgrid[0:fh, 0:fw].astype(np.float32)
        centers.append(np.stack([xs, ys], -1).reshape(-1, 2))
        strides.append(np.full(fh * fw, s, np.float32))
    return np.concatenate(centers), np.concatenate(strides)


def decode_outputs(raw: jax.Array, centers: jax.Array, strides: jax.Array
                   ) -> jax.Array:
    """(B, A, 5+C) raw → boxes xyxy + obj + cls (decode_outputs surface:
    xy = (pred + grid)·stride, wh = exp(pred)·stride)."""
    xy = (raw[..., :2] + centers) * strides[:, None]
    wh = jnp.exp(jnp.clip(raw[..., 2:4], -10, 8)) * strides[:, None]
    boxes = jnp.concatenate([xy - wh / 2, xy + wh / 2], axis=-1)
    return jnp.concatenate([boxes, raw[..., 4:]], axis=-1)


def simota_assign(decoded: jax.Array, centers: jax.Array,
                  strides: jax.Array, gt_boxes: jax.Array,
                  gt_labels: jax.Array, gt_valid: jax.Array,
                  num_classes: int, center_radius: float = 2.5,
                  topk_ious: int = 10) -> Dict[str, jax.Array]:
    """Fixed-shape SimOTA for one image. decoded (A, 5+C)."""
    a = decoded.shape[0]
    boxes = decoded[:, :4]
    obj = jax.nn.sigmoid(decoded[:, 4])
    cls = jax.nn.sigmoid(decoded[:, 5:])

    cx = (centers[:, 0] + 0.5) * strides
    cy = (centers[:, 1] + 0.5) * strides
    # gating: anchor center in gt box OR in center radius
    in_box = ((cx[None, :] > gt_boxes[:, None, 0])
              & (cx[None, :] < gt_boxes[:, None, 2])
              & (cy[None, :] > gt_boxes[:, None, 1])
              & (cy[None, :] < gt_boxes[:, None, 3]))
    gcx = (gt_boxes[:, 0] + gt_boxes[:, 2]) / 2
    gcy = (gt_boxes[:, 1] + gt_boxes[:, 3]) / 2
    rad = center_radius * strides[None, :]
    in_center = ((jnp.abs(cx[None, :] - gcx[:, None]) < rad)
                 & (jnp.abs(cy[None, :] - gcy[:, None]) < rad))
    fg_cand = (in_box | in_center) & gt_valid[:, None]    # (G, A)

    iou = box_ops.box_iou(gt_boxes, boxes)                # (G, A)
    iou = jnp.where(gt_valid[:, None], iou, 0.0)
    iou_cost = -jnp.log(iou + 1e-8)
    onehot = jax.nn.one_hot(gt_labels, num_classes)       # (G, C)
    joint = jnp.sqrt(jnp.clip(cls[None] * obj[None, :, None], 1e-8, 1.0))
    cls_cost = -(onehot[:, None, :] * jnp.log(joint)
                 + (1 - onehot[:, None, :]) * jnp.log(1 - joint + 1e-8))
    cls_cost = jnp.sum(cls_cost, -1)                      # (G, A)
    # reference adds an extra 1e5 for candidates not in BOTH box and
    # center (yolo_head.py get_assignments cost), preferring anchors that
    # satisfy both gates; non-candidates end up at 2e5, strictly worse.
    cost = (cls_cost + 3.0 * iou_cost + 1e5 * (~fg_cand)
            + 1e5 * (~(in_box & in_center)))

    # dynamic k per gt: clamp(sum of top-10 candidate IoUs, min 1)
    masked_iou = jnp.where(fg_cand, iou, 0.0)
    topk_vals, _ = jax.lax.top_k(masked_iou, min(topk_ious, a))
    dynamic_k = jnp.clip(jnp.sum(topk_vals, -1).astype(jnp.int32), 1, a)

    # rank of each anchor's cost within its gt row (0 = cheapest)
    order = jnp.argsort(cost, axis=1)
    rank = jnp.zeros_like(order).at[
        jnp.arange(cost.shape[0])[:, None], order].set(
        jnp.broadcast_to(jnp.arange(a), cost.shape))
    take = (rank < dynamic_k[:, None]) & fg_cand          # (G, A)

    # resolve anchors claimed by several gts: keep min-cost gt
    claimed = jnp.sum(take, axis=0)
    best_gt = jnp.argmin(jnp.where(take, cost, jnp.inf), axis=0)
    fg = claimed > 0
    matched_gt = jnp.where(fg, best_gt, 0)
    return {"fg": fg, "matched_gt": matched_gt,
            "matched_iou": jnp.where(
                fg, iou[matched_gt, jnp.arange(a)], 0.0)}


def yolox_loss(raw: jax.Array, centers: jax.Array, strides: jax.Array,
               gt_boxes: jax.Array, gt_labels: jax.Array,
               gt_valid: jax.Array, num_classes: int,
               use_l1: bool = False) -> Dict[str, jax.Array]:
    """get_losses surface: IoU loss + obj BCE + cls BCE (+ optional L1 on
    raw deltas in the no-aug phase), normalized by total positives."""
    decoded = decode_outputs(raw, centers, strides)

    def per_image(raw_i, dec_i, boxes, labels, valid):
        # assignment is a constant target (reference runs it under
        # no_grad, yolo_head.py:426): stop gradients through the matching
        assign = jax.tree.map(jax.lax.stop_gradient, simota_assign(
            dec_i, centers, strides, boxes, labels, valid, num_classes))
        fg = assign["fg"]
        mg = assign["matched_gt"]
        tgt_boxes = boxes[mg]
        iou = box_ops.elementwise_box_iou(dec_i[:, :4], tgt_boxes, "iou")
        iou_loss = jnp.sum((1.0 - iou ** 2) * fg)         # IOUloss squared
        obj_t = fg.astype(jnp.float32)
        obj_loss = L.binary_cross_entropy(raw_i[:, 4], obj_t,
                                          weights=None, pos_weight=1.0)
        obj_loss = obj_loss * raw_i.shape[0]              # sum form
        cls_t = jax.nn.one_hot(labels[mg], num_classes) \
            * assign["matched_iou"][:, None]
        # _weighted_mean with the (A,1) fg mask = sum over (fg, C) / n_fg;
        # multiplying back by n_fg recovers the reference's sum form
        cls_loss = L.binary_cross_entropy(raw_i[:, 5:], cls_t,
                                          weights=fg[:, None],
                                          pos_weight=1.0)
        cls_loss = cls_loss * jnp.sum(fg)
        n_fg = jnp.sum(fg)
        l1 = jnp.zeros(())
        if use_l1:
            tgt_xy = ((tgt_boxes[:, :2] + tgt_boxes[:, 2:]) / 2
                      / strides[:, None] - centers)
            tgt_wh = jnp.log(jnp.maximum(
                (tgt_boxes[:, 2:] - tgt_boxes[:, :2]) / strides[:, None],
                1e-6))
            l1_t = jnp.concatenate([tgt_xy, tgt_wh], -1)
            l1 = jnp.sum(jnp.abs(raw_i[:, :4] - l1_t) * fg[:, None])
        return iou_loss, obj_loss, cls_loss, l1, n_fg

    iou_l, obj_l, cls_l, l1_l, n_fg = jax.vmap(per_image)(
        raw, decoded, gt_boxes, gt_labels, gt_valid)
    norm = jnp.maximum(jnp.sum(n_fg), 1.0)
    return {"iou_loss": 5.0 * jnp.sum(iou_l) / norm,
            "obj_loss": jnp.sum(obj_l) / norm,
            "cls_loss": jnp.sum(cls_l) / norm,
            "l1_loss": jnp.sum(l1_l) / norm,
            "num_fg": jnp.sum(n_fg)}


def yolox_postprocess(raw: jax.Array, centers: jax.Array,
                      strides: jax.Array, score_thresh: float = 0.01,
                      nms_thresh: float = 0.65, max_det: int = 100,
                      nms_impl: str = "auto") -> Dict[str, jax.Array]:
    decoded = decode_outputs(raw, centers, strides)
    return postprocess_decoded(decoded, score_thresh=score_thresh,
                               nms_thresh=nms_thresh, max_det=max_det,
                               nms_impl=nms_impl)


def postprocess_decoded(decoded: jax.Array, score_thresh: float = 0.01,
                        nms_thresh: float = 0.65, max_det: int = 100,
                        nms_impl: str = "auto") -> Dict[str, jax.Array]:
    """NMS postprocess over already-decoded (B, A, 5+C) predictions —
    split out of yolox_postprocess so TTA can merge several decoded
    variants (multi-scale/flip) along A and run ONE suppression pass."""

    def per_image(dec):
        obj = jax.nn.sigmoid(dec[:, 4])
        cls = jax.nn.sigmoid(dec[:, 5:])
        scores_all = obj[:, None] * cls
        best_cls = jnp.argmax(scores_all, -1)
        best_score = jnp.max(scores_all, -1)
        keep_idx, keep_valid = nms_ops.batched_nms(
            dec[:, :4], best_score, best_cls, nms_thresh, max_det,
            score_threshold=score_thresh, impl=nms_impl)
        b, s, c = nms_ops.gather_nms_outputs(keep_idx, keep_valid,
                                             dec[:, :4], best_score,
                                             best_cls, fill=(0, 0, -1))
        return b, s, c, keep_valid

    boxes, scores, classes, valid = jax.vmap(per_image)(decoded)
    return {"boxes": boxes, "scores": scores, "labels": classes,
            "valid": valid}


_VARIANTS = {
    "yolox_nano": (0.33, 0.25), "yolox_tiny": (0.33, 0.375),
    "yolox_s": (0.33, 0.5), "yolox_m": (0.67, 0.75),
    "yolox_l": (1.0, 1.0), "yolox_x": (1.33, 1.25),
}

for _name, (_d, _w) in _VARIANTS.items():
    def _mk(dd, ww):
        def build(num_classes: int = 80, **kw):
            return YOLOX(num_classes=num_classes, depth_mult=dd,
                         width_mult=ww, **kw)
        return build
    MODELS.register(_name)(_mk(_d, _w))


@MODELS.register("yolox_yolov3")
def yolox_yolov3(num_classes: int = 80, **kw):
    """exps/default/yolov3.py: Darknet-53 + YOLOFPN + lrelu decoupled
    head at width 1.0."""
    return YOLOX(num_classes=num_classes, depth_mult=1.0, width_mult=1.0,
                 backbone_type="darknet53", **kw)
