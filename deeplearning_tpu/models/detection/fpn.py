"""Feature Pyramid Network neck.

Surface of detection/FPN/fpn_model.py (standalone ResNet50+FPN reference)
and fasterRcnn models/backbone/resnet50_fpn.py (BackboneWithFPN +
LastLevelMaxPool): lateral 1x1 + top-down upsample + 3x3 smooth, extra
levels by stride-2 pooling/conv (RetinaNet's P6/P7,
network_files/retinanet.py LastLevelP6P7).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from ...ops.padding import torch_pad


class FPN(nn.Module):
    out_channels: int = 256
    extra_levels: str = "pool"     # 'pool' (faster-rcnn P6) | 'p6p7'
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, feats: Dict[str, jax.Array]) -> Dict[str, jax.Array]:
        names = sorted(feats, key=lambda k: int(k[1:]))      # c2 < c3 < ...
        laterals = {
            n: nn.Conv(self.out_channels, (1, 1), dtype=self.dtype,
                       name=f"lateral_{n}")(feats[n]) for n in names}
        out: Dict[str, jax.Array] = {}
        prev: Optional[jax.Array] = None
        for n in reversed(names):
            x = laterals[n]
            if prev is not None:
                b, h, w, c = x.shape
                up = jax.image.resize(prev, (b, h, w, c), "nearest")
                x = x + up
            prev = x
            out[f"p{n[1:]}"] = nn.Conv(self.out_channels, (3, 3),
                                       padding="SAME", dtype=self.dtype,
                                       name=f"smooth_{n}")(x)
        top = int(names[-1][1:])
        if self.extra_levels == "pool":
            out[f"p{top + 1}"] = nn.max_pool(
                out[f"p{top}"], (1, 1), strides=(2, 2))
        elif self.extra_levels == "p6p7":
            p6 = nn.Conv(self.out_channels, (3, 3), strides=(2, 2),
                         padding=torch_pad(3), dtype=self.dtype,
                         name="p6")(feats[names[-1]])
            p7 = nn.Conv(self.out_channels, (3, 3), strides=(2, 2),
                         padding=torch_pad(3), dtype=self.dtype,
                         name="p7")(nn.relu(p6))
            out[f"p{top + 1}"] = p6
            out[f"p{top + 2}"] = p7
        return out
