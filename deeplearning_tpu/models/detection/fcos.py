"""FCOS: anchor-free per-pixel detection with center-ness.

Surface of detection/FCOS: FCOS/FCOSDetector (models/fcos.py:15/:85),
shared 4-conv heads with a learnable per-level scale on the exp regression
(fcos.py ScaleExp), GenTargets (models/loss.py:27 — per-level location
targets :66 by in-box test + scale-range assignment, center sampling),
Loss (:216 — focal :344, centerness BCE :279, GIoU :311), DetectHead
(:141 postprocess).

TPU-first: locations per level are static grids; target generation is a
dense (locations × MAX_GT) masked min/argmin — no per-image loops.
"""

from __future__ import annotations

import math
from typing import Any, Dict, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from ...core.registry import MODELS
from ...ops import boxes as box_ops
from ...ops import losses as L
from ...ops import nms as nms_ops
from ..classification.resnet import ResNet
from .fpn import FPN

# per-level regression ranges (loss.py limit_range)
LEVEL_RANGES = ((-1, 64), (64, 128), (128, 256), (256, 512), (512, 1e8))
STRIDES = (8, 16, 32, 64, 128)


class ScaleExp(nn.Module):
    @nn.compact
    def __call__(self, x):
        s = self.param("scale", nn.initializers.ones, ())
        # clipped exponent (same guard as yolox decode_outputs): an
        # unbounded exp overflows to inf early in training at high lr
        # and poisons the GIoU loss with nan
        return jnp.exp(jnp.clip(x * s, -10.0, 8.0))


class FCOSHead(nn.Module):
    num_classes: int
    num_convs: int = 4
    channels: int = 256
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, feats: Dict[str, jax.Array]):
        cls_tower = [nn.Conv(self.channels, (3, 3), padding="SAME",
                             dtype=self.dtype, name=f"cls_conv{i}")
                     for i in range(self.num_convs)]
        reg_tower = [nn.Conv(self.channels, (3, 3), padding="SAME",
                             dtype=self.dtype, name=f"reg_conv{i}")
                     for i in range(self.num_convs)]
        cls_pred = nn.Conv(self.num_classes, (3, 3), padding="SAME",
                           bias_init=nn.initializers.constant(
                               -math.log((1 - 0.01) / 0.01)),
                           dtype=self.dtype, name="cls_pred")
        ctr_pred = nn.Conv(1, (3, 3), padding="SAME", dtype=self.dtype,
                           name="ctr_pred")
        reg_pred = nn.Conv(4, (3, 3), padding="SAME", dtype=self.dtype,
                           name="reg_pred")
        cls_out, ctr_out, reg_out = [], [], []
        for li, name in enumerate(sorted(feats, key=lambda k: int(k[1:]))):
            x = feats[name]
            c = x
            for conv in cls_tower:
                c = nn.relu(conv(c))
            r = x
            for conv in reg_tower:
                r = nn.relu(conv(r))
            b = x.shape[0]
            cls_out.append(cls_pred(c).reshape(
                b, -1, self.num_classes).astype(jnp.float32))
            ctr_out.append(ctr_pred(r).reshape(b, -1).astype(jnp.float32))
            ltrb = ScaleExp(name=f"scale{li}")(
                reg_pred(r).astype(jnp.float32))
            reg_out.append(ltrb.reshape(b, -1, 4))
        return (jnp.concatenate(cls_out, 1), jnp.concatenate(ctr_out, 1),
                jnp.concatenate(reg_out, 1))


class FCOS(nn.Module):
    num_classes: int = 20
    backbone_sizes: Sequence[int] = (3, 4, 6, 3)
    fpn_channels: int = 256
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, images, train: bool = False):
        feats = ResNet(stage_sizes=self.backbone_sizes,
                       return_features=True, dtype=self.dtype,
                       name="backbone")(images, train=train)
        feats = {k: v for k, v in feats.items() if k in ("c3", "c4", "c5")}
        pyramid = FPN(self.fpn_channels, extra_levels="p6p7",
                      dtype=self.dtype, name="fpn")(feats)
        cls_logits, centerness, ltrb = FCOSHead(
            self.num_classes, dtype=self.dtype, name="head")(pyramid)
        return {"cls_logits": cls_logits, "centerness": centerness,
                "ltrb": ltrb}


def fcos_locations(image_hw: Tuple[int, int]) -> Tuple[np.ndarray, np.ndarray]:
    """All-level (x, y) centers + per-location level index."""
    h, w = image_hw
    locs, lvl = [], []
    for li, s in enumerate(STRIDES):
        fh, fw = math.ceil(h / s), math.ceil(w / s)
        ys, xs = np.mgrid[0:fh, 0:fw].astype(np.float32)
        pts = np.stack([(xs + 0.5) * s, (ys + 0.5) * s],
                       axis=-1).reshape(-1, 2)
        locs.append(pts)
        lvl.append(np.full(len(pts), li))
    return np.concatenate(locs), np.concatenate(lvl)


def fcos_targets(locations: jax.Array, level_idx: jax.Array,
                 gt_boxes: jax.Array, gt_labels: jax.Array,
                 gt_valid: jax.Array, center_radius: float = 1.5
                 ) -> Dict[str, jax.Array]:
    """Per-location targets (GenTargets surface): a location is positive
    if inside a gt (center-sampled) and its max ltrb falls in its level's
    range; ambiguity resolved by min-area gt."""
    ranges = jnp.asarray(LEVEL_RANGES)[level_idx]        # (L, 2)
    strides = jnp.asarray(STRIDES, jnp.float32)[level_idx]

    def per_image(boxes, labels, valid):
        x = locations[:, 0][:, None]                     # (L, 1)
        y = locations[:, 1][:, None]
        l = x - boxes[None, :, 0]                        # (L, G)
        t = y - boxes[None, :, 1]
        r = boxes[None, :, 2] - x
        b = boxes[None, :, 3] - y
        ltrb = jnp.stack([l, t, r, b], axis=-1)
        in_box = jnp.min(ltrb, -1) > 0
        max_reg = jnp.max(ltrb, -1)
        in_level = (max_reg >= ranges[:, 0:1]) & (max_reg <= ranges[:, 1:2])
        # center sampling: within radius*stride of gt center
        cx = (boxes[None, :, 0] + boxes[None, :, 2]) / 2
        cy = (boxes[None, :, 1] + boxes[None, :, 3]) / 2
        near = (jnp.abs(x - cx) <= center_radius * strides[:, None]) & \
            (jnp.abs(y - cy) <= center_radius * strides[:, None])
        cand = in_box & in_level & near & valid[None, :]
        area = box_ops.box_area(boxes)
        area_mat = jnp.where(cand, area[None, :], jnp.inf)
        best_gt = jnp.argmin(area_mat, axis=1)           # (L,)
        pos = jnp.any(cand, axis=1)
        cls_target = jnp.where(pos, labels[best_gt], -1)  # -1 = background
        reg_target = jnp.take_along_axis(
            ltrb, best_gt[:, None, None].repeat(4, -1), axis=1)[:, 0]
        lr = reg_target[:, [0, 2]]
        tb = reg_target[:, [1, 3]]
        ctr_target = jnp.sqrt(jnp.clip(
            (jnp.min(lr, -1) / jnp.maximum(jnp.max(lr, -1), 1e-9)) *
            (jnp.min(tb, -1) / jnp.maximum(jnp.max(tb, -1), 1e-9)), 0, 1))
        return {"cls": cls_target, "reg": reg_target, "ctr": ctr_target,
                "pos": pos}

    return jax.vmap(per_image)(gt_boxes, gt_labels, gt_valid)


def fcos_loss(outputs: Dict, targets: Dict) -> Dict[str, jax.Array]:
    num_classes = outputs["cls_logits"].shape[-1]

    def per_image(cls_logits, ctr, ltrb, tgt_cls, tgt_reg, tgt_ctr, pos):
        onehot = jax.nn.one_hot(jnp.where(tgt_cls >= 0, tgt_cls, 0),
                                num_classes) * (tgt_cls >= 0)[:, None]
        num_pos = jnp.maximum(jnp.sum(pos), 1)
        cls_loss = L.sigmoid_focal_loss(cls_logits, onehot,
                                        reduction="sum") / num_pos
        ctr_loss = L.binary_cross_entropy(ctr, tgt_ctr, weights=pos) \
            * jnp.sum(pos) / num_pos
        # GIoU on decoded boxes, centerness-weighted (FCOS-style)
        pred_boxes = jnp.stack([-ltrb[:, 0], -ltrb[:, 1],
                                ltrb[:, 2], ltrb[:, 3]], -1)
        tgt_boxes = jnp.stack([-tgt_reg[:, 0], -tgt_reg[:, 1],
                               tgt_reg[:, 2], tgt_reg[:, 3]], -1)
        giou = box_ops.elementwise_box_iou(pred_boxes, tgt_boxes, "giou")
        w = pos * tgt_ctr
        reg_loss = jnp.sum((1 - giou) * w) / jnp.maximum(jnp.sum(w), 1e-6)
        return cls_loss, ctr_loss, reg_loss

    cls_l, ctr_l, reg_l = jax.vmap(per_image)(
        outputs["cls_logits"], outputs["centerness"], outputs["ltrb"],
        targets["cls"], targets["reg"], targets["ctr"], targets["pos"])
    return {"cls_loss": jnp.mean(cls_l), "ctr_loss": jnp.mean(ctr_l),
            "reg_loss": jnp.mean(reg_l)}


def fcos_postprocess(outputs: Dict, locations: jax.Array,
                     image_hw: Tuple[int, int], score_thresh: float = 0.05,
                     nms_thresh: float = 0.6, topk: int = 1000,
                     max_det: int = 100,
                     nms_impl: str = "auto") -> Dict[str, jax.Array]:
    def per_image(cls_logits, ctr, ltrb):
        scores = jnp.sqrt(jax.nn.sigmoid(cls_logits)
                          * jax.nn.sigmoid(ctr)[:, None])
        boxes = jnp.stack([
            locations[:, 0] - ltrb[:, 0], locations[:, 1] - ltrb[:, 1],
            locations[:, 0] + ltrb[:, 2], locations[:, 1] + ltrb[:, 3]],
            axis=-1)
        boxes = box_ops.clip_boxes(boxes, image_hw)
        flat = scores.reshape(-1)
        k = min(topk, flat.shape[0])
        top_s, top_i = jax.lax.top_k(flat, k)
        nc = cls_logits.shape[-1]
        loc_i = top_i // nc
        cls_i = top_i % nc
        keep_idx, keep_valid = nms_ops.batched_nms(
            boxes[loc_i], top_s, cls_i, nms_thresh, max_det,
            score_threshold=score_thresh, impl=nms_impl)
        bsel, ssel, csel = nms_ops.gather_nms_outputs(
            keep_idx, keep_valid, boxes[loc_i], top_s, cls_i,
            fill=(0, 0, -1))
        return bsel, ssel, csel, keep_valid

    boxes, scores, classes, valid = jax.vmap(per_image)(
        outputs["cls_logits"], outputs["centerness"], outputs["ltrb"])
    return {"boxes": boxes, "scores": scores, "labels": classes,
            "valid": valid}


@MODELS.register("fcos_resnet50_fpn")
def fcos_resnet50_fpn(num_classes: int = 20, **kw):
    return FCOS(num_classes=num_classes, **kw)


@MODELS.register("fcos_resnet18_fpn")
def fcos_resnet18_fpn(num_classes: int = 20, **kw):
    return FCOS(num_classes=num_classes, backbone_sizes=(2, 2, 2, 2), **kw)
