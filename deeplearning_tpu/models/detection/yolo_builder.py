"""Spec-driven YOLO model assembly — the parse_model YAML builder.

Surface of detection/yolov5/models/yolo.py:121/:297: the model is a list
of layer specs ``[from, number, module, args]`` evaluated top to bottom,
where ``from`` indexes previously produced tensors (-1 = previous, lists
= concat inputs) — the mechanism behind yolov5s.yaml etc. Vocabulary:
Conv, C3 (CSP), SPP, Focus, Upsample, Concat, Detect. Specs can come
from a YAML file with the same structure as the reference's model yamls.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import flax.linen as nn
import jax
import jax.numpy as jnp
import yaml

from ...core.registry import MODELS
from .yolox import ConvBnSiLU, CSPLayer, SPPBottleneck

Spec = Tuple[Union[int, List[int]], int, str, list]


class SpecModel(nn.Module):
    """Evaluate a layer-spec list (parse_model semantics)."""
    spec: Sequence[Spec]
    num_classes: int = 80
    width_mult: float = 1.0
    depth_mult: float = 1.0
    anchors_per_loc: int = 3
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        def w(c):
            return max(int(c * self.width_mult), 1)

        def d(n):
            return max(int(round(n * self.depth_mult)), 1)

        outputs: List[jax.Array] = []
        y = x.astype(self.dtype)
        detect_outs: List[jax.Array] = []
        for li, (frm, num, mod, args) in enumerate(self.spec):
            # flax freezes module attrs: lists arrive as tuples
            frm_list = list(frm) if isinstance(frm, (list, tuple)) else [frm]
            inputs = [outputs[f] if f != -1 else y for f in frm_list]
            inp = inputs[0] if len(inputs) == 1 else None
            name = f"l{li}_{mod.lower()}"
            if mod == "Focus":
                p = jnp.concatenate([
                    inp[:, 0::2, 0::2], inp[:, 1::2, 0::2],
                    inp[:, 0::2, 1::2], inp[:, 1::2, 1::2]], axis=-1)
                y = ConvBnSiLU(w(args[0]), args[1] if len(args) > 1 else 3,
                               dtype=self.dtype, name=name)(p, train)
            elif mod == "Conv":
                ch, k = args[0], args[1] if len(args) > 1 else 1
                s = args[2] if len(args) > 2 else 1
                y = inp
                for r in range(max(d(num), 1)):   # parse_model repeats
                    y = ConvBnSiLU(w(ch), k, s if r == 0 else 1,
                                   dtype=self.dtype,
                                   name=f"{name}_{r}" if num > 1
                                   else name)(y, train)
            elif mod == "C3":
                shortcut = args[1] if len(args) > 1 else True
                y = CSPLayer(w(args[0]), d(num), shortcut,
                             dtype=self.dtype, name=name)(inp, train)
            elif mod == "SPP":
                y = SPPBottleneck(w(args[0]), self.dtype,
                                  name=name)(inp, train)
            elif mod in ("Upsample", "nn.Upsample"):
                # reference yaml args: [size(None), scale_factor, mode]
                scale = 2
                method = "nearest"
                if len(args) >= 2 and args[1]:
                    scale = int(args[1])
                if len(args) >= 3 and args[2]:
                    method = str(args[2])
                b, h, wd, c = inp.shape
                y = jax.image.resize(inp, (b, h * scale, wd * scale, c),
                                     method)
            elif mod == "Concat":
                y = jnp.concatenate(inputs, axis=-1)
            elif mod == "Detect":
                for di, feat in enumerate(inputs):
                    p = nn.Conv(self.anchors_per_loc
                                * (5 + self.num_classes), (1, 1),
                                dtype=self.dtype,
                                name=f"{name}_{di}")(feat)
                    b = p.shape[0]
                    detect_outs.append(p.reshape(
                        b, -1, 5 + self.num_classes))
                # Detect produces no feature map; keep a valid tensor in
                # the outputs slot so later `from` references fail loudly
                # in shape rather than on None
                y = inputs[0]
            else:
                raise ValueError(f"unknown module {mod!r} in spec")
            outputs.append(y)
        if detect_outs:
            return jnp.concatenate(detect_outs, 1).astype(jnp.float32)
        return y.astype(jnp.float32)


# yolov5-v5.0 layout as a spec list (the yolov5s.yaml content)
YOLOV5_SPEC: Sequence[Spec] = (
    (-1, 1, "Focus", [64]),          # 0
    (-1, 1, "Conv", [128, 3, 2]),    # 1
    (-1, 3, "C3", [128]),            # 2
    (-1, 1, "Conv", [256, 3, 2]),    # 3
    (-1, 9, "C3", [256]),            # 4  (P3)
    (-1, 1, "Conv", [512, 3, 2]),    # 5
    (-1, 9, "C3", [512]),            # 6  (P4)
    (-1, 1, "Conv", [1024, 3, 2]),   # 7
    (-1, 1, "SPP", [1024]),          # 8
    (-1, 3, "C3", [1024, False]),    # 9  (P5)
    (-1, 1, "Conv", [512, 1]),       # 10
    (-1, 1, "Upsample", []),         # 11
    ([-1, 6], 1, "Concat", []),      # 12
    (-1, 3, "C3", [512, False]),     # 13
    (-1, 1, "Conv", [256, 1]),       # 14
    (-1, 1, "Upsample", []),         # 15
    ([-1, 4], 1, "Concat", []),      # 16
    (-1, 3, "C3", [256, False]),     # 17 (out P3)
    (-1, 1, "Conv", [256, 3, 2]),    # 18
    ([-1, 14], 1, "Concat", []),     # 19
    (-1, 3, "C3", [512, False]),     # 20 (out P4)
    (-1, 1, "Conv", [512, 3, 2]),    # 21
    ([-1, 10], 1, "Concat", []),     # 22
    (-1, 3, "C3", [1024, False]),    # 23 (out P5)
    ([17, 20, 23], 1, "Detect", []),  # 24
)


def load_spec_yaml(path: str) -> Dict[str, Any]:
    """Load a reference-style model yaml: {depth_multiple, width_multiple,
    backbone: [...], head: [...]} → kwargs for SpecModel."""
    with open(path) as f:
        doc = yaml.safe_load(f)
    spec = [tuple(row) for row in
            list(doc.get("backbone", [])) + list(doc.get("head", []))]
    return {
        "spec": spec,
        "depth_mult": float(doc.get("depth_multiple", 1.0)),
        "width_mult": float(doc.get("width_multiple", 1.0)),
        "num_classes": int(doc.get("nc", 80)),
    }


@MODELS.register("yolov5_from_spec")
def yolov5_from_spec(num_classes: int = 80, spec=YOLOV5_SPEC,
                     **kw):
    defaults = dict(depth_mult=0.33, width_mult=0.5)
    return SpecModel(spec=tuple(spec), num_classes=num_classes,
                     **{**defaults, **kw})
