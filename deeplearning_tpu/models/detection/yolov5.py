"""YOLOv5: anchor-based YOLO with config-driven model assembly.

Surface of detection/yolov5: Detect head (models/yolo.py:39), the
YAML-driven Model/parse_model builder (:121/:297 — here a spec-list
builder over the same block vocabulary: Conv/C3/SPP/Focus from
models/common.py), ComputeLoss (utils/loss.py: CIoU box loss + obj BCE
weighted by IoU + cls BCE, anchor matching by wh-ratio with 3-neighbor
grid assignment), autoanchor k-means (utils/autoanchor.py:99
kmean_anchors), non_max_suppression (utils/general.py), fuse_conv_and_bn
(utils/torch_utils.py:211).

Reuses YOLOX's ConvBnSiLU/CSP blocks (identical math); the novelty here
is the anchor-based target assignment and the spec-driven builder.
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from ...core.registry import MODELS
from ...ops import boxes as box_ops
from ...ops import losses as L
from ...ops import nms as nms_ops
from .yolox import ConvBnSiLU, CSPLayer, SPPBottleneck

STRIDES = (8, 16, 32)
# default COCO anchors (per level, (w, h) pairs) — data/hyps defaults
DEFAULT_ANCHORS = (
    ((10, 13), (16, 30), (33, 23)),
    ((30, 61), (62, 45), (59, 119)),
    ((116, 90), (156, 198), (373, 326)),
)


class YOLOv5(nn.Module):
    num_classes: int = 80
    depth_mult: float = 0.33       # s variant
    width_mult: float = 0.5
    anchors: Sequence = DEFAULT_ANCHORS
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, images, train: bool = False):
        def w(c):
            return int(c * self.width_mult)

        def d(n):
            return max(int(round(n * self.depth_mult)), 1)
        x = images.astype(self.dtype)
        # backbone (v5.0 layout: Focus -> convs + C3 stages -> SPP)
        patches = jnp.concatenate([
            x[:, 0::2, 0::2], x[:, 1::2, 0::2],
            x[:, 0::2, 1::2], x[:, 1::2, 1::2]], axis=-1)
        y = ConvBnSiLU(w(64), 3, dtype=self.dtype, name="focus")(
            patches, train)
        y = ConvBnSiLU(w(128), 3, 2, dtype=self.dtype, name="c1")(y, train)
        y = CSPLayer(w(128), d(3), dtype=self.dtype, name="csp1")(y, train)
        y = ConvBnSiLU(w(256), 3, 2, dtype=self.dtype, name="c2")(y, train)
        p3 = CSPLayer(w(256), d(9), dtype=self.dtype, name="csp2")(y, train)
        y = ConvBnSiLU(w(512), 3, 2, dtype=self.dtype, name="c3")(p3, train)
        p4 = CSPLayer(w(512), d(9), dtype=self.dtype, name="csp3")(y, train)
        y = ConvBnSiLU(w(1024), 3, 2, dtype=self.dtype,
                       name="c4")(p4, train)
        y = SPPBottleneck(w(1024), self.dtype, name="spp")(y, train)
        p5 = CSPLayer(w(1024), d(3), shortcut=False, dtype=self.dtype,
                      name="csp4")(y, train)

        # PANet head
        def up(t):
            b, h, wd, c = t.shape
            return jax.image.resize(t, (b, h * 2, wd * 2, c), "nearest")
        y = ConvBnSiLU(w(512), 1, dtype=self.dtype, name="h1")(p5, train)
        h5 = y
        y = jnp.concatenate([up(y), p4], -1)
        y = CSPLayer(w(512), d(3), False, self.dtype, name="hcsp1")(y, train)
        y = ConvBnSiLU(w(256), 1, dtype=self.dtype, name="h2")(y, train)
        h4 = y
        y = jnp.concatenate([up(y), p3], -1)
        o3 = CSPLayer(w(256), d(3), False, self.dtype,
                      name="hcsp2")(y, train)
        y = ConvBnSiLU(w(256), 3, 2, dtype=self.dtype, name="h3")(o3, train)
        y = jnp.concatenate([y, h4], -1)
        o4 = CSPLayer(w(512), d(3), False, self.dtype,
                      name="hcsp3")(y, train)
        y = ConvBnSiLU(w(512), 3, 2, dtype=self.dtype, name="h4")(o4, train)
        y = jnp.concatenate([y, h5], -1)
        o5 = CSPLayer(w(1024), d(3), False, self.dtype,
                      name="hcsp4")(y, train)

        # Detect head: (B, H, W, A*(5+C)) per level -> (B, A_total, 5+C)
        na = len(self.anchors[0])
        outs = []
        for li, feat in enumerate((o3, o4, o5)):
            p = nn.Conv(na * (5 + self.num_classes), (1, 1),
                        dtype=self.dtype, name=f"detect{li}")(feat)
            b, fh, fw, _ = p.shape
            outs.append(p.reshape(b, fh * fw * na,
                                  5 + self.num_classes))
        return jnp.concatenate(outs, axis=1).astype(jnp.float32)


def yolov5_grid(image_hw: Tuple[int, int],
                anchors: Sequence = DEFAULT_ANCHORS
                ) -> Dict[str, np.ndarray]:
    """Per-prediction grid cell xy, anchor wh, stride (A_total,...)."""
    h, w = image_hw
    cells, awh, strides = [], [], []
    for (s, lvl_anchors) in zip(STRIDES, anchors):
        fh, fw = math.ceil(h / s), math.ceil(w / s)
        ys, xs = np.mgrid[0:fh, 0:fw].astype(np.float32)
        grid = np.stack([xs, ys], -1).reshape(-1, 1, 2)
        grid = np.tile(grid, (1, len(lvl_anchors), 1)).reshape(-1, 2)
        cells.append(grid)
        a = np.tile(np.asarray(lvl_anchors, np.float32)[None],
                    (fh * fw, 1, 1)).reshape(-1, 2)
        awh.append(a)
        strides.append(np.full(fh * fw * len(lvl_anchors), s, np.float32))
    return {"cell": np.concatenate(cells), "anchor": np.concatenate(awh),
            "stride": np.concatenate(strides)}


def decode_yolov5(raw: jax.Array, grid: Dict[str, jax.Array]) -> jax.Array:
    """v5 decode: xy = (2σ(p)−0.5 + cell)·stride; wh = (2σ(p))²·anchor."""
    xy = (2 * jax.nn.sigmoid(raw[..., :2]) - 0.5 + grid["cell"]) \
        * grid["stride"][:, None]
    wh = jnp.square(2 * jax.nn.sigmoid(raw[..., 2:4])) * grid["anchor"]
    boxes = jnp.concatenate([xy - wh / 2, xy + wh / 2], -1)
    return jnp.concatenate([boxes, raw[..., 4:]], -1)


def build_targets(grid: Dict[str, jax.Array], gt_boxes: jax.Array,
                  gt_labels: jax.Array, gt_valid: jax.Array,
                  anchor_t: float = 4.0) -> Dict[str, jax.Array]:
    """v5 assignment (ComputeLoss.build_targets surface), dense masked
    form: a prediction slot is positive for a gt if (a) wh ratio between
    its anchor and the gt is within anchor_t, and (b) the gt center falls
    in its cell or the adjacent half-cell (3-neighbor rule). Ambiguity →
    min wh-ratio cost."""
    def per_image(boxes, labels, valid):
        gwh = jnp.stack([boxes[:, 2] - boxes[:, 0],
                         boxes[:, 3] - boxes[:, 1]], -1)      # (G, 2)
        gxy = jnp.stack([(boxes[:, 0] + boxes[:, 2]) / 2,
                         (boxes[:, 1] + boxes[:, 3]) / 2], -1)
        ratio = gwh[:, None, :] / jnp.maximum(grid["anchor"][None], 1e-6)
        max_ratio = jnp.max(jnp.maximum(ratio, 1.0 / ratio), -1)  # (G, A)
        wh_ok = max_ratio < anchor_t
        # center distance in cell units for each slot's level
        cell_xy = gxy[:, None, :] / grid["stride"][None, :, None]
        d = jnp.abs(cell_xy - (grid["cell"][None] + 0.5))
        # own cell or ONE lateral/vertical neighbor within the half-cell
        # band — never the diagonal (v5 3-neighbor rule)
        near = (jnp.max(d, -1) < 1.0) & (jnp.min(d, -1) < 0.5)
        cand = wh_ok & near & valid[:, None]
        cost = jnp.where(cand, max_ratio, jnp.inf)
        best_gt = jnp.argmin(cost, axis=0)
        pos = jnp.any(cand, axis=0)
        return {"pos": pos, "matched_gt": jnp.where(pos, best_gt, 0)}

    return jax.vmap(per_image)(gt_boxes, gt_labels, gt_valid)


def yolov5_loss(raw: jax.Array, grid: Dict[str, jax.Array],
                gt_boxes: jax.Array, gt_labels: jax.Array,
                gt_valid: jax.Array, num_classes: int,
                box_gain: float = 0.05, obj_gain: float = 1.0,
                cls_gain: float = 0.5,
                balance: Sequence[float] = (4.0, 1.0, 0.4)
                ) -> Dict[str, jax.Array]:
    """ComputeLoss surface (yolov5/utils/loss.py:128-180), dense masked
    form with the reference's exact normalization: per-LEVEL means
    accumulated batch-globally (CIoU box loss and BCE cls loss averaged
    over that level's positives across the whole batch; obj BCE averaged
    over every slot of the level and weighted by ``balance``). CIoU is
    scale-invariant, so computing it on fully decoded pixel boxes equals
    the reference's grid-unit computation. The reference's final ``* bs``
    factor (loss.py:189) is NOT applied — it is a constant absorbed into
    the LR here. Slots claimed by several gt (rare) take the min-wh-ratio
    one, where the reference duplicates rows."""
    decoded = decode_yolov5(raw, grid)
    targets = build_targets(grid, gt_boxes, gt_labels, gt_valid)
    pos = targets["pos"].astype(jnp.float32)              # (B, A)
    mg = targets["matched_gt"]                            # (B, A)
    tgt_boxes = jnp.take_along_axis(
        gt_boxes, mg[..., None], axis=1)                  # (B, A, 4)
    ciou = jax.vmap(lambda d, t: box_ops.elementwise_box_iou(
        d[:, :4], t, "ciou"))(decoded, tgt_boxes)
    obj_t = jnp.where(pos > 0, jax.lax.stop_gradient(
        jnp.clip(ciou, 0.0, 1.0)), 0.0)
    obj_bce = L.binary_cross_entropy(raw[..., 4], obj_t,
                                     reduction="none")    # (B, A)
    cls_t = jax.nn.one_hot(jnp.take_along_axis(gt_labels, mg, axis=1),
                           num_classes)
    cls_bce = L.binary_cross_entropy(raw[..., 5:], cls_t,
                                     reduction="none")    # (B, A, K)
    # per-level masks from the STATIC stride ladder (grid["stride"] may be
    # a tracer under jit; yolov5_grid always lays levels out over STRIDES)
    box_loss = obj_loss = cls_loss = jnp.zeros(())
    for li, s in enumerate(STRIDES):
        m = (grid["stride"] == s).astype(jnp.float32)     # (A,)
        n_slots = jnp.maximum(jnp.sum(m), 1.0)
        n_pos = jnp.sum(pos * m)
        denom = jnp.maximum(n_pos, 1.0)
        box_loss += jnp.sum((1.0 - ciou) * pos * m) / denom
        obj_loss += (jnp.sum(obj_bce * m) / (raw.shape[0] * n_slots)) \
            * balance[min(li, len(balance) - 1)]
        if num_classes > 1:                  # loss.py:157 `if self.nc > 1`
            cls_loss += jnp.sum(cls_bce * (pos * m)[..., None]) \
                / (denom * num_classes)
    return {"box_loss": box_gain * box_loss,
            "obj_loss": obj_gain * obj_loss,
            "cls_loss": cls_gain * cls_loss}


def yolov5_postprocess(raw: jax.Array, grid: Dict[str, jax.Array],
                       score_thresh: float = 0.25,
                       nms_thresh: float = 0.45, max_det: int = 100,
                       nms_impl: str = "auto") -> Dict[str, jax.Array]:
    decoded = decode_yolov5(raw, grid)

    def per_image(dec):
        obj = jax.nn.sigmoid(dec[:, 4])
        cls = jax.nn.sigmoid(dec[:, 5:])
        conf = obj[:, None] * cls
        best_cls = jnp.argmax(conf, -1)
        best_score = jnp.max(conf, -1)
        keep_idx, keep_valid = nms_ops.batched_nms(
            dec[:, :4], best_score, best_cls, nms_thresh, max_det,
            score_threshold=score_thresh, impl=nms_impl)
        b, s, c = nms_ops.gather_nms_outputs(keep_idx, keep_valid,
                                             dec[:, :4], best_score,
                                             best_cls, fill=(0, 0, -1))
        return b, s, c, keep_valid

    boxes, scores, classes, valid = jax.vmap(per_image)(decoded)
    return {"boxes": boxes, "scores": scores, "labels": classes,
            "valid": valid}


def kmean_anchors(wh: np.ndarray, n: int = 9,
                  iterations: int = 30, seed: int = 0) -> np.ndarray:
    """Autoanchor k-means over gt wh (autoanchor.py:99 surface, plain
    k-means in wh space + sort by area; the genetic mutation step is
    replaced by k-means++ init)."""
    rng = np.random.default_rng(seed)
    wh = np.asarray(wh, np.float64)
    wh = wh[(wh >= 2.0).all(1)]
    # k-means++ init
    centers = [wh[rng.integers(len(wh))]]
    for _ in range(n - 1):
        d2 = np.min([np.sum((wh - c) ** 2, 1) for c in centers], axis=0)
        probs = d2 / d2.sum()
        centers.append(wh[rng.choice(len(wh), p=probs)])
    centers = np.stack(centers)
    for _ in range(iterations):
        d = np.linalg.norm(wh[:, None] - centers[None], axis=-1)
        assign = np.argmin(d, 1)
        for k in range(n):
            sel = wh[assign == k]
            if len(sel):
                centers[k] = sel.mean(0)
    return centers[np.argsort(centers.prod(1))]


def check_anchors(wh: np.ndarray, anchors: np.ndarray, thr: float = 4.0
                  ) -> dict:
    """Best-possible-recall anchor fit check (autoanchor.py:39
    check_anchors metric): for each gt wh, the best anchor's worst-side
    ratio must be within ``thr``. Returns {bpr, aat}: BPR = fraction of
    gts some anchor can match; AAT = anchors above threshold per gt.
    The reference recomputes anchors when BPR < 0.98."""
    wh = np.asarray(wh, np.float64)
    wh = wh[(wh > 0).all(1)]
    if len(wh) == 0:
        raise ValueError(
            "check_anchors: no valid gt boxes (all empty or non-positive "
            "wh) — a nan BPR would silently pass the < 0.98 gate")
    anchors = np.asarray(anchors, np.float64).reshape(-1, 2)
    r = wh[:, None] / anchors[None]                    # (G, A, 2)
    x = np.minimum(r, 1.0 / r).min(2)                  # worst side
    best = x.max(1)
    return {"bpr": float((best > 1.0 / thr).mean()),
            "aat": float((x > 1.0 / thr).sum(1).mean())}


_VARIANTS = {"yolov5s": (0.33, 0.5), "yolov5m": (0.67, 0.75),
             "yolov5l": (1.0, 1.0), "yolov5x": (1.33, 1.25)}

for _name, (_d, _w) in _VARIANTS.items():
    def _mk(dd, ww):
        def build(num_classes: int = 80, **kw):
            defaults = dict(depth_mult=dd, width_mult=ww)
            return YOLOv5(num_classes=num_classes, **{**defaults, **kw})
        return build
    MODELS.register(_name)(_mk(_d, _w))
