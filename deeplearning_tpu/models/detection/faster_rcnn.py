"""Faster R-CNN: two-stage detector with RPN + RoI heads on ResNet-FPN.

Surface of detection/fasterRcnn: FasterRCNNBase.forward
(models/faster_rcnn.py:44: backbone→rpn→roi_heads→postprocess),
TwoMLPHead (:115), FastRCNNPredictor (:138), RegionProposalNetwork
(models/rpn_function.py:304) with RPNHead (:207) and AnchorsGenerator
(:25), RoIHeads (models/roi_head.py:57) with fastrcnn_loss (:11),
Matcher/BalancedPositiveNegativeSampler/BoxCoder (utils/det_utils.py),
MultiScaleRoIAlign (faster_rcnn.py:305 → ops/roi_align.py).

TPU-first reformulation — every stage is fixed-shape:
- proposals: per-level top-k (static k) → concat → NMS to a fixed
  ``post_nms_top_n`` with a validity mask; suppressed slots carry zeros.
- training sampling: exact-count random masks (ops/matcher.balanced_sample)
  computed over ALL proposals; losses are mask-weighted sums — no gather
  to a dynamic subset. (FLOP cost of scoring unsampled rois is trivial
  next to the backbone.)
- gt boxes ride along padded (MAX_GT) with validity masks.
The image transform (resize/pad, transform.py:70) lives in the data
pipeline: the model consumes fixed-size batches.
"""

from __future__ import annotations

import math
from typing import Any, Dict, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from ...core.registry import MODELS
from ...ops import anchors as anc
from ...ops import boxes as box_ops
from ...ops import losses as L
from ...ops import matcher as M
from ...ops import nms as nms_ops
from ...ops.roi_align import multiscale_roi_align
from ..classification.resnet import ResNet
from .fpn import FPN


class RPNHead(nn.Module):
    anchors_per_loc: int = 3
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        x = nn.Conv(x.shape[-1], (3, 3), padding="SAME", dtype=self.dtype,
                    kernel_init=nn.initializers.normal(0.01),
                    name="conv")(x)
        x = nn.relu(x)
        obj = nn.Conv(self.anchors_per_loc, (1, 1), dtype=self.dtype,
                      kernel_init=nn.initializers.normal(0.01),
                      name="objectness")(x)
        deltas = nn.Conv(4 * self.anchors_per_loc, (1, 1), dtype=self.dtype,
                         kernel_init=nn.initializers.normal(0.01),
                         name="deltas")(x)
        b = x.shape[0]
        return (obj.reshape(b, -1).astype(jnp.float32),
                deltas.reshape(b, -1, 4).astype(jnp.float32))


class TwoMLPHead(nn.Module):
    hidden: int = 1024
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        x = x.reshape(x.shape[0], -1)
        x = nn.relu(nn.Dense(self.hidden, dtype=self.dtype, name="fc6")(x))
        x = nn.relu(nn.Dense(self.hidden, dtype=self.dtype, name="fc7")(x))
        return x


class FastRCNNPredictor(nn.Module):
    num_classes: int               # including background class 0
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        scores = nn.Dense(self.num_classes, dtype=self.dtype,
                          name="cls_score")(x)
        deltas = nn.Dense(4 * self.num_classes, dtype=self.dtype,
                          name="bbox_pred")(x)
        return scores.astype(jnp.float32), deltas.reshape(
            x.shape[0], self.num_classes, 4).astype(jnp.float32)


class FasterRCNN(nn.Module):
    """Forward returns raw heads; ``generate_proposals``/losses/postprocess
    are pure functions below so training and inference wire them freely."""
    num_classes: int = 21          # incl. background
    backbone_sizes: Sequence[int] = (3, 4, 6, 3)
    fpn_channels: int = 256
    anchors_per_loc: int = 3
    roi_output_size: int = 7
    roi_align_impl: str = "onepass"  # "onepass" packed-gather / "masked"
    dtype: Any = jnp.bfloat16
    backbone_frozen_bn: bool = False   # FrozenBatchNorm2d backbone stats
                                       # (resnet50_fpn.py:5); set True when
                                       # fine-tuning from ported weights

    @nn.compact
    def __call__(self, images: jax.Array, proposals: Optional[jax.Array]
                 = None, train: bool = False,
                 pyramid: Optional[Dict[str, jax.Array]] = None
                 ) -> Dict[str, Any]:
        """``pyramid``: pass the first call's ``out["pyramid"]`` to run
        the RoI stage WITHOUT recomputing backbone+FPN+RPN — the
        two-phase training step (rpn loss → proposals → roi loss) then
        costs one backbone forward, not two, and BN statistics update
        once per step (faster_rcnn.py:44 runs its single forward the
        same way; the double-apply here was 2× backbone cost)."""
        if pyramid is None:
            feats = ResNet(stage_sizes=self.backbone_sizes,
                           return_features=True, dtype=self.dtype,
                           frozen_bn=self.backbone_frozen_bn,
                           name="backbone")(images, train=train)
            pyramid = FPN(self.fpn_channels, extra_levels="pool",
                          dtype=self.dtype, name="fpn")(feats)
            rpn_head = RPNHead(self.anchors_per_loc, self.dtype,
                               name="rpn")
            obj, deltas = [], []
            level_counts = []
            for name in sorted(pyramid, key=lambda k: int(k[1:])):
                o, d = rpn_head(pyramid[name])
                obj.append(o)
                deltas.append(d)
                level_counts.append(o.shape[1])
            out = {
                "pyramid": pyramid,
                "rpn_obj": jnp.concatenate(obj, axis=1),
                "rpn_deltas": jnp.concatenate(deltas, axis=1),
                "level_counts": level_counts,
            }
        else:
            out = {"pyramid": pyramid}
        # second stage always runs (on a dummy roi when no proposals are
        # given) so the box-head params exist under eval-mode init
        run_props = proposals if proposals is not None else \
            jnp.zeros((images.shape[0], 1, 4), jnp.float32)
        # roi-align over p2..p5 (the pooled p6 extra level is RPN-only,
        # faster_rcnn.py:305 semantics)
        align_levels = sorted(pyramid, key=lambda k: int(k[1:]))[:-1]

        def roi_one(i):
            pyr_slice = {k: pyramid[k][i] for k in align_levels}
            return multiscale_roi_align(
                pyr_slice, run_props[i], self.roi_output_size,
                strides={k: 2 ** int(k[1]) for k in align_levels},
                impl=self.roi_align_impl)

        roi_feats = jax.vmap(roi_one)(jnp.arange(images.shape[0]))
        b, p = run_props.shape[:2]
        roi_feats = roi_feats.reshape(b * p, self.roi_output_size,
                                      self.roi_output_size,
                                      self.fpn_channels)
        h = TwoMLPHead(dtype=self.dtype, name="box_head")(
            roi_feats.astype(self.dtype))
        scores, box_deltas = FastRCNNPredictor(
            self.num_classes, self.dtype, name="box_predictor")(h)
        if proposals is not None:
            out["roi_scores"] = scores.reshape(b, p, self.num_classes)
            out["roi_deltas"] = box_deltas.reshape(b, p, self.num_classes, 4)
        return out


# ---------------------------------------------------------------- anchors
def fasterrcnn_anchors(image_hw: Tuple[int, int]) -> np.ndarray:
    """FPN anchors: one size per level ((32..512) × 3 ratios) on p2..p6."""
    h, w = image_hw
    shapes = {f"p{l}": (math.ceil(h / 2 ** l), math.ceil(w / 2 ** l))
              for l in (2, 3, 4, 5, 6)}
    strides = {k: 2 ** int(k[1]) for k in shapes}
    sizes = {f"p{l}": (2 ** (l + 3),) for l in (2, 3, 4, 5, 6)}
    all_anchors, _ = anc.pyramid_anchors(shapes, strides, sizes)
    return all_anchors


# -------------------------------------------------------------- proposals
def generate_proposals(outputs: Dict, anchors: jax.Array,
                       image_hw: Tuple[int, int],
                       pre_nms_top_n: int = 1000,
                       post_nms_top_n: int = 256,
                       nms_thresh: float = 0.7,
                       min_size: float = 1.0,
                       nms_impl: str = "auto") -> Tuple[jax.Array, jax.Array]:
    """(B, post_nms_top_n, 4) proposals + validity. Per-level pre-NMS
    top-k then joint NMS (rpn_function.py filter_proposals surface)."""
    level_counts = outputs["level_counts"]

    def per_image(obj, deltas):
        boxes = box_ops.decode_boxes(deltas, anchors)
        boxes = box_ops.clip_boxes(boxes, image_hw)
        valid = box_ops.remove_small_boxes_mask(boxes, min_size)
        scores = jnp.where(valid, obj, -1e9)
        # per-level top-k
        sel_boxes, sel_scores = [], []
        start = 0
        for count in level_counts:
            k = min(pre_nms_top_n, count)
            s_lvl = jax.lax.dynamic_slice_in_dim(scores, start, count)
            b_lvl = jax.lax.dynamic_slice_in_dim(boxes, start, count)
            top_s, top_i = jax.lax.top_k(s_lvl, k)
            sel_boxes.append(b_lvl[top_i])
            sel_scores.append(top_s)
            start += count
        cand_boxes = jnp.concatenate(sel_boxes, axis=0)
        cand_scores = jnp.concatenate(sel_scores, axis=0)
        keep_idx, keep_valid = nms_ops.nms(cand_boxes, cand_scores,
                                           nms_thresh, post_nms_top_n,
                                           score_threshold=-1e8,
                                           impl=nms_impl)
        props, = nms_ops.gather_nms_outputs(keep_idx, keep_valid, cand_boxes)
        return props, keep_valid

    return jax.vmap(per_image)(outputs["rpn_obj"], outputs["rpn_deltas"])


# ----------------------------------------------------------------- losses
def rpn_loss(outputs: Dict, anchors: jax.Array, gt_boxes: jax.Array,
             gt_valid: jax.Array, rng: jax.Array,
             batch_per_image: int = 256, positive_fraction: float = 0.5
             ) -> Dict[str, jax.Array]:
    def per_image(obj, deltas, boxes, valid, key):
        iou = box_ops.box_iou(boxes, anchors)
        matches = M.match_anchors(iou, valid, 0.7, 0.3,
                                  allow_low_quality=True)
        pos, neg = M.balanced_sample(matches, key, batch_per_image,
                                     positive_fraction)
        labels = (matches >= 0).astype(jnp.float32)
        sample = pos | neg
        obj_loss = L.binary_cross_entropy(obj, labels, weights=sample)
        safe = jnp.maximum(matches, 0)
        reg_targets = box_ops.encode_boxes(boxes[safe], anchors)
        reg_loss = L.smooth_l1(deltas, reg_targets, beta=1.0 / 9,
                               reduction="none")
        reg_loss = jnp.sum(reg_loss * pos[:, None]) / jnp.maximum(
            jnp.sum(sample), 1)
        return obj_loss, reg_loss

    keys = jax.random.split(rng, gt_boxes.shape[0])
    obj_l, reg_l = jax.vmap(per_image)(
        outputs["rpn_obj"], outputs["rpn_deltas"], gt_boxes, gt_valid, keys)
    return {"rpn_obj_loss": jnp.mean(obj_l),
            "rpn_reg_loss": jnp.mean(reg_l)}


def sample_rois(proposals: jax.Array, prop_valid: jax.Array,
                gt_boxes: jax.Array, gt_labels: jax.Array,
                gt_valid: jax.Array, rng: jax.Array,
                batch_per_image: int = 128, positive_fraction: float = 0.25
                ) -> Dict[str, jax.Array]:
    """Append gt to proposals (roi_head.py add_gt_boxes), match at 0.5,
    build per-roi cls/reg targets + sampled weight masks."""
    def per_image(props, pvalid, boxes, labels, valid, key):
        all_props = jnp.concatenate([props, boxes], axis=0)
        all_valid = jnp.concatenate([pvalid, valid], axis=0)
        iou = box_ops.box_iou(boxes, all_props)
        iou = jnp.where(all_valid[None, :], iou, -1.0)
        matches = M.match_anchors(iou, valid, 0.5, 0.5,
                                  allow_low_quality=False)
        # padded proposal slots must not be sampled as negatives: mark
        # them ignore (BETWEEN) so balanced_sample skips them
        matches = jnp.where(all_valid, matches, M.BETWEEN)
        pos, neg = M.balanced_sample(matches, key, batch_per_image,
                                     positive_fraction)
        safe = jnp.maximum(matches, 0)
        cls_target = jnp.where(matches >= 0, labels[safe], 0)  # 0 = bg
        reg_target = box_ops.encode_boxes(boxes[safe], all_props,
                                          weights=(10, 10, 5, 5))
        return {"rois": all_props, "cls_target": cls_target,
                "reg_target": reg_target, "pos": pos, "sample": pos | neg}

    keys = jax.random.split(rng, proposals.shape[0])
    return jax.vmap(per_image)(proposals, prop_valid, gt_boxes, gt_labels,
                               gt_valid, keys)


def roi_head_loss(roi_scores: jax.Array, roi_deltas: jax.Array,
                  samples: Dict[str, jax.Array]) -> Dict[str, jax.Array]:
    """fastrcnn_loss (roi_head.py:11): CE over sampled rois + smooth-L1 on
    positives' matched-class deltas."""
    def per_image(scores, deltas, cls_t, reg_t, pos, sample):
        cls_loss = L.cross_entropy(scores, cls_t, weights=sample)
        per_class = jnp.take_along_axis(
            deltas, cls_t[:, None, None].repeat(4, -1), axis=1)[:, 0]
        reg = L.smooth_l1(per_class, reg_t, beta=1.0, reduction="none")
        reg_loss = jnp.sum(reg * pos[:, None]) / jnp.maximum(
            jnp.sum(sample), 1)
        return cls_loss, reg_loss

    cls_l, reg_l = jax.vmap(per_image)(
        roi_scores, roi_deltas, samples["cls_target"],
        samples["reg_target"], samples["pos"], samples["sample"])
    return {"roi_cls_loss": jnp.mean(cls_l),
            "roi_reg_loss": jnp.mean(reg_l)}


def fasterrcnn_postprocess(roi_scores: jax.Array, roi_deltas: jax.Array,
                           proposals: jax.Array, image_hw: Tuple[int, int],
                           prop_valid: Optional[jax.Array] = None,
                           score_thresh: float = 0.05,
                           nms_thresh: float = 0.5,
                           max_det: int = 100,
                           nms_impl: str = "auto") -> Dict[str, jax.Array]:
    """Softmax → per-class decode → class-aware NMS → fixed max_det
    (roi_head.py:295-326 postprocess_detections surface). ``prop_valid``
    masks padded proposal slots out of the candidate pool (zero-area
    padded boxes do not suppress each other in NMS, so they MUST be
    masked here)."""
    num_classes = roi_scores.shape[-1]
    if prop_valid is None:
        prop_valid = jnp.ones(proposals.shape[:2], bool)

    def per_image(scores, deltas, props, pvalid):
        probs = jax.nn.softmax(scores, axis=-1)          # (P, C)
        p = props.shape[0]
        # expand (P, C-1) foreground candidates; invalid slots -> -inf
        fg_probs = jnp.where(pvalid[:, None], probs[:, 1:],
                             -jnp.inf).reshape(-1)
        classes = jnp.tile(jnp.arange(1, num_classes), p)
        boxes = box_ops.decode_boxes(
            deltas[:, 1:].reshape(-1, 4),
            jnp.repeat(props, num_classes - 1, axis=0),
            weights=(10, 10, 5, 5))
        boxes = box_ops.clip_boxes(boxes, image_hw)
        keep_idx, keep_valid = nms_ops.batched_nms(
            boxes, fg_probs, classes, nms_thresh, max_det,
            score_threshold=score_thresh, impl=nms_impl)
        out_boxes, out_scores, out_classes = nms_ops.gather_nms_outputs(
            keep_idx, keep_valid, boxes, fg_probs, classes,
            fill=(0, 0, -1))
        return out_boxes, out_scores, out_classes, keep_valid

    boxes, scores, classes, valid = jax.vmap(per_image)(
        roi_scores, roi_deltas, proposals, prop_valid)
    return {"boxes": boxes, "scores": scores, "labels": classes,
            "valid": valid}


@MODELS.register("fasterrcnn_resnet50_fpn")
def fasterrcnn_resnet50_fpn(num_classes: int = 21, **kw):
    return FasterRCNN(num_classes=num_classes, **kw)


@MODELS.register("fasterrcnn_resnet18_fpn")
def fasterrcnn_resnet18_fpn(num_classes: int = 21, **kw):
    return FasterRCNN(num_classes=num_classes,
                      backbone_sizes=(2, 2, 2, 2), **kw)
