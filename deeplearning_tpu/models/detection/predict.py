"""Family-dispatch inference builder for every registry detector.

The eval half of ``tools/train_detection.build_task`` (retinanet /
yolox / yolov5 / fcos / fasterrcnn), moved into the package so
non-training surfaces — the serving engine (``deeplearning_tpu.serve``),
``tools/predict.py``, ``tools/demo.py`` — can build a fixed-shape
postprocessed forward without importing a training CLI. ``build_task``
delegates its predict halves here; there is exactly ONE definition of
"run this detector and decode its boxes" in the repo.

Every returned ``predict_fn(params, batch_stats, images)`` is pure and
jit/AOT-friendly: fixed ``max_det`` output slots, padded rows carrying
class −1 (the PR 3 padding convention — never a real class), and the
image size read from the traced batch shape so grids/anchors rebuild per
static bucket.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp

__all__ = ["build_predict_fn", "is_detection_model", "DETECTION_PREFIXES"]

DETECTION_PREFIXES = ("retinanet", "yolox", "yolov5", "fcos", "fasterrcnn")


def is_detection_model(name: str) -> bool:
    """True when ``name`` belongs to a detection family this module can
    postprocess (the task auto-detect used by serve/ and predict.py)."""
    return name.startswith(DETECTION_PREFIXES)


def build_predict_fn(model, name: str, num_classes: int, *,
                     score_thresh: float = 0.05, max_det: int = 100,
                     post_nms_top_n: int = 256,
                     nms_impl: str = "auto") -> Callable:
    """Return ``predict_fn(params, batch_stats, images) -> det dict``
    ({boxes, scores, labels, valid}, fixed shapes) for any registry
    detector. ``post_nms_top_n`` sizes the fasterrcnn proposal stage;
    ``nms_impl`` selects the suppression path (ops/nms.py) for every
    family."""

    def apply_eval(params, stats, images, **kw):
        return model.apply({"params": params, "batch_stats": stats},
                           images, train=False, **kw)

    if name.startswith("retinanet"):
        from .retinanet import retinanet_anchors, retinanet_postprocess

        def predict_fn(params, stats, images):
            hw = images.shape[1:3]
            out = apply_eval(params, stats, images)
            return retinanet_postprocess(
                out, jnp.asarray(retinanet_anchors(hw)), hw,
                max_det=max_det, score_thresh=score_thresh,
                nms_impl=nms_impl)
        return predict_fn

    if name.startswith("yolox"):
        from .yolox import yolox_grid, yolox_postprocess

        def predict_fn(params, stats, images):
            hw = images.shape[1:3]
            centers, strides = (jnp.asarray(a) for a in yolox_grid(hw))
            out = apply_eval(params, stats, images)
            return yolox_postprocess(out, centers, strides,
                                     max_det=max_det,
                                     score_thresh=score_thresh,
                                     nms_impl=nms_impl)
        return predict_fn

    if name.startswith("yolov5"):
        from .yolov5 import yolov5_grid, yolov5_postprocess

        def predict_fn(params, stats, images):
            hw = images.shape[1:3]
            grid = {k: jnp.asarray(v) for k, v in yolov5_grid(hw).items()}
            out = apply_eval(params, stats, images)
            return yolov5_postprocess(out, grid, max_det=max_det,
                                      score_thresh=score_thresh,
                                      nms_impl=nms_impl)
        return predict_fn

    if name.startswith("fcos"):
        from .fcos import fcos_locations, fcos_postprocess

        def predict_fn(params, stats, images):
            hw = images.shape[1:3]
            locs, _ = fcos_locations(hw)
            out = apply_eval(params, stats, images)
            return fcos_postprocess(out, jnp.asarray(locs), hw,
                                    max_det=max_det,
                                    score_thresh=score_thresh,
                                    nms_impl=nms_impl)
        return predict_fn

    if name.startswith("fasterrcnn"):
        # two-stage: proposals from the RPN heads, RoI stage on the SAME
        # pyramid (no backbone recompute). The model's class space is
        # num_classes+1 with 0 = background; detections shift -1 back to
        # the caller's 0-based foreground ids.
        from .faster_rcnn import (fasterrcnn_anchors,
                                  fasterrcnn_postprocess,
                                  generate_proposals)

        def predict_fn(params, stats, images):
            hw = images.shape[1:3]
            anchors = jnp.asarray(fasterrcnn_anchors(hw))
            out = apply_eval(params, stats, images)
            props, pvalid = generate_proposals(
                out, anchors, hw, post_nms_top_n=post_nms_top_n,
                nms_impl=nms_impl)
            out2 = apply_eval(params, stats, images, proposals=props,
                              pyramid=out["pyramid"])
            det = fasterrcnn_postprocess(
                out2["roi_scores"], out2["roi_deltas"], props, hw,
                prop_valid=pvalid, score_thresh=score_thresh,
                max_det=max_det, nms_impl=nms_impl)
            det["labels"] = det["labels"] - 1      # back to 0-based fg
            return det
        return predict_fn

    raise ValueError(f"no detection predict path for model {name!r} "
                     "(expected retinanet*/fasterrcnn*/yolox*/yolov5*/"
                     "fcos*)")
