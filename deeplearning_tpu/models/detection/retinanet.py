"""RetinaNet: one-stage focal-loss detector on ResNet-FPN.

Surface of detection/RetinaNet: RetinaNetClassificationHead
(network_files/retinanet.py:23 — 4 convs + K*A sigmoid logits, prior-prob
bias init), RetinaNetRegressionHead (:120 — 4 convs + 4*A deltas),
RetinaNet (:238, forward :480: backbone→FPN p3-p7→heads→anchors→
loss/postprocess), sigmoid focal loss (network_files/losses.py:5),
anchor machinery (network_files/anchor_utils.py), Matcher thresholds
0.5/0.4 with low-quality matches.

TPU-first: the whole model is one jittable function over fixed-size
inputs; gt boxes come padded (MAX_GT, 4) + validity mask; postprocess
returns fixed (max_det) boxes + validity — no dynamic shapes anywhere.
"""

from __future__ import annotations

import math
from typing import Any, Dict, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from ...core.registry import MODELS
from ...ops import anchors as anc
from ...ops import boxes as box_ops
from ...ops import losses as L
from ...ops import matcher as M
from ...ops import nms as nms_ops
from ..classification.resnet import ResNet


class RetinaHead(nn.Module):
    """Shared-conv classification or regression tower."""
    num_outputs: int               # K*A or 4*A
    num_convs: int = 4
    channels: int = 256
    prior_bias: Optional[float] = None   # classification prior init
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        for i in range(self.num_convs):
            x = nn.Conv(self.channels, (3, 3), padding="SAME",
                        dtype=self.dtype, name=f"conv{i}")(x)
            x = nn.relu(x)
        bias_init = nn.initializers.zeros
        if self.prior_bias is not None:
            bias_init = nn.initializers.constant(self.prior_bias)
        return nn.Conv(self.num_outputs, (3, 3), padding="SAME",
                       dtype=self.dtype, bias_init=bias_init,
                       kernel_init=nn.initializers.normal(0.01),
                       name="pred")(x)


class RetinaNet(nn.Module):
    num_classes: int = 20
    backbone_sizes: Sequence[int] = (3, 4, 6, 3)     # resnet50
    anchors_per_loc: int = 9
    fpn_channels: int = 256
    dtype: Any = jnp.bfloat16
    backbone_frozen_bn: bool = False   # FrozenBatchNorm2d backbone stats
                                       # (resnet50_fpn.py:5)

    @nn.compact
    def __call__(self, images: jax.Array, train: bool = False
                 ) -> Dict[str, Any]:
        from .fpn import FPN
        backbone = ResNet(stage_sizes=self.backbone_sizes,
                          return_features=True, dtype=self.dtype,
                          frozen_bn=self.backbone_frozen_bn,
                          name="backbone")
        feats = backbone(images, train=train)
        feats = {k: v for k, v in feats.items() if k in ("c3", "c4", "c5")}
        pyramid = FPN(self.fpn_channels, extra_levels="p6p7",
                      dtype=self.dtype, name="fpn")(feats)

        cls_head = RetinaHead(
            self.num_classes * self.anchors_per_loc,
            prior_bias=-math.log((1 - 0.01) / 0.01),
            dtype=self.dtype, name="cls_head")
        reg_head = RetinaHead(4 * self.anchors_per_loc, dtype=self.dtype,
                              name="reg_head")

        cls_logits, bbox_deltas, shapes = [], [], {}
        for name in sorted(pyramid, key=lambda k: int(k[1:])):
            f = pyramid[name]
            shapes[name] = f.shape[1:3]
            b = f.shape[0]
            cls_logits.append(cls_head(f).reshape(
                b, -1, self.num_classes).astype(jnp.float32))
            bbox_deltas.append(reg_head(f).reshape(b, -1, 4).astype(
                jnp.float32))
        return {
            "cls_logits": jnp.concatenate(cls_logits, axis=1),
            "bbox_deltas": jnp.concatenate(bbox_deltas, axis=1),
            "feature_shapes": shapes,
        }


def retinanet_anchors(image_hw: Tuple[int, int]) -> np.ndarray:
    """All-level anchors for a fixed image size (host-side constant)."""
    h, w = image_hw
    shapes = {f"p{l}": (math.ceil(h / 2 ** l), math.ceil(w / 2 ** l))
              for l in (3, 4, 5, 6, 7)}
    strides = {k: 2 ** int(k[1]) for k in shapes}
    all_anchors, _ = anc.pyramid_anchors(shapes, strides,
                                         anc.retinanet_sizes())
    return all_anchors


def retinanet_loss(outputs: Dict, anchors: jax.Array, gt_boxes: jax.Array,
                   gt_labels: jax.Array, gt_valid: jax.Array
                   ) -> Dict[str, jax.Array]:
    """Focal cls loss over all non-ignored anchors + plain L1 on positives
    (RetinaNet compute_loss surface; matcher 0.5/0.4 w/ low-quality;
    the reference regression loss is F.l1_loss, retinanet.py:188-193,
    NOT smooth-L1 — both normalized per image by num_foreground then
    averaged over the batch).

    gt_boxes (B, G, 4); gt_labels (B, G) int; gt_valid (B, G) bool.
    """
    num_classes = outputs["cls_logits"].shape[-1]

    def per_image(cls_logits, deltas, boxes, labels, valid):
        iou = box_ops.box_iou(boxes, anchors)           # (G, A)
        matches = M.match_anchors(iou, valid, 0.5, 0.4,
                                  allow_low_quality=True)
        pos = matches >= 0
        ignore = matches == M.BETWEEN
        safe = jnp.maximum(matches, 0)
        target_cls = jax.nn.one_hot(labels[safe], num_classes) \
            * pos[:, None]
        cls_loss = L.sigmoid_focal_loss(
            cls_logits, target_cls, reduction="none")
        cls_loss = jnp.sum(cls_loss * (~ignore)[:, None])
        reg_targets = box_ops.encode_boxes(boxes[safe], anchors)
        reg_loss = jnp.sum(jnp.abs(deltas - reg_targets) * pos[:, None])
        num_pos = jnp.maximum(jnp.sum(pos), 1)
        return cls_loss / num_pos, reg_loss / num_pos

    cls_l, reg_l = jax.vmap(per_image)(
        outputs["cls_logits"], outputs["bbox_deltas"],
        gt_boxes, gt_labels, gt_valid)
    return {"cls_loss": jnp.mean(cls_l), "reg_loss": jnp.mean(reg_l)}


def retinanet_postprocess(outputs: Dict, anchors: jax.Array,
                          image_hw: Tuple[int, int],
                          score_thresh: float = 0.05,
                          nms_thresh: float = 0.5,
                          topk_candidates: int = 1000,
                          max_det: int = 100,
                          nms_impl: str = "auto") -> Dict[str, jax.Array]:
    """Decode → top-k per image → class-aware NMS → fixed max_det outputs
    (RetinaNet postprocess_detections surface, fixed-shape).

    ``nms_impl`` selects the NMS path (see ``ops.nms.nms``): "auto"
    routes the 1000-candidate set through the blocked sweep (Pallas
    kernel on TPU); "greedy" keeps the reference scan selectable."""

    def per_image(cls_logits, deltas):
        scores_all = jax.nn.sigmoid(cls_logits)          # (A, K)
        flat = scores_all.reshape(-1)
        k = min(topk_candidates, flat.shape[0])
        top_scores, top_idx = jax.lax.top_k(flat, k)
        anchor_idx = top_idx // cls_logits.shape[-1]
        class_idx = top_idx % cls_logits.shape[-1]
        boxes = box_ops.decode_boxes(deltas[anchor_idx],
                                     anchors[anchor_idx])
        boxes = box_ops.clip_boxes(boxes, image_hw)
        keep_idx, keep_valid = nms_ops.batched_nms(
            boxes, top_scores, class_idx, nms_thresh, max_det,
            score_threshold=score_thresh, impl=nms_impl)
        # padded slots: boxes/scores 0, class -1 (never a real class-0)
        out_boxes, out_scores, out_classes = nms_ops.gather_nms_outputs(
            keep_idx, keep_valid, boxes, top_scores, class_idx,
            fill=(0, 0, -1))
        return out_boxes, out_scores, out_classes, keep_valid

    boxes, scores, classes, valid = jax.vmap(per_image)(
        outputs["cls_logits"], outputs["bbox_deltas"])
    return {"boxes": boxes, "scores": scores, "labels": classes,
            "valid": valid}


@MODELS.register("retinanet_resnet50_fpn")
def retinanet_resnet50_fpn(num_classes: int = 20, **kw):
    return RetinaNet(num_classes=num_classes, **kw)


@MODELS.register("retinanet_resnet18_fpn")
def retinanet_resnet18_fpn(num_classes: int = 20, **kw):
    # small variant for tests/smoke
    return RetinaNet(num_classes=num_classes, backbone_sizes=(2, 2, 2, 2),
                     **kw)
