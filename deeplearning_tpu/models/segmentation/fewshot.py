"""Few-shot segmentation: SSP (self-support prototypes).

Surface of Image_segmentation/few_shot_segmentation (models/sspnet.py:
support/query episodes, masked average pooling of support features into
fg/bg prototypes, cosine-similarity matching, self-support refinement —
query pixels confidently matched become additional prototypes).
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from ...core.registry import MODELS
from ..classification.resnet import ResNet


def masked_average_pool(feats: jax.Array, mask: jax.Array) -> jax.Array:
    """(B, H, W, C) features + (B, H, W) {0,1} mask → (B, C) prototype."""
    m = mask[..., None].astype(feats.dtype)
    return jnp.sum(feats * m, axis=(1, 2)) / jnp.maximum(
        jnp.sum(m, axis=(1, 2)), 1e-6)


def cosine_similarity_map(feats: jax.Array, proto: jax.Array) -> jax.Array:
    """(B, H, W, C) × (B, C) → (B, H, W) cosine similarity."""
    from ...ops.losses import safe_normalize
    f = safe_normalize(feats, axis=-1)   # NaN-safe at zero features
    p = safe_normalize(proto, axis=-1)
    return jnp.einsum("bhwc,bc->bhw", f, p)


class SSPNet(nn.Module):
    """1-way k-shot episode segmenter."""
    backbone_sizes: Tuple[int, ...] = (2, 2, 2, 2)
    refine_thresh_fg: float = 0.7
    refine_thresh_bg: float = 0.6
    dtype: Any = jnp.bfloat16

    def setup(self):
        self.encoder = ResNet(stage_sizes=self.backbone_sizes,
                              block="basic", return_features=True,
                              dtype=self.dtype, name="encoder")

    def encode(self, x, train: bool = False):
        return self.encoder(x, train=train)["c4"]     # stride 16 features

    def __call__(self, support_img, support_mask, query_img,
                 train: bool = False):
        """support_img (B, S, H, W, 3); support_mask (B, S, H, W);
        query (B, H, W, 3) → logits (B, H, W, 2)."""
        b, s = support_img.shape[:2]
        sup = self.encode(support_img.reshape((-1,) + support_img.shape[2:]),
                          train)
        _, fh, fw, c = sup.shape
        sup = sup.reshape(b, s, fh, fw, c)
        m = jax.image.resize(support_mask.astype(jnp.float32),
                             (b, s, fh, fw), "nearest")
        # k-shot prototypes averaged over shots
        fg_proto = masked_average_pool(
            sup.reshape(b * s, fh, fw, c),
            m.reshape(b * s, fh, fw)).reshape(b, s, c).mean(1)
        bg_proto = masked_average_pool(
            sup.reshape(b * s, fh, fw, c),
            1 - m.reshape(b * s, fh, fw)).reshape(b, s, c).mean(1)

        q = self.encode(query_img, train)
        fg_sim = cosine_similarity_map(q, fg_proto)
        bg_sim = cosine_similarity_map(q, bg_proto)

        # self-support refinement: confident query pixels augment protos
        conf_fg = (fg_sim > self.refine_thresh_fg).astype(jnp.float32)
        conf_bg = (bg_sim > self.refine_thresh_bg).astype(jnp.float32)
        ssp_fg = masked_average_pool(q, conf_fg)
        ssp_bg = masked_average_pool(q, conf_bg)
        has_fg = (jnp.sum(conf_fg, axis=(1, 2)) > 0)[:, None]
        has_bg = (jnp.sum(conf_bg, axis=(1, 2)) > 0)[:, None]
        fg_proto = jnp.where(has_fg, 0.5 * fg_proto + 0.5 * ssp_fg,
                             fg_proto)
        bg_proto = jnp.where(has_bg, 0.5 * bg_proto + 0.5 * ssp_bg,
                             bg_proto)
        fg_sim = cosine_similarity_map(q, fg_proto)
        bg_sim = cosine_similarity_map(q, bg_proto)

        logits = jnp.stack([bg_sim, fg_sim], axis=-1) * 10.0   # temp
        bq, hq, wq, _ = query_img.shape
        return jax.image.resize(logits, (bq, hq, wq, 2), "bilinear")


@MODELS.register("sspnet_resnet18")
def sspnet_resnet18(**kw):
    return SSPNet(**kw)
