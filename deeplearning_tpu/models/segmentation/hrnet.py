"""HRNet: parallel multi-resolution streams with cross-resolution fusion.

Surface of Image_segmentation/HR-Net-Seg (models/seg_hrnet.py HRNet-W18/48)
and the pose_estimation/Insulator backbone (models/hrnet.py) — the same
trunk serves segmentation (concat-upsampled head) and keypoint heatmaps
(K-channel head), selected by ``head``.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

from ...ops.padding import torch_pad
from ...core.registry import MODELS


class ConvBN(nn.Module):
    features: int
    kernel: int = 3
    stride: int = 1
    relu: bool = True
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        # torch padding semantics (SAME pads (0,1) at stride 2, which
        # shifts sampling centers vs the reference)
        x = nn.Conv(self.features, (self.kernel,) * 2,
                    strides=(self.stride,) * 2,
                    padding=torch_pad(self.kernel),
                    use_bias=False, dtype=self.dtype, name="conv")(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         dtype=self.dtype, name="bn")(x)
        return nn.relu(x) if self.relu else x


class BasicResBlock(nn.Module):
    features: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        y = ConvBN(self.features, dtype=self.dtype, name="c1")(x, train)
        y = ConvBN(self.features, relu=False, dtype=self.dtype,
                   name="c2")(y, train)
        if x.shape[-1] != self.features:
            x = ConvBN(self.features, kernel=1, relu=False,
                       dtype=self.dtype, name="proj")(x, train)
        return nn.relu(x + y)


class FuseLayer(nn.Module):
    """Exchange info across resolution streams: down via strided conv,
    up via 1x1 + bilinear resize."""
    widths: Sequence[int]
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, streams, train: bool = False):
        n = len(streams)
        outs = []
        for i in range(n):
            acc = None
            for j in range(n):
                y = streams[j]
                if j > i:        # upsample j -> i
                    y = ConvBN(self.widths[i], kernel=1, relu=False,
                               dtype=self.dtype, name=f"up{j}to{i}")(
                        y, train)
                    b, h, w, c = streams[i].shape
                    y = jax.image.resize(y, (b, h, w, c), "bilinear")
                elif j < i:      # downsample j -> i by repeated stride-2
                    for k in range(i - j):
                        last = k == i - j - 1
                        y = ConvBN(self.widths[i] if last
                                   else self.widths[j], stride=2,
                                   relu=not last, dtype=self.dtype,
                                   name=f"down{j}to{i}_{k}")(y, train)
                acc = y if acc is None else acc + y
            outs.append(nn.relu(acc))
        return outs


class HRNet(nn.Module):
    num_classes: int = 19
    base_width: int = 18            # W18; W48 for the large variant
    head: str = "seg"               # 'seg' | 'keypoints' | 'features'
    blocks_per_stage: int = 2
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        w = self.base_width
        widths = [w, w * 2, w * 4, w * 8]
        in_h, in_w = x.shape[1:3]
        x = x.astype(self.dtype)
        x = ConvBN(64, stride=2, dtype=self.dtype, name="stem1")(x, train)
        x = ConvBN(64, stride=2, dtype=self.dtype, name="stem2")(x, train)

        streams = [x]
        for stage in range(4):
            # add a new lower-resolution stream
            if stage > 0:
                streams.append(ConvBN(widths[stage], stride=2,
                                      dtype=self.dtype,
                                      name=f"trans{stage}")(
                    streams[-1], train))
            # width-align + residual blocks per stream
            new_streams = []
            for si, s in enumerate(streams):
                for bi in range(self.blocks_per_stage):
                    s = BasicResBlock(widths[si], self.dtype,
                                      name=f"s{stage}_r{si}_b{bi}")(s, train)
                new_streams.append(s)
            streams = new_streams
            if stage > 0:
                streams = FuseLayer(widths[:len(streams)], self.dtype,
                                    name=f"fuse{stage}")(streams, train)

        if self.head == "features":
            return streams
        # upsample all to the highest resolution and concat
        b, h, wd, _ = streams[0].shape
        ups = [streams[0]]
        for s in streams[1:]:
            ups.append(jax.image.resize(
                s, (b, h, wd, s.shape[-1]), "bilinear"))
        y = jnp.concatenate(ups, axis=-1)
        y = ConvBN(sum(widths), kernel=1, dtype=self.dtype,
                   name="head_conv")(y, train)
        y = nn.Conv(self.num_classes, (1, 1), dtype=self.dtype,
                    name="cls")(y)
        if self.head == "seg":
            y = jax.image.resize(y.astype(jnp.float32),
                                 (b, in_h, in_w, self.num_classes),
                                 "bilinear")
            return y
        return y.astype(jnp.float32)     # keypoints: heatmaps at stride 4


@MODELS.register("hrnet_w18_seg")
def hrnet_w18_seg(num_classes: int = 19, **kw):
    return HRNet(num_classes=num_classes, base_width=18, head="seg", **kw)


@MODELS.register("hrnet_w48_seg")
def hrnet_w48_seg(num_classes: int = 19, **kw):
    return HRNet(num_classes=num_classes, base_width=48, head="seg", **kw)


# the keypoint-head variants live in models/pose/ (pose_estimation/
# Insulator parity) and reuse this HRNet trunk
