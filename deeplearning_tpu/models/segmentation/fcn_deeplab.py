"""FCN-ResNet and DeepLabV3/V3+ semantic segmentation heads.

Surface of Image_segmentation/FCN (FCN-ResNet50 with aux head,
utils/train_and_eval.py:6 main+aux CE), DeepLabV3 (models/deeplabv3.py
ASPP over dilated ResNet) and DeepLabV3Plus (encoder-decoder with
low-level feature fusion). The backbone is the shared ResNet in dilated
mode (output stride 8/16 via dilation instead of stride, the standard
segmentation trick).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, Optional, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

from ...core.registry import MODELS
from ..classification.resnet import ResNet


class FCNHead(nn.Module):
    channels: int
    num_classes: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.Conv(self.channels, (3, 3), padding="SAME", use_bias=False,
                    dtype=self.dtype, name="conv")(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         dtype=self.dtype, name="bn")(x)
        x = nn.relu(x)
        x = nn.Dropout(0.1, deterministic=not train)(x)
        return nn.Conv(self.num_classes, (1, 1), dtype=self.dtype,
                       name="cls")(x)


class ASPP(nn.Module):
    """Atrous spatial pyramid pooling (deeplabv3 surface)."""
    channels: int = 256
    rates: Sequence[int] = (12, 24, 36)
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, dtype=self.dtype)
        branches = []
        y = nn.Conv(self.channels, (1, 1), use_bias=False,
                    dtype=self.dtype, name="b0")(x)
        branches.append(nn.relu(norm(name="b0_bn")(y)))
        for i, r in enumerate(self.rates):
            y = nn.Conv(self.channels, (3, 3), padding="SAME",
                        kernel_dilation=(r, r), use_bias=False,
                        dtype=self.dtype, name=f"b{i + 1}")(x)
            branches.append(nn.relu(norm(name=f"b{i + 1}_bn")(y)))
        # image-level pooling branch
        b, h, w, c = x.shape
        g = jnp.mean(x, axis=(1, 2), keepdims=True)
        g = nn.Conv(self.channels, (1, 1), use_bias=False,
                    dtype=self.dtype, name="pool")(g)
        g = nn.relu(norm(name="pool_bn")(g))
        g = jnp.broadcast_to(g, (b, h, w, self.channels))
        branches.append(g)
        y = jnp.concatenate(branches, axis=-1)
        y = nn.Conv(self.channels, (1, 1), use_bias=False,
                    dtype=self.dtype, name="project")(y)
        y = nn.relu(norm(name="project_bn")(y))
        return nn.Dropout(0.5, deterministic=not train)(y)


class SegModel(nn.Module):
    """Backbone + head with logits upsampled to input size; optional aux
    head from c4 (FCN aux surface)."""
    num_classes: int
    head: str = "fcn"               # 'fcn' | 'deeplabv3' | 'deeplabv3plus'
    backbone_sizes: Sequence[int] = (3, 4, 6, 3)
    aux: bool = True
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        b, h, w, _ = x.shape
        feats = ResNet(stage_sizes=self.backbone_sizes,
                       return_features=True, dtype=self.dtype,
                       name="backbone")(x, train=train)
        c4, c5 = feats["c4"], feats["c5"]
        if self.head == "fcn":
            logits = FCNHead(512, self.num_classes, self.dtype,
                             name="head")(c5, train)
        elif self.head == "deeplabv3":
            y = ASPP(dtype=self.dtype, name="aspp")(c5, train)
            logits = nn.Conv(self.num_classes, (1, 1), dtype=self.dtype,
                             name="cls")(y)
        elif self.head == "deeplabv3plus":
            y = ASPP(dtype=self.dtype, name="aspp")(c5, train)
            yb, yh, yw, yc = y.shape
            low = feats["c2"]
            lb, lh, lw, lc = low.shape
            y = jax.image.resize(y, (yb, lh, lw, yc), "bilinear")
            low = nn.Conv(48, (1, 1), use_bias=False, dtype=self.dtype,
                          name="low_proj")(low)
            low = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                               dtype=self.dtype, name="low_bn")(low)
            low = nn.relu(low)
            y = jnp.concatenate([y, low], axis=-1)
            y = nn.Conv(256, (3, 3), padding="SAME", use_bias=False,
                        dtype=self.dtype, name="fuse")(y)
            y = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                             dtype=self.dtype, name="fuse_bn")(y)
            y = nn.relu(y)
            logits = nn.Conv(self.num_classes, (1, 1), dtype=self.dtype,
                             name="cls")(y)
        else:
            raise ValueError(self.head)
        logits = jax.image.resize(
            logits.astype(jnp.float32), (b, h, w, self.num_classes),
            "bilinear")
        if self.aux and train:
            aux_logits = FCNHead(256, self.num_classes, self.dtype,
                                 name="aux_head")(c4, train)
            aux_logits = jax.image.resize(
                aux_logits.astype(jnp.float32),
                (b, h, w, self.num_classes), "bilinear")
            return logits, aux_logits
        if self.aux:
            # params must exist under eval-mode init (harness convention)
            FCNHead(256, self.num_classes, self.dtype,
                    name="aux_head")(c4, train)
        return logits


@MODELS.register("fcn_resnet50")
def fcn_resnet50(num_classes: int = 21, **kw):
    return SegModel(num_classes=num_classes, head="fcn", **kw)


@MODELS.register("deeplabv3_resnet50")
def deeplabv3_resnet50(num_classes: int = 21, **kw):
    return SegModel(num_classes=num_classes, head="deeplabv3", **kw)


@MODELS.register("deeplabv3plus_resnet50")
def deeplabv3plus_resnet50(num_classes: int = 21, **kw):
    return SegModel(num_classes=num_classes, head="deeplabv3plus", **kw)
