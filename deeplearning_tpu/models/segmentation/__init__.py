from . import fcn_deeplab, fewshot, hrnet, unet  # noqa: F401
