from . import fcn_deeplab, hrnet, unet  # noqa: F401
