"""U-Net encoder-decoder for binary/multiclass segmentation.

Surface of Image_segmentation/U-Net (models/networks.py Down/Up blocks,
bilinear-upsample option, CE+dice training per train.py:107-138).
"""

from __future__ import annotations

from typing import Any, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

from ...core.registry import MODELS


class DoubleConv(nn.Module):
    features: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        for i in range(2):
            x = nn.Conv(self.features, (3, 3), padding="SAME",
                        use_bias=False, dtype=self.dtype,
                        name=f"conv{i}")(x)
            x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                             dtype=self.dtype, name=f"bn{i}")(x)
            x = nn.relu(x)
        return x


class UNet(nn.Module):
    num_classes: int = 2
    base_features: int = 64
    bilinear: bool = True
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        f = self.base_features
        x = x.astype(self.dtype)
        skips = []
        widths = [f, f * 2, f * 4, f * 8]
        for i, w in enumerate(widths):
            x = DoubleConv(w, self.dtype, name=f"down{i}")(x, train)
            skips.append(x)
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
        bottleneck_w = f * 16 // (2 if self.bilinear else 1)
        x = DoubleConv(bottleneck_w, self.dtype, name="bottleneck")(x, train)
        for i, (w, skip) in enumerate(zip(reversed(widths),
                                          reversed(skips))):
            b, h, wd, c = x.shape
            if self.bilinear:
                x = jax.image.resize(x, (b, h * 2, wd * 2, c), "bilinear")
            else:
                x = nn.ConvTranspose(c // 2, (2, 2), strides=(2, 2),
                                     dtype=self.dtype,
                                     name=f"up{i}_tconv")(x)
            x = jnp.concatenate([skip, x], axis=-1)
            out_w = w // (2 if self.bilinear and i < 3 else 1)
            x = DoubleConv(max(out_w, f), self.dtype,
                           name=f"up{i}")(x, train)
        x = nn.Conv(self.num_classes, (1, 1), dtype=self.dtype,
                    name="head")(x)
        return x.astype(jnp.float32)


@MODELS.register("unet")
def unet(num_classes: int = 2, **kw):
    return UNet(num_classes=num_classes, **kw)
