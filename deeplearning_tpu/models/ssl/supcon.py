"""Supervised-contrastive pretraining wrapper + SWA utility.

Surface of self-supervised/SupCon: encoder + 2-layer projection head
trained with SupConLoss (losses/SupConLoss.py:5 — see
ops/losses.supcon_loss), then a linear classifier fine-tune
(trainer/trainer.py:35 contrastive epoch / :100 CE epoch), stochastic
weight averaging (swa.py), and an LR-range finder (learning_rate_finder.py
— see train/lr_finder.py).
"""

from __future__ import annotations

from typing import Any, Callable

import flax.linen as nn
import jax
import jax.numpy as jnp

from ...core.registry import MODELS
from ..classification.resnet import ResNet


class SupConModel(nn.Module):
    """Backbone → normalized projection embedding (+ optional class head
    for the fine-tune phase)."""
    backbone: str = "resnet18"
    proj_dim: int = 128
    num_classes: int = 0            # >0 enables the classifier head
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False, mode: str = "projection"):
        sizes = {"resnet18": (2, 2, 2, 2), "resnet50": (3, 4, 6, 3)}
        block = "basic" if self.backbone == "resnet18" else "bottleneck"
        feats = ResNet(stage_sizes=sizes[self.backbone], block=block,
                       return_features=True, dtype=self.dtype,
                       name="encoder")(x, train=train)
        h = jnp.mean(feats["c5"].astype(jnp.float32), axis=(1, 2))
        # both heads always run so their params exist regardless of which
        # mode init was traced in (eval-mode init convention)
        z = nn.Dense(h.shape[-1], dtype=self.dtype, name="proj1")(
            h.astype(self.dtype))
        z = nn.relu(z)
        z = nn.Dense(self.proj_dim, dtype=self.dtype, name="proj2")(z)
        z = z.astype(jnp.float32)
        from ...ops.losses import safe_normalize
        z = safe_normalize(z, axis=-1)   # NaN-safe at z == 0
        logits = None
        if self.num_classes > 0:
            logits = nn.Dense(self.num_classes, dtype=self.dtype,
                              name="classifier")(h.astype(self.dtype)
                                                 ).astype(jnp.float32)
        if mode == "features":
            return h
        if mode == "classify":
            if logits is None:
                raise ValueError("num_classes must be set for classify mode")
            return logits
        return z


def swa_update(swa_params, params, n_averaged: int):
    """Running equal-weight average of params (SupCon swa.py surface) —
    call at each SWA checkpoint; returns (new_swa_params, n+1)."""
    if swa_params is None:
        return jax.tree.map(jnp.asarray, params), 1
    new = jax.tree.map(
        lambda s, p: s + (p.astype(s.dtype) - s) / (n_averaged + 1),
        swa_params, params)
    return new, n_averaged + 1


@MODELS.register("supcon_resnet18")
def supcon_resnet18(num_classes: int = 0, **kw):
    return SupConModel(backbone="resnet18", num_classes=num_classes, **kw)


@MODELS.register("supcon_resnet50")
def supcon_resnet50(num_classes: int = 0, **kw):
    return SupConModel(backbone="resnet50", num_classes=num_classes, **kw)
