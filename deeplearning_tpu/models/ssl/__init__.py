from . import mae, supcon  # noqa: F401
