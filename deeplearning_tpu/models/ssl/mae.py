"""Masked Autoencoder (MAE) pretraining on ViT.

Surface of self-supervised/MAE (models/MAE.py:7: forward :72 with
shuffle+mask at :85-86, mask_ratio=0.75, lightweight decoder, MSE on
masked patches :131-141; predict :144 reconstruction; LARS optimizer in
utils/LARS.py consumed via train/optim.py 'lars').

TPU-first: masking is a single gather by a per-image random permutation
(argsort of uniform noise — no boolean dynamic shapes); the encoder only
sees the kept tokens (real 4× FLOP saving at 75% masking), the decoder
sees kept tokens + learned mask tokens unshuffled back into place.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from ...core.registry import MODELS
from ..classification.vit import Block


def random_masking(x: jax.Array, mask_ratio: float, rng: jax.Array,
                   noise: Optional[jax.Array] = None
                   ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Per-image token shuffle-mask. x (B, N, C) → (kept (B, K, C),
    mask (B, N) 1=masked, restore_idx (B, N)). ``noise`` overrides the
    uniform draw (reproducible masking for tests/visualisation)."""
    b, n, c = x.shape
    keep = int(n * (1 - mask_ratio))
    if noise is None:
        noise = jax.random.uniform(rng, (b, n))
    shuffle = jnp.argsort(noise, axis=1)          # random perm per image
    restore = jnp.argsort(shuffle, axis=1)
    kept_idx = shuffle[:, :keep]
    kept = jnp.take_along_axis(x, kept_idx[:, :, None], axis=1)
    mask = jnp.take_along_axis(
        jnp.concatenate([jnp.zeros((b, keep), x.dtype),
                         jnp.ones((b, n - keep), x.dtype)], axis=1),
        restore, axis=1)
    return kept, mask, restore


def patchify(imgs: jax.Array, patch: int) -> jax.Array:
    """(B, H, W, C) → (B, N, patch²·C) pixel targets."""
    b, h, w, c = imgs.shape
    x = imgs.reshape(b, h // patch, patch, w // patch, patch, c)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(b, (h // patch) * (w // patch), patch * patch * c)


def unpatchify(x: jax.Array, patch: int, h: int, w: int, c: int = 3
               ) -> jax.Array:
    b, n, _ = x.shape
    x = x.reshape(b, h // patch, w // patch, patch, patch, c)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(b, h, w, c)


class MAE(nn.Module):
    patch_size: int = 16
    embed_dim: int = 768
    depth: int = 12
    num_heads: int = 12
    decoder_dim: int = 512
    decoder_depth: int = 8
    decoder_heads: int = 16
    mask_ratio: float = 0.75
    norm_pix_loss: bool = True
    dtype: Any = jnp.bfloat16
    attn_fn: Optional[Callable] = None   # e.g. make_ring_attn_fn(mesh)

    @nn.compact
    def __call__(self, imgs: jax.Array, train: bool = False,
                 rng: Optional[jax.Array] = None,
                 mask_noise: Optional[jax.Array] = None):
        """Returns (loss, pred_patches, mask). ``rng`` drives masking; in
        eval a fixed fold of the dropout rng is used. ``mask_noise``
        (B, N) overrides the random mask draw (tests/visualisation)."""
        if rng is None and mask_noise is None:
            rng = self.make_rng("masking")
        b, h, w, c = imgs.shape
        p = self.patch_size
        n = (h // p) * (w // p)

        # ---- encoder over kept tokens only
        x = nn.Conv(self.embed_dim, (p, p), strides=(p, p),
                    dtype=self.dtype, name="patch_embed")(
            imgs.astype(self.dtype))
        x = x.reshape(b, n, self.embed_dim)
        enc_pos = self.param("enc_pos",
                             nn.initializers.truncated_normal(0.02),
                             (1, n, self.embed_dim), jnp.float32)
        x = x + enc_pos.astype(x.dtype)
        kept, mask, restore = random_masking(x, self.mask_ratio, rng,
                                             noise=mask_noise)
        for i in range(self.depth):
            kept = Block(self.num_heads, dtype=self.dtype,
                         attn_fn=self.attn_fn,
                         name=f"enc_block{i}")(kept, deterministic=not train)
        kept = nn.LayerNorm(dtype=self.dtype, name="enc_norm")(kept)

        # ---- decoder over full token grid (mask tokens fill the holes)
        y = nn.Dense(self.decoder_dim, dtype=self.dtype,
                     name="dec_embed")(kept)
        mask_token = self.param("mask_token", nn.initializers.normal(0.02),
                                (1, 1, self.decoder_dim), jnp.float32)
        k = y.shape[1]
        fill = jnp.broadcast_to(mask_token.astype(y.dtype),
                                (b, n - k, self.decoder_dim))
        full = jnp.concatenate([y, fill], axis=1)
        full = jnp.take_along_axis(full, restore[:, :, None], axis=1)
        dec_pos = self.param("dec_pos",
                             nn.initializers.truncated_normal(0.02),
                             (1, n, self.decoder_dim), jnp.float32)
        full = full + dec_pos.astype(full.dtype)
        for i in range(self.decoder_depth):
            full = Block(self.decoder_heads, dtype=self.dtype,
                         attn_fn=self.attn_fn,
                         name=f"dec_block{i}")(full,
                                               deterministic=not train)
        full = nn.LayerNorm(dtype=self.dtype, name="dec_norm")(full)
        pred = nn.Dense(p * p * c, dtype=self.dtype,
                        name="dec_pred")(full).astype(jnp.float32)

        # ---- MSE on masked patches only (MAE.py:131-141)
        target = patchify(imgs, p).astype(jnp.float32)
        if self.norm_pix_loss:
            mean = target.mean(axis=-1, keepdims=True)
            var = target.var(axis=-1, keepdims=True)
            target = (target - mean) / jnp.sqrt(var + 1e-6)
        per_patch = jnp.mean(jnp.square(pred - target), axis=-1)
        maskf = mask.astype(jnp.float32)
        loss = jnp.sum(per_patch * maskf) / jnp.maximum(jnp.sum(maskf), 1)
        return loss, pred, mask

    def reconstruct(self, variables, imgs, rng):
        """predict() surface (MAE.py:144): masked-patch reconstruction
        composited over the visible original."""
        loss, pred, mask = self.apply(variables, imgs, train=False, rng=rng)
        b, h, w, c = imgs.shape
        p = self.patch_size
        recon = unpatchify(pred, p, h, w, c)
        m = mask.reshape(b, h // p, w // p)
        m = jnp.repeat(jnp.repeat(m, p, axis=1), p, axis=2)[..., None]
        return imgs * (1 - m) + recon * m


@MODELS.register("mae_vit_base_patch16")
def mae_vit_base_patch16(**kw):
    return MAE(**kw)


@MODELS.register("mae_vit_small_patch16")
def mae_vit_small_patch16(**kw):
    defaults = dict(embed_dim=384, depth=6, num_heads=6, decoder_dim=256,
                    decoder_depth=4, decoder_heads=8)
    return MAE(**{**defaults, **kw})
