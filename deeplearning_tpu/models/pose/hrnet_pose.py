"""HRNet keypoint (pose) models — pose_estimation/Insulator parity.

The reference project (pose_estimation/Insulator: models/hrnet.py,
utils/loss.py:6 KpLoss) predicts per-joint heatmaps at stride 4 from an
HRNet trunk. The trunk is shared with the segmentation family
(models/segmentation/hrnet.py); only the head differs. Heatmap targets /
decode / OKS evaluation are in evaluation/keypoints.py, the affine crop
data path in data/keypoint_transforms.py, and the visibility-weighted
MSE loss in ops/losses.heatmap_mse_loss.
"""

from __future__ import annotations

from ...core.registry import MODELS
from ..segmentation.hrnet import HRNet


@MODELS.register("hrnet_w18_keypoints")
def hrnet_w18_keypoints(num_classes: int = 17, **kw):
    """num_classes = number of keypoints (heatmap channels)."""
    return HRNet(num_classes=num_classes, base_width=18, head="keypoints",
                 **kw)


@MODELS.register("hrnet_w48_keypoints")
def hrnet_w48_keypoints(num_classes: int = 17, **kw):
    return HRNet(num_classes=num_classes, base_width=48, head="keypoints",
                 **kw)
