from . import hrnet_pose  # noqa: F401
