"""Hook-structured Trainer — the ONE shared harness (SURVEY.md §1.1 goal).

Merges the three reference archetypes: the simple epoch loop
(classification/mnist/train.py:141), the yacs/DDP/AMP harness features
(swin main.py:84-300: accumulation, auto-resume, save-freq, throughput
mode), and YOLOX's hook skeleton (yolox/core/trainer.py:69-88:
before_train/before_epoch/before_iter/after_iter/after_epoch/after_train)
with yolov5's Callbacks event registry (utils/callbacks.py:8).

The Trainer owns: the jitted steps, the loader epoch protocol
(set_epoch), metric meters, TB writer, Orbax checkpointing with best
tracking, EMA-evaluation, and hook dispatch. Everything device-side stays
in the jitted step functions it is given.
"""

from __future__ import annotations

import contextlib
import os
import time
from collections import defaultdict
from typing import Any, Callable, Dict, List, Optional

import jax
import numpy as np

from ..analysis import strict as strict_mod
from ..core import rng as rng_mod
from ..core.checkpoint import CheckpointManager
from ..core.logging import (LoggerHub, MetricLogger,
                            TensorBoardWriter, create_logger,
                            is_main_process)
from ..data.device_prefetch import DevicePrefetcher
from ..elastic import faults
from ..elastic import heartbeat as hb
from ..elastic.preempt import (Preempted, PreemptionGuard,
                               agree_preempt_step)
from ..obs import flight
from ..obs import metrics as obs_metrics
from ..obs.spans import span, step_span
from ..utils.profiling import RetraceGuard
from . import recovery as recovery_mod
from .async_metrics import DeferredMetrics
from .recovery import RecoveryExhausted, RecoveryManager, RecoveryPolicy

HOOKS = ("before_train", "after_train", "before_epoch", "after_epoch",
         "before_iter", "after_iter", "on_evaluate", "on_checkpoint")


class _DivergenceDetected(Exception):
    """Internal control flow: a lagged metrics entry surfaced a
    non-finite step. Carries the offending entry so the rollback path
    can report it; never escapes the Trainer."""

    def __init__(self, meta: Dict[str, Any], host: Dict[str, Any]):
        super().__init__(f"divergence at step {meta.get('step')}")
        self.meta = meta
        self.host = host


class Callbacks:
    """Named hook registry (yolov5 utils/callbacks.py surface)."""

    def __init__(self):
        self._hooks: Dict[str, List[Callable]] = defaultdict(list)

    def register(self, event: str, fn: Callable) -> None:
        if event not in HOOKS:
            raise KeyError(f"Unknown hook {event!r}; valid: {HOOKS}")
        self._hooks[event].append(fn)

    def fire(self, event: str, trainer: "Trainer", **kw) -> None:
        for fn in self._hooks[event]:
            fn(trainer, **kw)


class Trainer:
    def __init__(
        self, *,
        state,                                  # TrainState
        train_step: Callable,                   # (state, batch, rng)->...
        train_loader,
        eval_step: Optional[Callable] = None,   # (state, batch)->counts
        eval_loader=None,
        epochs: int = 1,
        seed: int = 0,
        log_every: int = 50,
        eval_every_epochs: int = 1,
        save_every_epochs: int = 1,
        workdir: Optional[str] = None,
        best_metric: str = "top1",
        callbacks: Optional[Callbacks] = None,
        metric_reducer: Optional[Callable[[Dict], Dict]] = None,
        abort_non_finite: bool = True,
        async_checkpoint: bool = False,
        log_backends=("tensorboard", "csv", "jsonl"),
        metrics_lag: Optional[int] = None,
        metrics_window: Optional[int] = None,
        retrace_warn: bool = True,
        prefetch="auto",
        obs="auto",
        run_config: Optional[Dict] = None,
        weight_update: Optional[str] = None,
        hbm_sample_s: float = 0.25,
        hbm_alert_frac: Optional[float] = None,
        preemptible: bool = True,
        heartbeat="auto",
        recovery=None,
        strict=None,
        metrics_port="auto",
    ):
        self.state = state
        # strict mode (README "Hot-loop sync policy"): arm JAX's own
        # sanitizers. "transfers" wraps every hot-loop step region in
        # transfer_guard_device_to_host("disallow") — a stray sync
        # between log points becomes a runtime error at the offending
        # line instead of a silent stall. "nans" arms jax_debug_nans
        # for the whole run. None defers to DLTPU_STRICT in the env.
        self.strict_modes = strict_mod.resolve(strict)
        self.strict_sections = 0     # guard regions entered (test hook)
        # "threads" arms the runtime thread sanitizer now, before the
        # prefetcher/heartbeat/metrics objects construct their locks —
        # enable() patches module threading attrs, so timing matters
        strict_mod.maybe_enable_threads(self.strict_modes)
        # self-healing policy (README "Self-healing policy"): None/"abort"
        # keeps the seed behavior (abort_non_finite raises on the first
        # bad step); "rollback" (or a RecoveryPolicy / RecoveryManager)
        # rolls back to a device-side anchor, skips the bad data window,
        # and dampens updates through a cooldown — aborting only once
        # the rollback budget is spent.
        if recovery is None or recovery == "abort":
            self._recovery: Optional[RecoveryManager] = None
        elif recovery == "rollback":
            self._recovery = RecoveryManager(RecoveryPolicy())
        elif isinstance(recovery, RecoveryPolicy):
            self._recovery = (RecoveryManager(recovery)
                              if recovery.mode == "rollback" else None)
        elif isinstance(recovery, RecoveryManager):
            self._recovery = recovery
        else:
            raise ValueError(f"recovery must be None|'abort'|'rollback'|"
                             f"RecoveryPolicy|RecoveryManager, "
                             f"got {recovery!r}")
        # elastic-run wiring (README "Elastic run policy"): preemptible
        # installs the chained SIGTERM/SIGINT guard (flush checkpoint →
        # Preempted at the next step boundary → exit 75); heartbeat
        # "auto" writes the supervisor's step/activity watermark file
        # when DLTPU_HEARTBEAT names one (a path forces it, False/None
        # disables).
        self.preemptible = bool(preemptible)
        self._heartbeat_opt = heartbeat
        self.preempt_guard: Optional[PreemptionGuard] = None
        self._beat: Optional[hb.Heartbeat] = None
        self._beat_writer: Optional[hb.HeartbeatWriter] = None
        self.hbm_alert_frac = hbm_alert_frac
        # observability (README "Observability policy"): spans + flight
        # recorder + HBM sampler. "auto" = on whenever the run has a
        # workdir to dump trace.json/flightrec.json into; True forces it
        # (tests), False disables. Retrace warnings always land in the
        # flight ring — recording is bounded and sync-free.
        self.obs_enabled = bool(workdir) if obs == "auto" else bool(obs)
        self.run_config = run_config
        # weight-update sharding mode ("replicated"/"zero1"), recorded in
        # every checkpoint's topology sidecar; None lets the sidecar
        # infer it from the state's moment/param layouts
        self.weight_update = weight_update
        self.hbm_sample_s = hbm_sample_s
        self._hbm = None
        self._obs_owns_tracer = False
        self._obs_started = False
        # fleet scrape surface: "auto" serves /metrics + /healthz only
        # when DLTPU_METRICS_PORT names a port (the supervisor/fleet
        # contract); an int forces that port (0 = ephemeral); None/False
        # disables. Train replicas then answer the same probes serve
        # replicas do.
        if metrics_port == "auto":
            raw = os.environ.get("DLTPU_METRICS_PORT")
            self.metrics_port = int(raw) if raw not in (None, "") else None
        else:
            self.metrics_port = (int(metrics_port)
                                 if metrics_port not in (None, False)
                                 else None)
        self._metrics_server = None
        self._owns_metrics_registry = False
        self.train_step = (RetraceGuard(
            train_step, name="train_step",
            on_retrace=lambda info: flight.record("retrace", **info))
            if retrace_warn else train_step)
        # overlapped device feed (see README "Input feed & donation
        # policy"): with a mesh-bearing loader the serial host→HBM
        # transfer is the hot loop's last blocking stage, so auto-wrap it
        # in a DevicePrefetcher. prefetch="auto" wraps only mesh loaders;
        # an int wraps any epoch-protocol loader at that depth; 0/None
        # disables wrapping.
        self.train_loader = self._wrap_prefetch(train_loader, prefetch)
        self.eval_step = eval_step
        self.eval_loader = eval_loader
        self.epochs = epochs
        self.log_every = log_every
        self.eval_every = eval_every_epochs
        self.save_every = save_every_epochs
        self.best_metric = best_metric
        self.best_value = float("-inf")
        self.callbacks = callbacks or Callbacks()
        self.metric_reducer = metric_reducer
        self.abort_non_finite = abort_non_finite
        self.workdir = workdir
        self.logger = create_logger("dltpu", workdir)
        # pluggable backends (yolov5 Loggers shape): tensorboard + csv +
        # offline-W&B jsonl by default; self.tb stays the TB handle for
        # figures/images
        self.hub = LoggerHub(workdir, log_backends)
        self.tb = self.hub.tb
        self.meters = MetricLogger()
        self.rng = rng_mod.host_key(seed)
        self.epoch = 0
        # sync-free hot loop (see README "Hot-loop sync policy"): every
        # step's device-scalar metrics are enqueued here and only entries
        # at least metrics_lag steps old are ever fetched — by then they
        # are resolved, so the fetch never stalls the dispatch queue.
        # Default lag = log_every: at each log point the previous log
        # window is ready, so divergence aborts within 2*log_every steps.
        self.metrics_lag = (metrics_lag if metrics_lag is not None
                            else log_every)
        # windowed on-device reduction: at log_every ≫ 100 holding (and
        # fetching) one scalar dict PER STEP is the remaining O(log_every)
        # host cost, so auto-fold the window into a device-resident
        # running mean (one fused add per push). None = auto threshold;
        # 0 disables; an int forces that window.
        self.metrics_window = (metrics_window if metrics_window is not None
                               else (log_every if log_every > 100 else 0))
        self.deferred = DeferredMetrics(lag=self.metrics_lag,
                                        window=self.metrics_window or None)
        self.eval_fetches = 0        # host materializations per evaluate()
        self._host_step: Optional[int] = None  # host mirror of state.step
        self._batches = None         # live epoch iterator (rollback hook)
        self.ckpt = (CheckpointManager(f"{workdir}/ckpt",
                                       async_save=async_checkpoint)
                     if workdir else None)

    @property
    def host_step(self) -> int:
        """Host-side step counter mirroring ``state.step`` without a
        per-use D2H fetch; seeded once (from the restored state) and
        incremented in lockstep with train_step calls."""
        if self._host_step is None:
            try:
                self._host_step = int(getattr(self.state, "step", 0))
            except TypeError:
                self._host_step = 0
        return self._host_step

    # ----------------------------------------------------- device feed
    @staticmethod
    def _wrap_prefetch(loader, prefetch):
        if loader is None or not prefetch:
            return loader
        if isinstance(loader, DevicePrefetcher):
            return loader                     # caller already wrapped it
        if prefetch == "auto":
            # only wrap loaders that own a mesh (their batches need the
            # make_global_array assembly the prefetcher hides) and speak
            # the epoch protocol the wrapper must preserve
            if getattr(loader, "mesh", None) is None or \
                    not hasattr(loader, "set_epoch"):
                return loader
            depth = 2
        else:
            depth = int(prefetch)
        return DevicePrefetcher(loader, depth=depth)

    def precompile(self):
        """AOT step warmup: compile the train step against the loader's
        ABSTRACT batch spec (``element_spec``) before any data exists —
        ``jit(...).lower(...).compile()`` lands the executable in jit's
        cache and the persistent compile cache (``core/compile_cache``),
        so the first real step dispatches instead of serializing a
        multi-minute XLA compile after the first batch arrives.

        When the train loader is a DevicePrefetcher, its worker thread
        is started FIRST, so first-batch decode + H2D transfer fill the
        queue while XLA compiles on this thread. Returns compile seconds,
        or None when the loader/step has no AOT surface."""
        from ..core.compile_cache import enable_compile_cache
        enable_compile_cache()
        self._obs_start()      # the compile span belongs on the timeline
        if hasattr(self.train_loader, "start"):
            self.train_loader.start()         # overlap feed with compile
        spec_fn = getattr(self.train_loader, "element_spec", None)
        batch_spec = spec_fn() if spec_fn is not None else None
        if batch_spec is None:
            return None
        # unwrap the RetraceGuard to reach the jitted function's .lower
        fn = getattr(self.train_step, "fn", self.train_step)
        if not hasattr(fn, "lower"):
            return None
        from ..obs.xla import tracked_compile
        t0 = time.perf_counter()
        self._aot_step = tracked_compile(
            fn.lower(self.state, batch_spec, self.rng), "train_step")
        dt = time.perf_counter() - t0
        self.precompile_seconds = dt
        self.logger.info(f"precompile: train step AOT-compiled in "
                         f"{dt:.2f}s (overlapped with feed warmup)")
        return dt

    # ----------------------------------------------------- observability
    def _obs_config(self) -> Dict[str, Any]:
        """Run config embedded in flightrec.json: the caller's full cfg
        when provided (tools/train.py), else the Trainer's own knobs."""
        if self.run_config is not None:
            return self.run_config
        return {"epochs": self.epochs, "log_every": self.log_every,
                "metrics_lag": self.metrics_lag,
                "metrics_window": self.metrics_window,
                "best_metric": self.best_metric,
                "workdir": self.workdir}

    def _obs_start(self) -> None:
        """Idempotent: called from both ``precompile()`` (so the AOT
        compile span lands on the timeline) and ``train()``."""
        if not self.obs_enabled or self._obs_started:
            return
        self._obs_started = True
        from ..obs import spans
        from ..obs.xla import HbmWatermark
        self._obs_owns_tracer = not spans.enabled()
        spans.enable()
        if self.workdir:
            flight.configure(os.path.join(self.workdir, "flightrec.json"),
                             config=self._obs_config())
            flight.install_signal_handler()
        self._hbm = HbmWatermark(interval_s=self.hbm_sample_s,
                                 alert_frac=self.hbm_alert_frac).start()
        # metrics registry: always on with obs (the push helpers in
        # _consume/feed/recovery need a home); the HTTP scrape server
        # only when a port was asked for
        self._owns_metrics_registry = not obs_metrics.enabled()
        obs_metrics.enable()
        if self.metrics_port is not None and self._metrics_server is None:
            self._metrics_server = obs_metrics.MetricsServer(
                port=self.metrics_port,
                healthz_fn=self._metrics_healthz).start()
            obs_metrics.write_endpoint(self._metrics_server.url,
                                       role="train")

    def _metrics_healthz(self):
        """Train-replica health: backed by the elastic heartbeat — the
        same step/activity watermark the supervisor's wedge detector
        reads, so /healthz and the heartbeat file never disagree."""
        payload = {"status": "ready", **obs_metrics.replica_identity()}
        if self._beat is not None:
            payload["step"] = self._beat.step
            payload["activity"] = self._beat.activity
            payload["phase"] = self._beat.phase
        return 200, payload

    def _obs_finish(self) -> None:
        if not self.obs_enabled:
            return
        from ..obs import spans
        if self._hbm is not None:
            self._hbm.stop()
            self.hbm_watermark = self._hbm.watermark()
        tracer = spans.get_tracer()
        if tracer is not None and self.workdir:
            tracer.dump(os.path.join(self.workdir, "trace.json"))
        if self._obs_owns_tracer:
            spans.disable()
        if self._metrics_server is not None:
            self._metrics_server.stop()
            self._metrics_server = None
        reg = obs_metrics.get_registry()
        if reg is not None and self.workdir:
            reg.dump(os.path.join(self.workdir, "metrics_registry.json"))
        if self._owns_metrics_registry:
            obs_metrics.disable()
        self._obs_started = False      # a second train() re-arms

    # ---------------------------------------------------------- elastic
    def _elastic_start(self) -> None:
        """Arm the preemption guard and the heartbeat writer. Idempotent
        like ``_obs_start`` (train() may be called twice)."""
        if self.preemptible and self.preempt_guard is None:
            guard = PreemptionGuard()
            if self.ckpt:
                # in-handler flush: the in-flight async write commits
                # even if the loop never reaches another step boundary
                guard.add_flush(self.ckpt.flush)
            if guard.install():
                self.preempt_guard = guard
        if self._beat_writer is None:
            path = self._heartbeat_opt
            if path == "auto":
                path = os.environ.get(hb.ENV_VAR)
            if path:
                self._beat = hb.Heartbeat(step=self.host_step)
                self._beat_writer = hb.HeartbeatWriter(
                    str(path), self._beat).start()

    def _elastic_finish(self) -> None:
        if self._beat_writer is not None:
            self._beat_writer.stop()
            self._beat_writer = None
        if self.preempt_guard is not None:
            self.preempt_guard.uninstall()
            self.preempt_guard = None

    def _beat_touch(self, phase: str) -> None:
        if self._beat is not None:
            self._beat.touch(phase, step=self.host_step)

    def _check_preempted(self) -> None:
        """Step-boundary poll (one Event.is_set when armed)."""
        # a SIGTERM handler defers its flight dump to here (the signal-
        # handler-safety contract: no open()/json on the signal stack)
        if self.obs_enabled:
            flight.flush_pending()
        if self.preempt_guard is not None and \
                self.preempt_guard.requested():
            raise Preempted(
                f"preemption signal at step {self.host_step}",
                signum=self.preempt_guard.signum, step=self.host_step)

    def _on_preempted(self, exc: Preempted) -> None:
        """Land the final state: checkpoint the interrupted step (unless
        a periodic save already wrote it), barrier the write, dump the
        flight ring with the distinct 'preempted' reason."""
        if self.ckpt:
            # sync is fine — we're dying; on a pod, agree on process 0's
            # step so every host lands the SAME checkpoint step even
            # when the pod-wide SIGTERM hit different step boundaries
            step = agree_preempt_step(int(self.state.step))
            if self.ckpt.latest_step() != step:
                self._save()
            self.ckpt.flush()
            self.logger.info(
                f"preempted (signal {exc.signum}): checkpoint flushed at "
                f"step {step}; exit with EXIT_PREEMPTED requeues")
        if self.obs_enabled:
            flight.dump("preempted", exception=exc)

    # ------------------------------------------------------------- train
    def _strict_ctx(self):
        """One hot-loop guard region (see ``analysis.strict``). Counted
        so tests can assert the guard really wrapped every step."""
        if "transfers" in self.strict_modes:
            self.strict_sections += 1
            return strict_mod.no_host_transfers()
        return contextlib.nullcontext()

    def train(self) -> Any:
        self._obs_start()
        self._elastic_start()
        try:
            if "nans" in self.strict_modes:
                # run-wide, not per-section: jax_debug_nans changes what
                # XLA compiles, so toggling it per step would retrace
                with strict_mod.debug_nans():
                    return self._train()
            return self._train()
        except Preempted as exc:
            self._on_preempted(exc)
            raise
        except BaseException as exc:
            if self.obs_enabled:
                reason = ("divergence"
                          if isinstance(exc, FloatingPointError)
                          else "exception")
                flight.dump(reason, exception=exc)
            raise
        finally:
            self._elastic_finish()
            self._obs_finish()

    def _train(self) -> Any:
        if self.ckpt:
            restored, step = self.ckpt.auto_resume(self.state)
            if step:
                self.state = restored
                steps_per_epoch = max(len(self.train_loader), 1)
                self.epoch = int(step) // steps_per_epoch
                self._host_step = int(step)
        if self._recovery is not None:
            # fresh init or just-restored checkpoint: both known-clean
            self._recovery.seed(self.host_step, self.state)
        self.callbacks.fire("before_train", self)
        try:
            for epoch in range(self.epoch, self.epochs):
                self.epoch = epoch
                self.callbacks.fire("before_epoch", self)
                self._train_one_epoch(epoch)
                self.callbacks.fire("after_epoch", self)
                if self.eval_step and self.eval_loader is not None and \
                        (epoch + 1) % self.eval_every == 0:
                    self.evaluate()
                if self.ckpt and (epoch + 1) % self.save_every == 0:
                    self._save()
        finally:
            # land any in-flight async write + pending best-copy even on
            # abort (non-finite guard, preemption) BEFORE callbacks that
            # might read the best dir
            if self.ckpt:
                self.ckpt.wait_until_finished()
        self.callbacks.fire("after_train", self)
        if self._recovery is not None and self._recovery.rollbacks \
                and self.obs_enabled:
            # the run SURVIVED its divergences — land the evidence in
            # flightrec.json even though nothing crashed
            flight.record("recovery_summary", **self._recovery.stats())
            flight.dump("recovered")
        # self.epochs, not self.epoch: the loop leaves self.epoch at the
        # last INDEX (epochs-1), and summary only runs on normal exit
        summary = {"epochs": self.epochs, **getattr(self, "_last_eval", {})}
        # omit when the metric never updated (no eval loader): -inf would
        # serialize as the non-standard JSON token -Infinity
        if self.best_value != float("-inf"):
            summary["best_" + self.best_metric] = self.best_value
        self.hub.summary(summary)
        self.hub.close()
        return self.state

    def _train_one_epoch(self, epoch: int) -> None:
        """One epoch, retried through divergence rollbacks: each
        ``_DivergenceDetected`` rolls the state back to the anchor and
        replays the epoch under a fresh loader permutation (the skip) —
        the budget inside ``_rollback`` bounds the retries."""
        while True:
            try:
                return self._epoch_pass(epoch)
            except _DivergenceDetected as d:
                self._rollback(d)

    def _epoch_pass(self, epoch: int) -> None:
        """Sync-free hot loop: the only host↔device round-trips are the
        lagged fetches inside ``self.deferred`` (entries ≥ metrics_lag
        steps old, already resolved) — never the in-flight step."""
        self.train_loader.set_epoch(epoch)
        self.host_step          # seed the host mirror before the loop
        n_iter = len(self.train_loader)
        t_data = time.time()
        batches = iter(self.train_loader)
        # kept for the rollback path: an abandoned pass must shut its
        # prefetch pipeline down instead of leaking the worker thread
        self._batches = batches
        it = 0
        while True:
            # data-wait phase: host blocked on the (possibly prefetched)
            # loader — on the span timeline this is the slice the feed
            # follow-ups in ROADMAP.md need to see shrink
            with span("data_wait", epoch=epoch):
                try:
                    batch = next(batches)
                except StopIteration:
                    break
            wall_wait = time.time() - t_data
            # prefer the loader's own queue-empty estimate (actual
            # starvation) over wall-clock-between-iterations, which
            # includes step dispatch time
            loader_wait = getattr(self.train_loader, "last_data_wait",
                                  None)
            data_time = loader_wait if loader_wait is not None else \
                wall_wait
            # strict region: under Trainer(strict="transfers") /
            # DLTPU_STRICT=1 everything from before_iter through the
            # deferred push runs under a d2h transfer-guard — the lagged
            # metrics poll below stays OUTSIDE it, because that fetch is
            # the one designed sync per log window
            with self._strict_ctx():
                self.callbacks.fire("before_iter", self, batch=batch)
                # recovery hooks, dispatched BEFORE the (possibly
                # donating) step consumes the state buffers: the periodic
                # device-side anchor snapshot, and — inside a
                # post-rollback cooldown — a params copy for the damped
                # update below
                prev_params = cooldown = None
                if self._recovery is not None:
                    self._recovery.maybe_snapshot(self.host_step,
                                                  self.state)
                    cooldown = self._recovery.cooldown_scale(
                        self.host_step)
                    if cooldown is not None:
                        prev_params = recovery_mod.snapshot_state(
                            self.state.params)
                # dispatch phase: enqueue the jitted step (async — this
                # span measures host dispatch, not device compute;
                # StepTrace-annotated so a concurrent XLA trace aligns
                # device ops)
                with step_span("dispatch", self.host_step):
                    self.state, metrics = self.train_step(
                        self.state, batch, self.rng)
                if cooldown is not None:
                    # shrink this step's param delta (exact LR decay for
                    # SGD); optimizer moments keep their own schedule
                    self.state = self.state.replace(
                        params=recovery_mod.damp_update(
                            prev_params, self.state.params, cooldown))
                self.callbacks.fire("after_iter", self, metrics=metrics)
                self._host_step = self.host_step + 1
                self.deferred.push(metrics, epoch=epoch, it=it,
                                   step=self.host_step, n_iter=n_iter,
                                   data_time=data_time)
            if it % self.log_every == 0:
                with span("metrics_flush"):
                    self._consume(self.deferred.poll())
            # elastic step boundary: advance the heartbeat watermark,
            # give the fault harness its mid-step hook (a sigterm fault
            # routes through the real kernel-delivered handler chain),
            # then land any requested preemption while state is clean
            self._beat_touch("step")
            faults.maybe_fire("step", step=self.host_step)
            if faults.consume("nan", "step", step=self.host_step):
                # poison the params so the NEXT step's loss goes NaN
                # through the real jitted bad_step path — divergence
                # detection and recovery run end to end, not shortcut
                self.state = recovery_mod.poison_state(self.state)
            self._check_preempted()
            t_data = time.time()
            it += 1
        # epoch-end barrier: one bulk fetch lands every remaining entry,
        # so short epochs still log and a NaN in the tail still aborts
        with span("metrics_flush", drain=True):
            self._consume(self.deferred.drain())
        # feed telemetry (DevicePrefetcher): queue occupancy + H2D wait
        # land next to the train scalars so an input-bound epoch is
        # visible without a profiler
        feed_stats = getattr(self.train_loader, "stats", None)
        if feed_stats is not None:
            stats = feed_stats()
            self.hub.scalars({f"feed/{k}": v for k, v in stats.items()},
                             self.host_step)
            if self.obs_enabled:
                flight.record("feed", epoch=epoch, **stats)
                for k, v in stats.items():
                    if isinstance(v, (int, float)):
                        obs_metrics.set_gauge(f"dltpu_feed_{k}", float(v))
            reset = getattr(self.train_loader, "reset_stats", None)
            if reset is not None:
                reset()

    def _consume(self, entries) -> None:
        """Divergence-check every materialized entry, then log the
        newest one (the stale snapshot that stands in for 'now')."""
        if not entries:
            return
        if self.obs_enabled:
            # flight ring: one structured snapshot per materialized
            # entry, so a crash dump carries the last-K step metrics
            for meta, host in entries:
                flight.record("step", step=meta.get("step"),
                              epoch=meta.get("epoch"), it=meta.get("it"),
                              data_time=meta.get("data_time"),
                              metrics=host)
        if self._recovery is not None or self.abort_non_finite:
            bad_i = None
            for i, (meta, host) in enumerate(entries):
                # bad_step is the jitted isfinite(loss) flag; the loss
                # check is the fallback for custom steps that don't
                # provide it (non-finite params keep it latched anyway)
                if host.get("bad_step", 0) > 0 or not np.isfinite(
                        host.get("loss", 0.0)):
                    bad_i = i
                    break
            if self._recovery is not None and bad_i != 0:
                # the newest verified-finite step vouches for every
                # pending anchor snapshot strictly older than it
                clean_meta = entries[len(entries) - 1 if bad_i is None
                                     else bad_i - 1][0]
                if clean_meta.get("step") is not None:
                    self._recovery.mark_verified(clean_meta["step"])
            if bad_i is not None:
                meta, host = entries[bad_i]
                self.logger.error(
                    f"Loss is {host.get('loss')}, "
                    + ("recovering" if self._recovery is not None
                       else "stopping training")
                    + f" (epoch {meta['epoch']} it {meta['it']})")
                if self.obs_enabled:
                    flight.record("divergence",
                                  step=meta.get("step"),
                                  epoch=meta["epoch"],
                                  it=meta["it"],
                                  loss=host.get("loss"))
                if self._recovery is not None:
                    raise _DivergenceDetected(meta, host)
                raise FloatingPointError(
                    f"non-finite loss {host.get('loss')} at epoch "
                    f"{meta['epoch']} it {meta['it']}")
        meta, host = entries[-1]
        host = {k: v for k, v in host.items() if k != "bad_step"}
        host["data_time"] = meta["data_time"]
        self.meters.update(**host)
        self.logger.info(
            f"epoch {meta['epoch']} it {meta['it']}/{meta['n_iter']} "
            f"{self.meters}")
        self.hub.scalars({f"train/{k}": v for k, v in host.items()},
                         meta["step"])
        # scrape surface: the same lagged (already-resolved) snapshot —
        # no extra D2H, the fleet sees exactly what the log line sees
        if meta.get("step") is not None:
            obs_metrics.set_gauge("dltpu_train_step", float(meta["step"]))
        for k, v in host.items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                safe = "".join(c if c.isalnum() else "_" for c in str(k))
                obs_metrics.set_gauge(f"dltpu_train_{safe}", float(v))

    # ---------------------------------------------------------- recovery
    def _rollback(self, d: _DivergenceDetected) -> None:
        """Roll back to the anchor, skip the offending data window, and
        arm the cooldown — or, with the budget spent, fall through to
        the seed abort path (FloatingPointError, same message shape)."""
        meta, host = d.meta, d.host
        bad_step = int(meta.get("step") or self.host_step)
        # the failed pass's prefetch pipeline must die before we restart
        close = getattr(self._batches, "close", None)
        if close is not None:
            close()
        try:
            anchor_step, state = self._recovery.on_divergence(bad_step)
        except RecoveryExhausted as exc:
            if self.obs_enabled:
                flight.record("recovery_exhausted", step=bad_step,
                              error=str(exc), **self._recovery.stats())
            raise FloatingPointError(
                f"non-finite loss {host.get('loss')} at epoch "
                f"{meta['epoch']} it {meta['it']} ({exc})") from exc
        self.state = state
        self._host_step = anchor_step
        # in-flight entries were computed from poisoned state — replace
        # the ring instead of materializing them
        self.deferred = DeferredMetrics(lag=self.metrics_lag,
                                        window=self.metrics_window or None)
        # skip the window: a reseed-capable loader replays the epoch
        # under a fresh permutation, so the poisonous batch order is
        # never retraced verbatim
        reseed = getattr(self.train_loader, "reseed", None)
        if reseed is not None:
            reseed(self._recovery.rollbacks)
        pol = self._recovery.policy
        self.logger.warning(
            f"divergence at step {bad_step} (loss {host.get('loss')}): "
            f"rolled back to step {anchor_step}, "
            + ("reseeded loader, " if reseed is not None else "")
            + f"lr x{pol.lr_decay} for {pol.cooldown_steps} steps "
            f"({len(self._recovery.recovery_steps)}/{pol.max_recoveries} "
            f"recoveries used)")
        obs_metrics.inc("dltpu_recovery_rollbacks_total")
        if self.obs_enabled:
            flight.record("recovery", step=bad_step,
                          anchor_step=anchor_step, loss=host.get("loss"),
                          epoch=meta.get("epoch"),
                          rollbacks=self._recovery.rollbacks,
                          skipped=[anchor_step, bad_step],
                          cooldown_steps=pol.cooldown_steps,
                          lr_decay=pol.lr_decay,
                          reseeded=reseed is not None)
        self._beat_touch("recovery")

    # -------------------------------------------------------------- eval
    def evaluate(self) -> Dict[str, float]:
        """Zero-sync eval: every batch's count dict stays on device while
        the loop runs (dispatch only), then ONE ``jax.device_get`` lands
        the whole list. Host-side accumulation order matches the old
        per-batch-float path exactly, so totals are bitwise identical."""
        self._beat_touch("eval")
        with span("eval", epoch=self.epoch):
            per_batch = [self.eval_step(self.state, batch)
                         for batch in self.eval_loader]
            # the one materialization
            # dltpu: allow(DLT100) designed: single bulk D2H per eval pass
            host_counts = jax.device_get(per_batch)
        self._beat_touch("eval")
        self.eval_fetches += 1
        totals: Dict[str, float] = defaultdict(float)
        for counts in host_counts:
            for k, v in counts.items():
                totals[k] += float(v)
        results = dict(totals)
        if self.metric_reducer:
            results = self.metric_reducer(results)
        elif "count" in totals and totals["count"] > 0:
            results = {k: v / totals["count"] for k, v in totals.items()
                       if k != "count"}
        self._last_eval = dict(results)
        self.callbacks.fire("on_evaluate", self, results=results)
        self.logger.info(f"eval @ epoch {self.epoch}: "
                         + "  ".join(f"{k}={v:.4f}"
                                     for k, v in results.items()))
        self.hub.scalars({f"eval/{k}": v for k, v in results.items()},
                         self.host_step)
        value = results.get(self.best_metric)
        if value is not None and value > self.best_value:
            self.best_value = value
            if self.ckpt:
                self._save(is_best=True)
        return results

    def _save(self, is_best: bool = False) -> None:
        step = int(self.state.step)
        self._beat_touch("checkpoint")
        faults.maybe_fire("checkpoint", step=step)
        with span("checkpoint", step=step, best=is_best):
            self.ckpt.save(step, self.state,
                           metrics={self.best_metric: self.best_value},
                           is_best=is_best,
                           topology=self._topology())
        if faults.consume("ckpt_corrupt", "checkpoint", step=step):
            # flush FIRST so the checksum sidecar records the intact
            # files — the bit-flip after commit is exactly the silent
            # on-disk corruption restore-time verification must catch
            self.ckpt.flush()
            hit = faults.corrupt_checkpoint(self.ckpt.directory, step)
            self.logger.warning(
                f"fault: corrupted checkpoint step {step} "
                f"({len(hit)} file(s))")
        self.callbacks.fire("on_checkpoint", self, step=step)

    def _topology(self) -> Optional[Dict[str, Any]]:
        """Topology fingerprint for the checkpoint sidecar — what a
        cross-topology resume reports it is re-sharding FROM."""
        try:
            from ..elastic.topology import current_topology
            return current_topology(state=self.state,
                                    weight_update=self.weight_update)
        except Exception:  # noqa: BLE001 - never block a save on it
            return None

    # -------------------------------------------------- throughput mode
    def throughput(self, n_iters: int = 30, lag: int = 3) -> float:
        """images/sec over n averaged iters (swin main.py:281-300).

        ONE pipelined pass over real loader batches. Per-step tail stats
        come from a lagged metrics ring instead of a per-iter
        ``float(m["loss"])`` sync: after dispatching step i the loop
        fetches step i-``lag``'s metrics — a buffer that is the only
        UNRETIRED work older than the ``lag`` steps still in flight, so
        the fetch completes the moment that step does without draining
        the dispatch queue. Timestamp deltas between those lagged
        completions ARE the pipelined per-step times (p50/p90), the same
        quantity the old serializing pass approximated while flushing
        the pipe every iteration.

        Donation-safe by construction: every dispatched batch is a fresh
        one from the loader (never reused), so ``donate_batch=True``
        steps measure identically. When the loader is a
        ``DevicePrefetcher``, its queue-occupancy / H2D-wait counters are
        folded into ``throughput_stats``."""
        import collections as _collections
        if n_iters < 2:
            raise ValueError("throughput needs n_iters >= 2")
        lag = max(1, min(int(lag), n_iters - 1))
        loader = self.train_loader
        reset = getattr(loader, "reset_stats", None)
        if reset is not None:
            reset()

        def cycle():
            while True:
                got = False
                for b in iter(loader):
                    got = True
                    yield b
                if not got:
                    raise ValueError("loader yielded zero batches")
        it = cycle()
        batch = next(it)
        bsz = jax.tree.leaves(batch)[0].shape[0]
        # warmup: compile + land the executable, then drain (clean start)
        self.state, m = self.train_step(self.state, batch, self.rng)
        float(m["loss"])                      # the one draining sync
        ring: "_collections.deque" = _collections.deque()
        lag_marks, data_times = [], []
        t0 = time.perf_counter()
        for _ in range(n_iters):
            t_d = time.perf_counter()
            batch = next(it)
            wait = getattr(loader, "last_data_wait", None)
            data_times.append(wait if wait is not None
                              else time.perf_counter() - t_d)
            self.state, m = self.train_step(self.state, batch, self.rng)
            ring.append(m)
            if len(ring) > lag:
                float(ring.popleft()["loss"])  # lagged, non-draining
                lag_marks.append(time.perf_counter())
        while ring:                            # end-of-run drain
            float(ring.popleft()["loss"])
            lag_marks.append(time.perf_counter())
        total = time.perf_counter() - t0
        ips = bsz * n_iters / total
        step_times = np.diff(lag_marks) if len(lag_marks) > 1 else \
            np.asarray([total / n_iters])  # dltpu: allow(DLT100) host floats
        p50, p90 = np.percentile(step_times, [50, 90])
        data_frac = sum(data_times) / total if total else 0.0
        self.throughput_stats = {
            "images_per_sec": ips,
            "step_ms_mean": total / n_iters * 1e3,
            "step_ms_p50": p50 * 1e3,
            "step_ms_p90": p90 * 1e3,
            "data_wait_frac": data_frac,
            "batch": bsz,
        }
        feed_stats = getattr(loader, "stats", None)
        if feed_stats is not None:
            self.throughput_stats.update(feed_stats())
        self.logger.info(
            f"throughput: {ips:.1f} images/s "
            f"({total / n_iters * 1e3:.1f} ms/iter pipelined, "
            f"p50 {p50 * 1e3:.1f} ms, p90 {p90 * 1e3:.1f} ms, "
            f"data-wait {data_frac:.1%}, batch {bsz}, lag {lag})")
        return ips
