"""Hook-structured Trainer — the ONE shared harness (SURVEY.md §1.1 goal).

Merges the three reference archetypes: the simple epoch loop
(classification/mnist/train.py:141), the yacs/DDP/AMP harness features
(swin main.py:84-300: accumulation, auto-resume, save-freq, throughput
mode), and YOLOX's hook skeleton (yolox/core/trainer.py:69-88:
before_train/before_epoch/before_iter/after_iter/after_epoch/after_train)
with yolov5's Callbacks event registry (utils/callbacks.py:8).

The Trainer owns: the jitted steps, the loader epoch protocol
(set_epoch), metric meters, TB writer, Orbax checkpointing with best
tracking, EMA-evaluation, and hook dispatch. Everything device-side stays
in the jitted step functions it is given.
"""

from __future__ import annotations

import time
from collections import defaultdict
from typing import Any, Callable, Dict, List, Optional

import jax
import numpy as np

from ..core import rng as rng_mod
from ..core.checkpoint import CheckpointManager
from ..core.logging import (LoggerHub, MetricLogger,
                            TensorBoardWriter, create_logger,
                            is_main_process)
from ..utils.profiling import RetraceGuard
from .async_metrics import DeferredMetrics

HOOKS = ("before_train", "after_train", "before_epoch", "after_epoch",
         "before_iter", "after_iter", "on_evaluate", "on_checkpoint")


class Callbacks:
    """Named hook registry (yolov5 utils/callbacks.py surface)."""

    def __init__(self):
        self._hooks: Dict[str, List[Callable]] = defaultdict(list)

    def register(self, event: str, fn: Callable) -> None:
        if event not in HOOKS:
            raise KeyError(f"Unknown hook {event!r}; valid: {HOOKS}")
        self._hooks[event].append(fn)

    def fire(self, event: str, trainer: "Trainer", **kw) -> None:
        for fn in self._hooks[event]:
            fn(trainer, **kw)


class Trainer:
    def __init__(
        self, *,
        state,                                  # TrainState
        train_step: Callable,                   # (state, batch, rng)->...
        train_loader,
        eval_step: Optional[Callable] = None,   # (state, batch)->counts
        eval_loader=None,
        epochs: int = 1,
        seed: int = 0,
        log_every: int = 50,
        eval_every_epochs: int = 1,
        save_every_epochs: int = 1,
        workdir: Optional[str] = None,
        best_metric: str = "top1",
        callbacks: Optional[Callbacks] = None,
        metric_reducer: Optional[Callable[[Dict], Dict]] = None,
        abort_non_finite: bool = True,
        async_checkpoint: bool = False,
        log_backends=("tensorboard", "csv", "jsonl"),
        metrics_lag: Optional[int] = None,
        retrace_warn: bool = True,
    ):
        self.state = state
        self.train_step = (RetraceGuard(train_step, name="train_step")
                           if retrace_warn else train_step)
        self.train_loader = train_loader
        self.eval_step = eval_step
        self.eval_loader = eval_loader
        self.epochs = epochs
        self.log_every = log_every
        self.eval_every = eval_every_epochs
        self.save_every = save_every_epochs
        self.best_metric = best_metric
        self.best_value = float("-inf")
        self.callbacks = callbacks or Callbacks()
        self.metric_reducer = metric_reducer
        self.abort_non_finite = abort_non_finite
        self.logger = create_logger("dltpu", workdir)
        # pluggable backends (yolov5 Loggers shape): tensorboard + csv +
        # offline-W&B jsonl by default; self.tb stays the TB handle for
        # figures/images
        self.hub = LoggerHub(workdir, log_backends)
        self.tb = self.hub.tb
        self.meters = MetricLogger()
        self.rng = rng_mod.host_key(seed)
        self.epoch = 0
        # sync-free hot loop (see README "Hot-loop sync policy"): every
        # step's device-scalar metrics are enqueued here and only entries
        # at least metrics_lag steps old are ever fetched — by then they
        # are resolved, so the fetch never stalls the dispatch queue.
        # Default lag = log_every: at each log point the previous log
        # window is ready, so divergence aborts within 2*log_every steps.
        self.metrics_lag = (metrics_lag if metrics_lag is not None
                            else log_every)
        self.deferred = DeferredMetrics(lag=self.metrics_lag)
        self.eval_fetches = 0        # host materializations per evaluate()
        self._host_step: Optional[int] = None  # host mirror of state.step
        self.ckpt = (CheckpointManager(f"{workdir}/ckpt",
                                       async_save=async_checkpoint)
                     if workdir else None)

    @property
    def host_step(self) -> int:
        """Host-side step counter mirroring ``state.step`` without a
        per-use D2H fetch; seeded once (from the restored state) and
        incremented in lockstep with train_step calls."""
        if self._host_step is None:
            try:
                self._host_step = int(getattr(self.state, "step", 0))
            except TypeError:
                self._host_step = 0
        return self._host_step

    # ------------------------------------------------------------- train
    def train(self) -> Any:
        if self.ckpt:
            restored, step = self.ckpt.auto_resume(self.state)
            if step:
                self.state = restored
                steps_per_epoch = max(len(self.train_loader), 1)
                self.epoch = int(step) // steps_per_epoch
                self._host_step = int(step)
        self.callbacks.fire("before_train", self)
        try:
            for epoch in range(self.epoch, self.epochs):
                self.epoch = epoch
                self.callbacks.fire("before_epoch", self)
                self._train_one_epoch(epoch)
                self.callbacks.fire("after_epoch", self)
                if self.eval_step and self.eval_loader is not None and \
                        (epoch + 1) % self.eval_every == 0:
                    self.evaluate()
                if self.ckpt and (epoch + 1) % self.save_every == 0:
                    self._save()
        finally:
            # land any in-flight async write + pending best-copy even on
            # abort (non-finite guard, preemption) BEFORE callbacks that
            # might read the best dir
            if self.ckpt:
                self.ckpt.wait_until_finished()
        self.callbacks.fire("after_train", self)
        # self.epochs, not self.epoch: the loop leaves self.epoch at the
        # last INDEX (epochs-1), and summary only runs on normal exit
        summary = {"epochs": self.epochs, **getattr(self, "_last_eval", {})}
        # omit when the metric never updated (no eval loader): -inf would
        # serialize as the non-standard JSON token -Infinity
        if self.best_value != float("-inf"):
            summary["best_" + self.best_metric] = self.best_value
        self.hub.summary(summary)
        self.hub.close()
        return self.state

    def _train_one_epoch(self, epoch: int) -> None:
        """Sync-free hot loop: the only host↔device round-trips are the
        lagged fetches inside ``self.deferred`` (entries ≥ metrics_lag
        steps old, already resolved) — never the in-flight step."""
        self.train_loader.set_epoch(epoch)
        self.host_step          # seed the host mirror before the loop
        n_iter = len(self.train_loader)
        t_data = time.time()
        for it, batch in enumerate(self.train_loader):
            wall_wait = time.time() - t_data
            # prefer the loader's own queue-empty estimate (actual
            # starvation) over wall-clock-between-iterations, which
            # includes step dispatch time
            loader_wait = getattr(self.train_loader, "last_data_wait",
                                  None)
            data_time = loader_wait if loader_wait is not None else \
                wall_wait
            self.callbacks.fire("before_iter", self, batch=batch)
            self.state, metrics = self.train_step(self.state, batch,
                                                  self.rng)
            self.callbacks.fire("after_iter", self, metrics=metrics)
            self._host_step = self.host_step + 1
            self.deferred.push(metrics, epoch=epoch, it=it,
                               step=self.host_step, n_iter=n_iter,
                               data_time=data_time)
            if it % self.log_every == 0:
                self._consume(self.deferred.poll())
            t_data = time.time()
        # epoch-end barrier: one bulk fetch lands every remaining entry,
        # so short epochs still log and a NaN in the tail still aborts
        self._consume(self.deferred.drain())

    def _consume(self, entries) -> None:
        """Divergence-check every materialized entry, then log the
        newest one (the stale snapshot that stands in for 'now')."""
        if not entries:
            return
        if self.abort_non_finite:
            for meta, host in entries:
                # bad_step is the jitted isfinite(loss) flag; the loss
                # check is the fallback for custom steps that don't
                # provide it (non-finite params keep it latched anyway)
                if host.get("bad_step", 0) > 0 or not np.isfinite(
                        host.get("loss", 0.0)):
                    self.logger.error(
                        f"Loss is {host.get('loss')}, stopping training "
                        f"(epoch {meta['epoch']} it {meta['it']})")
                    raise FloatingPointError(
                        f"non-finite loss {host.get('loss')} at epoch "
                        f"{meta['epoch']} it {meta['it']}")
        meta, host = entries[-1]
        host = {k: v for k, v in host.items() if k != "bad_step"}
        host["data_time"] = meta["data_time"]
        self.meters.update(**host)
        self.logger.info(
            f"epoch {meta['epoch']} it {meta['it']}/{meta['n_iter']} "
            f"{self.meters}")
        self.hub.scalars({f"train/{k}": v for k, v in host.items()},
                         meta["step"])

    # -------------------------------------------------------------- eval
    def evaluate(self) -> Dict[str, float]:
        """Zero-sync eval: every batch's count dict stays on device while
        the loop runs (dispatch only), then ONE ``jax.device_get`` lands
        the whole list. Host-side accumulation order matches the old
        per-batch-float path exactly, so totals are bitwise identical."""
        per_batch = [self.eval_step(self.state, batch)
                     for batch in self.eval_loader]
        host_counts = jax.device_get(per_batch)   # the one materialization
        self.eval_fetches += 1
        totals: Dict[str, float] = defaultdict(float)
        for counts in host_counts:
            for k, v in counts.items():
                totals[k] += float(v)
        results = dict(totals)
        if self.metric_reducer:
            results = self.metric_reducer(results)
        elif "count" in totals and totals["count"] > 0:
            results = {k: v / totals["count"] for k, v in totals.items()
                       if k != "count"}
        self._last_eval = dict(results)
        self.callbacks.fire("on_evaluate", self, results=results)
        self.logger.info(f"eval @ epoch {self.epoch}: "
                         + "  ".join(f"{k}={v:.4f}"
                                     for k, v in results.items()))
        self.hub.scalars({f"eval/{k}": v for k, v in results.items()},
                         self.host_step)
        value = results.get(self.best_metric)
        if value is not None and value > self.best_value:
            self.best_value = value
            if self.ckpt:
                self._save(is_best=True)
        return results

    def _save(self, is_best: bool = False) -> None:
        step = int(self.state.step)
        self.ckpt.save(step, self.state,
                       metrics={self.best_metric: self.best_value},
                       is_best=is_best)
        self.callbacks.fire("on_checkpoint", self, step=step)

    # -------------------------------------------------- throughput mode
    def throughput(self, n_iters: int = 30) -> float:
        """images/sec over n averaged iters (swin main.py:281-300).

        Two passes: a pipelined pass (single end sync) for the honest
        mean images/sec, then a per-iter-synced pass over REAL loader
        batches for step-time percentiles and the data-wait fraction —
        the tail stats a mean hides. Percentiles land in
        ``self.throughput_stats`` and perf_sweep output; the return value
        stays the pipelined images/sec."""
        it = iter(self.train_loader)
        batch = next(it)
        bsz = jax.tree.leaves(batch)[0].shape[0]
        self.state, m = self.train_step(self.state, batch, self.rng)
        float(m["loss"])                      # sync
        t0 = time.perf_counter()
        for _ in range(n_iters):
            self.state, m = self.train_step(self.state, batch, self.rng)
        float(m["loss"])
        dt = (time.perf_counter() - t0) / n_iters
        ips = bsz / dt

        step_times, data_times = [], []
        for _ in range(n_iters):
            t_d = time.perf_counter()
            try:
                batch = next(it)
            except StopIteration:
                it = iter(self.train_loader)
                batch = next(it)
            wait = getattr(self.train_loader, "last_data_wait", None)
            data_times.append(wait if wait is not None
                              else time.perf_counter() - t_d)
            t_s = time.perf_counter()
            self.state, m = self.train_step(self.state, batch, self.rng)
            float(m["loss"])                  # per-iter sync: tail stats
            step_times.append(time.perf_counter() - t_s)
        p50, p90 = np.percentile(step_times, [50, 90])
        busy = sum(step_times) + sum(data_times)
        data_frac = sum(data_times) / busy if busy else 0.0
        self.throughput_stats = {
            "images_per_sec": ips,
            "step_ms_mean": dt * 1e3,
            "step_ms_p50": p50 * 1e3,
            "step_ms_p90": p90 * 1e3,
            "data_wait_frac": data_frac,
            "batch": bsz,
        }
        self.logger.info(
            f"throughput: {ips:.1f} images/s ({dt * 1e3:.1f} ms/iter "
            f"pipelined, p50 {p50 * 1e3:.1f} ms, p90 {p90 * 1e3:.1f} ms, "
            f"data-wait {data_frac:.1%}, batch {bsz})")
        return ips
