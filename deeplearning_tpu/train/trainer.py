"""Hook-structured Trainer — the ONE shared harness (SURVEY.md §1.1 goal).

Merges the three reference archetypes: the simple epoch loop
(classification/mnist/train.py:141), the yacs/DDP/AMP harness features
(swin main.py:84-300: accumulation, auto-resume, save-freq, throughput
mode), and YOLOX's hook skeleton (yolox/core/trainer.py:69-88:
before_train/before_epoch/before_iter/after_iter/after_epoch/after_train)
with yolov5's Callbacks event registry (utils/callbacks.py:8).

The Trainer owns: the jitted steps, the loader epoch protocol
(set_epoch), metric meters, TB writer, Orbax checkpointing with best
tracking, EMA-evaluation, and hook dispatch. Everything device-side stays
in the jitted step functions it is given.
"""

from __future__ import annotations

import time
from collections import defaultdict
from typing import Any, Callable, Dict, List, Optional

import jax
import numpy as np

from ..core import rng as rng_mod
from ..core.checkpoint import CheckpointManager
from ..core.logging import (LoggerHub, MetricLogger,
                            TensorBoardWriter, create_logger,
                            is_main_process)

HOOKS = ("before_train", "after_train", "before_epoch", "after_epoch",
         "before_iter", "after_iter", "on_evaluate", "on_checkpoint")


class Callbacks:
    """Named hook registry (yolov5 utils/callbacks.py surface)."""

    def __init__(self):
        self._hooks: Dict[str, List[Callable]] = defaultdict(list)

    def register(self, event: str, fn: Callable) -> None:
        if event not in HOOKS:
            raise KeyError(f"Unknown hook {event!r}; valid: {HOOKS}")
        self._hooks[event].append(fn)

    def fire(self, event: str, trainer: "Trainer", **kw) -> None:
        for fn in self._hooks[event]:
            fn(trainer, **kw)


class Trainer:
    def __init__(
        self, *,
        state,                                  # TrainState
        train_step: Callable,                   # (state, batch, rng)->...
        train_loader,
        eval_step: Optional[Callable] = None,   # (state, batch)->counts
        eval_loader=None,
        epochs: int = 1,
        seed: int = 0,
        log_every: int = 50,
        eval_every_epochs: int = 1,
        save_every_epochs: int = 1,
        workdir: Optional[str] = None,
        best_metric: str = "top1",
        callbacks: Optional[Callbacks] = None,
        metric_reducer: Optional[Callable[[Dict], Dict]] = None,
        abort_non_finite: bool = True,
        async_checkpoint: bool = False,
        log_backends=("tensorboard", "csv", "jsonl"),
    ):
        self.state = state
        self.train_step = train_step
        self.train_loader = train_loader
        self.eval_step = eval_step
        self.eval_loader = eval_loader
        self.epochs = epochs
        self.log_every = log_every
        self.eval_every = eval_every_epochs
        self.save_every = save_every_epochs
        self.best_metric = best_metric
        self.best_value = float("-inf")
        self.callbacks = callbacks or Callbacks()
        self.metric_reducer = metric_reducer
        self.abort_non_finite = abort_non_finite
        self.logger = create_logger("dltpu", workdir)
        # pluggable backends (yolov5 Loggers shape): tensorboard + csv +
        # offline-W&B jsonl by default; self.tb stays the TB handle for
        # figures/images
        self.hub = LoggerHub(workdir, log_backends)
        self.tb = self.hub.tb
        self.meters = MetricLogger()
        self.rng = rng_mod.host_key(seed)
        self.epoch = 0
        self.ckpt = (CheckpointManager(f"{workdir}/ckpt",
                                       async_save=async_checkpoint)
                     if workdir else None)

    # ------------------------------------------------------------- train
    def train(self) -> Any:
        if self.ckpt:
            restored, step = self.ckpt.auto_resume(self.state)
            if step:
                self.state = restored
                steps_per_epoch = max(len(self.train_loader), 1)
                self.epoch = int(step) // steps_per_epoch
        self.callbacks.fire("before_train", self)
        try:
            for epoch in range(self.epoch, self.epochs):
                self.epoch = epoch
                self.callbacks.fire("before_epoch", self)
                self._train_one_epoch(epoch)
                self.callbacks.fire("after_epoch", self)
                if self.eval_step and self.eval_loader is not None and \
                        (epoch + 1) % self.eval_every == 0:
                    self.evaluate()
                if self.ckpt and (epoch + 1) % self.save_every == 0:
                    self._save()
        finally:
            # land any in-flight async write + pending best-copy even on
            # abort (non-finite guard, preemption) BEFORE callbacks that
            # might read the best dir
            if self.ckpt:
                self.ckpt.wait_until_finished()
        self.callbacks.fire("after_train", self)
        # self.epochs, not self.epoch: the loop leaves self.epoch at the
        # last INDEX (epochs-1), and summary only runs on normal exit
        summary = {"epochs": self.epochs, **getattr(self, "_last_eval", {})}
        # omit when the metric never updated (no eval loader): -inf would
        # serialize as the non-standard JSON token -Infinity
        if self.best_value != float("-inf"):
            summary["best_" + self.best_metric] = self.best_value
        self.hub.summary(summary)
        self.hub.close()
        return self.state

    def _train_one_epoch(self, epoch: int) -> None:
        self.train_loader.set_epoch(epoch)
        t_data = time.time()
        for it, batch in enumerate(self.train_loader):
            data_time = time.time() - t_data
            self.callbacks.fire("before_iter", self, batch=batch)
            self.state, metrics = self.train_step(self.state, batch,
                                                  self.rng)
            self.callbacks.fire("after_iter", self, metrics=metrics)
            if it % self.log_every == 0:
                # scalar fetch both syncs and feeds the meters
                host = {k: float(v) for k, v in metrics.items()}
                # non-finite-loss abort (mnist/utils.py:53-55,
                # fasterRcnn/train_eval_utils.py:44-47). Checked at the
                # sync points: a per-iter device fetch would serialize the
                # TPU pipeline, so divergence is caught within log_every
                # steps rather than instantly.
                if self.abort_non_finite and not np.isfinite(
                        host.get("loss", 0.0)):
                    self.logger.error(
                        f"Loss is {host['loss']}, stopping training "
                        f"(epoch {epoch} it {it})")
                    raise FloatingPointError(
                        f"non-finite loss {host['loss']} at epoch "
                        f"{epoch} it {it}")
                host["data_time"] = data_time
                self.meters.update(**host)
                step = int(self.state.step)
                self.logger.info(
                    f"epoch {epoch} it {it}/{len(self.train_loader)} "
                    f"{self.meters}")
                self.hub.scalars(
                    {f"train/{k}": v for k, v in host.items()}, step)
            t_data = time.time()

    # -------------------------------------------------------------- eval
    def evaluate(self) -> Dict[str, float]:
        totals: Dict[str, float] = defaultdict(float)
        for batch in self.eval_loader:
            counts = self.eval_step(self.state, batch)
            for k, v in counts.items():
                totals[k] += float(v)
        results = dict(totals)
        if self.metric_reducer:
            results = self.metric_reducer(results)
        elif "count" in totals and totals["count"] > 0:
            results = {k: v / totals["count"] for k, v in totals.items()
                       if k != "count"}
        self._last_eval = dict(results)
        self.callbacks.fire("on_evaluate", self, results=results)
        self.logger.info(f"eval @ epoch {self.epoch}: "
                         + "  ".join(f"{k}={v:.4f}"
                                     for k, v in results.items()))
        self.hub.scalars({f"eval/{k}": v for k, v in results.items()},
                         int(self.state.step))
        value = results.get(self.best_metric)
        if value is not None and value > self.best_value:
            self.best_value = value
            if self.ckpt:
                self._save(is_best=True)
        return results

    def _save(self, is_best: bool = False) -> None:
        step = int(self.state.step)
        self.ckpt.save(step, self.state,
                       metrics={self.best_metric: self.best_value},
                       is_best=is_best)
        self.callbacks.fire("on_checkpoint", self, step=step)

    # -------------------------------------------------- throughput mode
    def throughput(self, n_iters: int = 30) -> float:
        """images/sec over n averaged iters (swin main.py:281-300)."""
        it = iter(self.train_loader)
        batch = next(it)
        bsz = jax.tree.leaves(batch)[0].shape[0]
        self.state, m = self.train_step(self.state, batch, self.rng)
        float(m["loss"])                      # sync
        t0 = time.perf_counter()
        for _ in range(n_iters):
            self.state, m = self.train_step(self.state, batch, self.rng)
        float(m["loss"])
        dt = (time.perf_counter() - t0) / n_iters
        ips = bsz / dt
        self.logger.info(f"throughput: {ips:.1f} images/s "
                         f"({dt * 1e3:.1f} ms/iter, batch {bsz})")
        return ips
