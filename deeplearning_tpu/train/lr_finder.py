"""LR range test (SupCon learning_rate_finder.py surface): sweep lr
exponentially over one pass, record smoothed loss, suggest the steepest-
descent lr."""

from __future__ import annotations

from typing import Callable, Dict, List, Tuple

import jax
import numpy as np
import optax


def lr_range_test(
    make_state: Callable[[optax.Schedule], object],
    train_step_factory: Callable[[object], Callable],
    batches,
    min_lr: float = 1e-7,
    max_lr: float = 1.0,
    beta: float = 0.98,
) -> Dict[str, np.ndarray]:
    """make_state(schedule) builds a fresh TrainState with the given lr
    schedule; train_step_factory(state) returns the jitted step. Returns
    {lrs, losses, suggestion}."""
    batches = list(batches)
    n = len(batches)
    lrs = np.exp(np.linspace(np.log(min_lr), np.log(max_lr), n))

    def schedule(step):
        import jax.numpy as jnp
        idx = jnp.clip(step, 0, n - 1)
        return jnp.asarray(lrs)[idx]

    state = make_state(schedule)
    step_fn = train_step_factory(state)
    rng = jax.random.key(0)
    avg = 0.0
    smoothed: List[float] = []
    best = np.inf
    for i, batch in enumerate(batches):
        state, metrics = step_fn(state, batch, rng)
        loss = float(metrics["loss"])
        avg = beta * avg + (1 - beta) * loss
        corrected = avg / (1 - beta ** (i + 1))
        smoothed.append(corrected)
        best = min(best, corrected)
        if corrected > 4 * best and i > n // 10:   # diverged: stop early
            lrs = lrs[: i + 1]
            break
    losses = np.asarray(smoothed)
    # steepest negative slope of smoothed loss; skip the warmup-biased
    # first 10% of points (standard LR-finder practice)
    if len(losses) > 2:
        slopes = np.gradient(losses, np.log(lrs[: len(losses)]))
        skip = max(len(slopes) // 10, 1)
        suggestion = float(lrs[skip + int(np.argmin(slopes[skip:]))])
    else:
        suggestion = float(lrs[0])
    return {"lrs": lrs[: len(losses)], "losses": losses,
            "suggestion": suggestion}
