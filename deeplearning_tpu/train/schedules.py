"""Per-iteration LR schedules.

Port surface (not code) of the reference's schedulers: cosine LambdaLR with
warmup (classification/mnist/train.py:130-137), timm-style warmup-cosine
stepped per iteration (swin utils/lr_scheduler.py:7), YOLOX "yoloxwarmcos"
with quadratic warmup + no-aug floor (yolox/utils/lr_scheduler.py), poly
schedule with warmup for segmentation (FCN utils/train_and_eval.py:65),
multi-step decay. All are optax schedules (step -> lr), jit-safe.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

import optax

from ..core.registry import SCHEDULES


@SCHEDULES.register("constant")
def constant(base_lr: float, total_steps: int = 0, **_) -> optax.Schedule:
    return optax.constant_schedule(base_lr)


@SCHEDULES.register("warmup_cosine")
def warmup_cosine(base_lr: float, total_steps: int,
                  warmup_steps: int = 0, warmup_lr: float = 1e-7,
                  min_lr: float = 0.0, **_) -> optax.Schedule:
    """Linear warmup then cosine to min_lr (swin lr_scheduler.py:7)."""
    return optax.warmup_cosine_decay_schedule(
        init_value=warmup_lr, peak_value=base_lr,
        warmup_steps=max(warmup_steps, 1),
        decay_steps=max(total_steps, warmup_steps + 1),
        end_value=min_lr)


@SCHEDULES.register("cosine_lambda")
def cosine_lambda(base_lr: float, total_steps: int, lrf: float = 0.1,
                  **_) -> optax.Schedule:
    """The archetype-A cosine LambdaLR: lr(t) = base*((1+cos(pi t/T))/2*(1-lrf)+lrf)
    (classification/mnist/train.py:133-137)."""
    def sched(step):
        t = optax.cosine_decay_schedule(1.0, max(total_steps, 1))(step)
        # cosine_decay returns (1+cos)/2 shape already via alpha=0
        return base_lr * (t * (1 - lrf) + lrf)
    return sched


@SCHEDULES.register("yolox_warmcos")
def yolox_warmcos(base_lr: float, total_steps: int, warmup_steps: int = 0,
                  warmup_lr_start: float = 0.0, min_lr_ratio: float = 0.05,
                  no_aug_steps: int = 0, **_) -> optax.Schedule:
    """Quadratic warmup -> cosine -> flat floor during no-aug epochs
    (YOLOX yolox/utils/lr_scheduler.py)."""
    min_lr = base_lr * min_lr_ratio

    def sched(step):
        import jax.numpy as jnp
        step = jnp.asarray(step, jnp.float32)
        warm = (base_lr - warmup_lr_start) * jnp.square(
            step / max(warmup_steps, 1)) + warmup_lr_start
        main_span = max(total_steps - warmup_steps - no_aug_steps, 1)
        cos = min_lr + 0.5 * (base_lr - min_lr) * (
            1.0 + jnp.cos(math.pi * (step - warmup_steps) / main_span))
        lr = jnp.where(step < warmup_steps, warm,
                       jnp.where(step >= total_steps - no_aug_steps,
                                 min_lr, cos))
        return lr
    return sched


@SCHEDULES.register("poly")
def poly(base_lr: float, total_steps: int, warmup_steps: int = 0,
         power: float = 0.9, warmup_factor: float = 1e-3, **_) -> optax.Schedule:
    """Poly decay with linear warmup (FCN utils/train_and_eval.py:65)."""
    def sched(step):
        import jax.numpy as jnp
        step = jnp.asarray(step, jnp.float32)
        alpha = step / max(warmup_steps, 1)
        warm = base_lr * (warmup_factor * (1 - alpha) + alpha)
        frac = 1.0 - (step - warmup_steps) / max(total_steps - warmup_steps, 1)
        main = base_lr * jnp.power(jnp.clip(frac, 0.0, 1.0), power)
        return jnp.where(step < warmup_steps, warm, main)
    return sched


@SCHEDULES.register("multistep")
def multistep(base_lr: float, milestones: Sequence[int] = (),
              gamma: float = 0.1, warmup_steps: int = 0, **_) -> optax.Schedule:
    sched = optax.piecewise_constant_schedule(
        base_lr, {int(m): gamma for m in milestones})
    if warmup_steps:
        return optax.join_schedules(
            [optax.linear_schedule(0.0, base_lr, warmup_steps), sched],
            [warmup_steps])
    return sched


def build_schedule(name: str, **kwargs) -> optax.Schedule:
    return SCHEDULES.build(name, **kwargs)
