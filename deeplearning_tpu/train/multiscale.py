"""Multi-scale detection training via bucketed static shapes.

Reference behavior: yolov5 randomly rescales the batch to imgsz×[0.5,
1.5] each iter with the size broadcast from rank 0 (detection/yolov5/
train.py:357), and YOLOX's Exp.random_resize picks a size from
[448..832]/32 every 10 iters (detection/YOLOX/yolox/exp/
yolox_base.py:167, applied in trainer preprocess).

TPU-native form: XLA compiles one executable per static input shape, so
"random resize" becomes a FIXED bucket list — the jitted train step
retraces once per bucket (compile cache holds all of them; steady state
has zero recompiles), and the bucket choice is a counter-based pure
function of (seed, step), so every host/process picks the same size
with no broadcast collective (the rank-0 torch.distributed broadcast
becomes unnecessary by construction).
"""

from __future__ import annotations

from typing import Dict, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# YOLOX default buckets: [448..832] step 32 (yolox_base.py random_size
# range (10, 20) × 32)
YOLOX_SIZES: Tuple[int, ...] = tuple(range(448, 833, 32))


class MultiScaleSchedule:
    """Deterministic bucketed size schedule.

    ``size_for_step(step)`` returns the training size for a global step:
    constant within windows of ``change_every`` steps, pseudo-random
    across windows, identical on every host for the same seed.
    """

    def __init__(self, sizes: Sequence[int] = YOLOX_SIZES,
                 change_every: int = 10, seed: int = 0):
        if not sizes:
            raise ValueError("need at least one size bucket")
        self.sizes = tuple(int(s) for s in sizes)
        self.change_every = max(int(change_every), 1)
        self.seed = seed

    def size_for_step(self, step: int) -> int:
        window = int(step) // self.change_every
        idx = np.random.default_rng(
            [self.seed, window]).integers(len(self.sizes))
        return self.sizes[int(idx)]

    def __iter__(self):
        step = 0
        while True:
            yield self.size_for_step(step)
            step += 1


def resize_detection_batch(batch: Dict[str, jax.Array], size: int,
                           method: str = "bilinear"
                           ) -> Dict[str, jax.Array]:
    """Resize a padded detection batch to (size, size), scaling the box
    pixel coordinates by the same ratios (the target-rescale half of
    yolox random_resize). No-op when already at the target size."""
    imgs = batch["image"]
    b, h, w, c = imgs.shape
    if (h, w) == (size, size):
        return batch
    out = dict(batch)
    out["image"] = jax.image.resize(
        imgs, (b, size, size, c), method)
    if "boxes" in batch:
        sx, sy = size / w, size / h
        out["boxes"] = batch["boxes"] * jnp.asarray(
            [sx, sy, sx, sy], batch["boxes"].dtype)
    return out


def make_multiscale_step(step_fn, schedule: MultiScaleSchedule,
                         resize=resize_detection_batch,
                         start_step: int = 0):
    """Wrap a jitted train step: each call resizes the host batch to the
    scheduled bucket before invoking the step. ``step_fn`` retraces once
    per bucket; steady-state runs entirely from the executable cache.

    The step counter is host-side (seed with ``start_step`` when
    resuming): reading ``state.step`` back from the device every iter
    would force a D2H sync and serialize the async dispatch pipeline.
    """
    counter = {"n": int(start_step)}

    def wrapped(state, batch, *rest):
        size = schedule.size_for_step(counter["n"])
        counter["n"] += 1
        return step_fn(state, resize(batch, size), *rest)

    return wrapped
