"""Optimizer builders with parameter-group filtering + LARS.

Covers the reference's optimizer surface: SGD/Adam/AdamW with weight-decay
exclusion of norm/bias/special params (swin utils/optimizer.py:11-58
set_weight_decay keywords; yolov5 train.py three param groups), and the
LARS/LARC wrapper used for MAE pretrain (self-supervised/MAE/utils/
LARS.py:6). All expressed as optax chains with masks, so they compose with
any schedule and with gradient clipping.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence

import jax
import optax

from ..core.registry import OPTIMIZERS
from ..parallel.sharding import tree_paths

NO_DECAY_PATTERNS = ("bias", "scale", "norm", "bn", "pos_embed", "cls_token",
                     "relative_position_bias", "absolute_pos_embed", "logit_scale")


def decay_mask(params: Any,
               no_decay: Sequence[str] = NO_DECAY_PATTERNS) -> Any:
    """True where weight decay applies: 2D+ kernels, excluding listed names.
    1D params (biases, norm scales) never decay — matches the reference's
    keyword skip-list (swin optimizer.py:42-58)."""
    paths = tree_paths(params)

    def keep(path: str, leaf: Any) -> bool:
        lp = path.lower()
        if any(p in lp for p in no_decay):
            return False
        import numpy as np
        return np.ndim(leaf) >= 2
    return jax.tree.map(keep, paths, params)


@OPTIMIZERS.register("sgd")
def sgd(schedule, momentum: float = 0.9, nesterov: bool = False,
        weight_decay: float = 0.0, params: Any = None, **_):
    chain = []
    if weight_decay:
        chain.append(optax.add_decayed_weights(
            weight_decay, mask=decay_mask(params) if params is not None else None))
    chain.append(optax.sgd(schedule, momentum=momentum, nesterov=nesterov))
    return optax.chain(*chain)


@OPTIMIZERS.register("adam")
def adam(schedule, b1: float = 0.9, b2: float = 0.999, **_):
    return optax.adam(schedule, b1=b1, b2=b2)


@OPTIMIZERS.register("adamw")
def adamw(schedule, b1: float = 0.9, b2: float = 0.999,
          weight_decay: float = 0.05, eps: float = 1e-8,
          params: Any = None, **_):
    return optax.adamw(
        schedule, b1=b1, b2=b2, eps=eps, weight_decay=weight_decay,
        mask=decay_mask(params) if params is not None else None)


@OPTIMIZERS.register("lars")
def lars(schedule, momentum: float = 0.9, weight_decay: float = 0.0,
         trust_coefficient: float = 0.001, params: Any = None, **_):
    """LARS for large-batch SSL pretrain (MAE utils/LARS.py:6 LARC port —
    optax.lars implements the same layer-wise trust ratio)."""
    return optax.lars(
        schedule, weight_decay=weight_decay,
        weight_decay_mask=decay_mask(params) if params is not None else True,
        trust_coefficient=trust_coefficient, momentum=momentum)


def freeze_mask(params: Any, frozen: Sequence[str]) -> Any:
    """True where the param path matches a frozen pattern. The reference
    freezes via requires_grad=False — backbone freezing in fasterRcnn
    change_backbone_with*.py, staged fine-tuning in TransFG — and via
    FrozenBatchNorm2d (fasterRcnn/models/backbone/resnet50_fpn.py:5).
    Here the same effect is an optax mask that zeroes the updates; for
    frozen BN also run the layer with use_running_average so the stats
    stay put.

    Patterns match whole '/'-separated path components (possibly
    multi-segment, e.g. "backbone/conv1"), so freeze=("blocks_1",) does
    NOT also catch blocks_10/blocks_11 — the same boundary rule yolov5's
    freeze list applies by matching 'model.{x}.' with the trailing dot."""
    paths = tree_paths(params)

    def match(path: str) -> bool:
        padded = f"/{path.lower()}/"
        return any(f"/{p.lower().strip('/')}/" in padded for p in frozen)
    return jax.tree.map(lambda path, _: match(path), paths, params)


def build_optimizer(name: str, schedule, clip_grad_norm: Optional[float] = None,
                    params: Any = None,
                    freeze: Optional[Sequence[str]] = None,
                    **kwargs) -> optax.GradientTransformation:
    """Optimizer chain with optional global-norm clipping in front (the
    reference clips before step inside its AMP scaler,
    swin utils/torch_utils.py:303-318) and optional parameter freezing
    (path-substring patterns, e.g. freeze=("patch_embed", "blocks_0"))."""
    tx = OPTIMIZERS.build(name, schedule, params=params, **kwargs)
    if clip_grad_norm and clip_grad_norm > 0:
        tx = optax.chain(optax.clip_by_global_norm(clip_grad_norm), tx)
    if freeze:
        if params is None:
            raise ValueError("freeze patterns require params to build the mask")
        mask = freeze_mask(params, freeze)
        # Zero frozen grads BEFORE the clip so the global norm only counts
        # trainable params (requires_grad=False semantics: frozen grads don't
        # exist, so they must not shrink everyone else's clip budget), and
        # zero the FINAL updates AFTER the optimizer: decoupled weight decay
        # would otherwise still move frozen params.
        tx = optax.chain(
            optax.masked(optax.set_to_zero(), mask),
            tx,
            optax.masked(optax.set_to_zero(), mask))
    return tx
