"""Deferred device-metrics pipeline — the sync-free half of the Trainer.

The hot loop's MFU ceiling is set by host↔device round-trips, not
matmuls: every ``float(metrics["loss"])`` at a log point stalls the TPU
dispatch queue until the in-flight step retires (arXiv:2004.13336 makes
the same argument for weight-update overhead; the Gemma-on-TPU writeups
attribute the last few MFU points to host-loop overlap).

``DeferredMetrics`` removes the stall by decoupling *enqueue* from
*materialize*: the Trainer pushes the device-scalar metrics dict of every
step (a reference append — free), and only entries at least ``lag``
pushes old are ever fetched. By then the corresponding step has long
retired, so the D2H copy returns already-resolved buffers and costs
microseconds instead of a pipeline flush. All ready entries are fetched
in ONE ``jax.device_get`` call, so a poll is a single sync event no
matter how many steps it covers.

``window=W`` adds an ON-DEVICE windowed reduction (ROADMAP follow-up):
instead of holding W per-step dicts and fetching W trees per log point,
every push folds the step's metrics into a device-resident running sum
(one tiny fused add dispatch — async, never syncs), and a completed
window materializes as ONE dict of means. Host work per step and fetch
volume per log point both stay O(1) however large ``log_every`` grows.
Divergence detection survives the reduction: ``bad_step`` is summed, so
"any bad step in the window" is just ``sum > 0``, and a NaN loss
poisons the window mean.

``fetch_count`` counts sync EVENTS (one per materializing poll/drain),
``fetched_entries`` counts entries (windows, in windowed mode); both are
the instrumentation surface the zero-sync smoke test asserts on.
"""

from __future__ import annotations

import collections
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

Entry = Tuple[Dict[str, Any], Dict[str, float]]   # (meta, host metrics)

# metric keys reported as window SUMS, not means (latched flags where
# "did it ever fire" is the question)
_SUM_KEYS = ("bad_step",)


@jax.jit
def _accum(acc, tree):
    """One fused device add per push — the O(1) windowed reduction."""
    return jax.tree.map(jnp.add, acc, tree)


class DeferredMetrics:
    """FIFO ring of (meta, device-metrics) entries with lagged fetch.

    - ``push(tree, **meta)``: enqueue one step's device-scalar dict plus
      host-side metadata (epoch, it, data_time, ...). Never syncs.
    - ``poll()``: materialize (oldest-first) every entry that has at
      least ``lag`` newer pushes behind it; returns ``[(meta, host)]``.
      One ``jax.device_get`` per call that returns anything.
    - ``drain()``: materialize everything still buffered (epoch end /
      shutdown barrier).
    - ``window=W``: device-side reduction — pushes fold into a running
      sum, completed windows surface as single mean dicts (meta of the
      window's LAST step). ``lag`` then counts pushes since the window
      closed, so a fetch still never touches an in-flight step.
    """

    def __init__(self, lag: int = 1, window: Optional[int] = None):
        self.lag = max(int(lag), 0)
        self.window = max(int(window), 1) if window else None
        self._buf: collections.deque = collections.deque()
        self.fetch_count = 0        # sync events (materializing calls)
        self.fetched_entries = 0    # entries materialized in total
        # open-window accumulation state (window mode only)
        self._push_idx = 0
        self._open_acc = None
        self._open_n = 0
        self._open_meta: Dict[str, Any] = {}

    def push(self, tree: Dict[str, Any], **meta: Any) -> None:
        self._push_idx += 1
        if self.window is None:
            self._buf.append((meta, tree))
            return
        self._open_acc = (tree if self._open_acc is None
                          else _accum(self._open_acc, tree))
        self._open_n += 1
        self._open_meta = meta
        if self._open_n >= self.window:
            self._close_window()

    def _close_window(self) -> None:
        if not self._open_n:
            return
        self._buf.append((self._open_meta, self._open_acc, self._open_n,
                          self._push_idx))
        self._open_acc, self._open_n, self._open_meta = None, 0, {}

    @property
    def pending(self) -> int:
        return len(self._buf) + (1 if self._open_n else 0)

    def __len__(self) -> int:
        return self.pending

    def poll(self) -> List[Entry]:
        ready = []
        if self.window is None:
            while len(self._buf) > self.lag:
                ready.append(self._buf.popleft())
        else:
            # a closed window is ready once >= lag pushes happened after
            # it closed — its newest contribution resolved long ago
            while self._buf and \
                    self._push_idx - self._buf[0][3] >= self.lag:
                ready.append(self._buf.popleft())
        return self._materialize(ready)

    def drain(self) -> List[Entry]:
        if self.window is not None:
            self._close_window()
        ready = list(self._buf)
        self._buf.clear()
        return self._materialize(ready)

    def _materialize(self, entries) -> List[Entry]:
        if not entries:
            return []
        self.fetch_count += 1
        self.fetched_entries += len(entries)
        # one bulk transfer for every ready tree: a poll is ONE sync
        # event regardless of how many steps it covers
        if self.window is None:
            # dltpu: allow(DLT100) THE designed sync: one lagged bulk fetch
            host_trees = jax.device_get([tree for _, tree in entries])
            return [(meta, {k: float(v) for k, v in host.items()})
                    for (meta, _), host in zip(entries, host_trees)]
        # dltpu: allow(DLT100) THE designed sync: one fetch per closed window
        host_trees = jax.device_get([acc for _, acc, _, _ in entries])
        out: List[Entry] = []
        for (meta, _, n, _), host in zip(entries, host_trees):
            out.append((meta, {
                k: float(v) if k in _SUM_KEYS else float(v) / n
                for k, v in host.items()}))
        return out
