"""Deferred device-metrics pipeline — the sync-free half of the Trainer.

The hot loop's MFU ceiling is set by host↔device round-trips, not
matmuls: every ``float(metrics["loss"])`` at a log point stalls the TPU
dispatch queue until the in-flight step retires (arXiv:2004.13336 makes
the same argument for weight-update overhead; the Gemma-on-TPU writeups
attribute the last few MFU points to host-loop overlap).

``DeferredMetrics`` removes the stall by decoupling *enqueue* from
*materialize*: the Trainer pushes the device-scalar metrics dict of every
step (a reference append — free), and only entries at least ``lag``
pushes old are ever fetched. By then the corresponding step has long
retired, so the D2H copy returns already-resolved buffers and costs
microseconds instead of a pipeline flush. All ready entries are fetched
in ONE ``jax.device_get`` call, so a poll is a single sync event no
matter how many steps it covers.

``fetch_count`` counts sync EVENTS (one per materializing poll/drain),
``fetched_entries`` counts entries; both are the instrumentation surface
the zero-sync smoke test asserts on.
"""

from __future__ import annotations

import collections
from typing import Any, Dict, List, Tuple

import jax

Entry = Tuple[Dict[str, Any], Dict[str, float]]   # (meta, host metrics)


class DeferredMetrics:
    """FIFO ring of (meta, device-metrics) entries with lagged fetch.

    - ``push(tree, **meta)``: enqueue one step's device-scalar dict plus
      host-side metadata (epoch, it, data_time, ...). Never syncs.
    - ``poll()``: materialize (oldest-first) every entry that has at
      least ``lag`` newer entries behind it; returns ``[(meta, host)]``.
      One ``jax.device_get`` per call that returns anything.
    - ``drain()``: materialize everything still buffered (epoch end /
      shutdown barrier).
    """

    def __init__(self, lag: int = 1):
        self.lag = max(int(lag), 0)
        self._buf: collections.deque = collections.deque()
        self.fetch_count = 0        # sync events (materializing calls)
        self.fetched_entries = 0    # entries materialized in total

    def push(self, tree: Dict[str, Any], **meta: Any) -> None:
        self._buf.append((meta, tree))

    @property
    def pending(self) -> int:
        return len(self._buf)

    def __len__(self) -> int:
        return len(self._buf)

    def poll(self) -> List[Entry]:
        ready = []
        while len(self._buf) > self.lag:
            ready.append(self._buf.popleft())
        return self._materialize(ready)

    def drain(self) -> List[Entry]:
        ready = list(self._buf)
        self._buf.clear()
        return self._materialize(ready)

    def _materialize(self, entries) -> List[Entry]:
        if not entries:
            return []
        self.fetch_count += 1
        self.fetched_entries += len(entries)
        # one bulk transfer for every ready tree: a poll is ONE sync
        # event regardless of how many steps it covers
        host_trees = jax.device_get([tree for _, tree in entries])
        return [(meta, {k: float(v) for k, v in host.items()})
                for (meta, _), host in zip(entries, host_trees)]
