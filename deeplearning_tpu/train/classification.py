"""Classification task wiring: loss_fn + metric_fn for the shared step.

The per-batch logic of every archetype-A/B project's train_one_epoch /
evaluate pair (classification/mnist/utils.py:30-90, swin main.py:171-278)
expressed as the two pure functions the jitted steps consume. Supports
integer labels, label smoothing, and mixup soft targets (swin
main.py:111-118 criterion selection).
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

from ..evaluation.metrics import topk_correct
from ..ops import losses
from .state import TrainState


def make_loss_fn(label_smoothing: float = 0.0, has_batch_stats: bool = False,
                 aux_weight: float = 0.3):
    """``aux_weight`` handles models returning (logits, aux_logits_tuple)
    in train mode (GoogLeNet aux heads — the reference harness weighs the
    aux CE by 0.3)."""
    def loss_fn(params: Any, state: TrainState, batch: Dict, rng: jax.Array
                ) -> Tuple[jax.Array, Dict]:
        variables = state.variables(params)
        kwargs = dict(train=True, rngs={"dropout": rng})
        aux: Dict[str, Any] = {}
        # "losses" collects model-internal auxiliary losses (e.g. MoE
        # load-balance, sown by MoEMlp) — always harvested into the loss
        logits, mutated = state.apply_fn(
            variables, batch["image"],
            mutable=["batch_stats", "losses", "moe_metrics"],
            **kwargs)
        if has_batch_stats:
            aux["batch_stats"] = mutated["batch_stats"]
        model_aux_losses = jax.tree.leaves(mutated.get("losses", {}))
        aux_logits = ()
        if isinstance(logits, tuple):
            logits, aux_logits = logits
        labels = batch["label"]
        if labels.ndim == logits.ndim:          # mixup soft targets
            loss = losses.soft_target_cross_entropy(logits, labels)
            acc_labels = jnp.argmax(labels, -1)
        else:
            loss = losses.cross_entropy(logits, labels, label_smoothing)
            acc_labels = labels
        for a in aux_logits:
            if a is not None and labels.ndim < logits.ndim + 1:
                loss = loss + aux_weight * losses.cross_entropy(
                    a, acc_labels, label_smoothing)
        for al in model_aux_losses:
            loss = loss + al
        acc = jnp.mean((jnp.argmax(logits, -1) == acc_labels).astype(
            jnp.float32))
        aux["metrics"] = {"accuracy": acc}
        # surface per-layer MoE routing health as step metrics (mean over
        # layers for drop/util, max over layers for load imbalance)
        moe = mutated.get("moe_metrics", {})
        if moe:
            known = ("drop_rate", "capacity_util", "max_expert_load")
            by_name: Dict[str, list] = {}
            for path, leaf in jax.tree_util.tree_leaves_with_path(moe):
                pstr = jax.tree_util.keystr(path)
                name = next((k for k in known if k in pstr), None)
                if name is None:
                    continue
                by_name.setdefault(name, []).append(jnp.mean(leaf))
            for name, vals in by_name.items():
                stacked = jnp.stack(vals)
                aux["metrics"][f"moe/{name}"] = (
                    jnp.max(stacked) if name == "max_expert_load"
                    else jnp.mean(stacked))
        return loss, aux
    return loss_fn


def make_metric_fn(ks=(1, 5)):
    def metric_fn(params: Any, state: TrainState, batch: Dict) -> Dict:
        logits = state.apply_fn(state.variables(params), batch["image"],
                                train=False)
        counts = topk_correct(logits, batch["label"], ks)
        counts["loss_sum"] = losses.cross_entropy(
            logits, batch["label"]) * batch["label"].shape[0]
        return counts
    return metric_fn
