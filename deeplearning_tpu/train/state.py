"""TrainState: params + optimizer + step + EMA + batch stats, one pytree.

The reference scatters this state across objects per-project: model,
optimizer, lr_scheduler, GradScaler, epoch, max_accuracy, and a separate
ModelEMA deep-copy (YOLOX yolox/utils/ema.py:22, yolov5
utils/torch_utils.py:308). Here it is one flat pytree so the whole training
state jits, shards, and checkpoints atomically.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import flax.struct
import jax
import jax.numpy as jnp
import optax


class TrainState(flax.struct.PyTreeNode):
    step: jax.Array
    params: Any
    opt_state: optax.OptState
    batch_stats: Any = None          # mutable BN stats ({} for stateless nets)
    ema_params: Any = None           # decayed shadow of params (None = off)
    ema_decay: float = flax.struct.field(pytree_node=False, default=0.9998)
    tx: optax.GradientTransformation = flax.struct.field(pytree_node=False,
                                                         default=None)
    apply_fn: Callable = flax.struct.field(pytree_node=False, default=None)

    @classmethod
    def create(cls, *, apply_fn: Callable, params: Any,
               tx: optax.GradientTransformation,
               batch_stats: Any = None,
               use_ema: bool = False, ema_decay: float = 0.9998) -> "TrainState":
        return cls(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=tx.init(params),
            batch_stats=batch_stats if batch_stats is not None else {},
            ema_params=jax.tree.map(jnp.copy, params) if use_ema else None,
            ema_decay=ema_decay,
            tx=tx,
            apply_fn=apply_fn,
        )

    def apply_gradients(self, grads: Any, new_batch_stats: Any = None
                        ) -> "TrainState":
        updates, new_opt_state = self.tx.update(grads, self.opt_state,
                                                self.params)
        new_params = optax.apply_updates(self.params, updates)
        new_ema = self.ema_params
        if new_ema is not None:
            # YOLOX-style warmup-aware decay: d = decay*(1-exp(-step/2000))
            # (yolox/utils/ema.py:40) keeps early EMA close to raw params.
            d = self.ema_decay * (1.0 - jnp.exp(-(self.step + 1) / 2000.0))
            new_ema = jax.tree.map(lambda e, p: e * d + p.astype(e.dtype) * (1 - d),
                                   new_ema, new_params)
        return self.replace(
            step=self.step + 1,
            params=new_params,
            opt_state=new_opt_state,
            batch_stats=(new_batch_stats if new_batch_stats is not None
                         else self.batch_stats),
            ema_params=new_ema,
        )

    def shard_summary(self) -> dict:
        """JSON-able layout description (which leaves are sharded, how)
        — embedded in checkpoint topology sidecars so a cross-topology
        resume can report the layout it is resharding FROM."""
        from ..parallel.sharding import shard_layout_summary
        return shard_layout_summary(
            {"params": self.params, "opt_state": self.opt_state})

    @property
    def eval_params(self) -> Any:
        return self.ema_params if self.ema_params is not None else self.params

    def variables(self, params: Optional[Any] = None) -> dict:
        v = {"params": params if params is not None else self.params}
        if self.batch_stats:
            v["batch_stats"] = self.batch_stats
        return v
