"""Genetic hyperparameter evolution (yolov5 ``--evolve`` equivalent).

Reference behavior (detection/yolov5/train.py:637-716): keep a results
file across generations; each generation picks a parent from the top-5
previous runs by fitness (weighted random), multiplies each evolvable
hyperparameter by a clipped gaussian gain (mutation prob 0.8, sigma 0.2,
per-gene gain scale from a meta table, clip [0.3, 3.0], retry until
something changes), clamps to per-gene [low, high] bounds, trains, and
appends the result. Fitness for detection is the weighted metric mix
0.1·mAP@50 + 0.9·mAP (utils/metrics.py:15).

Differences here: records are JSONL (one {"fitness", "hyp"} object per
generation — append-only and resumable like evolve.csv), randomness comes
from a caller-seeded ``numpy.random.Generator`` instead of time-seeding,
and the train step is any callable ``hyp -> fitness`` so the same driver
evolves detection, classification, or a unit-test toy identically.
"""

from __future__ import annotations

import json
import os
from typing import Callable, Dict, Mapping, Optional, Sequence, Tuple

import numpy as np

__all__ = ["det_fitness", "mutate", "evolve", "load_records", "best_hyp",
           "DETECTION_META"]

# (mutation gain 0-1, lower, upper) per evolvable hyperparameter — the
# subset of yolov5's meta table (train.py:637-666) that maps onto this
# framework's detection hyps.
DETECTION_META: Dict[str, Tuple[float, float, float]] = {
    "lr": (1.0, 1e-5, 1e-1),
    "final_lr_frac": (1.0, 0.01, 1.0),
    "momentum": (0.3, 0.6, 0.98),
    "weight_decay": (1.0, 0.0, 0.001),
    "warmup_frac": (1.0, 0.0, 0.2),
    "box_gain": (1.0, 0.02, 0.2),
    "cls_gain": (1.0, 0.2, 4.0),
    "obj_gain": (1.0, 0.2, 4.0),
    "hsv_h": (1.0, 0.0, 0.1),
    "hsv_s": (1.0, 0.0, 0.9),
    "hsv_v": (1.0, 0.0, 0.9),
    "translate": (1.0, 0.0, 0.9),
    "scale": (1.0, 0.0, 0.9),
    "fliplr": (0.0, 0.0, 1.0),
    "mosaic": (1.0, 0.0, 1.0),
    "mixup": (1.0, 0.0, 1.0),
}


def det_fitness(metrics: Mapping[str, float]) -> float:
    """0.1·AP50 + 0.9·AP(0.5:0.95) — the reference's model-selection
    score (yolov5 utils/metrics.py:15 fitness, w=[0, 0, 0.1, 0.9]).
    Accepts either this repo's CocoEvaluator keys (AP/AP50) or
    lowercase."""
    ap = metrics.get("AP", metrics.get("ap", 0.0))
    ap50 = metrics.get("AP50", metrics.get("ap50", 0.0))
    return 0.1 * float(ap50) + 0.9 * float(ap)


def load_records(path: str) -> list:
    if not os.path.exists(path):
        return []
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def best_hyp(path: str) -> Optional[Dict[str, float]]:
    recs = load_records(path)
    if not recs:
        return None
    return max(recs, key=lambda r: r["fitness"])["hyp"]


def _select_parent(records: Sequence[dict],
                   rng: np.random.Generator, top_n: int = 5
                   ) -> Dict[str, float]:
    top = sorted(records, key=lambda r: -r["fitness"])[:top_n]
    fit = np.array([r["fitness"] for r in top])
    w = fit - fit.min() + 1e-6
    idx = rng.choice(len(top), p=w / w.sum())
    return dict(top[idx]["hyp"])


def mutate(hyp: Mapping[str, float],
           meta: Mapping[str, Tuple[float, float, float]],
           rng: np.random.Generator,
           mutation_prob: float = 0.8, sigma: float = 0.2
           ) -> Dict[str, float]:
    """One mutation: multiply each gene by a clipped gaussian gain,
    retrying until at least one gene changes, then clamp to bounds.
    Genes with mutation gain 0 are immutable; if nothing is mutable the
    hyp is returned unchanged (the retry loop could never exit)."""
    keys = [k for k in hyp if k in meta and meta[k][0] > 0]
    if not keys:
        return dict(hyp)
    gains = np.array([meta[k][0] for k in keys])
    v = np.ones(len(keys))
    while np.all(v == 1.0):
        v = (gains * (rng.random(len(keys)) < mutation_prob)
             * rng.standard_normal(len(keys)) * rng.random() * sigma
             + 1.0).clip(0.3, 3.0)
    out = dict(hyp)
    for k, g in zip(keys, v):
        lo, hi = meta[k][1], meta[k][2]
        out[k] = round(float(np.clip(hyp[k] * g, lo, hi)), 5)
    return out


def evolve(eval_fn: Callable[[Dict[str, float]], float],
           hyp0: Mapping[str, float],
           meta: Mapping[str, Tuple[float, float, float]],
           generations: int,
           records_path: str,
           seed: int = 0,
           top_n: int = 5,
           mutation_prob: float = 0.8,
           sigma: float = 0.2) -> Dict[str, float]:
    """Run ``generations`` evolution steps, appending each result to
    ``records_path`` (resumable: existing records seed the parent pool).
    ``eval_fn(hyp) -> fitness`` trains/evaluates one mutation — wrap
    ``det_fitness`` around a detection eval for the reference semantics.
    Returns the best hyp seen (including prior records)."""
    rng = np.random.default_rng(seed)
    records = load_records(records_path)
    os.makedirs(os.path.dirname(records_path) or ".", exist_ok=True)
    for _ in range(generations):
        if records:
            parent = _select_parent(records, rng, top_n)
            hyp = mutate(parent, meta, rng, mutation_prob, sigma)
        else:
            hyp = {k: round(float(v), 5) for k, v in hyp0.items()}
            # clamp the seed hyp too so eval always sees legal values
            for k, (_, lo, hi) in meta.items():
                if k in hyp:
                    hyp[k] = round(float(np.clip(hyp[k], lo, hi)), 5)
        fitness = float(eval_fn(dict(hyp)))
        rec = {"fitness": fitness, "hyp": hyp}
        records.append(rec)
        with open(records_path, "a") as f:
            f.write(json.dumps(rec) + "\n")
    return best_hyp(records_path)
