from . import lr_finder, multiscale, optim, schedules, trainer  # noqa: F401
from .async_metrics import DeferredMetrics  # noqa: F401
from .recovery import (RecoveryExhausted, RecoveryManager,  # noqa: F401
                       RecoveryPolicy)
from .state import TrainState  # noqa: F401
from .steps import make_train_step, make_eval_step, shard_state  # noqa: F401
