"""Jitted train/eval steps: GSPMD sharding, grad accumulation, remat.

This single function replaces the reference's per-project hot loops
(classification/mnist/utils.py:30 train_one_epoch; swin main.py:171-229
with AMP scaler + accumulation; YOLOX trainer.py:90 train_one_iter):

- data parallelism: the batch is sharded over the mesh's data axes and the
  loss is a mean over the GLOBAL batch, so ``jax.grad`` under GSPMD yields
  exactly DDP's all-reduced mean gradient — the compiler inserts the ICI
  all-reduce that NCCL did (others/train_with_DDP/train.py:195).
- gradient accumulation: a ``lax.scan`` over microbatches inside one jitted
  step (swin main.py:106,192-200 TRAIN.ACCUMULATION_STEPS analog) — no
  optimizer-state churn between micro-steps.
- bf16 autocast is a model-construction property (dtype policy), not a
  context manager; no loss scaling is needed on TPU (core/precision.py).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core import rng as rng_mod
from ..obs import flight
from ..parallel import collectives
from ..parallel._compat import shard_map
from ..parallel.mesh import DATA_AXIS, FSDP_AXIS
from ..parallel.sharding import (batch_spec, opt_state_shardings,
                                 shard_params_tree, zero1_partition_spec,
                                 zero1_shardings, Rules)
from .state import TrainState

# loss_fn(params, state, batch, rng, train) -> (loss, aux)
# aux: {'batch_stats': new_stats (optional), 'metrics': {...} (optional)}
LossFn = Callable[[Any, TrainState, Any, jax.Array], Tuple[jax.Array, Dict]]


def _microbatch(batch: Any, accum_steps: int, i: jax.Array) -> Any:
    def slice_leaf(x):
        micro = x.shape[0] // accum_steps
        return jax.lax.dynamic_slice_in_dim(x, i * micro, micro, axis=0)
    return jax.tree.map(slice_leaf, batch)


def make_train_step(
    loss_fn: LossFn,
    mesh: Optional[Mesh] = None,
    accum_steps: int = 1,
    donate: bool = True,
    donate_batch: bool = False,
    weight_update: str = "replicated",
    grad_comm: str = "fp32",
    rules: Optional[Rules] = None,
    comm_block: int = 256,
) -> Callable[[TrainState, Any, jax.Array], Tuple[TrainState, Dict]]:
    """Build the jitted train step. ``batch`` leaves must have a leading
    global-batch dim divisible by ``accum_steps`` (and by the data-axis
    size when a mesh is given).

    ``donate_batch=True`` additionally donates the batch argument
    (``donate_argnums=(0, 1)``): the input's HBM buffers are recycled by
    XLA instead of a fresh allocation per step — right for pipeline-fed
    batches that are used exactly once (the DevicePrefetcher/Trainer hot
    loop). Keep it off (the default) when the caller reuses a batch
    across calls, e.g. single-batch microbenchmarks.

    ``weight_update="zero1"`` (requires ``mesh``, pair it with
    ``shard_state(..., zero1=True)``) constrains gradients to the
    data-sharded optimizer-moment layout before ``apply_gradients`` and
    the new params back to the param layout after, so XLA lowers the DDP
    gradient all-reduce into reduce-scatter -> per-shard update ->
    all-gather instead of keeping full moments per device. ``rules`` must
    be the same TP/FSDP rules the state was sharded with.

    ``grad_comm="int8"`` (requires ``mesh``, ``accum_steps == 1``, no
    ``rules``, and a loss without batch_stats) computes per-replica local
    gradients under shard_map and reduces them with EQuARX-style
    block-scaled int8 collectives (block size ``comm_block``) instead of
    the implicit fp32 GSPMD all-reduce — combined with zero1, divisible
    leaves ride an int8 reduce-scatter and emerge already moment-sharded."""
    if weight_update not in ("replicated", "zero1"):
        raise ValueError(f"weight_update must be 'replicated' or 'zero1', "
                         f"got {weight_update!r}")
    if grad_comm not in ("fp32", "int8"):
        raise ValueError(f"grad_comm must be 'fp32' or 'int8', "
                         f"got {grad_comm!r}")
    if (weight_update == "zero1" or grad_comm == "int8") and mesh is None:
        raise ValueError("weight_update='zero1' / grad_comm='int8' need a mesh")
    if grad_comm == "int8" and accum_steps != 1:
        raise ValueError("grad_comm='int8' requires accum_steps == 1 "
                         "(the scan path already accumulates in fp32; "
                         "quantizing microbatch partial sums would stack "
                         "quantization error accum_steps times)")
    if grad_comm == "int8" and rules:
        raise ValueError("grad_comm='int8' is data-parallel only: TP/FSDP "
                         "rules shard params, but the shard_map grad path "
                         "replicates them")

    def step_fn(state: TrainState, batch: Any, rng: jax.Array
                ) -> Tuple[TrainState, Dict]:
        rng = rng_mod.step_key(rng, state.step)
        if mesh is not None:
            batch = jax.tree.map(
                lambda x: jax.lax.with_sharding_constraint(
                    x, NamedSharding(mesh, batch_spec())), batch)

        grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

        if grad_comm == "int8":
            (loss, aux), grads = _int8_value_and_grad(
                loss_fn, state, batch, rng, mesh,
                zero1=(weight_update == "zero1"), block=comm_block)
        elif accum_steps == 1:
            (loss, aux), grads = grad_fn(state.params, state, batch, rng)
            # fp32 gradient policy: the scan path below accumulates in
            # fp32; hand optax the same dtype here so bf16-param runs see
            # identical optimizer numerics at accum_steps 1 and N.
            grads = jax.tree.map(
                lambda g: g.astype(jnp.float32), grads)
        else:
            # batch_stats thread through the scan carry so every
            # microbatch's forward sees the stats advanced by the previous
            # one (matching torch BN across accum_steps forwards), and
            # metrics are averaged over microbatches instead of reporting
            # only the last one.
            aux_proto = _abstract_aux(loss_fn, state, batch, rng,
                                      accum_steps)
            has_stats = "batch_stats" in aux_proto

            def body(carry, i):
                grads_acc, loss_acc, aux_acc = carry
                mb = _microbatch(batch, accum_steps, i)
                st = (state.replace(batch_stats=aux_acc["batch_stats"])
                      if has_stats else state)
                (l, a), g = grad_fn(state.params, st,
                                    mb, jax.random.fold_in(rng, i))
                grads_acc = jax.tree.map(jnp.add, grads_acc, g)
                new_aux = dict(a)
                if "metrics" in a:
                    new_aux["metrics"] = jax.tree.map(
                        jnp.add, aux_acc.get("metrics", {}), a["metrics"])
                return (grads_acc, loss_acc + l, new_aux), None

            init_aux = dict(aux_proto)   # leaves are already jnp.zeros
            if has_stats:
                init_aux["batch_stats"] = state.batch_stats
            zero_grads = jax.tree.map(
                lambda p: jnp.zeros(p.shape, jnp.float32), state.params)
            (grads, loss, aux), _ = jax.lax.scan(
                body, (zero_grads, jnp.zeros((), jnp.float32), init_aux),
                jnp.arange(accum_steps))
            grads = jax.tree.map(lambda g: g / accum_steps, grads)
            loss = loss / accum_steps
            if "metrics" in aux:
                aux["metrics"] = jax.tree.map(
                    lambda m: m / accum_steps, aux["metrics"])

        if weight_update == "zero1":
            # grads pinned to the data-sharded moment layout BEFORE the
            # optimizer: GSPMD satisfies the constraint by reduce-scatter
            # (each replica keeps its 1/n shard of the summed grad), so
            # tx.update and apply_updates below run on shards.
            z_sh = zero1_shardings(state.params, mesh, rules)
            grads = jax.tree.map(jax.lax.with_sharding_constraint,
                                 grads, z_sh)

        new_stats = aux.get("batch_stats")
        state = state.apply_gradients(grads, new_stats)

        if weight_update == "zero1":
            # ...and the updated params pinned BACK to the param layout
            # (all-gather of the per-shard updates), moments pinned to
            # the moment layout so they never round-trip to replicated.
            rep = NamedSharding(mesh, P())
            param_sh = shard_params_tree(state.params, mesh, rules)
            param_treedef = jax.tree.structure(state.params)
            opt_sh = opt_state_shardings(state.opt_state, param_treedef,
                                         z_sh, rep)
            ema = state.ema_params
            if (ema is not None
                    and jax.tree.structure(ema) == param_treedef):
                ema = jax.tree.map(jax.lax.with_sharding_constraint,
                                   ema, param_sh)
            state = state.replace(
                params=jax.tree.map(jax.lax.with_sharding_constraint,
                                    state.params, param_sh),
                opt_state=jax.tree.map(jax.lax.with_sharding_constraint,
                                       state.opt_state, opt_sh),
                ema_params=ema)

        metrics = {"loss": loss, **aux.get("metrics", {})}
        metrics["grad_norm"] = jnp.sqrt(sum(
            jnp.sum(jnp.square(g.astype(jnp.float32)))
            for g in jax.tree.leaves(grads)))
        # device-side divergence flag: the Trainer's deferred-metrics
        # pipeline reads this from the stale snapshot instead of syncing
        # the in-flight loss, so a non-finite step aborts training within
        # the metrics lag with zero extra D2H round-trips
        metrics["bad_step"] = (~jnp.isfinite(loss)).astype(jnp.int32)
        return state, metrics

    donate_argnums: Tuple[int, ...] = ()
    if donate:
        donate_argnums += (0,)
    if donate_batch:
        donate_argnums += (1,)
    return jax.jit(step_fn, donate_argnums=donate_argnums)


def _int8_value_and_grad(loss_fn, state, batch, rng, mesh, zero1, block):
    """Per-replica local grads + EQuARX int8 reduction under shard_map.

    GSPMD's implicit gradient all-reduce cannot be intercepted, so the
    int8 path drops to shard_map over the data axes: each replica
    differentiates the loss over its LOCAL batch shard, then gradients
    are mean-reduced with block-scaled int8 payloads
    (``collectives.quantized_psum`` / ``quantized_reduce_scatter``).
    Under zero1, leaves whose zero1 spec shards dim 0 take the
    reduce-scatter and emerge already moment-sharded; everything else
    (and all leaves when zero1 is off) takes the full quantized psum and
    emerges replicated. Loss and metrics reduce in fp32 pmean — only
    gradients ride the quantized wire."""
    axes = (DATA_AXIS, FSDP_AXIS)
    n = mesh.shape[DATA_AXIS] * mesh.shape[FSDP_AXIS]
    dp = n if zero1 else 1
    z_specs = jax.tree.map(
        lambda p: zero1_partition_spec(tuple(p.shape), dp), state.params)

    def rs_eligible(leaf_shape, spec):
        return (zero1 and len(spec) > 0 and spec[0] is not None
                and leaf_shape[0] % n == 0)

    def local_grad(params, slim, batch, rng):
        # decorrelate per-replica dropout: without the fold every replica
        # would draw the SAME mask pattern over its local batch shard
        rng = jax.random.fold_in(rng, jax.lax.axis_index(axes))
        (loss, aux), g = jax.value_and_grad(loss_fn, has_aux=True)(
            params, slim, batch, rng)
        if "batch_stats" in aux:
            raise ValueError(
                "grad_comm='int8' does not support batch_stats losses: "
                "BN stats would need their own cross-replica reduction "
                "inside shard_map (use SyncBN-free models or fp32 comm)")
        g = jax.tree.map(lambda x: x.astype(jnp.float32), g)

        def reduce_leaf(x, spec):
            if rs_eligible(x.shape, spec):
                return collectives.quantized_reduce_scatter(
                    x, axes, block=block) / n
            return collectives.quantized_psum(x, axes, block=block) / n
        g = jax.tree.map(reduce_leaf, g, z_specs)
        loss = jax.lax.pmean(loss.astype(jnp.float32), axes)
        metrics = jax.tree.map(
            lambda m: jax.lax.pmean(m.astype(jnp.float32), axes),
            aux.get("metrics", {}))
        return (loss, metrics), g

    g_out_specs = jax.tree.map(
        lambda p, spec: spec if rs_eligible(p.shape, spec) else P(),
        state.params, z_specs)
    # the non-array TrainState fields (apply_fn, tx) are pytree-static;
    # params/opt_state/ema are stripped so shard_map only threads the
    # leaves the loss actually reads (step, batch_stats)
    slim = state.replace(params=None, opt_state=None, ema_params=None)
    mapped = shard_map(
        local_grad, mesh=mesh,
        in_specs=(P(), P(), batch_spec(), P()),
        out_specs=((P(), P()), g_out_specs),
        check_vma=False)
    (loss, metrics), grads = mapped(state.params, slim, batch, rng)
    return (loss, {"metrics": metrics} if metrics else {}), grads


def _abstract_aux(loss_fn, state, batch, rng, accum_steps):
    """Zero-valued aux with the right structure for the scan carry."""
    mb = _microbatch(batch, accum_steps, jnp.zeros((), jnp.int32))
    shapes = jax.eval_shape(lambda p, s, b, r: loss_fn(p, s, b, r)[1],
                            state.params, state, mb, rng)
    return jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), shapes)


def make_eval_step(
    metric_fn: Callable[[Any, TrainState, Any], Dict],
    mesh: Optional[Mesh] = None,
    use_ema: bool = True,
) -> Callable[[TrainState, Any], Dict]:
    """metric_fn(params, state, batch) -> dict of per-batch metric SUMS
    (summing, not averaging, lets callers weight by true batch size)."""

    def step_fn(state: TrainState, batch: Any) -> Dict:
        if mesh is not None:
            batch = jax.tree.map(
                lambda x: jax.lax.with_sharding_constraint(
                    x, NamedSharding(mesh, batch_spec())), batch)
        params = state.eval_params if use_ema else state.params
        return metric_fn(params, state, batch)

    return jax.jit(step_fn)


def shard_state(state: TrainState, mesh: Mesh,
                rules: Optional[Rules] = None,
                zero1: bool = False) -> TrainState:
    """Place a TrainState on the mesh: params (and their optimizer-moment /
    EMA mirrors) by ``rules`` — default fully replicated = pure DP — and
    scalars replicated. Optimizer moments that are param-shaped pytrees
    (optax ScaleByAdam mu/nu etc.) inherit the param shardings so TP/FSDP
    states shard consistently.

    ``zero1=True`` shards those moment leaves over the data axes instead
    (ZeRO-1): each device holds 1/dp of mu/nu while params (and EMA) stay
    in their param layout. Pair with
    ``make_train_step(weight_update="zero1")`` so the step keeps them
    there; leaves with no data-divisible dim stay replicated (visible in
    ``shard_layout_summary`` of the opt_state)."""
    rep = NamedSharding(mesh, P())
    param_sh = shard_params_tree(state.params, mesh, rules)
    moment_sh = (zero1_shardings(state.params, mesh, rules)
                 if zero1 else param_sh)
    param_treedef = jax.tree.structure(state.params)

    def mirror(tree):
        """Param shardings where subtree structure matches params, else
        replicated."""
        if tree is None:
            return None
        if jax.tree.structure(tree) == param_treedef:
            return param_sh
        return jax.tree.map(lambda x: rep, tree)

    def on_fallback(opt, e):
        # an un-flattenable field falls back to replicated — fine,
        # but leave a trace: a silently-replicated optimizer state
        # is exactly the HBM regression DLT104 exists to catch
        flight.record("shard_opt_fallback", field=type(opt).__name__,
                      error=repr(e))

    shardings = state.replace(
        step=rep,
        params=param_sh,
        opt_state=opt_state_shardings(state.opt_state, param_treedef,
                                      moment_sh, rep, on_fallback),
        batch_stats=jax.tree.map(lambda x: rep, state.batch_stats),
        ema_params=mirror(state.ema_params),
    )
    return jax.device_put(state, shardings)
