"""Jitted train/eval steps: GSPMD sharding, grad accumulation, remat.

This single function replaces the reference's per-project hot loops
(classification/mnist/utils.py:30 train_one_epoch; swin main.py:171-229
with AMP scaler + accumulation; YOLOX trainer.py:90 train_one_iter):

- data parallelism: the batch is sharded over the mesh's data axes and the
  loss is a mean over the GLOBAL batch, so ``jax.grad`` under GSPMD yields
  exactly DDP's all-reduced mean gradient — the compiler inserts the ICI
  all-reduce that NCCL did (others/train_with_DDP/train.py:195).
- gradient accumulation: a ``lax.scan`` over microbatches inside one jitted
  step (swin main.py:106,192-200 TRAIN.ACCUMULATION_STEPS analog) — no
  optimizer-state churn between micro-steps.
- bf16 autocast is a model-construction property (dtype policy), not a
  context manager; no loss scaling is needed on TPU (core/precision.py).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core import rng as rng_mod
from ..obs import flight
from ..parallel.sharding import batch_spec, shard_params_tree, Rules
from .state import TrainState

# loss_fn(params, state, batch, rng, train) -> (loss, aux)
# aux: {'batch_stats': new_stats (optional), 'metrics': {...} (optional)}
LossFn = Callable[[Any, TrainState, Any, jax.Array], Tuple[jax.Array, Dict]]


def _microbatch(batch: Any, accum_steps: int, i: jax.Array) -> Any:
    def slice_leaf(x):
        micro = x.shape[0] // accum_steps
        return jax.lax.dynamic_slice_in_dim(x, i * micro, micro, axis=0)
    return jax.tree.map(slice_leaf, batch)


def make_train_step(
    loss_fn: LossFn,
    mesh: Optional[Mesh] = None,
    accum_steps: int = 1,
    donate: bool = True,
    donate_batch: bool = False,
) -> Callable[[TrainState, Any, jax.Array], Tuple[TrainState, Dict]]:
    """Build the jitted train step. ``batch`` leaves must have a leading
    global-batch dim divisible by ``accum_steps`` (and by the data-axis
    size when a mesh is given).

    ``donate_batch=True`` additionally donates the batch argument
    (``donate_argnums=(0, 1)``): the input's HBM buffers are recycled by
    XLA instead of a fresh allocation per step — right for pipeline-fed
    batches that are used exactly once (the DevicePrefetcher/Trainer hot
    loop). Keep it off (the default) when the caller reuses a batch
    across calls, e.g. single-batch microbenchmarks."""

    def step_fn(state: TrainState, batch: Any, rng: jax.Array
                ) -> Tuple[TrainState, Dict]:
        rng = rng_mod.step_key(rng, state.step)
        if mesh is not None:
            batch = jax.tree.map(
                lambda x: jax.lax.with_sharding_constraint(
                    x, NamedSharding(mesh, batch_spec())), batch)

        grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

        if accum_steps == 1:
            (loss, aux), grads = grad_fn(state.params, state, batch, rng)
        else:
            # batch_stats thread through the scan carry so every
            # microbatch's forward sees the stats advanced by the previous
            # one (matching torch BN across accum_steps forwards), and
            # metrics are averaged over microbatches instead of reporting
            # only the last one.
            aux_proto = _abstract_aux(loss_fn, state, batch, rng,
                                      accum_steps)
            has_stats = "batch_stats" in aux_proto

            def body(carry, i):
                grads_acc, loss_acc, aux_acc = carry
                mb = _microbatch(batch, accum_steps, i)
                st = (state.replace(batch_stats=aux_acc["batch_stats"])
                      if has_stats else state)
                (l, a), g = grad_fn(state.params, st,
                                    mb, jax.random.fold_in(rng, i))
                grads_acc = jax.tree.map(jnp.add, grads_acc, g)
                new_aux = dict(a)
                if "metrics" in a:
                    new_aux["metrics"] = jax.tree.map(
                        jnp.add, aux_acc.get("metrics", {}), a["metrics"])
                return (grads_acc, loss_acc + l, new_aux), None

            init_aux = dict(aux_proto)   # leaves are already jnp.zeros
            if has_stats:
                init_aux["batch_stats"] = state.batch_stats
            zero_grads = jax.tree.map(
                lambda p: jnp.zeros(p.shape, jnp.float32), state.params)
            (grads, loss, aux), _ = jax.lax.scan(
                body, (zero_grads, jnp.zeros((), jnp.float32), init_aux),
                jnp.arange(accum_steps))
            grads = jax.tree.map(lambda g: g / accum_steps, grads)
            loss = loss / accum_steps
            if "metrics" in aux:
                aux["metrics"] = jax.tree.map(
                    lambda m: m / accum_steps, aux["metrics"])

        new_stats = aux.get("batch_stats")
        state = state.apply_gradients(grads, new_stats)
        metrics = {"loss": loss, **aux.get("metrics", {})}
        metrics["grad_norm"] = jnp.sqrt(sum(
            jnp.sum(jnp.square(g.astype(jnp.float32)))
            for g in jax.tree.leaves(grads)))
        # device-side divergence flag: the Trainer's deferred-metrics
        # pipeline reads this from the stale snapshot instead of syncing
        # the in-flight loss, so a non-finite step aborts training within
        # the metrics lag with zero extra D2H round-trips
        metrics["bad_step"] = (~jnp.isfinite(loss)).astype(jnp.int32)
        return state, metrics

    donate_argnums: Tuple[int, ...] = ()
    if donate:
        donate_argnums += (0,)
    if donate_batch:
        donate_argnums += (1,)
    return jax.jit(step_fn, donate_argnums=donate_argnums)


def _abstract_aux(loss_fn, state, batch, rng, accum_steps):
    """Zero-valued aux with the right structure for the scan carry."""
    mb = _microbatch(batch, accum_steps, jnp.zeros((), jnp.int32))
    shapes = jax.eval_shape(lambda p, s, b, r: loss_fn(p, s, b, r)[1],
                            state.params, state, mb, rng)
    return jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), shapes)


def make_eval_step(
    metric_fn: Callable[[Any, TrainState, Any], Dict],
    mesh: Optional[Mesh] = None,
    use_ema: bool = True,
) -> Callable[[TrainState, Any], Dict]:
    """metric_fn(params, state, batch) -> dict of per-batch metric SUMS
    (summing, not averaging, lets callers weight by true batch size)."""

    def step_fn(state: TrainState, batch: Any) -> Dict:
        if mesh is not None:
            batch = jax.tree.map(
                lambda x: jax.lax.with_sharding_constraint(
                    x, NamedSharding(mesh, batch_spec())), batch)
        params = state.eval_params if use_ema else state.params
        return metric_fn(params, state, batch)

    return jax.jit(step_fn)


def shard_state(state: TrainState, mesh: Mesh,
                rules: Optional[Rules] = None) -> TrainState:
    """Place a TrainState on the mesh: params (and their optimizer-moment /
    EMA mirrors) by ``rules`` — default fully replicated = pure DP — and
    scalars replicated. Optimizer moments that are param-shaped pytrees
    (optax ScaleByAdam mu/nu etc.) inherit the param shardings so TP/FSDP
    states shard consistently."""
    rep = NamedSharding(mesh, P())
    param_sh = shard_params_tree(state.params, mesh, rules)
    param_treedef = jax.tree.structure(state.params)

    def mirror(tree):
        """Param shardings where subtree structure matches params, else
        replicated."""
        if tree is None:
            return None
        if jax.tree.structure(tree) == param_treedef:
            return param_sh
        return jax.tree.map(lambda x: rep, tree)

    def shard_opt(opt):
        # optax states are (possibly nested) namedtuples whose fields are
        # either param-shaped pytrees or scalars; map field-wise.
        if hasattr(opt, "_fields"):
            return type(opt)(*(shard_opt(f) for f in opt))
        if isinstance(opt, (tuple, list)):
            return type(opt)(shard_opt(o) for o in opt)
        try:
            if jax.tree.structure(opt) == param_treedef:
                return param_sh
        except (TypeError, ValueError) as e:
            # an un-flattenable field falls back to replicated — fine,
            # but leave a trace: a silently-replicated optimizer state
            # is exactly the HBM regression DLT104 exists to catch
            flight.record("shard_opt_fallback", field=type(opt).__name__,
                          error=repr(e))
        return jax.tree.map(lambda x: rep, opt)

    shardings = state.replace(
        step=rep,
        params=param_sh,
        opt_state=shard_opt(state.opt_state),
        batch_stats=jax.tree.map(lambda x: rep, state.batch_stats),
        ema_params=mirror(state.ema_params),
    )
    return jax.device_put(state, shardings)
