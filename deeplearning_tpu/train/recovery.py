"""Divergence rollback-and-skip: a loss spike is a detour, not a death.

Large-batch training on real data diverges occasionally — a pathological
batch, an optimizer overflow, a cosmic-ray bitflip in HBM. The seed
policy (``Trainer(abort_non_finite=True)``) turns the jitted
``bad_step`` flag into :class:`FloatingPointError`, which at production
scale wastes everything since the last on-disk checkpoint and burns a
supervisor restart. This module implements the cheaper industrial
policy:

1. keep a device-side **anchor** copy of the TrainState, refreshed every
   ``anchor_every`` steps (one jitted ``jnp.copy`` tree-map — no host
   transfer, no disk);
2. when divergence fires, **roll back** to the anchor, **skip** the data
   window that produced it (the loader is re-seeded, so the replayed
   span draws a different permutation), and **dampen** updates for a
   cooldown window;
3. give up — the seed abort path, with full flight telemetry — only
   after ``max_recoveries`` rollbacks inside ``budget_steps``.

Anchor correctness under async metrics: the Trainer learns about
divergence ``metrics_lag`` steps late, so an anchor snapshotted at step
t is only *promoted* once a verified-finite metrics entry for a step
``> t`` arrives — entry t+1's loss was computed FROM state t, so a
finite entry at t+1 proves the params at t were clean. Until promotion a
snapshot waits in a small pending queue; a rollback clears it.

Donation safety: ``snapshot_state`` is dispatched BEFORE the donating
``train_step`` call consumes the buffers, and the copy is jitted so
output shardings mirror the inputs on any mesh.
"""

from __future__ import annotations

import collections
from typing import Any, Deque, List, Optional, Tuple

import jax
import jax.numpy as jnp

__all__ = ["RecoveryPolicy", "RecoveryManager", "RecoveryExhausted",
           "snapshot_state", "damp_update", "poison_state"]


class RecoveryExhausted(RuntimeError):
    """Rollback budget spent (or no anchor exists): the run is genuinely
    sick — fall through to the abort path."""


class RecoveryPolicy:
    """Knobs for divergence recovery. ``budget_steps=0`` means the
    ``max_recoveries`` budget spans the whole run; otherwise only
    rollbacks within the trailing ``budget_steps`` window count — a
    2M-step run is allowed one bad day per epoch, not three ever."""

    def __init__(self, *, mode: str = "rollback", anchor_every: int = 50,
                 max_recoveries: int = 3, budget_steps: int = 0,
                 cooldown_steps: int = 20, lr_decay: float = 0.1):
        if mode not in ("rollback", "abort"):
            raise ValueError(f"mode must be rollback|abort, got {mode!r}")
        self.mode = mode
        self.anchor_every = max(int(anchor_every), 1)
        self.max_recoveries = int(max_recoveries)
        self.budget_steps = int(budget_steps)
        self.cooldown_steps = max(int(cooldown_steps), 0)
        self.lr_decay = float(lr_decay)


# jit the copy so it runs device-side and the outputs inherit the input
# shardings on any mesh; TrainState's static fields (apply_fn, tx) are
# hashable aux data, so this traces once per trainer.
@jax.jit
def _copy_tree(tree: Any) -> Any:
    return jax.tree.map(jnp.copy, tree)


def snapshot_state(state: Any) -> Any:
    """Device-side deep copy of a TrainState (params + opt state +
    step + batch_stats). Dispatch this BEFORE a donating train_step call
    — the copy reads the buffers the step will consume."""
    return _copy_tree(state)


@jax.jit
def _damp(old: Any, new: Any, scale: jnp.ndarray) -> Any:
    return jax.tree.map(
        lambda o, n: o + scale.astype(o.dtype) * (n - o), old, new)


def damp_update(old_params: Any, new_params: Any, scale: float) -> Any:
    """``old + scale * (new - old)`` leaf-wise: shrink one step's param
    delta by ``scale``. Exactly an LR decay for SGD; the standard
    post-rollback damping for adaptive optimizers (whose moments keep
    their own schedule). ``scale`` is traced, so every cooldown strength
    shares one compiled program."""
    return _damp(old_params, new_params, jnp.float32(scale))


@jax.jit
def _poison_params(params: Any) -> Any:
    return jax.tree.map(
        lambda p: p * jnp.nan if jnp.issubdtype(p.dtype, jnp.floating)
        else p, params)


def poison_state(state: Any) -> Any:
    """NaN-poison the float params (the ``nan`` fault's effect): the next
    dispatched step computes a NaN loss through the REAL jitted
    ``bad_step`` path, so injection exercises detection end to end."""
    return state.replace(params=_poison_params(state.params))


class RecoveryManager:
    """Owns the anchor lifecycle and the rollback budget. Not
    thread-safe — everything runs on the Trainer's consumer thread."""

    def __init__(self, policy: Optional[RecoveryPolicy] = None):
        self.policy = policy or RecoveryPolicy()
        self._anchor: Optional[Tuple[int, Any]] = None
        # snapshots awaiting a verified-finite entry newer than them
        self._pending: Deque[Tuple[int, Any]] = collections.deque(maxlen=8)
        self._last_snap_step: Optional[int] = None
        self._cooldown_until = -1
        self.rollbacks = 0
        self.recovery_steps: List[int] = []        # budget accounting
        self.skipped: List[Tuple[int, int]] = []   # (anchor, bad) windows

    # ------------------------------------------------------------ anchor
    def seed(self, step: int, state: Any) -> None:
        """Anchor the known-clean starting state (fresh init or a
        just-restored checkpoint)."""
        self._anchor = (int(step), snapshot_state(state))
        self._pending.clear()
        self._last_snap_step = int(step)

    def maybe_snapshot(self, step: int, state: Any) -> None:
        """Hot-loop hook: one int compare when idle; every
        ``anchor_every`` steps, dispatch a device-side copy into the
        pending queue. Call BEFORE the donating step dispatch."""
        step = int(step)
        if step - (self._last_snap_step or 0) < self.policy.anchor_every:
            return
        self._last_snap_step = step
        self._pending.append((step, snapshot_state(state)))

    def mark_verified(self, step: int) -> None:
        """A metrics entry at ``step`` arrived finite: promote every
        pending snapshot strictly older than it (entry t+1's loss was
        computed from state t, so finiteness at t+1 vouches for t)."""
        step = int(step)
        promoted = None
        while self._pending and self._pending[0][0] < step:
            promoted = self._pending.popleft()
        if promoted is not None:
            self._anchor = promoted

    @property
    def anchor_step(self) -> Optional[int]:
        return self._anchor[0] if self._anchor is not None else None

    # --------------------------------------------------------- rollback
    def on_divergence(self, step: int) -> Tuple[int, Any]:
        """Account one divergence at host step ``step``; return
        ``(anchor_step, state_copy)`` to roll back to, or raise
        :class:`RecoveryExhausted` when the budget is spent. The caller
        gets a COPY of the anchor so a second divergence in the same
        window can roll back again."""
        step = int(step)
        if self.policy.budget_steps > 0:
            floor = step - self.policy.budget_steps
            self.recovery_steps = [s for s in self.recovery_steps
                                   if s >= floor]
        if self._anchor is None:
            raise RecoveryExhausted(
                f"divergence at step {step} with no verified anchor")
        if len(self.recovery_steps) >= self.policy.max_recoveries:
            raise RecoveryExhausted(
                f"divergence at step {step}: {len(self.recovery_steps)} "
                f"rollbacks already spent (max {self.policy.max_recoveries}"
                + (f" per {self.policy.budget_steps} steps"
                   if self.policy.budget_steps else "") + ")")
        self.recovery_steps.append(step)
        self.rollbacks += 1
        anchor_step, anchor_state = self._anchor
        self.skipped.append((anchor_step, step))
        # in-flight snapshots may postdate the poison — drop them, and
        # restart the snapshot cadence from the anchor
        self._pending.clear()
        self._last_snap_step = anchor_step
        self._cooldown_until = anchor_step + self.policy.cooldown_steps
        return anchor_step, snapshot_state(anchor_state)

    def cooldown_scale(self, step: int) -> Optional[float]:
        """``lr_decay`` while inside the post-rollback cooldown window,
        else None (one int compare on the hot path)."""
        if int(step) < self._cooldown_until:
            return self.policy.lr_decay
        return None

    def stats(self) -> dict:
        return {
            "rollbacks": self.rollbacks,
            "rollback_steps": list(self.recovery_steps),
            "skipped_windows": [list(w) for w in self.skipped],
            "anchor_step": self.anchor_step,
            "anchor_every": self.policy.anchor_every,
            "max_recoveries": self.policy.max_recoveries,
        }
