"""Retrieval metrics: CMC / mAP + k-reciprocal re-ranking.

Surface of metric_learning/BDB trainers/evaluator.py:52 (market1501-style
CMC + mAP over query/gallery with camera-id filtering) and
trainers/re_ranking.py (k-reciprocal encoding re-ranking). All host-side
numpy — these run on gathered embeddings after the jitted forward.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np


def pairwise_distances(query: np.ndarray, gallery: np.ndarray,
                       metric: str = "euclidean") -> np.ndarray:
    q = np.asarray(query, np.float32)
    g = np.asarray(gallery, np.float32)
    if metric == "cosine":
        qn = q / np.maximum(np.linalg.norm(q, axis=1, keepdims=True), 1e-12)
        gn = g / np.maximum(np.linalg.norm(g, axis=1, keepdims=True), 1e-12)
        return 1.0 - qn @ gn.T
    sq = np.sum(q * q, 1, keepdims=True)
    sg = np.sum(g * g, 1, keepdims=True)
    d2 = sq + sg.T - 2.0 * (q @ g.T)
    return np.sqrt(np.clip(d2, 0, None))


def cmc_map(dist: np.ndarray, q_ids: np.ndarray, g_ids: np.ndarray,
            q_cams: Optional[np.ndarray] = None,
            g_cams: Optional[np.ndarray] = None,
            topk: int = 50) -> Dict[str, np.ndarray]:
    """Market-1501 protocol: same-id same-cam gallery entries are removed
    per query (evaluator.py:52 eval_func surface)."""
    nq, ng = dist.shape
    if q_cams is None:
        q_cams = -np.ones(nq, np.int64)
    if g_cams is None:
        g_cams = -2 * np.ones(ng, np.int64)
    order = np.argsort(dist, axis=1, kind="mergesort")
    cmc = np.zeros(topk)
    aps = []
    valid_q = 0
    for qi in range(nq):
        ranked = order[qi]
        remove = (g_ids[ranked] == q_ids[qi]) & \
            (g_cams[ranked] == q_cams[qi])
        kept = ranked[~remove]
        matches = (g_ids[kept] == q_ids[qi]).astype(np.float64)
        if not matches.any():
            continue
        valid_q += 1
        first_hit = int(np.argmax(matches))
        if first_hit < topk:
            cmc[first_hit:] += 1
        # average precision
        hits = np.cumsum(matches)
        precision = hits / (np.arange(len(matches)) + 1)
        aps.append(float(np.sum(precision * matches) / matches.sum()))
    cmc = cmc / max(valid_q, 1)
    return {"cmc": cmc, "rank1": float(cmc[0]), "rank5": float(cmc[4]),
            "mAP": float(np.mean(aps)) if aps else 0.0}


def k_reciprocal_rerank(q_feats: np.ndarray, g_feats: np.ndarray,
                        k1: int = 20, k2: int = 6,
                        lambda_value: float = 0.3) -> np.ndarray:
    """k-reciprocal encoding re-ranking (re_ranking.py surface): Jaccard
    distance over k-reciprocal neighbor sets blended with the original
    distance."""
    feats = np.concatenate([q_feats, g_feats], axis=0).astype(np.float32)
    nq = len(q_feats)
    n = len(feats)
    original = pairwise_distances(feats, feats)
    original = original / np.maximum(original.max(axis=0, keepdims=True),
                                     1e-12)
    rank = np.argsort(original, axis=1, kind="mergesort")

    k1 = min(k1, n - 1)
    recip_sets = []
    for i in range(n):
        forward = rank[i, :k1 + 1]
        backward = rank[forward][:, :k1 + 1]
        recip = forward[np.any(backward == i, axis=1)]
        # expand with half-k1 reciprocal neighbors of the set
        expanded = list(recip)
        half = max(k1 // 2, 1)
        for cand in recip:
            c_fwd = rank[cand, :half + 1]
            c_bwd = rank[c_fwd][:, :half + 1]
            c_recip = c_fwd[np.any(c_bwd == cand, axis=1)]
            if len(np.intersect1d(c_recip, recip)) > 2 / 3 * len(c_recip):
                expanded.extend(c_recip)
        recip_sets.append(np.unique(np.asarray(expanded)))

    weights = np.zeros((n, n), np.float32)
    for i in range(n):
        weights[i, recip_sets[i]] = np.exp(-original[i, recip_sets[i]])
    if k2 > 1:
        weights = np.stack(
            [np.mean(weights[rank[i, :k2]], axis=0) for i in range(n)])
    weights = weights / np.maximum(weights.sum(1, keepdims=True), 1e-12)

    jaccard = np.zeros((nq, n), np.float32)
    for qi in range(nq):
        minimum = np.minimum(weights[qi][None, :], weights).sum(1)
        maximum = np.maximum(weights[qi][None, :], weights).sum(1)
        jaccard[qi] = 1.0 - minimum / np.maximum(maximum, 1e-12)
    final = (1 - lambda_value) * jaccard + lambda_value * original[:nq]
    return final[:, nq:]
